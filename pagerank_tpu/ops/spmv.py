"""The contribution-scatter SpMV (L3 hot op).

This single op replaces the reference's entire per-iteration shuffle
pipeline — `allUrls.join(ranks)` → flatMap(rank/out_degree per target) →
`reduceByKey(Sum)` (Sparky.java:192-216, 229; 3 shuffles / O(E)
emissions) — with a gather + multiply + sorted segment-sum over a
destination-sorted COO edge shard:

    contrib[t] = Σ_{edges s→t} r[s] / out_degree[s]

Edges arrive sorted by dst (graph.py packs keys dst-major), so
``indices_are_sorted=True`` takes XLA's fast segment-sum path on TPU.
Dangling sources have no edges, so they emit nothing — exactly the
reference's null-sentinel behavior (Sparky.java:198-206, SURVEY.md §2a.6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def edge_contrib_segment_sum(r, src, dst, w, n, accum_dtype=None):
    """contrib = Aᵀ_norm r over one COO edge shard.

    Args:
      r: [n] (or [n, k] batched) rank vector, replicated.
      src, dst: int32 [e] edge endpoints, sorted by dst. Padding edges
        must carry w == 0 (their contribution vanishes).
      w: [e] per-edge weight 1/out_degree[src].
      n: number of vertices (static).
      accum_dtype: dtype for the gather-multiply-accumulate; defaults to
        r.dtype. Use a wider type to protect the 1e-6 L1 budget on
        heavy-tailed in-degree distributions (SURVEY.md §7).

    Returns:
      [n] (or [n, k]) partial contribution sums in accum_dtype.
    """
    acc = accum_dtype or r.dtype
    wa = w.astype(acc)
    if r.ndim == 2:
        vals = r[src].astype(acc) * wa[:, None]
    else:
        vals = r[src].astype(acc) * wa
    return jax.ops.segment_sum(
        vals, dst, num_segments=n, indices_are_sorted=True
    )


def dangling_mass(r, dangling, accum_dtype=None):
    """m = Σ_{out_degree==0} r — the reference's ``danglingContrib`` loop
    (one distributed lookup per dangling URL per iteration,
    Sparky.java:219-222) collapsed to a single on-device reduction."""
    acc = accum_dtype or r.dtype
    d = dangling.astype(acc)
    if r.ndim == 2:
        return d @ r.astype(acc)
    return jnp.vdot(d, r.astype(acc))
