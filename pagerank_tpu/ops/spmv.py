"""The contribution-scatter SpMV (L3 hot op).

This single op replaces the reference's entire per-iteration shuffle
pipeline — `allUrls.join(ranks)` → flatMap(rank/out_degree per target) →
`reduceByKey(Sum)` (Sparky.java:192-216, 229; 3 shuffles / O(E)
emissions) — with a gather + multiply + sorted segment-sum over a
destination-sorted COO edge shard:

    contrib[t] = Σ_{edges s→t} r[s] / out_degree[s]

Edges arrive sorted by dst (graph.py packs keys dst-major), so
``indices_are_sorted=True`` takes XLA's fast segment-sum path on TPU.
Dangling sources have no edges, so they emit nothing — exactly the
reference's null-sentinel behavior (Sparky.java:198-206, SURVEY.md §2a.6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pagerank_tpu.ops import LANES


def edge_contrib_segment_sum(r, src, dst, w, n, accum_dtype=None):
    """contrib = Aᵀ_norm r over one COO edge shard.

    Args:
      r: [n] (or [n, k] batched) rank vector, replicated.
      src, dst: int32 [e] edge endpoints, sorted by dst. Padding edges
        must carry w == 0 (their contribution vanishes).
      w: [e] per-edge weight 1/out_degree[src].
      n: number of vertices (static).
      accum_dtype: dtype for the gather-multiply-accumulate; defaults to
        r.dtype. Use a wider type to protect the 1e-6 L1 budget on
        heavy-tailed in-degree distributions (SURVEY.md §7).

    Returns:
      [n] (or [n, k]) partial contribution sums in accum_dtype.
    """
    acc = accum_dtype or r.dtype
    wa = w.astype(acc)
    if r.ndim == 2:
        vals = r[src].astype(acc) * wa[:, None]
    else:
        vals = r[src].astype(acc) * wa
    return jax.ops.segment_sum(
        vals, dst, num_segments=n, indices_are_sorted=True
    )


def _group_scatter(v, sub, group, acc):
    """Redistribute per-slot values to lanes within their lane group
    (ops/ell.py grouped-lane layout): the slot at row position p carries
    ``sub`` selecting lane ``(p & ~(group-1)) | sub``. One ``group``-wide
    one-hot contraction per slot — VPU noise next to the slot gather."""
    c, lanes = v.shape
    ng = lanes // group
    v4 = v.reshape(c, ng, group)
    sel = jax.nn.one_hot(sub.reshape(c, ng, group), group, dtype=acc)
    return (v4[..., None].astype(acc) * sel).sum(2).reshape(c, lanes)


def _chunked_block_sum(chunk_sum, src_slots, row_block, chunk_rows,
                       num_segments, slab, chunk_bases=None):
    """Run ``chunk_sum(src_chunk, segment_ids_chunk, n_seg)`` over slot
    rows in ``chunk_rows``-sized chunks via lax.scan, accumulating the
    per-segment results. Bounds the gather intermediate each chunk
    materializes.

    Two accumulation modes:
      - slab=False: each chunk segment-sums into the FULL
        (num_segments, ...) output and the scan adds them. Simple, but
        the carry traffic is num_segments*128*itemsize bytes per chunk —
        ruinous for big graphs (134MB/chunk at 33M vertices).
      - slab=True: ``row_block`` must be DENSE ranks (gap-free ascending
        per stripe; ops/ell.py packers + the engine provide this), so a
        chunk of R rows touches <= R consecutive ranks. Each chunk
        segment-sums LOCALLY (ids - ids[0], chunk_rows segments) and
        read-modify-writes a chunk-sized slab of the carry at its first
        rank — carry traffic per chunk drops to the slab (1MB at
        chunk=2048), independent of graph size. The carry has
        ``chunk_rows`` slack rows so the final slab never clamps.

    ``chunk_bases`` (partition-centric layouts, ops/ell.py
    "Partition-centric sub-binning"): int32 [nc, 2] of per-chunk
    (gather-window row base, slab rank base). When set, ``chunk_sum``
    is called as ``chunk_sum(src_c, rb_c, nseg, window_base)``,
    ``row_block`` already carries CHUNK-LOCAL dense ranks (no ``- r0``
    renormalization), and the slab lands at the prefetched rank base —
    the scalar rides the scan's xs, so the scan body stays a single
    fused program per chunk. Implies slab=True and chunking.

    The scan carry is seeded from chunk 0 (not plain zeros) so that
    under shard_map the carry is device-varying like the body output.
    """
    n_rows = src_slots.shape[0]
    if chunk_bases is None and (chunk_rows is None or chunk_rows >= n_rows):
        return chunk_sum(src_slots, row_block, num_segments)
    if chunk_rows is None or n_rows % chunk_rows:
        raise ValueError(f"chunk_rows {chunk_rows} must divide rows {n_rows}")
    nc = n_rows // chunk_rows

    src_c = src_slots.reshape(nc, chunk_rows, -1)
    rb_c = row_block.reshape(nc, chunk_rows)

    if not slab:
        def body(y2, args):
            s_c, r_c = args
            return y2 + chunk_sum(s_c, r_c, num_segments), None

        y2, _ = jax.lax.scan(
            body,
            chunk_sum(src_c[0], rb_c[0], num_segments),
            (src_c[1:], rb_c[1:]),
        )
        return y2

    if chunk_bases is not None:
        if chunk_bases.shape[0] != nc:
            raise ValueError(
                f"chunk_bases rows {chunk_bases.shape[0]} != chunks {nc}"
            )

        def slab_add_p(y2, s_c, r_c, base2):
            part = chunk_sum(s_c, r_c, chunk_rows, base2[0])
            zero = jnp.zeros((), base2.dtype)
            start = (base2[1],) + (zero,) * (part.ndim - 1)
            cur = jax.lax.dynamic_slice(y2, start, part.shape)
            return jax.lax.dynamic_update_slice(y2, cur + part, start)

        probe = jax.eval_shape(
            lambda s, r, b: chunk_sum(s, r, chunk_rows, b[0]),
            src_c[0], rb_c[0], chunk_bases[0],
        )
        zeros = jnp.zeros(
            (num_segments + chunk_rows,) + probe.shape[1:], probe.dtype
        )

        def body_p(y2, args):
            return slab_add_p(y2, *args), None

        y2, _ = jax.lax.scan(
            body_p,
            slab_add_p(zeros, src_c[0], rb_c[0], chunk_bases[0]),
            (src_c[1:], rb_c[1:], chunk_bases[1:]),
        )
        return y2[:num_segments]

    def slab_add(y2, s_c, r_c):
        r0 = r_c[0]
        part = chunk_sum(s_c, r_c - r0, chunk_rows)
        # All start indices must share one dtype (x64 would promote
        # literal zeros to int64 against an int32 r0).
        zero = jnp.zeros((), r0.dtype)
        start = (r0,) + (zero,) * (part.ndim - 1)
        cur = jax.lax.dynamic_slice(y2, start, part.shape)
        return jax.lax.dynamic_update_slice(y2, cur + part, start)

    probe = jax.eval_shape(
        lambda s, r: chunk_sum(s, r, chunk_rows), src_c[0], rb_c[0]
    )
    zeros = jnp.zeros(
        (num_segments + chunk_rows,) + probe.shape[1:], probe.dtype
    )

    def body(y2, args):
        return slab_add(y2, *args), None

    y2, _ = jax.lax.scan(
        body,
        slab_add(zeros, src_c[0], rb_c[0]),
        (src_c[1:], rb_c[1:]),
    )
    return y2[:num_segments]


def unpack_words24(slots8):
    """Decode a 3-byte PLANAR slot-word array — int8 [rows, 3*LANES]
    with byte plane k of slot (r, l) at column k*LANES + l — back to
    int32 [rows, LANES] words. The partition-centric layout stores slot
    words this way: partition-local source alphabets fit 24 bits where
    stripe-local ones need 30+, so the dominant per-slot HBM stream
    drops from 4 to 3 bytes (ops/ell.py "Partition-centric
    sub-binning"). Planar (not interleaved) so each byte plane is a
    contiguous 128-lane vector load."""
    b = slots8.astype(jnp.int32) & 0xFF  # int8 sign-extends; mask it off
    return b[..., :LANES] | (b[..., LANES:2 * LANES] << 8) \
        | (b[..., 2 * LANES:] << 16)


def pack_words24(words, xp=jnp):
    """Inverse of :func:`unpack_words24` (build side): int32
    [rows, LANES] words < 2**24 to the int8 [rows, 3*LANES] planar
    form."""
    return xp.concatenate(
        [words & 0xFF, (words >> 8) & 0xFF, (words >> 16) & 0xFF], axis=-1
    ).astype(xp.int8)


def ell_contrib(z_ext, src_slots, row_block, num_blocks, accum_dtype=None,
                gather_width=8, chunk_rows=None, group=1, num_present=None,
                window_rows=0, chunk_bases=None):
    """contrib = Aᵀ_norm r over blocked-ELL slots (ops/ell.py layout),
    with the row-normalization PRE-SCALED into the rank vector.

    TPU-native formulation of the reference's scatter pipeline
    (Sparky.java:192-229): XLA's per-edge scatter on TPU measures ~100M
    edges/s, so the reduce is restructured as (a) a dense per-slot gather
    and (b) a segment-sum over slot *rows* (128 slots each) — 128x fewer
    scatter keys. The gather uses a width-8 row-gather + one-hot dot, the
    fastest XLA gather form measured on v5e (~2.3x plain take).

    The caller passes ``z_ext = concat(r * inv_out_degree, zeros(gw))``:
    scaling by 1/out_degree once per vertex (instead of once per slot)
    removes the per-slot weight array entirely — half the slot bytes
    streamed from HBM — and inert slots (ELL padding, duplicate edges)
    simply point at the zero sentinel block ``z_ext[n_pad:]``. When the
    caller performs the prescale multiply in the accumulation dtype
    (jax_engine does), products are bit-identical to the per-slot form:
    w_slot was exactly ``inv_out[src]``.

    Args:
      z_ext: [n_pad + gather_width] pre-scaled rank vector; the trailing
        ``gather_width`` lanes MUST be zero (sentinel block).
      src_slots: int32 [rows, 128] relabeled source per slot; inert slots
        hold the sentinel index ``n_pad``. When ``group`` > 1 the words
        are packed ``(src << log2(group)) | lane_sub`` (ops/ell.py
        grouped-lane layout; sentinel = ``n_pad << log2(group)``).
      row_block: int32 [rows] ascending dst-block id per row.
      num_blocks: static number of 128-lane dst blocks.
      chunk_rows: process slot rows in chunks of this size via lax.scan —
        bounds the (slots, gather_width) gather intermediate (which would
        otherwise materialize ~8x the slot array in HBM). Must divide the
        row count. None = single chunk.
      group: lane-group size of the packing (static).
      num_present: static count of DISTINCT blocks with rows. When set,
        ``row_block`` must hold dense block RANKS (0..num_present-1,
        gap-free ascending) and the result is the COMPACT
        [num_present * 128] sums — the slab-scan mode of
        _chunked_block_sum, whose carry traffic is O(chunk), not
        O(num_blocks); the caller expands ranks to blocks. None keeps
        global block ids and a full-width result.
      window_rows: partition-centric mode (ops/ell.py
        "Partition-centric sub-binning"). When > 0, ``z_ext`` is the
        PARTITION-PADDED table (each partition's span followed by
        ``gather_width`` zero lanes), slot words are PARTITION-LOCAL
        (3-byte planar int8 when ``src_slots.dtype`` is int8 —
        :func:`unpack_words24` — int32 otherwise), ``row_block``
        carries CHUNK-LOCAL dense (partition, block)-pair ranks, and
        each chunk's gather reads only the ``window_rows``-row
        dynamic slice of the table starting at its prefetched window
        base — the chunk's whole gather working set, sized to stay
        VMEM/cache-resident. Requires ``chunk_bases`` and
        ``num_present`` (the compact result is per PAIR).
      chunk_bases: int32 [num_chunks, 2] per-chunk (window row base,
        slab rank base) — see _chunked_block_sum.

    Returns:
      [num_blocks * 128] contribution sums (relabeled, padded), or
      [num_present * 128] compact sums when ``num_present`` is set
      (per (partition, block) pair in partition-centric mode).
    """
    acc = accum_dtype or (
        z_ext.dtype if z_ext.dtype.itemsize >= 4 else jnp.float32
    )
    zw = z_ext.reshape(-1, gather_width)
    shift = gather_width.bit_length() - 1
    mask = gather_width - 1
    log2g = group.bit_length() - 1
    if (window_rows > 0) != (chunk_bases is not None):
        raise ValueError("window_rows and chunk_bases go together")
    if window_rows and num_present is None:
        raise ValueError("partition-centric mode needs num_present")
    # Low-precision streamed table (config.stream_dtype): the one-hot
    # select runs in the TABLE dtype — products are x*1 or x*0 and the
    # row-sum has exactly one nonzero term, so selection is EXACT at
    # any float dtype — and only the selected (chunk, 128) values are
    # widened to the accumulation dtype. Keeps the dominant
    # (chunk, 128, gather_width) gather intermediates at stream width.
    sel_dt = (
        zw.dtype
        if jnp.dtype(zw.dtype).itemsize < jnp.dtype(acc).itemsize
        else acc
    )

    def select(rows, lane_ix):
        sel = jax.nn.one_hot(lane_ix, gather_width, dtype=sel_dt)
        return (rows.astype(sel_dt) * sel).sum(-1).astype(acc)

    def chunk_sum(src_c, rb_c, nseg, *base):
        if src_c.dtype == jnp.int8:
            src_c = unpack_words24(src_c)
        if group > 1:
            sub = src_c & (group - 1)
            src_c = src_c >> log2g
        if window_rows:
            table = jax.lax.dynamic_slice(
                zw, (base[0], jnp.zeros((), base[0].dtype)),
                (window_rows, gather_width),
            )
        else:
            table = zw
        rows = table[src_c >> shift]  # (chunk, 128, gather_width)
        v = select(rows, src_c & mask)
        if group > 1:
            v = _group_scatter(v, sub, group, acc)
        rb_c = rb_c.astype(jnp.int32)  # chunk-local ranks may be int16
        return jax.ops.segment_sum(
            v, rb_c, num_segments=nseg, indices_are_sorted=True
        )

    return _chunked_block_sum(
        chunk_sum, src_slots, row_block, chunk_rows,
        num_present or num_blocks, slab=num_present is not None,
        chunk_bases=chunk_bases,
    ).reshape(-1)


def ell_contrib_pair(z_hi_ext, z_lo_ext, src_slots, row_block, num_blocks,
                     accum_dtype=None, gather_width=8, chunk_rows=None,
                     group=1, num_present=None):
    """``ell_contrib`` with the pre-scaled rank vector carried as an exact
    f32 (hi, lo) pair and the reduction done in a wide dtype — the fast
    path to f64-grade accuracy on TPU (which has no native f64).

    The per-vertex values are ``z = hi + lo`` exactly (hi = f32(z64),
    lo = f32(z64 - hi) — a Dekker split of the f64 prescale). hi and lo
    rows are packed side by side into ONE (n/w, 2w) gather table, so the
    expensive row gather runs once at plain-f32 cost; the two one-hot
    contractions are exact (pure selection), and only the per-slot
    ``hi64 + lo64`` add and the row/block segment-sum pay the emulated
    f64 price. Per-iteration rounding is then O(2^-48) relative, vs
    O(2^-24) for the plain f32 path — the 1e-6 L1 north-star gate
    (BASELINE.md) with room to spare, at a fraction of full-f64 cost.

    Row-byte note: the packed row is ``2*gather_width`` f32 lanes; the
    fast-gather regime needs rows <= 512B, so gather_width caps at 64
    here (vs 128 for the plain table).

    Args:
      z_hi_ext, z_lo_ext: [n_pad + gather_width] f32 pair; trailing
        ``gather_width`` lanes MUST be zero (sentinel block).
      src_slots, row_block, num_blocks, chunk_rows: as in ``ell_contrib``.
      accum_dtype: reduction dtype, default float64 (requires x64).

    Returns:
      [num_blocks * 128] contribution sums in accum_dtype.
    """
    acc = accum_dtype or jnp.float64
    w = gather_width
    shift = w.bit_length() - 1
    mask = w - 1
    log2g = group.bit_length() - 1
    zw = jnp.concatenate(
        [z_hi_ext.reshape(-1, w), z_lo_ext.reshape(-1, w)], axis=1
    )  # (n_pad/w + 1, 2w): hi lanes then lo lanes, sentinel row all-zero

    def chunk_sum(src_c, rb_c, nseg):
        if group > 1:
            sub = src_c & (group - 1)
            src_c = src_c >> log2g
        rows = zw[src_c >> shift]  # (chunk, 128, 2w) — ONE gather
        sel = jax.nn.one_hot(src_c & mask, w, dtype=rows.dtype)
        v_hi = (rows[..., :w] * sel).sum(-1)  # exact: selection
        v_lo = (rows[..., w:] * sel).sum(-1)  # exact: selection
        v = v_hi.astype(acc) + v_lo.astype(acc)
        if group > 1:
            v = _group_scatter(v, sub, group, acc)
        return jax.ops.segment_sum(
            v, rb_c, num_segments=nseg, indices_are_sorted=True
        )

    return _chunked_block_sum(
        chunk_sum, src_slots, row_block, chunk_rows,
        num_present or num_blocks, slab=num_present is not None,
    ).reshape(-1)


def ell_contrib_spmm(z2_ext, src_slots, row_block, num_blocks,
                     accum_dtype=None, chunk_rows=None, num_present=None):
    """Batched blocked-ELL contribution (SpMM): k personalized rank
    columns at once (BASELINE.md config 5).

    Where the rank-vector path reshapes a 1-D table into (rows, width)
    lanes, the batch IS the row here: ``z2_ext`` is a (sz + 1, k)
    pre-scaled rank *matrix* slice whose LAST row is the zero sentinel
    (inert slots point at index sz). One row gather per slot fetches all
    k columns — the per-slot issue cost is paid once for k columns of
    work, so edge·vector throughput scales ~k-fold over the vector path
    while the table stays inside the fast-gather regime (callers stripe
    sources so sz + 1 <= 2**17 rows; k*4B <= 512B rows for f32 k<=128).

    Args:
      z2_ext: [sz + 1, k] pre-scaled rank columns; last row MUST be zero.
      src_slots: int32 [rows, 128] stripe-local source per slot (sz for
        inert slots).
      row_block: int32 [rows] ascending dst-block id per row.
      num_blocks: static number of 128-lane dst blocks.
      chunk_rows: lax.scan chunking (bounds the (chunk, 128, k) gather
        intermediate); must divide the row count. None = single chunk.
      num_present: as in :func:`ell_contrib` — dense-rank ``row_block``
        and a compact [num_present * 128, k] result (the slab carry
        matters k-fold more here than in the vector path).

    Returns:
      [num_blocks * 128, k] contribution sums in accum_dtype, or the
      compact [num_present * 128, k] when ``num_present`` is set.
    """
    acc = accum_dtype or z2_ext.dtype
    k = z2_ext.shape[1]

    def chunk_sum(src_c, rb_c, nseg):
        v = z2_ext[src_c].astype(acc)  # (chunk, 128, k) row gather
        return jax.ops.segment_sum(
            v, rb_c, num_segments=nseg, indices_are_sorted=True
        )

    return _chunked_block_sum(
        chunk_sum, src_slots, row_block, chunk_rows,
        num_present or num_blocks, slab=num_present is not None,
    ).reshape((num_present or num_blocks) * LANES, k)


def scatter_block_sums(total, part, ids, is_prefix):
    """Add compact per-present-block sums ``part`` ([P, 128, ...]) into
    the global block array ``total`` ([num_blocks, 128, ...]): a static
    prefix-slice add when the present blocks are 0..P-1, else a
    sorted-unique scatter-add at ``ids``."""
    if is_prefix:
        return total.at[: part.shape[0]].add(part)
    return total.at[ids].add(
        part, indices_are_sorted=True, unique_indices=True
    )


def dangling_mass(r, dangling, accum_dtype=None):
    """m = Σ_{out_degree==0} r — the reference's ``danglingContrib`` loop
    (one distributed lookup per dangling URL per iteration,
    Sparky.java:219-222) collapsed to a single on-device reduction.

    The reduction is a masked elementwise-multiply + sum, NOT a
    dot/matmul, whenever the accumulation is 64-bit: XLA lowers an f64
    dot on TPU through reduced-precision dot hardware (measured 9.5e-8
    relative error at 1M terms vs 2e-14 for multiply+sum), and since
    ``m/N`` feeds EVERY vertex, that error excites the global scale
    mode reference semantics amplifies — docs/PERF_NOTES.md
    "Reference-mode mass growth and the f64-vdot lowering bug". The 2-D
    (PPR batch) form keeps the matmul ONLY for 32-bit accumulation,
    where the MXU path is full precision by design."""
    acc = jnp.dtype(accum_dtype or r.dtype)
    d = dangling.astype(acc)
    if r.ndim == 2:
        if acc.itemsize < 8:
            return d @ r.astype(acc)
        return jnp.sum(d[:, None] * r.astype(acc), axis=0)
    return jnp.sum(d * r.astype(acc))
