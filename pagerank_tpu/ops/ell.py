"""Blocked-ELL edge packing — the TPU-native sparse format for the
contribution scatter (C13/C16 in SURVEY.md §2).

Why: XLA's per-element scatter-add on TPU runs at ~100M edges/s (measured
on v5e), two orders of magnitude under HBM bandwidth. Packing edges into
(row, 128-lane) slots with lane = dst % 128 turns the per-edge scatter
into a per-*row* segment-sum (128x fewer scatter keys) and a dense
axis-0 sum — both fast on TPU. The gather side uses an 8-wide row-gather
(one_hot dot over a (N/8, 8) view of the rank vector), the fastest XLA
gather form measured on this chip (~235M slots/s vs ~100M for 1-D take).

Layout:
  - vertices are RELABELED by descending in-degree (stable), so the 128
    dsts sharing a block have similar in-degree and the per-block depth
    max(in_degree) wastes little padding on power-law graphs;
  - dst-block b owns lanes 0..127 = relabeled dsts b*128..b*128+127;
  - slot (r, l) of block b holds one in-edge of dst b*128+l; a block's
    rows are its in-degree depth; blocks are concatenated into tall
    (rows_total, 128) arrays with a per-row block id;
  - padding slots have weight 0 and src 0;
  - blocks whose 128 dsts all have in-degree 0 produce no rows at all
    (zero-in vertices cost nothing in the SpMV).

Grouped-lane variant (``group`` > 1): per-block rows cost
max-over-LANES(in_degree), which on measured power-law graphs is 20-30%
padding even after the in-degree sort. Letting a slot serve ANY of
``group`` adjacent lanes collapses that to max-over-GROUPS(ceil(
group_edges / group)) — ~8% at group=8 on R-MAT — at the cost of one
extra ``group``-wide one-hot redistribution per slot in the SpMV (VPU
noise next to the gather). Slot words are then packed as
``(src << log2(group)) | lane_sub``: the slot at row position p serves
lane ``(p & ~(group-1)) | lane_sub``. group=1 keeps plain source ids.

Partition-centric sub-binning (ISSUE 6; Lakhotia et al.,
arXiv:1709.07122): packing with ``stripe_size`` set to a PARTITION span
(config.partition_span) makes the stripes source partitions — slots
are sub-binned by source partition WITHIN each dst block by the same
single composite-key sort, a build-time static permutation. The engine
(engines/jax_engine.py:_setup_ell_partitioned) then concatenates the
partitions partition-major into ONE chunked sweep whose per-chunk
gather reads only its partition's window of the rank table
(ops/spmv.py:ell_contrib window mode), stores slot words
partition-local (3-byte planar int8 when span*group < 2^24 —
ops/spmv.py:pack_words24), and expands the compact per-(partition,
block)-pair sums with one sorted-unique scatter per partition. The
span must keep (partition, dst-block) cells DENSE — every nonempty
cell still costs ceil-granular rows, exactly the striping padding
floor — which is what JaxTpuEngine.partition_span's auto rule gates.

All ids inside the packed arrays are in RELABELED space; `perm` maps
relabeled -> original id, `inv_perm` the reverse.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from pagerank_tpu.graph import Graph
from pagerank_tpu.ops import LANES


@dataclass
class EllPack:
    """Destination-blocked ELL representation of a graph (relabeled)."""

    n: int  # vertex count (unpadded)
    n_padded: int  # next multiple of 128
    num_blocks: int  # n_padded // 128
    src: np.ndarray  # int32 [rows, 128] — RELABELED source id per slot; packed (src << log2(group)) | lane_sub when group > 1
    weight: np.ndarray  # float64 [rows, 128] — 1/out_degree, 0 for padding (cast to compute dtype at device placement)
    row_block: np.ndarray  # int32 [rows] — dst block id per row, ascending
    perm: np.ndarray  # int32 [n] — relabeled id -> original id
    inv_perm: np.ndarray  # int32 [n] — original id -> relabeled id
    num_real_edges: int
    group: int = 1  # lane-group size (see module docstring)

    @property
    def num_rows(self) -> int:
        return int(self.src.shape[0])

    @property
    def padding_ratio(self) -> float:
        slots = self.num_rows * LANES
        return slots / max(1, self.num_real_edges)


def ell_pack(graph: Graph, group: int = 1, block_deal: int = 0) -> EllPack:
    """Pack a dst-sorted COO graph into blocked-ELL form (the
    single-stripe specialization of :func:`ell_pack_striped` — one stripe
    spanning the whole padded vertex range, so stripe-local source ids
    equal relabeled ids)."""
    n_padded = -(-graph.n // LANES) * LANES
    sp = ell_pack_striped(graph, stripe_size=max(LANES, n_padded), group=group,
                          block_deal=block_deal)
    if sp.n_stripes == 0:  # n == 0 edge case: no stripes at all
        src = np.zeros((0, LANES), np.int32)
        weight = np.zeros((0, LANES), np.float64)
        row_block = np.zeros(0, np.int32)
    else:
        src, weight, row_block = sp.src[0], sp.weight[0], sp.row_block[0]
    return EllPack(
        n=sp.n, n_padded=sp.n_padded, num_blocks=sp.num_blocks,
        src=src, weight=weight, row_block=row_block,
        perm=sp.perm, inv_perm=sp.inv_perm,
        num_real_edges=sp.num_real_edges, group=group,
    )


@dataclass
class StripedEllPack:
    """ELL packing split into contiguous SOURCE-range stripes.

    The fast XLA gather regime caps the reshaped rank table at 2**17 rows
    of <=512B (engines/jax_engine.py:_gather_width), i.e. ~16.8M vertices
    for a plain f32 table and ~8.4M for the pair-packed one. Larger
    graphs split the (relabeled) vertex range into ``n_stripes``
    contiguous stripes; each stripe packs ONLY the edges whose source
    lies in it, with stripe-LOCAL source indices, so each per-stripe
    gather table stays in the fast regime. The solver sums the stripes'
    block outputs (same dst-block space) before the mesh psum.

    Per-stripe padding: a dst block contributes rows to every stripe
    that feeds it, so total slots grow with stripe count on hub-heavy
    blocks — the price of keeping the gather fast (SURVEY.md §7 "hard
    parts": power-law skew).
    """

    n: int
    n_padded: int
    num_blocks: int
    stripe_size: int  # vertices per stripe (multiple of 128; last may be short of n_padded)
    src: list  # [stripes] int32 [rows_s, 128] — STRIPE-LOCAL source per slot (packed with lane_sub when group > 1)
    weight: list  # [stripes] float64 [rows_s, 128]
    row_block: list  # [stripes] int32 [rows_s], ascending per stripe
    perm: np.ndarray
    inv_perm: np.ndarray
    num_real_edges: int
    group: int = 1

    @property
    def n_stripes(self) -> int:
        return len(self.src)

    @property
    def num_rows(self) -> int:
        return int(sum(s.shape[0] for s in self.src))

    @property
    def padding_ratio(self) -> float:
        return self.num_rows * LANES / max(1, self.num_real_edges)


def deal_block_order(n: int, n_padded: int, ndev: int,
                     weights=None) -> np.ndarray:
    """Block-level deal permutation for destination-partitioned
    (owner-computes) vertex sharding: dst blocks — 128-vertex groups of
    the in-degree-DESCENDING relabel, so block index is depth rank —
    are dealt across ``ndev`` contiguous device ranges of
    ``ceil(num_blocks/ndev)`` block slots each by capacity-constrained
    LPT (longest-processing-time greedy: each block, visited in depth
    order, goes to the least-loaded device with slots left). Each
    device then owns a near-equal share of slot rows — measured
    max/mean 1.01 at R-MAT scale 20 vs 1.83 for round-robin (the
    single hottest block can't be split, so round-robin's fixed stride
    leaves the ceil-floor skew unbalanced) and 7.3 for undealt
    contiguous ranges. FILLED slots stay contiguous from 0: the
    partial block (n % 128 vertices), if any, lands globally last, and
    virtual padding block slots trail it — so the dealt vertex order
    is still a dense permutation of [0, n).

    ``weights``: per-filled-block load estimates ([n_padded/128]
    array; the packer passes exact unstriped row counts). None = equal
    weights (degenerates to round-robin-with-quotas).

    The greedy loop is a Python heap over the blocks — O(nb log ndev),
    ~2s at 524k blocks (scale 26), amortized into a build that is
    minutes at that scale.

    Returns ``new_of_old`` (int64 [n_padded/128]): old block id -> new
    block id. New block ids b land on device b // ceil(nb/ndev).
    """
    import heapq

    nb_fill = n_padded // LANES
    nb_full = n // LANES
    partial = nb_fill != nb_full
    nbd = -(-nb_fill // ndev)
    devs = np.arange(ndev)
    # Filled-slot capacity per device: filled slots pack global new ids
    # 0..nb_fill-1, so trailing devices may be short or empty.
    cap = np.clip(nb_fill - devs * nbd, 0, nbd)
    quota = cap.copy()
    if partial:
        quota[(nb_fill - 1) // nbd] -= 1  # reserve the LAST filled slot
    if weights is None:
        w = np.ones(nb_fill)
    else:
        w = np.asarray(weights, dtype=np.float64)
        if w.shape != (nb_fill,):
            raise ValueError(
                f"weights must have shape ({nb_fill},), got {w.shape}"
            )
    # LPT with capacities; ties broken by device id for determinism.
    heap = [(0.0, int(d)) for d in devs]
    counts = np.zeros(ndev, np.int64)
    new_of_old = np.empty(nb_fill, np.int64)
    for j in range(nb_full):
        while True:
            load, d = heapq.heappop(heap)
            if counts[d] < quota[d]:
                break
        new_of_old[j] = d * nbd + counts[d]
        counts[d] += 1
        heapq.heappush(heap, (load + w[j], d))
    if partial:
        new_of_old[nb_full] = nb_fill - 1
    return new_of_old


def block_row_weights(in_degree_sorted: np.ndarray, n_padded: int,
                      group: int) -> np.ndarray:
    """Exact unstriped slot-row count per dst block from the in-degree
    vector in RELABELED (descending) order — the packer's own formula
    (rows = max over lane groups of ceil(group_edges/group), min 1) —
    used as the LPT deal weight. Striping adds per-stripe row floors on
    top; this remains the right relative ordering."""
    nb = n_padded // LANES
    pad = n_padded - len(in_degree_sorted)
    d = np.concatenate([
        in_degree_sorted.astype(np.int64), np.zeros(pad, np.int64)
    ])
    ge = d.reshape(nb, LANES // group, group).sum(axis=2)
    return np.maximum(1, -(-ge.max(axis=1) // group))


def ell_pack_striped(
    graph: Graph, stripe_size: int, group: int = 1, block_deal: int = 0
) -> StripedEllPack:
    """Pack a graph into source-striped blocked-ELL form.

    ``stripe_size`` must be a positive multiple of 128; sources with
    relabeled id in [s*stripe_size, (s+1)*stripe_size) land in stripe s.
    ``group`` (power of two, <= 128) enables the grouped-lane layout:
    slot words become ``(src << log2(group)) | lane_sub``.
    ``block_deal`` > 1 composes :func:`deal_block_order` over that many
    device ranges into the relabel (the dst-partitioned vertex-sharded
    mode); per-block lane composition — and therefore ELL padding — is
    unchanged, only whole blocks move.
    """
    if stripe_size <= 0 or stripe_size % LANES:
        raise ValueError(f"stripe_size must be a positive multiple of {LANES}")
    if group < 1 or group > LANES or (group & (group - 1)):
        raise ValueError(f"group must be a power of two in [1, {LANES}]")
    n = graph.n
    n_padded = -(-n // LANES) * LANES
    num_blocks = n_padded // LANES
    n_stripes = -(-n_padded // stripe_size)

    order = np.argsort(-graph.in_degree.astype(np.int64), kind="stable")
    if block_deal > 1 and n:
        new_of_old = deal_block_order(
            n, n_padded, block_deal,
            weights=block_row_weights(
                graph.in_degree[order], n_padded, group
            ),
        )
        ids = np.arange(n, dtype=np.int64)
        new_pos = new_of_old[ids // LANES] * LANES + (ids % LANES)
        dealt = np.empty(n, order.dtype)
        dealt[new_pos] = order
        order = dealt
    perm = order.astype(np.int32)
    inv_perm = np.empty(n, dtype=np.int32)
    inv_perm[perm] = np.arange(n, dtype=np.int32)

    new_dst = inv_perm[graph.dst].astype(np.int64)
    new_src = inv_perm[graph.src].astype(np.int64)
    stripe_of = new_src // stripe_size
    # Sort edges by (stripe, dst, relabeled src): dst-major slot order
    # within each stripe, relabeled-src-ascending within a dst — the
    # same total order as the device builder's single composite-key
    # sort (ops/device_build.py:_relabel_sort), so the two packers
    # agree slot-for-slot. (Graph inputs here are pre-deduplicated by
    # build_graph, so the device builder's raw-in-degree relabel also
    # matches this packer's unique-in-degree argsort exactly.)
    sort = np.lexsort((new_src, new_dst, stripe_of))
    new_dst = new_dst[sort]
    new_src = new_src[sort]
    weight = graph.edge_weight[sort]
    stripe_of = stripe_of[sort]

    log2g = group.bit_length() - 1
    if group > 1 and (stripe_size + 1) << log2g > np.iinfo(np.int32).max:
        raise ValueError(
            f"grouped slot words overflow int32: stripe_size {stripe_size} "
            f"* group {group}"
        )
    srcs, weights, row_blocks = [], [], []
    bounds = np.searchsorted(stripe_of, np.arange(n_stripes + 1))
    for s in range(n_stripes):
        lo, hi = bounds[s], bounds[s + 1]
        d_s = new_dst[lo:hi]
        s_s = (new_src[lo:hi] - s * stripe_size).astype(np.int32)
        w_s = weight[lo:hi]
        e = d_s.shape[0]
        if e == 0:
            srcs.append(np.zeros((0, LANES), np.int32))
            weights.append(np.zeros((0, LANES), np.float64))
            row_blocks.append(np.zeros(0, np.int32))
            continue
        block = d_s // LANES
        # Lane-group run index: with group=1 a "lane group" is a single
        # dst and this reduces exactly to per-dst depth. d_s is sorted,
        # so groups are runs; k counts a slot's rank within its group.
        grp = d_s >> log2g
        gstarts = np.flatnonzero(np.r_[True, grp[1:] != grp[:-1]])
        cnt = np.diff(np.r_[gstarts, e])
        k = np.arange(e, dtype=np.int64) - np.repeat(gstarts, cnt)
        row = k >> log2g
        pos = ((d_s % LANES) >> log2g) * group + (k & (group - 1))
        # Rows per block within THIS stripe = max over its lane groups of
        # ceil(group_edges / group) (counts are NOT monotone within a
        # stripe, so a real max is needed). Only blocks present in the
        # stripe are touched (O(e_s), not O(n)).
        g_rows = -(-cnt // group)
        log2_lanes = LANES.bit_length() - 1
        gb = grp[gstarts] >> (log2_lanes - log2g)  # block id per group run
        bstarts = np.flatnonzero(np.r_[True, gb[1:] != gb[:-1]])
        block_rows = np.zeros(num_blocks, np.int64)
        block_rows[gb[bstarts]] = np.maximum.reduceat(g_rows, bstarts)
        row_offset = np.concatenate([[0], np.cumsum(block_rows)])
        rows_total = int(row_offset[-1])
        src_slots = np.zeros((rows_total, LANES), np.int32)
        w_slots = np.zeros((rows_total, LANES), np.float64)
        flat = (row_offset[block] + row) * LANES + pos
        word = (
            s_s if group == 1
            else (s_s.astype(np.int32) << log2g)
            | (d_s & (group - 1)).astype(np.int32)
        )
        src_slots.reshape(-1)[flat] = word
        w_slots.reshape(-1)[flat] = w_s
        srcs.append(src_slots)
        weights.append(w_slots)
        row_blocks.append(
            np.repeat(np.arange(num_blocks, dtype=np.int32), block_rows)
        )

    return StripedEllPack(
        n=n, n_padded=n_padded, num_blocks=num_blocks,
        stripe_size=stripe_size, src=srcs, weight=weights,
        row_block=row_blocks, perm=perm, inv_perm=inv_perm,
        num_real_edges=int(new_dst.shape[0]), group=group,
    )


def dense_block_ranks(row_block: np.ndarray, num_blocks: int):
    """(ranks, present_ids, num_present, is_prefix) for a SORTED block-id
    array — the dense-rank inputs of the slab-scan accumulator
    (ops/spmv.py:_chunked_block_sum).

    ``ranks`` renumbers each distinct block to its 0-based run index
    (gap-free ascending), ``present_ids`` maps rank -> block id,
    ``is_prefix`` says the present blocks are exactly 0..num_present-1
    (letting callers expand with a static-slice add instead of a
    scatter). Empty input gets one sentinel id so downstream shapes stay
    non-empty (its sums are all zero)."""
    rb = row_block
    starts = (
        np.concatenate([[True], rb[1:] != rb[:-1]])
        if len(rb) else np.zeros(0, bool)
    )
    ids = rb[starts].astype(np.int32)
    ranks = (np.cumsum(starts) - 1).astype(np.int32)
    pcount = max(1, len(ids))
    prefix = bool(len(ids) == ids[-1] + 1 if len(ids) else True)
    if len(ids) == 0:
        ids = np.array([num_blocks - 1], np.int32)
    return ranks, ids, pcount, prefix


def ell_spmv_reference(pack: EllPack, z: np.ndarray) -> np.ndarray:
    """Numpy oracle for the packed SpMV: y[d] = sum over in-edges of
    z[src]*w, in RELABELED space. z and result are length n (relabeled)."""
    g = pack.group
    y2 = np.zeros((pack.num_blocks, LANES), dtype=z.dtype)
    if g == 1:
        v = z[pack.src] * pack.weight  # (rows, 128)
        np.add.at(y2, pack.row_block, v)
    else:
        log2g = g.bit_length() - 1
        v = z[pack.src >> log2g] * pack.weight
        pos = np.arange(LANES)
        lane = (pos[None, :] & ~(g - 1)) | (pack.src & (g - 1))
        np.add.at(y2, (pack.row_block[:, None], lane), v)
    return y2.reshape(-1)[: pack.n]
