"""Blocked-ELL edge packing — the TPU-native sparse format for the
contribution scatter (C13/C16 in SURVEY.md §2).

Why: XLA's per-element scatter-add on TPU runs at ~100M edges/s (measured
on v5e), two orders of magnitude under HBM bandwidth. Packing edges into
(row, 128-lane) slots with lane = dst % 128 turns the per-edge scatter
into a per-*row* segment-sum (128x fewer scatter keys) and a dense
axis-0 sum — both fast on TPU. The gather side uses an 8-wide row-gather
(one_hot dot over a (N/8, 8) view of the rank vector), the fastest XLA
gather form measured on this chip (~235M slots/s vs ~100M for 1-D take).

Layout:
  - vertices are RELABELED by descending in-degree (stable), so the 128
    dsts sharing a block have similar in-degree and the per-block depth
    max(in_degree) wastes little padding on power-law graphs;
  - dst-block b owns lanes 0..127 = relabeled dsts b*128..b*128+127;
  - slot (r, l) of block b holds one in-edge of dst b*128+l; a block's
    rows are its in-degree depth; blocks are concatenated into tall
    (rows_total, 128) arrays with a per-row block id;
  - padding slots have weight 0 and src 0;
  - blocks whose 128 dsts all have in-degree 0 produce no rows at all
    (zero-in vertices cost nothing in the SpMV).

All ids inside the packed arrays are in RELABELED space; `perm` maps
relabeled -> original id, `inv_perm` the reverse.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from pagerank_tpu.graph import Graph

LANES = 128


@dataclass
class EllPack:
    """Destination-blocked ELL representation of a graph (relabeled)."""

    n: int  # vertex count (unpadded)
    n_padded: int  # next multiple of 128
    num_blocks: int  # n_padded // 128
    src: np.ndarray  # int32 [rows, 128] — RELABELED source id per slot
    weight: np.ndarray  # float64 [rows, 128] — 1/out_degree, 0 for padding (cast to compute dtype at device placement)
    row_block: np.ndarray  # int32 [rows] — dst block id per row, ascending
    perm: np.ndarray  # int32 [n] — relabeled id -> original id
    inv_perm: np.ndarray  # int32 [n] — original id -> relabeled id
    num_real_edges: int

    @property
    def num_rows(self) -> int:
        return int(self.src.shape[0])

    @property
    def padding_ratio(self) -> float:
        slots = self.num_rows * LANES
        return slots / max(1, self.num_real_edges)


def ell_pack(graph: Graph) -> EllPack:
    """Pack a dst-sorted COO graph into blocked-ELL form."""
    n = graph.n
    n_padded = -(-n // LANES) * LANES

    # Relabel by descending in-degree (stable => deterministic).
    order = np.argsort(-graph.in_degree.astype(np.int64), kind="stable")
    perm = order.astype(np.int32)  # relabeled -> original
    inv_perm = np.empty(n, dtype=np.int32)
    inv_perm[perm] = np.arange(n, dtype=np.int32)

    # Relabeled edges, sorted by new dst then slot order.
    new_dst = inv_perm[graph.dst].astype(np.int64)
    new_src = inv_perm[graph.src].astype(np.int32)
    sort = np.argsort(new_dst, kind="stable")
    new_dst = new_dst[sort]
    new_src = new_src[sort]
    weight = graph.edge_weight[sort]  # float64; engine casts to compute dtype

    # Per-edge slot depth: k-th in-edge of its dst (0-based). new_dst is
    # sorted, so depth = position - first-position-of-dst.
    e = new_dst.shape[0]
    if e == 0:
        return EllPack(
            n=n, n_padded=n_padded, num_blocks=n_padded // LANES,
            src=np.zeros((0, LANES), np.int32),
            weight=np.zeros((0, LANES), np.float64),
            row_block=np.zeros(0, np.int32),
            perm=perm, inv_perm=inv_perm, num_real_edges=0,
        )
    first = np.searchsorted(new_dst, new_dst)  # first index of each dst value
    depth = (np.arange(e, dtype=np.int64) - first).astype(np.int64)

    block = new_dst // LANES  # per-edge dst block
    lane = (new_dst % LANES).astype(np.int64)

    # Rows per block = max in-degree within the block. After the
    # descending in-degree relabel, in-degrees are non-increasing, so the
    # block max is simply the block's FIRST vertex's in-degree — no
    # scatter-max needed (np.maximum.at is pathologically slow at scale).
    num_blocks = n_padded // LANES
    indeg_rel = np.zeros(n_padded, dtype=np.int64)
    indeg_rel[:n] = graph.in_degree[perm]
    block_rows = indeg_rel[0::LANES].copy()

    row_offset = np.concatenate([[0], np.cumsum(block_rows)])
    rows_total = int(row_offset[-1])

    src_slots = np.zeros((rows_total, LANES), dtype=np.int32)
    w_slots = np.zeros((rows_total, LANES), dtype=np.float64)
    flat_pos = (row_offset[block] + depth) * LANES + lane
    src_flat = src_slots.reshape(-1)
    w_flat = w_slots.reshape(-1)
    src_flat[flat_pos] = new_src
    w_flat[flat_pos] = weight

    row_block = np.repeat(
        np.arange(num_blocks, dtype=np.int32), block_rows
    )

    return EllPack(
        n=n, n_padded=n_padded, num_blocks=num_blocks,
        src=src_slots, weight=w_slots, row_block=row_block,
        perm=perm, inv_perm=inv_perm, num_real_edges=e,
    )


def ell_spmv_reference(pack: EllPack, z: np.ndarray) -> np.ndarray:
    """Numpy oracle for the packed SpMV: y[d] = sum over in-edges of
    z[src]*w, in RELABELED space. z and result are length n (relabeled)."""
    v = z[pack.src] * pack.weight  # (rows, 128)
    y2 = np.zeros((pack.num_blocks, LANES), dtype=z.dtype)
    np.add.at(y2, pack.row_block, v)
    return y2.reshape(-1)[: pack.n]
