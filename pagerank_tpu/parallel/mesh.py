"""Device mesh construction (L0) — the stand-in for the reference's
SparkConf/JavaSparkContext cluster bring-up (Sparky.java:40-41).

The framework's single parallel axis is the *edge dimension* (SURVEY.md
§2 P1/P5): a 1-D mesh whose devices each own a contiguous block of the
destination-sorted edge list. Rank vectors are replicated; per-iteration
communication is one `psum` of dense partials over ICI (intra-slice) or
DCN (multi-host).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    num_devices: Optional[int] = None,
    axis_name: str = "data",
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over the first ``num_devices`` visible devices (all by
    default)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devs)} visible"
            )
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for edge arrays: split along the (only) mesh axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for rank vectors / masks / scalars: fully replicated —
    the analogue of Spark broadcast variables (Sparky.java:135,162)."""
    return NamedSharding(mesh, P())


def vertex_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for PARTITIONED per-vertex state (config.vertex_sharded):
    contiguous vertex blocks over the mesh axis — the analogue of the
    reference's hash-partitioned ``ranks`` RDD (Sparky.java:165-170)."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


class DeadlineExpired(TimeoutError):
    """A deadline-bounded dispatch did not come back in time. The work
    may still complete later (the worker thread is daemonic and
    abandoned, never killed) — the CALLER's view is what timed out."""


def run_with_deadline(fn: Callable[[], object], timeout_s: float):
    """Run ``fn()`` on a worker thread and wait at most ``timeout_s``
    for it — the deadline-bounded dispatch primitive of the elastic
    layer (parallel/elastic.py). A device_get against a dead or wedged
    device blocks FOREVER inside the runtime; bounding it from a
    sibling thread is the only portable way to turn "hung" into a
    classifiable signal. Raises :class:`DeadlineExpired` on timeout and
    re-raises ``fn``'s own exception otherwise."""
    box: Dict[str, object] = {}
    done = threading.Event()

    def work():
        try:
            box["value"] = fn()
        except BaseException as e:  # surfaced to the caller below
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=work, name="pagerank-deadline-dispatch",
                         daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise DeadlineExpired(
            f"dispatch did not complete within {timeout_s:g}s"
        )
    if "error" in box:
        raise box["error"]
    return box["value"]


def deadline_device_get(value, timeout_s: float):
    """``jax.device_get(value)`` bounded by ``timeout_s`` (see
    :func:`run_with_deadline`)."""
    return run_with_deadline(lambda: jax.device_get(value), timeout_s)


def probe_liveness(devices: Optional[Sequence] = None,
                   timeout_s: float = 2.0,
                   clock: Callable[[], float] = time.monotonic,
                   ) -> Dict[int, bool]:
    """Per-device liveness: {device id: alive}. Each device gets one
    tiny round-trip (device_put + device_get of a scalar) under a
    SHARED deadline — a device that cannot answer a 4-byte echo within
    ``timeout_s`` is classified dead (preempted, wedged, or detached),
    which is exactly the hang-vs-device-lost discrimination the rescue
    path needs (parallel/elastic.py). All echoes launch CONCURRENTLY
    (one daemon thread each), so a mesh with several dead devices
    still classifies in ~``timeout_s`` total, not ndev * timeout_s.
    Any error — timeout or a backend exception from the dead device —
    counts as not-alive; the probe itself never raises.

    ``clock`` is injectable (the utils/retry.py discipline, PTR006):
    this runs in the stall watchdog's context, and virtual-time tests
    must be able to drive the shared deadline."""
    devs = list(devices) if devices is not None else list(jax.devices())
    results: Dict[int, bool] = {}

    def echo(dev):
        try:
            ok = int(jax.device_get(jax.device_put(np.int32(1), dev))) == 1
        except Exception:
            ok = False
        results[dev.id] = ok  # per-key dict writes are GIL-atomic

    threads = []
    for d in devs:
        t = threading.Thread(target=echo, args=(d,),
                             name="pagerank-liveness-probe", daemon=True)
        t.start()
        threads.append(t)
    deadline = clock() + timeout_s
    for t in threads:
        t.join(max(0.0, deadline - clock()))
    # A device whose echo thread missed the shared deadline is dead.
    return {d.id: results.get(d.id, False) for d in devs}


def surviving_devices(dead_ids, devices: Optional[Sequence] = None):
    """The visible device list minus ``dead_ids`` — the mesh substrate
    a rescue rebuilds over. Raises when nothing survives (there is no
    mesh to rescue onto; the caller surfaces that as terminal)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    dead = set(dead_ids)
    out = [d for d in devs if d.id not in dead]
    if not out:
        raise RuntimeError(
            f"no surviving devices: all of {sorted(d.id for d in devs)} "
            f"reported dead"
        )
    return out


@dataclasses.dataclass
class DeviceStats:
    """One device's identity + live memory sample — the STRUCTURED form
    of the old ``device_view()`` string (ISSUE 10): the watchdog line,
    the ``device.<id>.*`` exporter gauges, the Chrome-trace HBM counter
    tracks, and the run report's OOM-forensics watermark all render
    from this one record. Memory fields are None-tolerant by contract:
    CPU devices and older PJRT plugins report nothing
    (``memory_stats()`` returns None or raises), and a diagnostic must
    never fail gathering its own evidence."""

    id: int
    platform: str
    kind: str
    process_index: int
    bytes_in_use: Optional[int] = None
    bytes_limit: Optional[int] = None
    #: The backend's OWN peak watermark when it keeps one
    #: (``peak_bytes_in_use``); the sampler keeps a cross-sample
    #: watermark on top for backends that don't.
    peak_bytes_in_use: Optional[int] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _opt_int(stats: Optional[dict], key: str) -> Optional[int]:
    if not stats:
        return None
    v = stats.get(key)
    try:
        return int(v) if v is not None else None
    except (TypeError, ValueError):
        return None


def device_stats(devices: Optional[Sequence] = None) -> List[DeviceStats]:
    """Typed per-device samples (id, kind, process, HBM use/limit/peak)
    for ``devices`` (default: every visible device). THE one source of
    truth for per-device evidence — ``device_view()`` renders its
    strings from this, obs/devices.DeviceSampler feeds gauges, trace
    counter tracks, and the run-report watermark from it. Never raises;
    every memory field degrades to None independently."""
    out = []
    for d in devices if devices is not None else jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        out.append(DeviceStats(
            id=int(d.id),
            platform=str(d.platform),
            kind=str(d.device_kind),
            process_index=int(d.process_index),
            bytes_in_use=_opt_int(stats, "bytes_in_use"),
            bytes_limit=_opt_int(stats, "bytes_limit"),
            peak_bytes_in_use=_opt_int(stats, "peak_bytes_in_use"),
        ))
    return out


def _render_device_line(s: DeviceStats) -> str:
    """One watchdog line from one :class:`DeviceStats` — byte-identical
    to the historical ``device_view()`` formatting (pinned by
    tests/test_devices.py::test_device_view_renders_from_device_stats):
    the hbm clause appears only when ``bytes_in_use`` is known, the
    limit only when truthy."""
    line = f"{s.platform}:{s.id} ({s.kind}, proc {s.process_index})"
    if s.bytes_in_use is not None:
        line += f" hbm {s.bytes_in_use / 1e9:.2f}GB"
        if s.bytes_limit:
            line += f"/{s.bytes_limit / 1e9:.2f}GB"
    return line


def device_view(devices: Optional[Sequence] = None) -> Sequence[str]:
    """One human line per visible device — id, kind, process, and (when
    the backend reports it) live HBM use — the per-device evidence the
    stall watchdog prints when a multichip solve wedges (obs/live.py).
    A rendering of :func:`device_stats` (one source of truth; the
    string output is pinned byte-identical by a regression test)."""
    return [_render_device_line(s) for s in device_stats(devices)]
