"""Device mesh construction (L0) — the stand-in for the reference's
SparkConf/JavaSparkContext cluster bring-up (Sparky.java:40-41).

The framework's single parallel axis is the *edge dimension* (SURVEY.md
§2 P1/P5): a 1-D mesh whose devices each own a contiguous block of the
destination-sorted edge list. Rank vectors are replicated; per-iteration
communication is one `psum` of dense partials over ICI (intra-slice) or
DCN (multi-host).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    num_devices: Optional[int] = None,
    axis_name: str = "data",
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over the first ``num_devices`` visible devices (all by
    default)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devs)} visible"
            )
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for edge arrays: split along the (only) mesh axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for rank vectors / masks / scalars: fully replicated —
    the analogue of Spark broadcast variables (Sparky.java:135,162)."""
    return NamedSharding(mesh, P())


def vertex_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for PARTITIONED per-vertex state (config.vertex_sharded):
    contiguous vertex blocks over the mesh axis — the analogue of the
    reference's hash-partitioned ``ranks`` RDD (Sparky.java:165-170)."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def device_view() -> Sequence[str]:
    """One human line per visible device — id, kind, process, and (when
    the backend reports it) live HBM use — the per-device evidence the
    stall watchdog prints when a multichip solve wedges (obs/live.py).
    Memory stats are best-effort: CPU devices and older plugins return
    None, and a diagnostic must never fail gathering itself."""
    lines = []
    for d in jax.devices():
        line = f"{d.platform}:{d.id} ({d.device_kind}, proc {d.process_index})"
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            used = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit")
            if used is not None:
                line += f" hbm {used / 1e9:.2f}GB"
                if limit:
                    line += f"/{limit / 1e9:.2f}GB"
        lines.append(line)
    return lines
