"""Multi-host initialization (L0 over DCN).

The reference scales out via Spark's cluster manager + netty shuffle
(inherited, SURVEY.md §5 "Distributed communication backend"). The
TPU-native equivalent: `jax.distributed.initialize` brings up the
multi-host runtime; after that, the SAME solver code runs unchanged —
the 1-D edge mesh simply spans all hosts' devices, psum partials ride
ICI within a slice and DCN across slices. No shuffle machinery exists to
port: the graph is statically partitioned once (parallel/partition.py).

Single-host (or single-chip) runs skip initialization entirely.

Startup is RETRIED (ISSUE 7 satellite): at multihost bring-up the
coordinator and its workers race — a worker that dials before the
coordinator's port is bound sees a connection refusal/timeout that a
second attempt moments later would not. ``maybe_initialize_distributed``
therefore runs the initialize call under a ``utils/retry.RetryPolicy``
(jittered exponential backoff + a wall-clock deadline) instead of
aborting the whole run on the first transient; attempts land in the
``distributed.init_retries`` counter for the run report.
"""

from __future__ import annotations

import os
from typing import Optional

from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.utils.retry import RetryPolicy

#: Default bring-up policy: 5 attempts over at most ~2 minutes — wide
#: enough for a slow coordinator container, bounded enough that a
#: genuinely absent coordinator still fails the run promptly.
DEFAULT_INIT_RETRY = dict(max_attempts=5, base_delay=1.0, max_delay=15.0,
                          deadline=120.0)


def _init_retryable(exc: BaseException) -> bool:
    """Coordinator-race classifier: connection/timeout errors (and the
    RuntimeError/XlaRuntimeError spellings jax wraps them in when the
    coordinator is not yet listening) retry; everything else — bad
    process ids, double initialization — is a configuration error that
    must surface unchanged."""
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in (
        "deadline_exceeded", "deadline exceeded", "unavailable",
        "connection refused", "connection reset", "failed to connect",
        "barrier timed out", "timed out",
    ))


def maybe_initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    retry_policy: Optional[RetryPolicy] = None,
    _initialize=None,
) -> bool:
    """Initialize jax.distributed when multi-host context is present.

    Resolution order: explicit args > PAGERANK_TPU_* env vars > cloud
    TPU auto-detection (jax.distributed.initialize() with no args reads
    the TPU metadata server). Returns True if initialization ran.

    The initialize call runs under ``retry_policy`` (default:
    ``DEFAULT_INIT_RETRY`` — jittered backoff + deadline) so a
    transient coordinator race at startup costs a retry, not the run;
    re-attempts are counted in ``distributed.init_retries``.
    ``_initialize`` is injectable for tests (virtual-time schedules).
    """
    import jax

    init = _initialize if _initialize is not None else (
        jax.distributed.initialize
    )
    policy = retry_policy if retry_policy is not None else RetryPolicy(
        retryable=_init_retryable, **DEFAULT_INIT_RETRY
    )

    def on_retry(failures, delay, exc):
        obs_metrics.counter(
            "distributed.init_retries",
            "jax.distributed.initialize re-attempts after transient "
            "coordinator races at multihost startup",
        ).inc()
        obs_log.warn(
            f"jax.distributed.initialize attempt {failures} failed "
            f"({type(exc).__name__}: {str(exc)[:120]}); retrying in "
            f"{delay:.1f}s"
        )

    coordinator = coordinator_address or os.environ.get("PAGERANK_TPU_COORDINATOR")
    nproc = num_processes if num_processes is not None else _env_int("PAGERANK_TPU_NUM_PROCESSES")
    pid = process_id if process_id is not None else _env_int("PAGERANK_TPU_PROCESS_ID")

    if coordinator is not None:
        policy.call(
            lambda: init(
                coordinator_address=coordinator,
                num_processes=nproc,
                process_id=pid,
            ),
            on_retry=on_retry,
            retryable=_init_retryable,
        )
        return True
    if os.environ.get("TPU_WORKER_HOSTNAMES") and _env_int("TPU_WORKER_ID") is not None \
            and os.environ.get("PAGERANK_TPU_AUTO_DISTRIBUTED") == "1":
        policy.call(init, on_retry=on_retry, retryable=_init_retryable)
        return True
    return False


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def process_info():
    """(process_index, process_count) — (0, 1) when not distributed."""
    import jax

    return jax.process_index(), jax.process_count()
