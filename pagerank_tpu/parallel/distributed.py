"""Multi-host initialization (L0 over DCN).

The reference scales out via Spark's cluster manager + netty shuffle
(inherited, SURVEY.md §5 "Distributed communication backend"). The
TPU-native equivalent: `jax.distributed.initialize` brings up the
multi-host runtime; after that, the SAME solver code runs unchanged —
the 1-D edge mesh simply spans all hosts' devices, psum partials ride
ICI within a slice and DCN across slices. No shuffle machinery exists to
port: the graph is statically partitioned once (parallel/partition.py).

Single-host (or single-chip) runs skip initialization entirely.
"""

from __future__ import annotations

import os
from typing import Optional


def maybe_initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed when multi-host context is present.

    Resolution order: explicit args > PAGERANK_TPU_* env vars > cloud
    TPU auto-detection (jax.distributed.initialize() with no args reads
    the TPU metadata server). Returns True if initialization ran.
    """
    import jax

    coordinator = coordinator_address or os.environ.get("PAGERANK_TPU_COORDINATOR")
    nproc = num_processes if num_processes is not None else _env_int("PAGERANK_TPU_NUM_PROCESSES")
    pid = process_id if process_id is not None else _env_int("PAGERANK_TPU_PROCESS_ID")

    if coordinator is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=nproc,
            process_id=pid,
        )
        return True
    if os.environ.get("TPU_WORKER_HOSTNAMES") and _env_int("TPU_WORKER_ID") is not None \
            and os.environ.get("PAGERANK_TPU_AUTO_DISTRIBUTED") == "1":
        jax.distributed.initialize()
        return True
    return False


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def process_info():
    """(process_index, process_count) — (0, 1) when not distributed."""
    import jax

    return jax.process_index(), jax.process_count()
