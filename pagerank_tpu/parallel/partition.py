"""Static edge partitioning (replaces the reference's per-iteration
shuffles, SURVEY.md §2 P2).

Spark re-keys O(E) records across executors three times per iteration
(join/subtractByKey/reduceByKey, Sparky.java:192,224,229). Here the graph
is partitioned exactly once on the host: the destination-sorted edge list
is cut into equal-count contiguous chunks, one per device. Equal *edge*
count (not vertex count) is what balances work under power-law degree
skew — a heavy row simply spans several chunks and its partial sums meet
in the psum (the "Sparse Allreduce" pattern, PAPERS.md:5).

Padding edges carry weight 0 and dst = n-1, preserving both the
zero-contribution invariant and per-chunk dst-sortedness.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import numpy as np

from pagerank_tpu.graph import Graph


@dataclass
class EdgeShards:
    """Flat padded edge arrays, length divisible by num_shards; chunk i
    (contiguous) belongs to device i."""

    src: np.ndarray  # int32 [E_pad]
    dst: np.ndarray  # int32 [E_pad]
    weight: np.ndarray  # [E_pad] float, 0 on padding
    num_shards: int
    num_real_edges: int

    @property
    def edges_per_shard(self) -> int:
        return self.src.shape[0] // self.num_shards


def partition_edges(graph: Graph, num_shards: int, weight_dtype=np.float32) -> EdgeShards:
    """Cut the dst-sorted edge list into ``num_shards`` equal contiguous
    chunks, padding the tail with inert edges (w=0, dst=n-1)."""
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    e = graph.num_edges
    per = max(1, -(-e // num_shards))  # ceil; at least 1 so empty graphs still shard
    e_pad = per * num_shards
    pad = e_pad - e

    src = np.concatenate([graph.src, np.zeros(pad, np.int32)])
    dst = np.concatenate([graph.dst, np.full(pad, graph.n - 1, np.int32)])
    w = np.concatenate(
        [graph.edge_weight.astype(weight_dtype), np.zeros(pad, weight_dtype)]
    )
    return EdgeShards(
        src=src, dst=dst, weight=w, num_shards=num_shards, num_real_edges=e
    )


# -- sparse boundary exchange (ISSUE 8; Zhao & Canny, arXiv:1312.3020) -----
#
# The vertex-sharded step's dense exchange moves the WHOLE rank vector
# every iteration (all_gather of z + full-width reduce-scatter of the
# contribution merge), but on power-law graphs most of a chip's rank
# entries are irrelevant to most peers. The halo builder below derives,
# ONCE at build time from the packed slot tables, exactly which remote
# vertices each chip's edges actually gather (its per-owner READ SETS)
# and which destination ranges each chip's partials actually write (its
# WRITE BAND) — compacted into static int32 tables the step consumes as
# runtime arguments, so there is zero per-iteration host work and the
# per-iteration exchanged bytes scale with the BOUNDARY size instead of
# n. The high in-degree HEAD (read by nearly every shard on an
# RMAT/crawl graph) is replicated via one small psum instead of being
# repeated in every point-to-point pair set.

#: Minimum per-round payload width: degenerate 1-element rounds would
#: trace as scalar collectives (muddying the PTC001 bulk-vs-scalar
#: tally) and tiny payloads round up to a wire packet anyway.
_HALO_MIN_WIDTH = 8


@dataclass
class HaloRound:
    """One static point-to-point exchange round: a partial permutation
    over the mesh axis (``perm``: (source, target) device pairs — a
    ``lax.ppermute`` argument) carrying a fixed-width payload per
    device. Read rounds move z values owner -> reader at ring offset
    ``offset``; write rounds move contribution windows writer -> owner
    at signed block offset ``offset``."""

    offset: int
    width: int
    perm: Tuple[Tuple[int, int], ...]


@dataclass
class HaloPlan:
    """Build-time sparse-exchange plan for the vertex-sharded step.

    Tables are numpy, one row per device, ready for a sharded
    ``device_put``; pads are inert by construction (send pads index the
    owner's zero slot ``blk``, receive pads land on the trash slot
    ``n_vs`` / the trash band at local index ``blk``).

    Byte model convention (``docs/PERF_NOTES.md`` "Sparse boundary
    exchange"): bytes SENT per chip per iteration under the standard
    ring lowering — all_gather/reduce_scatter of an n-vector cost
    ``(ndev-1) * n/ndev`` sends per chip, an all-reduce twice that, a
    ppermute exactly its payload.
    """

    ndev: int
    n_vs: int  # padded sharded state length (multiple of 128 * ndev)
    blk: int  # vertices per device block (n_vs // ndev)
    head_k: int  # replicated head prefix [0, head_k), multiple of 128
    z_item: int  # bytes per exchanged z element
    accum_item: int  # bytes per exchanged contribution element
    rs_merge: bool  # dense comparator merges via reduce-scatter (vs psum)
    read_rounds: List[HaloRound] = field(default_factory=list)
    write_rounds: List[HaloRound] = field(default_factory=list)
    #: per read round: int32 [ndev, width] owner-LOCAL send indices
    #: (pad = blk, the owner's appended zero slot)
    send_idx: List[np.ndarray] = field(default_factory=list)
    #: per read round: int32 [ndev, width] GLOBAL ids of the entries
    #: each device receives (pad = n_vs, the trash slot)
    recv_ids: List[np.ndarray] = field(default_factory=list)
    #: per write round: int32 [ndev] flat global window start per
    #: sending device (inactive = n_vs, a zero region)
    wsend_start: List[np.ndarray] = field(default_factory=list)
    #: per write round: int32 [ndev] owner-local landing start per
    #: receiving device (inactive = blk, the trash band)
    wrecv_start: List[np.ndarray] = field(default_factory=list)
    #: total UNPADDED tail read-set entries over all (owner, reader)
    #: pairs — the boundary the exchange actually moves
    boundary_entries: int = 0
    #: [owner, reader] tail read-set sizes (diagnostics + oracle tests)
    reads_per_pair: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 0), np.int64)
    )

    @property
    def halo_fraction(self) -> float:
        """Fraction of the dense all_gather's remotely received entries
        that are actually read remotely (tail boundary over
        ``(ndev-1) * n_vs``) — the sparsity the exchange exploits."""
        denom = (self.ndev - 1) * self.n_vs
        return self.boundary_entries / denom if denom else 0.0

    def sparse_bytes_per_iter(self) -> int:
        """Modeled bytes sent per chip per iteration by the SPARSE
        exchange: head all-reduce + read-round payloads (z dtype) +
        write-round windows (accumulation dtype)."""
        if self.ndev <= 1:
            return 0
        head = 2 * (self.ndev - 1) * self.head_k * self.z_item // self.ndev
        reads = sum(r.width for r in self.read_rounds) * self.z_item
        writes = sum(r.width for r in self.write_rounds) * self.accum_item
        return int(head + reads + writes)

    def overlappable_bytes_per_iter(self) -> int:
        """The OVERLAPPABLE share of the sparse exchange (ISSUE 17;
        config.halo_async): head all-reduce + read-round payloads —
        the z-side traffic the stale-boundary double buffer moves off
        the critical path (the reads consume LAST iteration's buffer
        while this iteration's ships). The write-band merge stays
        synchronous: contribution windows are consumed by the rank
        update of the same iteration that produced them."""
        if self.ndev <= 1:
            return 0
        head = 2 * (self.ndev - 1) * self.head_k * self.z_item // self.ndev
        reads = sum(r.width for r in self.read_rounds) * self.z_item
        return int(head + reads)

    def dense_bytes_per_iter(self) -> int:
        """Modeled bytes sent per chip per iteration by the DENSE
        exchange this plan replaces — THE one spelling lives in
        parallel/comms.py:dense_exchange_bytes (the dense-mode runs
        publish the same formula), so the comparator every
        sparse-vs-dense gate measures against cannot desynchronize."""
        from pagerank_tpu.parallel.comms import dense_exchange_bytes

        return dense_exchange_bytes(self.ndev, self.blk, self.z_item,
                                    self.accum_item, self.rs_merge)

    def summary(self) -> dict:
        """JSON-safe record for layout_info / bench artifacts."""
        return {
            "head_k": int(self.head_k),
            "read_rounds": len(self.read_rounds),
            "write_rounds": len(self.write_rounds),
            "read_width_total": int(sum(r.width for r in self.read_rounds)),
            "write_width_total": int(
                sum(r.width for r in self.write_rounds)
            ),
            "boundary_entries": int(self.boundary_entries),
            "halo_fraction": float(self.halo_fraction),
            "sparse_bytes_per_iter": self.sparse_bytes_per_iter(),
            "dense_bytes_per_iter": self.dense_bytes_per_iter(),
        }


def slot_read_ids(src_slots: np.ndarray, *, stripe: int, sz: int,
                  group: int) -> np.ndarray:
    """Decode one stripe's packed slot words into the sorted unique
    GLOBAL source ids they gather (sentinel slots excluded) — the read
    set of whatever row range ``src_slots`` covers. Slot words are
    ``(stripe_local_src << log2(group)) | lane_sub`` with sentinel
    local id ``sz`` (ops/ell.py)."""
    log2g = group.bit_length() - 1
    local = np.asarray(src_slots).reshape(-1) >> log2g
    local = local[local < sz]
    if local.size == 0:
        return np.zeros(0, np.int64)
    return np.unique(local.astype(np.int64)) + stripe * sz


def device_read_sets(src_slots: List[np.ndarray], *, ndev: int, sz: int,
                     group: int) -> List[np.ndarray]:
    """Per-device sorted unique global read ids over all stripes.
    ``src_slots[s]`` is the stripe's FULL padded [rows, 128] slot array;
    device d owns rows [d*rows/ndev, (d+1)*rows/ndev) — the engine's
    row sharding (P(axis, None))."""
    per_dev: List[List[np.ndarray]] = [[] for _ in range(ndev)]
    for s, ss in enumerate(src_slots):
        ss = np.asarray(ss)
        rows = ss.shape[0]
        assert rows % ndev == 0, (rows, ndev)
        rpd = rows // ndev
        for d in range(ndev):
            per_dev[d].append(
                slot_read_ids(ss[d * rpd:(d + 1) * rpd], stripe=s, sz=sz,
                              group=group)
            )
    return [
        np.unique(np.concatenate(chunks)) if chunks else
        np.zeros(0, np.int64)
        for chunks in per_dev
    ]


def _round_widths(pair_sizes: np.ndarray) -> int:
    """Total padded read-round width for a [ndev, ndev] matrix of
    (owner, reader) tail set sizes: one round per ring offset, each
    padded to its max pair (min ``_HALO_MIN_WIDTH``); all-empty
    offsets cost nothing (the round is skipped)."""
    ndev = pair_sizes.shape[0]
    total = 0
    for k in range(1, ndev):
        m = max(int(pair_sizes[d, (d + k) % ndev]) for d in range(ndev))
        if m:
            total += max(m, _HALO_MIN_WIDTH)
    return total


def auto_head_k(pair_sets, *, ndev: int, n_vs: int,
                z_item: int = 4) -> int:
    """The head-replication K rule: choose the RELABELED prefix
    [0, K) whose replication MINIMIZES the modeled per-chip exchange
    bytes — ``2*(ndev-1)/ndev * K`` elements of all-reduce traffic
    bought against the tail rounds' padded-width shrink, evaluated on
    the exact build-time pair sets (``pair_sets[p][d]``: sorted global
    ids owner p sends reader d at K=0). The relabel is descending
    in-degree (ops/ell.py), so the widely read vertices concentrate at
    the front and a prefix captures them compactly; candidates are
    power-of-two multiples of 128 (plus 0), capped at half the state —
    beyond that 'replication' stops being a head. A reader-count
    threshold was the first cut here, but it over-replicates on dense
    R-MAT tails (measured at scale 18: threshold rule 0.80x dense vs
    0.63x for the model argmin — docs/PERF_NOTES.md "Sparse boundary
    exchange")."""
    if ndev <= 1:
        return 0
    cap = min((n_vs // 256) * 128, 1 << 20)
    cands = [0]
    k = 128
    while k <= cap:
        cands.append(k)
        k *= 2
    best_k, best_cost = 0, None
    sizes = np.zeros((ndev, ndev), np.int64)
    for K in cands:
        for p in range(ndev):
            for d in range(ndev):
                s = pair_sets[p][d]
                sizes[p, d] = s.size - np.searchsorted(s, K)
        cost = (2 * (ndev - 1) * K // ndev + _round_widths(sizes)) \
            * z_item
        if best_cost is None or cost < best_cost:
            best_k, best_cost = K, cost
    return best_k


def device_write_bands(row_ranks: List[np.ndarray],
                       present_ids: List[np.ndarray], *, ndev: int,
                       n_vs: int) -> List[Tuple[int, int]]:
    """Per-device [lo, hi) hull of flat contribution positions the
    device's slot rows can write: rows are block-sorted and evenly
    row-sharded, so each device's blocks per stripe are one contiguous
    run — the hull over stripes is the union. ``row_ranks[s]`` are the
    stripe's dense block RANKS (ops/ell.dense_block_ranks),
    ``present_ids[s]`` maps rank -> global block id."""
    lo = [n_vs] * ndev
    hi = [0] * ndev
    for rk, ids in zip(row_ranks, present_ids):
        rk = np.asarray(rk)
        ids = np.asarray(ids)
        rows = rk.shape[0]
        assert rows % ndev == 0, (rows, ndev)
        rpd = rows // ndev
        for d in range(ndev):
            sl = rk[d * rpd:(d + 1) * rpd]
            if sl.size == 0:
                continue
            lo[d] = min(lo[d], int(ids[int(sl[0])]) * 128)
            hi[d] = max(hi[d], int(ids[int(sl[-1])]) * 128 + 128)
    return [(min(lo[d], n_vs), min(max(hi[d], lo[d]), n_vs))
            for d in range(ndev)]


def build_halo_plan(src_slots: List[np.ndarray],
                    row_ranks: List[np.ndarray],
                    present_ids: List[np.ndarray], *, ndev: int,
                    n_vs: int, sz: int, group: int, head_k: int = -1,
                    z_item: int = 4, accum_item: int = 4,
                    rs_merge: bool = True) -> HaloPlan:
    """Derive the full sparse-exchange plan from the packed slot
    tables (see module comment). ``head_k``: -1 = the auto rule
    (:func:`auto_head_k`), 0 = no replication, > 0 = explicit K
    (rounded up to a 128 multiple, clamped to ``n_vs``)."""
    if n_vs % (128 * max(1, ndev)):
        raise ValueError(f"n_vs {n_vs} not a multiple of 128*{ndev}")
    blk = n_vs // ndev
    reads = device_read_sets(src_slots, ndev=ndev, sz=sz, group=group)

    # Full (owner, reader) remote read sets BEFORE head removal — the
    # K rule evaluates its byte model on exactly these.
    pair_sets = [[np.zeros(0, np.int64)] * ndev for _ in range(ndev)]
    for d, ids in enumerate(reads):
        remote = ids[ids // blk != d]
        owners = remote // blk
        cuts = np.searchsorted(owners, np.arange(ndev + 1))
        for p in range(ndev):
            pair_sets[p][d] = remote[cuts[p]:cuts[p + 1]]

    if head_k < 0:
        K = auto_head_k(pair_sets, ndev=ndev, n_vs=n_vs, z_item=z_item)
    else:
        K = min(-(-int(head_k) // 128) * 128, n_vs)
    plan = HaloPlan(ndev=ndev, n_vs=n_vs, blk=blk, head_k=K,
                    z_item=z_item, accum_item=accum_item,
                    rs_merge=rs_merge)
    if ndev <= 1:
        plan.reads_per_pair = np.zeros((ndev, ndev), np.int64)
        return plan

    # -- tail read rounds: owner d -> reader (d+k) % ndev ------------------
    sizes = np.zeros((ndev, ndev), np.int64)
    for p in range(ndev):
        for d in range(ndev):
            s = pair_sets[p][d]
            s = s[np.searchsorted(s, K):]  # drop the replicated head
            pair_sets[p][d] = s
            sizes[p, d] = s.size
    plan.reads_per_pair = sizes
    plan.boundary_entries = int(sizes.sum())
    for k in range(1, ndev):
        widths = [sizes[d, (d + k) % ndev] for d in range(ndev)]
        m_k = int(max(widths))
        if m_k == 0:
            continue
        m_k = max(m_k, _HALO_MIN_WIDTH)
        send = np.full((ndev, m_k), blk, np.int32)
        recv = np.full((ndev, m_k), n_vs, np.int32)
        perm = []
        for d in range(ndev):
            r = (d + k) % ndev
            s = pair_sets[d][r]
            if s.size == 0:
                continue
            perm.append((d, r))
            send[d, :s.size] = (s - d * blk).astype(np.int32)
            recv[r, :s.size] = s.astype(np.int32)
        plan.read_rounds.append(HaloRound(k, m_k, tuple(perm)))
        plan.send_idx.append(send)
        plan.recv_ids.append(recv)

    # -- write rounds: writer d -> owner d+k (signed, no wrap) -------------
    bands = device_write_bands(row_ranks, present_ids, ndev=ndev,
                               n_vs=n_vs)
    seg = {}
    for d, (lo, hi) in enumerate(bands):
        for p in range(ndev):
            if p == d:
                continue  # own overlap rides the local slice, not the wire
            s_lo = max(lo, p * blk)
            s_hi = min(hi, (p + 1) * blk)
            if s_lo < s_hi:
                seg.setdefault(p - d, {})[d] = (s_lo, s_hi - s_lo)
    for k in sorted(seg):
        segs = seg[k]
        w_k = max(_HALO_MIN_WIDTH, max(w for _lo, w in segs.values()))
        ws = np.full(ndev, n_vs, np.int32)
        wr = np.full(ndev, blk, np.int32)
        perm = []
        for d, (s_lo, _w) in sorted(segs.items()):
            perm.append((d, d + k))
            ws[d] = s_lo
            wr[d + k] = s_lo - (d + k) * blk
        plan.write_rounds.append(HaloRound(k, int(w_k), tuple(perm)))
        plan.wsend_start.append(ws)
        plan.wrecv_start.append(wr)
    return plan
