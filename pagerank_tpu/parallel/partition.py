"""Static edge partitioning (replaces the reference's per-iteration
shuffles, SURVEY.md §2 P2).

Spark re-keys O(E) records across executors three times per iteration
(join/subtractByKey/reduceByKey, Sparky.java:192,224,229). Here the graph
is partitioned exactly once on the host: the destination-sorted edge list
is cut into equal-count contiguous chunks, one per device. Equal *edge*
count (not vertex count) is what balances work under power-law degree
skew — a heavy row simply spans several chunks and its partial sums meet
in the psum (the "Sparse Allreduce" pattern, PAPERS.md:5).

Padding edges carry weight 0 and dst = n-1, preserving both the
zero-contribution invariant and per-chunk dst-sortedness.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from pagerank_tpu.graph import Graph


@dataclass
class EdgeShards:
    """Flat padded edge arrays, length divisible by num_shards; chunk i
    (contiguous) belongs to device i."""

    src: np.ndarray  # int32 [E_pad]
    dst: np.ndarray  # int32 [E_pad]
    weight: np.ndarray  # [E_pad] float, 0 on padding
    num_shards: int
    num_real_edges: int

    @property
    def edges_per_shard(self) -> int:
        return self.src.shape[0] // self.num_shards


def partition_edges(graph: Graph, num_shards: int, weight_dtype=np.float32) -> EdgeShards:
    """Cut the dst-sorted edge list into ``num_shards`` equal contiguous
    chunks, padding the tail with inert edges (w=0, dst=n-1)."""
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    e = graph.num_edges
    per = max(1, -(-e // num_shards))  # ceil; at least 1 so empty graphs still shard
    e_pad = per * num_shards
    pad = e_pad - e

    src = np.concatenate([graph.src, np.zeros(pad, np.int32)])
    dst = np.concatenate([graph.dst, np.full(pad, graph.n - 1, np.int32)])
    w = np.concatenate(
        [graph.edge_weight.astype(weight_dtype), np.zeros(pad, weight_dtype)]
    )
    return EdgeShards(
        src=src, dst=dst, weight=w, num_shards=num_shards, num_real_edges=e
    )
