"""Per-iteration communication accounting for the sharded solve
(ISSUE 8; docs/PERF_NOTES.md "Sparse boundary exchange").

The XLA cost model (obs/costs.py) accounts a compiled program's FLOPs
and HBM bytes but is blind to what crosses the INTERCONNECT — the axis
the sparse boundary exchange optimizes. This module is that ledger's
comms-side counterpart: a static byte model per exchange mode (derived
once at build from the engine's resolved layout / halo plan, never
measured per iteration — the tables are static, so the model IS the
measurement) plus the live instruments every solve publishes through
the PR 4/5 registry and exporter:

  - ``comms.bytes_exchanged``   counter, modeled wire bytes sent by
                                 this chip, accumulated per iteration;
  - ``comms.bytes_per_iter``    gauge, the per-iteration rate;
  - ``comms.dense_bytes_per_iter`` gauge, what the DENSE exchange
                                 (all_gather + full-width merge) would
                                 move — the standing comparator;
  - ``comms.halo_fraction``     gauge, tail boundary entries over the
                                 dense all_gather's remote entries
                                 (sparse mode only);
  - ``comms.head_k``            gauge, replicated head size (sparse).

Byte convention (shared with parallel/partition.HaloPlan): bytes SENT
per chip per iteration under the standard ring lowering —
all_gather/reduce_scatter of an n-element vector cost
``(ndev-1) * n/ndev`` sends per chip, an all-reduce twice that, a
ppermute exactly its payload.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from pagerank_tpu.obs import metrics as obs_metrics


def dense_exchange_bytes(ndev: int, blk: int, z_item: int,
                         accum_item: int, rs_merge: bool = True) -> int:
    """Modeled bytes sent per chip per iteration by the dense
    vertex-sharded exchange: one tiled all_gather of the z shard plus
    the full-width contribution merge (reduce-scatter; ``rs_merge``
    False models the psum + local-slice fallback backends without a
    wide reduce-scatter take — engines/jax_engine.py)."""
    if ndev <= 1:
        return 0
    merge = (ndev - 1) * blk * accum_item
    if not rs_merge:
        merge *= 2
    return int((ndev - 1) * blk * z_item + merge)


def model_dense(ndev: int, blk: int, z_item: int, accum_item: int,
                rs_merge: bool = True) -> dict:
    """Comms model record for the dense vertex-sharded step."""
    dense = dense_exchange_bytes(ndev, blk, z_item, accum_item, rs_merge)
    return {
        "mode": "dense",
        "bytes_per_iter": dense,
        "dense_bytes_per_iter": dense,
        "sparse_bytes_per_iter": None,
        "halo_fraction": None,
        "head_k": None,
    }


def model_sparse(plan) -> dict:
    """Comms model record for a halo-exchange step, from its build-time
    :class:`pagerank_tpu.parallel.partition.HaloPlan`."""
    return {
        "mode": "sparse",
        "bytes_per_iter": plan.sparse_bytes_per_iter(),
        "dense_bytes_per_iter": plan.dense_bytes_per_iter(),
        "sparse_bytes_per_iter": plan.sparse_bytes_per_iter(),
        "halo_fraction": plan.halo_fraction,
        "head_k": plan.head_k,
    }


def model_async(plan) -> dict:
    """Comms model record for the ASYNCHRONOUS stale-boundary step
    (ISSUE 17; config.halo_async). Same wire bytes as the synchronous
    sparse exchange — overlap reorders the collectives, it never adds
    or removes one (the vs_halo_async PTC001 contract) — plus the
    overlap split the gate and the bench attribution read."""
    m = model_sparse(plan)
    m["mode"] = "sparse_async"
    m["overlappable_bytes_per_iter"] = plan.overlappable_bytes_per_iter()
    return m


#: Standing exchange-fraction assumption when no measurement exists
#: yet (a fresh build gates BEFORE its first attribution run). PR 10's
#: TPU attributions put the sparse exchange at 20-40% of the step wall
#: at headline scale; 0.3 is the midpoint — conservative enough that a
#: boundary-light plan still gates off on its own overlappable share.
DEFAULT_EXCHANGE_FRACTION = 0.3


def predict_overlap_gain(plan, exchange_fraction: Optional[float] = None
                         ) -> float:
    """Predicted fractional step-wall saving of the stale-boundary
    overlap (ISSUE 17): ``exchange_fraction x overlappable_share``,
    where overlappable_share is the head + read-round portion of the
    sparse exchange bytes (the write-band merge cannot be hidden —
    parallel/partition.HaloPlan.overlappable_bytes_per_iter). The
    exchange fraction comes from the caller (the engine passes the
    live ``comms.exchange_fraction`` gauge when a prior attribution
    measured one) or falls back to :data:`DEFAULT_EXCHANGE_FRACTION`.
    Zero on single-device meshes and boundary-free plans — the
    auto-gate's refusal signal."""
    sparse = plan.sparse_bytes_per_iter()
    if not sparse:
        return 0.0
    share = plan.overlappable_bytes_per_iter() / sparse
    ef = exchange_fraction
    if ef is None:
        gauges = obs_metrics.get_registry().snapshot()["gauges"]
        ef = gauges.get("comms.exchange_fraction")
    if ef is None:
        ef = DEFAULT_EXCHANGE_FRACTION
    return float(max(0.0, min(1.0, ef)) * share)


def publish_overlap_gain(gain: float) -> None:
    """Publish the predicted payoff next to the measured exchange
    fraction so `obs report` shows the gate's evidence."""
    obs_metrics.gauge(
        "comms.predicted_overlap_gain",
        "predicted fractional step-wall saving of the stale-boundary "
        "overlap (exchange fraction x overlappable byte share)",
    ).set(float(gain))


def register(model: dict) -> Optional[obs_metrics.Counter]:
    """Publish a comms model through the central registry (gauges) and
    return the ``comms.bytes_exchanged`` counter the solve loop feeds
    per iteration. None for an empty model (single device: nothing
    crosses the wire, and a zero-rate counter would just be noise)."""
    if not model or not model.get("bytes_per_iter"):
        return None
    obs_metrics.gauge(
        "comms.bytes_per_iter",
        "modeled wire bytes sent per chip per solve iteration",
    ).set(model["bytes_per_iter"])
    obs_metrics.gauge(
        "comms.dense_bytes_per_iter",
        "what the dense all_gather+reduce-scatter exchange would send",
    ).set(model["dense_bytes_per_iter"])
    if model.get("halo_fraction") is not None:
        obs_metrics.gauge(
            "comms.halo_fraction",
            "tail boundary entries / the dense all_gather's remote "
            "entries",
        ).set(model["halo_fraction"])
    if model.get("head_k") is not None:
        obs_metrics.gauge(
            "comms.head_k", "replicated high in-degree head size"
        ).set(model["head_k"])
    return obs_metrics.counter(
        "comms.bytes_exchanged",
        "modeled wire bytes sent by this chip, accumulated per "
        "iteration",
    )


# -- skew-driven load prediction (ISSUE 13; obs/graph_profile.py) -----------
#
# The device plane measures straggler skew (elastic.straggler_skew) and
# the comms model prices the halo AFTER a build exists; this section
# PREDICTS both from the data-plane GraphProfile alone — per-device
# load imbalance from the per-(stripe, dst-block) edge/row geometry,
# and the halo head-K from the in-degree distribution — so a TPU
# session's balance risk is readable BEFORE burning chip time, and
# predicted-vs-measured is one `obs report` diff (graph.* gauges next
# to the measured elastic.*/comms.* values).


def predict_device_load(profile, ndev: int) -> Optional[dict]:
    """Per-device unique-edge counts for the row-sharded
    vertex-sharded solve, predicted from the profile's per-(stripe,
    128-dst-block) edge and row counts: slot rows concatenate in
    (stripe, block) order and shard evenly over ``ndev`` devices (the
    engine's ``P(axis, None)`` row sharding, rows padded to an ndev
    multiple), with each block's edges spread uniformly over its own
    rows — exact up to within-block row-density variation. None when
    the profile lacks the block geometry or the graph is edge-free."""
    be = getattr(profile, "block_edges", None)
    br = getattr(profile, "block_rows", None)
    if be is None or br is None or not ndev or ndev < 1:
        return None
    be = np.asarray(be, np.float64)
    br = np.asarray(br, np.int64)
    num_blocks = profile.n_padded // 128 if profile.n_padded else 0
    if num_blocks == 0 or be.shape != br.shape:
        return None
    n_stripes = max(1, be.shape[0] // num_blocks)
    edges_dev = np.zeros(ndev, np.float64)
    for s in range(n_stripes):
        e = be[s * num_blocks:(s + 1) * num_blocks]
        r = br[s * num_blocks:(s + 1) * num_blocks]
        rows = int(r.sum())
        if rows == 0:
            continue
        rows_pad = -(-rows // ndev) * ndev
        per_row = np.repeat(e / np.maximum(r, 1), r)
        if rows_pad > rows:
            per_row = np.concatenate(
                [per_row, np.zeros(rows_pad - rows)])
        edges_dev += per_row.reshape(ndev, rows_pad // ndev).sum(axis=1)
    total = float(edges_dev.sum())
    if total <= 0:
        return None
    mean = total / ndev
    return {
        "ndev": int(ndev),
        "device_edges": [float(x) for x in edges_dev],
        "straggler_skew": float(edges_dev.max() / mean),
    }


def _expected_remote_readers(d: np.ndarray, ndev: int) -> np.ndarray:
    """Expected distinct NON-OWNER devices whose rows gather a vertex
    of unique in-degree ``d``, under uniform edge-to-row placement:
    distinct devices among d draws = ndev*(1-(1-1/ndev)^d), of which
    (ndev-1)/ndev are remote on average."""
    hit = 1.0 - np.power(1.0 - 1.0 / ndev, np.asarray(d, np.float64))
    return (ndev - 1) * hit


def predict_halo_head_k(profile, ndev: int) -> int:
    """Predicted head-replication K for the sparse boundary exchange,
    from the profile's log2 in-degree histogram alone — the same cost
    argmin as parallel/partition.auto_head_k (replicating the first K
    relabeled vertices costs ``2*(ndev-1)*K/ndev`` all-reduce elements
    against the tail pair entries it removes), with the exact pair
    sets replaced by the expected remote-reader count per degree bin
    (bin k's vertices carry the bin's geometric-midpoint degree; the
    relabel is in-degree descending, so a prefix IS the high-degree
    head). A prediction, not the plan: `obs report` diffs it against
    the measured ``comms.head_k``."""
    if ndev <= 1:
        return 0
    # Descending (degree, count) sequence from the histogram.
    seq: List[tuple] = []
    hist = list(getattr(profile, "in_hist", []) or [])
    for k in range(len(hist) - 1, 0, -1):
        c = int(hist[k])
        if not c:
            continue
        d = 1.0 if k == 1 else 1.5 * (1 << (k - 1))
        seq.append((d, c))
    if not seq:
        return 0
    n_vs = -(-profile.n_padded // (128 * ndev)) * (128 * ndev)
    cap = min((n_vs // 256) * 128, 1 << 20)
    cands = [0]
    k = 128
    while k <= cap:
        cands.append(k)
        k *= 2
    degs = np.asarray([d for d, _ in seq])
    cnts = np.asarray([c for _, c in seq], np.int64)
    readers = _expected_remote_readers(degs, ndev)
    # Per-CHIP tail cost of one tail vertex with r expected remote
    # readers: the real plan pays one padded round per ring offset
    # (sum over offsets of the MAX pair width). A fully-shared vertex
    # (r = ndev-1) sits in every pair, so it costs each chip one slot
    # in every round: ndev-1. A scattered vertex (r small) hits r of
    # the ndev pairs per offset on average: ~r/ndev ~ r^2/(ndev-1)
    # per chip. r^2/(ndev-1) interpolates both ends exactly.
    per_vertex = readers * readers / (ndev - 1)
    cum = np.concatenate([[0], np.cumsum(cnts)])
    total_tail = float((per_vertex * cnts).sum())
    best_k, best_cost = 0, None
    for K in cands:
        # Tail cost beyond rank K: whole bins past K plus the partial
        # bin K lands in.
        i = int(np.searchsorted(cum, K, side="right")) - 1
        if i >= len(cnts):
            tail = 0.0
        else:
            head = float((per_vertex[:i] * cnts[:i]).sum())
            head += per_vertex[i] * (K - cum[i])
            tail = total_tail - head
        # The head all-reduce costs 2*(ndev-1)*K/ndev sends per chip
        # (the HaloPlan ring convention).
        cost = 2.0 * (ndev - 1) * K / ndev + tail
        if best_cost is None or cost < best_cost:
            best_k, best_cost = K, cost
    return int(best_k)


def predict_from_profile(profile, ndev: int) -> Optional[dict]:
    """The data-plane prediction block: per-device load + straggler
    skew + halo head-K for a target mesh size, from the profile alone
    (no build, no devices). Embedded in run reports/bench legs next to
    the measured values; published as ``graph.*`` gauges by
    :func:`publish_prediction`."""
    if profile is None or not ndev:
        return None
    load = predict_device_load(profile, ndev)
    pred = {
        "ndev": int(ndev),
        "predicted_straggler_skew": (load["straggler_skew"]
                                     if load else None),
        "predicted_device_edges": (load["device_edges"]
                                   if load else None),
        "predicted_halo_head_k": predict_halo_head_k(profile, ndev),
    }
    return pred


def publish_prediction(pred: Optional[dict]) -> None:
    """Mirror a prediction block into ``graph.*`` gauges so predicted
    sits next to measured (elastic.straggler_skew / comms.head_k) in
    the exporter and the run-report diff."""
    if not pred:
        return
    if pred.get("predicted_straggler_skew") is not None:
        obs_metrics.gauge(
            "graph.predicted_straggler_skew",
            "max/mean per-device edge load predicted from the graph "
            "profile (compare: elastic.straggler_skew)",
        ).set(pred["predicted_straggler_skew"])
    if pred.get("predicted_halo_head_k") is not None:
        obs_metrics.gauge(
            "graph.predicted_halo_head_k",
            "halo head-K predicted from the in-degree histogram "
            "(compare: comms.head_k)",
        ).set(pred["predicted_halo_head_k"])


def measured_device_edges(engine, ndev: Optional[int] = None
                          ) -> Optional[np.ndarray]:
    """ACTUAL per-device real-slot counts of a built engine's
    row-sharded tables (the measurement the predicted skew is gated
    against, scripts/acceptance smoke S): rows split evenly over the
    mesh, sentinel/duplicate slots excluded. None on layouts whose
    slot words aren't plain packed int words (the 3-byte partitioned
    planes) or whose rows don't divide the mesh."""
    import jax

    layout = engine.layout_info()
    group = int(layout.get("group") or 1)
    sz = int(layout.get("stripe_span") or getattr(engine, "_n_state", 0))
    if not sz:
        return None
    ndev = int(ndev or engine.mesh.devices.size)
    log2g = group.bit_length() - 1
    counts = np.zeros(ndev, np.int64)
    for s in getattr(engine, "_src", []) or []:
        a = np.asarray(jax.device_get(s))
        # Plain packed slot words are int32 [rows, 128]; anything else
        # (the partitioned layout's 3-byte planar int8 planes) is not
        # decodable here — None, never garbage counts.
        if a.ndim != 2 or a.dtype != np.int32:
            return None
        rows = a.shape[0]
        if rows % ndev:
            return None
        real = (a.astype(np.int64) >> log2g) < sz
        counts += real.reshape(ndev, (rows // ndev) * a.shape[1]
                               ).sum(axis=1)
    return counts
