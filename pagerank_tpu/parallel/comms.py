"""Per-iteration communication accounting for the sharded solve
(ISSUE 8; docs/PERF_NOTES.md "Sparse boundary exchange").

The XLA cost model (obs/costs.py) accounts a compiled program's FLOPs
and HBM bytes but is blind to what crosses the INTERCONNECT — the axis
the sparse boundary exchange optimizes. This module is that ledger's
comms-side counterpart: a static byte model per exchange mode (derived
once at build from the engine's resolved layout / halo plan, never
measured per iteration — the tables are static, so the model IS the
measurement) plus the live instruments every solve publishes through
the PR 4/5 registry and exporter:

  - ``comms.bytes_exchanged``   counter, modeled wire bytes sent by
                                 this chip, accumulated per iteration;
  - ``comms.bytes_per_iter``    gauge, the per-iteration rate;
  - ``comms.dense_bytes_per_iter`` gauge, what the DENSE exchange
                                 (all_gather + full-width merge) would
                                 move — the standing comparator;
  - ``comms.halo_fraction``     gauge, tail boundary entries over the
                                 dense all_gather's remote entries
                                 (sparse mode only);
  - ``comms.head_k``            gauge, replicated head size (sparse).

Byte convention (shared with parallel/partition.HaloPlan): bytes SENT
per chip per iteration under the standard ring lowering —
all_gather/reduce_scatter of an n-element vector cost
``(ndev-1) * n/ndev`` sends per chip, an all-reduce twice that, a
ppermute exactly its payload.
"""

from __future__ import annotations

from typing import Optional

from pagerank_tpu.obs import metrics as obs_metrics


def dense_exchange_bytes(ndev: int, blk: int, z_item: int,
                         accum_item: int, rs_merge: bool = True) -> int:
    """Modeled bytes sent per chip per iteration by the dense
    vertex-sharded exchange: one tiled all_gather of the z shard plus
    the full-width contribution merge (reduce-scatter; ``rs_merge``
    False models the psum + local-slice fallback backends without a
    wide reduce-scatter take — engines/jax_engine.py)."""
    if ndev <= 1:
        return 0
    merge = (ndev - 1) * blk * accum_item
    if not rs_merge:
        merge *= 2
    return int((ndev - 1) * blk * z_item + merge)


def model_dense(ndev: int, blk: int, z_item: int, accum_item: int,
                rs_merge: bool = True) -> dict:
    """Comms model record for the dense vertex-sharded step."""
    dense = dense_exchange_bytes(ndev, blk, z_item, accum_item, rs_merge)
    return {
        "mode": "dense",
        "bytes_per_iter": dense,
        "dense_bytes_per_iter": dense,
        "sparse_bytes_per_iter": None,
        "halo_fraction": None,
        "head_k": None,
    }


def model_sparse(plan) -> dict:
    """Comms model record for a halo-exchange step, from its build-time
    :class:`pagerank_tpu.parallel.partition.HaloPlan`."""
    return {
        "mode": "sparse",
        "bytes_per_iter": plan.sparse_bytes_per_iter(),
        "dense_bytes_per_iter": plan.dense_bytes_per_iter(),
        "sparse_bytes_per_iter": plan.sparse_bytes_per_iter(),
        "halo_fraction": plan.halo_fraction,
        "head_k": plan.head_k,
    }


def register(model: dict) -> Optional[obs_metrics.Counter]:
    """Publish a comms model through the central registry (gauges) and
    return the ``comms.bytes_exchanged`` counter the solve loop feeds
    per iteration. None for an empty model (single device: nothing
    crosses the wire, and a zero-rate counter would just be noise)."""
    if not model or not model.get("bytes_per_iter"):
        return None
    obs_metrics.gauge(
        "comms.bytes_per_iter",
        "modeled wire bytes sent per chip per solve iteration",
    ).set(model["bytes_per_iter"])
    obs_metrics.gauge(
        "comms.dense_bytes_per_iter",
        "what the dense all_gather+reduce-scatter exchange would send",
    ).set(model["dense_bytes_per_iter"])
    if model.get("halo_fraction") is not None:
        obs_metrics.gauge(
            "comms.halo_fraction",
            "tail boundary entries / the dense all_gather's remote "
            "entries",
        ).set(model["halo_fraction"])
    if model.get("head_k") is not None:
        obs_metrics.gauge(
            "comms.head_k", "replicated high in-degree head size"
        ).set(model["head_k"])
    return obs_metrics.counter(
        "comms.bytes_exchanged",
        "modeled wire bytes sent by this chip, accumulated per "
        "iteration",
    )
