"""Elastic multi-device solve: preemption-tolerant rescue (ISSUE 7;
docs/ROBUSTNESS.md "Elastic solve").

PR 3's self-healing loop survives bad NUMBERS (NaN/mass drift ->
snapshot rollback) and PR 5's watchdog makes hung collectives LOUD —
but nothing could finish a solve once a device dropped out of the mesh.
This module closes that gap with the recovery strategy the
asynchronous-iteration literature licenses (Kollias et al.,
arXiv:cs/0606047: PageRank converges from stale/partial state):

  1. classify — a step failure or watchdog fire is probed per device
     (parallel/mesh.probe_liveness: deadline-bounded echo round-trips)
     into *hang* (every device answers; keep waiting / warn) vs
     *device-lost* (some device cannot answer a 4-byte echo);
  2. rescue — tear down the mesh, rebuild it over the survivors
     (mesh.surviving_devices), re-shard the graph by rebuilding the
     engine at the smaller device count (the partitioner and every
     layout planner are mesh-size-parametric already), and warm-start
     from the newest valid snapshot (snapshots store the CANONICAL
     host-order rank vector, so a snapshot taken on N devices restores
     onto any M-device mesh — utils/snapshot.py "Mesh-shape-agnostic");
  3. bound — rescues spend the same budget class as rollbacks
     (config.robustness.max_rescues, defaulting to max_rollbacks);
     exhausting it raises :class:`ElasticExhaustedError` naming every
     device lost along the way.

Stragglers are NOT rescued: a slow step that completes is telemetry
(:class:`DeviceHealthMonitor` -> ``elastic.slow_steps`` /
``elastic.straggler_skew``), never a teardown — rescue costs a rebuild
plus recomputed iterations, and a straggler resolves itself.

Everything is testable on CPU: ``testing/faults.DeviceFaultSchedule``
injects kills/delays/poisons through a mesh-aware shim, and the
liveness prober is injectable so an 8-fake-device run
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) exercises the
full classify -> teardown -> re-shard -> resume path
(tests/test_elastic.py; acceptance smoke L).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.parallel import mesh as mesh_lib
from pagerank_tpu.utils.snapshot import resume_engine


class DeviceLostError(RuntimeError):
    """A mesh device is gone (preempted, detached, or wedged past the
    liveness deadline). Carries the lost device ids so the rescue path
    can rebuild over the survivors. Raised by the fault-injection shim
    on CPU and mapped from backend runtime errors (confirmed by a
    liveness probe) on real hardware."""

    def __init__(self, message: str, device_ids: Sequence[int] = ()):
        super().__init__(message)
        self.device_ids = tuple(device_ids)


class DeviceQuarantinedError(DeviceLostError):
    """A device is ALIVE but LYING: the SDC plane (pagerank_tpu/sdc.py,
    ISSUE 15) convicted it of sticky silent data corruption — repeat
    ABFT-invariant breaches across a clean-state re-execution, both
    attributing to the same chip. The rescue path treats the carried
    ids as lost (teardown -> re-shard over the remaining devices) even
    though every liveness probe answers, and records them in
    ``ElasticRunner.quarantined_device_ids`` (the ``on_quarantine``
    hook fires for runner-side consumers). Durable persistence
    (job.json) happens AT conviction time via the sdc quarantine hook
    — before this error even raises — so a resumed job never
    re-adopts a known-bad chip."""


class ElasticExhaustedError(RuntimeError):
    """The rescue budget is spent (or no devices survive). Carries the
    full casualty list and the rescue count — the 3am-page diagnostic,
    same contract as engine.SolverHealthError."""

    def __init__(self, message: str, lost_device_ids: Sequence[int],
                 rescues: int):
        super().__init__(message)
        self.lost_device_ids = tuple(lost_device_ids)
        self.rescues = rescues


# Substrings that mark a backend runtime error as PLAUSIBLY a device
# loss (worth a liveness probe before rescuing). Deliberately narrow:
# an unrelated XLA error must re-raise, not trigger a teardown.
_DEVICE_LOSS_MARKERS = (
    "device_lost", "device lost", "deadline_exceeded", "data_loss",
    "failed to connect", "socket closed", "unavailable",
    "device or resource busy", "halted", "preempt",
)


def looks_like_device_loss(exc: BaseException) -> bool:
    """Whether a step failure is worth a liveness probe (vs a plain
    programming/numerics error that must surface unchanged)."""
    if isinstance(exc, DeviceLostError):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _DEVICE_LOSS_MARKERS)


class DeviceHealthMonitor:
    """Per-step health telemetry: straggler detection by step-time
    skew. A step that takes more than ``straggler_factor`` times the
    EWMA of previous steps — but COMPLETES — is a slow step, not a
    stall: it increments ``elastic.slow_steps``, publishes the skew in
    the ``elastic.straggler_skew`` gauge (the live exporter picks both
    up), and logs once per episode. Per-device attribution, when the
    caller has it (the fault shim does; real hardware gets it from the
    per-device cost/metrics plumbing), lands in
    ``elastic.device_skew`` as max/median across devices.

    ``clock`` is injectable (utils/retry.py discipline) so tests drive
    step timing in virtual time."""

    def __init__(self, straggler_factor: float = 4.0, warmup_steps: int = 2,
                 ewma_alpha: float = 0.3,
                 clock: Callable[[], float] = time.monotonic):
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must be > 1, got {straggler_factor}"
            )
        self.straggler_factor = float(straggler_factor)
        self.warmup_steps = int(warmup_steps)
        self.ewma_alpha = float(ewma_alpha)
        self.clock = clock
        self._ewma: Optional[float] = None
        self._steps = 0
        self._t_last: Optional[float] = None
        self.slow_steps = 0
        self.last_skew: Optional[float] = None
        # Eager registration (ISSUE 8 satellite): a HEALTHY sharded
        # solve must still expose the elastic instruments through the
        # Prometheus exporter — a dashboard keyed on
        # elastic_straggler_skew reads the 1.0 no-skew baseline, not a
        # missing series, until the first slow step overwrites it.
        obs_metrics.counter(
            "elastic.slow_steps",
            "steps slower than straggler_factor x the step-time "
            "EWMA (completed — telemetry only, never a rescue)",
        )
        g = obs_metrics.gauge(
            "elastic.straggler_skew",
            "latest slow step's wall / step-time EWMA",
        )
        if g.value is None:
            g.set(1.0)

    def reset(self) -> None:
        """Re-baseline after a rescue: the fresh engine's first steps
        pay compile/warm-up wall that must not read as stragglers, and
        the degraded mesh's steady-state step time is legitimately
        different."""
        self._ewma = None
        self._steps = 0
        self._t_last = None

    def begin_step(self) -> None:
        self._t_last = self.clock()

    def end_step(self, iteration: int) -> None:
        """Record one completed step's wall (measured on ``clock``
        since :meth:`begin_step`); flags it slow AFTER the warmup once
        it exceeds ``straggler_factor`` x the EWMA."""
        if self._t_last is None:
            return
        dt = self.clock() - self._t_last
        self._t_last = None
        self._steps += 1
        if self._ewma is not None and self._steps > self.warmup_steps:
            skew = dt / max(self._ewma, 1e-12)
            if skew > self.straggler_factor:
                self.slow_steps += 1
                self.last_skew = skew
                obs_metrics.counter(
                    "elastic.slow_steps",
                    "steps slower than straggler_factor x the step-time "
                    "EWMA (completed — telemetry only, never a rescue)",
                ).inc()
                obs_metrics.gauge(
                    "elastic.straggler_skew",
                    "latest slow step's wall / step-time EWMA",
                ).set(float(skew))
                obs_log.warn(
                    f"slow step at iteration {iteration}: {dt:.3f}s is "
                    f"{skew:.1f}x the {self._ewma:.3f}s EWMA "
                    f"(straggler telemetry; not a stall, not rescued)"
                )
                return  # a straggler must not poison the EWMA baseline
        self._ewma = (
            dt if self._ewma is None
            else (1 - self.ewma_alpha) * self._ewma + self.ewma_alpha * dt
        )

    def record_device_times(self, iteration: int,
                            device_seconds: Dict[int, float]) -> None:
        """Optional per-device step walls (fault shim / per-device cost
        plumbing): publishes max/median skew across devices."""
        if not device_seconds:
            return
        vals = sorted(device_seconds.values())
        med = vals[len(vals) // 2]
        skew = vals[-1] / max(med, 1e-12)
        obs_metrics.gauge(
            "elastic.device_skew",
            "max/median per-device step wall at the latest measured "
            "iteration",
        ).set(float(skew))


class ElasticRunner:
    """The rescue driver around ``engine.run``.

    ``engine_factory(devices)`` must build a FRESH engine over exactly
    ``devices`` (re-sharding the graph through the normal build path —
    parallel/partition.py and the layout planners are mesh-size-
    parametric). ``snapshotter`` is both the per-iteration sink's
    Snapshotter and the warm-start source after a rescue; snapshots
    hold the canonical host-order vector, so any mesh shape restores
    (utils/snapshot.py). ``liveness`` is the device prober — injectable
    so CPU chaos tests (and the fault shim) control which devices are
    "dead"; the default is mesh.probe_liveness under
    ``liveness_timeout_s``.

    ``on_rebuild(engine)`` fires after every rescue with the fresh
    engine — the hook tests use to re-install the fault shim and the
    CLI uses to rebind sinks.
    """

    def __init__(
        self,
        engine,
        engine_factory: Callable[[Sequence], object],
        snapshotter=None,
        *,
        max_rescues: int = 3,
        liveness: Optional[Callable[..., Dict[int, bool]]] = None,
        liveness_timeout_s: float = 5.0,
        resume_timeout_s: float = 60.0,
        monitor: Optional[DeviceHealthMonitor] = None,
        on_rebuild: Optional[Callable[[object], None]] = None,
        exclude_device_ids: Sequence[int] = (),
        on_quarantine: Optional[Callable[[Sequence[int]], None]] = None,
    ):
        self.engine = engine
        self._factory = engine_factory
        self._snap = snapshotter
        self.max_rescues = int(max_rescues)
        self._liveness = liveness
        self._liveness_timeout_s = float(liveness_timeout_s)
        self._resume_timeout_s = float(resume_timeout_s)
        self.monitor = monitor
        self._on_rebuild = on_rebuild
        self._on_quarantine = on_quarantine
        self.rescues = 0
        self.restarts = 0  # rescues that found no snapshot (iteration 0)
        self.lost_device_ids: List[int] = []
        # Devices a rescue must NEVER rebuild over: the persisted
        # quarantine list (ISSUE 15) — known-bad chips from prior
        # runs. Kept SEPARATE from lost_device_ids (the casualty
        # record the 3am-page diagnostics report): a healthy-but-
        # excluded chip is not a loss of THIS run — the two lists
        # merge only where the next mesh is chosen.
        self.excluded_device_ids: List[int] = [
            int(d) for d in exclude_device_ids
        ]
        #: Devices convicted of sticky SDC THIS run (a subset of
        #: lost_device_ids once their rescue fires).
        self.quarantined_device_ids: List[int] = []
        obs_metrics.gauge(
            "elastic.mesh_devices", "devices in the current solve mesh"
        ).set(self._ndev())

    def _ndev(self) -> int:
        mesh = getattr(self.engine, "mesh", None)
        return int(mesh.devices.size) if mesh is not None else 1

    def _devices(self) -> List:
        return list(self.engine.mesh.devices.reshape(-1))

    def _probe(self) -> Dict[int, bool]:
        if self._liveness is not None:
            return self._liveness(self._devices(),
                                  self._liveness_timeout_s)
        return mesh_lib.probe_liveness(self._devices(),
                                       self._liveness_timeout_s)

    # -- rescue ------------------------------------------------------------

    def _rescue(self, dead_ids: Sequence[int], cause: str):
        """Teardown -> rebuild over survivors -> warm-start. Raises
        :class:`ElasticExhaustedError` past the budget (or when nothing
        survives)."""
        dead = sorted(set(int(d) for d in dead_ids))
        self.lost_device_ids.extend(
            d for d in dead if d not in self.lost_device_ids
        )
        obs_metrics.counter(
            "elastic.devices_lost",
            "mesh devices declared dead across the run",
        ).inc(len(dead))
        if self.rescues >= self.max_rescues:
            raise ElasticExhaustedError(
                f"rescue budget ({self.max_rescues}) exhausted after "
                f"losing device(s) {self.lost_device_ids} ({cause})",
                lost_device_ids=self.lost_device_ids,
                rescues=self.rescues,
            )
        with obs_trace.span("elastic/rescue", cause=cause,
                            dead_devices=",".join(map(str, dead))) as sp:
            try:
                survivors = mesh_lib.surviving_devices(
                    self.lost_device_ids + self.excluded_device_ids,
                    self._devices(),
                )
            except RuntimeError as e:
                raise ElasticExhaustedError(
                    f"no surviving devices to rescue onto ({e}); lost "
                    f"{self.lost_device_ids}",
                    lost_device_ids=self.lost_device_ids,
                    rescues=self.rescues,
                ) from e
            obs_log.warn(
                f"ELASTIC RESCUE #{self.rescues + 1}: device(s) {dead} "
                f"lost ({cause}); rebuilding the mesh over "
                f"{len(survivors)} survivor(s) and warm-starting from "
                f"the newest valid snapshot"
            )
            self.engine = self._factory(survivors)
            resumed = 0
            if self._snap is not None:
                # DEADLINE-BOUNDED warm-start scan: it can touch
                # buffers homed on the lost mesh — a
                # WriterSyncedSnapshotter flushes the async writer,
                # whose pending decode does a device_get that blocks
                # forever against a dead device. Only the SCAN runs
                # under the deadline (abandoned past it — the solve
                # restarts from r0 instead: slower, still
                # convergent); the set_ranks restore always happens
                # here on the caller's thread via resume_engine's
                # _found hand-off, so an abandoned scan thread can
                # never mutate the fresh engine later.
                try:
                    found = mesh_lib.run_with_deadline(
                        self._snap.load_latest_valid,
                        self._resume_timeout_s,
                    )
                    # found=None means "no snapshot", already decided
                    # under the deadline — never rescan unbounded.
                    resumed = (
                        resume_engine(self.engine, self._snap,
                                      _found=found)
                        if found is not None else 0
                    )
                except mesh_lib.DeadlineExpired:
                    obs_log.warn(
                        f"elastic rescue: warm-start source did not "
                        f"answer within {self._resume_timeout_s:g}s "
                        f"(pending writes against the lost mesh?); "
                        f"abandoning it and restarting from the "
                        f"initial vector"
                    )
                    resumed = 0
            if resumed:
                obs_log.info(
                    f"elastic rescue resumed from iteration {resumed} on "
                    f"{len(survivors)} device(s)"
                )
            else:
                # Nothing valid to warm-start from: restart the solve
                # from r0 on the degraded mesh — convergent (stale-start
                # theory), just slower; counted separately.
                self.restarts += 1
                obs_metrics.counter(
                    "elastic.restarts",
                    "rescues that found no valid snapshot and restarted "
                    "from the initial rank vector",
                ).inc()
                obs_log.warn(
                    "elastic rescue found no valid snapshot; restarting "
                    "from the initial rank vector on the degraded mesh"
                )
            self.rescues += 1
            obs_metrics.counter(
                "elastic.rescues",
                "mesh teardown + re-shard + warm-start recoveries",
            ).inc()
            obs_metrics.gauge(
                "elastic.mesh_devices",
                "devices in the current solve mesh",
            ).set(self._ndev())
            if sp is not None:
                sp.attrs["resumed_iteration"] = resumed
                sp.attrs["survivors"] = len(survivors)
            if self.monitor is not None:
                self.monitor.reset()
            if self._on_rebuild is not None:
                self._on_rebuild(self.engine)
        return self.engine

    def _classify_and_rescue(self, exc: BaseException, cause: str):
        """Confirm a plausible device loss with the liveness probe;
        rescue when the probe finds casualties, re-raise otherwise
        (a live mesh means the error is the caller's problem)."""
        alive = self._probe()
        dead = [d for d, ok in alive.items() if not ok]
        if isinstance(exc, DeviceLostError) and exc.device_ids:
            dead = sorted(set(dead) | set(exc.device_ids))
        if not dead:
            return None
        return self._rescue(dead, cause)

    # -- drive -------------------------------------------------------------

    def run(self, num_iters: Optional[int] = None, on_iteration=None,
            probes=None) -> np.ndarray:
        """``engine.run`` with rescue: a step failure that classifies
        as device loss (or a watchdog fire under ``--stall-action
        rescue`` whose probe finds casualties) tears down and rebuilds;
        anything else propagates unchanged. Numeric self-healing
        (NaN -> rollback) keeps running INSIDE engine.run with the
        same snapshotter."""
        monitor = self.monitor
        wrapped = on_iteration
        if monitor is not None:
            def wrapped(i, info, _inner=on_iteration):
                monitor.end_step(i)
                if _inner is not None:
                    _inner(i, info)
                monitor.begin_step()

        while True:
            try:
                if monitor is not None:
                    monitor.begin_step()
                return self.engine.run(
                    num_iters=num_iters, on_iteration=wrapped,
                    snapshotter=self._snap, probes=probes,
                )
            except KeyboardInterrupt:
                wd = obs_live.get_watchdog()
                if wd is None or not wd.consume_rescue():
                    raise
                # Watchdog-initiated: a stall past the timeout. Probe:
                # dead device(s) -> rescue; all alive -> a hang we must
                # not "fix" by teardown (the watchdog already logged
                # loudly) — surface it.
                if self._classify_and_rescue(
                        KeyboardInterrupt(), "stall watchdog") is None:
                    raise RuntimeError(
                        "stall watchdog fired but every device answers "
                        "its liveness probe: hang, not device loss — "
                        "not rescuing (see the watchdog diagnostic)"
                    )
            except Exception as e:
                if not looks_like_device_loss(e):
                    raise
                cause = f"step failure: {type(e).__name__}"
                if isinstance(e, DeviceQuarantinedError):
                    # An SDC conviction (ISSUE 15): the chip ANSWERS
                    # liveness probes but cannot be trusted — record
                    # it, persist it via the hook, and rescue on the
                    # carried ids (classify unions them with any probe
                    # casualties).
                    self._note_quarantine(e.device_ids)
                    cause = "sdc quarantine"
                if self._classify_and_rescue(e, cause) is None:
                    raise

    def _note_quarantine(self, device_ids: Sequence[int]) -> None:
        new = [int(d) for d in device_ids
               if int(d) not in self.quarantined_device_ids]
        if not new:
            return
        self.quarantined_device_ids.extend(new)
        if self._on_quarantine is not None:
            self._on_quarantine(list(self.quarantined_device_ids))
