"""Silent-data-corruption defense (ISSUE 15; docs/ROBUSTNESS.md
"Silent data corruption").

The robustness planes so far cover chips that DIE (parallel/elastic.py
rescues device loss) and processes that die (jobs.py resumes them) —
but not chips that LIE: a flipped bit in the SpMV propagates through
every later iteration with no symptom the NaN/Inf health check or the
global ``--mass-tol`` scalar can see. PageRank is unusually well
suited to algorithm-based fault tolerance: the step is LINEAR, so a
handful of cheap redundant invariants localize a corruption to a
device, and recovery costs exactly one bounded re-execution —
asynchronous-iteration theory (Kollias et al., arXiv:cs/0606047)
guarantees convergence survives that kind of localized redo, and the
bf16-streamed leg (arXiv:2009.10443) is why the tolerances below are
DERIVED from dtype/edge-count rather than ad-hoc epsilons: rounding
and corruption must be distinguishable.

Three layers, all opt-in via ``--sdc-check-every K`` (0 = today's
step, bit-identical, ZERO check computations — the tracer/sampler
booby-trap discipline, tests/test_sdc.py):

1. **Detection** — every K-th step runs the engine's SDC-checked step
   (``JaxTpuEngine.step_sdc``): the rank-mass-ledger core (ISSUE 13)
   plus per-device ABFT check partials computed INSIDE the step's own
   dispatch — local reductions only, the exact collective multiset of
   the plain step (contract PTC008). Host-side, four invariant
   families reconcile (:func:`evaluate_check`):

   - **copy consistency** (replicated forms): every device holds its
     own copy of the rank vector, and each computes the seeded
     random-projection fingerprint ``w . r`` over ITS buffer — the
     per-device values are bitwise equal absent corruption, so ANY
     divergent copy (mass-preserving flips included: ``w`` is a
     Rademacher vector, two cancelling flips cannot cancel in the
     projection) is detected AND localized in one pass;
   - **dual fingerprint** (every form): ``w . r`` is computed two
     independent ways — a standalone state dispatch at the boundary
     and the in-step check tail — so a buffer that changes between
     retiring and being consumed is caught, per-shard partials
     localizing the owner on sharded forms;
   - **link conservation** (every form): the contribution total
     (measured through the whole gather/segment-sum machinery) must
     equal the directly-measured source mass ``sum(r[out_degree>0])``
     — two independent computations of the same linear functional;
   - **mass-ledger identity** (every form): the ISSUE-13
     decomposition (teleport + link + retained + dangling vs measured
     mass) with its NAMED leak — the link/teleport/dangling corruption
     classes fall out of the existing ledger vocabulary.

2. **Localization + recovery** (:class:`SdcGuard`) — a breach
   triggers a deadline-bounded re-execution of the window since the
   last clean boundary from the RETAINED device-side state
   (double-buffered like the health-check rollback; the retained copy
   is taken at clean boundaries only, so a poisoned iterate is never
   retained). A clean redo classifies the episode TRANSIENT (counted,
   solve continues); a repeat breach attributing to the SAME device
   classifies STICKY and raises
   :class:`~pagerank_tpu.parallel.elastic.DeviceQuarantinedError` —
   the elastic rescue path tears the mesh down and re-shards over the
   remaining devices with the convicted chip excluded, and the id is
   persisted (job.json + snapshot mesh_meta) so a resumed job never
   re-adopts a known-bad chip.

3. **Injection + telemetry** — ``testing/faults.DeviceFaultSchedule``
   grows seed-deterministic bit-flip kinds (mantissa/exponent/sign,
   chosen device/iteration, sticky or one-shot) so the whole
   detect -> localize -> redo -> quarantine machine runs on 8 fake CPU
   devices; ``sdc.*`` counters ride the metrics registry and the run
   report's ``sdc`` section (diffed by ``obs report``), and bench legs
   carry the measured per-checked-iteration overhead
   (``sdc_check_overhead_pct``).

Import cost: stdlib + numpy + obs.metrics (jax stays inside the
engine), the obs/graph_profile.py discipline.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional

import numpy as np

from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.parallel.elastic import DeviceQuarantinedError

#: Copy-consistency / dual-fingerprint tolerance factor: the redundant
#: computations are the SAME deterministic program over the same bits
#: (replicated copies; boundary state vs in-step tail), so they agree
#: to reduction-order rounding only — ``16 * eps * sqrt(n)`` bounds an
#: n-term accumulation-dtype sum's random-walk error with margin while
#: staying far below any single injected flip at realistic ranks.
SDC_COPY_TOL_FACTOR = 16.0

#: Analytic-invariant tolerance factor (link conservation): the two
#: sides accumulate over different term counts (E edge products vs n
#: vertex terms), so the bound follows the PR-13 ``ledger_tolerance``
#: idiom over the LARGER count — ``64 * eps * sqrt(max(n, E))``.
SDC_TOL_FACTOR = 64.0

#: Detection floor: a flip whose projection deviation lands below the
#: derived tolerance is indistinguishable from rounding BY
#: CONSTRUCTION (that is what principled tolerances mean); the chaos
#: kinds (mantissa high bit, exponent, sign) all sit orders of
#: magnitude above it at any realistic rank magnitude.

_FLOAT_KINDS = (int, float, np.floating, np.integer)


def copy_tolerance(eps: float, n: int,
                   factor: float = SDC_COPY_TOL_FACTOR) -> float:
    """Relative tolerance for the redundant (copy/dual) invariants of
    an n-vertex state in a dtype with machine epsilon ``eps``."""
    return factor * float(eps) * max(1.0, math.sqrt(max(1, n)))


def sdc_tolerance(eps: float, n: int, num_edges: Optional[int] = None,
                  factor: float = SDC_TOL_FACTOR) -> float:
    """Relative tolerance for the analytic invariants (link
    conservation): dtype epsilon scaled by the square root of the
    LARGER accumulation count — vertex terms or edge products."""
    count = max(1, int(n), int(num_edges or 0))
    return factor * float(eps) * max(1.0, math.sqrt(count))


def fingerprint_vector(seed: int, n_state: int) -> np.ndarray:
    """The seeded random-projection vector ``w``: Rademacher (+-1)
    entries from a counter-based Philox stream, so the SAME (seed,
    length) yields the same vector on every host/process — exactly
    representable in every float dtype (the projection adds no
    quantization of its own)."""
    rng = np.random.Generator(np.random.Philox(key=int(seed)))
    return (rng.integers(0, 2, int(n_state)).astype(np.int8) * 2 - 1
            ).astype(np.float64)


# -- run-scoped summary (the graph_profile publish discipline) --------------

_SUMMARY: Dict[str, object] = {}
_QUARANTINE_HOOK = None


def set_quarantine_hook(fn) -> None:
    """Register the persistence sink convictions flow through AT
    conviction time (before the quarantine error even raises): the CLI
    points this at ``job.quarantine_devices`` so a sticky chip lands
    in job.json no matter which run mode convicted it — a run WITHOUT
    the elastic rescue wired still persists the id before dying, and
    the resumed job excludes the chip from its first mesh. Cleared by
    :func:`reset` (per-run scoping)."""
    global _QUARANTINE_HOOK
    _QUARANTINE_HOOK = fn


def _blank() -> Dict[str, object]:
    return {
        "checks": 0,
        "flips_detected": 0,
        "transient": 0,
        "sticky": 0,
        "redos": 0,
        "quarantined_devices": [],
        "last_breach": None,
    }


def reset() -> None:
    """Drop the run-scoped summary + quarantine hook (cli.main entry
    discipline)."""
    global _SUMMARY, _QUARANTINE_HOOK
    _SUMMARY = {}
    _QUARANTINE_HOOK = None


def _summary() -> Dict[str, object]:
    global _SUMMARY
    if not _SUMMARY:
        _SUMMARY = _blank()
    return _SUMMARY


def report_section() -> Dict[str, object]:
    """The run report's ``sdc`` section — empty on a disarmed run (the
    key still rides every report, null-shaped, like ``lowering``)."""
    return dict(_SUMMARY) if _SUMMARY else {}


# -- invariant evaluation ---------------------------------------------------


class SdcVerdict:
    """One boundary's reconciliation result: ``ok``; the breach
    ``reasons`` (kind, deviation, tol, per-invariant suspect); and the
    consolidated ``suspect`` — a MESH POSITION index (None when the
    breach does not localize from a single pass)."""

    def __init__(self, ok: bool, reasons: List[Dict[str, object]],
                 suspect: Optional[int]):
        self.ok = ok
        self.reasons = reasons
        self.suspect = suspect

    def describe(self) -> str:
        return "; ".join(
            f"{r['kind']} deviation {r['deviation']:.3e} > tol "
            f"{r['tol']:.3e}"
            + (f" (device position {r['suspect']})"
               if r.get("suspect") is not None else "")
            for r in self.reasons
        ) or "ok"


def _vec(x) -> np.ndarray:
    return np.atleast_1d(np.asarray(x, np.float64))


def _spread_suspect(v: np.ndarray) -> int:
    return int(np.argmax(np.abs(v - np.median(v))))


def evaluate_check(pre: Dict[str, object], chk: Dict[str, object], *,
                   damping: float, semantics: str, n: int,
                   num_edges: Optional[int], eps: float,
                   stale_slack: float = 0.0) -> SdcVerdict:
    """Reconcile one checked step's ABFT values.

    ``pre`` is the standalone boundary-state dispatch over the INPUT
    rank vector (``JaxTpuEngine.sdc_state_values``: fp/mass/src per
    device); ``chk`` is the checked step's own record (in-step
    fp/mass/src over the input, fp/mass over the output, and the
    ledger sums). Both carry per-device arrays — full-copy values on
    replicated forms, per-shard partials on sharded ones
    (``chk["sharded"]``).

    ``stale_slack`` (mass units; ISSUE 17): under the asynchronous
    stale-boundary step (config.halo_async) the measured contribution
    total mixes this iteration's own-block mass with LAST iteration's
    boundary mass, so the link-conservation and flow-conservation
    identities hold only up to the previous step's L1 delta. The
    engine passes that delta as the slack; it decays to zero as the
    solve converges, so detection power is recovered exactly where a
    long solve spends its time. The fingerprint/copy duals and the
    ledger identity residual are staleness-free and keep their sharp
    tolerances — a flipped bit in the state still convicts."""
    sharded = bool(chk.get("sharded"))
    scale = float(n) if semantics == "reference" else 1.0
    tol_copy = copy_tolerance(eps, n)
    tol_link = sdc_tolerance(eps, n, num_edges)
    reasons: List[Dict[str, object]] = []

    def breach(kind: str, deviation: float, tol: float,
               suspect: Optional[int]) -> None:
        reasons.append({
            "kind": kind,
            "deviation": float(deviation),
            "tol": float(tol),
            "suspect": suspect,
        })

    # 1. copy consistency (replicated forms): every per-device vector
    # must agree across the copies.
    if not sharded:
        for name in ("fp_in", "fp_out", "mass_in", "mass_out",
                     "src_in"):
            v = chk.get(name)
            if v is None:
                continue
            v = _vec(v)
            if v.size < 2:
                continue
            dev = float(v.max() - v.min()) / max(scale, 1e-30)
            if dev > tol_copy:
                breach(f"copy:{name}", dev, tol_copy,
                       _spread_suspect(v))

    # 2. dual fingerprint / dual mass: the standalone boundary dispatch
    # vs the in-step tail, over the same input buffers. Per-device
    # diffs localize on sharded forms; replicated diffs fold into the
    # copy check above but the total still guards the window between
    # the two dispatches.
    for a_name, b_name, kind in (("fp", "fp_in", "dual:fingerprint"),
                                 ("mass", "mass_in", "dual:mass"),
                                 ("src", "src_in", "dual:src")):
        a, b = pre.get(a_name), chk.get(b_name)
        if a is None or b is None:
            continue
        a, b = _vec(a), _vec(b)
        if a.shape != b.shape:
            continue
        diff = b - a
        dev = float(np.abs(diff).max()) / max(scale, 1e-30)
        if dev > tol_copy:
            breach(kind, dev, tol_copy,
                   int(np.argmax(np.abs(diff))) if sharded else
                   _spread_suspect(b))

    # 3. link conservation: contribution total (through the gather
    # machinery) vs the directly-measured source mass. Forms without a
    # prescale argument (coo) measure no src — the ledger identity
    # below still covers them.
    contrib_total = float(np.sum(_vec(chk["contrib"])))
    src = chk.get("src_in")
    if src is not None:
        src_total = (float(np.sum(_vec(src))) if sharded
                     else float(np.median(_vec(src))))
        dev = abs(contrib_total - src_total) / max(scale, 1e-30)
        tol_link_eff = tol_link + abs(stale_slack) / max(scale, 1e-30)
        if dev > tol_link_eff:
            suspect = None
            if sharded:
                d = _vec(chk["contrib"]) - _vec(src)
                suspect = (int(np.argmax(np.abs(d)))
                           if d.size > 1 else None)
            breach("link_conservation", dev, tol_link_eff, suspect)

    # 4. mass-ledger identity (ISSUE 13 vocabulary): the decomposition
    # names the leaking term — the link/teleport/dangling corruption
    # classes, at the SDC tolerance over the larger count.
    from pagerank_tpu.obs import graph_profile

    mass_out = _vec(chk["mass_out"])
    mass = (float(mass_out.sum()) if sharded
            else float(np.median(mass_out)))
    mass_prev = float(np.sum(_vec(chk["mass_prev"])))
    entry = graph_profile.mass_ledger_entry(
        damping=damping, semantics=semantics, n=n, eps=eps,
        mass_prev=mass_prev, mass=mass,
        dangling_mass=float(chk["dangling_mass"]),
        contrib_total=contrib_total,
        retained_total=float(np.sum(_vec(chk["retained"]))),
        tol_factor=SDC_TOL_FACTOR * max(
            1.0, math.sqrt(max(1, num_edges or n) / max(1, n))),
        flow_slack=stale_slack,
    )
    if not entry["ok"]:
        breach(f"mass_ledger:{entry['leak']}",
               abs(entry["residual"])
               if entry["leak"] == "teleport"
               else abs(entry["unaccounted"] or 0.0),
               entry["tol"], None)

    suspects = [r["suspect"] for r in reasons
                if r.get("suspect") is not None]
    suspect = suspects[0] if suspects else None
    return SdcVerdict(not reasons, reasons, suspect)


def localize_diff(bad: Dict[str, object],
                  good: Dict[str, object]) -> Optional[int]:
    """Attribute a breach to a mesh position by diffing the breached
    attempt's per-device check vectors against a clean redo's — the
    deterministic step reproduces every value bit-for-bit absent
    corruption, so the mismatching position IS the suspect."""
    best, best_dev = None, 0.0
    for name in ("fp_in", "fp_out", "mass_in", "mass_out", "src_in",
                 "contrib"):
        a, b = bad.get(name), good.get(name)
        if a is None or b is None:
            continue
        a, b = _vec(a), _vec(b)
        if a.shape != b.shape or a.size < 2:
            continue
        d = np.abs(a - b)
        i = int(np.argmax(d))
        if float(d[i]) > best_dev:
            best, best_dev = i, float(d[i])
    return best


# -- the guard (detect -> redo -> classify -> quarantine) -------------------


class SdcExhaustedError(RuntimeError):
    """A breach survived the redo budget/deadline without attributing
    to one device — the state cannot be trusted and no chip can be
    convicted. Carries the boundary iteration and the last verdict
    text (the 3am-page diagnostic, the SolverHealthError contract)."""

    def __init__(self, message: str, iteration: int, redos: int):
        super().__init__(message)
        self.iteration = iteration
        self.redos = redos


def attach_guard(engine) -> Optional["SdcGuard"]:
    """Build the run's SDC guard, or None when disarmed — the solve
    loop then takes the exact pre-ISSUE-15 code path (zero check
    computations, zero retained copies; tests/test_sdc.py
    booby-traps it). Armed on an engine that cannot measure the
    invariants (the CPU oracle; a form without a ledger core) warns
    once and stays off rather than silently degrading coverage."""
    every = int(getattr(engine.config, "sdc_check_every", 0) or 0)
    if every <= 0:
        return None
    if not (hasattr(engine, "step_sdc") and engine.sdc_supported()):
        obs_log.warn(
            "--sdc-check-every is armed but this engine/form cannot "
            "measure the ABFT invariants; SDC checking disabled"
        )
        return None
    return SdcGuard(engine)


class SdcGuard:
    """Per-run SDC state machine around the checked step.

    One instance per ``engine.run`` call (a rescue's fresh engine gets
    a fresh guard; the run-scoped summary and the metrics counters
    accumulate across them). The retained state is a DEVICE-side copy
    taken at clean boundaries only — the double buffer the redo
    restores from."""

    def __init__(self, engine):
        self.engine = engine
        cfg = engine.config
        rb = cfg.robustness
        self.every = int(cfg.sdc_check_every)
        self.redo_deadline_s = float(
            getattr(rb, "sdc_redo_deadline_s", 30.0))
        self.max_redos = int(getattr(rb, "sdc_max_redos", 2))
        self._token = engine.retain_state()
        # Eager registration (the elastic-monitor discipline): a
        # checked solve exposes the sdc instruments through the
        # exporter from step one, not from the first breach.
        for name, help_ in (
            ("sdc.checks", "SDC-checked steps taken this run"),
            ("sdc.flips_detected",
             "checked steps whose ABFT invariants breached"),
            ("sdc.transient_flips",
             "breaches healed by a clean bounded re-execution"),
            ("sdc.sticky_flips",
             "repeat breaches attributed to one device (quarantined)"),
            ("sdc.redos", "bounded re-executions performed"),
            ("sdc.quarantined_devices",
             "devices convicted of sticky corruption and excluded"),
        ):
            obs_metrics.counter(name, help_)
        _summary()  # the run report section exists once armed

    def wants(self, iteration: int) -> bool:
        """Absolute cadence, like probes/snapshots — a resumed run
        checks the same iterations."""
        return (iteration + 1) % self.every == 0

    def note_rollback(self) -> None:
        """The run loop's health check rolled the engine back (NaN /
        mass drift -> snapshot restore): the retained token now points
        PAST the live iteration, and restoring it would jump the solve
        forward onto the very state the health check rejected. Re-base
        the double buffer on the freshly restored state."""
        self._token = self.engine.retain_state()

    # -- internals ---------------------------------------------------------

    def _evaluate(self, pre, chk) -> SdcVerdict:
        eng = self.engine
        ne = (int(eng.graph.num_edges)
              if eng.graph is not None and eng.graph.num_edges else None)
        return evaluate_check(
            pre, chk,
            damping=eng.config.damping,
            semantics=eng.config.semantics,
            n=int(eng.graph.n),
            num_edges=ne,
            eps=eng._ledger_eps(),
            # Prefer the per-attempt stamp (the delta bound of the
            # state THIS chk was measured from); fall back to the
            # engine's live value for engines that don't stamp.
            stale_slack=float(
                chk.get("stale_slack", eng._stale_slack()) or 0.0),
        )

    def _device_id(self, position: Optional[int]) -> Optional[int]:
        if position is None:
            return None
        mesh = getattr(self.engine, "mesh", None)
        if mesh is None:
            return None
        devs = list(mesh.devices.reshape(-1))
        if 0 <= position < len(devs):
            return int(devs[position].id)
        return None

    def _commit(self, info: Dict[str, float]) -> Dict[str, float]:
        # Retain AFTER a clean check only: the double buffer must never
        # hold a poisoned iterate.
        self._token = self.engine.retain_state(
            iteration=self.engine.iteration + 1)
        return info

    def _quarantine(self, position: int, iteration: int,
                    detail: str) -> None:
        dev_id = self._device_id(position)
        s = _summary()
        s["sticky"] = int(s["sticky"]) + 1
        if dev_id is not None and dev_id not in s["quarantined_devices"]:
            s["quarantined_devices"].append(dev_id)
        obs_metrics.counter("sdc.sticky_flips").inc()
        obs_metrics.counter("sdc.quarantined_devices").inc()
        if _QUARANTINE_HOOK is not None and dev_id is not None:
            # Persist BEFORE raising: even a run with no rescue wired
            # records the conviction durably before it dies.
            try:
                _QUARANTINE_HOOK([dev_id])
            except Exception as e:  # persistence must not mask the verdict
                obs_log.warn(
                    f"quarantine persistence hook failed ({e!r}); the "
                    f"conviction still raises"
                )
        obs_log.warn(
            f"SDC STICKY at iteration {iteration}: device "
            f"{dev_id} (mesh position {position}) breached the ABFT "
            f"invariants twice across a clean-state re-execution "
            f"({detail}); quarantining it through the elastic rescue "
            f"path"
        )
        raise DeviceQuarantinedError(
            f"sticky silent data corruption on device {dev_id} at "
            f"iteration {iteration} ({detail})",
            device_ids=[dev_id] if dev_id is not None else [],
        )

    # -- the checked step ---------------------------------------------------

    def checked_step(self) -> Dict[str, float]:
        """Run one SDC-checked iteration: standalone boundary-state
        dispatch, the checked step, reconciliation — and on a breach
        the deadline-bounded redo/classify machine. Returns the step
        info (the plain step's scalars plus ``rank_mass`` and a small
        ``sdc`` record); raises
        :class:`~pagerank_tpu.parallel.elastic.DeviceQuarantinedError`
        on a sticky conviction and :class:`SdcExhaustedError` when the
        budget/deadline is spent without one."""
        eng = self.engine
        boundary = eng.iteration
        if self._token[0] > boundary:
            # Defensive twin of :meth:`note_rollback`: a token from the
            # future (the engine was rewound behind our back) must
            # never be restored — re-base on the current state so a
            # redo re-executes THIS boundary only.
            self._token = eng.retain_state()
        s = _summary()
        s["checks"] = int(s["checks"]) + 1
        obs_metrics.counter("sdc.checks").inc()
        pre = eng.sdc_state_values()
        info, chk = eng.step_sdc()
        verdict = self._evaluate(pre, chk)
        if verdict.ok:
            info["sdc"] = {"ok": True}
            return self._commit(info)

        # -- breach: detect, then redo/classify ----------------------------
        s["flips_detected"] = int(s["flips_detected"]) + 1
        s["last_breach"] = {
            "iteration": int(boundary),
            "reasons": list(verdict.reasons),
        }
        obs_metrics.counter("sdc.flips_detected").inc()
        obs_metrics.gauge(
            "sdc.last_breach_iteration",
            "iteration of the latest ABFT invariant breach",
        ).set(int(boundary))
        tracer = obs_trace.get_tracer()
        if tracer.enabled:
            tracer.add_event("sdc/breach", iteration=boundary,
                             detail=verdict.describe())
        obs_log.warn(
            f"SDC breach at iteration {boundary}: "
            f"{verdict.describe()}; re-executing from the retained "
            f"state (iteration {self._token[0]})"
        )
        suspect = verdict.suspect
        bad_chk = chk
        t0 = time.monotonic()
        redos = 0
        while True:
            if redos >= self.max_redos:
                break
            if time.monotonic() - t0 > self.redo_deadline_s:
                obs_log.warn(
                    f"SDC redo deadline ({self.redo_deadline_s:g}s) "
                    f"exceeded at iteration {boundary}"
                )
                break
            redos += 1
            s["redos"] = int(s["redos"]) + 1
            obs_metrics.counter("sdc.redos").inc()
            eng.restore_state(self._token)
            # Replay the window since the last clean boundary with
            # PLAIN steps (the fault shim re-consults its schedule
            # deterministically: one-shot flips stay healed, a sticky
            # chip re-corrupts), then re-run the checked step.
            while eng.iteration < boundary:
                eng.step()
                eng.iteration += 1
            pre = eng.sdc_state_values()
            info, chk = eng.step_sdc()
            v2 = self._evaluate(pre, chk)
            if v2.ok:
                # TRANSIENT: the clean redo's values are the ground
                # truth the breached attempt diffs against — the
                # mismatching device position is the suspect.
                pos = suspect
                if pos is None:
                    pos = localize_diff(bad_chk, chk)
                dev_id = self._device_id(pos)
                s["transient"] = int(s["transient"]) + 1
                s["last_breach"]["classified"] = "transient"
                s["last_breach"]["device"] = dev_id
                obs_metrics.counter("sdc.transient_flips").inc()
                obs_log.warn(
                    f"SDC TRANSIENT at iteration {boundary}: clean "
                    f"re-execution reconciles; suspect device "
                    f"{dev_id} (mesh position {pos}); continuing"
                )
                info["sdc"] = {"ok": True, "transient": True,
                               "redos": redos,
                               "suspect_device": dev_id}
                return self._commit(info)
            # Repeat breach: same attributed device => sticky.
            s2 = v2.suspect
            if s2 is None:
                s2 = suspect
            if s2 is not None and (suspect is None or s2 == suspect):
                s["last_breach"]["classified"] = "sticky"
                s["last_breach"]["device"] = self._device_id(s2)
                self._quarantine(s2, boundary, v2.describe())
            # Attribution moved: keep the newest suspect and spend
            # another redo on it (bounded above).
            suspect = s2 if s2 is not None else suspect
            bad_chk = chk
        if suspect is not None:
            # Budget spent but an attribution stands: convicting the
            # suspect beats solving on state that cannot be trusted.
            s["last_breach"]["classified"] = "sticky"
            s["last_breach"]["device"] = self._device_id(suspect)
            self._quarantine(suspect, boundary, verdict.describe())
        raise SdcExhaustedError(
            f"SDC breach at iteration {boundary} survived {redos} "
            f"re-execution(s) without attributing to a device "
            f"({verdict.describe()}); state cannot be trusted",
            iteration=boundary, redos=redos,
        )
