"""Bounded retry with exponential backoff and full jitter (the
substrate half of the reference's fault story).

The reference inherits ALL of its fault tolerance from Spark — task
retry, lineage recovery, straggler re-execution (SURVEY.md §5); its own
code has none. This build replaced that substrate with direct I/O
(utils/fsio, utils/s3), so the retry discipline has to live here: a
:class:`RetryPolicy` owns the attempt budget, the backoff curve
(exponential, capped, FULL jitter — delay is uniform in ``[0, cap]``,
the AWS-recommended variant that decorrelates a thundering herd of
writers hitting a throttled store), an optional wall-clock deadline,
and the retryable-predicate. Every time source is injectable
(``clock``/``sleep``/``seed``) so tests run the whole schedule in
virtual time and a given seed reproduces the same jitter sequence
bit-for-bit (tests/test_faults.py).

Consumers: ``S3FileSystem._request`` (5xx / SlowDown / connection
reset / timeout), the snapshot sink guard
(utils/snapshot.SinkGuard / AsyncRankWriter), and anything else with a
transient failure mode. The full retry matrix — which errors retry
where — is the table in docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from pagerank_tpu.obs import trace as obs_trace


def default_retryable(exc: BaseException) -> bool:
    """Transient-I/O default: network/socket/timeout errors retry;
    *semantic* filesystem errors (missing key, existing file, permission)
    never do — retrying those only hides a real bug."""
    if isinstance(exc, (FileNotFoundError, FileExistsError, IsADirectoryError,
                        NotADirectoryError, PermissionError)):
        return False
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


@dataclass
class RetryStats:
    """Mutable counters a caller threads through :meth:`RetryPolicy.call`
    (the CLI surfaces them in the run summary)."""

    attempts: int = 0  # total call attempts (successes included)
    retries: int = 0   # re-attempts after a retryable failure
    slept: float = 0.0  # total backoff seconds requested

    def add(self, other: "RetryStats") -> None:
        self.attempts += other.attempts
        self.retries += other.retries
        self.slept += other.slept


@dataclass
class RetryPolicy:
    """Attempt budget + backoff curve for one class of transient failure.

    ``max_attempts`` counts TOTAL attempts (1 = no retry). The delay
    before re-attempt ``k`` (1-based failure count) is drawn uniformly
    from ``[0, min(max_delay, base_delay * 2**(k-1))]`` — full jitter.
    ``deadline`` (seconds, measured on ``clock``) bounds the whole
    sequence: a retry whose backoff would land past it re-raises
    instead. ``seed`` pins the jitter stream; ``clock``/``sleep`` are
    injectable so tests run in virtual time.
    """

    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    deadline: Optional[float] = None
    retryable: Callable[[BaseException], bool] = default_retryable
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    seed: Optional[int] = None
    _rng: random.Random = field(init=False, repr=False, compare=False,
                                default=None)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("backoff delays must be >= 0")
        self._rng = random.Random(self.seed)

    def backoff(self, failure: int) -> float:
        """Full-jitter delay before the retry that follows the
        ``failure``-th (1-based) failed attempt. Consumes the jitter
        stream — deterministic per ``seed``."""
        cap = min(self.max_delay, self.base_delay * (2 ** (failure - 1)))
        return self._rng.uniform(0.0, cap)

    def call(self, fn: Callable[[], object], *,
             stats: Optional[RetryStats] = None,
             on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
             retryable: Optional[Callable[[BaseException], bool]] = None):
        """Run ``fn()`` under this policy; returns its result. The final
        failure re-raises the ORIGINAL exception (never a wrapper — the
        caller's except clauses keep working). ``on_retry(failure,
        delay, exc)`` fires before each backoff sleep."""
        is_retryable = retryable if retryable is not None else self.retryable
        # Tracer read once per call(): each attempt becomes a
        # ``retry/attempt`` span (with the failure count and backoff as
        # attributes) when tracing is on; the disabled path touches the
        # tracer zero times per attempt.
        tracer = obs_trace.get_tracer()
        traced = tracer.enabled
        start = self.clock()
        failures = 0
        while True:
            if stats is not None:
                stats.attempts += 1
            try:
                if traced:
                    with tracer.span("retry/attempt", attempt=failures + 1):
                        return fn()
                return fn()
            except BaseException as e:
                failures += 1
                if failures >= self.max_attempts or not is_retryable(e):
                    raise
                delay = self.backoff(failures)
                if (self.deadline is not None
                        and (self.clock() - start) + delay > self.deadline):
                    raise
                if on_retry is not None:
                    on_retry(failures, delay, e)
                if stats is not None:
                    stats.retries += 1
                    stats.slept += delay
                if traced:
                    tracer.add_event(
                        "retry/backoff", failure=failures,
                        delay_s=delay, error=type(e).__name__,
                    )
                self.sleep(delay)
