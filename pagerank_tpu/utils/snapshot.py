"""Checkpoint / resume (SURVEY.md §5).

The reference's de-facto checkpoint is ``saveAsTextFile`` of the full
rank vector after every iteration (Sparky.java:237) with no resume logic.
Here snapshots are first-class: (ranks, iteration, graph fingerprint,
semantics) per file, a ``latest()`` scan, and ``resume_engine`` that
validates the fingerprint before restoring — restart-from-latest is the
failure-recovery story (kill-and-resume is tested in
tests/test_snapshot.py).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional, Tuple

import numpy as np

_PAT = re.compile(r"^ranks_iter(\d+)\.npz$")


class Snapshotter:
    """Writes ``ranks_iter{i}.npz`` files into ``directory``."""

    def __init__(self, directory: str, graph_fingerprint: str, semantics: str):
        self.directory = directory
        self.fingerprint = graph_fingerprint
        self.semantics = semantics
        os.makedirs(directory, exist_ok=True)

    def path(self, iteration: int) -> str:
        return os.path.join(self.directory, f"ranks_iter{iteration}.npz")

    def save(self, iteration: int, ranks: np.ndarray) -> str:
        p = self.path(iteration)
        tmp = p + ".tmp.npz"
        np.savez(
            tmp,
            ranks=ranks,
            iteration=np.int64(iteration),
            fingerprint=np.bytes_(self.fingerprint.encode()),
            semantics=np.bytes_(self.semantics.encode()),
        )
        os.replace(tmp, p)  # atomic: a killed run never leaves a torn file
        return p

    def latest(self) -> Optional[int]:
        best = None
        try:
            entries = os.listdir(self.directory)
        except FileNotFoundError:
            return None
        for name in entries:
            m = _PAT.match(name)
            if m:
                i = int(m.group(1))
                best = i if best is None else max(best, i)
        return best

    def load(self, iteration: int) -> Tuple[np.ndarray, Dict[str, str]]:
        with np.load(self.path(iteration)) as z:
            meta = {
                "fingerprint": bytes(z["fingerprint"]).decode(),
                "semantics": bytes(z["semantics"]).decode(),
                "iteration": int(z["iteration"]),
            }
            return z["ranks"].copy(), meta

class TextDumper:
    """Per-iteration plain-text rank dumps mirroring the reference's
    ``ranks.saveAsTextFile("…/PageRank"+iter+"/")`` (Sparky.java:237):
    one directory per iteration, ``(key,rank)`` tuple lines, Spark
    part-file naming. Pair with :class:`Snapshotter` when you also want
    binary resumable checkpoints."""

    def __init__(self, directory: str, names=None):
        self.directory = directory
        self.names = names
        os.makedirs(directory, exist_ok=True)

    def dump(self, iteration: int, ranks: np.ndarray) -> str:
        d = os.path.join(self.directory, f"PageRank{iteration}")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "part-00000")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for i, r in enumerate(ranks):
                key = self.names[i] if self.names is not None else i
                f.write(f"({key},{float(r)!r})\n")
        os.replace(tmp, path)
        return path


def resume_engine(engine, snap: Snapshotter) -> int:
    """Restore the latest snapshot into ``engine``; returns the iteration
    resumed from (0 if none found). Refuses a snapshot taken on a
    different graph or semantics mode."""
    it = snap.latest()
    if it is None:
        return 0
    ranks, meta = snap.load(it)
    if meta["fingerprint"] != snap.fingerprint:
        raise ValueError(
            f"snapshot graph fingerprint {meta['fingerprint']} != current "
            f"{snap.fingerprint}; refusing to resume"
        )
    if meta["semantics"] != snap.semantics:
        raise ValueError(
            f"snapshot semantics {meta['semantics']!r} != current {snap.semantics!r}"
        )
    engine.set_ranks(ranks, iteration=meta["iteration"])
    return meta["iteration"]
