"""Checkpoint / resume (SURVEY.md §5).

The reference's de-facto checkpoint is ``saveAsTextFile`` of the full
rank vector after every iteration (Sparky.java:237) with no resume logic.
Here snapshots are first-class: (ranks, iteration, graph fingerprint,
semantics) per file, a ``latest()`` scan, and ``resume_engine`` that
validates the fingerprint before restoring — restart-from-latest is the
failure-recovery story (kill-and-resume is tested in
tests/test_snapshot.py).
"""

from __future__ import annotations

import queue
import re
import threading
from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from pagerank_tpu.utils import fsio

_PAT = re.compile(r"^ranks_iter(\d+)\.npz$")


class Snapshotter:
    """Writes ``ranks_iter{i}.npz`` files into ``directory`` — a local
    path or any registered URI scheme (utils/fsio; the reference's sink
    is an S3 bucket, Sparky.java:237)."""

    def __init__(self, directory: str, graph_fingerprint: str, semantics: str):
        self.directory = directory
        self.fingerprint = graph_fingerprint
        self.semantics = semantics
        fsio.makedirs(directory, exist_ok=True)

    def path(self, iteration: int) -> str:
        return fsio.join(self.directory, f"ranks_iter{iteration}.npz")

    def save(self, iteration: int, ranks: np.ndarray) -> str:
        p = self.path(iteration)
        tmp = p + ".tmp.npz"
        with fsio.fopen(tmp, "wb") as f:
            np.savez(
                f,
                ranks=ranks,
                iteration=np.int64(iteration),
                fingerprint=np.bytes_(self.fingerprint.encode()),
                semantics=np.bytes_(self.semantics.encode()),
            )
        fsio.replace(tmp, p)  # atomic: a killed run never leaves a torn file
        return p

    def latest(self) -> Optional[int]:
        best = None
        try:
            entries = fsio.listdir(self.directory)
        except FileNotFoundError:
            return None
        for name in entries:
            m = _PAT.match(name)
            if m:
                i = int(m.group(1))
                best = i if best is None else max(best, i)
        return best

    def load(self, iteration: int) -> Tuple[np.ndarray, Dict[str, str]]:
        with fsio.fopen(self.path(iteration), "rb") as f, np.load(f) as z:
            meta = {
                "fingerprint": bytes(z["fingerprint"]).decode(),
                "semantics": bytes(z["semantics"]).decode(),
                "iteration": int(z["iteration"]),
            }
            return z["ranks"].copy(), meta

class TextDumper:
    """Per-iteration plain-text rank dumps mirroring the reference's
    ``ranks.saveAsTextFile("…/PageRank"+iter+"/")`` (Sparky.java:237):
    one directory per iteration, ``(key,rank)`` tuple lines, Spark
    part-file naming. Pair with :class:`Snapshotter` when you also want
    binary resumable checkpoints.

    Formatting goes through the native bulk formatter when the library
    is available (ingest/native.format_rank_lines_native — byte-
    identical output, ~40x the per-line Python loop; the loop remains
    as the no-toolchain fallback). The reference's per-iteration dump
    is most of its L4 wall-clock, so the formatter rate is a first-
    class number (VERDICT r4 weak #1; docs/PERF_NOTES.md "Text-dump
    rate")."""

    def __init__(self, directory: str, names=None):
        self.directory = directory
        self.names = names
        self._blob: Optional[Tuple[bytes, np.ndarray]] = None
        fsio.makedirs(directory, exist_ok=True)

    def _names_blob(self, n: int):
        """(utf-8 blob, int64 offsets) for the first n names; None when
        the name table can't feed the native path (length mismatch or
        non-utf-8-encodable names — the Python loop handles those by
        crashing identically or writing the str form)."""
        if self._blob is None or self._blob[1].shape[0] != n + 1:
            if len(self.names) < n:
                return None
            try:
                enc = [
                    str(k).encode("utf-8") for k in self.names[:n]
                ]
            except UnicodeEncodeError:
                return None
            offs = np.zeros(n + 1, np.int64)
            np.cumsum([len(b) for b in enc], out=offs[1:])
            self._blob = (b"".join(enc), offs)
        return self._blob

    #: Rows formatted per write: bounds the formatter's transient output
    #: buffer (48 B/line integer-key cap -> ~50 MB per chunk) so a dump
    #: at any scale runs in O(chunk) extra RSS, not O(n).
    CHUNK_ROWS = 1 << 20

    def dump(self, iteration: int, ranks: np.ndarray) -> str:
        from pagerank_tpu.ingest.native import format_rank_lines_native

        d = fsio.join(self.directory, f"PageRank{iteration}")
        fsio.makedirs(d, exist_ok=True)
        path = fsio.join(d, "part-00000")
        tmp = path + ".tmp"
        blob = None if self.names is None else self._names_blob(len(ranks))
        with fsio.fopen(tmp, "wb") as f:
            for lo in range(0, len(ranks), self.CHUNK_ROWS):
                hi = min(lo + self.CHUNK_ROWS, len(ranks))
                chunk = ranks[lo:hi]
                if self.names is None:
                    data = format_rank_lines_native(chunk, key_base=lo)
                elif blob is not None:
                    offs = blob[1]
                    data = format_rank_lines_native(
                        chunk,
                        blob[0][offs[lo] : offs[hi]],
                        offs[lo : hi + 1] - offs[lo],
                    )
                else:
                    data = None
                if data is None:
                    # Python fallback — encoded to utf-8 bytes
                    # explicitly so the two paths stay byte-identical
                    # on any locale/platform (text mode would use the
                    # locale codec and '\n' translation).
                    data = "".join(
                        f"({self.names[i] if self.names is not None else i},"
                        f"{float(r)!r})\n"
                        for i, r in enumerate(chunk, start=lo)
                    ).encode("utf-8")
                f.write(data)
        fsio.replace(tmp, path)
        # Hadoop job-completion marker (saveAsTextFile writes one per
        # output dir); written LAST so its presence certifies a
        # complete, untorn dump to downstream Hadoop-convention tooling.
        with fsio.fopen(fsio.join(d, "_SUCCESS"), "w"):
            pass
        return path


class AsyncRankWriter:
    """Overlap the device->host rank offload and file writes with device
    compute — C17's TPU-native build target (SURVEY.md §2: "async
    device→host offload + file write per iteration"), vs the
    reference's synchronous ``saveAsTextFile`` barrier per iteration
    (Sparky.java:237).

    The iteration loop calls ``submit(i, payload)`` with a cheap
    payload — for the JAX engine a *device-side copy* of the rank
    vector (``engine.device_ranks()``; the live buffer is donated to
    the next step, so a copy is required) — and keeps dispatching
    steps. A worker thread runs ``decode(payload)`` (the blocking
    device->host transfer releases the GIL) and feeds every sink.
    ``max_pending`` bounds in-flight copies; when the writer falls
    behind, ``submit`` blocks — snapshots are never dropped. Worker
    errors surface on the next ``submit`` or on ``close``; ``submit``
    re-checks after enqueueing so a failure that lands during a blocking
    put aborts immediately, but a sink error can still go unnoticed for
    up to one iteration (the run keeps computing until the next submit —
    acceptable for a side-channel sink, never for result correctness).
    """

    def __init__(
        self,
        decode: Callable[[object], np.ndarray],
        sinks: Iterable[Callable[[int, np.ndarray], object]],
        max_pending: int = 4,
    ):
        self._decode = decode
        self._sinks = list(sinks)
        self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="rank-writer", daemon=True
        )
        self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                if self._err is not None:
                    continue  # drain after failure
                iteration, payload = item
                ranks = self._decode(payload)
                for sink in self._sinks:
                    sink(iteration, ranks)
            except BaseException as e:  # surfaced to the submitter
                self._err = e
            finally:
                self._q.task_done()

    def _check(self):
        if self._err is not None:
            raise RuntimeError(
                f"async rank writer failed: {self._err}"
            ) from self._err

    def submit(self, iteration: int, payload) -> None:
        self._check()
        self._q.put((iteration, payload))
        # Re-check: if the worker failed while the put above blocked on a
        # full queue, fail now rather than queueing more device copies.
        self._check()

    def close(self) -> None:
        """Flush all pending writes and stop the worker; raises if any
        write failed."""
        self._q.put(None)
        self._thread.join()
        self._check()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def resume_engine(engine, snap: Snapshotter) -> int:
    """Restore the latest snapshot into ``engine``; returns the iteration
    resumed from (0 if none found). Refuses a snapshot taken on a
    different graph or semantics mode."""
    it = snap.latest()
    if it is None:
        return 0
    ranks, meta = snap.load(it)
    if meta["fingerprint"] != snap.fingerprint:
        raise ValueError(
            f"snapshot graph fingerprint {meta['fingerprint']} != current "
            f"{snap.fingerprint}; refusing to resume (note: crawl-input "
            "graphs hash their dangling mask into the fingerprint since "
            "r3 — older crawl-input snapshots no longer validate; see "
            "docs/PARITY.md 'Snapshot-compat note')"
        )
    if meta["semantics"] != snap.semantics:
        raise ValueError(
            f"snapshot semantics {meta['semantics']!r} != current {snap.semantics!r}"
        )
    engine.set_ranks(ranks, iteration=meta["iteration"])
    return meta["iteration"]
