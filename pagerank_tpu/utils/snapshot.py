"""Checkpoint / resume (SURVEY.md §5).

The reference's de-facto checkpoint is ``saveAsTextFile`` of the full
rank vector after every iteration (Sparky.java:237) with no resume logic.
Here snapshots are first-class: (ranks, iteration, graph fingerprint,
semantics, content checksum) per file, a ``latest()`` scan, and
``resume_engine`` that validates the fingerprint before restoring —
restart-from-latest is the failure-recovery story (kill-and-resume is
tested in tests/test_snapshot.py). Every save is atomic
(fsio.atomic_write: tmp + rename) and every load verifies the sha256
sidecar, so a torn, truncated, or bit-flipped snapshot is DETECTED and
skipped (``load_latest_valid``) rather than resumed into — the rollback
substrate for the self-healing solve loop (engine.run;
docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import hashlib
import json
import queue
import re
import threading
import time
import warnings
import zipfile
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.utils import fsio
from pagerank_tpu.utils.retry import RetryPolicy

_PAT = re.compile(r"^ranks_iter(\d+)\.npz$")


class SnapshotCorruptError(RuntimeError):
    """A snapshot file exists but cannot be trusted: unreadable npz,
    missing members, or checksum mismatch. Distinct from
    FileNotFoundError (no snapshot) and ValueError (valid snapshot,
    wrong graph/semantics) so recovery code can skip-and-fall-back on
    corruption while still failing loudly on real mismatches."""


def _digest(ranks: np.ndarray, iteration: int, fingerprint: str,
            semantics: str, mesh: str = "") -> str:
    """sha256 over the rank payload AND its identifying metadata — a
    corrupt header is as fatal as corrupt ranks. ``mesh`` is the
    mesh-topology JSON when the snapshot carries one (empty keeps the
    pre-elastic digest, so older snapshots still verify)."""
    h = hashlib.sha256()
    h.update(
        f"{iteration}|{fingerprint}|{semantics}|"
        f"{ranks.dtype.str}|{ranks.shape}|".encode()
    )
    if mesh:
        h.update(f"mesh:{mesh}|".encode())
    h.update(np.ascontiguousarray(ranks).tobytes())
    return h.hexdigest()


def _gather_to_host(ranks) -> np.ndarray:
    """ONE host-resident contiguous buffer from whatever the caller
    handed us. A sharded engine's rank vector is a jax Array whose
    shards live across devices — ``np.ascontiguousarray`` on it can
    tear through per-shard ``__array__`` paths mid-save, and the
    checksum MUST cover the exact bytes written. Gathering first
    (``jax.device_get`` assembles addressable shards into one numpy
    array) makes save/checksum mesh-shape-agnostic: the file always
    holds the canonical host-order vector regardless of how many
    devices computed it (docs/ROBUSTNESS.md "Elastic solve")."""
    if isinstance(ranks, np.ndarray):
        return np.ascontiguousarray(ranks)
    if hasattr(ranks, "addressable_shards") or hasattr(ranks, "devices"):
        import jax

        ranks = jax.device_get(ranks)
    return np.ascontiguousarray(np.asarray(ranks))


class Snapshotter:
    """Writes ``ranks_iter{i}.npz`` files into ``directory`` — a local
    path or any registered URI scheme (utils/fsio; the reference's sink
    is an S3 bucket, Sparky.java:237).

    Snapshots are MESH-SHAPE-AGNOSTIC (ISSUE 7): the payload is always
    the canonical host-order rank vector (``_gather_to_host`` assembles
    sharded device buffers first), and ``mesh_meta`` — the mesh
    topology + partition geometry of the engine that produced it
    (``JaxTpuEngine.snapshot_meta``) — rides as checksummed JSON
    metadata. A snapshot taken on N devices therefore restores onto
    any M-device (or single-device) mesh: ``resume_engine`` hands the
    canonical vector to ``engine.set_ranks``, which re-shards it
    through the target mesh's own placement (the elastic rescue's
    warm-start, parallel/elastic.py). ``mesh_meta`` is diagnostic
    provenance, never a restore constraint."""

    def __init__(self, directory: str, graph_fingerprint: str,
                 semantics: str, mesh_meta: Optional[Dict] = None):
        self.directory = directory
        self.fingerprint = graph_fingerprint
        self.semantics = semantics
        #: Provenance recorded in every save (mutable: the elastic
        #: runner updates it after a rescue re-shards the mesh).
        self.mesh_meta = mesh_meta
        fsio.makedirs(directory, exist_ok=True)

    def path(self, iteration: int) -> str:
        return fsio.join(self.directory, f"ranks_iter{iteration}.npz")

    def save(self, iteration: int, ranks: np.ndarray) -> str:
        p = self.path(iteration)
        with obs_trace.span("snapshot/save", iteration=iteration) as sp:
            # Gather BEFORE checksumming: a sharded engine's device
            # array becomes one host buffer, so the digest covers the
            # exact bytes np.savez writes (the torn-shard hazard).
            ranks = _gather_to_host(ranks)
            mesh_json = (
                json.dumps(self.mesh_meta, sort_keys=True)
                if self.mesh_meta else ""
            )
            members = {}
            if mesh_json:
                members["mesh"] = np.bytes_(mesh_json.encode())
            # atomic: a killed run never leaves a torn file under the
            # consumers' name pattern (suffix keeps the historical
            # *.tmp.npz spelling tests/test_hardening.py filters on)
            with fsio.atomic_write(p, "wb", suffix=".tmp.npz") as f:
                np.savez(
                    f,
                    ranks=ranks,
                    iteration=np.int64(iteration),
                    fingerprint=np.bytes_(self.fingerprint.encode()),
                    semantics=np.bytes_(self.semantics.encode()),
                    checksum=np.bytes_(
                        _digest(ranks, iteration, self.fingerprint,
                                self.semantics, mesh_json).encode()
                    ),
                    **members,
                )
                nbytes = f.tell()
            obs_metrics.counter(
                "snapshot.bytes_written",
                "total snapshot payload bytes committed",
            ).inc(nbytes)
            obs_metrics.histogram(
                "snapshot.save_bytes", "per-snapshot file size"
            ).record(nbytes)
            if sp is not None:
                sp.attrs["bytes"] = nbytes
        return p

    def iterations(self) -> List[int]:
        """All snapshot iterations present, ascending (by NAME only —
        no validity check; load_latest_valid does that)."""
        try:
            entries = fsio.listdir(self.directory)
        except FileNotFoundError:
            return []
        out = []
        for name in entries:
            m = _PAT.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self) -> Optional[int]:
        its = self.iterations()
        return its[-1] if its else None

    def load(self, iteration: int, verify: bool = True
             ) -> Tuple[np.ndarray, Dict[str, str]]:
        """Load one snapshot. Raises FileNotFoundError when absent and
        :class:`SnapshotCorruptError` when present but unreadable or
        failing its checksum. Pre-checksum snapshots (no ``checksum``
        member) load with a warning — their integrity is unverifiable."""
        path = self.path(iteration)
        try:
            with fsio.fopen(path, "rb") as f, np.load(f) as z:
                meta = {
                    "fingerprint": bytes(z["fingerprint"]).decode(),
                    "semantics": bytes(z["semantics"]).decode(),
                    "iteration": int(z["iteration"]),
                }
                mesh_json = (
                    bytes(z["mesh"]).decode() if "mesh" in z.files else ""
                )
                # Parsed topology/geometry provenance (None on
                # pre-elastic snapshots): purely diagnostic — a resume
                # onto a different mesh shape is the DESIGN, not an
                # error (docs/ROBUSTNESS.md "Elastic solve").
                meta["mesh"] = json.loads(mesh_json) if mesh_json else None
                ranks = z["ranks"].copy()
                stored = (
                    bytes(z["checksum"]).decode()
                    if "checksum" in z.files else None
                )
        except FileNotFoundError:
            raise
        except (OSError, ValueError, KeyError, EOFError,
                zipfile.BadZipFile) as e:
            raise SnapshotCorruptError(
                f"snapshot {path} is unreadable: {e!r}"
            ) from e
        if verify:
            if stored is None:
                warnings.warn(
                    f"snapshot {path} predates content checksums; "
                    f"integrity not verifiable", RuntimeWarning,
                )
            else:
                want = _digest(ranks, meta["iteration"],
                               meta["fingerprint"], meta["semantics"],
                               mesh_json)
                if stored != want:
                    raise SnapshotCorruptError(
                        f"snapshot {path} failed its checksum "
                        f"(stored {stored[:12]}…, computed {want[:12]}…)"
                    )
        return ranks, meta

    def load_latest_valid(
        self, max_iteration: Optional[int] = None, match: bool = False
    ) -> Optional[Tuple[int, np.ndarray, Dict[str, str]]]:
        """Newest loadable, checksum-valid snapshot (optionally at or
        below ``max_iteration``): ``(iteration, ranks, meta)`` or None.
        Corrupt/truncated files are skipped WITH A WARNING and the scan
        falls back to the next older one — a damaged snapshot directory
        degrades recovery granularity, never crashes it.

        ``match=True`` additionally skips (with a warning) snapshots
        whose fingerprint/semantics differ from this Snapshotter's —
        the ROLLBACK contract (engine.run must never restore another
        graph's ranks, e.g. from a reused --snapshot-dir). The resume
        path keeps ``match=False`` so a mismatch RAISES there
        (resume_engine) instead of silently starting over."""
        for it in reversed(self.iterations()):
            if max_iteration is not None and it > max_iteration:
                continue
            try:
                ranks, meta = self.load(it)
            except FileNotFoundError:
                continue  # raced with cleanup
            except SnapshotCorruptError as e:
                warnings.warn(
                    f"skipping corrupt snapshot for iteration {it}: {e}",
                    RuntimeWarning,
                )
                continue
            if match and (meta["fingerprint"] != self.fingerprint
                          or meta["semantics"] != self.semantics):
                warnings.warn(
                    f"skipping snapshot for iteration {it}: taken on a "
                    f"different graph/semantics "
                    f"({meta['fingerprint'][:12]}…/{meta['semantics']} vs "
                    f"{self.fingerprint[:12]}…/{self.semantics})",
                    RuntimeWarning,
                )
                continue
            return it, ranks, meta
        return None

class TextDumper:
    """Per-iteration plain-text rank dumps mirroring the reference's
    ``ranks.saveAsTextFile("…/PageRank"+iter+"/")`` (Sparky.java:237):
    one directory per iteration, ``(key,rank)`` tuple lines, Spark
    part-file naming. Pair with :class:`Snapshotter` when you also want
    binary resumable checkpoints.

    Formatting goes through the native bulk formatter when the library
    is available (ingest/native.format_rank_lines_native — byte-
    identical output, ~40x the per-line Python loop; the loop remains
    as the no-toolchain fallback). The reference's per-iteration dump
    is most of its L4 wall-clock, so the formatter rate is a first-
    class number (VERDICT r4 weak #1; docs/PERF_NOTES.md "Text-dump
    rate")."""

    def __init__(self, directory: str, names=None):
        self.directory = directory
        self.names = names
        self._blob: Optional[Tuple[bytes, np.ndarray]] = None
        fsio.makedirs(directory, exist_ok=True)

    def _names_blob(self, n: int):
        """(utf-8 blob, int64 offsets) for the first n names; None when
        the name table can't feed the native path (length mismatch or
        non-utf-8-encodable names — the Python loop handles those by
        crashing identically or writing the str form)."""
        if self._blob is None or self._blob[1].shape[0] != n + 1:
            if len(self.names) < n:
                return None
            try:
                enc = [
                    str(k).encode("utf-8") for k in self.names[:n]
                ]
            except UnicodeEncodeError:
                return None
            offs = np.zeros(n + 1, np.int64)
            np.cumsum([len(b) for b in enc], out=offs[1:])
            self._blob = (b"".join(enc), offs)
        return self._blob

    #: Rows formatted per write: bounds the formatter's transient output
    #: buffer (48 B/line integer-key cap -> ~50 MB per chunk) so a dump
    #: at any scale runs in O(chunk) extra RSS, not O(n).
    CHUNK_ROWS = 1 << 20

    def dump(self, iteration: int, ranks: np.ndarray) -> str:
        with obs_trace.span("snapshot/dump", iteration=iteration,
                            rows=len(ranks)):
            return self._dump(iteration, ranks)

    def _dump(self, iteration: int, ranks: np.ndarray) -> str:
        from pagerank_tpu.ingest.native import format_rank_lines_native

        d = fsio.join(self.directory, f"PageRank{iteration}")
        fsio.makedirs(d, exist_ok=True)
        # Same atomic tmp+rename path as Snapshotter.save
        # (fsio.atomic_write): a mid-dump kill leaves at worst a
        # part-00000.tmp no Hadoop-convention consumer matches — never
        # a half-written, parseable-looking part file.
        path = fsio.join(d, "part-00000")
        blob = None if self.names is None else self._names_blob(len(ranks))
        with fsio.atomic_write(path, "wb") as f:
            for lo in range(0, len(ranks), self.CHUNK_ROWS):
                hi = min(lo + self.CHUNK_ROWS, len(ranks))
                chunk = ranks[lo:hi]
                if self.names is None:
                    data = format_rank_lines_native(chunk, key_base=lo)
                elif blob is not None:
                    offs = blob[1]
                    data = format_rank_lines_native(
                        chunk,
                        blob[0][offs[lo] : offs[hi]],
                        offs[lo : hi + 1] - offs[lo],
                    )
                else:
                    data = None
                if data is None:
                    # Python fallback — encoded to utf-8 bytes
                    # explicitly so the two paths stay byte-identical
                    # on any locale/platform (text mode would use the
                    # locale codec and '\n' translation).
                    data = "".join(
                        f"({self.names[i] if self.names is not None else i},"
                        f"{float(r)!r})\n"
                        for i, r in enumerate(chunk, start=lo)
                    ).encode("utf-8")
                f.write(data)
        # Hadoop job-completion marker (saveAsTextFile writes one per
        # output dir); written LAST so its presence certifies a
        # complete, untorn dump to downstream Hadoop-convention tooling.
        with fsio.fopen(fsio.join(d, "_SUCCESS"), "w"):
            pass
        return path


class SinkGuard:
    """Bounded-retry + write-failure policy for rank sinks, shared by
    :class:`AsyncRankWriter`'s worker and the synchronous ``--sync-io``
    path (cli.py) so both modes have identical failure semantics
    (docs/ROBUSTNESS.md).

    ``on_failure='fail'`` (default) re-raises after the retry budget —
    a lost snapshot fails the run. ``'warn_and_drop'`` keeps the run
    alive: the iteration is recorded in ``dropped`` (and appended to the
    ``dead_letter_path`` JSON manifest when set), a RuntimeWarning is
    emitted, and the caller moves on — the side-channel sink never
    outranks result correctness, but what was dropped is never silent.
    """

    ON_FAILURE = ("fail", "warn_and_drop")

    def __init__(
        self,
        retry_policy: Optional[RetryPolicy] = None,
        on_failure: str = "fail",
        dead_letter_path: Optional[str] = None,
        label: str = "rank writer",
    ):
        if on_failure not in self.ON_FAILURE:
            raise ValueError(
                f"on_failure must be one of {self.ON_FAILURE}, "
                f"got {on_failure!r}"
            )
        self._policy = retry_policy
        self.on_failure = on_failure
        self.dead_letter_path = dead_letter_path
        self.label = label
        self.retries = 0
        self.dropped: List[Dict[str, object]] = []

    def __call__(self, iteration: int, fn: Callable[[], object]) -> bool:
        """Run ``fn()`` under the policy; True when it ran, False when
        it was dropped (warn_and_drop). Raises in 'fail' mode."""

        def on_retry(failures, delay, exc):
            self.retries += 1
            obs_metrics.counter(
                "sink.write_retries",
                "snapshot/dump write re-attempts under the SinkGuard "
                "policy",
            ).inc()

        try:
            if self._policy is not None:
                self._policy.call(fn, on_retry=on_retry)
            else:
                fn()
            return True
        except BaseException as e:
            # KeyboardInterrupt/SystemExit are never "write failures"
            # to drop — swallowing them is the PTL006 failure mode.
            if self.on_failure == "fail" or not isinstance(e, Exception):
                raise
            self.dropped.append(
                {"iteration": int(iteration), "error": repr(e)}
            )
            obs_metrics.counter(
                "sink.dead_letters",
                "iterations dropped under on_write_failure="
                "'warn_and_drop'",
            ).inc()
            self._flush_dead_letter()
            warnings.warn(
                f"{self.label}: dropped iteration {iteration} after "
                f"{self.retries} retr{'y' if self.retries == 1 else 'ies'}: "
                f"{e!r}",
                RuntimeWarning,
            )
            return False

    def _flush_dead_letter(self) -> None:
        if not self.dead_letter_path:
            return
        try:
            with fsio.fopen(self.dead_letter_path, "w") as f:
                json.dump({"dropped": self.dropped}, f, indent=2)
        except OSError as e:
            warnings.warn(
                f"{self.label}: could not write dead-letter manifest "
                f"{self.dead_letter_path!r}: {e!r}",
                RuntimeWarning,
            )


class AsyncRankWriter:
    """Overlap the device->host rank offload and file writes with device
    compute — C17's TPU-native build target (SURVEY.md §2: "async
    device→host offload + file write per iteration"), vs the
    reference's synchronous ``saveAsTextFile`` barrier per iteration
    (Sparky.java:237).

    The iteration loop calls ``submit(i, payload)`` with a cheap
    payload — for the JAX engine a *device-side copy* of the rank
    vector (``engine.device_ranks()``; the live buffer is donated to
    the next step, so a copy is required) — and keeps dispatching
    steps. A worker thread runs ``decode(payload)`` (the blocking
    device->host transfer releases the GIL) and feeds every sink.
    ``max_pending`` bounds in-flight copies; when the writer falls
    behind, ``submit`` blocks — snapshots are never dropped. Worker
    errors surface on the next ``submit`` or on ``close``; ``submit``
    re-checks after enqueueing so a failure that lands during a blocking
    put aborts immediately, but a sink error can still go unnoticed for
    up to one iteration (the run keeps computing until the next submit —
    acceptable for a side-channel sink, never for result correctness).
    """

    def __init__(
        self,
        decode: Callable[[object], np.ndarray],
        sinks: Iterable[Callable[[int, np.ndarray], object]],
        max_pending: int = 4,
        guard: Optional[SinkGuard] = None,
    ):
        self._decode = decode
        self._sinks = list(sinks)
        self._guard = guard if guard is not None else SinkGuard()
        self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
        self._err: Optional[BaseException] = None
        self._closed = False
        self._abandoned = False
        self._thread = threading.Thread(
            target=self._run, name="rank-writer", daemon=True
        )
        self._thread.start()

    @property
    def guard(self) -> SinkGuard:
        """The write-failure policy in effect (retry/drop counters live
        here — the CLI's robustness summary reads them)."""
        return self._guard

    def _run(self):
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                if self._err is not None:
                    continue  # drain after failure
                iteration, payload = item

                def work():
                    ranks = self._decode(payload)
                    for sink in self._sinks:
                        sink(iteration, ranks)

                self._guard(iteration, work)
            except BaseException as e:  # surfaced to the submitter
                self._err = e
            finally:
                self._q.task_done()

    def _check(self):
        if self._err is not None:
            raise RuntimeError(
                f"async rank writer failed: {self._err}"
            ) from self._err

    def submit(self, iteration: int, payload) -> None:
        if self._closed:
            raise RuntimeError("submit() after close()")
        self._check()
        tracer = obs_trace.get_tracer()
        if tracer.enabled:
            # The put blocks when the writer falls behind (max_pending
            # full) — exactly the backpressure a trace must show: it is
            # solve wall-clock spent waiting on I/O.
            with tracer.span("writer/queue_wait", iteration=iteration):
                self._q.put((iteration, payload))
        else:
            self._q.put((iteration, payload))
        # Re-check: if the worker failed while the put above blocked on a
        # full queue, fail now rather than queueing more device copies.
        self._check()

    def flush(self) -> None:
        """Block until every already-submitted write has been processed
        (written, retried, or dropped per the guard's policy), keeping
        the worker alive; raises if a write failed in 'fail' mode. The
        rollback path drains through this so load_latest_valid never
        races snapshots still sitting in the queue."""
        self._q.join()
        self._check()

    def close(self, timeout: Optional[float] = None) -> None:
        """Flush all pending writes and stop the worker; raises if ANY
        write failed — including one raised by the background thread
        after the final ``submit``, which is only observable here.
        Idempotent: every call (first or repeated, e.g. an explicit
        close inside a ``with`` block) re-raises a recorded failure, so
        no caller path can exit cleanly over a lost write.

        ``timeout`` (seconds) bounds the flush — the preemption drain's
        deadline (pagerank_tpu/jobs.py): a sink wedged PAST the
        SinkGuard's own bounded retries must not hold the drain beyond
        its deadline. On expiry the worker (a daemon thread) is
        abandoned with a RuntimeWarning and a ``sink.drain_timeouts``
        count; any failure it already recorded still re-raises. The
        guard's dead-letter semantics are untouched: a FAILING (not
        hanging) sink drains normally inside the deadline, dropping to
        ``dead_letter.json`` per policy."""
        if not self._closed:
            self._closed = True
            if timeout is None:
                self._q.put(None)
                self._thread.join()
            else:
                # Bounded close must not block on the sentinel put:
                # with the worker wedged inside a sink and the queue
                # full, an unbounded put(None) would hang before ever
                # reaching the bounded join. But a HEALTHY backlogged
                # worker frees a slot within its next write, so retry
                # the put under the same deadline — dropping the
                # sentinel outright would leave a fully-drained worker
                # parked on q.get() and burn the whole deadline in
                # join() for a false abandonment.
                deadline = time.monotonic() + timeout
                while True:
                    left = deadline - time.monotonic()
                    try:
                        self._q.put(None, timeout=max(0.01, min(0.1, left)))
                        break
                    except queue.Full:
                        if left <= 0:
                            break
                self._thread.join(max(0.0, deadline - time.monotonic()))
        elif timeout is not None and self._thread.is_alive():
            self._thread.join(timeout)  # repeat close: one more grace
        if self._thread.is_alive() and not self._abandoned:
            # Warn + count ONCE per abandonment: a repeat close (e.g.
            # the __exit__ after an explicit drain close, which passes
            # no timeout) must stay a cheap no-op, not a second count
            # — and a bounded join only ever leaves the thread alive
            # when a numeric timeout expired, so the message can name
            # it.
            self._abandoned = True
            obs_metrics.counter(
                "sink.drain_timeouts",
                "async-writer flushes abandoned at the drain deadline",
            ).inc()
            warnings.warn(
                f"async rank writer still flushing after the "
                f"{timeout:g}s drain deadline; abandoning the worker "
                f"(pending writes may be lost — the durable job "
                f"artifacts and snapshots already committed are safe)",
                RuntimeWarning,
            )
        self._check()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class WriterSyncedSnapshotter:
    """Rollback view of a :class:`Snapshotter` that drains an
    :class:`AsyncRankWriter` before every scan: without the flush, a
    mid-run rollback could scan the directory while the most recent
    healthy snapshots still sit in the writer's queue — burning
    rollback budget on a stale restore point (or finding nothing at
    all early in a run). The CLI hands THIS to ``engine.run`` whenever
    the async writer is active."""

    def __init__(self, snap: Snapshotter, writer: AsyncRankWriter):
        self._snap = snap
        self._writer = writer

    @property
    def fingerprint(self) -> str:
        return self._snap.fingerprint

    @property
    def semantics(self) -> str:
        return self._snap.semantics

    def load_latest_valid(self, max_iteration=None, match=False):
        self._writer.flush()
        return self._snap.load_latest_valid(
            max_iteration=max_iteration, match=match
        )


def resume_engine(engine, snap: Snapshotter, _found=None) -> int:
    """Restore the latest VALID snapshot into ``engine``; returns the
    iteration resumed from (0 if none found). Corrupt or truncated
    snapshots are skipped (warning) and the scan falls back to the
    newest valid one — a damaged snapshot directory costs recovery
    granularity, never the resume. Refuses a snapshot taken on a
    different graph or semantics mode (that is a configuration error,
    not corruption).

    Mesh-shape-AGNOSTIC (ISSUE 7): the payload is the canonical
    host-order vector, so a snapshot taken on an N-device mesh
    restores onto whatever mesh ``engine`` runs — ``set_ranks``
    re-shards through the target's own placement. A shape change is
    logged (and counted in ``snapshot.mesh_reshards``) for the run
    report — AFTER the fingerprint/semantics validation, so a refused
    resume never records a reshard that didn't happen — never
    refused: it is the elastic rescue's warm-start path
    (parallel/elastic.py), whose deadline-bounded scan hands the
    already-loaded result in via ``_found`` so the restore itself
    always runs on the CALLER's thread (an abandoned scan thread must
    never be able to set_ranks later)."""
    found = snap.load_latest_valid() if _found is None else _found
    if found is None:
        return 0
    _it, ranks, meta = found
    if meta["fingerprint"] != snap.fingerprint:
        raise ValueError(
            f"snapshot graph fingerprint {meta['fingerprint']} != current "
            f"{snap.fingerprint}; refusing to resume (note: crawl-input "
            "graphs hash their dangling mask into the fingerprint since "
            "r3 — older crawl-input snapshots no longer validate; see "
            "docs/PARITY.md 'Snapshot-compat note')"
        )
    if meta["semantics"] != snap.semantics:
        raise ValueError(
            f"snapshot semantics {meta['semantics']!r} != current {snap.semantics!r}"
        )
    saved_mesh = meta.get("mesh")
    engine_mesh = getattr(engine, "mesh", None)
    if saved_mesh is not None and engine_mesh is not None:
        saved_nd = saved_mesh.get("num_devices")
        now_nd = int(engine_mesh.devices.size)
        if saved_nd is not None and int(saved_nd) != now_nd:
            obs_metrics.counter(
                "snapshot.mesh_reshards",
                "resumes that re-sharded a snapshot onto a different "
                "mesh shape",
            ).inc()
            obs_log.info(
                f"resuming a {saved_nd}-device snapshot onto a "
                f"{now_nd}-device mesh (canonical-order payload; "
                f"set_ranks re-shards)"
            )
    engine.set_ranks(ranks, iteration=meta["iteration"])
    return meta["iteration"]
