"""Configuration (SURVEY.md §5: the reference has none — everything is
hardcoded: iterations `Sparky.java:187`, damping `:233`, input paths
`:44-58`, output bucket `:237`. Here all of it is a dataclass + CLI flags).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Tuple

from pagerank_tpu.utils.retry import RetryPolicy

# Semantics modes (SURVEY.md §2a): "reference" reproduces the Spark
# program's local-mode behavior bit-for-bit in exact arithmetic;
# "textbook" is the standard normalized PageRank.
SEMANTICS_REFERENCE = "reference"
SEMANTICS_TEXTBOOK = "textbook"


@dataclass
class RobustnessConfig:
    """Fault-tolerance knobs (docs/ROBUSTNESS.md). The reference
    inherited all of this from Spark (task retry, lineage recovery —
    SURVEY.md §5); here it is explicit: per-step solver health checks
    with snapshot rollback, bounded I/O retries, and the write-failure
    policy for the async snapshot/dump path."""

    #: Per-step health check in the driver loop (engine.run): any
    #: non-finite value in the step info (l1_delta, dangling_mass)
    #: triggers rollback-or-raise. Costs nothing — the scalars are
    #: already on host.
    health_checks: bool = True

    #: Opt-in rank-mass drift check: relative change of sum(ranks)
    #: allowed per step before the step is declared unhealthy. None
    #: disables (the default — reference semantics legitimately moves
    #: mass early on; see docs/ROBUSTNESS.md for calibration).
    mass_tol: Optional[float] = None

    #: Total snapshot rollbacks engine.run may perform before raising a
    #: diagnostic SolverHealthError naming the first bad iteration.
    max_rollbacks: int = 3

    #: Sink-write retry budget for snapshots/text dumps (total
    #: attempts; 1 disables) and what to do when it is exhausted:
    #: 'fail' aborts the run, 'warn_and_drop' records the iteration in
    #: the dead-letter manifest and keeps solving.
    write_attempts: int = 3
    on_write_failure: str = "fail"

    #: Elastic-rescue budget (ISSUE 7, parallel/elastic.py): mesh
    #: teardown + re-shard + warm-start recoveries the run may perform
    #: after device losses before raising ElasticExhaustedError. None
    #: (default) spends the SAME budget class as rollbacks
    #: (max_rollbacks) — one knob bounds total recovery work unless
    #: the operator splits them.
    max_rescues: Optional[int] = None

    #: Straggler-detection threshold (DeviceHealthMonitor): a step
    #: slower than this factor times the step-time EWMA — but
    #: COMPLETED — is flagged as slow-step telemetry (elastic.slow_steps
    #: counter, straggler_skew gauge). Never triggers a rescue.
    straggler_factor: float = 4.0

    #: Wall-clock budget for one SDC breach's bounded re-execution
    #: window (ISSUE 15; pagerank_tpu/sdc.py): the redo replays the
    #: iterations since the last clean check boundary from the
    #: retained device-side state; past the deadline the episode
    #: escalates (quarantine when an attribution stands, a diagnostic
    #: SdcExhaustedError otherwise).
    sdc_redo_deadline_s: float = 30.0

    #: Re-executions one SDC breach episode may spend before
    #: escalating: the first clean redo classifies TRANSIENT, a repeat
    #: breach attributing to the same device classifies STICKY
    #: (quarantine) — 2 leaves one extra attempt for a moved
    #: attribution.
    sdc_max_redos: int = 2

    def validate(self) -> "RobustnessConfig":
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {self.max_rollbacks}"
            )
        if self.max_rescues is not None and self.max_rescues < 0:
            raise ValueError(
                f"max_rescues must be >= 0 (None = max_rollbacks), got "
                f"{self.max_rescues}"
            )
        if self.straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must be > 1, got {self.straggler_factor}"
            )
        if self.write_attempts < 1:
            raise ValueError(
                f"write_attempts must be >= 1, got {self.write_attempts}"
            )
        if self.on_write_failure not in ("fail", "warn_and_drop"):
            raise ValueError(
                f"on_write_failure must be 'fail' or 'warn_and_drop', "
                f"got {self.on_write_failure!r}"
            )
        if self.mass_tol is not None and not (0.0 < self.mass_tol):
            raise ValueError(
                f"mass_tol must be positive, got {self.mass_tol}"
            )
        if self.sdc_redo_deadline_s <= 0:
            raise ValueError(
                f"sdc_redo_deadline_s must be positive, got "
                f"{self.sdc_redo_deadline_s}"
            )
        if self.sdc_max_redos < 1:
            raise ValueError(
                f"sdc_max_redos must be >= 1, got {self.sdc_max_redos}"
            )
        return self

    def write_retry_policy(self) -> Optional[RetryPolicy]:
        """RetryPolicy for sink writes, or None when retries are off."""
        if self.write_attempts <= 1:
            return None
        return RetryPolicy(max_attempts=self.write_attempts)

    def rescue_budget(self) -> int:
        """The resolved elastic-rescue budget (max_rescues, defaulting
        to the rollback budget — ONE recovery-work bound by default)."""
        return (self.max_rescues if self.max_rescues is not None
                else self.max_rollbacks)


@dataclass
class PageRankConfig:
    """All knobs for a PageRank run.

    Defaults reproduce the reference workload shape: 10 iterations
    (Sparky.java:187), damping 0.85 (:233), reference semantics
    (N-scaled ranks initialized to 1.0, :168).
    """

    num_iters: int = 10
    damping: float = 0.85
    semantics: str = SEMANTICS_REFERENCE

    # Numerics. dtype holds the rank vector; accum_dtype is used for the
    # contribution segment-sum and dangling-mass reduction (the central
    # precision/speed tradeoff on TPU — SURVEY.md §7 hard parts).
    dtype: str = "float32"
    accum_dtype: str = "float32"

    # SpMV kernel: "pallas" = hand Pallas kernel, rank vector pinned in
    # VMEM (ops/pallas_spmv.py; probes Mosaic support at build and falls
    # back to ell; refuses graphs over the VMEM budget). EXPERIMENTAL:
    # on the current jaxlib/Mosaic BOTH gather strategies fail to lower
    # on real TPU hardware (docs/PERF_NOTES.md "The Pallas kernel,
    # settled end-to-end"); the probe failure now REBUILDS the NATIVE
    # ell layout (grouped lanes + slab scan — the r2-r5 fallback ran
    # the pallas-shaped group-1 arrays at a ~9% penalty instead), logs
    # the downgrade, and records it in engine.layout_info().
    # "ell" = blocked-ELL + row segment-sum (TPU-fast,
    # ops/ell.py), "coo" = dst-sorted COO + per-edge segment-sum
    # (simple; also the portable baseline), "auto" = ell.
    kernel: str = "auto"

    # Lane-group size for the blocked-ELL layout (ops/ell.py grouped-lane
    # variant): a slot may serve any of ``lane_group`` adjacent dsts,
    # collapsing per-lane ELL padding (20-30% on power-law graphs) to
    # ~8% at 8 and ~4% at 64. Power of two, 1..128, or 0 = auto: 64 for
    # plain accumulation, 16 for the pair-packed wide path (both measured
    # fastest end-to-end on v5e at bench scale — the pair path's
    # group-redistribution one-hot runs in the wide dtype, so smaller
    # groups win there; 128's one-hot cost regresses either way).
    # Applies to the ell kernel (pallas packs at group 1).
    lane_group: int = 0

    # How a 64-bit accum_dtype runs the ELL gather when it is wider than
    # dtype's storage: "pair" = pair-packed f32 (hi, lo) split gather +
    # wide reduce (fast on TPU, ~2^-48 relative per slot;
    # ops/spmv.py:ell_contrib_pair), "native" = gather genuinely wide
    # rows (exact to ~1 ulp; ~3.4x slower on TPU where f64 is emulated),
    # "auto" = pair on TPU backends, native elsewhere.
    wide_accum: str = "auto"

    # Partition-centric SpMV restage (Lakhotia et al., arXiv:1709.07122;
    # ops/ell.py "Partition-centric sub-binning"): sub-bin slots within
    # each dst block by SOURCE partition of this span at build time (a
    # static permutation absorbed into the composite-key sort), so each
    # scan chunk's gather working set is one bounded, VMEM/cache-
    # resident window of the rank table instead of the full stripe —
    # and the partition-local index alphabet fits 3-byte slot words
    # (25% off the dominant per-slot HBM stream). Multiple of 128;
    # 0 disables (the default form). Resolved by the shared planner
    # (ops/device_build.plan_build: JaxTpuEngine.partition_span picks
    # the smallest span whose (partition, dst-block) cells stay DENSE —
    # sparse cells pay an ELL row-padding floor that swamps the stream
    # savings). Requires the ell kernel, 32-bit accumulation, and the
    # replicated (non-vertex-sharded) mode.
    partition_span: int = 0

    # Reduced-precision gather-table stream (arXiv:2009.10443: PageRank
    # tolerates a narrow streamed operand when accumulation stays
    # wide): "" keeps the table in the rank dtype; "bfloat16" streams
    # it in bf16 with the one-hot select in bf16 (exact — pure
    # selection) and all accumulation still in accum_dtype, roughly
    # halving the dominant table-side HBM traffic. Accuracy cost is
    # the bf16 quantization of z (~2^-9 relative); the bench
    # ``fast_bf16`` leg reports its oracle-L1 bound alongside.
    stream_dtype: str = ""

    # Early stop: if set, stop when L1(r' - r) <= tol. The reference has
    # no convergence check (Sparky.java:187); None reproduces that.
    tol: Optional[float] = None

    # Parallelism: number of mesh devices (None = all visible devices).
    num_devices: Optional[int] = None
    mesh_axis: str = "data"

    # Partitioned-rank execution (VERDICT r3 #1): shard the per-vertex
    # state (rank vector, masks, 1/out-degree) over the mesh instead of
    # replicating it — the analogue of the reference's hash-partitioned
    # `ranks` RDD (Sparky.java:165-170), where per-vertex state scales
    # out with the cluster. Per iteration the sharded z = r/out_degree
    # is all-gathered to feed the stripe gathers and the contribution
    # merge is a psum_scatter (reduce-scatter) instead of a psum — the
    # same total bytes over ICI as the replicated mode's all-reduce,
    # but persistent per-vertex HBM drops to 1/num_devices per chip.
    # Requires the ell kernel (pallas pins z in VMEM; coo has no
    # prescale path).
    vertex_sharded: bool = False

    # Sparse boundary exchange (ISSUE 8; Zhao & Canny, arXiv:1312.3020;
    # parallel/partition.build_halo_plan): replace the vertex-sharded
    # step's DENSE exchange (all_gather of the whole z vector + a
    # full-width reduce-scatter merge) with a build-time halo plan —
    # the top-K high in-degree HEAD is replicated with one small psum,
    # the tail boundary moves point-to-point over static ppermute
    # rounds, and the contribution merge returns only each writer's
    # band windows — so per-iteration exchanged bytes scale with the
    # BOUNDARY size instead of n. The gather inputs are bit-identical
    # to the dense path (tests/test_halo.py); only the merge regroups
    # (rounding-level). Requires vertex_sharded + the ell kernel; the
    # plain (non-vs_bounded) mode only — vs_bounded has its own
    # owner-computes exchange. Downgrades to the dense exchange (with
    # a logged note) on multi-dispatch layouts and on TPU backends
    # with a 64-bit exchanged dtype (the X64 rewriter gap class).
    halo_exchange: bool = False

    # Head-replication K for halo_exchange: -1 = auto (the relabeled
    # in-degree prefix whose replication MINIMIZES the modeled
    # exchange bytes over the exact build-time pair sets —
    # parallel/partition.auto_head_k; may honestly resolve to 0 on
    # mild graphs), 0 = none, > 0 = explicit (rounded up to a
    # multiple of 128).
    halo_head: int = -1

    # Asynchronous stale-boundary iteration (ISSUE 17; Kollias et al.,
    # arXiv:cs/0606047; streaming overlap per arXiv:2009.10443): thread
    # a two-slot boundary buffer through the halo-exchange step so
    # iteration k's local segment-sum runs concurrently with the
    # exchange of iteration k's boundary outputs — boundary reads lag
    # ONE iteration (each device's own block is always fresh), dropping
    # the per-step cost from compute + comms toward
    # max(compute, comms). PageRank provably converges under bounded
    # staleness; the probe residuals / pair-f64 oracle bound the
    # convergence cost (typically a handful of extra iterations to the
    # same tol). Requires halo_exchange; auto-gated at build: refused
    # (logged, layout_info records halo_async="off:<reason>") on
    # single-device meshes or when the comms model predicts overlap
    # gain below halo_async_min_gain
    # (parallel/comms.predict_overlap_gain).
    halo_async: bool = False

    # Staleness guard for halo_async: the MAXIMUM boundary-read lag the
    # solve may run with. 1 = the double-buffered overlap form (reads
    # lag one iteration); 0 = demand exactness — the build takes the
    # synchronous vs_halo path verbatim (zero extra buffers,
    # bit-identical results; the booby-trapped staleness-0 contract,
    # tests/test_halo_async.py). Deeper pipelines (lag > 1) are
    # rejected: nothing in the convergence instrumentation bounds them.
    stale_max_lag: int = 1

    # Predicted-overlap-gain floor for the halo_async auto-gate: the
    # fraction of the step wall the overlap must be predicted to hide
    # (exchange fraction x overlappable byte share) before the async
    # form is worth its buffer + staleness cost. Mirrors the pallas
    # probe-downgrade idiom — below the floor the build logs and runs
    # the synchronous sparse exchange.
    halo_async_min_gain: float = 0.02

    # Bounded-transient vertex sharding (VERDICT r4 #1 / ROADMAP
    # "Engine"): destination-partitioned slot rows + per-stripe z
    # broadcast. The plain vertex-sharded mode shards the PERSISTENT
    # per-vertex state but each chip still materializes O(N) step
    # transients (the all_gathered z planes and the [num_blocks, 128]
    # contribution accumulator, merged by an O(N) psum). With
    # vs_bounded, dst blocks are dealt across device ranges by
    # capacity-constrained LPT (ops/ell.deal_block_order —
    # edge-balancing the per-device row load; measured max/mean 1.01
    # vs 1.83 for round-robin), each chip owns exactly the slot rows
    # of its OWN dst range,
    # the accumulator shrinks to [num_blocks/ndev, 128], the
    # contribution merge disappears entirely, and the only per-
    # iteration communication is one [stripe_span] psum per stripe —
    # per-chip step transients are O(stripe_span + N/ndev), never O(N).
    # Numerics: block sums regroup (a block's rows are summed on one
    # chip instead of split across chips and psum-merged), so results
    # agree with the replicated/plain-sharded modes to accumulation-
    # dtype rounding, not bitwise (identical on 1 device). Dispatch
    # forms mirror the replicated mode: one fused program at or below
    # SCAN_STRIPE_UNITS, pipelined per-stripe z-broadcast + gather
    # dispatches past it. Requires vertex_sharded, the ell kernel, and
    # a host-built graph.
    vs_bounded: bool = False

    # Snapshots (the reference writes the full rank vector to S3 after
    # *every* iteration, Sparky.java:237). snapshot_every=0 disables.
    snapshot_dir: Optional[str] = None
    snapshot_every: int = 1
    resume: bool = False

    # Observability.
    log_every: int = 1
    profile_dir: Optional[str] = None

    # In-loop convergence probes (obs/probes.py; ISSUE 5): every
    # probe_every iterations the step also computes the L1 residual,
    # rank mass, and top-probe_topk churn ON DEVICE (contract PTC007:
    # no extra host syncs between probe points, no collectives beyond
    # the form's budget). 0 disables — the solve takes the exact
    # unprobed code path (zero probe calls), reproducing the
    # reference's check-free loop (Sparky.java:187). stop_tol
    # early-exits when the PROBED residual reaches it (checked at
    # probe points only — unlike `tol`, which checks every iteration);
    # None keeps exact Sparky semantics.
    probe_every: int = 0
    probe_topk: int = 64
    stop_tol: Optional[float] = None

    # Silent-data-corruption defense (ISSUE 15; pagerank_tpu/sdc.py;
    # docs/ROBUSTNESS.md "Silent data corruption"): every K-th step
    # runs the SDC-checked variant — per-device ABFT invariants
    # (replicated-copy fingerprints, dual w.r projection, link-mass
    # conservation, the mass-ledger identity) computed inside the
    # step's own dispatch (contract PTC008: the exact collective
    # multiset of the plain step), with a breach triggering the
    # bounded redo -> transient/sticky -> quarantine machine. 0
    # (default) disables: the solve takes the exact unchecked code
    # path — zero check computations, bit-identical ranks (the
    # booby-trapped contract, tests/test_sdc.py).
    sdc_check_every: int = 0

    # Seed of the Rademacher random-projection vector the SDC
    # fingerprints contract against (sdc.fingerprint_vector) —
    # schedule identity, reproducible per (seed, n_state).
    sdc_seed: int = 0

    # Fault tolerance (docs/ROBUSTNESS.md): solver health checks +
    # rollback budget + sink-write failure policy.
    robustness: RobustnessConfig = field(default_factory=RobustnessConfig)

    def validate(self) -> "PageRankConfig":
        self.robustness.validate()
        if self.semantics not in (SEMANTICS_REFERENCE, SEMANTICS_TEXTBOOK):
            raise ValueError(f"unknown semantics mode: {self.semantics!r}")
        if not (0.0 < self.damping < 1.0):
            raise ValueError(f"damping must be in (0,1), got {self.damping}")
        if self.num_iters < 0:
            raise ValueError("num_iters must be >= 0")
        if self.snapshot_every < 0:
            raise ValueError(
                f"snapshot_every must be >= 0 (0 disables), got "
                f"{self.snapshot_every}"
            )
        if self.tol is not None and not (0.0 < self.tol < float("inf")):
            raise ValueError(
                f"tol must be a finite positive float, got {self.tol}"
            )
        if self.probe_every < 0:
            raise ValueError(
                f"probe_every must be >= 0 (0 disables), got "
                f"{self.probe_every}"
            )
        if self.probe_topk < 1:
            raise ValueError(
                f"probe_topk must be >= 1, got {self.probe_topk}"
            )
        if self.sdc_check_every < 0:
            raise ValueError(
                f"sdc_check_every must be >= 0 (0 disables), got "
                f"{self.sdc_check_every}"
            )
        if self.stop_tol is not None:
            if not (0.0 < self.stop_tol < float("inf")):
                raise ValueError(
                    f"stop_tol must be a finite positive float, got "
                    f"{self.stop_tol}"
                )
            if self.probe_every == 0:
                raise ValueError(
                    "stop_tol is checked at probe points only; set "
                    "probe_every > 0 (or use tol for an every-"
                    "iteration check)"
                )
        if self.kernel not in ("auto", "ell", "coo", "pallas"):
            raise ValueError(f"unknown kernel: {self.kernel!r}")
        if self.vertex_sharded and self.kernel in ("coo", "pallas"):
            raise ValueError(
                f"vertex_sharded requires the ell kernel, got "
                f"{self.kernel!r}"
            )
        if self.vs_bounded and not self.vertex_sharded:
            raise ValueError("vs_bounded requires vertex_sharded")
        if self.halo_exchange:
            if not self.vertex_sharded:
                raise ValueError("halo_exchange requires vertex_sharded")
            if self.vs_bounded:
                raise ValueError(
                    "halo_exchange targets the plain vertex-sharded "
                    "exchange; vs_bounded has its own owner-computes "
                    "exchange"
                )
        if self.halo_async and not self.halo_exchange:
            raise ValueError(
                "halo_async overlaps the sparse boundary exchange; "
                "set halo_exchange (the dense all_gather step has no "
                "boundary buffer to double)"
            )
        if self.stale_max_lag not in (0, 1):
            raise ValueError(
                f"stale_max_lag must be 0 (exact sync) or 1 (double-"
                f"buffered overlap), got {self.stale_max_lag}"
            )
        if self.halo_async_min_gain < 0:
            raise ValueError(
                f"halo_async_min_gain must be >= 0, got "
                f"{self.halo_async_min_gain}"
            )
        if self.halo_head < -1:
            raise ValueError(
                f"halo_head must be -1 (auto), 0 (off), or positive, "
                f"got {self.halo_head}"
            )
        if self.wide_accum not in ("auto", "pair", "native"):
            raise ValueError(f"unknown wide_accum mode: {self.wide_accum!r}")
        if self.stream_dtype not in ("", "bfloat16"):
            raise ValueError(
                f"stream_dtype must be '' or 'bfloat16', got "
                f"{self.stream_dtype!r}"
            )
        if self.stream_dtype and not self.partition_span:
            raise ValueError(
                "stream_dtype is consumed by the partition-centric "
                "layout only; set partition_span (the default layout "
                "would silently ignore the narrowed stream)"
            )
        if self.partition_span:
            if self.partition_span < 0 or self.partition_span % 128:
                raise ValueError(
                    f"partition_span must be a positive multiple of 128 "
                    f"(0 disables), got {self.partition_span}"
                )
            if self.kernel not in ("auto", "ell", "pallas"):
                raise ValueError(
                    f"partition_span requires the ell or pallas kernel, "
                    f"got {self.kernel!r}"
                )
            if self.vertex_sharded:
                raise ValueError(
                    "partition_span is a replicated-mode layout; it does "
                    "not compose with vertex_sharded"
                )
        if self.stream_dtype or self.partition_span:
            import numpy as _np

            if _np.dtype(self.accum_dtype).itemsize > 4:
                raise ValueError(
                    "partition_span/stream_dtype support 32-bit "
                    "accumulation only (the pair/native wide paths keep "
                    "the default layout)"
                )
        g = self.lane_group
        if g != 0 and (not (1 <= g <= 128) or (g & (g - 1))):
            raise ValueError(
                f"lane_group must be 0 (auto) or a power of two in "
                f"[1, 128], got {g}"
            )
        import numpy as _np

        if _np.dtype(self.accum_dtype).itemsize < _np.dtype(self.dtype).itemsize:
            raise ValueError(
                f"accum_dtype {self.accum_dtype} narrower than dtype "
                f"{self.dtype}"
            )
        return self

    def replace(self, **kw) -> "PageRankConfig":
        return dataclasses.replace(self, **kw)

    def effective_lane_group(self, pair: bool, striped: bool = False,
                             widened: bool = False) -> int:
        """Resolve ``lane_group`` (0 = auto) for the chosen accumulation
        mode and layout — v5e-measured optima (docs/PERF_NOTES.md
        "Occupancy-aware stripes" and "Accumulation dtypes"):

        - plain (non-pair): 64 everywhere;
        - pair: 16 — the group one-hot runs in the wide dtype, so
          small groups win. r3 re-measurement: this now holds for
          STRIPED pair layouts too (scale 23: group 16 2.16e8 vs 64
          2.03e8; scale 25: 2.00e8 vs 1.84e8), inverting the r2
          scale-23 result (2.5x the other way) that had flipped the
          striped default to 64 — the per-stripe chunk autotune and
          exact-shape multi-dispatch introduced since are the changed
          variables;
        - pair on an occupancy-WIDENED span (``widened``;
          engines/jax_engine.occupancy_span): 8 — at the ~one-row-per-
          cell occupancy these spans target, row count is group-
          insensitive and only the one-hot narrows (measured 128
          1.47e8, 64 1.98e8, 32 2.12e8, 16 2.20e8, 8 2.22e8, 4
          2.20e8); group 8 is within noise of 16 on the other pair
          layouts (scale 25: 2.006 vs 1.997; scale 22 single-stripe:
          292.8 vs 294.3 ms/iter), so the split keeps each regime at
          its measured best."""
        if self.lane_group:
            return self.lane_group
        if pair and striped and widened:
            return 8
        return 16 if pair else 64
