"""Structured per-iteration metrics (SURVEY.md §5: the reference's entire
observability is one println per iteration, Sparky.java:188).

Logs iter, L1 delta, dangling mass, wall-clock, iters/sec and
edges/sec/chip — the BASELINE.json metrics — to stderr and optionally a
JSONL file.
"""

from __future__ import annotations

import json
import math
import sys
import time
from typing import Dict, Optional, TextIO

from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.utils import fsio


def oracle_l1(r, r_ref):
    """(raw L1, raw normalized L1, mass-normalized L1) between a rank
    vector and an oracle's — the accuracy numbers bench.py and
    scripts/acceptance.py report. The raw and mass-normalized numbers
    can diverge only through a GLOBAL-SCALE error — exactly how the
    (since fixed) reduced-precision f64-vdot dangling-mass reduction
    was caught (docs/PERF_NOTES.md "Reference-mode mass growth and the
    f64-vdot lowering bug") — so reporting both keeps that error class
    visible; the mass-normalized number carries the relative structure
    PageRank defines."""
    import numpy as np

    r = np.asarray(r, dtype=np.float64)
    r_ref = np.asarray(r_ref, dtype=np.float64)
    l1 = float(np.abs(r - r_ref).sum())
    norm = l1 / float(np.abs(r_ref).sum())
    mass = float(np.abs(r / r.sum() - r_ref / r_ref.sum()).sum())
    return l1, norm, mass


class MetricsLogger:
    """Per-iteration logger; use as the engine's ``on_iteration`` hook."""

    def __init__(
        self,
        num_edges: int,
        num_chips: int = 1,
        log_every: int = 1,
        jsonl_path: Optional[str] = None,
        stream: Optional[TextIO] = None,
    ):
        self.num_edges = num_edges
        self.num_chips = max(1, num_chips)
        self.log_every = log_every
        self.stream = stream if stream is not None else sys.stderr
        self._jsonl = fsio.fopen(jsonl_path, "a") if jsonl_path else None
        self._t_last = time.perf_counter()
        self.history = []

    def __call__(self, iteration: int, info: Dict[str, float]) -> None:
        now = time.perf_counter()
        dt = now - self._t_last
        self._t_last = now
        self.record(iteration, info, dt)

    def record(self, iteration: int, info: Dict[str, float],
               dt: float, timing: Optional[str] = None) -> None:
        """Log one iteration with explicit wall-clock ``dt`` — for fused
        runs where per-iteration timing is an average of one device
        dispatch (JaxTpuEngine.run_fused) rather than measured per call.
        Pass ``timing="averaged"`` there so JSONL consumers can tell the
        synthetic per-record seconds from genuinely measured ones."""
        # A zero/negative dt (clock granularity on a trivial graph)
        # yields null rates, NOT float("inf"): json.dumps writes inf as
        # a bare ``Infinity`` token, which is not JSON — strict JSONL
        # consumers (json.loads with parse_constant raising) choke on
        # the whole line (tests/test_obs.py::test_metrics_jsonl_is_strict_json).
        rec = {
            "iter": iteration,
            "seconds": dt,
            "iters_per_sec": (1.0 / dt) if dt > 0 else None,
            "edges_per_sec_per_chip": self.num_edges / dt / self.num_chips
            if dt > 0
            else None,
        }
        if timing is not None:
            rec["timing"] = timing
        # rank_mass / topk_churn appear on probe iterations only
        # (obs/probes.py) — the per-iteration history is where the run
        # report's convergence telemetry lives.
        for k in ("l1_delta", "dangling_mass", "rank_mass"):
            if k in info:
                # Non-finite step info (a diverging solve under
                # --no-health-checks) is encoded as null too — NaN is
                # no more a JSON token than Infinity is.
                v = float(info[k])
                rec[k] = v if math.isfinite(v) else None
        if "topk_churn" in info:
            rec["topk_churn"] = int(info["topk_churn"])
        self.history.append(rec)
        # Mirror the headline scalars into registry gauges + the
        # step-seconds histogram — the live exporter's (obs/live.py)
        # per-iteration feed; plain in-GIL arithmetic, no I/O.
        obs_live.update_solve_gauges(iteration, rec, dt)
        if self._jsonl:
            # allow_nan=False: any non-finite float reaching the dump
            # is a bug in the sanitizing above — fail loudly rather
            # than emitting a non-spec line.
            self._jsonl.write(json.dumps(rec, allow_nan=False) + "\n")
            self._jsonl.flush()
        if self.log_every and iteration % self.log_every == 0:
            parts = [f"iter {iteration}", f"{dt * 1e3:.1f} ms"]
            if rec.get("l1_delta") is not None:
                parts.append(f"l1_delta {rec['l1_delta']:.3e}")
            if rec.get("dangling_mass") is not None:
                parts.append(f"mass {rec['dangling_mass']:.6g}")
            eps = rec["edges_per_sec_per_chip"]
            if eps is not None:
                parts.append(f"{eps:.3g} edges/s/chip")
            print("  ".join(parts), file=self.stream)

    def summary(
        self,
        iters: Optional[int] = None,
        total_seconds: Optional[float] = None,
    ) -> Dict[str, float]:
        """Aggregate stats. By default both the iteration count and the
        wall-clock are inferred from the per-call history; fused tol
        runs (one record for a dynamic trip count) pass the true
        ``iters`` and ``total_seconds`` explicitly instead.

        Consistent across paths (VERDICT r2 weak-6): ``iters`` is the
        count of EXECUTED iterations in both forms, and ``timed_iters``
        is how many fed the means — the stepwise form excludes the
        compile iteration 0 from timing whenever more than one record
        exists (so there ``timed_iters == iters - 1``), while fused
        forms time every executed iteration. Consumers comparing modes
        should divide by ``timed_iters``."""
        if iters is not None:
            if iters <= 0 or not total_seconds:
                return {}
            return {
                "iters": iters,
                "timed_iters": iters,
                "mean_iter_seconds": total_seconds / iters,
                "iters_per_sec": iters / total_seconds,
                "edges_per_sec_per_chip":
                    self.num_edges * iters / total_seconds / self.num_chips,
            }
        if not self.history:
            return {}
        # Skip iteration 0 (compile) when there are enough samples.
        hist = self.history[1:] if len(self.history) > 1 else self.history
        total = sum(h["seconds"] for h in hist)
        n = len(hist)
        return {
            "iters": len(self.history),
            "timed_iters": n,
            "mean_iter_seconds": total / n,
            # Same discipline as record(): a degenerate zero wall-clock
            # reports null rates, never Infinity (the summary is embedded
            # verbatim in run_report.json, which is strict JSON).
            "iters_per_sec": n / total if total > 0 else None,
            "edges_per_sec_per_chip": self.num_edges * n / total / self.num_chips
            if total > 0
            else None,
        }

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None
