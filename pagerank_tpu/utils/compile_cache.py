"""Persistent XLA compile cache (shared by bench.py and the CLI).

The graph-build + engine-setup chain issues ~50 small jitted programs,
each ~0.6s to compile through the remote-compile service on a tunneled
TPU but far below the 1s default persistence threshold; caching them
cuts a warm scale-21 device build from ~49s to ~10s (measured v5e).
Off by default for library users (a global config flip is the caller's
call); bench.py always enables it, and the CLI enables it for every
jax-engine run (opt out with --no-compile-cache).
"""

from __future__ import annotations

import os
import sys


def default_cache_dir() -> str:
    """``.jax_cache`` at the checkout root when the package parent is
    writable (a dev/repo checkout — shared with bench.py so CLI and
    bench reuse each other's executables), else a per-user cache dir (a
    site-packages install may be read-only, and a failed cache write
    means the speedup silently never materializes)."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.access(repo, os.W_OK):
        return os.path.join(repo, ".jax_cache")
    return os.path.join(
        os.path.expanduser("~"), ".cache", "pagerank_tpu", "jax"
    )


def tuning_get(key: str):
    """Look up a persisted build-time tuning decision (e.g. the ELL
    chunk autotune winner) from ``tuning.json`` next to the compile
    cache. Returns None on any miss/error — tuning persistence is an
    optimization, never a requirement."""
    import json

    d = _active_cache_dir()
    if d is None:
        return None
    try:
        with open(os.path.join(d, "tuning.json")) as f:
            return json.load(f).get(key)
    except Exception:
        return None


def tuning_put(key: str, value) -> None:
    """Persist a tuning decision (atomic replace; best-effort)."""
    import json
    import tempfile

    d = _active_cache_dir()
    if d is None:
        return
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "tuning.json")
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:
            data = {}
        data[key] = value
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tuning")
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except Exception:
        pass


def _active_cache_dir():
    """The persistence root, or None when cross-run persistence is OFF
    (no enable_compile_cache call / --no-compile-cache): tuning state
    must not outlive the run when the user opted out of the compile
    cache — the two are one persistence switch."""
    import jax

    return jax.config.jax_compilation_cache_dir or None


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: :func:`default_cache_dir`) with a 0s persistence
    threshold. Failures are non-fatal — the cache is an optimization,
    never a requirement.

    No-op on the CPU backend: the cache exists to amortize the ~0.6s
    remote-compile round trips of tunneled TPU runs; CPU compiles of
    these programs are milliseconds, and on jax 0.4.x the CPU backend
    SEGFAULTS deserializing warm cache entries (reproduced: a second
    `bench.py --host-build` run of the same geometry crashes at
    executable load; first/cold runs are fine)."""
    import jax

    if jax.default_backend() == "cpu":
        return
    if cache_dir is None:
        cache_dir = default_cache_dir()
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:
        print(f"pagerank_tpu: compilation cache unavailable ({e})",
              file=sys.stderr)
