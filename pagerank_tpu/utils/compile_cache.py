"""Persistent XLA compile cache (shared by bench.py and the CLI).

The graph-build + engine-setup chain issues ~50 small jitted programs,
each ~0.6s to compile through the remote-compile service on a tunneled
TPU but far below the 1s default persistence threshold; caching them
cuts a warm scale-21 device build from ~49s to ~10s (measured v5e).
Off by default for library users (a global config flip is the caller's
call); bench.py always enables it, and the CLI enables it for every
jax-engine run (opt out with --no-compile-cache).

Besides the cross-process persistent cache this module owns two
smaller, same-keyed caches:

  - ``tuning_get``/``tuning_put`` — persisted build-time tuning
    decisions (the ELL chunk autotune winner);
  - ``stage_call`` — an IN-PROCESS AOT executable cache for the
    device graph-build stages (ops/device_build.py). Its key is
    (stage name, device kind, arg avals, statics) — deliberately NOT
    the process-global ``jax_enable_x64`` flag: the build stages are
    pinned to 32-bit indices (analysis contract PTC006), so their
    programs are x64-invariant and the pair-f64 config's mid-process
    x64 flip must not re-trace or re-compile them. Under plain
    ``jax.jit`` that flip invalidates every build executable (the jit
    cache keys on the config context), which is exactly what made the
    bench couple's second build pay a full compile pass again.
"""

from __future__ import annotations

import os
import time


def default_cache_dir() -> str:
    """``.jax_cache`` at the checkout root when the package parent is
    writable (a dev/repo checkout — shared with bench.py so CLI and
    bench reuse each other's executables), else a per-user cache dir (a
    site-packages install may be read-only, and a failed cache write
    means the speedup silently never materializes)."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.access(repo, os.W_OK):
        return os.path.join(repo, ".jax_cache")
    return os.path.join(
        os.path.expanduser("~"), ".cache", "pagerank_tpu", "jax"
    )


def tuning_get(key: str):
    """Look up a persisted build-time tuning decision (e.g. the ELL
    chunk autotune winner) from ``tuning.json`` next to the compile
    cache. Returns None on any miss/error — tuning persistence is an
    optimization, never a requirement."""
    import json

    d = _active_cache_dir()
    if d is None:
        return None
    try:
        with open(os.path.join(d, "tuning.json")) as f:
            return json.load(f).get(key)
    except Exception:
        return None


def tuning_put(key: str, value) -> None:
    """Persist a tuning decision (atomic replace; best-effort)."""
    import json
    import tempfile

    d = _active_cache_dir()
    if d is None:
        return
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "tuning.json")
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:
            data = {}
        data[key] = value
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tuning")
        with os.fdopen(fd, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except Exception:
        pass


# -- build-stage executable cache ------------------------------------------

_STAGE_EXECS: dict = {}


def clear_stage_cache() -> None:
    """Drop the in-process stage executables (tests; a device reset)."""
    _STAGE_EXECS.clear()


def usable_donations(fn, args, donate_argnums):
    """The subset of ``donate_argnums`` whose (shape, dtype) matches a
    DISTINCT output leaf of ``fn(*args)`` — mirroring jax's own
    donation matching (mlir._set_up_aliases pairs donated inputs to
    outputs by stripped aval, greedily), via one abstract eval. A
    donation with no matching output can never alias and only produces
    the "Some donated buffers were not usable" lowering warning — the
    r1-r5 bench/multichip tails' warning class (analysis contract
    PTC003). Returns the filtered tuple; on any eval failure returns
    ``donate_argnums`` unchanged (the check must never break a build).
    """
    if not donate_argnums:
        return ()
    import jax
    import numpy as _np

    try:
        outs = jax.tree_util.tree_leaves(jax.eval_shape(fn, *args))
    except Exception:
        return tuple(donate_argnums)
    pool: dict = {}
    for o in outs:
        k = (tuple(o.shape), _np.dtype(o.dtype))
        pool[k] = pool.get(k, 0) + 1
    kept = []
    for i in donate_argnums:
        k = (tuple(args[i].shape), _np.dtype(args[i].dtype))
        if pool.get(k, 0) > 0:
            pool[k] -= 1
            kept.append(i)
    return tuple(kept)


_DONATION_WARNING = "donated buffers were not usable"


def stage_call(name: str, fn, args, *, static_key=(), donate_argnums=(),
               timings=None):
    """Run one build-stage program through the AOT executable cache.

    ``fn`` must be a pure function of ``args`` (statics baked in via
    functools.partial and mirrored in ``static_key``). On the first
    call for a given (name, device kind, avals, static_key) the stage
    is lowered and compiled once (hitting the persistent compile cache
    when enabled — warm TPU builds skip the remote compile); later
    calls dispatch the cached executable directly, with no re-trace
    even across a ``jax_enable_x64`` flip (see module docstring — the
    stages are 32-bit-pinned, so the flag cannot change their program).

    Donations are pre-filtered to the CONSUMABLE subset
    (:func:`usable_donations`) and, as a belt-and-braces for jax
    versions whose matching is stricter than the aval check (sharding/
    layout-sensitive matchers), any residual "donated buffers were not
    usable" warning at lowering triggers ONE re-lower without
    donations — peak memory is identical either way (an unusable
    donation never aliased), the dropped donation is obs-logged, and
    no stage can leak that warning into a bench/multichip tail again
    (the r5 residual; analysis contract PTC003 covers the structural
    half).

    ``timings``: optional dict; compile seconds are accumulated under
    ``"compile_s"`` so build breakdowns separate compile from execute.
    """
    import warnings as _warnings

    import jax

    dev = jax.devices()[0]
    aval_key = tuple(
        (tuple(a.shape), str(a.dtype)) for a in args
    )
    from pagerank_tpu.obs import log as obs_log
    from pagerank_tpu.obs import metrics as obs_metrics
    from pagerank_tpu.obs import trace as obs_trace

    key = (name, dev.platform, getattr(dev, "device_kind", ""),
           tuple(static_key), tuple(donate_argnums), aval_key)
    exe = _STAGE_EXECS.get(key)
    if exe is None:
        obs_metrics.counter(
            "compile_cache.stage_misses",
            "build-stage programs lowered+compiled this process",
        ).inc()
        donate = usable_donations(fn, args, tuple(donate_argnums))
        if donate != tuple(donate_argnums):
            dropped = sorted(set(donate_argnums) - set(donate))
            obs_log.info(
                f"build stage '{name}': dropped unconsumable "
                f"donation(s) at arg(s) {dropped} (no matching output "
                "aval; aliasing was impossible)"
            )
        t0 = time.perf_counter()
        with obs_trace.span("build/compile", stage=name):
            with _warnings.catch_warnings(record=True) as wlog:
                _warnings.simplefilter("always")
                exe = jax.jit(fn, donate_argnums=donate).lower(
                    *args
                ).compile()
            for w in wlog:  # pass every OTHER warning through
                if _DONATION_WARNING not in str(w.message):
                    _warnings.warn_explicit(
                        w.message, w.category, w.filename, w.lineno
                    )
            if donate and any(
                _DONATION_WARNING in str(w.message) for w in wlog
            ):
                # This jax's matcher rejected an aval-compatible
                # donation (layout/sharding-level). Re-lower clean so
                # the warning never reaches users and the executable
                # carries no dead donation.
                obs_log.info(
                    f"build stage '{name}': donation rejected at "
                    "lowering; re-lowered without donations"
                )
                with _warnings.catch_warnings():
                    _warnings.simplefilter("ignore")
                    exe = jax.jit(fn).lower(*args).compile()
        _STAGE_EXECS[key] = exe
        # Every build-stage compile feeds the cost ledger (obs/costs):
        # FLOPs / HBM bytes / peak allocation per stage, the "what a
        # build SHOULD cost" model the run report and `obs report`
        # diffs carry. Harvest never raises (degrades to None fields).
        from pagerank_tpu.obs import costs as obs_costs
        from pagerank_tpu.obs import hlo as obs_hlo

        obs_costs.harvest("build/" + name, exe)
        # Compiler-plane harvest (ISSUE 11; obs/hlo.py): same compiled
        # handle, zero extra compiles — and a bare flag read when the
        # inspector is disarmed (the booby-trap contract).
        obs_hlo.maybe_inspect("build/" + name, exe)
        if timings is not None:
            timings["compile_s"] = (
                timings.get("compile_s", 0.0) + time.perf_counter() - t0
            )
    else:
        obs_metrics.counter(
            "compile_cache.stage_hits",
            "build-stage dispatches served by the AOT executable cache",
        ).inc()
    return exe(*args)


def _active_cache_dir():
    """The persistence root, or None when cross-run persistence is OFF
    (no enable_compile_cache call / --no-compile-cache): tuning state
    must not outlive the run when the user opted out of the compile
    cache — the two are one persistence switch."""
    import jax

    return jax.config.jax_compilation_cache_dir or None


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: :func:`default_cache_dir`) with a 0s persistence
    threshold. Failures are non-fatal — the cache is an optimization,
    never a requirement.

    No-op on the CPU backend: the cache exists to amortize the ~0.6s
    remote-compile round trips of tunneled TPU runs; CPU compiles of
    these programs are milliseconds, and on jax 0.4.x the CPU backend
    SEGFAULTS deserializing warm cache entries (reproduced: a second
    `bench.py --host-build` run of the same geometry crashes at
    executable load; first/cold runs are fine)."""
    import jax

    if jax.default_backend() == "cpu":
        return
    if cache_dir is None:
        cache_dir = default_cache_dir()
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:
        from pagerank_tpu.obs import log as obs_log

        obs_log.warn(f"compilation cache unavailable ({e})")
