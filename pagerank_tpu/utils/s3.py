"""Minimal S3-protocol :class:`~pagerank_tpu.utils.fsio.FileSystem`.

The reference's literal inputs are 301 ``s3n://`` Common Crawl URIs and
its output an S3 bucket (``/root/reference/Sparky.java:44-58,237``),
resolved by Hadoop's S3 client. This module is the build's concrete
object-store backend for that seam: a dependency-free (stdlib
``http.client``) REST client speaking the S3 wire protocol —
GET/PUT/HEAD/DELETE objects, ListObjectsV2 with prefix/delimiter
pagination, server-side COPY — against a configurable endpoint, with
optional AWS Signature V4 request signing when credentials are present
(anonymous requests otherwise, for stubs and open buckets).

Endpoint/credentials resolve from the environment
(``PAGERANK_TPU_S3_ENDPOINT``, ``AWS_ACCESS_KEY_ID``,
``AWS_SECRET_ACCESS_KEY``, ``AWS_REGION``); when the endpoint variable
is set, ``s3://``/``s3n://``/``s3a://`` paths auto-register through
:func:`pagerank_tpu.utils.fsio.get_fs` — every loader and sink (edge
lists, SequenceFile segments, snapshots, text dumps, metrics JSONL)
then reads and writes S3 URIs with no further wiring. In this
zero-egress environment the protocol is exercised against an in-process
HTTP stub server (tests/s3stub.py + tests/test_s3.py); the signer is
additionally pinned to the published AWS SigV4 test vector.

Addressing is path-style (``endpoint/bucket/key``) — what MinIO/stub
servers and most private object stores speak.
"""

from __future__ import annotations

import binascii
import datetime
import hashlib
import hmac
import http.client
import io
import os
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, Iterator, List, Optional, Tuple
from xml.sax.saxutils import escape as ET_escape

from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.utils import fsio
from pagerank_tpu.utils.retry import RetryPolicy, RetryStats

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()

#: HTTP statuses that retry (AWS transient classes): 500 InternalError,
#: 502, 503 SlowDown/ServiceUnavailable, 504. Everything else is
#: semantic (404 NoSuchKey, 403, 400 InvalidPart, ...) and must surface
#: immediately — retrying a permission error only hides it.
RETRYABLE_STATUSES = (500, 502, 503, 504)


class _TransientStatus(Exception):
    """Internal: a response whose status is in RETRYABLE_STATUSES,
    raised inside the retry loop so the policy re-attempts it; when the
    budget runs out the LAST response is returned to the caller, whose
    normal error path (_raise) then reports it."""

    def __init__(self, result):
        super().__init__(f"transient HTTP {result[0]}")
        self.result = result


def _s3_retryable(exc: BaseException) -> bool:
    """The S3 retry matrix's exception half (docs/ROBUSTNESS.md):
    transient statuses, connection reset / refused / broken pipe
    (ConnectionError), timeouts, truncated or malformed responses
    (http.client.HTTPException covers IncompleteRead, BadStatusLine,
    RemoteDisconnected), and socket-level OSErrors. Inside one HTTP
    transaction no semantic OSError (FileNotFoundError etc.) can arise
    — those are raised AFTER the response, outside the retry scope."""
    return isinstance(
        exc, (_TransientStatus, http.client.HTTPException, OSError)
    )


def sign_v4(
    method: str,
    host: str,
    path: str,
    query: str,
    headers: Dict[str, str],
    payload_hash: str,
    *,
    region: str,
    access_key: str,
    secret_key: str,
    amzdate: str,
    service: str = "s3",
) -> str:
    """AWS Signature Version 4 ``Authorization`` header value.

    Pure function of its inputs (``amzdate`` = ``YYYYMMDDTHHMMSSZ``) so
    it can be pinned against AWS's published test vector
    (tests/test_s3.py::test_sigv4_aws_reference_vector). ``headers``
    must already include ``host`` and ``x-amz-date``.
    """
    datestamp = amzdate[:8]
    # Canonical request: URI-encoded path (segments only), sorted
    # canonical query, sorted lowercase headers.
    canon_path = urllib.parse.quote(path, safe="/") or "/"
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    canon_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(pairs)
    )
    items = sorted((k.lower(), " ".join(v.split())) for k, v in headers.items())
    canon_headers = "".join(f"{k}:{v}\n" for k, v in items)
    signed = ";".join(k for k, _ in items)
    canonical = "\n".join(
        [method, canon_path, canon_query, canon_headers, signed, payload_hash]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join(
        ["AWS4-HMAC-SHA256", amzdate, scope,
         hashlib.sha256(canonical.encode()).hexdigest()]
    )

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    return (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}"
    )


def _local(tag: str) -> str:
    """XML tag name with any namespace prefix stripped."""
    return tag.rsplit("}", 1)[-1]


def _find_text(root: Optional[ET.Element], tag: str) -> Optional[str]:
    """Text of the first element named ``tag`` (namespace-agnostic)."""
    for el in root.iter() if root is not None else ():
        if _local(el.tag) == tag:
            return el.text
    return None


def _header(headers: Dict[str, str], name: str) -> Optional[str]:
    """Case-insensitive response-header lookup."""
    return {k.lower(): v for k, v in headers.items()}.get(name)


def _split_uri(path: str) -> Tuple[str, str]:
    """``s3://bucket/key`` -> (bucket, key). Key may be empty."""
    scheme = fsio.scheme_of(path)
    if scheme is None:
        raise ValueError(f"not an object-store URI: {path!r}")
    rest = path[len(scheme) + 3:]
    bucket, _, key = rest.partition("/")
    if not bucket:
        raise ValueError(f"S3 URI has no bucket: {path!r}")
    return bucket, key


class _RangedReader(io.RawIOBase):
    """Seekable read-only stream over one S3 object via Range requests.

    Wrapped in an ``io.BufferedReader`` so sequential consumers fetch
    ~1 MB chunks and whole-file ``read()`` collapses to a single
    ranged GET (``readall``); seek+small-read consumers (zipfile /
    np.load on snapshot .npz) fetch only the regions they touch.

    ``head`` seeds the reader with the object's first bytes (the
    open() probe request already fetched them). If the server ever
    answers a range request with the FULL object (200: Range ignored),
    the body is cached and all further reads are served locally — never
    re-fetch a whole object per read call."""

    def __init__(self, fs: "S3FileSystem", path: str, size: int,
                 head: bytes = b""):
        self._fs = fs
        self._path = path
        self._size = size
        self._pos = 0
        self._head = head
        self._full: Optional[bytes] = None

    def readable(self):
        return True

    def seekable(self):
        return True

    def tell(self):
        return self._pos

    def seek(self, offset, whence=io.SEEK_SET):
        if whence == io.SEEK_SET:
            pos = offset
        elif whence == io.SEEK_CUR:
            pos = self._pos + offset
        elif whence == io.SEEK_END:
            pos = self._size + offset
        else:
            raise ValueError(f"bad whence {whence}")
        if pos < 0:
            raise OSError("negative seek position")
        self._pos = pos
        return self._pos

    def _fetch(self, lo: int, hi: int) -> bytes:
        """Bytes [lo, hi] from cache/head when possible, else one
        ranged GET (head-overlapping reads fetch only the tail)."""
        if self._full is not None:
            return self._full[lo:hi + 1]
        nh = len(self._head)
        if hi < nh:
            return self._head[lo:hi + 1]
        prefix = self._head[lo:] if lo < nh else b""
        data, entire = self._fs._get_range(self._path, max(lo, nh), hi)
        if entire:  # server ignored Range: cache, serve locally forever
            self._full = data
            return data[lo:hi + 1]
        return prefix + data if prefix else data

    def readinto(self, b):
        if self._pos >= self._size or not len(b):
            return 0
        n = min(len(b), self._size - self._pos)
        data = self._fetch(self._pos, self._pos + n - 1)
        b[: len(data)] = data
        self._pos += len(data)
        return len(data)

    def readall(self):
        if self._pos >= self._size:
            return b""
        data = self._fetch(self._pos, self._size - 1)
        self._pos += len(data)
        return data


#: Sentinel for "use the default retry policy" — distinct from an
#: explicit ``retry_policy=None``, which DISABLES retries.
_DEFAULT_RETRY = object()


class S3FileSystem(fsio.FileSystem):
    """S3 REST client bound to one endpoint.

    Thread-compatible: every request opens its own connection (the
    async snapshot writer commits from a worker thread). Objects are
    written with single-PUT semantics via the shared buffered writer
    (:class:`fsio._MemWriter` commits through :meth:`_commit` at
    CLOSE; ``COMMIT_ON_FLUSH`` is off because re-uploading the whole
    accumulated object per ``flush()`` — e.g. the per-record JSONL
    metrics flush — would be O(records^2) network bytes against a real
    store). Readers never observe partial objects, matching the
    reference's S3 output contract (Sparky.java:237); incremental
    sinks pointed at ``s3://`` get durability at close, not per
    record.
    """

    COMMIT_ON_FLUSH = False

    def __init__(
        self,
        endpoint: str,
        region: str = "us-east-1",
        access_key: Optional[str] = None,
        secret_key: Optional[str] = None,
        timeout: float = 30.0,
        retry_policy=_DEFAULT_RETRY,
    ):
        u = urllib.parse.urlsplit(endpoint)
        if u.scheme not in ("http", "https") or not u.netloc:
            raise ValueError(
                f"S3 endpoint must be http(s)://host[:port], got {endpoint!r}"
            )
        self._secure = u.scheme == "https"
        self._netloc = u.netloc
        self._region = region
        self._access_key = access_key
        self._secret_key = secret_key
        self._timeout = timeout
        #: Transient-failure policy for idempotent requests (GET / PUT /
        #: HEAD / DELETE / initiate are all safe to repeat; multipart
        #: COMPLETE is not — see _multipart). Default: 5 jittered
        #: attempts; pass ``retry_policy=None`` to disable retries.
        self.retry: Optional[RetryPolicy] = (
            RetryPolicy(retryable=_s3_retryable)
            if retry_policy is _DEFAULT_RETRY else retry_policy
        )
        #: Counters the CLI surfaces in its robustness summary.
        self.retry_stats = RetryStats()

    # -- wire protocol ----------------------------------------------------

    def _request(
        self,
        method: str,
        bucket: str,
        key: str,
        query: str = "",
        body: bytes = b"",
        extra_headers: Optional[Dict[str, str]] = None,
        idempotent: bool = True,
    ) -> Tuple[int, Dict[str, str], bytes]:
        """One S3 request, retried under ``self.retry`` when
        ``idempotent`` (each attempt re-signs with a fresh x-amz-date).
        A transient status that survives the whole budget is RETURNED
        (not raised) so callers' normal error paths report it; network
        exceptions that survive the budget propagate."""
        if not idempotent or self.retry is None:
            return self._transact(method, bucket, key, query, body,
                                  extra_headers)

        def once():
            result = self._transact(method, bucket, key, query, body,
                                    extra_headers)
            if result[0] in RETRYABLE_STATUSES:
                raise _TransientStatus(result)
            return result

        def on_retry(failures, delay, exc):
            # Per-instance RetryStats stays the CLI's summary source;
            # the central registry gets the same count so one snapshot
            # covers every S3FileSystem in the process (obs/metrics).
            obs_metrics.counter(
                "s3.request.retries",
                "transparent S3 request re-attempts (transient "
                "status / network error)",
            ).inc()

        try:
            return self.retry.call(once, stats=self.retry_stats,
                                   on_retry=on_retry)
        except _TransientStatus as e:
            return e.result

    def _transact(
        self,
        method: str,
        bucket: str,
        key: str,
        query: str = "",
        body: bytes = b"",
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, Dict[str, str], bytes]:
        path = "/" + bucket + (("/" + key) if key else "")
        payload_hash = hashlib.sha256(body).hexdigest() if body else _EMPTY_SHA256
        headers = {
            "host": self._netloc,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": datetime.datetime.now(datetime.timezone.utc).strftime(
                "%Y%m%dT%H%M%SZ"
            ),
        }
        if extra_headers:
            headers.update(extra_headers)
        if self._access_key and self._secret_key:
            headers["authorization"] = sign_v4(
                method, self._netloc, path, query, headers, payload_hash,
                region=self._region, access_key=self._access_key,
                secret_key=self._secret_key, amzdate=headers["x-amz-date"],
            )
        conn_cls = (
            http.client.HTTPSConnection if self._secure
            else http.client.HTTPConnection
        )
        conn = conn_cls(self._netloc, timeout=self._timeout)
        try:
            url = urllib.parse.quote(path, safe="/") + (f"?{query}" if query else "")
            conn.request(method, url, body=body or None, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()

    def _raise(self, status: int, data: bytes, path: str):
        if status == 404:
            raise FileNotFoundError(path)
        raise OSError(
            f"S3 request failed with HTTP {status} for {path!r}: "
            f"{data[:200].decode(errors='replace')}"
        )

    # -- FileSystem interface ---------------------------------------------

    #: Objects larger than this commit via multipart upload (S3 caps a
    #: single PUT at 5 GB; well before that, one multi-GB request has no
    #: retry granularity). 64 MB parts keep a Twitter-2010-class rank
    #: snapshot (41.7M f64 = 334 MB) at ~6 parts.
    MULTIPART_PART_SIZE = 64 * 1024 * 1024

    def _commit(self, path: str, data: bytes) -> None:
        """PUT the full object (the buffered writer's commit hook);
        objects over :attr:`MULTIPART_PART_SIZE` go through the S3
        multipart protocol (initiate / per-part PUT / complete, abort on
        any failure so no orphan upload accrues storage)."""
        bucket, key = _split_uri(path)
        if len(data) > self.MULTIPART_PART_SIZE:
            self._commit_multipart(bucket, key, data, path)
            return
        status, _, body = self._request("PUT", bucket, key, body=data)
        if status not in (200, 201, 204):
            self._raise(status, body, path)

    @staticmethod
    def _xml_root(body: bytes) -> Optional[ET.Element]:
        """Parse an S3 XML response body, tolerating the keep-alive
        whitespace real S3 streams ahead of the document. None when the
        body holds no parseable XML (callers route that to _raise)."""
        text = body.strip()
        if not text:
            return None
        try:
            return ET.fromstring(text)
        except ET.ParseError:
            return None

    def _commit_multipart(
        self, bucket: str, key: str, data: bytes, path: str
    ) -> None:
        def put_part(num: int, uid: str) -> str:
            off = (num - 1) * self.MULTIPART_PART_SIZE
            status, headers, body = self._request(
                "PUT", bucket, key,
                query=f"partNumber={num}&uploadId={uid}",
                body=data[off:off + self.MULTIPART_PART_SIZE],
            )
            if status != 200:
                self._raise(status, body, path)
            etag = _header(headers, "etag")
            if not etag:
                raise OSError(f"S3 part {num} of {path!r} returned no ETag")
            return etag

        nparts = -(-len(data) // self.MULTIPART_PART_SIZE)
        self._multipart(bucket, key, path, nparts, put_part,
                        expected_size=len(data))

    def _multipart(self, bucket, key, path, nparts, put_part,
                   expected_size=None) -> None:
        """The multipart skeleton: initiate, ``put_part(num, uid) ->
        etag`` per part, complete — abort on any failure so no orphan
        upload accrues storage. Initiate and part PUTs are idempotent
        and ride the standard retry; COMPLETE is not (see
        _complete_multipart)."""
        status, _, body = self._request("POST", bucket, key, query="uploads")
        if status != 200:
            self._raise(status, body, path)
        upload_id = _find_text(self._xml_root(body), "UploadId")
        if not upload_id:
            raise OSError(f"S3 initiate-multipart returned no UploadId for {path!r}")
        uid = urllib.parse.quote(upload_id, safe="-_.~")
        try:
            etags = [put_part(num, uid) for num in range(1, nparts + 1)]
            self._complete_multipart(bucket, key, path, uid, etags,
                                     expected_size=expected_size)
        except BaseException:
            # Best-effort abort: leave no billable orphan parts behind
            # (AbortMultipartUpload is a no-op once a complete landed,
            # so the committed-but-response-lost path is never undone).
            try:
                self._request("DELETE", bucket, key, query=f"uploadId={uid}")
            except Exception:
                pass
            raise

    def _list_parts(
        self, bucket: str, key: str, uid: str, path: str
    ) -> Optional[Dict[int, str]]:
        """ListParts for an in-flight upload: ``{part_number: etag}``,
        or None when the upload no longer exists (NoSuchUpload — the
        complete may have landed server-side)."""
        status, _, body = self._request(
            "GET", bucket, key, query=f"uploadId={uid}"
        )
        if status == 404:
            return None
        if status != 200:
            self._raise(status, body, path)
        parts: Dict[int, str] = {}
        root = self._xml_root(body)
        for el in root.iter() if root is not None else ():
            if _local(el.tag) != "Part":
                continue
            num = etag = None
            for sub in el:
                if _local(sub.tag) == "PartNumber":
                    num = int(sub.text or 0)
                elif _local(sub.tag) == "ETag":
                    etag = sub.text
            if num is not None and etag is not None:
                parts[num] = etag
        return parts

    @staticmethod
    def _multipart_etag(etags: List[str]) -> Optional[str]:
        """The ETag S3 assigns a multipart object: md5 over the
        concatenated BINARY part MD5s, suffixed ``-nparts``. None when
        any part ETag is not a plain hex md5 (e.g. SSE-KMS stores) —
        verification then falls back to size."""
        bins = []
        for t in etags:
            t = (t or "").strip().strip('"')
            if len(t) != 32:
                return None
            try:
                bins.append(binascii.unhexlify(t))
            except (binascii.Error, ValueError):
                return None
        digest = hashlib.md5(b"".join(bins)).hexdigest()
        return f'"{digest}-{len(bins)}"'

    def _object_matches_upload(
        self, bucket: str, key: str, etags: List[str],
        expected_size: Optional[int],
    ) -> bool:
        """Did the lost/failed COMPLETE actually commit OUR upload?
        Mere key existence proves nothing — a previous version of the
        same key (the snapshot overwrite pattern) would pass. Verify
        the object's ETag against the multipart ETag computed from the
        part ETags we just uploaded; when either side is unverifiable,
        fall back to an exact size match; with neither, refuse."""
        status, headers, _ = self._request("HEAD", bucket, key)
        if status != 200:
            return False
        etag = _header(headers, "etag")
        want = self._multipart_etag(etags)
        if etag and want:
            return etag.strip() == want
        if expected_size is not None:
            cl = _header(headers, "content-length")
            return cl is not None and cl.isdigit() and int(cl) == expected_size
        return False

    def _complete_multipart(
        self, bucket: str, key: str, path: str, uid: str, etags: List[str],
        expected_size: Optional[int] = None,
    ) -> None:
        """CompleteMultipartUpload with NON-BLIND recovery. Complete is
        not idempotent (the first attempt may commit server-side while
        its response is lost), so a transient failure is never simply
        re-POSTed: re-LIST the parts first — upload gone + object
        present means the commit already landed (success); parts intact
        and matching means a re-complete is safe; anything else is a
        real error. Attempts/backoff share ``self.retry``'s budget."""
        complete_xml = (
            "<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>{ET_escape(t)}</ETag></Part>"
                for n, t in enumerate(etags, start=1)
            ) + "</CompleteMultipartUpload>"
        ).encode()
        expected = {n: t for n, t in enumerate(etags, start=1)}
        attempts = self.retry.max_attempts if self.retry is not None else 1
        failures = 0
        while True:
            transient: Optional[BaseException] = None
            status, body = 0, b""
            try:
                status, _, body = self._request(
                    "POST", bucket, key, query=f"uploadId={uid}",
                    body=complete_xml, idempotent=False,
                )
            except BaseException as e:
                if not _s3_retryable(e):
                    raise
                transient = e
            if transient is None:
                # Complete may return 200 and stream an <Error> document
                # after keep-alive whitespace; only a
                # CompleteMultipartUploadResult root is success.
                root = self._xml_root(body) if status == 200 else None
                if root is not None and _local(root.tag) == "CompleteMultipartUploadResult":
                    return
                if status not in RETRYABLE_STATUSES:
                    self._raise(status, body, path)  # semantic: surface now
            failures += 1
            # Recovery probe (idempotent, internally retried): did the
            # lost/failed complete actually commit?
            listed = self._list_parts(bucket, key, uid, path)
            if listed is None:
                if self._object_matches_upload(bucket, key, etags,
                                               expected_size):
                    return  # committed server-side; response was lost
                raise OSError(
                    f"S3 multipart upload for {path!r} disappeared without "
                    f"a verifiable commit — the key's current object does "
                    f"not match the uploaded parts (complete failed with "
                    f"{transient or ('HTTP %d' % status)})"
                )
            if listed != expected:
                raise OSError(
                    f"S3 multipart parts for {path!r} no longer match what "
                    f"was uploaded ({len(listed)}/{len(expected)} parts "
                    f"listed); refusing to re-complete"
                )
            if failures >= attempts:
                if transient is not None:
                    raise transient
                self._raise(status, body, path)
            if self.retry is not None:
                delay = self.retry.backoff(failures)
                self.retry_stats.retries += 1
                self.retry_stats.slept += delay
                obs_metrics.counter("s3.request.retries").inc()
                self.retry.sleep(delay)

    def _get(self, path: str) -> bytes:
        bucket, key = _split_uri(path)
        status, _, data = self._request("GET", bucket, key)
        if status != 200:
            self._raise(status, data, path)
        return data

    def _get_range(self, path: str, lo: int, hi: int) -> Tuple[bytes, bool]:
        """GET bytes [lo, hi] (inclusive) -> (data, entire): ``entire``
        flags a server that ignored Range and sent the whole object
        (200) — callers must then treat ``data`` as the full body."""
        bucket, key = _split_uri(path)
        status, _, data = self._request(
            "GET", bucket, key,
            extra_headers={"range": f"bytes={lo}-{hi}"},
        )
        if status == 206:
            return data, False
        if status == 200:
            return data, True
        self._raise(status, data, path)

    #: Objects at or below this arrive whole in the open() probe GET;
    #: larger ones read through a seekable ranged reader (zip-backed
    #: formats — npz snapshots — then fetch only the members they touch
    #: instead of the whole object).
    STREAM_THRESHOLD = 8 * 1024 * 1024

    def open(self, path, mode="r", **kwargs):
        binary = "b" in mode
        kind = mode.replace("b", "").replace("t", "") or "r"
        if kind == "r":
            # ONE probe GET for the first STREAM_THRESHOLD bytes: small
            # objects arrive complete (no HEAD round-trip — this is the
            # hot path for multi-file segment ingest over s3://), large
            # ones seed the ranged reader with their head + total size
            # from Content-Range.
            bucket, key = _split_uri(path)
            status, headers, data = self._request(
                "GET", bucket, key,
                extra_headers={"range": f"bytes=0-{self.STREAM_THRESHOLD - 1}"},
            )
            if status == 200:  # Range ignored: whole object in hand
                raw: io.IOBase = io.BytesIO(data)
            elif status == 416:
                # Real S3 answers 416 InvalidRange when start >= size —
                # i.e. a zero-byte object (the '_SUCCESS' markers this
                # codebase writes). Plain GET resolves it (or surfaces
                # the real error).
                raw = io.BytesIO(self._get(path))
            elif status == 206:
                total = None
                crange = _header(headers, "content-range")
                if crange and "/" in crange:
                    tail = crange.rsplit("/", 1)[1]
                    if tail.isdigit():
                        total = int(tail)
                if total is None:
                    # A 206 without a parseable Content-Range could hide
                    # bytes past the probe — refuse rather than silently
                    # truncate a big object to its first 8 MB.
                    raise OSError(
                        f"S3 endpoint returned 206 without a usable "
                        f"Content-Range for {path!r}; cannot size the object"
                    )
                if total <= len(data):
                    raw = io.BytesIO(data)
                else:
                    raw = io.BufferedReader(
                        _RangedReader(self, path, total, head=data), 1 << 20
                    )
            else:
                self._raise(status, data, path)
        elif kind in ("w", "x", "a"):
            if kind == "x" and self.isfile(path):
                raise FileExistsError(path)
            initial = b""
            if kind == "a":
                try:
                    initial = self._get(path)
                except FileNotFoundError:
                    pass
            raw = fsio._MemWriter(self, path, initial)
            if kind == "a":
                raw.seek(0, io.SEEK_END)
        else:
            raise ValueError(f"unsupported mode {mode!r}")
        if binary:
            return raw
        kwargs.pop("newline", None)
        kwargs.setdefault("encoding", "utf-8")
        return fsio._MemTextWrapper(raw, **kwargs)

    def isfile(self, path):
        bucket, key = _split_uri(path)
        if not key:
            return False
        status, _, _ = self._request("HEAD", bucket, key)
        return status == 200

    def _list(
        self, bucket: str, prefix: str, delimiter: str = "",
        max_keys: int = 1000,
    ) -> Iterator[Tuple[str, bool]]:
        """Yield (name, is_prefix) from ListObjectsV2, following
        continuation tokens (the client-side half of S3 pagination)."""
        token = None
        while True:
            q = [("list-type", "2"), ("prefix", prefix),
                 ("max-keys", str(max_keys))]
            if delimiter:
                q.append(("delimiter", delimiter))
            if token:
                q.append(("continuation-token", token))
            query = urllib.parse.urlencode(sorted(q))
            status, _, data = self._request("GET", bucket, "", query=query)
            if status != 200:
                self._raise(status, data, f"s3://{bucket}/{prefix}")
            root = ET.fromstring(data)
            token = None
            truncated = False
            for el in root:
                name = _local(el.tag)
                if name == "Contents":
                    for sub in el:
                        if _local(sub.tag) == "Key":
                            yield sub.text or "", False
                elif name == "CommonPrefixes":
                    for sub in el:
                        if _local(sub.tag) == "Prefix":
                            yield sub.text or "", True
                elif name == "NextContinuationToken":
                    token = el.text
                elif name == "IsTruncated":
                    truncated = (el.text or "").strip().lower() == "true"
            if not truncated or not token:
                return

    def isdir(self, path):
        bucket, key = _split_uri(path.rstrip("/") + "/")
        if key == "/":  # bucket root
            key = ""
        for _ in self._list(bucket, key, max_keys=1):
            return True
        return False

    def exists(self, path):
        return self.isfile(path) or self.isdir(path)

    def listdir(self, path):
        bucket, key = _split_uri(path.rstrip("/") + "/")
        if key == "/":
            key = ""
        names = set()
        found = False
        for name, is_prefix in self._list(bucket, key, delimiter="/"):
            found = True
            tail = name[len(key):]
            if is_prefix:
                tail = tail.rstrip("/")
            if tail:
                names.add(tail)
        if not found:
            raise FileNotFoundError(path)
        return sorted(names)

    def makedirs(self, path, exist_ok=True):
        # Object stores have no directories; prefixes exist implicitly
        # once a key is written (mirrors Hadoop-on-S3 behavior).
        return None

    def replace(self, src, dst):
        sb, sk = _split_uri(src)
        db_, dk = _split_uri(dst)
        copy_source = "/" + sb + "/" + urllib.parse.quote(sk)
        status, headers, data = self._request("HEAD", sb, sk)
        if status != 200:
            self._raise(status, data, src)
        size = int(_header(headers, "content-length") or 0)
        if size > self.MULTIPART_PART_SIZE:
            # Real S3 caps single CopyObject at 5 GB; past the part
            # threshold, copy server-side in ranges (UploadPartCopy) —
            # the snapshot tmp+rename path hits this for large objects.
            def copy_part(num: int, uid: str) -> str:
                lo = (num - 1) * self.MULTIPART_PART_SIZE
                hi = min(lo + self.MULTIPART_PART_SIZE, size) - 1
                status, _, body = self._request(
                    "PUT", db_, dk,
                    query=f"partNumber={num}&uploadId={uid}",
                    extra_headers={
                        "x-amz-copy-source": copy_source,
                        "x-amz-copy-source-range": f"bytes={lo}-{hi}",
                    },
                )
                etag = _find_text(
                    self._xml_root(body) if status == 200 else None, "ETag"
                )
                if not etag:  # UploadPartCopy returns the ETag in XML
                    self._raise(status, body, src)
                return etag

            nparts = -(-size // self.MULTIPART_PART_SIZE)
            self._multipart(db_, dk, dst, nparts, copy_part,
                            expected_size=size)
        else:
            status, _, data = self._request(
                "PUT", db_, dk,
                extra_headers={"x-amz-copy-source": copy_source},
            )
            # CopyObject has the same 200-with-streamed-<Error> failure
            # mode as CompleteMultipartUpload.
            root = self._xml_root(data) if status == 200 else None
            if root is None or _local(root.tag) != "CopyObjectResult":
                self._raise(status, data, src)
        status, _, data = self._request("DELETE", sb, sk)
        if status not in (200, 204):
            self._raise(status, data, src)


S3_SCHEMES = ("s3", "s3n", "s3a")
ENDPOINT_ENV = "PAGERANK_TPU_S3_ENDPOINT"


def from_env() -> Optional[S3FileSystem]:
    """Build an :class:`S3FileSystem` from the environment, or None when
    no endpoint is configured. ``PAGERANK_TPU_S3_RETRIES`` (total
    attempts; 1 disables) overrides the default retry budget."""
    endpoint = os.environ.get(ENDPOINT_ENV)
    if not endpoint:
        return None
    policy = _DEFAULT_RETRY
    attempts = os.environ.get("PAGERANK_TPU_S3_RETRIES")
    if attempts:
        n = max(1, int(attempts))
        # 1 total attempt = retries off (None; _DEFAULT_RETRY means
        # "use the default policy", an explicit None disables)
        policy = (
            RetryPolicy(max_attempts=n, retryable=_s3_retryable)
            if n > 1 else None
        )
    return S3FileSystem(
        endpoint,
        region=os.environ.get("AWS_REGION", "us-east-1"),
        access_key=os.environ.get("AWS_ACCESS_KEY_ID"),
        secret_key=os.environ.get("AWS_SECRET_ACCESS_KEY"),
        retry_policy=policy,
    )


def register_s3(
    fs: Optional[S3FileSystem] = None, only_missing: bool = False
) -> Optional[S3FileSystem]:
    """Register ``fs`` (default: :func:`from_env`) for all S3 schemes —
    the reference's inputs are spelled ``s3n://`` (Sparky.java:44-58),
    modern Hadoop uses ``s3a://``, plain ``s3://`` is the native form.
    ``only_missing`` skips schemes that already have a registration (the
    lazy get_fs hook must not silently replace an explicitly registered
    store with the env endpoint)."""
    fs = fs or from_env()
    if fs is not None:
        for scheme in S3_SCHEMES:
            if only_missing and fsio.registered(scheme):
                continue
            fsio.register(scheme, fs)
    return fs
