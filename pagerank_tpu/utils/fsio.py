"""Pluggable URI-scheme file I/O (L1/L4 edges of the system).

The reference's literal inputs are 301 ``s3n://`` URIs and its output an
S3 bucket (``/root/reference/Sparky.java:44-58,237``) — the Hadoop
filesystem layer resolves the scheme and streams bytes. This module is
that seam for the TPU build: every loader (edge lists, .npz, crawl TSV,
SequenceFiles) and every sink (Snapshotter, TextDumper, rank TSV, JSONL
metrics) opens paths through here, so an object-store backend plugs in
by registering a :class:`FileSystem` for its scheme — no loader changes.

Scheme-less paths use the local OS filesystem unchanged. Two non-local
backends exist: :class:`MemoryFileSystem` (an object-store-semantics
in-memory store) under ``mock://`` in tests/test_fsio.py, and the real
S3-protocol client (utils/s3.py — stdlib HTTP + SigV4 signing,
auto-registered for ``s3://``/``s3n://``/``s3a://`` when
``PAGERANK_TPU_S3_ENDPOINT`` is set; exercised against an in-process
stub server in tests/test_s3.py, since this environment has zero
egress). Both round-trip ingest -> snapshot -> resume through the CLI.
"""

from __future__ import annotations

import contextlib
import io
import os
import re
import threading
from typing import Dict, List, Optional, Tuple

# Two+ characters: a single letter before :// is Windows drive syntax,
# not a URI scheme.
_SCHEME_RE = re.compile(r"^([A-Za-z][A-Za-z0-9+.-]+)://")


def scheme_of(path: str) -> Optional[str]:
    """URI scheme of ``path``, or None for a plain local path. Single-
    letter "schemes" are never URIs (Windows drive syntax), and this
    codebase treats anything without ``://`` as local."""
    m = _SCHEME_RE.match(path)
    return m.group(1).lower() if m else None


def registered(scheme: Optional[str]) -> bool:
    """Whether a filesystem is registered for ``scheme`` (None — local —
    is always available)."""
    return scheme is None or scheme.lower() in _REGISTRY


class FileSystem:
    """Minimal filesystem interface the loaders/sinks need. Implementors
    receive FULL paths (scheme included) — an object store keys by URI.

    ``replace`` must be atomic within the store (the Snapshotter's
    torn-file guarantee rides on it; a backend without native rename can
    implement copy+delete only if readers never see partial objects,
    which object stores guarantee per-object).

    ``COMMIT_ON_FLUSH``: whether buffered writers publish their bytes
    on every ``flush()`` (crash durability for incremental sinks) or
    only at close (real object stores, where a per-flush re-PUT of the
    whole object is O(records^2) network bytes — see _MemWriter)."""

    COMMIT_ON_FLUSH = True

    def open(self, path: str, mode: str = "r", **kwargs):
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def isdir(self, path: str) -> bool:
        raise NotImplementedError

    def isfile(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        raise NotImplementedError

    def replace(self, src: str, dst: str) -> None:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    def open(self, path, mode="r", **kwargs):
        return open(path, mode, **kwargs)

    def exists(self, path):
        return os.path.exists(path)

    def isdir(self, path):
        return os.path.isdir(path)

    def isfile(self, path):
        return os.path.isfile(path)

    def listdir(self, path):
        return os.listdir(path)

    def makedirs(self, path, exist_ok=True):
        os.makedirs(path, exist_ok=exist_ok)

    def replace(self, src, dst):
        os.replace(src, dst)


class _MemWriter(io.BytesIO):
    """Write buffer that commits to the store atomically on clean close —
    object-store PUT semantics (readers never see a partial object).
    Exiting a ``with`` block on an exception ABORTS the put (a real
    store abandons the upload), so a writer that dies mid-serialization
    never publishes a torn object.

    An explicit ``flush()`` ALSO commits the bytes so far when the
    owning filesystem opts in (``COMMIT_ON_FLUSH``, default True):
    incremental sinks (the JSONL metrics logger) flush after every
    record precisely so a killed run keeps its records, and for
    in-memory stores that crash behavior must match the local backend.
    A REAL object store sets it False — re-PUTting the whole object
    per record is O(records^2) network bytes, so there durability
    arrives at close (utils/s3.py). Writers that need torn-object
    protection get it by never flushing mid-serialization (none in
    this codebase do) — the atomic rename in the Snapshotter guards
    the rest."""

    def __init__(self, fs: "MemoryFileSystem", path: str, initial: bytes = b""):
        super().__init__()
        self.write(initial)
        self._fs = fs
        self._path = path
        self._aborted = False

    def abort(self):
        self._aborted = True

    def flush(self):
        super().flush()
        if (not self.closed and not self._aborted
                and getattr(self._fs, "COMMIT_ON_FLUSH", True)):
            self._fs._commit(self._path, self.getvalue())

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.abort()
        return super().__exit__(exc_type, exc, tb)

    def close(self):
        if not self.closed and not self._aborted:
            self._fs._commit(self._path, self.getvalue())
        super().close()


class _MemTextWrapper(io.TextIOWrapper):
    """Text wrapper that propagates with-block exceptions to the
    underlying writer's abort-on-error semantics."""

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None and isinstance(self.buffer, _MemWriter):
            self.buffer.abort()
        return super().__exit__(exc_type, exc, tb)


class MemoryFileSystem(FileSystem):
    """In-memory object store: flat ``{uri: bytes}`` plus implicit
    directories (any key prefix), mirroring S3-style stores closely
    enough to exercise every loader/sink contract. Thread-safe — the
    async snapshot writer commits from a worker thread."""

    def __init__(self):
        self._lock = threading.RLock()
        self.files: Dict[str, bytes] = {}
        self.dirs = set()

    def _commit(self, path: str, data: bytes) -> None:
        with self._lock:
            self.files[path] = data

    def open(self, path, mode="r", **kwargs):
        binary = "b" in mode
        kind = mode.replace("b", "").replace("t", "") or "r"
        with self._lock:
            if kind == "r":
                if path not in self.files:
                    raise FileNotFoundError(path)
                raw: io.IOBase = io.BytesIO(self.files[path])
            elif kind in ("w", "x"):
                if kind == "x" and path in self.files:
                    raise FileExistsError(path)
                raw = _MemWriter(self, path)
            elif kind == "a":
                raw = _MemWriter(self, path, self.files.get(path, b""))
                raw.seek(0, io.SEEK_END)
            else:
                raise ValueError(f"unsupported mode {mode!r}")
        if binary:
            return raw
        kwargs.pop("newline", None)
        kwargs.setdefault("encoding", "utf-8")
        return _MemTextWrapper(raw, **kwargs)

    def exists(self, path):
        return self.isfile(path) or self.isdir(path)

    def isfile(self, path):
        with self._lock:
            return path in self.files

    def isdir(self, path):
        prefix = path.rstrip("/") + "/"
        with self._lock:
            return path.rstrip("/") in self.dirs or any(
                k.startswith(prefix) for k in self.files
            )

    def listdir(self, path):
        prefix = path.rstrip("/") + "/"
        names = set()
        with self._lock:
            if not (path.rstrip("/") in self.dirs
                    or any(k.startswith(prefix) for k in self.files)):
                raise FileNotFoundError(path)
            for k in list(self.files) + list(self.dirs):
                if k.startswith(prefix):
                    names.add(k[len(prefix):].split("/", 1)[0])
        return sorted(n for n in names if n)

    def makedirs(self, path, exist_ok=True):
        key = path.rstrip("/")
        with self._lock:
            if not exist_ok and key in self.dirs:
                raise FileExistsError(path)
            self.dirs.add(key)

    def replace(self, src, dst):
        with self._lock:
            if src not in self.files:
                raise FileNotFoundError(src)
            self.files[dst] = self.files.pop(src)


_LOCAL = LocalFileSystem()
_REGISTRY: Dict[str, FileSystem] = {}


def register(scheme: str, fs: FileSystem) -> None:
    """Make ``scheme://...`` paths resolve through ``fs`` everywhere
    (loaders, snapshots, text dumps, CLI outputs)."""
    _REGISTRY[scheme.lower()] = fs


def unregister(scheme: str) -> None:
    _REGISTRY.pop(scheme.lower(), None)


def get_fs(path: str) -> FileSystem:
    scheme = scheme_of(path)
    if scheme is None:
        return _LOCAL
    fs = _REGISTRY.get(scheme)
    if fs is None and scheme in ("s3", "s3n", "s3a"):
        # Lazy S3 auto-registration from the environment (utils/s3):
        # with PAGERANK_TPU_S3_ENDPOINT set, s3:// paths work with no
        # wiring — the reference's inputs are s3n:// URIs
        # (Sparky.java:44-58). Fills only MISSING schemes, never
        # replacing an explicit registration.
        from pagerank_tpu.utils import s3 as s3_mod

        s3_mod.register_s3(only_missing=True)
        fs = _REGISTRY.get(scheme)
    if fs is None:
        hint = (
            "set PAGERANK_TPU_S3_ENDPOINT (and AWS_* credentials "
            "if the store needs them) or "
            if scheme in ("s3", "s3n", "s3a") else ""
        )
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(path {path!r}); {hint}register one with "
            f"pagerank_tpu.utils.fsio.register({scheme!r}, fs) "
            f"(registered: {sorted(_REGISTRY) or 'none'})"
        )
    return fs


# -- module-level conveniences (the loader/sink call surface) -------------


def fopen(path: str, mode: str = "r", **kwargs):
    return get_fs(path).open(path, mode, **kwargs)


def exists(path: str) -> bool:
    return get_fs(path).exists(path)


def isdir(path: str) -> bool:
    return get_fs(path).isdir(path)


def isfile(path: str) -> bool:
    return get_fs(path).isfile(path)


def listdir(path: str) -> List[str]:
    return get_fs(path).listdir(path)


def makedirs(path: str, exist_ok: bool = True) -> None:
    get_fs(path).makedirs(path, exist_ok=exist_ok)


def replace(src: str, dst: str) -> None:
    """Atomic rename within ONE store. A cross-scheme pair would silently
    rename inside src's store (creating a key spelled with the other
    scheme), so it is rejected up front — callers that really mean
    copy-across-stores must stream bytes explicitly."""
    if scheme_of(src) != scheme_of(dst):
        raise ValueError(
            f"fsio.replace is same-store only: {src!r} -> {dst!r} "
            f"cross schemes ({scheme_of(src)!r} vs {scheme_of(dst)!r})"
        )
    get_fs(src).replace(src, dst)


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "wb", suffix: str = ".tmp", **kwargs):
    """Write-then-rename: bytes land at ``path`` only when the writer
    body completes — THE one torn-file guard for every sink that must
    never publish a parseable-looking partial file (Snapshotter.save and
    TextDumper.dump both ride this path; docs/ROBUSTNESS.md). A kill or
    exception mid-write leaves at worst a ``path + suffix`` temp the
    consumers' name patterns never match (object-store backends abort
    the upload outright — nothing is published at all)."""
    if any(c in mode for c in "ra+"):
        raise ValueError(f"atomic_write is write-only, got mode {mode!r}")
    tmp = path + suffix
    with fopen(tmp, mode, **kwargs) as f:
        yield f
    replace(tmp, path)


def join(base: str, *parts: str) -> str:
    """Path join that preserves URI schemes. Scheme paths are joined with
    literal '/' — os.path.join would insert the OS separator on Windows
    and silently discard the scheme/base for a part starting with '/'.
    Local paths keep os.path.join semantics."""
    scheme = scheme_of(base)
    if scheme is None:
        return os.path.join(base, *parts)
    # Never strip into the '//' of the scheme authority: a bare root
    # like 'mock://' must stay a URI ('mock://a', not 'mock:/a' which
    # would silently resolve to the LOCAL filesystem).
    root = len(scheme) + 3
    out = base
    for part in parts:
        head = out[:root] + out[root:].rstrip("/")
        out = head + ("" if head.endswith("/") else "/") + part.lstrip("/")
    return out
