"""Version compatibility shims for the JAX APIs this package leans on.

The solver targets the current JAX API surface (`jax.shard_map` with its
``check_vma`` flag, `pltpu.CompilerParams`), but the supported floor is
jax 0.4.x, where `shard_map` still lives in `jax.experimental.shard_map`
(flag spelled ``check_rep``) and the Pallas TPU params class is
`TPUCompilerParams`. Every engine/op module imports through here so the
version probe happens ONCE and the call sites keep the modern spelling.
"""

from __future__ import annotations

import functools

try:  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

# The kwarg rename (check_rep -> check_vma) and the top-level export
# landed in DIFFERENT jax releases, so pick the spelling from the
# signature, not the import location.
import inspect as _inspect

_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else "check_rep"
)


@functools.wraps(_shard_map)
def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """`jax.shard_map` with the modern keyword surface on every
    supported jax (``check_vma`` maps onto 0.4.x's ``check_rep``)."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


def compiled_cost_analysis(compiled):
    """XLA's per-program cost model from an AOT ``Compiled`` object, as
    one flat ``{metric: float}`` dict — or None when this backend /
    jax version does not report one (PJRT plugins may raise
    ``NotImplementedError``; some return empty). The jax API has
    shifted shape across releases (a list of per-computation dicts on
    0.4.x, a bare dict later), so THIS is the one place that
    normalizes it (obs/costs.py consumes it)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # unimplemented on this backend: degrade to None
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    out = {}
    for k, v in ca.items():
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out or None


def compiled_memory_analysis(compiled):
    """XLA's compiled-memory breakdown as a plain ``{field: int}`` dict
    (argument/output/temp/alias/generated-code bytes, plus
    ``peak_bytes`` — the explicit attr when the backend reports one,
    else the argument+output+temp sum, the standard upper proxy for a
    program's device allocation). None when unavailable — same
    degrade-to-None contract as :func:`compiled_cost_analysis`."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    fields = {
        "argument_bytes": "argument_size_in_bytes",
        "output_bytes": "output_size_in_bytes",
        "temp_bytes": "temp_size_in_bytes",
        "alias_bytes": "alias_size_in_bytes",
        "generated_code_bytes": "generated_code_size_in_bytes",
    }
    out = {}
    for name, attr in fields.items():
        v = getattr(ma, attr, None)
        if v is not None:
            try:
                out[name] = int(v)
            except (TypeError, ValueError):
                continue
    peak = getattr(ma, "peak_memory_in_bytes", None)
    if peak is None:
        parts = [out.get(k) for k in
                 ("argument_bytes", "output_bytes", "temp_bytes")]
        peak = sum(p for p in parts if p is not None) if any(
            p is not None for p in parts) else None
    if peak is not None:
        out["peak_bytes"] = int(peak)
    return out or None


def compiled_hlo_text(compiled):
    """The OPTIMIZED (post-pass, scheduled) HLO text of an AOT
    ``Compiled`` object, or None when this backend / jax version does
    not expose one — ``as_text()`` first (the modern surface), then
    ``hlo_modules()[0].to_string()`` (older jaxlibs / bare PJRT
    handles). Same degrade-to-None contract as the cost/memory shims:
    the compiler-plane inspector (obs/hlo.py) must never fail a run
    on a backend that keeps its HLO to itself."""
    try:
        text = compiled.as_text()
    except Exception:
        text = None  # fall through to the legacy surface
    if isinstance(text, str) and text.strip():
        return text
    try:
        mods = compiled.hlo_modules()
        text = mods[0].to_string() if mods else None
    except Exception:
        return None
    if isinstance(text, str) and text.strip():
        return text
    return None


def pallas_tpu_compiler_params(**kw):
    """`pltpu.CompilerParams` (jax >= 0.6) / `pltpu.TPUCompilerParams`
    (jax 0.4.x) — renamed class, and the older one lacks some fields
    (e.g. ``has_side_effects``). Unknown fields are DROPPED: they are
    hints (DCE/effect annotations), never correctness-bearing for the
    kernels here — the SpMV kernel's output is consumed, so it cannot
    be dead-code-eliminated regardless."""
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    known = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kw.items() if k in known})
