"""Version compatibility shims for the JAX APIs this package leans on.

The solver targets the current JAX API surface (`jax.shard_map` with its
``check_vma`` flag, `pltpu.CompilerParams`), but the supported floor is
jax 0.4.x, where `shard_map` still lives in `jax.experimental.shard_map`
(flag spelled ``check_rep``) and the Pallas TPU params class is
`TPUCompilerParams`. Every engine/op module imports through here so the
version probe happens ONCE and the call sites keep the modern spelling.
"""

from __future__ import annotations

import functools

try:  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

# The kwarg rename (check_rep -> check_vma) and the top-level export
# landed in DIFFERENT jax releases, so pick the spelling from the
# signature, not the import location.
import inspect as _inspect

_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else "check_rep"
)


@functools.wraps(_shard_map)
def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """`jax.shard_map` with the modern keyword surface on every
    supported jax (``check_vma`` maps onto 0.4.x's ``check_rep``)."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


def pallas_tpu_compiler_params(**kw):
    """`pltpu.CompilerParams` (jax >= 0.6) / `pltpu.TPUCompilerParams`
    (jax 0.4.x) — renamed class, and the older one lacks some fields
    (e.g. ``has_side_effects``). Unknown fields are DROPPED: they are
    hints (DCE/effect annotations), never correctness-bearing for the
    kernels here — the SpMV kernel's output is consumed, so it cannot
    be dead-code-eliminated regardless."""
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    known = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kw.items() if k in known})
