"""Synthetic graph generators.

The reference's workload is the Common Crawl web graph (Sparky.java:44-58)
and the BASELINE configs are SNAP web graphs — none downloadable in this
zero-egress environment. R-MAT (Graph500 parameters) reproduces their
defining property, heavy power-law degree tails, which is exactly what
stresses edge-balanced partitioning (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import numpy as np


def rmat_edges(
    scale: int,
    edge_factor: int = 16,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    dtype=np.int32,
):
    """Generate ``edge_factor * 2**scale`` R-MAT edges over ``2**scale``
    vertices (Graph500 defaults a=0.57, b=0.19, c=0.19, d=0.05).

    Vectorized: one pass per scale level over all edges at once.
    Returns (src, dst); duplicates and self-loops are left in (the graph
    builder dedups, matching reference semantics).
    """
    n_edges = edge_factor << scale
    rng = np.random.default_rng(seed)
    src = np.zeros(n_edges, dtype=np.int64)
    dst = np.zeros(n_edges, dtype=np.int64)
    ab = a + b
    a_frac = a / ab
    c_frac = c / (1.0 - ab)
    for _ in range(scale):
        src <<= 1
        dst <<= 1
        r_bit = rng.random(n_edges, dtype=np.float32)
        c_bit = rng.random(n_edges, dtype=np.float32)
        src_bit = r_bit >= np.float32(ab)
        threshold = np.where(src_bit, np.float32(c_frac), np.float32(a_frac))
        dst_bit = c_bit >= threshold
        src |= src_bit
        dst |= dst_bit
    # Permute vertex labels so high-degree vertices aren't clustered at 0.
    perm = rng.permutation(1 << scale)
    return perm[src].astype(dtype), perm[dst].astype(dtype)


def uniform_edges(n: int, e: int, seed: int = 0, dtype=np.int32):
    """Uniform random edges — the no-skew control case."""
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, n, e).astype(dtype),
        rng.integers(0, n, e).astype(dtype),
    )
