"""Preemption-safe resumable jobs (ISSUE 12; docs/ROBUSTNESS.md
"Preemption & resumable jobs").

The reference pipeline survives a lost driver through Spark's durable
RDD lineage: a re-run recomputes only what was lost. This build's
in-process self-healing (snapshot rollback, elastic rescue) heals a
run that is still ALIVE; nothing survived the process dying — a
preempted TPU VM lost the ingest and the 30-75 s device build
outright. This module is the lineage analogue:

- **stage machine** (:class:`JobSupervisor`): the end-to-end run
  (ingest -> build -> solve -> output) persists one checksummed,
  fingerprint-keyed durable artifact per stage into ``--job-dir`` via
  the same ``fsio.atomic_write`` idiom as snapshots. A restarted job
  validates each artifact (sha256 + graph fingerprint + layout
  geometry + config hash) and SKIPS completed stages; a corrupt or
  mismatched artifact is skipped like a PR-3 snapshot and recomputed —
  never trusted.
- **graceful drain** (:class:`GracefulDrain`): SIGTERM/SIGINT handlers
  installed only around ``cli.main`` (injectable for tests) request a
  deadline-bounded drain — the in-flight step finishes, the async
  writer flushes under its SinkGuard policy, a final snapshot plus an
  interrupted-marked run report are written, and the process exits
  :data:`~pagerank_tpu.exitcodes.ExitCode.INTERRUPTED`. A second
  signal hard-exits ``128 + signum`` immediately.
- **process chaos** (testing/faults.py :class:`ProcessKillPlan` /
  :func:`run_job_subprocess`): a real job is SIGTERM/SIGKILL'd at a
  seeded staged point and the resumed job must complete with
  oracle-parity ranks and bounded recomputed work, bit-for-bit
  reproducibly.

Telemetry rides the existing planes: ``job.*`` gauges/counters
(stage, resumes, stages skipped, drain seconds), ``job/<stage>``
spans, and a ``job`` section in the run report that ``obs report``
diffs.

Library modules stay handler-free for embeddability: lint **PTL008**
(analysis/lint.py) bans ``signal.signal``/``atexit.register`` outside
this module and ``cli.py``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import signal
import time
import warnings
import zipfile
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from pagerank_tpu.exitcodes import hard_exit_code
from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.utils import fsio

#: The stage machine, in execution order. ``ingest`` parses/loads (or
#: restores) the host-side inputs, ``build`` packs the device layout,
#: ``solve`` iterates, ``output`` writes the final ranks.
STAGES = ("ingest", "build", "solve", "output")

MANIFEST_NAME = "job.json"
MANIFEST_SCHEMA = 1

#: Default drain deadline (seconds): GCE preemption notice is 30 s; the
#: drain must flush inside it or give up the slower sinks.
DEFAULT_DRAIN_DEADLINE_S = 20.0


class DrainInterrupt(BaseException):
    """Raised at a safe point (stage boundary / completed iteration)
    after a drain request. A BaseException on purpose: no best-effort
    ``except Exception`` site (SinkGuard, telemetry exporters) may ever
    swallow a preemption — the PTL006 discipline applied to signals."""

    def __init__(self, signum: int, where: str = ""):
        super().__init__(
            f"drain requested by signal {signum}"
            + (f" (at {where})" if where else "")
        )
        self.signum = signum
        self.where = where


class ArtifactCorruptError(RuntimeError):
    """A stage artifact exists but cannot be trusted: unreadable npz,
    missing members, or checksum mismatch. The loader converts this to
    skip-and-recompute (the PR-3 snapshot discipline) — it never
    propagates out of :meth:`JobSupervisor.load_artifact`."""


# -- config hashing ---------------------------------------------------------

#: Config fields that shape the GRAPH/LAYOUT artifact (ingest/build
#: stages): a change here means the packed planes are for a different
#: layout and must be rebuilt.
GRAPH_HASH_FIELDS = (
    "dtype", "accum_dtype", "kernel", "lane_group", "wide_accum",
    "partition_span", "stream_dtype", "vertex_sharded", "vs_bounded",
    "halo_exchange", "halo_head",
)

#: Config fields that shape the SOLVE result (solve-stage artifact):
#: anything that can move the final rank vector or the iteration count.
SOLVE_HASH_FIELDS = GRAPH_HASH_FIELDS + (
    "num_iters", "damping", "semantics", "tol", "stop_tol",
    "probe_every", "num_devices",
)


def _hash_fields(cfg, fields: Iterable[str]) -> str:
    d = dataclasses.asdict(cfg)
    doc = {k: d.get(k) for k in fields}
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()
    ).hexdigest()[:16]


def graph_config_hash(cfg) -> str:
    """Layout-relevant config hash: keys the ingest/build artifacts."""
    return _hash_fields(cfg, GRAPH_HASH_FIELDS)


def solve_config_hash(cfg) -> str:
    """Result-relevant config hash: keys the solve-stage artifact."""
    return _hash_fields(cfg, SOLVE_HASH_FIELDS)


def key_hash(key: Dict[str, object]) -> str:
    """Stable hash of an arbitrary JSON-able key dict (the CLI keys
    ingest/build artifacts off the input spec + layout args BEFORE a
    PageRankConfig exists)."""
    return hashlib.sha256(
        json.dumps(key, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


# -- artifact format --------------------------------------------------------


def _artifact_digest(arrays: Dict[str, np.ndarray], meta_json: str) -> str:
    """sha256 over the meta json AND every payload array (name, dtype,
    shape, bytes) — a corrupt header is as fatal as corrupt planes
    (the Snapshotter._digest discipline)."""
    h = hashlib.sha256()
    h.update(meta_json.encode())
    for name in sorted(arrays):
        a = arrays[name]
        h.update(f"|{name}|{a.dtype.str}|{a.shape}|".encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def save_artifact(path: str, arrays: Dict[str, np.ndarray],
                  meta: Dict[str, object]) -> str:
    """Atomically persist one stage artifact: payload arrays + JSON
    meta + a sha256 checksum over both. A killed writer leaves at
    worst a ``*.tmp.npz`` no loader matches (fsio.atomic_write)."""
    arrays = {k: np.ascontiguousarray(v) for k, v in arrays.items()}
    meta_json = json.dumps(meta, sort_keys=True, allow_nan=False)
    digest = _artifact_digest(arrays, meta_json)
    with obs_trace.span("job/artifact_save", path=path) as sp:
        with fsio.atomic_write(path, "wb", suffix=".tmp.npz") as f:
            np.savez(
                f,
                meta=np.bytes_(meta_json.encode()),
                checksum=np.bytes_(digest.encode()),
                **arrays,
            )
            nbytes = f.tell()
        obs_metrics.counter(
            "job.artifact_bytes_written",
            "total stage-artifact payload bytes committed",
        ).inc(nbytes)
        if sp is not None:
            sp.attrs["bytes"] = nbytes
    return path


def load_artifact(path: str) -> Tuple[Dict[str, np.ndarray], Dict]:
    """Load + verify one stage artifact. Raises FileNotFoundError when
    absent and :class:`ArtifactCorruptError` when present but
    unreadable or failing its checksum — callers recompute, never
    trust."""
    try:
        with fsio.fopen(path, "rb") as f, np.load(f) as z:
            meta_json = bytes(z["meta"]).decode()
            stored = bytes(z["checksum"]).decode()
            arrays = {
                k: z[k].copy() for k in z.files
                if k not in ("meta", "checksum")
            }
    except FileNotFoundError:
        raise
    except (OSError, ValueError, KeyError, EOFError,
            zipfile.BadZipFile) as e:
        raise ArtifactCorruptError(
            f"stage artifact {path} is unreadable: {e!r}"
        ) from e
    want = _artifact_digest(arrays, meta_json)
    if stored != want:
        raise ArtifactCorruptError(
            f"stage artifact {path} failed its checksum "
            f"(stored {stored[:12]}…, computed {want[:12]}…)"
        )
    return arrays, json.loads(meta_json)


def encode_names(names) -> Dict[str, np.ndarray]:
    """Vertex-name table as (utf-8 blob, int64 offsets) payload arrays
    — object arrays would drag pickle into the artifact format."""
    enc = [str(k).encode("utf-8") for k in names]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(b) for b in enc], out=offs[1:])
    return {
        "names_blob": np.frombuffer(b"".join(enc), dtype=np.uint8),
        "names_offs": offs,
    }


def decode_names(arrays: Dict[str, np.ndarray]) -> Optional[List[str]]:
    if "names_blob" not in arrays or "names_offs" not in arrays:
        return None
    blob = arrays["names_blob"].tobytes()
    offs = arrays["names_offs"]
    return [
        blob[offs[i]:offs[i + 1]].decode("utf-8")
        for i in range(len(offs) - 1)
    ]


def doc_to_arrays(doc) -> Dict[str, np.ndarray]:
    """A JSON document as a payload-array dict (utf-8 blob, the
    encode_names idiom) so measurement records ride the same
    checksummed artifact format as numeric planes — the campaign
    orchestrator's per-leg artifacts (obs/campaign.py)."""
    blob = json.dumps(doc, sort_keys=True, allow_nan=False,
                      default=str).encode("utf-8")
    return {"doc_blob": np.frombuffer(blob, dtype=np.uint8)}


def doc_from_arrays(arrays: Dict[str, np.ndarray]):
    """Inverse of :func:`doc_to_arrays`; returns None when the payload
    carries no document."""
    if "doc_blob" not in arrays:
        return None
    return json.loads(arrays["doc_blob"].tobytes().decode("utf-8"))


class RestoredIds:
    """Thin stand-in for an ingest id table restored from an artifact:
    the post-ingest CLI only reads ``.names`` (text dumps / --out)."""

    def __init__(self, names: List[str]):
        self.names = names


# -- host-graph artifact marshalling ---------------------------------------

_GRAPH_ARRAYS = ("src", "dst", "out_degree", "in_degree",
                 "dangling_mask", "zero_in_mask", "edge_weight")


def graph_to_arrays(graph) -> Tuple[Dict[str, np.ndarray], Dict]:
    """Host :class:`~pagerank_tpu.graph.Graph` -> artifact payload.
    The BUILT graph is the artifact (post-dedup/sort), so a restart
    skips the host parse AND the host sort."""
    arrays = {k: np.asarray(getattr(graph, k)) for k in _GRAPH_ARRAYS}
    if graph.vertex_names is not None:
        arrays.update(encode_names(graph.vertex_names))
    meta = {
        "kind": "host_graph",
        "n": int(graph.n),
        "num_edges": int(graph.num_edges),
        "fingerprint": graph.fingerprint(),
    }
    return arrays, meta


def graph_from_arrays(arrays: Dict[str, np.ndarray], meta: Dict):
    from pagerank_tpu.graph import Graph

    names = decode_names(arrays)
    g = Graph(
        n=int(meta["n"]),
        vertex_names=names,
        **{k: arrays[k] for k in _GRAPH_ARRAYS},
    )
    fp = g.fingerprint()
    if fp != meta.get("fingerprint"):
        raise ArtifactCorruptError(
            f"restored host graph fingerprint {fp} != recorded "
            f"{meta.get('fingerprint')}"
        )
    return g


# -- graceful drain ---------------------------------------------------------


class GracefulDrain:
    """SIGTERM/SIGINT -> deadline-bounded drain request (the tentpole's
    preemption half). Context manager; install ONLY around the CLI
    entry point — library modules must stay handler-free (PTL008).

    First signal: records the request (``job.drain_requests`` counter,
    loud log line) and returns — the run notices at its next safe
    point (:meth:`check` raises :class:`DrainInterrupt` there). Second
    signal: hard-exits ``128 + signum`` immediately via the injectable
    ``hard_exit`` (``os._exit`` by default — no flush, the operator
    asked twice).

    Injectable for tests: ``install`` (defaults to ``signal.signal``),
    ``hard_exit``, and ``clock``. Installation degrades to a no-op
    (with a log line) off the main thread, where CPython refuses
    handlers — an embedded library use keeps working, just without
    drain."""

    def __init__(
        self,
        deadline_s: float = DEFAULT_DRAIN_DEADLINE_S,
        signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
        install=signal.signal,
        hard_exit=os._exit,
        clock=time.monotonic,
    ):
        self.deadline_s = float(deadline_s)
        self._signals = tuple(signals)
        self._install = install
        self._hard_exit = hard_exit
        self._clock = clock
        self._prev: Dict[int, object] = {}
        self._installed = False
        self.requested = False
        self.signum: Optional[int] = None
        self._t_request: Optional[float] = None
        # Pre-allocated handler flag: the handler may only SET simple
        # scalars (PTR003); the counter/log emission it used to do
        # in-handler is deferred to the next safe point.
        self._pending_note = False

    # -- handler lifecycle --------------------------------------------------

    def __enter__(self) -> "GracefulDrain":
        for s in self._signals:
            try:
                self._prev[s] = self._install(s, self._handler)
            except ValueError as e:
                # Non-main thread: CPython refuses handlers. Degrade —
                # embedded callers keep working without drain.
                obs_log.info(
                    f"signal handlers unavailable ({e}); preemption "
                    "drain disabled for this run"
                )
                break
        else:
            self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._prev.items():
            try:
                self._install(s, prev)
            except ValueError:
                pass
        self._prev.clear()
        self._installed = False

    def _handler(self, signum, frame) -> None:
        """Signal-handler context (PTR003, docs/ANALYSIS.md): this body
        may only set pre-allocated flags/simple scalars. CPython runs
        handlers ON THE MAIN THREAD between bytecodes — a handler that
        takes a lock (the pre-fix ``obs_log.warn`` reached the
        tracer's ``add_event`` lock, and the registry get-or-create
        takes the registry lock) self-deadlocks the moment the signal
        lands while the main thread holds that lock. Telemetry is
        deferred to :meth:`_note_request` at the next safe point;
        ``hard_exit`` (``os._exit``) is the sanctioned exception — the
        operator asked twice."""
        if self.requested:
            # Second signal: the operator means NOW.
            self._hard_exit(hard_exit_code(signum))
            return  # injectable hard_exit may not exit (tests)
        self.requested = True
        self.signum = int(signum)
        self._t_request = self._clock()
        self._pending_note = True

    def _note_request(self) -> None:
        """Emit the drain request's counter + log line OUTSIDE handler
        context — called from every drain-side entry point (check /
        remaining / finish), so the first safe point after the signal
        reports it exactly once."""
        if not self._pending_note:
            return
        self._pending_note = False
        obs_metrics.counter(
            "job.drain_requests",
            "graceful-drain requests received (first SIGTERM/SIGINT)",
        ).inc()
        obs_log.warn(
            f"signal {self.signum}: draining (deadline "
            f"{self.deadline_s:g}s; a second signal hard-exits)"
        )

    # -- drain-side API -----------------------------------------------------

    def check(self, where: str = "") -> None:
        """Raise :class:`DrainInterrupt` when a drain was requested —
        call at safe points only (stage boundaries, completed
        iterations): the in-flight step always finishes."""
        if self.requested:
            self._note_request()
            raise DrainInterrupt(self.signum or 0, where)

    def remaining(self) -> Optional[float]:
        """Seconds left of the drain deadline (None before a request,
        never below a small positive floor so bounded flushes still
        get one attempt)."""
        if self._t_request is None:
            return None
        self._note_request()
        left = self.deadline_s - (self._clock() - self._t_request)
        return max(0.5, left)

    def finish(self) -> float:
        """Record the drain's wall (request -> flushes done) in the
        ``job.drain_seconds`` gauge; returns it."""
        self._note_request()
        spent = (
            self._clock() - self._t_request
            if self._t_request is not None else 0.0
        )
        obs_metrics.gauge(
            "job.drain_seconds",
            "wall seconds between the drain request and the final "
            "flush",
        ).set(spent)
        return spent


# -- the stage machine ------------------------------------------------------


class JobSupervisor:
    """Durable stage machine over a job directory.

    The manifest (``job.json``, atomic rewrite per transition) records
    stage statuses and the resume count — it is ADVISORY: truth about
    whether a stage can be skipped lives in its artifact's checksum +
    key validation, so a torn manifest costs bookkeeping, never
    correctness. Artifacts live next to it (``ingest.npz`` /
    ``build.npz`` / ``solve.npz``) plus the ``snapshots/`` dir the
    solve stage reuses for its iteration checkpoints."""

    def __init__(self, directory: str, clock=time.perf_counter):
        self.directory = directory
        self._clock = clock
        self._t0: Dict[str, float] = {}
        self._skipped_this_run = 0
        fsio.makedirs(directory, exist_ok=True)
        self.manifest = self._read_manifest()
        self.resumed = self.manifest is not None
        if self.manifest is None:
            self.manifest = {
                "schema_version": MANIFEST_SCHEMA,
                "created_unix": time.time(),
                "resumes": 0,
                "status": "running",
                "stages": {s: {"status": "pending"} for s in STAGES},
            }
        else:
            self.manifest["resumes"] = int(
                self.manifest.get("resumes", 0)) + 1
            self.manifest["status"] = "running"
            obs_metrics.counter(
                "job.resumes",
                "job restarts that found a prior manifest in --job-dir",
            ).inc()
            obs_log.info(
                f"resuming job in {directory} (resume #"
                f"{self.manifest['resumes']})"
            )
        self._write_manifest()
        # Seeded process-kill chaos (testing/faults.py): active only
        # when the env plan is set — zero cost otherwise.
        from pagerank_tpu.testing.faults import ProcessKillPlan

        self.chaos = ProcessKillPlan.from_env()

    # -- manifest -----------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return fsio.join(self.directory, MANIFEST_NAME)

    def _read_manifest(self) -> Optional[Dict]:
        try:
            with fsio.fopen(self.manifest_path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            warnings.warn(
                f"job manifest {self.manifest_path} unreadable ({e!r}); "
                "starting a fresh manifest (artifacts still validate "
                "independently)", RuntimeWarning,
            )
            return None
        if not isinstance(doc, dict) or "stages" not in doc:
            return None
        for s in STAGES:
            doc["stages"].setdefault(s, {"status": "pending"})
        return doc

    def _write_manifest(self) -> None:
        with fsio.atomic_write(self.manifest_path, "w") as f:
            json.dump(self.manifest, f, indent=2, allow_nan=False)
            f.write("\n")

    # -- stage lifecycle ----------------------------------------------------

    def artifact_path(self, stage: str) -> str:
        return fsio.join(self.directory, f"{stage}.npz")

    def snapshots_dir(self) -> str:
        return fsio.join(self.directory, "snapshots")

    def _set(self, stage: str, status: str, **detail) -> None:
        rec = self.manifest["stages"].setdefault(stage, {})
        rec["status"] = status
        rec.update(detail)
        self._write_manifest()

    def begin(self, stage: str) -> None:
        self.tick(stage)
        self._t0[stage] = self._clock()
        obs_metrics.gauge(
            "job.stage", "index of the stage the job is executing "
            "(0=ingest 1=build 2=solve 3=output)",
        ).set(STAGES.index(stage) if stage in STAGES else -1)
        self._set(stage, "running")

    def complete(self, stage: str, **detail) -> None:
        wall = (
            self._clock() - self._t0[stage]
            if stage in self._t0 else None
        )
        self._set(stage, "done", wall_s=wall, skipped=False, **detail)

    def skip(self, stage: str, **detail) -> None:
        """Stage satisfied by a validated durable artifact — record it
        and bump the skip telemetry (the resume's whole point). The
        gauge counts THIS run's skips from an instance counter, not
        the manifest — a reloaded manifest still carries the PRIOR
        run's skipped flags."""
        self.tick(stage)
        self._skipped_this_run += 1
        obs_metrics.gauge(
            "job.stages_skipped",
            "stages satisfied by validated durable artifacts this run",
        ).set(self._skipped_this_run)
        self._set(stage, "done", skipped=True, wall_s=0.0, **detail)
        obs_log.info(f"job stage '{stage}' skipped (durable artifact)")

    def interrupt(self, stage: str, **detail) -> None:
        """Mark the manifest interrupted at ``stage``. A stage whose
        record is already ``done`` is NOT downgraded — the post-commit
        drain checkpoints raise with the COMPLETED stage's name, and
        its artifact is durable; the interrupt point rides the
        manifest-level ``interrupted_after`` instead, so the report
        still answers "did we lose the build" correctly (no)."""
        self.manifest["status"] = "interrupted"
        rec = self.manifest["stages"].get(stage, {})
        if rec.get("status") == "done":
            self.manifest["interrupted_after"] = stage
            self.manifest.update(
                {f"interrupt_{k}": v for k, v in detail.items()})
            self._write_manifest()
            return
        self._set(stage, "interrupted", **detail)

    def finish(self) -> None:
        self.manifest["status"] = "complete"
        self._write_manifest()

    def stage_span(self, stage: str):
        """``job/<stage>`` span + begin bookkeeping (the caller marks
        complete/skip — completion detail differs per stage)."""
        self.begin(stage)
        return obs_trace.span(f"job/{stage}")

    def tick(self, stage: str, iteration: Optional[int] = None) -> None:
        """Chaos hook: the seeded process-kill plan fires here (stage
        boundaries + per solve iteration). No-op without a plan."""
        if self.chaos is not None:
            self.chaos.check(stage, iteration)

    # -- artifacts ----------------------------------------------------------

    def save_stage_artifact(self, stage: str,
                            arrays: Dict[str, np.ndarray],
                            meta: Dict[str, object]) -> str:
        meta = dict(meta)
        meta["stage"] = stage
        return save_artifact(self.artifact_path(stage), arrays, meta)

    def load_stage_artifact(
        self, stage: str, expect: Optional[Dict[str, object]] = None,
    ) -> Optional[Tuple[Dict[str, np.ndarray], Dict]]:
        """Validated artifact for ``stage``, or None (absent, corrupt,
        or key-mismatched — each logged; corrupt/mismatched artifacts
        are recomputed, never trusted)."""
        path = self.artifact_path(stage)
        try:
            arrays, meta = load_artifact(path)
        except FileNotFoundError:
            return None
        except ArtifactCorruptError as e:
            obs_metrics.counter(
                "job.artifacts_rejected",
                "stage artifacts rejected at resume (corrupt or "
                "key-mismatched) and recomputed",
            ).inc()
            warnings.warn(
                f"job stage '{stage}': corrupt artifact recomputed "
                f"({e})", RuntimeWarning,
            )
            return None
        for k, v in (expect or {}).items():
            if meta.get(k) != v:
                obs_metrics.counter(
                    "job.artifacts_rejected",
                    "stage artifacts rejected at resume (corrupt or "
                    "key-mismatched) and recomputed",
                ).inc()
                warnings.warn(
                    f"job stage '{stage}': artifact key mismatch "
                    f"({k}: artifact {meta.get(k)!r} != run {v!r}); "
                    "recomputing", RuntimeWarning,
                )
                return None
        return arrays, meta

    def save_profile(self, profile) -> None:
        """Persist the data-plane graph profile (ISSUE 13;
        obs/graph_profile.GraphProfile) as a checksummed artifact next
        to the stage artifacts, keyed by graph fingerprint — a resumed
        build-skipping run republishes it instead of losing the data
        plane (the post-sort packed planes can't re-derive the raw
        dedup stats)."""
        arrays, meta = profile.to_arrays()
        save_artifact(
            fsio.join(self.directory, "profile.npz"), arrays, meta)

    def load_profile(self, fingerprint: Optional[str]):
        """Validated graph-profile artifact matching ``fingerprint``,
        or None (absent / corrupt / fingerprint-mismatched — the same
        never-trust discipline as the stage artifacts)."""
        from pagerank_tpu.obs.graph_profile import GraphProfile

        path = fsio.join(self.directory, "profile.npz")
        try:
            arrays, meta = load_artifact(path)
        except FileNotFoundError:
            return None
        except ArtifactCorruptError as e:
            obs_metrics.counter(
                "job.artifacts_rejected",
                "stage artifacts rejected at resume (corrupt or "
                "key-mismatched) and recomputed",
            ).inc()
            warnings.warn(
                f"job graph-profile artifact rejected ({e})",
                RuntimeWarning,
            )
            return None
        if fingerprint is not None and \
                meta.get("fingerprint") != fingerprint:
            obs_metrics.counter(
                "job.artifacts_rejected",
                "stage artifacts rejected at resume (corrupt or "
                "key-mismatched) and recomputed",
            ).inc()
            warnings.warn(
                f"job graph-profile artifact is for a different graph "
                f"({meta.get('fingerprint')!r} != {fingerprint!r}); "
                "ignored", RuntimeWarning,
            )
            return None
        try:
            return GraphProfile.from_arrays(arrays, meta)
        except (KeyError, ValueError) as e:
            warnings.warn(
                f"job graph-profile artifact undecodable ({e!r})",
                RuntimeWarning,
            )
            return None

    # -- SDC quarantine persistence (ISSUE 15; pagerank_tpu/sdc.py) ---------

    def quarantined_devices(self) -> List[int]:
        """Device ids convicted of sticky silent data corruption in
        ANY run of this job — a resumed job must never re-adopt a
        known-bad chip, so the exclusion list rides the manifest
        (atomic rewrite, like every stage transition)."""
        return [int(d) for d in
                self.manifest.get("quarantined_devices", [])]

    def quarantine_devices(self, device_ids) -> None:
        """Merge freshly convicted device ids into the persisted
        exclusion list (idempotent; survives resumes)."""
        have = set(self.quarantined_devices())
        new = sorted(have | {int(d) for d in device_ids})
        if new == sorted(have):
            return
        self.manifest["quarantined_devices"] = new
        self._write_manifest()

    def save_names(self, names, key: str) -> None:
        """Persist an ingest id->name table (crawl inputs) next to the
        stage artifacts so a resumed job's --out/--dump-text-dir still
        writes urls, not integer ids."""
        save_artifact(
            fsio.join(self.directory, "names.npz"),
            encode_names(names), {"key": key, "kind": "names"},
        )

    def load_names(self, key: str) -> Optional[List[str]]:
        try:
            arrays, meta = load_artifact(
                fsio.join(self.directory, "names.npz"))
        except (FileNotFoundError, ArtifactCorruptError):
            return None
        if meta.get("key") != key:
            return None
        return decode_names(arrays)

    # -- reporting ----------------------------------------------------------

    def report_section(self) -> Dict[str, object]:
        """The run report's ``job`` section (obs/report.py REPORT_KEYS;
        diffed by ``obs report A B``)."""
        stages = {
            s: {
                "status": r.get("status"),
                "skipped": bool(r.get("skipped", False)),
                "wall_s": r.get("wall_s"),
                **{k: v for k, v in r.items()
                   if k not in ("status", "skipped", "wall_s")},
            }
            for s, r in self.manifest["stages"].items()
        }
        out = {
            "dir": self.directory,
            "status": self.manifest.get("status"),
            "resumes": int(self.manifest.get("resumes", 0)),
            "stages": stages,
        }
        if "interrupted_after" in self.manifest:
            out["interrupted_after"] = self.manifest["interrupted_after"]
        quarantined = self.quarantined_devices()
        if quarantined:
            out["quarantined_devices"] = quarantined
        return out
