"""Batched Personalized PageRank engines (SpMM power iteration).

Device path: source batch is processed in column chunks; each chunk is a
[n, kc] rank matrix replicated across the mesh, edges sharded, and the
per-iteration communication is one psum of the dense [n, kc] partials —
the same pattern as the rank-vector solver, with k-fold arithmetic
intensity. Results are returned as per-source top-k (a full [num_sources,
n] matrix would not fit host memory at scale).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from pagerank_tpu.graph import Graph
from pagerank_tpu.models import ppr as ppr_model
from pagerank_tpu.utils.config import PageRankConfig


@dataclass
class PprResult:
    sources: np.ndarray  # [s] source vertex ids
    topk_ids: np.ndarray  # [s, k] highest-rank vertex ids per source
    topk_scores: np.ndarray  # [s, k]

    def rank_of(self, source_index: int):
        return self.topk_ids[source_index], self.topk_scores[source_index]


def ppr_cpu(
    graph: Graph,
    sources: np.ndarray,
    num_iters: int = 20,
    damping: float = 0.85,
    dangling_to: str = ppr_model.DANGLING_TO_SOURCE,
) -> np.ndarray:
    """Float64 oracle: full [n, s] PPR matrix (small graphs only)."""
    from pagerank_tpu.graph import to_csr_transpose

    at = to_csr_transpose(graph)
    n, s = graph.n, len(sources)
    p = np.zeros((n, s))
    p[sources, np.arange(s)] = 1.0
    d = (graph.out_degree == 0).astype(np.float64)
    r = p.copy()
    for _ in range(num_iters):
        contrib = at @ r
        mass = d @ r
        r = ppr_model.apply_ppr_update(
            contrib, p, mass, n, damping, dangling_to, np
        )
    return r


def ppr_cpu_topk(
    graph: Graph, config: PageRankConfig, sources: np.ndarray,
    topk: int = 100, dangling_to: str = ppr_model.DANGLING_TO_SOURCE,
) -> PprResult:
    """Run the float64 CPU oracle and shape its full [n, s] matrix into
    the same top-k ``PprResult`` the device engine returns (CLI
    ``--engine cpu`` path)."""
    sources = np.asarray(sources, dtype=np.int64)
    r = ppr_cpu(
        graph, sources, num_iters=config.num_iters,
        damping=config.damping, dangling_to=dangling_to,
    )  # [n, s]
    k = min(topk, graph.n)
    order = np.argsort(-r, axis=0, kind="stable")[:k]  # [k, s]
    ids = order.T.astype(np.int32)  # [s, k]
    scores = np.take_along_axis(r, order, axis=0).T
    return PprResult(sources=sources, topk_ids=ids, topk_scores=scores)


class PprJaxEngine:
    """Chunked batched PPR on the device mesh."""

    def __init__(self, config: Optional[PageRankConfig] = None,
                 dangling_to: str = ppr_model.DANGLING_TO_SOURCE,
                 devices=None):
        self.config = (config or PageRankConfig()).validate()
        self.dangling_to = dangling_to
        self._devices = devices
        self.graph: Optional[Graph] = None

    def build(self, graph: Graph) -> "PprJaxEngine":
        import functools

        import jax
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from pagerank_tpu.ops import spmv
        from pagerank_tpu.parallel import mesh as mesh_lib
        from pagerank_tpu.parallel import partition

        cfg = self.config
        self.graph = graph
        self._mesh = mesh_lib.make_mesh(
            cfg.num_devices, cfg.mesh_axis, devices=self._devices
        )
        axis = cfg.mesh_axis
        ndev = self._mesh.devices.size
        dtype = jnp.dtype(cfg.dtype)
        accum = jnp.dtype(cfg.accum_dtype)
        n = graph.n

        shards = partition.partition_edges(graph, ndev, weight_dtype=dtype)
        e_shard = mesh_lib.edge_sharding(self._mesh)
        rep = mesh_lib.replicated(self._mesh)
        self._src = jax.device_put(shards.src, e_shard)
        self._dst = jax.device_put(shards.dst, e_shard)
        self._w = jax.device_put(shards.weight, e_shard)
        self._dangling = jax.device_put(
            (graph.out_degree == 0).astype(dtype), rep
        )

        damping = cfg.damping
        dangling_to = self.dangling_to

        def sharded_contrib(r, src, dst, w):
            part = spmv.edge_contrib_segment_sum(r, src, dst, w, n, accum)
            return jax.lax.psum(part, axis)

        contrib_fn = shard_map(
            sharded_contrib,
            mesh=self._mesh,
            in_specs=(P(), P(axis), P(axis), P(axis)),
            out_specs=P(),
        )

        @functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(0,))
        def run_chunk(r, p_onehot, num_iters, src, dst, w, dangling):
            def body(_, r):
                contrib = contrib_fn(r, src, dst, w).astype(accum)
                mass = dangling.astype(accum) @ r.astype(accum)
                return ppr_model.apply_ppr_update(
                    contrib, p_onehot.astype(accum), mass, n, damping,
                    dangling_to, jnp,
                ).astype(r.dtype)

            return jax.lax.fori_loop(0, num_iters, body, r)

        @functools.partial(jax.jit, static_argnums=(1,))
        def topk_fn(r, k):
            scores, ids = jax.lax.top_k(r.T, k)  # per column
            return ids, scores

        self._run_chunk = run_chunk
        self._topk = topk_fn
        self._jnp = jnp
        self._jax = jax
        self._dtype = dtype
        return self

    def run(
        self,
        sources: np.ndarray,
        num_iters: Optional[int] = None,
        topk: int = 100,
        chunk: int = 64,
    ) -> PprResult:
        if self.graph is None:
            raise RuntimeError("call build(graph) before run()")
        jax, jnp = self._jax, self._jnp
        cfg = self.config
        iters = cfg.num_iters if num_iters is None else num_iters
        n = self.graph.n
        sources = np.asarray(sources, dtype=np.int64)
        topk = min(topk, n)

        ids_out = np.zeros((len(sources), topk), np.int32)
        scores_out = np.zeros((len(sources), topk), self._dtype)
        from pagerank_tpu.parallel.mesh import replicated

        rep = replicated(self._mesh)
        for lo in range(0, len(sources), chunk):
            batch = sources[lo : lo + chunk]
            p = np.zeros((n, len(batch)), dtype=self._dtype)
            p[batch, np.arange(len(batch))] = 1.0
            p_dev = jax.device_put(jnp.asarray(p), rep)
            r = self._run_chunk(
                p_dev.copy(), p_dev, iters,
                self._src, self._dst, self._w, self._dangling,
            )
            ids, scores = self._topk(r, topk)
            ids_out[lo : lo + len(batch)] = np.asarray(jax.device_get(ids))
            scores_out[lo : lo + len(batch)] = np.asarray(jax.device_get(scores))
        return PprResult(sources=sources, topk_ids=ids_out, topk_scores=scores_out)
