"""Batched Personalized PageRank engines (SpMM power iteration).

Device path: source batch is processed in column chunks; each chunk is a
[n, kc] rank matrix replicated across the mesh, edges sharded, and the
per-iteration communication is one psum of the dense [n, kc] partials —
the same pattern as the rank-vector solver, with k-fold arithmetic
intensity. Results are returned as per-source top-k (a full [num_sources,
n] matrix would not fit host memory at scale).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from pagerank_tpu.graph import Graph
from pagerank_tpu.models import ppr as ppr_model
from pagerank_tpu.utils.config import PageRankConfig


@dataclass
class PprResult:
    sources: np.ndarray  # [s] source vertex ids
    topk_ids: np.ndarray  # [s, k] highest-rank vertex ids per source
    topk_scores: np.ndarray  # [s, k]

    def rank_of(self, source_index: int):
        return self.topk_ids[source_index], self.topk_scores[source_index]


def ppr_cpu(
    graph: Graph,
    sources: np.ndarray,
    num_iters: int = 20,
    damping: float = 0.85,
    dangling_to: str = ppr_model.DANGLING_TO_SOURCE,
) -> np.ndarray:
    """Float64 oracle: full [n, s] PPR matrix (small graphs only)."""
    from pagerank_tpu.graph import to_csr_transpose

    at = to_csr_transpose(graph)
    n, s = graph.n, len(sources)
    p = np.zeros((n, s))
    p[sources, np.arange(s)] = 1.0
    d = (graph.out_degree == 0).astype(np.float64)
    r = p.copy()
    for _ in range(num_iters):
        contrib = at @ r
        mass = d @ r
        r = ppr_model.apply_ppr_update(
            contrib, p, mass, n, damping, dangling_to, np
        )
    return r


def ppr_cpu_topk(
    graph: Graph, config: PageRankConfig, sources: np.ndarray,
    topk: int = 100, dangling_to: str = ppr_model.DANGLING_TO_SOURCE,
) -> PprResult:
    """Run the float64 CPU oracle and shape its full [n, s] matrix into
    the same top-k ``PprResult`` the device engine returns (CLI
    ``--engine cpu`` path)."""
    sources = np.asarray(sources, dtype=np.int64)
    r = ppr_cpu(
        graph, sources, num_iters=config.num_iters,
        damping=config.damping, dangling_to=dangling_to,
    )  # [n, s]
    k = min(topk, graph.n)
    order = np.argsort(-r, axis=0, kind="stable")[:k]  # [k, s]
    ids = order.T.astype(np.int32)  # [s, k]
    scores = np.take_along_axis(r, order, axis=0).T
    return PprResult(sources=sources, topk_ids=ids, topk_scores=scores)


class PprJaxEngine:
    """Chunked batched PPR on the device mesh.

    Layout: the same source-striped blocked-ELL packing as the
    rank-vector solver (ops/ell.py), with one twist — the batch of k
    personalized columns IS the gather row (ops/spmv.py:ell_contrib_spmm
    docstring), so stripes are sized to 2**17 - 128 sources to keep each
    (sz + 1, k) table slice in the fast-gather regime. Rows stream in
    fixed chunks, bounding the gather intermediate (the earlier COO path
    materialized an (edges, k) product that OOM'd real graphs)."""

    # Stripe sources so the per-stripe table (sz + 1 rows with the zero
    # sentinel appended) stays within the <= 2**17-row fast regime.
    STRIPE = (1 << 17) - 128
    CHUNK_ROWS = 1024  # (chunk, 128, k) gather intermediate, ~32MB at k=64

    def __init__(self, config: Optional[PageRankConfig] = None,
                 dangling_to: str = ppr_model.DANGLING_TO_SOURCE,
                 devices=None):
        self.config = (config or PageRankConfig()).validate()
        self.dangling_to = dangling_to
        self._devices = devices
        self.graph: Optional[Graph] = None

    def build(self, graph: Graph) -> "PprJaxEngine":
        import functools

        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from pagerank_tpu.utils.jax_compat import shard_map

        from pagerank_tpu import graph as graph_lib
        from pagerank_tpu.ops import ell as ell_lib
        from pagerank_tpu.ops import spmv
        from pagerank_tpu.parallel import mesh as mesh_lib

        cfg = self.config
        for d in (cfg.dtype, cfg.accum_dtype):
            if np.dtype(d).itemsize == 8 and not jax.config.jax_enable_x64:
                raise ValueError(
                    f"dtype {d} needs jax_enable_x64 (see conftest.py)"
                )
        self.graph = graph
        self._mesh = mesh_lib.make_mesh(
            cfg.num_devices, cfg.mesh_axis, devices=self._devices
        )
        axis = cfg.mesh_axis
        ndev = self._mesh.devices.size
        dtype = jnp.dtype(cfg.dtype)
        accum = jnp.dtype(cfg.accum_dtype)
        n = graph.n  # Graph guarantees n >= 1, so S >= 1 stripes below
        n_padded = -(-n // 128) * 128

        sz = max(128, min(self.STRIPE, n_padded))
        pack = ell_lib.ell_pack_striped(graph, stripe_size=sz)
        S = pack.n_stripes
        n_state = pack.n_padded
        self._perm = pack.perm  # relabeled -> original
        num_blocks = pack.num_blocks
        pad = n_state - n

        shard2d = jax.sharding.NamedSharding(self._mesh, P(axis, None))
        e_shard = mesh_lib.edge_sharding(self._mesh)
        rep = mesh_lib.replicated(self._mesh)

        srcs, rbs, chunks = [], [], []
        pres_ids, num_present, prefix_flags = [], [], []
        for s in range(S):
            ss = np.where(pack.weight[s] != 0, pack.src[s], np.int32(sz))
            rows = ss.shape[0]
            # Dense block ranks for the slab-scan accumulator
            # (ops/spmv.py:_chunked_block_sum) — the carry matters
            # k-fold more in the SpMM than the vector path.
            rb, ids, pcount, prefix = ell_lib.dense_block_ranks(
                pack.row_block[s], num_blocks
            )
            prefix_flags.append(prefix)
            # Chunk per stripe: a short tail stripe pads only to its own
            # ndev*chunk_s, not to the largest stripe's chunk.
            chunk_s = min(self.CHUNK_ROWS, -(-max(rows, 1) // ndev))
            mult = ndev * chunk_s
            tgt = -(-max(rows, 1) // mult) * mult
            ss = np.concatenate(
                [ss, np.full((tgt - rows, 128), np.int32(sz), np.int32)]
            )
            rb = np.concatenate(
                [rb, np.full(tgt - rows, pcount - 1, np.int32)]
            )
            srcs.append(jax.device_put(ss, shard2d))
            rbs.append(jax.device_put(rb, e_shard))
            chunks.append(chunk_s)
            pres_ids.append(jax.device_put(jnp.asarray(ids), rep))
            num_present.append(pcount)
        pack.src = pack.weight = pack.row_block = []  # free host copies

        # Prescale in the widest dtype the solver uses, so per-edge
        # products carry accum precision into the segment-sum exactly as
        # a per-slot-weight form would (same rule as jax_engine).
        inv_dtype = accum if accum.itemsize > dtype.itemsize else dtype
        inv = graph_lib.inv_out_degree(graph.out_degree, dtype=inv_dtype)
        inv_rel = np.concatenate([inv[pack.perm], np.zeros(pad, inv_dtype)])
        self._inv_out = jax.device_put(inv_rel, rep)
        # bool on device (1 byte/vertex); cast in-step where consumed —
        # same rule as jax_engine._finalize.
        dang = (graph.out_degree == 0)[pack.perm]
        self._dangling = jax.device_put(
            np.concatenate([dang, np.zeros(pad, bool)]), rep
        )
        valid = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
        self._valid = jax.device_put(valid, rep)
        self._slot_args = tuple(
            a for triple in zip(srcs, rbs, pres_ids) for a in triple
        )

        damping = cfg.damping
        dangling_to = self.dangling_to
        total_z = S * sz

        def sharded_contrib(z2, *slots):
            k = z2.shape[1]
            total = None
            for s in range(S):
                src_s, rb_s, ids_s = slots[3 * s : 3 * s + 3]
                z_s = jnp.concatenate(
                    [z2[s * sz : (s + 1) * sz],
                     jnp.zeros((1, k), z2.dtype)]
                )
                Ps = num_present[s]
                part = spmv.ell_contrib_spmm(
                    z_s, src_s, rb_s, num_blocks, accum_dtype=accum,
                    chunk_rows=chunks[s], num_present=Ps,
                ).reshape(Ps, 128, k)
                if total is None:
                    total = jnp.zeros((num_blocks, 128, k), part.dtype)
                total = spmv.scatter_block_sums(
                    total, part, ids_s, prefix_flags[s]
                )
            return jax.lax.psum(total.reshape(num_blocks * 128, k), axis)

        contrib_fn = shard_map(
            sharded_contrib,
            mesh=self._mesh,
            in_specs=(P(),) + (P(axis, None), P(axis), P()) * S,
            out_specs=P(),
        )

        @functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(0,))
        def run_chunk(r, p_onehot, num_iters, inv_out, dangling, valid_m,
                      *slots):
            def body(_, r):
                z2 = r * inv_out[:, None]
                if total_z > n_state:
                    z2 = jnp.concatenate(
                        [z2, jnp.zeros((total_z - n_state, z2.shape[1]),
                                       z2.dtype)]
                    )
                contrib = contrib_fn(z2, *slots)[:n_state].astype(accum)
                # Shared mass reduction: picks multiply+sum for 64-bit
                # accumulation (the TPU f64-dot lowering is reduced
                # precision; ops/spmv.py:dangling_mass docstring).
                mass = spmv.dangling_mass(r, dangling, accum)
                r_new = ppr_model.apply_ppr_update(
                    contrib, p_onehot.astype(accum), mass, n, damping,
                    dangling_to, jnp,
                )
                return (r_new * valid_m[:, None].astype(accum)).astype(r.dtype)

            return jax.lax.fori_loop(0, num_iters, body, r)

        @functools.partial(jax.jit, static_argnums=(1,))
        def topk_fn(r, k):
            scores, ids = jax.lax.top_k(r.T, k)  # per column, relabeled
            return ids, scores

        self._run_chunk = run_chunk
        self._topk = topk_fn
        self._jnp = jnp
        self._jax = jax
        self._dtype = dtype
        self._n_state = n_state
        self._inv_perm = pack.inv_perm  # original -> relabeled id
        return self

    def run(
        self,
        sources: np.ndarray,
        num_iters: Optional[int] = None,
        topk: int = 100,
        chunk: int = 64,
    ) -> PprResult:
        if self.graph is None:
            raise RuntimeError("call build(graph) before run()")
        cfg = self.config
        iters = cfg.num_iters if num_iters is None else num_iters
        n = self.graph.n
        sources = np.asarray(sources, dtype=np.int64)
        topk = min(topk, n)

        jax, jnp = self._jax, self._jnp
        ids_out = np.zeros((len(sources), topk), np.int32)
        scores_out = np.zeros((len(sources), topk), self._dtype)
        from pagerank_tpu.parallel.mesh import replicated

        rep = replicated(self._mesh)
        inv_perm = self._inv_perm
        for lo in range(0, len(sources), chunk):
            batch = sources[lo : lo + chunk]
            p = np.zeros((self._n_state, len(batch)), dtype=self._dtype)
            p[inv_perm[batch], np.arange(len(batch))] = 1.0
            p_dev = jax.device_put(jnp.asarray(p), rep)
            r = self._run_chunk(
                p_dev.copy(), p_dev, iters,
                self._inv_out, self._dangling, self._valid,
                *self._slot_args,
            )
            ids, scores = self._topk(r, topk)
            ids_rel = np.asarray(jax.device_get(ids))
            # Padding lanes carry score exactly 0 and original ids only
            # exist for relabeled ids < n; clip (their score 0 keeps
            # ordering honest — a real vertex with score 0 ties anyway).
            ids_out[lo : lo + len(batch)] = self._perm[
                np.minimum(ids_rel, n - 1)
            ]
            scores_out[lo : lo + len(batch)] = np.asarray(jax.device_get(scores))
        return PprResult(sources=sources, topk_ids=ids_out, topk_scores=scores_out)
