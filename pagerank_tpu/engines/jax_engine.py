"""JaxTpuEngine — the TPU-native solver (L3 over L0).

The reference's per-iteration dataflow (Sparky.java:187-238) — 3 shuffles,
|dangUrls|+1 driver round-trips, one S3 write — collapses into ONE jitted
step per iteration:

  - edge data lives sharded across a 1-D device mesh;
  - the rank vector is replicated (a Spark "broadcast" that never leaves
    device, Sparky.java:135);
  - each device computes a dense contribution partial, then one
    `jax.lax.psum` over ICI merges partials — the only cross-device
    communication per iteration;
  - dangling mass, zero-in-degree retention, and the teleport term are
    fused elementwise arithmetic (XLA fuses them into the epilogue);
  - the rank buffer is donated, so device memory is O(1) in iterations
    (the reference instead re-caches every iteration with no unpersist,
    Sparky.java:216,235 — SURVEY.md §3.3).

Three SpMV kernels (config.kernel):
  - "ell": blocked-ELL slots + row segment-sum + adaptive-width row
    gather (ops/ell.py, ops/spmv.py:ell_contrib) — the TPU-fast XLA
    path. Vertices are relabeled by in-degree internally; ranks()
    translates back. The rank vector is pre-scaled by 1/out_degree so
    slots carry only a source index (ops/spmv.py docstring). The gather
    row widens with the state size (_gather_width) and graphs past the
    fast-gather regime use the source-striped layout
    (ops/ell.py:ell_pack_striped). A 64-bit accum_dtype runs the
    pair-packed (hi, lo) f32 gather with wide reduction
    (ops/spmv.py:ell_contrib_pair) for f64-grade accuracy at near-f32
    speed (config.wide_accum).
  - "pallas": hand Mosaic kernel with the pre-scaled rank vector pinned
    in VMEM (ops/pallas_spmv.py). Requires the vector to fit a ~12MB
    VMEM budget; gather strategies ("take", then "onehot8") are
    probe-compiled at build and the engine falls back to "ell" if
    Mosaic rejects both on this TPU generation.
  - "coo": dst-sorted COO + per-edge sorted segment-sum — simple
    portable baseline.

Zero host round-trips per iteration unless the caller asks for per-iter
logging/snapshots; the L1 delta and dangling mass come back as device
scalars fetched lazily.

NOTE on timing: on some remote-tunnel backends `jax.block_until_ready`
returns before execution finishes; fences here use a scalar device_get,
which is always honest.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from pagerank_tpu.utils.jax_compat import shard_map

from pagerank_tpu import graph as graph_mod
from pagerank_tpu.engine import PageRankEngine, register_engine
from pagerank_tpu.graph import Graph
from pagerank_tpu.obs import costs as obs_costs
from pagerank_tpu.obs import hlo as obs_hlo
from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs import log as obs_log
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.models import pagerank as pr_model
from pagerank_tpu.ops import ell as ell_lib
from pagerank_tpu.ops import spmv
from pagerank_tpu.parallel import mesh as mesh_lib
from pagerank_tpu.parallel import partition


class PallasUnavailableError(RuntimeError):
    """Raised by the ELL setup when kernel='pallas' was requested but
    BOTH Mosaic gather strategies fail to probe-compile on this
    backend. The build entry points catch it and REBUILD with the
    native ell layout (grouped lanes + slab scan) instead of running
    the XLA path on the pallas-shaped group-1 non-slab arrays — the
    ~9% fallback penalty PERF_NOTES measured was that layout, not
    kernel arithmetic."""


def _split_pair(z):
    """Dekker split z = hi + lo exactly, both f32 — the pair-packed
    gather's two planes (ops/spmv.py:ell_contrib_pair docstring). One
    spelling shared by every prescale so the split cannot drift."""
    hi = z.astype(jnp.float32)
    lo = (z - hi.astype(z.dtype)).astype(jnp.float32)
    return hi, lo


def _pad_rows(a, multiple: int, fill, xp=np):
    rows = a.shape[0]
    target = -(-max(rows, 1) // multiple) * multiple
    if target == rows:
        return a
    pad_shape = (target - rows,) + a.shape[1:]
    return xp.concatenate([a, xp.full(pad_shape, fill, dtype=a.dtype)])


def _ledger_sums(contrib, r, zero_in, accum):
    """Rank-mass-ledger raw sums (ISSUE 13; obs/graph_profile.py) over
    FULL (replicated) vectors: (contrib_total, retained_total,
    mass_prev) as accum-dtype scalars. Local reductions only — the
    ledger-enabled probed step keeps the plain step's exact collective
    multiset (the PTC007 discipline)."""
    return (
        jnp.sum(contrib.astype(accum)),
        jnp.sum(jnp.where(zero_in, r, jnp.zeros((), r.dtype))
                .astype(accum)),
        jnp.sum(r.astype(accum)),
    )


def _ledger_partials(contrib_l, r_l, zin_l, accum):
    """The sharded twin of :func:`_ledger_sums`: per-shard PARTIAL sums
    shaped [1] so a ``P(axis)`` out-spec concatenates them to [ndev]
    and the HOST finishes the reduction — no psum joins the step (the
    probed program's collective multiset stays exactly the plain
    step's)."""
    return (
        jnp.reshape(jnp.sum(contrib_l.astype(accum)), (1,)),
        jnp.reshape(
            jnp.sum(jnp.where(zin_l, r_l, jnp.zeros((), r_l.dtype))
                    .astype(accum)), (1,)),
        jnp.reshape(jnp.sum(r_l.astype(accum)), (1,)),
    )


@register_engine("jax")
class JaxTpuEngine(PageRankEngine):
    """Sharded power iteration over a 1-D device mesh."""

    def __init__(self, config=None, devices=None, pack_cache=None):
        super().__init__(config)
        self._devices = devices
        self._mesh = None
        # Optional host-pack reuse across engine builds of the SAME
        # graph (ISSUE 17 bench satellite): a caller-owned dict keyed
        # on the RESOLVED packing plan (graph identity, packer form,
        # span, lane group, block deal). Legs whose plans resolve
        # identically (dense / sparse / async exchange differ only in
        # the step program, never in the ELL layout) share one packed
        # graph instead of re-sorting the edge list per leg; a plan
        # mismatch (the pallas partitioned leg) is a clean miss. When
        # set, the build leaves the pack's host arrays alive — the
        # cache owns them and the caller frees by dropping the dict.
        self._pack_cache = pack_cache
        self._pack: Optional[ell_lib.EllPack] = None
        self._perm: Optional[np.ndarray] = None  # relabeled -> original
        self._ms_stripe = None  # set by _setup_multi_dispatch
        self._inv_in_args = False  # set by _finalize
        # Resolved-layout record (layout_info): every setup path fills
        # this so bench JSON / the run report can say what ACTUALLY ran
        # — including a pallas->ell probe fallback.
        self._layout: Dict[str, object] = {}
        self._kernel_requested: Optional[str] = None
        # Comms accounting (ISSUE 8, parallel/comms.py): filled by the
        # vertex-sharded setups; None/0 for replicated forms (no
        # per-vertex exchange to model).
        self._comms_model: Optional[Dict[str, object]] = None
        self._comms_counter = None
        self._comms_bytes_per_iter = 0
        self._halo_plan = None
        # Step-carried device state beyond the rank vector (ISSUE 17;
        # config.halo_async): the async halo setup threads its two-slot
        # boundary buffer here, at _device_args index 1, and every step
        # form returns the refreshed carry right after the rank output.
        # Empty on every synchronous form — the staleness-0 booby trap
        # (tests/test_halo_async.py) asserts exactly that.
        self._carry_args: tuple = ()
        self._carry_prime = None  # () -> fresh carry tuple, or None
        self._last_step_delta = 0.0  # see _begin_build / _stale_slack
        # Exchange-only sub-program for comms-vs-compute wall
        # attribution (ISSUE 10; obs/devices.attribute_exchange): the
        # vertex-sharded setups stash the un-jitted body here; it is
        # jitted LAZILY on first attribution use, so a solve that never
        # attributes pays nothing — not even a compile.
        self._exchange_core = None
        self._exchange_fn = None
        self._lowering_cache = None
        self._step_core_ledger = None
        self._ms_final_ledger = None

    # -- build ------------------------------------------------------------

    def _begin_build(self):
        cfg = self.config
        # Engine-side build attribution (autotune wall etc.), read by
        # bench.py --build-only alongside the device builder's stage
        # timings.
        self.build_timings = {}
        # A REBUILD must drop the previous layout's exchange-only
        # program: the jitted fn closes over the old mesh/state width,
        # and a layout without an exchange (replicated/multi-dispatch)
        # must not inherit one — the vs setups reassign _exchange_core
        # when they apply. Same for the previous program's lowering
        # reports: the memo is per-engine-PER-BUILD, never the shared
        # process ledger (a rebuilt engine must re-classify).
        self._exchange_core = None
        self._exchange_fn = None
        self._lowering_cache = None
        # A rebuild into a synchronous form must not inherit the async
        # boundary buffer (or its priming program): the carry rides the
        # step signature, so a stale one would desynchronize
        # _device_args from the compiled step.
        self._carry_args = ()
        self._carry_prime = None
        # Previous stepwise iteration's L1 delta — the staleness bound
        # _stale_slack feeds the SDC/ledger conservation checks under
        # the async form. 0.0 after any (re)build or state replacement:
        # the freshly primed buffer makes the next step lag-0 exact.
        self._last_step_delta = 0.0
        # Rank-mass-ledger step variants (ISSUE 13): every setup path
        # that supports the ledger reassigns these; a rebuild into a
        # form that doesn't must not inherit the previous layout's.
        self._step_core_ledger = None
        self._ms_final_ledger = None
        self._mesh = mesh_lib.make_mesh(
            cfg.num_devices, cfg.mesh_axis, devices=self._devices
        )
        for d in (cfg.dtype, cfg.accum_dtype):
            if np.dtype(d).itemsize == 8 and not jax.config.jax_enable_x64:
                obs_log.info(
                    f"config requests {d}; enabling jax_enable_x64 "
                    "(process-global)"
                )
                jax.config.update("jax_enable_x64", True)
        self._dtype = jnp.dtype(cfg.dtype)
        self._accum_dtype = jnp.dtype(cfg.accum_dtype)
        # 64-bit accumulation can run the pair-packed gather + wide
        # reduce (ops/spmv.py:ell_contrib_pair) — TPUs have no native
        # f64, so the f64 work is confined to one add per slot + the
        # segment-sum. config.wide_accum: "auto" picks pair only on TPU
        # (native f64 gathers elsewhere are exact and fast).
        self._pair = self.resolve_pair(cfg)

    @staticmethod
    def resolve_pair(cfg) -> bool:
        """Whether this config runs the pair-packed wide accumulation —
        THE single resolution of ``wide_accum`` (shared with
        ops/device_build.plan_build so bench/CLI layout planning cannot
        drift from what the engine actually runs)."""
        wide = np.dtype(cfg.accum_dtype).itemsize == 8
        mode = cfg.wide_accum
        if mode == "auto":
            mode = "pair" if jax.default_backend() == "tpu" else "native"
        return wide and mode == "pair"

    @staticmethod
    def gather_z_item(cfg, pair: bool) -> int:
        """Bytes per gather-table lane for this config: pair tables
        carry two f32 planes (4 bytes/lane each), native-wide tables
        genuinely wide rows. Shared with plan_build (see resolve_pair)."""
        return max(np.dtype(cfg.dtype).itemsize,
                   4 if pair else np.dtype(cfg.accum_dtype).itemsize)

    @staticmethod
    def max_gather_lanes(pair: bool, z_item: int) -> int:
        """Widest fast-regime gather width for the dtype: pair tables
        fetch (hi|lo) rows so 64 lanes is the 512B-row bound; plain
        tables cap at 512B/z_item lanes, at most 128. THE single
        spelling — used for the actual gather width (_setup_ell) and
        for occupancy_span's 2^17-row span cap, which must stay in
        lockstep."""
        return 64 if pair else min(128, 512 // max(1, z_item))

    @staticmethod
    def is_widened_span(span, stripe_target: int, striped: bool) -> bool:
        """Whether a resolved stripe span is an occupancy-WIDENED one
        (occupancy_span exceeded the normal target) — the regime whose
        lane-group optimum differs (config.effective_lane_group). THE
        single spelling, shared with plan_build so bench/CLI-planned
        layouts cannot drift from what the engine builds."""
        return bool(striped and span is not None and span > stripe_target)

    @staticmethod
    def clamp_group_for_span(group: int, span: int) -> int:
        """Largest power-of-two group <= ``group`` whose packed slot
        words (src << log2(group) | sub) fit int32 at ``span`` —
        shared by plan_build and the host build so an occupancy-widened
        span can never make an explicit lane_group raise in the packer."""
        while group > 1 and (span + 1) * group > np.iinfo(np.int32).max:
            group //= 2
        return group

    # Partition-centric layout rule (ISSUE 6; Lakhotia et al.,
    # arXiv:1709.07122). A (partition, 128-dst block) cell must stay
    # DENSE: every nonempty cell still costs ceil-granular slot rows
    # (max over lane groups of ceil(cell_group_edges/group)), so below
    # ~512 expected edges per cell the ELL padding floor swamps the
    # stream savings (measured on the cost model: slots/edge 1.50 at
    # 256 edges/cell vs 1.13 at 1000 — docs/PERF_NOTES.md
    # "Partition-centric restage"). The window must also be
    # VMEM/cache-resident — the same ~12MB budget the pallas kernel
    # uses for its resident z.
    PART_MIN_CELL_EDGES = 512
    PART_MAX_WINDOW_BYTES = 12 << 20
    # Hard cap on partition count: each partition pads its rows to a
    # chunk multiple and unrolls one expand scatter into the step
    # program, so an undersized EXPLICIT span would explode memory and
    # compile time (the density-gated auto rule can't get here).
    MAX_PARTITIONS = 256

    @classmethod
    def partition_span(cls, n_padded: int, num_edges, z_item: int = 4) -> int:
        """Auto partition span for the partition-centric layout: the
        SMALLEST power-of-two span (multiple of 128, >= 2^15) whose
        expected (partition, dst-block) cell edges
        (``num_edges * span * 128 / n_padded^2``) reach
        ``PART_MIN_CELL_EDGES`` — smallest dense span = tightest gather
        window — subject to the window fitting
        ``PART_MAX_WINDOW_BYTES`` and the layout having at least two
        partitions. 0 = the partitioned form is not worth engaging
        (graph too small/sparse: its padding floor would exceed the
        stream savings). ``num_edges`` may be the RAW pre-dedup count
        (density threshold, like occupancy_span)."""
        if not num_edges or n_padded < (2 << 15):
            return 0
        span = 1 << 15
        # Respect the engine's partition-count cap from the start: the
        # finest span the rule may pick still keeps n_padded/span <=
        # MAX_PARTITIONS (an auto-resolved span must never trip the
        # setup's own explicit-span guard).
        while span * cls.MAX_PARTITIONS < n_padded:
            span *= 2
        # Every span that still leaves >= 2 partitions gets its density
        # check — including n_padded/2 itself, the coarsest layout the
        # rule may pick.
        while span * 2 <= n_padded:
            cells = num_edges * span * 128.0 / float(n_padded) ** 2
            if cells >= cls.PART_MIN_CELL_EDGES:
                break
            span *= 2
        else:
            return 0
        if span * 2 > n_padded or span * z_item > cls.PART_MAX_WINDOW_BYTES:
            return 0
        return span

    @staticmethod
    def partition_words24(span: int, group: int) -> bool:
        """Whether partition-local packed slot words
        (src << log2(group) | sub, sentinel = span << log2(group)) fit
        24 bits — the 3-byte planar slot stream
        (ops/spmv.py:pack_words24), 25% off the dominant per-slot HBM
        bytes. Falls back to int32 words when the alphabet is too
        wide; the layout is otherwise identical."""
        return span * group < (1 << 24)

    def _pallas_fallback(self, exc: PallasUnavailableError) -> None:
        """Downgrade the config to the NATIVE ell layout after a pallas
        probe failure (satellite of ISSUE 6): the rebuild re-packs with
        grouped lanes + slab scan instead of running the XLA path on
        the pallas-shaped group-1 non-slab arrays (the measured ~9%
        penalty, docs/PERF_NOTES.md "The Pallas kernel, settled").
        The requested kernel is kept in ``kernel_requested`` /
        ``layout_info()`` so bench JSON records what actually ran."""
        self._kernel_requested = "pallas"
        obs_log.warn(
            "pallas kernel unavailable on this backend; rebuilding with "
            "the NATIVE ell layout (grouped lanes + slab scan) — "
            f"{exc}"
        )
        self.config = self.config.replace(kernel="ell")

    def build_device(self, dg) -> "JaxTpuEngine":
        """Build from an on-device blocked-ELL graph
        (ops/device_build.DeviceEllGraph) — no bulk host->device
        transfer; see device_build's module docstring."""
        with obs_trace.span("engine/build", mode="device"):
            try:
                return self._build_device_impl(dg)
            except PallasUnavailableError as e:
                self._pallas_fallback(e)
                # A pallas device graph is group=1/single-stripe by
                # construction; the native rebuild reuses it with the
                # slab scan engaged (dense ranks). The group-1 padding
                # stays — regrouping needs the raw edges, which a
                # device graph no longer holds.
                return self._build_device_impl(dg)

    def _build_device_impl(self, dg) -> "JaxTpuEngine":
        from pagerank_tpu.ops.device_build import DeviceEllGraph

        assert isinstance(dg, DeviceEllGraph)
        cfg = self.config
        self.graph = dg
        self._begin_build()
        if (cfg.kernel if cfg.kernel != "auto" else "ell") not in ("ell", "pallas"):
            raise ValueError("build_device supports the ell/pallas kernels only")
        group = getattr(dg, "group", 1)
        stripe_size = getattr(dg, "stripe_size", 0)
        if cfg.kernel == "pallas" and group > 1:
            raise ValueError(
                "kernel='pallas' needs a group=1 device graph; pass "
                "group=1 to build_ell_device"
            )
        if cfg.kernel == "pallas" and stripe_size and not cfg.partition_span:
            raise ValueError(
                "kernel='pallas' without partition_span needs a "
                "single-stripe device graph; pass stripe_size=0 to "
                "build_ell_device (or set partition_span to run the "
                "partitioned kernel)"
            )
        part = int(cfg.partition_span)
        if part:
            part = min(part, dg.n_padded) if dg.n_padded else part
            # The partition-centric layout consumes a device graph
            # whose STRIPES are the partitions (the shared planner —
            # ops/device_build.plan_build — sizes the build that way).
            if (stripe_size or dg.n_padded) != part:
                raise ValueError(
                    f"partition_span {part} needs a device graph built "
                    f"with stripe_size={part} (got "
                    f"{stripe_size or dg.n_padded}); plan the build via "
                    "ops/device_build.plan_build"
                )
        sz = stripe_size or dg.n_padded
        allowed = self.occupancy_span(
            self._stripe_max(), dg.n_padded, dg.num_edges, self._pair,
            self.gather_z_item(cfg, self._pair),
        )
        if sz > allowed and not part:
            # (Partitioned layouts gather per-chunk WINDOWS — the
            # fast-regime bound applies to the window, not the span.)
            obs_log.warn(
                f"device-built graph has stripe span "
                f"{sz} > {allowed} — the gather runs outside "
                "the fast regime (~4x slower SpMV); rebuild with "
                f"stripe_size<={allowed}"
            )

        n, pad = dg.n, dg.n_padded - dg.n
        # Masks arrive in ORIGINAL id space; permute to relabeled space
        # and pad (on device — these are [n] bool arrays).
        mass = dg.dangling_mask[dg.perm]
        zin = dg.zero_in_mask[dg.perm]
        zpad = jnp.zeros(pad, bool)
        self._perm = np.asarray(jax.device_get(dg.perm))
        # Compute 1/out_degree directly in the widest dtype the solver
        # will use — the pair-packed path splits it exactly from this.
        inv_dtype = (
            self._accum_dtype
            if self._accum_dtype.itemsize > self._dtype.itemsize
            else self._dtype
        )
        inv = graph_mod.inv_out_degree(dg.out_degree, jnp, dtype=inv_dtype)
        inv_out_rel = jnp.concatenate(
            [inv[dg.perm], jnp.zeros(pad, inv_dtype)]
        )
        src_in, w_in, rb_in = dg.src, dg.weight, dg.row_block
        if part and not isinstance(src_in, (list, tuple)):
            # A single-partition graph (span == n_padded) arrives as
            # bare arrays; the partitioned setup expects lists.
            src_in, w_in, rb_in = [src_in], [w_in], [rb_in]
        self._setup_ell(
            src_in, w_in, rb_in,
            jnp.concatenate([mass, zpad]),
            jnp.concatenate([zin, zpad]),
            jnp.concatenate([jnp.ones(n, bool), zpad]),
            n=n, n_state=dg.n_padded, num_blocks=dg.num_blocks,
            inv_out_rel=inv_out_rel, group=group,
            stripe_size=stripe_size or None, partition_span=part,
        )
        # The slot arrays are donated to the engine: _setup_ell derives
        # its sentinel-ized copies, and keeping the originals referenced
        # from dg would pin a second full-size set of [rows, 128] arrays
        # in HBM for the engine's lifetime. The structural fingerprint
        # (snapshot validation) hashes those arrays, so capture it
        # first — it caches on the graph (one cheap reduction pass).
        dg.fingerprint()
        dg.src = dg.weight = dg.row_block = None
        return self

    def build(self, graph: Graph) -> "JaxTpuEngine":
        with obs_trace.span("engine/build", mode="host"):
            try:
                return self._build_impl(graph)
            except PallasUnavailableError as e:
                self._pallas_fallback(e)
                return self._build_impl(graph)

    def _cached_pack(self, key, make):
        """Resolve one host ELL pack through the caller-owned
        ``pack_cache`` (see ``__init__``); pack fresh when no cache is
        wired or the resolved-plan key misses."""
        if self._pack_cache is None:
            return make()
        pack = self._pack_cache.get(key)
        if pack is None:
            pack = make()
            self._pack_cache[key] = pack
        else:
            obs_log.info(
                f"reusing cached ELL pack for resolved plan "
                f"{key[0]}(span/group/deal={key[2:]})"
            )
        return pack

    def _build_impl(self, graph: Graph) -> "JaxTpuEngine":
        cfg = self.config
        self.graph = graph
        self._begin_build()
        axis = cfg.mesh_axis
        ndev = self._mesh.devices.size
        mesh = self._mesh

        dtype = self._dtype
        accum = self._accum_dtype

        kernel = cfg.kernel if cfg.kernel != "auto" else "ell"
        self._kernel = kernel

        n = graph.n
        rep = mesh_lib.replicated(self._mesh)
        e_shard = mesh_lib.edge_sharding(self._mesh)

        # Reference mode: post-repair dangUrls (uncrawled targets).
        # Textbook mode: standard dangling definition (out_degree == 0).
        mass_mask = (
            graph.dangling_mask
            if cfg.semantics == "reference"
            else graph.out_degree == 0
        )
        zero_in = graph.zero_in_mask

        if kernel in ("ell", "pallas") and cfg.partition_span:
            # Partition-centric layout (ISSUE 6): the packer's stripes
            # ARE the source partitions — the sub-binning permutation is
            # absorbed into its one composite-key sort. kernel='pallas'
            # runs the same layout through the hand kernel
            # (ops/pallas_spmv.ell_contrib_pallas_partitioned, ISSUE 16)
            # on plain group-1 slot ids.
            psz = int(cfg.partition_span)
            n_padded = -(-n // 128) * 128
            group = (
                1 if kernel == "pallas"
                else self.clamp_group_for_span(
                    cfg.lane_group or cfg.effective_lane_group(False),
                    psz,
                )
            )
            pack = self._cached_pack(
                ("striped", id(graph), min(psz, max(128, n_padded)),
                 group, 0),
                lambda: ell_lib.ell_pack_striped(
                    graph, stripe_size=min(psz, max(128, n_padded)),
                    group=group,
                ),
            )
            self._pack = pack
            self._perm = pack.perm
            n_state = pack.n_padded
            pad = n_state - n
            mass_mask = np.concatenate(
                [mass_mask[pack.perm], np.zeros(pad, bool)]
            )
            zero_in = np.concatenate(
                [zero_in[pack.perm], np.zeros(pad, bool)]
            )
            valid = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
            inv = graph_mod.inv_out_degree(graph.out_degree)
            inv_out_rel = np.concatenate([inv[pack.perm], np.zeros(pad)])
            self._setup_ell(
                pack.src, pack.weight, pack.row_block,
                mass_mask, zero_in, valid,
                n=n, n_state=n_state, num_blocks=pack.num_blocks,
                inv_out_rel=inv_out_rel, group=group,
                partition_span=min(psz, max(128, n_padded)),
            )
            if self._pack_cache is None:
                pack.src, pack.weight, pack.row_block = [], [], []
            return self

        if kernel in ("ell", "pallas"):
            stripe_max = self._stripe_max()
            n_padded = -(-n // 128) * 128
            # The pallas kernel consumes plain source ids; group only on
            # the XLA ell path. Stripedness is known before packing and
            # flips the pair-mode optimum (config.effective_lane_group).
            striped = n_padded > stripe_max
            span = (
                self.occupancy_span(
                    self._stripe_target(), n_padded, graph.num_edges,
                    self._pair, self.gather_z_item(cfg, self._pair),
                )
                if striped else None
            )
            group = (
                1 if kernel == "pallas"
                else cfg.effective_lane_group(
                    self._pair, striped=striped,
                    widened=self.is_widened_span(
                        span, self._stripe_target(), striped
                    ),
                )
            )
            # vs_bounded: the packer deals dst blocks across the mesh's
            # device ranges by capacity-constrained LPT
            # (ops/ell.deal_block_order) so the dst-partitioned rows
            # balance (_setup_ell_vs_bounded).
            deal = ndev if (cfg.vertex_sharded and cfg.vs_bounded) else 0
            if striped:
                # An occupancy-widened span can push an explicit large
                # lane_group past the packed-word int32 bound; clamp
                # like plan_build instead of letting the packer raise.
                grp = self.clamp_group_for_span(group, span)
                if grp != group:
                    obs_log.info(
                        f"lane group clamped to {grp} "
                        f"for stripe span {span}"
                    )
                    group = grp
                pack = self._cached_pack(
                    ("striped", id(graph), span, group, deal),
                    lambda: ell_lib.ell_pack_striped(
                        graph, stripe_size=span, group=group,
                        block_deal=deal,
                    ),
                )
                srcs, weights, rbs = pack.src, pack.weight, pack.row_block
                stripe_size = pack.stripe_size
            else:
                pack = self._cached_pack(
                    ("flat", id(graph), group, deal),
                    lambda: ell_lib.ell_pack(graph, group=group,
                                             block_deal=deal),
                )
                srcs, weights, rbs = [pack.src], [pack.weight], [pack.row_block]
                stripe_size = None
            self._pack = pack
            self._perm = pack.perm
            n_state = pack.n_padded  # device rank vector length (padded)
            pad = n_state - n
            # Relabel + pad masks; padding lanes are all-zero.
            mass_mask = np.concatenate([mass_mask[pack.perm], np.zeros(pad, bool)])
            zero_in = np.concatenate([zero_in[pack.perm], np.zeros(pad, bool)])
            valid = np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])
            inv = graph_mod.inv_out_degree(graph.out_degree)
            inv_out_rel = np.concatenate([inv[pack.perm], np.zeros(pad)])
            self._setup_ell(
                srcs, weights, rbs,
                mass_mask, zero_in, valid,
                n=n, n_state=n_state, num_blocks=pack.num_blocks,
                inv_out_rel=inv_out_rel,
                stripe_size=stripe_size, group=group,
            )
            # The engine's sentinel-ized slot copies now live on device;
            # drop the host-side arrays (float64 weights are 8B/slot —
            # multi-GB at the scales the striped layout targets). Stats
            # survive in _pack_stats for introspection.
            self._pack_stats = {
                "num_rows": pack.num_rows,
                "padding_ratio": pack.padding_ratio,
                "n_stripes": getattr(pack, "n_stripes", 1),
            }
            if self._pack_cache is None:
                if isinstance(pack, ell_lib.StripedEllPack):
                    pack.src, pack.weight, pack.row_block = [], [], []
                else:
                    pack.src = pack.weight = pack.row_block = None
            return self
        else:
            self._pack = None
            self._perm = None
            n_state = n
            shards = partition.partition_edges(graph, ndev, weight_dtype=dtype)
            self._src = jax.device_put(shards.src, e_shard)
            self._dst = jax.device_put(shards.dst, e_shard)
            self._w = jax.device_put(shards.weight, e_shard)

            def sharded_contrib(r, src, dst, w):
                part = spmv.edge_contrib_segment_sum(r, src, dst, w, n, accum)
                return jax.lax.psum(part, axis)

            contrib_fn = shard_map(
                sharded_contrib,
                mesh=mesh,
                in_specs=(P(), P(axis), P(axis), P(axis)),
                out_specs=P(),
            )
            contrib_args = (self._src, self._dst, self._w)
            valid = np.ones(n, bool)  # no padding in coo state
            self._layout = {
                "form": "coo", "group": None, "gather_width": None,
                "n_stripes": 1, "stripe_span": n_state,
                "partition_span": 0, "chunk": None, "pair": False,
                "stream_dtype": None,
            }
            self._finalize(
                contrib_fn, contrib_args, mass_mask, zero_in, valid, n, n_state
            )
            return self

    GATHER_WIDTH = 8  # minimum; _gather_width widens for large tables
    # Unrolled-stripe program-size budget: the per-stripe contrib code
    # unrolls into the HLO, and past this many "units" (a pair stripe
    # counts double — two z planes) the serialized program exceeds the
    # remote-compile request limit (measured: 8 pair stripes = 16 units
    # -> HTTP 413; 8 plain stripes = 8 units compile fine). Beyond it
    # EVERY run form routes through the multi-dispatch machinery
    # (_setup_multi_dispatch; run_fused/run_fused_tol by delegation).
    SCAN_STRIPE_UNITS = 12

    @staticmethod
    def stripe_limits(z_item: int, pair: bool):
        """(stripe_max, stripe_target) for a gather table of ``z_item``
        bytes/lane (pair tables carry 2x lanes/row).

        stripe_max: largest vertex range worth keeping in ONE stripe — a
        ~33MB f32 gather table (8.4M vertices). Gather throughput
        degrades with table bytes (0.345 Gslot/s at 8MB -> 0.29 at 33MB
        on v5e) then cliffs ~2x at 67MB (spills XLA's working set), at
        which point striping wins despite its padding cost. Measured at
        R-MAT scale 23/25: single stripe beats 4.2M stripes below this
        bound, loses above it.

        stripe_target: span to use once striping IS needed. Plain
        dtypes: the full bound (8.4M f32) — the r2 half-bound
        preference (4.2M beat 8.4M, 2.09e8 vs 1.64e8 at scale 25)
        INVERTED under the current code (r3 re-sweep: 8.4M spans beat
        4.2M — scale 25: 3.38e8 vs 3.14e8, scale 24: 3.49e8 vs
        3.32e8). Pair: 4.2M, HALF its single-stripe bound — dense
        8.4M pair stripes measured 0.87e8 vs 1.84e8 at scale 25, so
        once striping is unavoidable pair wants narrow spans (the
        sparse exception is occupancy_span's widening, which composes
        on top of this target). Same meta-lesson throughout
        (PERF_NOTES "Accumulation dtypes"): re-sweep layout optima on
        current code.

        Shared by the engine and bench.py so the two can't diverge."""
        if pair:
            # Single-stripe bound 8.4M (r3): a gw-64 pair table is 2^17
            # rows at that span — ONE 67MB table measured 19% faster
            # than 2x4.2M stripes at scale 23 (2.58e8 vs 2.16e8
            # edges/s/chip; no striping overhead beats the working-set
            # penalty). The STRIPED target stays 4.2M: dense 8.4M pair
            # stripes measured 0.87e8 at scale 25 — once striping is
            # unavoidable, narrow spans win for pair, and the
            # occupancy_span widening handles the sparse exception.
            return 64 << 17, 32 << 17
        smax = (256 // z_item) * (1 << 17)
        return smax, smax

    def _stripe_max(self) -> int:
        z_item = self.gather_z_item(self.config, self._pair)
        return self.stripe_limits(z_item, self._pair)[0]

    def _stripe_target(self) -> int:
        z_item = self.gather_z_item(self.config, self._pair)
        return self.stripe_limits(z_item, self._pair)[1]

    # Expected edges per (stripe, 128-dst block) cell below which a
    # stripe span doubles (see occupancy_span): <= 128 means the
    # typical cell fills at most ONE grouped row, so widening the span
    # collapses per-cell row floors instead of adding real rows.
    OCC_DOUBLE_CELL_EDGES = 128

    @classmethod
    def occupancy_span(cls, span: int, n_padded: int, num_edges,
                       pair: bool, z_item: int = 4) -> int:
        """Occupancy-aware stripe span for SPARSE graphs (VERDICT r2
        #1). Striping multiplies the (stripe, 128-dst block) cell
        count, and every nonempty cell costs at least one 128-slot row
        — on a sparse graph (low edge factor) that floor dominates: at
        R-MAT scale 26 / ef 8, 4.2M-span stripes average 64 edges per
        cell, i.e. ~2x slot padding.

        Rule: DOUBLE the span while the expected edges per cell
        (``num_edges * span * 128 / n_padded^2``) is <= 128 — the
        point where a typical cell at most fills one row — and the
        doubled gather table still fits the fast regime's hard 2^17-row
        bound at the dtype's widest gather (64 lanes for pair tables,
        512B/z_item capped at 128 otherwise), i.e. span caps at 8.4M
        pair / 16.8M f32. Measured at scale 26 ef 8 (10 iters, honest
        fence): pair 1.52e8 -> 1.98e8 (4.2M -> 8.4M; 16.8M = 2^18 rows
        collapses to 0.78e8), f32 2.71e8 -> 3.01e8 -> 3.95e8 (4.2M ->
        8.4M -> 16.8M). On DENSE graphs the rule keeps the measured
        optima unchanged (scale 25 ef 16: cell edges 253 at 4.2M; the
        wider pair span measured 0.87e8 vs 1.84e8 there — no padding
        to win back, pure working-set loss). docs/PERF_NOTES.md
        "Occupancy-aware stripes".

        ``num_edges`` may be the RAW (pre-dedup) count — the rule is a
        threshold on an order-of-magnitude density estimate. None (or
        a non-striped layout) returns ``span`` unchanged.
        """
        if num_edges is None or n_padded <= span or span <= 0:
            return span
        bound = cls.max_gather_lanes(pair, z_item) << 17
        while (
            span * 2 <= bound
            and span < n_padded
            and num_edges * span * 128 / float(n_padded) ** 2
                <= cls.OCC_DOUBLE_CELL_EDGES
        ):
            span *= 2
        return min(span, n_padded)

    @staticmethod
    def _dense_ranks_device(rb, num_blocks: int):
        """Device-side counterpart of ops/ell.dense_block_ranks —
        (ranks, present_ids, num_present, is_prefix) for a sorted
        block-id device array. ONE spelling for every device-built
        layout (plain slab and partitioned). cumsum dtype pinned:
        cumsum of bool follows numpy's default-int promotion — int64
        under the pair config's x64 flip (same class as PTC006)."""
        present = jnp.zeros(num_blocks, bool).at[rb].set(True)
        pc = max(1, int(present.sum()))
        rank_of = jnp.cumsum(present, dtype=jnp.int32) - 1
        ranks = rank_of[rb]
        ids = jnp.nonzero(
            present, size=pc, fill_value=num_blocks - 1
        )[0].astype(jnp.int32)
        prefix = bool(jax.device_get(ids[-1]) == pc - 1)
        return ranks, ids, pc, prefix

    @staticmethod
    def _gather_width(n_state: int, max_width: int = 128) -> int:
        """XLA's fast TPU gather regime (measured on v5e, see
        scripts/probe_gather.py) needs the reshaped (rows, width) table to
        have <= 2**17 rows and <= 512-byte rows; outside it throughput
        drops ~4x. Widen the row until the row count fits, capping at
        ``max_width`` lanes (128 f32 lanes = 512B for the plain table; 64
        for the pair-packed table whose rows carry 2x lanes)."""
        width = 8
        while width < max_width and n_state // width > (1 << 17):
            width *= 2
        return width

    def _autotune_chunk(self, *args, **kw):
        """Timing shim: record the autotune wall under
        ``build_timings["autotune_s"]`` for EVERY caller (bench.py's
        --build-only breakdown reads it — the autotune was historically
        the largest engine-side build line), then delegate."""
        import time as _time

        t0 = _time.perf_counter()
        try:
            with obs_trace.span("engine/autotune"):
                return self._autotune_chunk_impl(*args, **kw)
        finally:
            self.build_timings["autotune_s"] = _time.perf_counter() - t0

    def _autotune_chunk_impl(self, cands, stripe_rows_dev, sz, z_item, gw,
                             group, pair, accum, num_present, ndev,
                             part=None):
        """Pick the scan chunk for the ELL gather by TIMING the candidate
        chunks on the largest stripe's real slot arrays.

        ``part`` (partition-centric layouts): dict with the windowed
        op's geometry — window_rows, table_len, table_dt, the placed
        slot array, a ``bases_for(c)`` callback building the per-chunk
        (window, rank) base arrays for a candidate, and the pair
        count. The same compile-all-then-time protocol runs on the
        windowed form of the op so chunk/partition geometry is tuned
        by measurement exactly like the plain form (ISSUE 6).

        Rationale (measured on v5e): below ~16MB of gather table the
        chunk barely matters (mild preference for larger chunks), so the
        LARGEST candidate is returned untimed. Above it, XLA's
        fusion/working-set behavior flips the
        winner between geometries in ways static rules mispredict (33MB
        intermediates win at sz=8.4M/gw64 but lose at sz=4.2M/gw32 with
        group=64), so ~seconds of build-time timing buys back minutes of
        iteration time. Runs only on the single-device mesh (the
        multi-device case times under shard_map semantics the probe
        can't cheaply reproduce) and on TPU backends."""
        cands = [c for c in cands if c <= max(stripe_rows_dev)]
        if not cands:
            return 256
        if sz * z_item < (1 << 24) or len(cands) < 2:
            # Small tables are chunk-insensitive with a mild preference
            # for larger chunks (fewer scan steps) — measured 96 vs 98
            # ms/iter at R-MAT scale 21.
            return cands[-1]
        if ndev != 1 or jax.default_backend() != "tpu":
            # Can't time representatively: take the ~33MB-intermediate
            # candidate, the safe default for big tables.
            return cands[0]
        import functools
        import time as _time

        from pagerank_tpu.utils import compile_cache

        # The winner is deterministic per (hardware, geometry): persist
        # it next to the compile cache so repeat builds skip the ~8s of
        # candidate timing (measured scale 23 — the autotune was the
        # single largest line in the build breakdown, docs/PERF_NOTES.md
        # "Device-build cost").
        tune_key = "chunk:" + ":".join(map(str, (
            jax.devices()[0].device_kind, sz, z_item, gw, group, pair,
            jnp.dtype(accum).name, max(stripe_rows_dev), tuple(cands),
            # Partitioned-window geometry tunes separately from the
            # plain form at the same table size.
            0 if part is None else part["window_rows"],
            0 if part is None else part["pairs"],
        )))
        cached = compile_cache.tuning_get(tune_key)
        if cached in cands:
            return cached

        if part is not None:
            rows = stripe_rows_dev[0]
            z_a = jnp.ones(part["table_len"], part["table_dt"])

            def part_fn(c):
                # num_blocks is unused in compact (num_present) mode;
                # pass the pair count for shape sanity. The bases ride
                # as a POSITIONAL arg of the jitted wrapper so the
                # compiled executable's call signature stays flat.
                return jax.jit(lambda z, s, r, b: spmv.ell_contrib(
                    z, s, r, part["pairs"], accum_dtype=accum,
                    gather_width=gw, chunk_rows=c, group=group,
                    num_present=part["pairs"],
                    window_rows=part["window_rows"], chunk_bases=b,
                ))

            compiled = []
            for c in cands:
                if rows % c:
                    continue
                rb_c, bases_c = part["bases_for"](c)
                try:
                    compiled.append((c, part_fn(c).lower(
                        z_a, part["src_dev"], rb_c, bases_c
                    ).compile(), rb_c, bases_c))
                except Exception:
                    continue
            best, best_t = cands[0], None
            for c, exe, rb_c, bases_c in compiled:
                try:
                    out = exe(z_a, part["src_dev"], rb_c, bases_c)
                    jax.device_get(jnp.sum(out))
                    t0 = _time.perf_counter()
                    for _ in range(3):
                        out = exe(z_a, part["src_dev"], rb_c, bases_c)
                    jax.device_get(jnp.sum(out))
                    dt = (_time.perf_counter() - t0) / 3
                except Exception:
                    continue
                if best_t is None or dt < best_t:
                    best, best_t = c, dt
            if best_t is not None:
                compile_cache.tuning_put(tune_key, best)
            return best

        s_big = int(np.argmax(stripe_rows_dev))
        src_a, rb_a = self._src[s_big], self._row_block[s_big]
        rows = stripe_rows_dev[s_big]
        Ps = num_present[s_big]
        if pair:
            z_args = (
                jnp.ones(sz + gw, jnp.float32),
                jnp.zeros(sz + gw, jnp.float32),
            )
            op = functools.partial(
                spmv.ell_contrib_pair, accum_dtype=accum, gather_width=gw,
                group=group, num_present=Ps,
            )
        else:
            z_args = (jnp.ones(sz + gw, jnp.dtype(f"float{z_item * 8}")),)
            op = functools.partial(
                spmv.ell_contrib, accum_dtype=accum, gather_width=gw,
                group=group, num_present=Ps,
            )
        # Compile EVERY candidate before timing ANY: lowering + compile
        # is host/remote-service work, so on a cache-miss build it
        # overlaps the slot scatter and placement transfers still
        # queued on the device (the in-order queue makes that legal —
        # the first timed execution simply lands behind them), instead
        # of serializing compile -> time -> compile -> time as the old
        # interleaved loop did.
        compiled = []
        for c in cands:
            if rows % c:
                continue
            # num_blocks is unused by the ops in compact (num_present)
            # mode; pass Ps for shape sanity.
            fn = jax.jit(functools.partial(
                op, num_blocks=Ps, chunk_rows=c
            ))
            try:
                compiled.append(
                    (c, fn.lower(*z_args, src_a, rb_a).compile())
                )
            except Exception:  # lowering/compile issue: skip candidate
                continue
        best, best_t = cands[0], None
        for c, exe in compiled:
            try:
                out = exe(*z_args, src_a, rb_a)
                jax.device_get(jnp.sum(out))  # settle (drain the queue)
                t0 = _time.perf_counter()
                for _ in range(3):
                    out = exe(*z_args, src_a, rb_a)
                jax.device_get(jnp.sum(out))
                dt = (_time.perf_counter() - t0) / 3
            except Exception:  # OOM at execute: skip candidate
                continue
            if best_t is None or dt < best_t:
                best, best_t = c, dt
        if best_t is not None:
            compile_cache.tuning_put(tune_key, best)
        return best

    def _setup_ell(self, src_slots, w_slots, row_block, mass_mask, zero_in,
                   valid, *, n, n_state, num_blocks, inv_out_rel,
                   stripe_size=None, group=1, partition_span=0):
        """Common ELL-path setup from slot arrays (host numpy or device
        jnp) — pads rows to the per-device chunk multiple, places arrays
        over the mesh, builds the sharded contribution fn.

        The per-slot weights are NOT placed on device: the solver
        pre-scales the rank vector by ``inv_out_rel`` each iteration
        (ops/spmv.py:ell_contrib docstring), so ``w_slots`` is consumed
        here only to locate inert slots (weight 0: ELL padding, duplicate
        edges), which are re-pointed at the zero sentinel ``n_state``.
        Half the slot bytes stream from HBM per iteration as a result.

        ``partition_span``: the slot lists are per-PARTITION (packed at
        stripe_size=partition_span) and the whole setup routes to the
        partition-centric layout (:meth:`_setup_ell_partitioned`).
        """
        if partition_span:
            self._setup_ell_partitioned(
                src_slots, w_slots, row_block, mass_mask, zero_in, valid,
                n=n, n_state=n_state, num_blocks=num_blocks,
                inv_out_rel=inv_out_rel, psz=int(partition_span),
                group=group,
            )
            return
        cfg = self.config
        mesh = self._mesh
        axis = cfg.mesh_axis
        ndev = mesh.devices.size
        dtype = self._dtype
        accum = self._accum_dtype
        pair = self._pair

        # Normalize to the striped form: lists of per-stripe slot arrays
        # (ops/ell.py:StripedEllPack). Single-stripe packs arrive as bare
        # arrays; stripe_size None means one stripe spanning n_state.
        if not isinstance(src_slots, (list, tuple)):
            src_slots, w_slots, row_block = [src_slots], [w_slots], [row_block]
        sz = int(stripe_size) if stripe_size else n_state
        n_stripes = len(src_slots)
        assert n_stripes == -(-n_state // sz), (n_stripes, n_state, sz)

        # 1/out_degree in RELABELED space, zero-padded to n_state. Kept
        # (and the prescale multiply performed) in accum_dtype when that
        # is wider than the rank dtype, so per-edge products carry accum
        # precision into the segment-sum exactly as the per-slot-weight
        # form did.
        z_dtype = accum if jnp.dtype(accum).itemsize > jnp.dtype(dtype).itemsize else dtype
        z_item = 4 if pair else jnp.dtype(z_dtype).itemsize
        # Cap at 128 lanes: array lengths are only guaranteed multiples
        # of 128, and the reshape contract needs gw | sz.
        gw = max(
            self.GATHER_WIDTH,
            self._gather_width(sz, self.max_gather_lanes(pair, z_item)),
        )
        want_pallas = cfg.kernel == "pallas"
        if want_pallas and n_stripes > 1:
            obs_log.info(
                "kernel='pallas' cannot run the striped "
                "large-graph layout; using the XLA ell path"
            )
            want_pallas = False
        self._kernel = "pallas" if want_pallas else "ell"
        shard2d = jax.sharding.NamedSharding(mesh, P(axis, None))
        e_shard = mesh_lib.edge_sharding(mesh)

        # Chunk the gather so its per-chunk intermediates — the (chunk,
        # 128, gw[, 2]) gather rows and the (chunk, 128, group) grouped-
        # lane one-hot — stay bounded. Small tables (< ~16MB) are
        # insensitive to the chunk; large ones interact with XLA's
        # fusion/working-set heuristics in ways simple rules mispredict
        # (measured on v5e: at sz=8.4M the ~33MB rule wins 4x, at
        # sz=4.2M with group=64 a 67MB one-hot beats the 33MB one), so
        # for big tables the build TIMES the candidate chunks on the
        # real arrays and keeps the winner (_autotune_chunk). Rows are
        # padded to the largest candidate so every candidate divides.
        # The pallas kernel instead streams fixed 256-row chunks (its
        # VMEM scratch is sized by this).
        pallas_chunk = 256
        fetch_lanes = gw * (2 if pair else 1)  # pair gathers (hi|lo) rows
        chunk_cands = sorted({
            max(256, 8192 * 8 // max(fetch_lanes, group)),
            max(256, 8192 * 8 // fetch_lanes),
            max(256, 32768 * 8 // fetch_lanes),
        })
        cand_max = chunk_cands[-1]
        if cfg.vertex_sharded and cfg.vs_bounded:
            self._setup_ell_vs_bounded(
                src_slots, w_slots, row_block, mass_mask, zero_in, valid,
                n=n, n_state=n_state, inv_out_rel=inv_out_rel, sz=sz,
                n_stripes=n_stripes, gw=gw, group=group, z_dtype=z_dtype,
                z_item=z_item, chunk_cands=chunk_cands,
            )
            return
        xp = np if isinstance(src_slots[0], np.ndarray) else jnp
        self._src, self._row_block, stripe_rows_dev = [], [], []
        present_ids, num_present, prefix_flags = [], [], []
        rep = mesh_lib.replicated(mesh)
        log2g = group.bit_length() - 1
        for s in range(n_stripes):
            # Inert slots (weight 0) -> per-stripe sentinel index ``sz``
            # (shifted into the packed-word form when grouped); real
            # slots keep their stripe-local source id. Row padding
            # (added below) is all-inert. presentinel device builds
            # (with_weights=False) arrive already sentinel-ized with no
            # weight plane at all.
            sent = np.int32(sz << log2g)
            if w_slots[s] is None:
                ss = src_slots[s]
            else:
                ss = xp.where(w_slots[s] != 0, src_slots[s], sent)
            rows_s = ss.shape[0]
            rb = row_block[s]
            if want_pallas:
                # The pallas kernel consumes GLOBAL block ids (it does
                # its own slab RMW against the full output). The ids
                # placeholder keeps the contrib-arg shape for the
                # probe-failure fallback to the non-slab ell path.
                ids = jnp.zeros(1, jnp.int32)
                pcount, prefix = num_blocks, True
            else:
                # Dense block RANKS per stripe: the slab-scan accumulator
                # (ops/spmv.py:_chunked_block_sum) needs gap-free ids so
                # a chunk's rank span is bounded by its row count; the
                # compact (pcount, 128) result is expanded to blocks
                # below.
                if xp is np:
                    rb, ids, pcount, prefix = ell_lib.dense_block_ranks(
                        rb, num_blocks
                    )
                else:
                    rb, ids, pcount, prefix = self._dense_ranks_device(
                        rb, num_blocks
                    )
                ids = jax.device_put(jnp.asarray(ids), rep)
            rows_per_dev = -(-max(1, rows_s) // ndev)
            if want_pallas:
                chunk_rows = pallas_chunk
            elif rows_per_dev >= cand_max:
                chunk_rows = cand_max
            else:
                # Round small stripes up to a power of two so every
                # (power-of-two) chunk candidate divides them.
                chunk_rows = 1 << (rows_per_dev - 1).bit_length()
            pad_multiple = ndev * chunk_rows
            ss = _pad_rows(ss, pad_multiple, sent, xp)
            pad_id = max(0, (num_blocks if want_pallas else pcount) - 1)
            rb = _pad_rows(rb, pad_multiple, pad_id, xp)
            self._src.append(jax.device_put(ss, shard2d))
            self._row_block.append(jax.device_put(rb, e_shard))
            stripe_rows_dev.append(ss.shape[0] // ndev)
            present_ids.append(ids)
            num_present.append(pcount)
            prefix_flags.append(prefix)

        # Whether the placed arrays follow the slab contract (dense
        # ranks); pallas-built arrays keep global ids, and the probe
        # fallback below must run them non-slab.
        arrays_slab = not want_pallas
        if want_pallas:
            ell_chunks = [pallas_chunk] * n_stripes
        else:
            chosen = self._autotune_chunk(
                chunk_cands, stripe_rows_dev, sz, z_item, gw, group, pair,
                accum, num_present, ndev,
            )
            # Per-stripe: the chosen chunk, clamped to the stripe's
            # padded per-device rows (short stripes run one chunk;
            # divisibility holds because padded rows are a multiple of
            # cand_max or a power of two >= the clamped chunk).
            ell_chunks = [min(chosen, r) for r in stripe_rows_dev]
        self._layout = {
            "form": "step",
            "group": group,
            "gather_width": gw,
            "n_stripes": n_stripes,
            "stripe_span": sz,
            "partition_span": 0,
            "chunk": max(ell_chunks) if ell_chunks else None,
            "pair": bool(pair),
            "stream_dtype": None,
        }

        inv_out_rel = xp.asarray(inv_out_rel)
        if inv_out_rel.dtype != z_dtype:
            inv_out_rel = inv_out_rel.astype(z_dtype)
        if not cfg.vertex_sharded:
            self._inv_out = jax.device_put(
                inv_out_rel, mesh_lib.replicated(mesh)
            )

        # Very-many-stripe layouts: the unrolled Python loop duplicates
        # the whole chunked-gather program per stripe and its serialized
        # HLO exceeds remote-compile request limits around 8 pair
        # stripes (measured: R-MAT scale-25 f64-pair, HTTP 413). Past
        # the threshold EVERY public run form routes through the
        # multi-dispatch machinery (_setup_multi_dispatch) — one small
        # exact-shape executable per stripe, the fast top-level gather
        # lowering kept, async dispatch pipelining hiding per-dispatch
        # cost: _device_step directly, run_fused / run_fused_tol by
        # delegation to run_fused_chunked. The unrolled single-program
        # step below is still CONSTRUCTED (it is the nominal definition
        # the multi-dispatch path is tested against at toy scale) but
        # never compiled at real scale; an in-program scan-over-stripes
        # fallback used to exist for the fused forms and was removed in
        # r3 — it lost the fast gather (0.91e8 vs 3.33e8 edges/s/chip
        # at scale 24) and its uniform restack exceeded single-chip HBM
        # at scale-25 pair (docs/PERF_NOTES.md "Scan bodies defeat the
        # fast gather").
        multi_dispatch = (
            not want_pallas
            and n_stripes * (2 if pair else 1) > self.SCAN_STRIPE_UNITS
        )

        def accumulate_stripes(zs, rest):
            """Per-device stripe loop — THE one spelling of the
            z-slice + blocked-ELL gather + compact-sum scatter body,
            shared by the replicated contrib fn and the vertex-sharded
            step so the two modes cannot drift (their bit-equality is a
            tested contract). ``rest`` is (src, row_block, ids) per
            stripe; returns the [num_blocks, 128] partial accumulator
            (cross-device merge is the caller's: psum or
            psum_scatter)."""
            total = None
            for s in range(n_stripes):
                src, rb, ids = rest[3 * s : 3 * s + 3]
                z_s = [
                    jnp.concatenate(
                        [z[s * sz : (s + 1) * sz],
                         jnp.zeros(gw, z.dtype)]
                    )
                    for z in zs
                ]
                # Arrays built for the pallas kernel carry GLOBAL
                # block ids (slab's dense-rank contract doesn't
                # hold) — the probe-failure fallback runs them in
                # full non-slab mode.
                Ps = num_present[s] if arrays_slab else None
                if pair:
                    part = spmv.ell_contrib_pair(
                        z_s[0], z_s[1], src, rb, num_blocks,
                        accum_dtype=accum, gather_width=gw,
                        chunk_rows=ell_chunks[s], group=group,
                        num_present=Ps,
                    )
                else:
                    part = spmv.ell_contrib(
                        z_s[0], src, rb, num_blocks,
                        accum_dtype=accum, gather_width=gw,
                        chunk_rows=ell_chunks[s], group=group,
                        num_present=Ps,
                    )
                # Expand the compact (Ps, 128) sums to global
                # blocks (full-width plain add on the non-slab
                # fallback).
                width = Ps if Ps is not None else num_blocks
                p2 = part.reshape(width, 128)
                if total is None:
                    total = jnp.zeros((num_blocks, 128), p2.dtype)
                if Ps is None:
                    total = total + p2
                else:
                    total = spmv.scatter_block_sums(
                        total, p2, ids, prefix_flags[s]
                    )
            return total

        if cfg.vertex_sharded:
            self._setup_vertex_sharded(
                n_stripes=n_stripes, sz=sz, gw=gw, group=group, pair=pair,
                accum=accum, num_blocks=num_blocks, chunks=ell_chunks,
                num_present=num_present, prefix_flags=prefix_flags,
                ids=present_ids, n=n, n_state=n_state,
                mass_mask=mass_mask, zero_in=zero_in, valid=valid,
                inv_out_rel=inv_out_rel, multi_dispatch=multi_dispatch,
                accumulate_stripes=accumulate_stripes, xp=xp,
            )
            return

        def make_contrib(mode):
            """mode: 'ell' (XLA path) or a pallas gather strategy name."""
            if mode != "ell":
                from pagerank_tpu.ops import pallas_spmv

                interp = jax.default_backend() != "tpu"

                def sharded_contrib(z_ext, src, row_block):
                    rb0 = row_block[::pallas_chunk]
                    part = pallas_spmv.ell_contrib_pallas(
                        z_ext, src, row_block, rb0, num_blocks,
                        chunk=pallas_chunk, gather=mode,
                        accum_dtype=accum, interpret=interp,
                    )
                    return jax.lax.psum(part, axis)

                in_specs = (P(), P(axis, None), P(axis))
            else:
                nz = 2 if pair else 1

                def sharded_contrib(*args):
                    zs, rest = args[:nz], args[nz:]
                    total = accumulate_stripes(zs, rest)
                    return jax.lax.psum(total.reshape(-1), axis)

                in_specs = (P(),) * nz + (
                    P(axis, None), P(axis), P()
                ) * n_stripes

            return shard_map(
                sharded_contrib,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=P(),
                # pallas_call's out_shape carries no varying-mesh-axes
                # annotation, which the checker insists on; the psum
                # already makes the output replicated.
                check_vma=(mode == "ell"),
            )

        total_z = n_stripes * sz  # >= n_state; prescale zero-fills the tail

        # Dekker split of the wide prescale: z = hi + lo exactly, both
        # f32 — ops/spmv.py:ell_contrib_pair docstring. Per-stripe
        # sentinel pads are appended inside the contrib fn; the pallas
        # kernel instead consumes a gw-padded plain z pinned in VMEM, so
        # the prescale is bound per-kernel after the probe below.
        # ``inv`` is a runtime ARGUMENT, never a closure: a closed-over
        # device array lowers as an embedded HLO constant, and at large
        # scales the 1/out-degree vector alone can blow the
        # remote-compile request limit (268MB f64 at scale 25 -> HTTP
        # 413, docs/PERF_NOTES.md "Multi-dispatch stripes").
        def _z(r, inv):
            z = r.astype(inv.dtype) * inv
            if total_z > n_state:
                z = jnp.concatenate(
                    [z, jnp.zeros(total_z - n_state, z.dtype)]
                )
            return z

        def prescale_pair(r, inv):
            return _split_pair(_z(r, inv))

        def prescale_plain(r, inv):
            return _z(r, inv)

        def prescale_pallas(r, inv):
            z = r.astype(inv.dtype) * inv
            return jnp.concatenate([z, jnp.zeros(gw, dtype=z.dtype)])

        prescale = prescale_pair if pair else prescale_plain

        if want_pallas:
            # The legacy pallas kernel pins the WHOLE z_ext in VMEM;
            # refuse graphs that cannot fit (the XLA path has no such
            # limit) with the clean downgrade signal, not a runtime TPU
            # crash — ISSUE 16 satellite. The bound is the shared
            # PTK001 budget (obs/costs.pallas_vmem_budget), so the
            # static analyzer and this probe can never disagree.
            z_bytes = (n_state + gw) * jnp.dtype(self._inv_out.dtype).itemsize
            budget = obs_costs.pallas_vmem_budget(
                jax.devices()[0].device_kind
            )
            if z_bytes > budget:
                raise PallasUnavailableError(
                    f"rank vector does not fit the VMEM budget "
                    f"({z_bytes / 1e6:.0f}MB > {budget / 1e6:.0f}MB at "
                    f"n_padded={n_state}); set partition_span for the "
                    f"windowed pallas kernel, or use kernel='ell'"
                )
            # Probe-compile each gather strategy at build: Mosaic gather
            # support varies by TPU generation — try the direct take,
            # then the one-hot form, then fall back to the XLA path.
            contrib_fn = None
            for mode in ("take", "onehot8"):
                candidate = make_contrib(mode)
                try:
                    probe = jax.jit(
                        lambda src, rb, inv, fn=candidate: fn(
                            prescale_pallas(
                                jnp.zeros(n_state, inv.dtype), inv
                            ),
                            src, rb,
                        )
                    )
                    jax.block_until_ready(
                        probe(self._src[0], self._row_block[0],
                              self._inv_out)
                    )
                    contrib_fn = candidate
                    prescale = prescale_pallas
                    self._kernel = f"pallas:{mode}"
                    break
                except Exception as e:  # pragma: no cover - hw-dependent
                    msg = str(e).splitlines()[0][:160] if str(e) else ""
                    if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
                        raise  # OOM is not a lowering problem; surface it
                    obs_log.info(
                        f"pallas gather '{mode}' unavailable "
                        f"({type(e).__name__}: {msg})"
                    )
            if contrib_fn is None:
                # Do NOT run the XLA path on these pallas-shaped
                # (group-1, non-slab) arrays — that layout measured ~9%
                # slower than the native ell layout (PERF_NOTES "The
                # Pallas kernel, settled"). Signal the build entry
                # point to rebuild natively instead.
                raise PallasUnavailableError(
                    "both Mosaic gather strategies failed to lower"
                )
        else:
            contrib_fn = make_contrib("ell")

        if self._kernel.startswith("pallas"):
            contrib_args = (self._src[0], self._row_block[0])
        else:
            contrib_args = tuple(
                a for triple in zip(self._src, self._row_block, present_ids)
                for a in triple
            )
        self._finalize(
            contrib_fn, contrib_args,
            mass_mask, zero_in, valid, n, n_state, prescale=prescale,
        )
        if multi_dispatch:
            self._setup_multi_dispatch(
                n_stripes=n_stripes, sz=sz, gw=gw, group=group, pair=pair,
                accum=accum, num_blocks=num_blocks, chunks=ell_chunks,
                num_present=num_present, prefix_flags=prefix_flags,
                ids=present_ids, n=n, n_state=n_state, prescale=prescale,
            )

    def _setup_ell_partitioned(self, src_slots, w_slots, row_block,
                               mass_mask, zero_in, valid, *, n, n_state,
                               num_blocks, inv_out_rel, psz, group):
        """Partition-centric ELL layout (ISSUE 6 tentpole; Lakhotia et
        al., arXiv:1709.07122). The source range is split into
        ``psz``-vertex partitions and slots are sub-binned by source
        partition WITHIN each dst block at build time — a static
        permutation the packer's single composite-key sort absorbs
        (``ell_pack_striped(stripe_size=psz)`` /
        ``build_ell_device(stripe_size=psz)``), never a per-iteration
        shuffle. Per iteration:

          - the prescale lays z out partition-padded: each partition's
            ``psz`` lanes followed by ``gather_width`` zero lanes, so
            every partition owns its own zero sentinel block;
          - ONE chunked ell_contrib sweep runs over the concatenated
            partition-major rows; each chunk's gather reads only the
            dynamic window of its OWN partition
            (ops/spmv.py:ell_contrib window mode) — the chunk's whole
            gather working set is ``psz * z_item`` bytes,
            VMEM/cache-resident by the partition_span rule, instead of
            the full table;
          - the compact per-(partition, block)-pair sums expand into
            the global block accumulator with one sorted-unique
            scatter per partition (static slices of the pair axis).

        Because the fast-gather bound now applies to the WINDOW, the
        layout needs no source striping at any graph size: one
        program, always below SCAN_STRIPE_UNITS, no multi-dispatch.
        Partition-local words also shrink the slot alphabet — when it
        fits 24 bits the slot stream is stored as 3-byte planar int8
        (``partition_words24``), 25% off the dominant per-slot HBM
        bytes. Row bookkeeping rides per-chunk: CHUNK-LOCAL int16
        dense pair ranks plus an int32 [nc, 2] (window base, rank
        base) prefetch array.

        Replicated mode, 32-bit accumulation only (config.validate).
        ``cfg.stream_dtype='bfloat16'`` additionally streams the
        gather table in bf16 with the one-hot select in bf16 (exact —
        pure selection) and f32 accumulation (arXiv:2009.10443).
        """
        cfg = self.config
        mesh = self._mesh
        axis = cfg.mesh_axis
        ndev = mesh.devices.size
        accum = self._accum_dtype
        dtype = self._dtype
        self._kernel = "ell"
        xp = np if isinstance(src_slots[0], np.ndarray) else jnp
        K = len(src_slots)
        assert K == -(-n_state // psz), (K, n_state, psz)
        if K > self.MAX_PARTITIONS:
            # Each partition pads to a chunk multiple and unrolls one
            # expand scatter into the step program; a span this small
            # relative to the graph would explode both. The auto rule
            # never lands here (density-gated) — only an explicit
            # undersized span can.
            raise ValueError(
                f"partition_span {psz} gives {K} partitions "
                f"(> {self.MAX_PARTITIONS}): span too small for this "
                f"graph — raise partition_span (auto rule: "
                f"JaxTpuEngine.partition_span)"
            )

        stream = jnp.dtype(cfg.stream_dtype) if cfg.stream_dtype else None
        z_dtype = dtype  # accum is 32-bit here by config contract
        table_dt = stream or z_dtype
        z_item = jnp.dtype(table_dt).itemsize
        gw = max(
            self.GATHER_WIDTH,
            self._gather_width(psz, self.max_gather_lanes(False, z_item)),
        )
        win_rows = (psz + gw) // gw
        log2g = group.bit_length() - 1
        words24 = self.partition_words24(psz, group)
        table_len = K * (psz + gw)

        shard2d = jax.sharding.NamedSharding(mesh, P(axis, None))
        e_shard = mesh_lib.edge_sharding(mesh)
        rep = mesh_lib.replicated(mesh)

        # Chunk candidates: the plain path's fetch-byte heuristic,
        # CAPPED at 4096 rows — every partition's rows pad to
        # ndev * cand_max so any candidate divides each partition AND
        # device shards split on chunk boundaries (a chunk can then
        # never straddle a partition, which is what makes the
        # per-chunk window exact), and that per-partition pad must
        # stay small next to the partition's real rows.
        chunk_cands = sorted({
            min(4096, max(256, 8192 * 8 // max(gw, group))),
            min(4096, max(256, 8192 * 8 // gw)),
            min(4096, max(256, 32768 * 8 // gw)),
        })
        cand_max = chunk_cands[-1]
        unit = ndev * cand_max
        sent = np.int32(psz << log2g)

        parts_src, parts_rank, ids_list, prefix_flags, counts = \
            [], [], [], [], []
        rows_per_part = []
        pair_off = 0
        for p in range(K):
            if w_slots[p] is None:
                ss = src_slots[p]
            else:
                ss = xp.where(w_slots[p] != 0, src_slots[p], sent)
            rb = row_block[p]
            if xp is np:
                rk, ids_p, pc, prefix = ell_lib.dense_block_ranks(
                    rb, num_blocks
                )
            else:
                rk, ids_p, pc, prefix = self._dense_ranks_device(
                    rb, num_blocks
                )
            ss = _pad_rows(ss, unit, sent, xp)
            rk = _pad_rows(
                xp.asarray(rk, xp.int32), unit, max(0, pc - 1), xp
            ) + xp.int32(pair_off)
            parts_src.append(ss)
            parts_rank.append(rk)
            ids_list.append(ids_p)
            prefix_flags.append(prefix)
            counts.append(int(pc))
            rows_per_part.append(int(ss.shape[0]))
            pair_off += int(pc)
        pairs_total = pair_off
        rows_total = sum(rows_per_part)

        src_cat = xp.concatenate(parts_src)
        del parts_src
        if words24:
            src_cat = spmv.pack_words24(src_cat.astype(xp.int32), xp)
        ranks_cat = xp.concatenate(parts_rank)  # GLOBAL pair ranks
        del parts_rank
        src_dev = jax.device_put(src_cat, shard2d)
        del src_cat
        ranks_dev = jnp.asarray(ranks_cat)  # transient: base building
        del ranks_cat
        ids_cat = jax.device_put(
            jnp.concatenate([jnp.asarray(i) for i in ids_list]), rep
        )
        offs = np.concatenate([[0], np.cumsum(counts)]).astype(int)

        def wb_for(c):
            """Window row base per chunk (host math: chunks never
            straddle partitions, see the padding rule above)."""
            per_part = [r // c for r in rows_per_part]
            return np.repeat(
                np.arange(K, dtype=np.int32) * np.int32(win_rows), per_part
            )

        def bases_for(c):
            rb0 = ranks_dev[::c]
            rb_loc = (
                ranks_dev - jnp.repeat(rb0, c, total_repeat_length=rows_total)
            ).astype(jnp.int16)
            bases = jnp.stack(
                [jnp.asarray(wb_for(c)), rb0.astype(jnp.int32)], axis=1
            )
            return rb_loc, bases

        # inv_out in z_dtype, replicated (the prescale argument).
        inv_out_rel = xp.asarray(inv_out_rel)
        if inv_out_rel.dtype != z_dtype:
            inv_out_rel = inv_out_rel.astype(z_dtype)
        self._inv_out = jax.device_put(inv_out_rel, rep)

        if cfg.kernel == "pallas":
            # Same slot/rank layout, hand kernel (ISSUE 16): route to
            # the partition-centric Pallas setup. Shares src_dev /
            # ranks / ids / offs verbatim — a probe failure downgrades
            # via PallasUnavailableError and the rebuild re-enters this
            # function with kernel='ell' (group regains its native
            # value there; the arrays here are group-1 by routing).
            self._setup_ell_partitioned_pallas(
                src_dev=src_dev, ranks_dev=ranks_dev, ids_cat=ids_cat,
                offs=offs, prefix_flags=prefix_flags,
                rows_per_part=rows_per_part, rows_total=rows_total,
                pairs_total=pairs_total, K=K, psz=psz, words24=words24,
                num_blocks=num_blocks, n=n, n_state=n_state,
                mass_mask=mass_mask, zero_in=zero_in, valid=valid,
                z_dtype=z_dtype, stream=stream, gw=gw, group=group,
            )
            return

        chosen = self._autotune_chunk(
            chunk_cands, [rows_total // ndev], table_len, z_item, gw,
            group, False, accum, [pairs_total], ndev,
            part=dict(window_rows=win_rows, table_len=table_len,
                      table_dt=table_dt, src_dev=src_dev,
                      bases_for=bases_for, pairs=pairs_total),
        )
        chunk = min(chosen, rows_total // ndev)
        rb_loc, bases = bases_for(chunk)
        rb_dev = jax.device_put(rb_loc, e_shard)
        bases_dev = jax.device_put(bases, shard2d)
        del rb_loc, bases, ranks_dev

        self._src = [src_dev]
        self._row_block = [rb_dev]
        self._layout = {
            "form": "partitioned",
            "partition_span": psz,
            "partitions": K,
            "group": group,
            "gather_width": gw,
            "window_rows": win_rows,
            "words24": words24,
            "stream_dtype": cfg.stream_dtype or None,
            "chunk": chunk,
            "pairs": pairs_total,
            "slot_rows": rows_total,
            "n_stripes": 1,
            "stripe_span": n_state,
            "pair": False,
        }
        self._pack_stats = {
            "num_rows": rows_total,
            "padding_ratio": None,
            "n_stripes": 1,
        }

        nb = num_blocks
        nz_pad = K * psz - n_state

        def prescale_part(r, inv):
            z = r.astype(z_dtype) * inv
            if nz_pad:
                z = jnp.concatenate([z, jnp.zeros(nz_pad, z.dtype)])
            if stream is not None:
                z = z.astype(stream)
            z2 = z.reshape(K, psz)
            z2 = jnp.concatenate(
                [z2, jnp.zeros((K, gw), z2.dtype)], axis=1
            )
            return z2.reshape(-1)

        def sharded_contrib(z, src, rb_l, bases_a, ids_a):
            part = spmv.ell_contrib(
                z, src, rb_l, nb, accum_dtype=accum, gather_width=gw,
                chunk_rows=chunk, group=group, num_present=pairs_total,
                window_rows=win_rows, chunk_bases=bases_a,
            )
            p2 = part.reshape(pairs_total, 128)
            total = jnp.zeros((nb, 128), p2.dtype)
            # Expand (partition, block) pairs into the global block
            # accumulator: one sorted-UNIQUE scatter per partition
            # (static pair-axis slices) — the ids repeat ACROSS
            # partitions, and a single non-unique scatter serializes
            # (the vs_bounded pad lesson, docs/PERF_NOTES.md).
            for j in range(K):
                lo, hi = int(offs[j]), int(offs[j + 1])
                total = spmv.scatter_block_sums(
                    total, p2[lo:hi], ids_a[lo:hi], prefix_flags[j]
                )
            return jax.lax.psum(total.reshape(-1), axis)

        contrib_fn = shard_map(
            sharded_contrib,
            mesh=mesh,
            in_specs=(P(), P(axis, None), P(axis), P(axis, None), P()),
            out_specs=P(),
        )
        self._finalize(
            contrib_fn, (src_dev, rb_dev, bases_dev, ids_cat),
            mass_mask, zero_in, valid, n, n_state,
            prescale=prescale_part,
        )

    # Fixed row-chunk of the partitioned pallas kernel: 1024 rows keep
    # the streamed src block at 384KB (words24 planar) with the one-hot
    # segment matmul MXU-shaped. Divisibility is structural: the shared
    # partitioned layout pads every partition to ndev * cand_max rows
    # with cand_max >= 2048, so 1024 divides both partitions and device
    # shards and a chunk can never straddle either boundary.
    PALLAS_PART_CHUNK = 1024

    def _setup_ell_partitioned_pallas(
            self, *, src_dev, ranks_dev, ids_cat, offs, prefix_flags,
            rows_per_part, rows_total, pairs_total, K, psz, words24,
            num_blocks, n, n_state, mass_mask, zero_in, valid, z_dtype,
            stream, gw, group):
        """Partition-centric Pallas kernel setup (ISSUE 16 payload):
        consumes the layout `_setup_ell_partitioned` already built
        (partition-major group-1 rows, words24/int32 slot words, dense
        global pair ranks) and binds
        ops/pallas_spmv.ell_contrib_pallas_partitioned in place of the
        XLA window sweep. Differences from the XLA path:

          - z lays out as [K, W, 128] partition WINDOWS (W*128 lanes =
            span rounded to 2048, zero tail = the sentinel target); the
            kernel's window BlockSpec picks row ``bases[i, 0]``, so the
            Pallas pipeline double-buffers each window through VMEM
            exactly once per sweep instead of trusting the cache;
          - the 3-byte planar slot words stream to the core VERBATIM
            and unpack on-chip — the XLA path pays an HLO unpack pass;
          - pair ranks ride CHUNK-local in [0, width); the one-hot
            segment matmul is (chunk, width) x (chunk, 128) on the MXU
            with f32 scratch accumulation whatever the stream dtype.

        Probe/downgrade contract matches the legacy kernel: both gather
        strategies are probe-compiled at build, failure raises
        PallasUnavailableError and the entry points rebuild with
        kernel='ell' on the native (grouped) partitioned layout,
        recording ``kernel_requested`` in layout_info()."""
        from pagerank_tpu.ops import pallas_spmv

        cfg = self.config
        mesh = self._mesh
        axis = cfg.mesh_axis
        assert group == 1, group  # routing forces plain slot ids
        chunk = self.PALLAS_PART_CHUNK
        table_dt = stream or z_dtype
        z_item = jnp.dtype(table_dt).itemsize

        # Partition window padded so (1, W, 128) z blocks tile cleanly
        # in both f32 (8x128) and bf16 (16x128): 2048 lanes = 16 rows
        # of 128. The +8 keeps the onehot8 strategy's width-8 row at
        # the zero sentinel (index psz) in range.
        pspan = -(-(psz + 8) // 2048) * 2048
        w_rows = pspan // 128

        # width: max CHUNK-local pair-rank span. Dense ranks increment
        # <= 1 per row so it is bounded by chunk + 1, and in practice
        # is a handful of pairs; rounded to 128 for a lane-clean f32
        # scratch. A chunk whose span exceeded width would silently
        # drop rows — exactly the hazard PTK003's write-coverage proof
        # (analysis/kernels.py) rules out statically.
        spans = ranks_dev[chunk - 1 :: chunk] - ranks_dev[::chunk] + 1
        width = int(jax.device_get(jnp.max(spans)))
        width = -(-width // 128) * 128

        src_lanes, src_item = (3 * 128, 1) if words24 else (128, 4)
        resident = (
            2 * w_rows * 128 * z_item           # double-buffered z window
            + 2 * chunk * src_lanes * src_item  # streamed src block
            + 2 * (chunk // 128) * 128 * 4      # streamed rank block
            + width * 128 * 4                   # f32 accumulator scratch
        )
        budget = obs_costs.pallas_vmem_budget(jax.devices()[0].device_kind)
        if resident > budget:
            # Same shared bound as PTK001; an explicit oversized span
            # lands here and downgrades to the XLA window sweep.
            raise PallasUnavailableError(
                f"partitioned kernel VMEM residency "
                f"{resident / 1e6:.1f}MB > {budget / 1e6:.0f}MB budget "
                f"(span {psz}, chunk {chunk}, width {width})"
            )

        part_ids = np.repeat(
            np.arange(K, dtype=np.int32),
            [r // chunk for r in rows_per_part],
        )
        rb0 = ranks_dev[::chunk].astype(jnp.int32)
        bases = jnp.stack([jnp.asarray(part_ids), rb0], axis=1)
        rk_loc = (
            ranks_dev
            - jnp.repeat(rb0, chunk, total_repeat_length=rows_total)
        ).astype(jnp.int32).reshape(rows_total // 128, 128)
        shard2d = jax.sharding.NamedSharding(mesh, P(axis, None))
        rk_dev = jax.device_put(rk_loc, shard2d)
        bases_dev = jax.device_put(bases, shard2d)
        del rk_loc, bases, spans

        self._src = [src_dev]
        self._row_block = [rk_dev]
        self._layout = {
            "form": "pallas_partitioned",
            "partition_span": psz,
            "partitions": K,
            "group": group,
            "gather_width": gw,
            "window_rows": w_rows,
            "words24": words24,
            "stream_dtype": cfg.stream_dtype or None,
            "chunk": chunk,
            "width": width,
            "pairs": pairs_total,
            "slot_rows": rows_total,
            "n_stripes": 1,
            "stripe_span": n_state,
            "pair": False,
        }
        self._pack_stats = {
            "num_rows": rows_total,
            "padding_ratio": None,
            "n_stripes": 1,
        }

        nb = num_blocks
        nz_pad = K * psz - n_state

        def prescale_pallas_part(r, inv):
            z = r.astype(z_dtype) * inv
            if nz_pad:
                z = jnp.concatenate([z, jnp.zeros(nz_pad, z.dtype)])
            if stream is not None:
                z = z.astype(stream)
            z2 = z.reshape(K, psz)
            z2 = jnp.concatenate(
                [z2, jnp.zeros((K, pspan - psz), z2.dtype)], axis=1
            )
            return z2.reshape(K, w_rows, 128)

        interp = jax.default_backend() != "tpu"

        def make_contrib(mode):
            def sharded_contrib(z3, src, rk, bases_a, ids_a):
                part = pallas_spmv.ell_contrib_pallas_partitioned(
                    z3, src, rk, bases_a, pairs_total, chunk=chunk,
                    width=width, gather=mode, interpret=interp,
                )
                p2 = part.reshape(pairs_total, 128)
                total = jnp.zeros((nb, 128), p2.dtype)
                # Pair -> global block expansion, identical to the XLA
                # partitioned path: one sorted-UNIQUE scatter per
                # partition (static pair-axis slices).
                for j in range(K):
                    lo, hi = int(offs[j]), int(offs[j + 1])
                    total = spmv.scatter_block_sums(
                        total, p2[lo:hi], ids_a[lo:hi], prefix_flags[j]
                    )
                return jax.lax.psum(total.reshape(-1), axis)

            return shard_map(
                sharded_contrib,
                mesh=mesh,
                in_specs=(P(), P(axis, None), P(axis, None),
                          P(axis, None), P()),
                out_specs=P(),
                # pallas_call's out_shape carries no varying-mesh-axes
                # annotation (see make_contrib above).
                check_vma=False,
            )

        contrib_fn = None
        for mode in ("take", "onehot8"):
            candidate = make_contrib(mode)
            try:
                probe = jax.jit(
                    lambda src, rk, b, ids, inv, fn=candidate: fn(
                        prescale_pallas_part(
                            jnp.zeros(n_state, z_dtype), inv
                        ),
                        src, rk, b, ids,
                    )
                )
                jax.block_until_ready(
                    probe(src_dev, rk_dev, bases_dev, ids_cat,
                          self._inv_out)
                )
                contrib_fn = candidate
                self._kernel = f"pallas_part:{mode}"
                break
            except Exception as e:  # pragma: no cover - hw-dependent
                msg = str(e).splitlines()[0][:160] if str(e) else ""
                if ("RESOURCE_EXHAUSTED" in msg
                        or "out of memory" in msg.lower()):
                    raise  # OOM is not a lowering problem; surface it
                obs_log.info(
                    f"partitioned pallas gather '{mode}' unavailable "
                    f"({type(e).__name__}: {msg})"
                )
        if contrib_fn is None:
            raise PallasUnavailableError(
                "both Mosaic gather strategies failed to lower the "
                "partitioned kernel"
            )

        self._finalize(
            contrib_fn, (src_dev, rk_dev, bases_dev, ids_cat),
            mass_mask, zero_in, valid, n, n_state,
            prescale=prescale_pallas_part,
        )

    def _setup_multi_dispatch(self, *, n_stripes, sz, gw, group, pair,
                              accum, num_blocks, chunks, num_present,
                              prefix_flags, ids, n, n_state, prescale):
        """Fast stepwise path for very-many-stripe layouts: run each
        stripe's contribution as its OWN dispatch (per-stripe compiled
        executable, EXACT per-stripe shapes and a STATIC per-stripe z
        slice — the literal unrolled-loop body as a standalone program),
        each returning its compact per-present-block partial; ONE
        finalize dispatch then scatters all partials into the global
        block array, reduces across devices, and applies the rank
        update.

        Why: the unrolled single-program form exceeds the remote-compile
        size limit past SCAN_STRIPE_UNITS, and the (since removed, r3)
        in-program scan-over-stripes fallback lost XLA's fast gather
        lowering (0.91e8 vs 3.33e8 edges/s/chip at scale 24,
        docs/PERF_NOTES.md "Scan bodies defeat the fast gather") and
        exceeded single-chip HBM at scale-25 pair. Per-stripe dispatches
        get both: each compile request is O(one stripe) — the 413 limit was
        per-request, so S small requests are fine where one S-stripe
        program was not — and each dispatch is a top-level program whose
        gather table is a (statically sliced) root argument, keeping the
        fast lowering. Two measured dead ends shaped this design
        (scale-24 pair, 8x2.1M stripes, v5e):

        - uniform rows_max shapes: power-law skew (stripe rows measured
          [2.0M, 139K, 74K, 49K, 33K x4]) makes every stripe cost like
          the biggest — 2.5 s/iter where ~0.5 s was expected;
        - accumulating into a donated [num_blocks, 128] accum-dtype slab
          per stripe: the scatter's full-table read-modify-write put a
          ~60 ms FLOOR under every dispatch (measured flat across
          stripes with 8x differing work) — hence compact per-stripe
          outputs with all scatters batched into the one finalize
          program.

        Per-dispatch host latency (~1-5 ms measured) is hidden by async
        dispatch pipelining. Used by ``_device_step`` (run_fast / run /
        run_fused_chunked) — and therefore, by delegation, by EVERY
        public run form on these layouts (run_fused / run_fused_tol
        route through run_fused_chunked).
        """
        mesh = self._mesh
        axis = self.config.mesh_axis

        def ms_prescale(r, inv):
            # The engine's own (inv-parametric) prescale, normalized to
            # a tuple of gather planes.
            z = prescale(r, inv)
            return z if isinstance(z, tuple) else (z,)

        self._ms_prescale = jax.jit(ms_prescale)

        self._ms_stripe_fns = self._make_ms_stripe_fns(
            n_stripes=n_stripes, sz=sz, gw=gw, group=group, pair=pair,
            accum=accum, num_blocks=num_blocks, chunks=chunks,
            num_present=num_present,
        )
        self._ms_stripe = self._ms_stripe_fns[0]  # engaged-flag + probe

        update_tail = self._update_tail  # set by _finalize, shared

        def _merge_parts(rest):
            parts = rest[:n_stripes]
            ids_l = rest[n_stripes : 2 * n_stripes]
            total = jnp.zeros((num_blocks, 128), accum)
            for s in range(n_stripes):
                # .sum(0) collapses the per-device partials (GSPMD turns
                # it into the cross-device reduce); the scatters stay in
                # ONE program so XLA keeps one resident accumulator.
                total = spmv.scatter_block_sums(
                    total, parts[s].sum(0), ids_l[s], prefix_flags[s]
                )
            return total

        def final_body(r, *rest):
            dangling, zero_in, valid_m = rest[2 * n_stripes :]
            contrib = _merge_parts(rest).reshape(-1)[: r.shape[0]]
            return update_tail(contrib, r, dangling, zero_in, valid_m)

        def final_body_ledger(r, *rest):
            # The ledger finalize (ISSUE 13): same merge + three local
            # reductions; a separate lazily-compiled executable so the
            # plain dispatch sequence never carries them.
            dangling, zero_in, valid_m = rest[2 * n_stripes :]
            contrib = _merge_parts(rest).reshape(-1)[: r.shape[0]]
            led = _ledger_sums(contrib, r, zero_in, accum)
            return (*update_tail(contrib, r, dangling, zero_in,
                                 valid_m), *led)

        self._ms_final = jax.jit(final_body, donate_argnums=(0,))
        self._ms_final_ledger = jax.jit(final_body_ledger,
                                        donate_argnums=(0,))
        self._ms_ids = list(ids)
        self._ms_n_stripes = n_stripes
        self._layout = dict(self._layout, form="multi_dispatch")

    def _make_ms_stripe_fns(self, *, n_stripes, sz, gw, group, pair, accum,
                            num_blocks, chunks, num_present,
                            local_planes=False):
        """The per-stripe multi-dispatch executables (see
        _setup_multi_dispatch): each stripe's contribution as its own
        jitted shard_map with EXACT per-stripe shapes and a static
        per-stripe z slice, returning compact per-present-block
        partials. Shared by the replicated and vertex-sharded modes —
        the stripe fns consume REPLICATED z planes either way (the
        modes differ only in how z is produced and how partials merge
        into the rank update). ``local_planes``: the planes are
        per-stripe [sz] (vs_bounded's broadcast dispatches) instead of
        full-length z, so the static slice starts at 0. Either way the
        gather table derives from a ROOT argument of the dispatch — a
        table computed behind a collective in the same program loses
        XLA's fast gather lowering (measured 2.6x slower end-to-end at
        scale 23; same failure class as PERF_NOTES "Scan bodies defeat
        the fast gather")."""
        mesh = self._mesh
        axis = self.config.mesh_axis
        nz = 2 if pair else 1

        def make_stripe_fn(s, Ps, ck):
            lo_ix = 0 if local_planes else s * sz

            def stripe_body(*args):
                zs, (src, rb) = args[:nz], args[nz:]
                z_s = [
                    jnp.concatenate(
                        [z[lo_ix : lo_ix + sz], jnp.zeros(gw, z.dtype)]
                    )
                    for z in zs
                ]
                if pair:
                    part = spmv.ell_contrib_pair(
                        z_s[0], z_s[1], src, rb, num_blocks,
                        accum_dtype=accum, gather_width=gw, chunk_rows=ck,
                        group=group, num_present=Ps,
                    )
                else:
                    part = spmv.ell_contrib(
                        z_s[0], src, rb, num_blocks, accum_dtype=accum,
                        gather_width=gw, chunk_rows=ck, group=group,
                        num_present=Ps,
                    )
                return part.reshape(1, Ps, 128)

            return jax.jit(
                shard_map(
                    stripe_body,
                    mesh=mesh,
                    in_specs=(P(),) * nz + (P(axis, None), P(axis)),
                    out_specs=P(axis, None, None),
                )
            )

        return [
            make_stripe_fn(s, num_present[s], chunks[s])
            for s in range(n_stripes)
        ]

    def _place_vs_state(self, mass_mask, zero_in, valid, inv_out_rel, *,
                        n, n_vs, xp):
        """Shard the persistent per-vertex state over the mesh in
        contiguous vertex blocks (parallel/mesh.vertex_sharding),
        padding every vector to ``n_vs`` (a multiple of 128*ndev so the
        shards are even); padding is inert (valid=0, inv=0). Shared by
        both vertex-sharded modes."""
        cfg = self.config
        dtype = self._dtype
        vshard = mesh_lib.vertex_sharding(self._mesh)
        n_state = len(mass_mask)
        padv = n_vs - n_state

        def pad_vs(a):
            if padv == 0:
                return xp.asarray(a)
            a = xp.asarray(a)
            return xp.concatenate([a, xp.zeros(padv, a.dtype)])

        self._n_state = n_vs
        self._state_sharding = vshard
        self._dangling = jax.device_put(
            pad_vs(xp.asarray(mass_mask, bool)), vshard
        )
        self._zero_in = jax.device_put(
            pad_vs(xp.asarray(zero_in, bool)), vshard
        )
        valid = pad_vs(xp.asarray(valid, bool))
        self._valid = jax.device_put(valid, vshard)
        self._inv_out = jax.device_put(pad_vs(inv_out_rel), vshard)
        r0_value = 1.0 if cfg.semantics == "reference" else 1.0 / n
        r0 = xp.full(n_vs, r0_value, dtype=dtype) * valid
        self._r = jax.device_put(jnp.asarray(r0, dtype=dtype), vshard)
        self.iteration = 0

    def _make_vs_tail(self, accum, n):
        """update_tail's semantics on LOCAL vertex blocks: the two
        scalar reductions (dangling mass, L1 delta) are per-shard
        partials merged by psum; the elementwise update runs on the
        shard. Same apply_update spelling as every other form. Shared
        by both vertex-sharded modes."""
        axis = self.config.mesh_axis
        damping = self.config.damping
        semantics = self.config.semantics

        def vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l):
            m = jax.lax.psum(
                jnp.sum(dang_l.astype(accum) * r_l.astype(accum)), axis
            )
            r_new = pr_model.apply_update(
                contrib_l, r_l.astype(accum), zin_l.astype(accum), m, n,
                damping, semantics, jnp,
            )
            r_new = (r_new * valid_l.astype(accum)).astype(r_l.dtype)
            delta = jax.lax.psum(
                jnp.sum(jnp.abs(r_new.astype(accum) - r_l.astype(accum))),
                axis,
            )
            return r_new, delta, m

        return vs_tail

    def _setup_vertex_sharded(self, *, n_stripes, sz, gw, group, pair,
                              accum, num_blocks, chunks, num_present,
                              prefix_flags, ids, n, n_state, mass_mask,
                              zero_in, valid, inv_out_rel, multi_dispatch,
                              accumulate_stripes, xp):
        """Partitioned-rank execution (config.vertex_sharded; VERDICT r3
        #1): per-vertex state — rank vector, masks, 1/out-degree — is
        SHARDED over the mesh in contiguous vertex blocks, the analogue
        of the reference's hash-partitioned ``ranks`` RDD
        (Sparky.java:165-170). The replicated mode's per-chip copy of
        every per-vertex vector caps the largest representable graph
        regardless of mesh size; here persistent per-vertex HBM is
        1/ndev per chip, so adding chips raises the ceiling.

        Per-iteration dataflow (one shard_map over the whole step):

          1. z_local = r_local * inv_local          (sharded elementwise)
          2. z = all_gather(z_local)                (the stripe gathers
             need arbitrary source entries; gathered z is TRANSIENT —
             freed after the contribution — unlike the replicated
             mode's persistent copies)
          3. per-stripe blocked-ELL gathers into the block accumulator
             (identical kernels to the replicated mode)
          4. contrib_local = psum_scatter(flat)     (reduce-scatter:
             each chip keeps exactly its vertex block of the merged sum)
          5. rank update on the local block; dangling mass and the L1
             delta are per-shard partial reductions merged by scalar
             psums.

        Total per-iteration bytes over ICI equal the replicated mode's
        single all-reduce (all_gather + reduce_scatter = all-reduce),
        so this trades no bandwidth for the memory scaling.

        Equality vs the replicated mode (tests/test_vertex_sharded.py):
        the contribution merge is bit-exact (psum_scatter slices agree
        with psum bitwise — pinned by the first-step test); the one
        legitimate divergence is the mass/L1 scalar reductions, whose
        per-shard regrouping shifts the f64 sum by <= 1 ulp per
        iteration. f32-storage configs round that away (bit-equal full
        runs); f64 storage carries it (measured max 4 nulp after 50
        iterations, no amplification).

        The state length pads from n_state to n_vs (next multiple of
        128*ndev) so every per-vertex vector shards evenly; the padding
        is inert (valid=0, inv=0). Layouts past SCAN_STRIPE_UNITS use
        the same per-stripe multi-dispatch executables as the
        replicated mode with a sharded prescale/finalize
        (_setup_multi_dispatch_vs)."""
        cfg = self.config
        mesh = self._mesh
        axis = cfg.mesh_axis
        ndev = mesh.devices.size

        unit = 128 * ndev
        n_vs = -(-n_state // unit) * unit
        padv = n_vs - n_state

        self._kernel = "ell"
        self._place_vs_state(
            mass_mask, zero_in, valid, inv_out_rel, n=n, n_vs=n_vs, xp=xp
        )

        total_z = n_stripes * sz

        vs_tail = self._make_vs_tail(accum, n)
        self._vs_tail = vs_tail

        # XLA-TPU's X64 rewriter implements f64 all-reduce but NOT f64
        # reduce-scatter (probed on the current libtpu: "While rewriting
        # computation to not contain X64 element types ... not
        # implemented: reduce-scatter f64[...]", even at 1 device), so
        # 64-bit accumulation on TPU backends merges with psum + a
        # local slice — same bits as the replicated mode's merge, at
        # all-reduce bandwidth instead of reduce-scatter's half (the
        # memory scaling, which is the point of this mode, is
        # unaffected). Revisit on libtpu upgrades.
        use_rs = (
            jnp.dtype(accum).itemsize < 8
            or jax.default_backend() != "tpu"
        )
        blk = n_vs // ndev

        # Sparse boundary exchange (ISSUE 8): resolve whether this
        # build runs the halo-exchange step instead of the dense
        # all_gather + reduce-scatter below. Downgrades (logged +
        # recorded in layout_info) keep the solve correct on layouts
        # the sparse form does not cover: the multi-dispatch stripes
        # (its per-stripe executables consume replicated z planes) and
        # TPU backends with a 64-bit exchanged dtype (the same X64
        # rewrite gap class as the reduce-scatter above — ppermute/psum
        # of f64 payloads is exactly what the rewriter lacks).
        halo = bool(cfg.halo_exchange)
        halo_note = None
        if halo and multi_dispatch:
            halo, halo_note = False, "multi_dispatch"
        if halo and jax.default_backend() == "tpu" and (
            jnp.dtype(self._inv_out.dtype).itemsize == 8
            or jnp.dtype(accum).itemsize == 8
        ):
            halo, halo_note = False, "wide_dtype_tpu"
        if halo_note:
            obs_log.warn(
                f"halo_exchange downgraded to the dense exchange "
                f"({halo_note})"
            )
            self._layout = dict(self._layout, halo=f"off:{halo_note}")
            if cfg.halo_async:
                # The async overlap rides the sparse exchange; when
                # that downgrades, the overlap goes with it — recorded
                # so layout_info explains BOTH refusals.
                self._layout = dict(self._layout,
                                    halo_async=f"off:{halo_note}")
        if halo:
            self._setup_vs_halo(
                n_stripes=n_stripes, sz=sz, group=group, pair=pair,
                accum=accum, ids=ids, n_vs=n_vs, padv=padv, blk=blk,
                total_z=total_z, use_rs=use_rs,
                accumulate_stripes=accumulate_stripes, vs_tail=vs_tail,
                want_async=bool(cfg.halo_async),
            )
            return
        from pagerank_tpu.parallel import comms as comms_lib

        self._set_comms_model(comms_lib.model_dense(
            ndev, blk, jnp.dtype(self._inv_out.dtype).itemsize,
            jnp.dtype(accum).itemsize, use_rs,
        ))

        def gather_z(r_l, inv_l):
            """Steps 1-2: sharded prescale + tiled all_gather; returns
            the gather plane tuple (split AFTER the gather in pair mode
            so one f64 vector crosses ICI, not two f32 planes plus a
            second launch)."""
            z_l = r_l.astype(inv_l.dtype) * inv_l
            z = jax.lax.all_gather(z_l, axis, tiled=True)  # [n_vs]
            if total_z > n_vs:
                z = jnp.concatenate(
                    [z, jnp.zeros(total_z - n_vs, z.dtype)]
                )
            return _split_pair(z) if pair else (z,)

        def merge_scatter(total):
            """Step 4: pad the merged block accumulator to the sharded
            state length and reduce-scatter it so each chip keeps its
            own contiguous contribution block (psum + slice where the
            backend cannot lower a 64-bit reduce-scatter, see above)."""
            flat = total.reshape(-1)  # [n_state]
            if padv:
                flat = jnp.concatenate([flat, jnp.zeros(padv, accum)])
            if use_rs:
                return jax.lax.psum_scatter(
                    flat, axis, scatter_dimension=0, tiled=True
                )
            full = jax.lax.psum(flat, axis)
            i = jax.lax.axis_index(axis)
            return jax.lax.dynamic_slice_in_dim(full, i * blk, blk)

        def vs_body(r_l, inv_l, dang_l, zin_l, valid_l, *rest):
            zs = gather_z(r_l, inv_l)
            # Same stripe body as the replicated contrib fn (ONE
            # spelling — accumulate_stripes); only the merge differs.
            total = accumulate_stripes(zs, rest)
            contrib_l = merge_scatter(total)
            return vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)

        def vs_body_ledger(r_l, inv_l, dang_l, zin_l, valid_l, *rest):
            zs = gather_z(r_l, inv_l)
            total = accumulate_stripes(zs, rest)
            contrib_l = merge_scatter(total)
            out = vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)
            # Ledger sums ride as [1] per-shard partials (out P(axis)
            # -> [ndev] on host) — no collective joins the step
            # (_ledger_partials docstring).
            return (*out, *_ledger_partials(contrib_l, r_l, zin_l,
                                            accum))

        vs_in_specs = (P(axis),) * 5 \
            + (P(axis, None), P(axis), P()) * n_stripes
        step_core = shard_map(
            vs_body,
            mesh=mesh,
            in_specs=vs_in_specs,
            out_specs=(P(axis), P(), P()),
        )
        self._step_core_ledger = shard_map(
            vs_body_ledger,
            mesh=mesh,
            in_specs=vs_in_specs,
            out_specs=(P(axis), P(), P()) + (P(axis),) * 3,
        )

        self._contrib_args = tuple(
            a for triple in zip(self._src, self._row_block, ids)
            for a in triple
        )
        self._inv_in_args = True
        self._step_core = step_core
        self._step_fn = self._jit_step(step_core)
        if not multi_dispatch:
            self._exchange_core = self._make_exchange_core(
                gather_z_fn=lambda r_l, inv_l, rest: gather_z(r_l, inv_l),
                merge_fn=lambda flat, rest: merge_scatter(flat),
                n_state_flat=n_vs - padv, accum=accum,
                in_specs=(P(axis),) * 5
                + (P(axis, None), P(axis), P()) * n_stripes,
            )
        self._fused_cache = {}
        self.last_run_metrics = {
            "l1_delta": np.zeros(0, self._accum_dtype),
            "dangling_mass": np.zeros(0, self._accum_dtype),
        }
        self._layout = dict(self._layout, form="vertex_sharded")
        if multi_dispatch:
            self._setup_multi_dispatch_vs(
                n_stripes=n_stripes, sz=sz, gw=gw, group=group, pair=pair,
                accum=accum, num_blocks=num_blocks, chunks=chunks,
                num_present=num_present, prefix_flags=prefix_flags,
                ids=ids, n_vs=n_vs, padv=padv, gather_z=gather_z,
                merge_scatter=merge_scatter,
            )

    def _setup_vs_halo(self, *, n_stripes, sz, group, pair, accum, ids,
                       n_vs, padv, blk, total_z, use_rs,
                       accumulate_stripes, vs_tail, want_async=False):
        """Sparse boundary exchange for the vertex-sharded step
        (ISSUE 8; config.halo_exchange; Zhao & Canny, arXiv:1312.3020).

        The plain vertex-sharded step all_gathers the WHOLE z vector
        and reduce-scatters the FULL-width contribution merge every
        iteration — O(n) wire bytes per chip regardless of how little
        of the remote state this chip's edges actually touch. Here the
        exchange is restricted to the build-time BOUNDARY
        (parallel/partition.build_halo_plan, derived once from the
        packed slot tables; static int32 tables ride as runtime
        arguments — no per-iteration host work, no embedded constants):

          1. z_local = r_local * inv_local            (as dense)
          2. head = psum(masked own slice of [0, K))  — the top-K
             in-degree prefix nearly every shard reads on a power-law
             graph, replicated with ONE small all-reduce instead of
             being repeated in every point-to-point pair set;
          3. tail boundary z moves point-to-point: one ppermute round
             per ring offset with a static per-round width, each
             device gathering its send set from z_local and scattering
             received entries into a sparse z image (entries nobody
             here reads stay zero — the gathers never touch them, so
             the per-stripe contributions are BIT-IDENTICAL to the
             dense path's, tests/test_halo.py);
          4. per-stripe gathers (identical kernels — accumulate_stripes
             is THE one spelling shared with every other mode);
          5. the merge returns only each writer's contribution WINDOWS:
             rows are dst-sorted and evenly row-sharded, so a device's
             nonzero partials form one contiguous flat band — each
             (writer -> owner) overlap moves as one ppermute window and
             scatter-adds into the owner's block (exactly-once: window
             overrun past an owner's block lands in a trash band and
             the same values reach the next owner in its own round).
             A position's partials may regroup vs the reduce-scatter
             (<= 1 ulp in accum dtype) — the one legitimate numerics
             divergence from the dense mode.

        Per-iteration wire bytes: head all-reduce + boundary payloads
        + band windows — the comms model (parallel/comms.py) publishes
        both this and the dense comparator through the metrics
        registry, and `comms.bytes_exchanged` accumulates per step.
        Latency caveat: the rounds serialize up to 2*(ndev-1) small
        collectives where the dense path issues 2 large ones — a
        bandwidth/latency trade that pays exactly when the boundary is
        sparse (docs/PERF_NOTES.md "Sparse boundary exchange").

        ``want_async`` (ISSUE 17; config.halo_async) additionally asks
        for the ASYNCHRONOUS stale-boundary form (_setup built here
        under form "vs_halo_async"): a two-slot boundary buffer rides
        the step carry so iteration k's local segment-sum consumes
        iteration k-1's boundary while iteration k's ships — boundary
        reads lag one iteration, own blocks stay fresh, and the head +
        read-round collectives leave the critical path. Auto-gated
        right here, where the plan's byte split exists: refused
        (logged; layout_info carries halo_async="off:<reason>") on
        single-device meshes, boundary-free plans, a predicted overlap
        gain below config.halo_async_min_gain, or stale_max_lag=0 (the
        exactness demand — the synchronous body below IS the lag-0
        form, zero extra buffers)."""
        cfg = self.config
        mesh = self._mesh
        axis = cfg.mesh_axis
        ndev = mesh.devices.size
        zd = jnp.dtype(self._inv_out.dtype)

        from pagerank_tpu.parallel import comms as comms_lib
        from pagerank_tpu.parallel.partition import build_halo_plan

        with obs_trace.span("engine/halo_plan"):
            # One-time host pull of the placed slot/rank tables: the
            # plan needs exact read sets, and build_device graphs only
            # exist on device. Build-time cost, never per-iteration.
            src_host = [np.asarray(jax.device_get(s)) for s in self._src]
            rk_host = [np.asarray(jax.device_get(r))
                       for r in self._row_block]
            ids_host = [np.asarray(jax.device_get(i)) for i in ids]
            plan = build_halo_plan(
                src_host, rk_host, ids_host, ndev=ndev, n_vs=n_vs,
                sz=sz, group=group, head_k=cfg.halo_head,
                z_item=zd.itemsize,
                accum_item=jnp.dtype(accum).itemsize, rs_merge=use_rs,
            )
        self._halo_plan = plan

        # Async auto-gate (ISSUE 17): decided HERE, where the plan's
        # byte split exists — mirroring the pallas probe-downgrade
        # idiom (logged, recorded, solve stays correct either way).
        # The predicted payoff is published even on refusal, so `obs
        # report` always shows the gate's evidence.
        use_async = False
        if want_async:
            gain = comms_lib.predict_overlap_gain(plan)
            comms_lib.publish_overlap_gain(gain)
            async_note = None
            if cfg.stale_max_lag == 0:
                # Exactness demanded: the synchronous body IS the
                # lag-0 form (bit-identical, zero extra buffers) — an
                # expected path, not a payoff refusal.
                async_note = "stale_max_lag=0"
                obs_log.info(
                    "halo_async with stale_max_lag=0: running the "
                    "synchronous sparse exchange (exact, unbuffered)"
                )
            elif ndev < 2:
                async_note = "single_device"
            elif not plan.overlappable_bytes_per_iter():
                async_note = "no_boundary"
            elif gain < cfg.halo_async_min_gain:
                async_note = (f"gain {gain:.4f} < "
                              f"{cfg.halo_async_min_gain:g}")
            if async_note and async_note != "stale_max_lag=0":
                obs_log.warn(
                    f"halo_async downgraded to the synchronous sparse "
                    f"exchange ({async_note})"
                )
            if async_note:
                self._layout = dict(self._layout,
                                    halo_async=f"off:{async_note}")
            else:
                use_async = True

        self._set_comms_model(
            comms_lib.model_async(plan) if use_async
            else comms_lib.model_sparse(plan)
        )
        RR, WR = plan.read_rounds, plan.write_rounds
        nread = len(RR)
        K = plan.head_k
        wmax = max((r.width for r in WR), default=0)

        shard2d = jax.sharding.NamedSharding(mesh, P(axis, None))
        dshard = mesh_lib.edge_sharding(mesh)
        halo_args, halo_specs = [], []
        for si, gi in zip(plan.send_idx, plan.recv_ids):
            halo_args += [jax.device_put(si, shard2d),
                          jax.device_put(gi, shard2d)]
            halo_specs += [P(axis, None), P(axis, None)]
        for ws, wr in zip(plan.wsend_start, plan.wrecv_start):
            halo_args += [jax.device_put(ws, dshard),
                          jax.device_put(wr, dshard)]
            halo_specs += [P(axis), P(axis)]
        n_halo = len(halo_args)

        def gather_z_sparse(r_l, inv_l, halo):
            """Steps 1-3: sharded prescale + head psum + tail ppermute
            rounds into the sparse z image (the [n_vs]+trash vector
            whose READ positions carry exactly the dense z's bits)."""
            z_l = r_l.astype(zd) * inv_l  # [blk]
            z_le = jnp.concatenate([z_l, jnp.zeros(1, zd)])
            me = jax.lax.axis_index(axis)
            zf = jnp.zeros(n_vs + 1, zd)
            zf = jax.lax.dynamic_update_slice(zf, z_l, (me * blk,))
            for i, rnd in enumerate(RR):
                si = halo[2 * i][0]  # [width] owner-local send indices
                gi = halo[2 * i + 1][0]  # [width] global landing ids
                recv = jax.lax.ppermute(z_le[si], axis, perm=rnd.perm)
                # Real landings are unique (one owner per vertex);
                # pads land on the n_vs trash slot with zero payload.
                zf = zf.at[gi].add(recv)
            if K:
                idx = jnp.arange(K, dtype=jnp.int32)
                pos = idx - me * blk
                vals = z_le[jnp.clip(pos, 0, blk)]
                mask = (pos >= 0) & (pos < blk)
                head = jax.lax.psum(
                    jnp.where(mask, vals, jnp.zeros((), zd)), axis
                )
                # Exactly one owner contributed each entry (psum of
                # x + zeros = x bitwise), so overwriting the owner's
                # own copy is a no-op and every reader sees the same
                # bits the dense all_gather would deliver.
                zf = jax.lax.dynamic_update_slice(zf, head, (0,))
            z = zf[:n_vs]
            if total_z > n_vs:
                z = jnp.concatenate(
                    [z, jnp.zeros(total_z - n_vs, zd)]
                )
            return _split_pair(z) if pair else (z,)

        def merge_sparse(total, halo):
            """Step 5: own-block slice + per-offset band windows. Each
            writer ships one static-width window per active offset;
            owners scatter-add received windows (overrun lands in the
            [blk, blk+wmax) trash band, underrun ships zeros)."""
            flat = total.reshape(-1)  # [n_state]
            if padv:
                flat = jnp.concatenate([flat, jnp.zeros(padv, accum)])
            me = jax.lax.axis_index(axis)
            own = jax.lax.dynamic_slice_in_dim(flat, me * blk, blk)
            if not WR:
                return own
            flat_ext = jnp.concatenate([flat, jnp.zeros(wmax, accum)])
            buf = jnp.zeros(blk + wmax, accum)
            for j, rnd in enumerate(WR):
                ws = halo[2 * nread + 2 * j][0]  # global window start
                wr = halo[2 * nread + 2 * j + 1][0]  # local landing
                win = jax.lax.dynamic_slice_in_dim(flat_ext, ws,
                                                   rnd.width)
                recv = jax.lax.ppermute(win, axis, perm=rnd.perm)
                buf = buf.at[
                    wr + jnp.arange(rnd.width, dtype=jnp.int32)
                ].add(recv)
            return own + buf[:blk]

        if use_async:
            self._setup_vs_halo_async(
                plan=plan, RR=RR, WR=WR, K=K, halo_args=halo_args,
                halo_specs=halo_specs, n_halo=n_halo, ids=ids, zd=zd,
                accum=accum, pair=pair, blk=blk, n_vs=n_vs, padv=padv,
                total_z=total_z, n_stripes=n_stripes,
                accumulate_stripes=accumulate_stripes, vs_tail=vs_tail,
                merge_sparse=merge_sparse,
            )
            return

        def vs_body(r_l, inv_l, dang_l, zin_l, valid_l, *rest):
            halo, stripes = rest[:n_halo], rest[n_halo:]
            zs = gather_z_sparse(r_l, inv_l, halo)
            total = accumulate_stripes(zs, stripes)
            contrib_l = merge_sparse(total, halo)
            return vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)

        def vs_body_ledger(r_l, inv_l, dang_l, zin_l, valid_l, *rest):
            halo, stripes = rest[:n_halo], rest[n_halo:]
            zs = gather_z_sparse(r_l, inv_l, halo)
            total = accumulate_stripes(zs, stripes)
            contrib_l = merge_sparse(total, halo)
            out = vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)
            # Each position's contribution lands at its owner exactly
            # once (the window/trash-band construction), so per-shard
            # sums of contrib_l add to the full contribution total.
            return (*out, *_ledger_partials(contrib_l, r_l, zin_l,
                                            accum))

        halo_in_specs = (P(axis),) * 5 + tuple(halo_specs) \
            + (P(axis, None), P(axis), P()) * n_stripes
        step_core = shard_map(
            vs_body,
            mesh=mesh,
            in_specs=halo_in_specs,
            out_specs=(P(axis), P(), P()),
        )
        self._step_core_ledger = shard_map(
            vs_body_ledger,
            mesh=mesh,
            in_specs=halo_in_specs,
            out_specs=(P(axis), P(), P()) + (P(axis),) * 3,
        )

        self._contrib_args = tuple(halo_args) + tuple(
            a for triple in zip(self._src, self._row_block, ids)
            for a in triple
        )
        self._inv_in_args = True
        self._step_core = step_core
        self._step_fn = self._jit_step(step_core)
        self._exchange_core = self._make_exchange_core(
            gather_z_fn=lambda r_l, inv_l, rest: gather_z_sparse(
                r_l, inv_l, rest[:n_halo]),
            merge_fn=lambda flat, rest: merge_sparse(flat, rest[:n_halo]),
            n_state_flat=n_vs - padv, accum=accum,
            in_specs=(P(axis),) * 5 + tuple(halo_specs)
            + (P(axis, None), P(axis), P()) * n_stripes,
        )
        self._fused_cache = {}
        self.last_run_metrics = {
            "l1_delta": np.zeros(0, self._accum_dtype),
            "dangling_mass": np.zeros(0, self._accum_dtype),
        }
        self._layout = dict(self._layout, form="vs_halo",
                            halo=plan.summary())
        obs_log.info(
            f"sparse boundary exchange: head K={K}, {nread} read + "
            f"{len(WR)} write round(s), model "
            f"{plan.sparse_bytes_per_iter():,} vs dense "
            f"{plan.dense_bytes_per_iter():,} B/chip/iter"
        )

    def _setup_vs_halo_async(self, *, plan, RR, WR, K, halo_args,
                             halo_specs, n_halo, ids, zd, accum, pair,
                             blk, n_vs, padv, total_z, n_stripes,
                             accumulate_stripes, vs_tail, merge_sparse):
        """Asynchronous stale-boundary halo step (ISSUE 17;
        config.halo_async; Kollias et al., arXiv:cs/0606047; overlap
        per arXiv:2009.10443): the PR 8 plan's exchange, double-
        buffered so it leaves the critical path.

        A per-device boundary buffer of width ``W = K + sum(read
        widths)`` — the head-replica plane followed by each read
        round's ppermute landing zone — rides the step carry
        (``_device_args`` index 1, donated like the rank buffer).
        Iteration k:

          1. ships THIS iteration's boundary: the SAME head psum and
             read-round ppermutes as the synchronous gather, landing
             in the buffer returned as the next carry (``buf_new``) —
             nothing waits on them;
          2. builds the sparse z image from the STALE buffer
             (iteration k-1's boundary): stale head at [0, K), stale
             landings scatter-added at their global ids, then the OWN
             block written LAST — a device's own partition is always
             fresh, only remote boundary reads lag one iteration;
          3. per-stripe gathers + the write-band contribution merge
             run unchanged (merge stays synchronous: windows are
             consumed by the same iteration's rank update).

        XLA sees the shipped collectives feeding only the carry output
        while the segment-sum consumes the stale buffer — no data
        dependence between them, so the scheduler is free to overlap
        wire and compute and the per-step cost drops from compute +
        comms toward max(compute, comms). The collective MULTISET is
        identical to vs_halo's (overlap reorders, never adds —
        contract PTC001 pins it).

        Staleness bookkeeping: the buffer is PRIMED from the current
        rank vector at build end and after every state replacement
        (set_ranks — which snapshot resume, elastic rescue and the SDC
        redo all route through), so the first step after any (re)start
        is exactly the synchronous step and the lag never exceeds
        config.stale_max_lag = 1. Convergence under bounded staleness
        is classical (async iterations contract under the same
        spectral radius); the measured cost is a few extra iterations
        to tol, bounded by the bench staleness sweep and the probe
        residuals."""
        cfg = self.config
        mesh = self._mesh
        axis = cfg.mesh_axis
        ndev = mesh.devices.size
        nread = len(RR)
        W = K + sum(r.width for r in RR)
        assert W > 0, "gate admits only plans with a boundary"
        shard2d = jax.sharding.NamedSharding(mesh, P(axis, None))

        def ship_boundary(z_le, halo):
            """This iteration's boundary onto the wire: head psum +
            one ppermute per read round — the synchronous gather's
            exact collectives — concatenated into the [1, W] buffer
            slot the NEXT iteration consumes."""
            me = jax.lax.axis_index(axis)
            parts = []
            if K:
                idx = jnp.arange(K, dtype=jnp.int32)
                pos = idx - me * blk
                vals = z_le[jnp.clip(pos, 0, blk)]
                mask = (pos >= 0) & (pos < blk)
                parts.append(jax.lax.psum(
                    jnp.where(mask, vals, jnp.zeros((), zd)), axis
                ))
            for i, rnd in enumerate(RR):
                si = halo[2 * i][0]  # [width] owner-local send indices
                parts.append(
                    jax.lax.ppermute(z_le[si], axis, perm=rnd.perm)
                )
            return jnp.concatenate(parts)[None, :]

        def stale_z_image(z_l, buf_l, halo):
            """The sparse z image from LAST iteration's boundary
            buffer. Same landing geometry as the synchronous gather
            (head window, then unique scatter landings); the own block
            goes in LAST so it is always this iteration's fresh z —
            head/landing ids never alias another device's block, so
            the one overwrite the orders differ on ([0, K) cap own
            block) resolves to the fresh owner copy, exactly like the
            sync path's psum-overwrite no-op."""
            me = jax.lax.axis_index(axis)
            b = buf_l[0]
            zf = jnp.zeros(n_vs + 1, zd)
            if K:
                zf = jax.lax.dynamic_update_slice(zf, b[:K], (0,))
            off = K
            for i, rnd in enumerate(RR):
                gi = halo[2 * i + 1][0]  # [width] global landing ids
                zf = zf.at[gi].add(b[off:off + rnd.width])
                off += rnd.width
            zf = jax.lax.dynamic_update_slice(zf, z_l, (me * blk,))
            z = zf[:n_vs]
            if total_z > n_vs:
                z = jnp.concatenate(
                    [z, jnp.zeros(total_z - n_vs, zd)]
                )
            return _split_pair(z) if pair else (z,)

        def vs_body_async(r_l, buf_l, inv_l, dang_l, zin_l, valid_l,
                          *rest):
            halo, stripes = rest[:n_halo], rest[n_halo:]
            z_l = r_l.astype(zd) * inv_l
            z_le = jnp.concatenate([z_l, jnp.zeros(1, zd)])
            buf_new = ship_boundary(z_le, halo)
            zs = stale_z_image(z_l, buf_l, halo)
            total = accumulate_stripes(zs, stripes)
            contrib_l = merge_sparse(total, halo)
            out = vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)
            return (out[0], buf_new, out[1], out[2])

        def vs_body_async_ledger(r_l, buf_l, inv_l, dang_l, zin_l,
                                 valid_l, *rest):
            halo, stripes = rest[:n_halo], rest[n_halo:]
            z_l = r_l.astype(zd) * inv_l
            z_le = jnp.concatenate([z_l, jnp.zeros(1, zd)])
            buf_new = ship_boundary(z_le, halo)
            zs = stale_z_image(z_l, buf_l, halo)
            total = accumulate_stripes(zs, stripes)
            contrib_l = merge_sparse(total, halo)
            out = vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)
            return (out[0], buf_new, out[1], out[2],
                    *_ledger_partials(contrib_l, r_l, zin_l, accum))

        async_in_specs = (P(axis), P(axis, None)) + (P(axis),) * 4 \
            + tuple(halo_specs) \
            + (P(axis, None), P(axis), P()) * n_stripes
        step_core = shard_map(
            vs_body_async,
            mesh=mesh,
            in_specs=async_in_specs,
            out_specs=(P(axis), P(axis, None), P(), P()),
        )
        self._step_core_ledger = shard_map(
            vs_body_async_ledger,
            mesh=mesh,
            in_specs=async_in_specs,
            out_specs=(P(axis), P(axis, None), P(), P())
            + (P(axis),) * 3,
        )

        self._contrib_args = tuple(halo_args) + tuple(
            a for triple in zip(self._src, self._row_block, ids)
            for a in triple
        )
        self._inv_in_args = True
        self._step_core = step_core
        # A zero pre-prime buffer so _device_args is well-formed while
        # the step jits; the REAL boundary is primed below, before any
        # caller can step.
        self._carry_args = (jax.device_put(
            np.zeros((ndev, W), zd), shard2d
        ),)
        self._step_fn = self._jit_step(step_core)

        def exchange_body(r_l, buf_l, inv_l, dang_l, zin_l, valid_l,
                          *rest):
            halo = rest[:n_halo]
            z_l = r_l.astype(zd) * inv_l
            z_le = jnp.concatenate([z_l, jnp.zeros(1, zd)])
            buf_new = ship_boundary(z_le, halo)
            zs = stale_z_image(z_l, buf_l, halo)
            # Dependency seed from BOTH exchange halves (ship + stale
            # read) so neither DCEs out of the timing program.
            flat = jnp.zeros(n_vs - padv, accum).at[0].add(
                zs[0][0].astype(accum) + buf_new[0, 0].astype(accum)
            )
            contrib_l = merge_sparse(flat, halo)
            return contrib_l[:1]

        self._exchange_core = shard_map(
            exchange_body, mesh=mesh, in_specs=async_in_specs,
            out_specs=P(axis), check_vma=False,
        )

        read_args = tuple(halo_args[:2 * nread])
        read_specs = tuple(halo_specs[:2 * nread])

        def prime_body(r_l, inv_l, *halo):
            z_l = r_l.astype(zd) * inv_l
            z_le = jnp.concatenate([z_l, jnp.zeros(1, zd)])
            return ship_boundary(z_le, halo)

        prime_core = shard_map(
            prime_body, mesh=mesh,
            in_specs=(P(axis), P(axis)) + read_specs,
            out_specs=P(axis, None), check_vma=False,
        )

        def prime_carry():
            fn = self._fused_cache.get("carry_prime")
            if fn is None:
                with obs_trace.span("engine/compile",
                                    form="halo_prime"):
                    fn = jax.jit(prime_core)
                self._fused_cache["carry_prime"] = fn
            return (fn(self._r, self._inv_out, *read_args),)

        self._carry_prime = prime_carry
        self._fused_cache = {}
        self.last_run_metrics = {
            "l1_delta": np.zeros(0, self._accum_dtype),
            "dangling_mass": np.zeros(0, self._accum_dtype),
        }
        self._layout = dict(
            self._layout, form="vs_halo_async", halo=plan.summary(),
            halo_async=f"on:lag{int(cfg.stale_max_lag)}",
            halo_buffer_width=int(W),
        )
        obs_log.info(
            f"async stale-boundary exchange: head K={K}, {nread} read "
            f"+ {len(WR)} write round(s), buffer {W} x "
            f"{jnp.dtype(zd).itemsize} B/device, overlappable "
            f"{plan.overlappable_bytes_per_iter():,} of "
            f"{plan.sparse_bytes_per_iter():,} B/chip/iter"
        )
        self._prime_carry()

    def _setup_multi_dispatch_vs(self, *, n_stripes, sz, gw, group, pair,
                                 accum, num_blocks, chunks, num_present,
                                 prefix_flags, ids, n_vs, padv, gather_z,
                                 merge_scatter):
        """Vertex-sharded counterpart of _setup_multi_dispatch for
        layouts past SCAN_STRIPE_UNITS: the SAME per-stripe compiled
        executables (replicated z planes in, compact per-device partials
        out — _make_ms_stripe_fns), with the prescale and finalize
        re-homed to sharded state: the prescale shard_map all_gathers
        the sharded z, the finalize scatters each device's OWN partials
        into the block accumulator and reduce-scatters the merge before
        the local rank update (no .sum(0) cross-device reduce — the
        psum_scatter IS the reduction)."""
        mesh = self._mesh
        axis = self.config.mesh_axis
        nz = 2 if pair else 1

        pres = shard_map(
            gather_z,
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(),) * nz,
            # The planes ARE replicated (tiled all_gather output), but
            # the static varying-mesh-axes checker cannot infer that
            # through the concat/Dekker-split epilogue.
            check_vma=False,
        )
        self._ms_prescale = jax.jit(pres)
        self._ms_stripe_fns = self._make_ms_stripe_fns(
            n_stripes=n_stripes, sz=sz, gw=gw, group=group, pair=pair,
            accum=accum, num_blocks=num_blocks, chunks=chunks,
            num_present=num_present,
        )
        self._ms_stripe = self._ms_stripe_fns[0]
        vs_tail = self._vs_tail

        accum_dt = self._accum_dtype

        def _merge_vs_parts(rest):
            parts = rest[:n_stripes]
            ids_l = rest[n_stripes : 2 * n_stripes]
            total = jnp.zeros((num_blocks, 128), accum_dt)
            for s in range(n_stripes):
                # parts[s] is this device's OWN compact partial
                # ([1, Ps, 128] under the P(axis, None, None) spec);
                # the cross-device reduction happens in merge_scatter's
                # psum_scatter, not here.
                total = spmv.scatter_block_sums(
                    total, parts[s][0], ids_l[s], prefix_flags[s]
                )
            return total

        def final_body(r_l, *rest):
            dang_l, zin_l, valid_l = rest[2 * n_stripes :]
            contrib_l = merge_scatter(_merge_vs_parts(rest))
            return vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)

        def final_body_ledger(r_l, *rest):
            dang_l, zin_l, valid_l = rest[2 * n_stripes :]
            contrib_l = merge_scatter(_merge_vs_parts(rest))
            out = vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)
            return (*out, *_ledger_partials(contrib_l, r_l, zin_l,
                                            accum_dt))

        ms_in_specs = (P(axis),) \
            + (P(axis, None, None),) * n_stripes \
            + (P(),) * n_stripes \
            + (P(axis),) * 3
        self._ms_final = jax.jit(
            shard_map(
                final_body,
                mesh=mesh,
                in_specs=ms_in_specs,
                out_specs=(P(axis), P(), P()),
            ),
            donate_argnums=(0,),
        )
        self._ms_final_ledger = jax.jit(
            shard_map(
                final_body_ledger,
                mesh=mesh,
                in_specs=ms_in_specs,
                out_specs=(P(axis), P(), P()) + (P(axis),) * 3,
            ),
            donate_argnums=(0,),
        )
        self._ms_ids = list(ids)
        self._ms_n_stripes = n_stripes
        self._layout = dict(self._layout, form="vs_multi_dispatch")

    def _setup_ell_vs_bounded(self, src_slots, w_slots, row_blocks,
                              mass_mask, zero_in, valid, *, n, n_state,
                              inv_out_rel, sz, n_stripes, gw, group,
                              z_dtype, z_item, chunk_cands):
        """Destination-partitioned (owner-computes) vertex sharding —
        config.vs_bounded, VERDICT r4 #1 / ROADMAP "Engine" stages
        (a)+(b). The plain vertex-sharded mode shards the persistent
        per-vertex state but each chip still materializes O(N) step
        transients: the all_gathered z planes and the [num_blocks, 128]
        accumulator, merged by an O(N)-per-chip psum. Here:

          - dst blocks are DEALT across contiguous device ranges by
            capacity-constrained LPT over in-degree depth
            (ops/ell.deal_block_order, composed into the relabel by the
            packer), so each device's range carries a near-equal share
            of slot rows despite power-law skew;
          - each device holds exactly the slot rows whose dst block
            falls in its OWN range (stage b): the contribution
            accumulator shrinks to the local [num_blocks/ndev, 128]
            and the cross-device contribution merge disappears — the
            per-dst sums are computed where they are owned;
          - the per-stripe z planes are built by one [stripe_span] psum
            each (stage a): every device zero-extends its local z
            shard, takes a clamped dynamic-slice at the stripe's
            offset (non-overlapping devices land wholly in the zero
            pads), and the psum of those disjoint slices IS the
            replicated stripe plane — exact, since each element has
            one nonzero contributor.

        Per-chip per-step transients are O(stripe_span + N/ndev) —
        never O(N) — and per-iteration ICI traffic is one psum of
        ~total_z = N elements (the plain mode moves the same N through
        all_gather + psum). Numerics: a dst block's rows are summed on
        ONE chip (sequential chunked scan) instead of split across
        chips and psum-merged, so ranks agree with the other modes to
        accumulation-dtype rounding, not bitwise (identical at ndev=1,
        where this mode degenerates to the same row order).

        Dispatch forms mirror the replicated mode: at or below
        SCAN_STRIPE_UNITS the step is ONE fused shard_map program
        (measured-fastest; see the step-construction comment), past it
        pipelined per-stripe z-broadcast + gather dispatches
        (run_fused/run_fused_tol delegate via run_fused_chunked). The
        analogue in the reference: Spark's reduceByKey delivers each
        key's sums to the partition that OWNS the key
        (Sparky.java:229), which is precisely owner-computes; the
        plain mode's merge-everywhere was the deviation. Requires a
        host-built graph (the device builder does not deal dst
        blocks)."""
        cfg = self.config
        mesh = self._mesh
        axis = cfg.mesh_axis
        ndev = mesh.devices.size
        accum = self._accum_dtype
        pair = self._pair
        if not isinstance(src_slots[0], np.ndarray):
            raise ValueError(
                "vs_bounded requires a host-built graph (build(), not "
                "build_device: the device builder does not deal dst "
                "blocks across device ranges)"
            )

        unit = 128 * ndev
        n_vs = -(-n_state // unit) * unit
        blk = n_vs // ndev
        nbd = blk // 128  # local dst blocks per device

        inv_out_rel = np.asarray(inv_out_rel)
        if inv_out_rel.dtype != z_dtype:
            inv_out_rel = inv_out_rel.astype(z_dtype)
        self._kernel = "ell"
        self._place_vs_state(
            mass_mask, zero_in, valid, inv_out_rel, n=n, n_vs=n_vs, xp=np
        )

        # -- dst-partitioned slot placement --------------------------------
        log2g = group.bit_length() - 1
        sent = np.int32(sz << log2g)
        shard2d = jax.sharding.NamedSharding(mesh, P(axis, None))
        e_shard = mesh_lib.edge_sharding(mesh)
        cand_max = chunk_cands[-1]

        self._src, self._row_block = [], []
        ids_list, num_present, stripe_rows_dev = [], [], []
        dev_bounds = np.arange(ndev + 1, dtype=np.int64) * nbd
        for s in range(n_stripes):
            if w_slots[s] is None:
                ss_all = src_slots[s]
            else:
                ss_all = np.where(w_slots[s] != 0, src_slots[s], sent)
            rb_all = row_blocks[s]
            # row_block is ascending, so each device's rows are one
            # contiguous run ending at its dst-range boundary.
            cuts = np.searchsorted(rb_all, dev_bounds)
            per_dev = []
            rows_max = 1
            for d in range(ndev):
                lo, hi = int(cuts[d]), int(cuts[d + 1])
                rb_local = (
                    rb_all[lo:hi].astype(np.int64) - d * nbd
                ).astype(np.int32)
                rk, ids_d, pc, _prefix = ell_lib.dense_block_ranks(
                    rb_local, nbd
                )
                per_dev.append((ss_all[lo:hi], rk, ids_d, pc))
                rows_max = max(rows_max, hi - lo)
            Ps = max(pc for (_, _, _, pc) in per_dev)
            if rows_max >= cand_max:
                chunk_rows = cand_max
            else:
                chunk_rows = 1 << (rows_max - 1).bit_length()
            rows_pad = -(-rows_max // chunk_rows) * chunk_rows
            ss_parts, rk_parts, ids_parts = [], [], []
            for ssd, rk, ids_d, pc in per_dev:
                padr = rows_pad - ssd.shape[0]
                if padr:
                    # Pad rows are all-sentinel (zero gather) at the
                    # LAST rank — kept ascending; their zero sums land
                    # on a real rank or drop out of the chunk span.
                    ssd = np.concatenate(
                        [ssd, np.full((padr, 128), sent, np.int32)]
                    )
                    rk = np.concatenate(
                        [rk, np.full(padr, Ps - 1, np.int32)]
                    )
                if ids_d.shape[0] < Ps:
                    # Pad with CONSECUTIVE ids past nbd (a trash band
                    # on the accumulator): sorted AND unique is
                    # preserved, so the finalize scatter keeps XLA's
                    # fast sorted-unique path — a repeated-last-id pad
                    # forfeits unique_indices and measured 2.8x slower
                    # end-to-end at scale 23 (the non-unique scatter
                    # serializes). The padded ranks carry zero sums.
                    pad_n = Ps - ids_d.shape[0]
                    ids_d = np.concatenate([
                        ids_d,
                        nbd + np.arange(pad_n, dtype=np.int32),
                    ])
                ss_parts.append(ssd)
                rk_parts.append(rk)
                ids_parts.append(ids_d)
            self._src.append(
                jax.device_put(np.concatenate(ss_parts), shard2d)
            )
            self._row_block.append(
                jax.device_put(np.concatenate(rk_parts), e_shard)
            )
            ids_list.append(jax.device_put(np.stack(ids_parts), shard2d))
            num_present.append(Ps)
            stripe_rows_dev.append(rows_pad)

        chosen = self._autotune_chunk(
            chunk_cands, stripe_rows_dev, sz, z_item, gw, group, pair,
            accum, num_present, ndev,
        )
        ell_chunks = [min(chosen, r) for r in stripe_rows_dev]
        self._layout = {
            "form": "vs_bounded",
            "group": group,
            "gather_width": gw,
            "n_stripes": n_stripes,
            "stripe_span": sz,
            "partition_span": 0,
            "chunk": max(ell_chunks) if ell_chunks else None,
            "pair": bool(pair),
            "stream_dtype": None,
        }

        # -- step construction --------------------------------------------
        # Mirrors the replicated architecture (and for the same
        # measured reason): at or below SCAN_STRIPE_UNITS the whole
        # step is ONE shard_map program — XLA's cross-op fusion around
        # the chunked gather is worth 2.3x at the big single-stripe
        # geometry (scale 23: 662 ms/iter fused vs 1507 through the
        # dispatch-per-stripe machinery, with the gather dispatch
        # itself accounting for the difference at identical chunks).
        # Past the threshold the unrolled program exceeds the
        # remote-compile limit and the multi-dispatch machinery takes
        # over: a z-broadcast dispatch per stripe feeding the SAME
        # per-stripe gather executables as the replicated mode, then a
        # local finalize.
        zd = jnp.dtype(z_dtype)
        vs_tail = self._make_vs_tail(accum, n)
        S = n_stripes
        multi_dispatch = n_stripes * (2 if pair else 1) > self.SCAN_STRIPE_UNITS
        # Accumulator with a trash band: pad ids land at nbd..nbd+Ps-1
        # (zero partials), keeping every scatter sorted AND unique — a
        # repeated-last-id pad forfeits unique_indices and the scatter
        # serializes.
        trash = max(num_present) if n_stripes else 1

        def stripe_plane(z_l, s):
            """Stage (a): per-stripe z broadcast — replicated [sz]
            plane from the sharded z. The start is clipped EXPLICITLY
            not to guard against wraparound — lax.dynamic_slice CLAMPS
            out-of-bounds starts toward the valid range (it does not
            wrap NumPy-style) — but to FORCE the intended landing: the
            clip pins a no-overlap device's slice wholly inside the
            zero pads (clamping alone would leave the landing implicit
            in the slice-size arithmetic). After the clip, both
            out-of-range destinations are zero pads, overlapping
            devices are in-range (no clip), and each element of the
            psum has ONE nonzero contributor (exact)."""
            zeros = jnp.zeros(sz, z_l.dtype)
            ze = jnp.concatenate([zeros, z_l, zeros])
            off = jnp.clip(
                s * sz + sz - jax.lax.axis_index(axis) * blk,
                0, blk + sz,
            )
            return jax.lax.psum(
                jax.lax.dynamic_slice_in_dim(ze, off, sz), axis
            )

        def stripe_part(zp, src_s, rb_s, s):
            """Gather + compact segment-sum for one stripe; ``zp`` is
            the [sz] replicated plane."""
            zp = jnp.concatenate([zp, jnp.zeros(gw, zp.dtype)])
            Ps = num_present[s]
            if pair:
                hi, lo = _split_pair(zp)
                part = spmv.ell_contrib_pair(
                    hi, lo, src_s, rb_s, Ps, accum_dtype=accum,
                    gather_width=gw, chunk_rows=ell_chunks[s],
                    group=group, num_present=Ps,
                )
            else:
                part = spmv.ell_contrib(
                    zp, src_s, rb_s, Ps, accum_dtype=accum,
                    gather_width=gw, chunk_rows=ell_chunks[s],
                    group=group, num_present=Ps,
                )
            return part.reshape(Ps, 128)

        self._inv_in_args = True
        self._fused_cache = {}
        self.last_run_metrics = {
            "l1_delta": np.zeros(0, self._accum_dtype),
            "dangling_mass": np.zeros(0, self._accum_dtype),
        }
        self._contrib_args = tuple(
            a for triple in zip(self._src, self._row_block, ids_list)
            for a in triple
        )

        if not multi_dispatch:
            def _vsb_contrib(r_l, inv_l, rest):
                z_l = r_l.astype(zd) * inv_l
                total = jnp.zeros((nbd + trash, 128), accum)
                for s in range(S):
                    src_s, rb_s, ids_s = rest[3 * s : 3 * s + 3]
                    part = stripe_part(stripe_plane(z_l, s), src_s,
                                       rb_s, s)
                    # Stage (b): each device's partials land ONLY in
                    # its own local dst range — no cross-device merge.
                    total = total.at[ids_s[0]].add(
                        part, indices_are_sorted=True,
                        unique_indices=True,
                    )
                return total[:nbd].reshape(-1)

            def vs_body(r_l, inv_l, dang_l, zin_l, valid_l, *rest):
                contrib_l = _vsb_contrib(r_l, inv_l, rest)
                return vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)

            def vs_body_ledger(r_l, inv_l, dang_l, zin_l, valid_l,
                               *rest):
                contrib_l = _vsb_contrib(r_l, inv_l, rest)
                out = vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)
                return (*out, *_ledger_partials(contrib_l, r_l, zin_l,
                                                accum))

            vsb_in_specs = (P(axis),) * 5 \
                + (P(axis, None), P(axis), P(axis, None)) * S
            step_core = shard_map(
                vs_body, mesh=mesh,
                in_specs=vsb_in_specs,
                out_specs=(P(axis), P(), P()),
            )
            self._step_core = step_core
            self._step_fn = self._jit_step(step_core)
            self._step_core_ledger = shard_map(
                vs_body_ledger, mesh=mesh,
                in_specs=vsb_in_specs,
                out_specs=(P(axis), P(), P()) + (P(axis),) * 3,
            )
            return

        # -- multi-dispatch form (past SCAN_STRIPE_UNITS) ------------------
        def pres(r_l, inv_l):
            return (r_l.astype(zd) * inv_l,)

        self._ms_prescale = jax.jit(shard_map(
            pres, mesh=mesh,
            in_specs=(P(axis), P(axis)), out_specs=(P(axis),),
        ))

        gather_fns = self._make_ms_stripe_fns(
            n_stripes=n_stripes, sz=sz, gw=gw, group=group, pair=pair,
            accum=accum, num_blocks=nbd, chunks=ell_chunks,
            num_present=num_present, local_planes=True,
        )
        nz = 2 if pair else 1

        def make_zb_fn(s):
            def zb_body(z_l):
                zp = stripe_plane(z_l, s)
                return _split_pair(zp) if pair else (zp,)

            return jax.jit(shard_map(
                zb_body, mesh=mesh,
                in_specs=(P(axis),), out_specs=(P(),) * nz,
                # The planes ARE replicated (psum output), but the
                # static varying-mesh-axes checker cannot infer that
                # through the Dekker-split epilogue.
                check_vma=False,
            ))

        def make_stripe_fn(s):
            zb, gf = make_zb_fn(s), gather_fns[s]

            def call(z_l, src, rb):
                return gf(*zb(z_l), src, rb)

            return call

        self._ms_stripe_fns = [
            make_stripe_fn(s) for s in range(n_stripes)
        ]
        self._ms_stripe = self._ms_stripe_fns[0]

        def _vsb_merge(rest):
            parts = rest[:S]
            ids_l = rest[S : 2 * S]
            total = jnp.zeros((nbd + trash, 128), accum)
            for s in range(S):
                # Stage (b): each device's partials land ONLY in its
                # own local dst range — no cross-device merge exists.
                total = total.at[ids_l[s][0]].add(
                    parts[s][0], indices_are_sorted=True,
                    unique_indices=True,
                )
            return total[:nbd].reshape(-1)

        def final_body(r_l, *rest):
            dang_l, zin_l, valid_l = rest[2 * S :]
            contrib_l = _vsb_merge(rest)
            return vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)

        def final_body_ledger(r_l, *rest):
            dang_l, zin_l, valid_l = rest[2 * S :]
            contrib_l = _vsb_merge(rest)
            out = vs_tail(contrib_l, r_l, dang_l, zin_l, valid_l)
            return (*out, *_ledger_partials(contrib_l, r_l, zin_l,
                                            accum))

        vsb_ms_in_specs = (P(axis),) \
            + (P(axis, None, None),) * S \
            + (P(axis, None),) * S \
            + (P(axis),) * 3
        self._ms_final = jax.jit(
            shard_map(
                final_body, mesh=mesh,
                in_specs=vsb_ms_in_specs,
                out_specs=(P(axis), P(), P()),
            ),
            donate_argnums=(0,),
        )
        self._ms_final_ledger = jax.jit(
            shard_map(
                final_body_ledger, mesh=mesh,
                in_specs=vsb_ms_in_specs,
                out_specs=(P(axis), P(), P()) + (P(axis),) * 3,
            ),
            donate_argnums=(0,),
        )
        self._ms_ids = ids_list
        self._ms_n_stripes = S
        self._layout = dict(self._layout, form="vsb_multi_dispatch")

    def _finalize(self, contrib_fn, contrib_args, mass_mask, zero_in, valid,
                  n, n_state, prescale=None):
        """Masks + r0 placement and the fused jitted step."""
        cfg = self.config
        dtype = self._dtype
        accum = self._accum_dtype
        rep = mesh_lib.replicated(self._mesh)

        xp = np if isinstance(mass_mask, np.ndarray) else jnp
        self._n_state = n_state
        self._state_sharding = rep
        # Masks live on device as bool (1 byte/vertex) and are cast to
        # the accumulation dtype INSIDE the step (update_tail), where
        # XLA fuses the cast into the consuming elementwise ops. Storing
        # them pre-cast to the rank dtype — f64 in the accuracy config —
        # tripled the replicated per-vertex footprint for zero speed
        # (VERDICT r3 weak #2: ~2.7 GB of replicated vectors at
        # scale-26 f64 before any gather table).
        self._dangling = jax.device_put(xp.asarray(mass_mask, bool), rep)
        self._zero_in = jax.device_put(xp.asarray(zero_in, bool), rep)
        valid = xp.asarray(valid, bool)
        self._valid = jax.device_put(valid, rep)

        # Initial value uses the TRUE n (1/n in textbook mode), laid out
        # over the padded state vector with zeros in padding lanes.
        r0_value = 1.0 if cfg.semantics == "reference" else 1.0 / n
        r0 = xp.full(n_state, r0_value, dtype=dtype) * valid
        self._r = jax.device_put(jnp.asarray(r0, dtype=dtype), rep)
        self.iteration = 0

        damping = cfg.damping
        semantics = cfg.semantics

        def update_tail(contrib, r, dangling, zero_in, valid_m):
            """Rank update + masks + L1 delta — the ONE spelling shared
            by the fused step and the multi-dispatch finalize so the
            semantics cannot drift between dispatch forms."""
            m = spmv.dangling_mass(r, dangling, accum)
            r_new = pr_model.apply_update(
                contrib, r.astype(accum), zero_in.astype(accum), m, n,
                damping, semantics, jnp,
            )
            r_new = (r_new * valid_m.astype(accum)).astype(r.dtype)
            delta = jnp.sum(jnp.abs(r_new.astype(accum) - r.astype(accum)))
            return r_new, delta, m

        self._update_tail = update_tail

        # With a prescale, the step takes the 1/out-degree vector as a
        # runtime argument (see _setup_ell: closed-over device arrays
        # embed as HLO constants and can blow the remote-compile
        # request limit at scale). The coo path has no prescale and no
        # inv argument.
        self._inv_in_args = prescale is not None
        if prescale is None:
            def step_core(r, dangling, zero_in, valid_m, *c_args):
                contrib = contrib_fn(r, *c_args)[: r.shape[0]]
                return update_tail(contrib, r, dangling, zero_in, valid_m)

            def step_core_ledger(r, dangling, zero_in, valid_m,
                                 *c_args):
                contrib = contrib_fn(r, *c_args)[: r.shape[0]]
                led = _ledger_sums(contrib, r, zero_in, accum)
                return (*update_tail(contrib, r, dangling, zero_in,
                                     valid_m), *led)
        else:
            def step_core(r, inv, dangling, zero_in, valid_m, *c_args):
                z = prescale(r, inv)
                zs = z if isinstance(z, tuple) else (z,)
                contrib = contrib_fn(*zs, *c_args)[: r.shape[0]]
                return update_tail(contrib, r, dangling, zero_in, valid_m)

            def step_core_ledger(r, inv, dangling, zero_in, valid_m,
                                 *c_args):
                z = prescale(r, inv)
                zs = z if isinstance(z, tuple) else (z,)
                contrib = contrib_fn(*zs, *c_args)[: r.shape[0]]
                led = _ledger_sums(contrib, r, zero_in, accum)
                return (*update_tail(contrib, r, dangling, zero_in,
                                     valid_m), *led)

        # Rank-mass-ledger step variant (ISSUE 13): the SAME body plus
        # three local reductions over intermediates the plain step
        # already computes — compiled lazily only when a probed run
        # wants the ledger (step_probed), so plain runs never pay.
        self._step_core_ledger = step_core_ledger
        self._contrib_args = contrib_args
        self._step_core = step_core
        self._step_fn = jax.jit(step_core, donate_argnums=(0,))
        self._fused_cache = {}
        # Per-iteration traces of the most recent run_fused; empty until
        # one runs (kept across no-op repeat calls).
        self.last_run_metrics = {
            "l1_delta": np.zeros(0, self._accum_dtype),
            "dangling_mass": np.zeros(0, self._accum_dtype),
        }

    # -- comms accounting (ISSUE 8; parallel/comms.py) ---------------------

    def _jit_step(self, step_core):
        """jit the fused step with the rank donation routed through the
        ``usable_donations`` pre-filter (same protocol as
        utils/compile_cache.stage_call): a donation whose aval cannot
        match an output never aliases — it only emits the 'Some donated
        buffers were not usable' lowering warning, the class that sat
        in the MULTICHIP_r05 tail. The structural half is contract
        PTC003 (extended to the vertex-sharded forms)."""
        from pagerank_tpu.utils.compile_cache import usable_donations

        # The rank buffer donates always; step-carried state (the async
        # boundary buffer) donates right behind it — each slot's output
        # aval matches its input, so the pre-filter keeps them all on
        # every supported backend.
        want = tuple(range(1 + len(self._carry_args)))
        donate = usable_donations(step_core, self._device_args(), want)
        if donate != want:
            obs_log.warn(
                "rank-buffer donation is not consumable for this step "
                "form; lowering without it"
            )
        return jax.jit(step_core, donate_argnums=donate)

    def _set_comms_model(self, model) -> None:
        """Adopt a per-iteration comms model (parallel/comms.py):
        publish its gauges and keep the per-step counter feed."""
        from pagerank_tpu.parallel import comms as comms_lib

        self._comms_model = model
        self._comms_bytes_per_iter = int(model.get("bytes_per_iter") or 0)
        self._comms_counter = comms_lib.register(model)

    def comms_model(self) -> Optional[Dict[str, object]]:
        """The resolved per-iteration exchange byte model of this build
        (dense or sparse vertex-sharded), or None when the form has no
        per-vertex exchange (replicated modes). Static per build — the
        exchange tables are static, so the model IS the per-iteration
        measurement; bench legs and MULTICHIP artifacts embed it."""
        m = self._comms_model
        return dict(m) if m else None

    def _note_comms(self, iters: int = 1) -> None:
        """Accumulate ``comms.bytes_exchanged`` for ``iters`` executed
        iterations — called from every run form's dispatch site."""
        if self._comms_counter is not None and iters > 0:
            self._comms_counter.inc(
                self._comms_bytes_per_iter * int(iters)
            )

    # -- comms-vs-compute attribution (ISSUE 10; obs/devices.py) -----------

    def _make_exchange_core(self, *, gather_z_fn, merge_fn, n_state_flat,
                            accum, in_specs):
        """The EXCHANGE-ONLY sub-program of a vertex-sharded step: the
        same z exchange (all_gather, or head psum + ppermute rounds)
        and the same contribution merge (reduce-scatter / band
        windows), with the per-stripe gathers — the compute — replaced
        by a zero accumulator. Timing this program against the full
        step attributes the iteration wall between wire and compute
        (obs/devices.attribute_exchange): the Sparse Allreduce line of
        work (arXiv:1312.3020) only pays when comms time is measured
        SEPARATELY from compute, and fake CPU devices can't model ICI
        — only a fenced sub-dispatch on the real mesh can.

        The zero accumulator carries one element seeded from the
        gathered z plane so XLA cannot dead-code-eliminate the gather
        half; the collectives move their full static widths regardless
        (the payloads are static-shaped). Accepts the FULL step
        argument tuple (``_device_args``) so dispatch needs no
        argument re-prep; the stripe tables are simply unused.
        ``check_vma=False``: the varying-mesh-axes checker cannot see
        through the dependency-seed epilogue (the same reason
        _setup_multi_dispatch_vs's prescale disables it)."""
        mesh = self._mesh
        axis = self.config.mesh_axis

        def exchange_body(r_l, inv_l, dang_l, zin_l, valid_l, *rest):
            zs = gather_z_fn(r_l, inv_l, rest)
            flat = jnp.zeros(n_state_flat, accum).at[0].add(
                zs[0][0].astype(accum)
            )
            contrib_l = merge_fn(flat, rest)
            return contrib_l[:1]

        return shard_map(
            exchange_body, mesh=mesh, in_specs=in_specs,
            out_specs=P(axis), check_vma=False,
        )

    def has_exchange_program(self) -> bool:
        """Whether this build can time its exchange separately (the
        fused vertex-sharded forms; multi-dispatch layouts and
        replicated modes cannot)."""
        return self._exchange_core is not None

    def _exchange_step(self):
        """One dispatch of the exchange-only sub-program over the live
        step arguments; returns a tiny device array to fence on.
        Compiled lazily on first call — a run that never attributes
        never lowers it (the attribution-off transparency contract,
        tests/test_devices.py booby trap)."""
        if self._exchange_core is None:
            raise RuntimeError(
                "this layout has no exchange-only program "
                "(replicated or multi-dispatch form)"
            )
        if self._exchange_fn is None:
            with obs_trace.span("engine/compile", form="exchange_only"):
                self._exchange_fn = jax.jit(self._exchange_core)
        return self._exchange_fn(*self._device_args())

    def time_exchange_split(self, iters: int = 10, warmup: int = 2):
        """Fenced sub-dispatch timing for comms-vs-compute attribution
        (obs/devices.attribute_exchange): ``(exchange_s_per_iter,
        step_s_per_iter)``, each measured over ``iters`` dispatches
        behind its own warmup and closed by the honest scalar
        device_get fence (block_until_ready is not honest on tunneled
        backends — the module's measurement protocol). The step half
        ADVANCES the solve state (the rank buffer is donated through
        the timing steps), so the pre-timing rank vector and iteration
        count are restored afterward — attribution is a probe, never a
        mutation; the comms.bytes_exchanged counter DOES count the
        timing steps (they really moved those bytes), so callers that
        assert counter/model equality must read their deltas before
        attributing."""
        import time

        if iters < 1:
            raise ValueError(f"iters must be >= 1, got {iters}")
        r0, it0 = jnp.copy(self._r), self.iteration
        c0 = tuple(jnp.copy(c) for c in self._carry_args)
        try:
            out = None
            for _ in range(max(0, warmup)):
                out = self._exchange_step()
            if out is not None:
                jax.device_get(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = self._exchange_step()
            jax.device_get(out)
            exchange_s = (time.perf_counter() - t0) / iters

            for _ in range(max(0, warmup)):
                self._device_step()
            self.fence()
            t0 = time.perf_counter()
            for _ in range(iters):
                self._device_step()
            self.fence()
            step_s = (time.perf_counter() - t0) / iters
        finally:
            self._r = r0
            if c0:
                self._carry_args = c0
            self.iteration = it0
        return exchange_s, step_s

    # -- iteration --------------------------------------------------------

    def _device_step(self):
        """One iteration; returns (delta, mass) as device scalars. On
        very-many-stripe layouts this is the multi-dispatch sequence
        (prescale, one dispatch per stripe, finalize) — see
        _setup_multi_dispatch; otherwise one fused jitted step."""
        if self._ms_stripe is not None:
            zs = self._ms_prescale(self._r, self._inv_out)
            parts = [
                self._ms_stripe_fns[s](
                    *zs, self._src[s], self._row_block[s]
                )
                for s in range(self._ms_n_stripes)
            ]
            self._r, delta, m = self._ms_final(
                self._r, *parts, *self._ms_ids,
                self._dangling, self._zero_in, self._valid,
            )
            self._note_comms(1)
            return delta, m
        delta, m = self._adopt_step_out(
            self._step_fn(*self._device_args())
        )
        self._note_comms(1)
        return delta, m

    def step(self) -> Dict[str, float]:
        delta, m = self._device_step()
        self._last_step_delta = float(delta)
        return {"l1_delta": self._last_step_delta,
                "dangling_mass": float(m)}

    def _stale_slack(self) -> float:
        """Previous stepwise iteration's L1 delta when the async
        stale-boundary form is live (base-class docstring has the
        bound); 0.0 on every synchronous form AND right after a
        prime (build / set_ranks / restore), where the next step is
        lag-0 exact."""
        if str(self._layout.get("halo_async", "")).startswith("on:"):
            return self._last_step_delta
        return 0.0

    # -- convergence probes (obs/probes.py; ISSUE 5) -----------------------

    def _probe_tail(self, k: int):
        """The ON-DEVICE probe computation over a (padded, relabeled)
        rank vector — THE one spelling shared by the fused probed step
        and the standalone boundary probe so the two cannot drift:
        rank mass in the accumulation dtype, top-k ids over VALID lanes
        (padding masked to -inf; ``lax.top_k`` tie-breaks by lowest
        index, matching the CPU oracle's stable argsort), and the
        entered-count against the previous probe's ids. int32
        throughout (the churn count is a sum of bools — an unpinned
        dtype would widen under the pair config's x64 flip)."""
        accum = self._accum_dtype

        def tail(r, valid_m, prev_ids):
            mass = jnp.sum(r.astype(accum))
            rv = jnp.where(valid_m, r, -jnp.inf)
            vals, ids = jax.lax.top_k(rv, k)
            ids = ids.astype(jnp.int32)
            entered = jnp.sum(
                (ids[:, None] != prev_ids[None, :]).all(axis=1),
                dtype=jnp.int32,
            )
            # Top-k rank concentration (ISSUE 13): the mass the top-k
            # hold — -inf fillers (k > valid lanes) masked out.
            topk_mass = jnp.sum(
                jnp.where(jnp.isfinite(vals), vals,
                          jnp.zeros((), vals.dtype)).astype(accum)
            )
            return mass, ids, entered, topk_mass

        return tail

    def _get_probe_fn(self, k: int):
        """Standalone probe dispatch over the current state — used on
        multi-dispatch layouts (where the step is already a pipelined
        dispatch sequence) and at fused-chunk boundaries. Cached per k
        alongside the fused executables."""
        key = ("probe_fn", k)
        fn = self._fused_cache.get(key)
        if fn is None:
            fn = jax.jit(self._probe_tail(k))
            self._fused_cache[key] = fn
        return fn

    def _get_probed_step(self, k: int, ledger: bool = False):
        """The probe-enabled step: ONE jitted program running the
        step body plus the probe tail on its output — probing adds no
        extra dispatch, no host callback, and no collective beyond the
        form's own budget (the tail is elementwise + top_k on the
        already-merged rank vector; contract PTC007 proves it). The
        rank buffer stays donated exactly like the plain step.
        ``ledger=True`` runs the rank-mass-ledger core instead (same
        body + three local reductions — ISSUE 13; the collective
        multiset still matches the plain step's), appending the raw
        ledger sums to the outputs."""
        key = ("probe_step_ledger" if ledger else "probe_step", k)
        fn = self._fused_cache.get(key)
        if fn is None:
            core = self._step_core_ledger if ledger else self._step_core
            tail = self._probe_tail(k)
            nc = len(self._carry_args)
            # valid's position in the device-args tail (see
            # _device_args: prescaled forms carry inv right after the
            # rank vector and any step-carried state).
            vi = (4 if self._inv_in_args else 3) + nc

            def probed(*args):
                prev_ids = args[-1]
                core_args = args[:-1]
                r2, *rest = core(*core_args)
                carry, (delta, m, *led) = rest[:nc], rest[nc:]
                mass, ids, entered, topk_mass = tail(
                    r2, core_args[vi], prev_ids)
                return (r2, *carry, delta, m, mass, ids, entered,
                        topk_mass, *led)

            from pagerank_tpu.utils.compile_cache import usable_donations

            donate = usable_donations(
                probed,
                (*self._device_args(),
                 jax.ShapeDtypeStruct((k,), jnp.int32)),
                tuple(range(1 + nc)),
            )
            fn = jax.jit(probed, donate_argnums=donate)
            self._fused_cache[key] = fn
        return fn

    def _resolve_probe_k(self, k: int) -> int:
        return max(1, min(int(k), self.graph.n))

    def probe_values(self, k: int, prev_ids):
        """Device-side probe of the CURRENT state (fused-chunk
        boundaries; PageRankEngine.probe_values contract). One
        dispatch, one host sync for the scalars + k ids."""
        k = self._resolve_probe_k(k)
        prev_dev = (jnp.full((k,), jnp.int32(-1)) if prev_ids is None
                    else prev_ids)
        mass, ids, entered, topk_mass = self._get_probe_fn(k)(
            self._r, self._valid, prev_dev
        )
        mass_h, ent_h, ids_np, tm_h = jax.device_get(
            (mass, entered, ids, topk_mass))
        ids_np = np.asarray(ids_np)
        ids_orig = self._perm[ids_np] if self._perm is not None else ids_np
        return (float(mass_h), int(ent_h), ids, np.asarray(ids_orig),
                float(tm_h))

    def _ledger_eps(self) -> float:
        return float(jnp.finfo(self._accum_dtype).eps)

    def _device_step_ledger(self):
        """The multi-dispatch sequence with the LEDGER finalize
        (ISSUE 13): same prescale + per-stripe dispatches, the
        ``_ms_final_ledger`` executable in place of the plain finalize.
        Returns (delta, mass, (contrib_p, retained_p, prev_p)) — the
        ledger values as device arrays (per-shard partials on the
        sharded forms), fetched by step_probed's one device_get."""
        zs = self._ms_prescale(self._r, self._inv_out)
        parts = [
            self._ms_stripe_fns[s](
                *zs, self._src[s], self._row_block[s]
            )
            for s in range(self._ms_n_stripes)
        ]
        self._r, delta, m, lk, rt, pv = self._ms_final_ledger(
            self._r, *parts, *self._ms_ids,
            self._dangling, self._zero_in, self._valid,
        )
        self._note_comms(1)
        return delta, m, (lk, rt, pv)

    def step_probed(self, probes):
        """One iteration + probe in a single device dispatch (the
        multi-dispatch layouts append one standalone probe dispatch to
        their pipelined sequence instead — still zero extra host
        syncs: everything is fetched in the ONE device_get the
        stepwise loop already pays per iteration). When the build
        stashed a ledger core (every form except a pallas downgrade's
        edge cases), the probed step ALSO measures the rank-mass
        ledger sums and the info carries the named decomposition
        (ISSUE 13; obs/graph_profile.mass_ledger_entry)."""
        k = self._resolve_probe_k(probes.topk)
        prev = probes.prev_ids
        prev_dev = jnp.full((k,), jnp.int32(-1)) if prev is None else prev
        led = None
        if self._ms_stripe is not None:
            if self._ms_final_ledger is not None:
                delta, m, led = self._device_step_ledger()
            else:
                delta, m = self._device_step()
            mass, ids, entered, topk_mass = self._get_probe_fn(k)(
                self._r, self._valid, prev_dev
            )
        elif self._step_core_ledger is not None:
            fn = self._get_probed_step(k, ledger=True)
            (delta, m, mass, ids, entered, topk_mass,
             *led) = self._adopt_step_out(
                fn(*self._device_args(), prev_dev))
            self._note_comms(1)
        else:
            fn = self._get_probed_step(k)
            (delta, m, mass, ids, entered,
             topk_mass) = self._adopt_step_out(
                fn(*self._device_args(), prev_dev))
            self._note_comms(1)
        fetch = [delta, m, mass, entered, ids, topk_mass]
        if led:
            fetch.extend(led)
        host = jax.device_get(tuple(fetch))
        d_h, m_h, mass_h, ent_h, ids_np, tm_h = host[:6]
        info = {
            "l1_delta": float(d_h),
            "dangling_mass": float(m_h),
            "rank_mass": float(mass_h),
            "topk_churn": 0 if prev is None else int(ent_h),
            "topk_mass": float(tm_h),
        }
        if led:
            # Sharded forms return per-shard partials ([ndev]); the
            # host finishes the reduction (no step collective).
            lk_h, rt_h, pv_h = host[6:9]
            info["ledger_contrib_total"] = float(np.asarray(lk_h).sum())
            info["ledger_retained_total"] = float(np.asarray(rt_h).sum())
            info["ledger_mass_prev"] = float(np.asarray(pv_h).sum())
            # Ledger first, delta update second: the flow-conservation
            # slack must be the PREVIOUS step's delta (the staleness
            # bound), not this one's.
            info["mass_ledger"] = self._ledger_entry(info)
        self._last_step_delta = info["l1_delta"]
        ids_np = np.asarray(ids_np)
        ids_orig = self._perm[ids_np] if self._perm is not None else ids_np
        return info, (ids, np.asarray(ids_orig))

    # -- silent-data-corruption checks (ISSUE 15; pagerank_tpu/sdc.py) -----

    def sdc_supported(self) -> bool:
        """Whether this build can run the SDC-checked step: it rides
        the rank-mass-ledger cores (ISSUE 13), so every form that
        stashed one qualifies — the fused single-program forms via
        ``_step_core_ledger``, the multi-dispatch forms via the ledger
        finalize."""
        return (self._step_core_ledger is not None
                or self._ms_final_ledger is not None)

    def retain_state(self, iteration: Optional[int] = None):
        """Device-side double buffer for the SDC redo (and any caller
        that must rewind without a snapshot round-trip): an opaque
        ``(iteration, rank copy, carry copies, last delta)`` token.
        The copies stay on device — no host transfer, no decode. The
        carried state (the async boundary buffer) and the previous
        step's L1 delta (the staleness slack the conservation checks
        run under) are part of the token so a redo replays the SAME
        staleness bits AND judges them by the same tolerance —
        bit-determinism of the redo is what makes the SDC verdict
        meaningful."""
        it = self.iteration if iteration is None else int(iteration)
        return (it, jnp.copy(self._r),
                tuple(jnp.copy(c) for c in self._carry_args),
                float(self._last_step_delta))

    def restore_state(self, token) -> None:
        """Rewind to a :meth:`retain_state` token (the token itself
        stays reusable — a second redo restores the same bits). Legacy
        two-field tokens restore the rank vector and re-prime the
        carry from it (lag-0, still correct — just not bit-identical
        to the pre-token staleness)."""
        it, r, *rest = token
        self._r = jnp.copy(r)
        carry = rest[0] if rest else ()
        if carry:
            self._carry_args = tuple(jnp.copy(c) for c in carry)
            self._last_step_delta = (float(rest[1]) if len(rest) > 1
                                     else 0.0)
        else:
            if self._carry_args:
                self._prime_carry()
            # Primed (or synchronous) state: the next step is lag-0
            # exact, so the conservation checks need no slack.
            self._last_step_delta = 0.0
        self.iteration = int(it)

    def _sdc_w(self):
        """The seeded Rademacher projection vector, placed at the
        state sharding in the accumulation dtype (+-1 is exact in any
        float dtype). Built lazily on the first checked step — a
        disarmed run never touches it (the booby-trap contract)."""
        w = self._fused_cache.get("sdc_w")
        if w is None:
            from pagerank_tpu import sdc as sdc_mod

            host = sdc_mod.fingerprint_vector(
                self.config.sdc_seed, self._n_state
            ).astype(self._accum_dtype)
            w = jax.device_put(jnp.asarray(host), self._state_sharding)
            self._fused_cache["sdc_w"] = w
        return w

    def _sdc_specs(self):
        """(state in-spec, per-device out-spec) of the check programs:
        replicated forms run each check over every device's OWN copy
        of the state (the copy-consistency invariant needs exactly
        that), sharded forms over each device's shard — either way the
        [1]-shaped local reductions concatenate to [ndev] under a
        ``P(axis)`` out-spec with NO collective joining the program
        (the ``_ledger_partials`` discipline)."""
        axis = self.config.mesh_axis
        state = P(axis) if self.config.vertex_sharded else P()
        return state, P(axis)

    def _sdc_has_inv(self) -> bool:
        return getattr(self, "_inv_out", None) is not None

    def _get_sdc_state_fn(self):
        """The standalone boundary-state check program: per-device
        (w.r fingerprint, rank-mass, source-mass) local reductions
        over the CURRENT state — the dual-fingerprint counterpart of
        the in-step tail, and the multi-dispatch layouts' whole check
        (dispatched around the pipelined step like the standalone
        probe). Collective- and callback-free by contract (PTC008)."""
        fn = self._fused_cache.get("sdc_state_fn")
        if fn is None:
            accum = self._accum_dtype
            state_spec, out_spec = self._sdc_specs()
            has_inv = self._sdc_has_inv()

            def body(w, r, *inv):
                ra = r.astype(accum)
                fp = jnp.reshape(jnp.sum(ra * w), (1,))
                mass = jnp.reshape(jnp.sum(ra), (1,))
                if inv:
                    src = jnp.reshape(jnp.sum(
                        jnp.where(inv[0] != 0, ra,
                                  jnp.zeros((), accum))), (1,))
                else:
                    src = jnp.zeros(1, accum)
                return fp, mass, src

            sm = shard_map(
                body, mesh=self._mesh,
                in_specs=(state_spec,) * (3 if has_inv else 2),
                out_specs=(out_spec,) * 3,
                # Replicated-input forms compute a per-copy value the
                # static varying-mesh-axes checker cannot type.
                check_vma=False,
            )
            fn = jax.jit(sm)
            self._fused_cache["sdc_state_fn"] = fn
        return fn

    def _get_sdc_step(self):
        """The SDC-checked fused step: the LEDGER core (same body,
        same collective multiset — PTC008 proves it) plus the ABFT
        check tail as one more shard_map of local reductions in the
        SAME program: per-device fingerprints/masses over the input
        and output rank vectors and the directly-measured source
        mass. The rank donation stays consumable exactly like the
        plain step's."""
        fn = self._fused_cache.get("sdc_step")
        if fn is None:
            core = self._step_core_ledger
            accum = self._accum_dtype
            state_spec, out_spec = self._sdc_specs()
            has_inv = self._inv_in_args

            def check_body(w, r_in, r_out, *inv):
                ra, rb = r_in.astype(accum), r_out.astype(accum)
                fp_in = jnp.reshape(jnp.sum(ra * w), (1,))
                mass_in = jnp.reshape(jnp.sum(ra), (1,))
                if inv:
                    src_in = jnp.reshape(jnp.sum(
                        jnp.where(inv[0] != 0, ra,
                                  jnp.zeros((), accum))), (1,))
                else:
                    src_in = jnp.zeros(1, accum)
                fp_out = jnp.reshape(jnp.sum(rb * w), (1,))
                mass_out = jnp.reshape(jnp.sum(rb), (1,))
                return fp_in, mass_in, src_in, fp_out, mass_out

            check = shard_map(
                check_body, mesh=self._mesh,
                in_specs=(state_spec,) * (4 if has_inv else 3),
                out_specs=(out_spec,) * 5,
                check_vma=False,
            )

            nc = len(self._carry_args)

            def sdc_core(w, *args):
                r = args[0]
                r2, *rest = core(*args)
                carry, (delta, m, ck, rt, pv) = rest[:nc], rest[nc:]
                # inv sits right behind the rank vector and any
                # step-carried state (see _device_args).
                extra = (args[1 + nc],) if has_inv else ()
                checks = check(w, r, r2, *extra)
                return (r2, *carry, delta, m, ck, rt, pv, *checks)

            from pagerank_tpu.utils.compile_cache import usable_donations

            donate = usable_donations(
                sdc_core, (self._sdc_w(), *self._device_args()),
                tuple(range(1, 2 + nc)),
            )
            with obs_trace.span("engine/compile", form="sdc_step"):
                fn = jax.jit(sdc_core, donate_argnums=donate)
            self._fused_cache["sdc_step"] = fn
        return fn

    def sdc_state_values(self):
        """One standalone boundary-state check dispatch over the
        current state; per-device numpy arrays on host (full-copy
        values on replicated forms, per-shard partials otherwise)."""
        w = self._sdc_w()
        inv = (self._inv_out,) if self._sdc_has_inv() else ()
        fp, mass, src = self._get_sdc_state_fn()(w, self._r, *inv)
        fp_h, mass_h, src_h = jax.device_get((fp, mass, src))
        # Plain host arrays in the device dtype — the evaluator
        # (sdc.evaluate_check) upcasts once, where the reconciliation
        # arithmetic actually happens.
        return {
            "fp": np.asarray(fp_h),
            "mass": np.asarray(mass_h),
            "src": (np.asarray(src_h)
                    if self._sdc_has_inv() else None),
        }

    def step_sdc(self):
        """One SDC-checked iteration: ``(info, check record)``. On
        single-program layouts the ledger core and the check tail run
        in ONE dispatch; on multi-dispatch layouts the pipelined
        ledger sequence is bracketed by two standalone state-check
        dispatches (input and output side) — still zero collectives
        beyond the form's own budget. Never called when SDC checking
        is off (the zero-computation contract, tests/test_sdc.py)."""
        sharded = bool(self.config.vertex_sharded)
        has_inv = self._sdc_has_inv()
        if self._ms_stripe is not None:
            w = self._sdc_w()
            inv = (self._inv_out,) if has_inv else ()
            state_fn = self._get_sdc_state_fn()
            fin, min_, sin = state_fn(w, self._r, *inv)
            delta, m, (lk, rt, pv) = self._device_step_ledger()
            fout, mout, _ = state_fn(w, self._r, *inv)
            host = jax.device_get(
                (delta, m, lk, rt, pv, fin, min_, sin, fout, mout))
        else:
            fn = self._get_sdc_step()
            (delta, m, lk, rt, pv, fin, min_, sin, fout,
             mout) = self._adopt_step_out(
                fn(self._sdc_w(), *self._device_args()))
            self._note_comms(1)
            host = jax.device_get(
                (delta, m, lk, rt, pv, fin, min_, sin, fout, mout))
        (d_h, m_h, lk_h, rt_h, pv_h, fin_h, min_h, sin_h, fout_h,
         mout_h) = host
        mout_np = np.asarray(mout_h)
        chk = {
            "sharded": sharded,
            "fp_in": np.asarray(fin_h),
            "mass_in": np.asarray(min_h),
            "src_in": np.asarray(sin_h) if has_inv else None,
            "fp_out": np.asarray(fout_h),
            "mass_out": mout_np,
            "contrib": np.asarray(lk_h),
            "retained": np.asarray(rt_h),
            "mass_prev": np.asarray(pv_h),
            "dangling_mass": float(m_h),
            # Stamped per attempt so the guard judges a redo by the
            # slack its OWN input state warrants (delta before this
            # step), not by whatever step ran since.
            "stale_slack": self._stale_slack(),
        }
        info = {
            "l1_delta": float(d_h),
            "dangling_mass": float(m_h),
            "rank_mass": float(mout_np.astype(float).sum() if sharded
                               else np.median(mout_np)),
        }
        self._last_step_delta = info["l1_delta"]
        return info, chk

    # -- cost accounting (obs/costs.py; ISSUE 5) ---------------------------

    def cost_reports(self, refresh: bool = False) -> Dict[str, dict]:
        """Harvest the step program(s)' XLA cost model — FLOPs, HBM
        bytes accessed, peak/argument/output/temp allocation — into
        the cost ledger and return its snapshot (the run report's
        ``costs`` section; bench.py embeds the same dict).

        The stepwise executable is dispatch-compiled (``jax.jit``), so
        this AOT-lowers ``step_core`` once more to get a harvestable
        Compiled handle — persistent-compile-cache-assisted on TPU,
        milliseconds on CPU, and cached here so repeat calls are free.
        Multi-dispatch layouts harvest prescale / per-stripe /
        finalize individually (stripe inputs come from
        ``jax.eval_shape``, so nothing executes). Fields are None on
        backends whose PJRT plugin doesn't report — never zero. Best
        effort by contract: accounting must not be able to fail a
        run.

        The repeat-call memo is the LEDGER itself (is this engine's
        whole-iteration form already filed?), not an engine flag: a
        per-leg ``costs.reset()`` (bench) must force a re-harvest, and
        a stale flag would return an empty block there."""
        whole_form = "step" if self._ms_stripe is None else "final"
        if not refresh and obs_costs.get_report(whole_form) is not None:
            return obs_costs.ledger_snapshot()
        try:
            for label, compiled, ne in self.iteration_programs():
                obs_costs.harvest(label, compiled, num_edges=ne)
                # Compiler plane (ISSUE 11): SAME compiled handle, so
                # arming the inspector costs zero extra compiles.
                obs_hlo.maybe_inspect(label, compiled, num_edges=ne)
        except Exception as e:  # accounting never fails a run
            obs_log.warn(
                f"cost harvest unavailable ({type(e).__name__}: "
                f"{str(e)[:120]})"
            )
        return obs_costs.ledger_snapshot()

    def iteration_programs(self, wrap_unjitted: bool = False):
        """``(label, Compiled, num_edges)`` for every program ONE
        iteration dispatches — the whole-iteration ``step`` on
        single-program layouts, ``prescale``/``stripe{i}``/``final``
        on multi-dispatch ones. AOT lowering only (nothing executes;
        stripe inputs come from ``jax.eval_shape``), and the handles
        are the ones :meth:`cost_reports` and the PTH lowering
        contracts (analysis/contracts.check_hlo_form) both inspect —
        the ONE place that knows the dispatch set and its argument
        threading. ``num_edges`` attaches only to the whole-iteration
        form (per-program models stay unmeasured on multi-dispatch —
        see cost_reports).

        ``wrap_unjitted`` additionally ``jax.jit``-wraps stage fns the
        engine doesn't keep jitted (the vs-bounded multi-dispatch
        stripes) so their programs can be inspected too; cost_reports
        keeps the default (skip them) so its ledger shape is
        unchanged."""
        ne = (int(self.graph.num_edges)
              if self.graph is not None and self.graph.num_edges else None)

        def lower(fn, args):
            if not hasattr(fn, "lower"):
                fn = jax.jit(fn)
            return fn.lower(*args).compile()

        if self._ms_stripe is None:
            with obs_trace.span("engine/compile", form="cost_step"):
                compiled = jax.jit(
                    self._step_core, donate_argnums=(0,)
                ).lower(*self._device_args()).compile()
            return [("step", compiled, ne)]
        out = []
        pres_args = (self._r, self._inv_out)
        with obs_trace.span("engine/compile", form="cost_ms"):
            if wrap_unjitted or hasattr(self._ms_prescale, "lower"):
                out.append(("prescale",
                            lower(self._ms_prescale, pres_args), None))
            zs = jax.eval_shape(self._ms_prescale, *pres_args)
            parts = []
            for s, fn in enumerate(self._ms_stripe_fns):
                stripe_args = (*zs, self._src[s], self._row_block[s])
                if wrap_unjitted or hasattr(fn, "lower"):
                    out.append((f"stripe{s}",
                                lower(fn, stripe_args), None))
                parts.append(jax.eval_shape(fn, *stripe_args))
            final_args = (self._r, *parts, *self._ms_ids,
                          self._dangling, self._zero_in, self._valid)
            out.append(("final", lower(self._ms_final, final_args), ne))
        return out

    def lowering_reports(self, refresh: bool = False) -> Dict[str, dict]:
        """Harvest the step program(s)' OPTIMIZED-HLO lowering reports
        (obs/hlo.py; ISSUE 11) — gather-strategy classification,
        fusion/collective structure, bf16-stream verification, the
        HLO-derived traffic estimate — and return the lowering-ledger
        snapshot (the per-leg ``lowering`` block of bench JSON and the
        run report's ``lowering`` section).

        Arms the inspector around ONE :meth:`cost_reports` pass, so
        the lowering harvest reuses the exact compiled handles the
        cost harvest holds: zero extra compiles. Out-of-band by
        contract — never called from the hot loop, and a disarmed run
        never reaches this method (the booby-trap discipline).

        Note the forced cost re-harvest refiles the cost ledger's
        reports WITHOUT any previously attached measurement — callers
        that attach a measured wall (bench) must harvest lowering
        FIRST (or simply arm the inspector before their own
        cost_reports call, which is what bench._leg_costs does).

        The repeat-call memo is PER-ENGINE (``_lowering_cache``,
        dropped by ``_begin_build`` on a rebuild): the process-global
        hlo ledger is shared across engines, so memoizing on it would
        hand a second engine (or an in-place rebuild on a new graph)
        the FIRST program's verdict — the staleness class the
        exchange-only jit already guards against."""
        cache = getattr(self, "_lowering_cache", None)
        if not refresh and cache is not None:
            return cache
        was_armed = obs_hlo.armed()
        obs_hlo.arm()
        try:
            self.cost_reports(refresh=True)
        finally:
            if not was_armed:
                obs_hlo.disarm()
        snap = obs_hlo.ledger_snapshot()
        self._lowering_cache = snap
        return snap

    def run_fast(self, num_iters: Optional[int] = None) -> np.ndarray:
        """Benchmark loop: no per-iteration host sync; one honest scalar
        fence at the end."""
        total = self.config.num_iters if num_iters is None else num_iters
        delta = None
        while self.iteration < total:
            delta, _ = self._device_step()
            self.iteration += 1
        if delta is not None:
            jax.device_get(delta)  # honest fence (see module docstring)
        return self.ranks()

    def run_fused(self, num_iters: Optional[int] = None) -> np.ndarray:
        """All remaining iterations in ONE device dispatch: a
        ``lax.scan`` over the step body with the rank buffer donated —
        the literal realization of SURVEY.md §3.2's mapping ("the entire
        loop body becomes one jitted function; zero host round-trips").

        Equivalent math to :meth:`run_fast` (the scan body IS
        ``step_core``); differs only in dispatch: one XLA invocation for
        the whole hot loop, so per-step dispatch/queueing overhead and
        remote-backend (tunnel) latency vanish from the run. Snapshots
        and per-iteration logging need host control between steps — use
        :meth:`PageRankEngine.run` for those; ``tol`` early-stopping has
        its own fused, on-device form (:meth:`run_fused_tol`).

        On very-many-stripe layouts (past ``SCAN_STRIPE_UNITS``) the
        single-program constraint would force a scan-over-stripes body
        that loses XLA's fast gather and whose uniform in-program
        restack exceeded single-chip HBM at scale-25 pair, so this
        DELEGATES to :meth:`run_fused_chunked` with one chunk: the fast
        multi-dispatch stripes, pipelined (per-dispatch cost hidden),
        identical math and identical ``last_run_metrics`` traces — the
        only difference from a literal single program is dispatch
        count, which is not a throughput lever on any measured backend
        (docs/PERF_NOTES.md "Measurement protocol").
        Per-iteration (l1_delta, dangling_mass) traces are kept as device
        arrays in :attr:`last_run_metrics`.
        """
        total = self.config.num_iters if num_iters is None else num_iters
        k = total - self.iteration
        if k <= 0:
            # No-op: a completed prior run's traces are kept.
            return self.ranks()
        if self._ms_stripe is not None:
            return self.run_fused_chunked(num_iters=total, every=0)
        fused = self._get_fused(k)
        out = fused(*self._device_args())
        deltas, masses = out[-1]
        self._adopt_step_out(out[:-1])
        self.iteration = total
        self._note_comms(k)
        self.last_run_metrics = {"l1_delta": deltas, "dangling_mass": masses}
        return self.ranks()

    def run_fused_tol(
        self, tol: Optional[float] = None, num_iters: Optional[int] = None
    ) -> np.ndarray:
        """Convergence-driven fused run: a jitted ``lax.while_loop``
        stepping until ``L1(r' - r) <= tol`` or the iteration budget is
        spent — early stopping entirely ON DEVICE, one dispatch, zero
        host round-trips (the reference has no convergence check at all,
        Sparky.java:187; the stepwise :meth:`PageRankEngine.run` checks
        tol on host every iteration instead).

        Unlike :meth:`run_fused`, per-iteration traces cannot be stacked
        (the trip count is dynamic); ``last_run_metrics`` carries the
        FINAL iteration's (l1_delta, dangling_mass) only.

        On very-many-stripe layouts (``_ms_stripe`` engaged) a
        single-program while_loop is not viable (the unrolled body
        exceeds remote-compile request limits; the removed
        scan-over-stripes fallback lost XLA's fast gather — PERF_NOTES
        "Scan bodies defeat the fast gather"), so this delegates to
        :meth:`run_fused_chunked` with a per-iteration tol check: same stopping iteration as the
        while_loop form (the delta is inspected after every iteration),
        fast multi-dispatch stripes, at the cost of one host scalar
        fetch per iteration — noise next to the multi-second iterations
        these layouts have. There ``last_run_metrics`` keeps FULL
        per-iteration traces (strictly more than this method's
        final-only contract).
        """
        tol = self.config.tol if tol is None else tol
        if tol is None:
            raise ValueError("run_fused_tol needs a tol (arg or config)")
        total = self.config.num_iters if num_iters is None else num_iters
        k = total - self.iteration
        if k <= 0:
            return self.ranks()
        if self._ms_stripe is not None:
            return self.run_fused_chunked(num_iters=total, every=1, tol=tol)
        fused = self._get_fused_tol(k, float(tol))
        i_done, delta, mass = self._adopt_step_out(
            fused(*self._device_args())
        )
        done = int(jax.device_get(i_done))
        self.iteration += done
        self._note_comms(done)
        self.last_run_metrics = {
            "l1_delta": jnp.reshape(delta, (1,)),
            "dangling_mass": jnp.reshape(mass, (1,)),
        }
        return self.ranks()

    def run_fused_chunked(
        self,
        num_iters: Optional[int] = None,
        every: int = 1,
        on_chunk=None,
        tol: Optional[float] = None,
    ) -> np.ndarray:
        """Fused dispatches BETWEEN snapshot points: each chunk of
        ``every`` iterations is one XLA invocation (the same cached scan
        executable every full chunk), and ``on_chunk(iterations_done,
        ranks_thunk, (deltas, masses))`` fires at each boundary;
        ``ranks_thunk()`` returns a device-side rank copy for the
        snapshot sinks to decode off-thread. The copy is made only when
        the callback calls the thunk, so a boundary the callback skips
        (the CLI skips off-cadence final-remainder boundaries) costs no
        device-side copy. This is the C17 persistence contract
        (every-iteration in the reference, Sparky.java:237; every-k
        here) without giving up fused dispatch between snapshot points —
        the fix for fused runs being uncheckpointable.

        With ``tol``, stops after the first chunk whose final L1 delta
        is <= tol — checked host-side at the boundary, which costs
        nothing extra since the boundary already materializes the chunk
        traces. ``on_chunk`` may also return a truthy value to stop
        after its boundary (the CLI's probe-point ``--stop-tol``, which
        must NOT fire at snapshot-only boundaries when both cadences
        are engaged). Unlike :meth:`run_fused_tol`, per-iteration
        traces for every executed iteration survive in
        ``last_run_metrics``.
        """
        total = self.config.num_iters if num_iters is None else num_iters
        if every is not None and every < 0:
            raise ValueError(f"every must be >= 0, got {every}")
        every = int(every) if every else max(1, total - self.iteration)
        # An armed stall watchdog is fed at chunk boundaries — the
        # finest host-visible progress granularity of a fused run
        # (size --stall-timeout above every * the expected iteration
        # wall there).
        watchdog = obs_live.get_watchdog()
        ds, ms = [], []
        while self.iteration < total:
            # Align boundaries to ABSOLUTE multiples of ``every`` so a
            # resumed run lands on the same snapshot cadence as the
            # stepwise loop ((i+1) % every == 0); the final chunk may be
            # a short remainder ending off-cadence at ``total``.
            k = min(every - self.iteration % every, total - self.iteration)
            if self._ms_stripe is not None:
                # Very-many-stripe layouts: pipelined multi-dispatch
                # steps (the ONLY fused-capable form there;
                # _setup_multi_dispatch docstring).
                dl, ml = [], []
                for _ in range(k):
                    d, m = self._device_step()
                    dl.append(d)
                    ml.append(m)
                deltas, masses = jnp.stack(dl), jnp.stack(ml)
                self.iteration += k  # _device_step does not count
            else:
                fused = self._get_fused(k)
                out = fused(*self._device_args())
                deltas, masses = out[-1]
                self._adopt_step_out(out[:-1])
                self.iteration += k
                self._note_comms(k)
            ds.append(deltas)
            ms.append(masses)
            if watchdog is not None:
                watchdog.heartbeat(self.iteration - 1)
            stop = None
            if on_chunk is not None:
                stop = on_chunk(self.iteration, self.device_ranks,
                                (deltas, masses))
            if stop:
                break
            if tol is not None and float(jax.device_get(deltas[-1])) <= tol:
                break
        if ds:
            self.last_run_metrics = {
                "l1_delta": jnp.concatenate(ds),
                "dangling_mass": jnp.concatenate(ms),
            }
        return self.ranks()

    def prepare_fused(
        self,
        num_iters: Optional[int] = None,
        tol: Optional[float] = None,
        every: Optional[int] = None,
    ) -> int:
        """Compile the fused executable for the remaining iteration count
        without running it; returns that count. Lets callers keep the
        one-time XLA compile out of timed regions (the stepwise path
        isolates compile in iteration 0; the fused dispatch would
        otherwise smear it across every iteration's average). With a
        ``tol`` it prepares the while_loop form run_fused_tol uses; with
        ``every`` the chunk executable run_fused_chunked reuses (a short
        final remainder chunk, if any, still compiles lazily)."""
        total = self.config.num_iters if num_iters is None else num_iters
        k = total - self.iteration
        if k > 0:
            if self._ms_stripe is not None:
                # EVERY fused form steps the multi-dispatch path on
                # these layouts (run_fused and run_fused_tol delegate
                # to run_fused_chunked): warm ALL its executables with
                # one throwaway step on a copy of the state, so the
                # caller's timed region pays no per-stripe remote
                # compiles. Compiling a single-program executable here
                # would pay for a program the delegations never run.
                keep = jnp.copy(self._r)
                self._device_step()
                self.fence()
                self._r = keep
                return k
            if every and every > 0:
                e = int(every)
                # Chunks align to absolute multiples of ``e`` (see
                # run_fused_chunked): compile the possibly-short first
                # chunk and the steady-state full chunk.
                first = min(e - self.iteration % e, k)
                self._get_fused(first)
                if k - first >= e:
                    self._get_fused(e)
            elif tol is not None:
                self._get_fused_tol(k, float(tol))
            else:
                self._get_fused(k)
        return max(0, k)

    def _get_fused_tol(self, k, tol):
        """AOT-compiled early-stopping while_loop executable (cached per
        (k, tol))."""
        key = ("tol", k, tol)
        fused = self._fused_cache.get(key)
        if fused is None:
            core = self._step_core
            acc = self._accum_dtype
            nc = len(self._carry_args)

            def fused_fn(r, *rest):
                cs, tail = rest[:nc], rest[nc:]

                def cond(carry):
                    i, delta = carry[1 + nc], carry[2 + nc]
                    return jnp.logical_and(i < k, delta > tol)

                def body(carry):
                    r2, *out = core(carry[0], *carry[1:1 + nc], *tail)
                    return (r2, *out[:nc], carry[1 + nc] + 1,
                            out[nc], out[nc + 1])

                init = (r, *cs, jnp.int32(0), jnp.array(jnp.inf, acc),
                        jnp.zeros((), acc))
                return jax.lax.while_loop(cond, body, init)

            with obs_trace.span("engine/compile", form="fused_tol",
                                iters=k):
                fused = jax.jit(
                    fused_fn, donate_argnums=tuple(range(1 + nc))
                ).lower(
                    *self._device_args()
                ).compile()
            # iters=k is the BUDGET (the while_loop may stop early):
            # per-iteration fields are a floor, not a measurement.
            obs_costs.harvest(
                "fused_tol", fused, iters=k,
                num_edges=int(self.graph.num_edges) if self.graph else None,
            )
            obs_hlo.maybe_inspect(
                "fused_tol", fused,
                num_edges=int(self.graph.num_edges) if self.graph else None,
            )
            self._fused_cache[key] = fused
        return fused

    def _get_fused(self, k):
        """AOT-compiled k-iteration scan executable (cached per k)."""
        fused = self._fused_cache.get(k)
        if fused is None:
            core = self._step_core
            nc = len(self._carry_args)

            def fused_fn(r, *rest):
                cs, tail = rest[:nc], rest[nc:]

                def body(carry, _):
                    r2, *out = core(carry[0], *carry[1:], *tail)
                    return (r2, *out[:nc]), (out[nc], out[nc + 1])

                fin, ys = jax.lax.scan(body, (r, *cs), None, length=k)
                return (*fin, ys)

            with obs_trace.span("engine/compile", form="fused_scan",
                                iters=k):
                fused = jax.jit(
                    fused_fn, donate_argnums=tuple(range(1 + nc))
                ).lower(
                    *self._device_args()
                ).compile()
            # Cost ledger entry per compile; per-iteration fields
            # divide by k, so chunked runs (several k's) agree.
            obs_costs.harvest(
                "fused_scan", fused, iters=k,
                num_edges=int(self.graph.num_edges) if self.graph else None,
            )
            obs_hlo.maybe_inspect(
                "fused_scan", fused,
                num_edges=int(self.graph.num_edges) if self.graph else None,
            )
            self._fused_cache[k] = fused
        return fused

    def _device_args(self):
        """The step/fused argument tuple — ONE spelling so the
        AOT-lowered signature and the dispatch call cannot drift. The
        prescaled (ell/pallas) paths carry the 1/out-degree vector as a
        runtime argument (never an embedded constant); step-carried
        state (the async boundary buffer, ISSUE 17) rides at index 1,
        right behind the rank vector it is donated with."""
        if self._inv_in_args:
            return (self._r, *self._carry_args, self._inv_out,
                    self._dangling, self._zero_in, self._valid,
                    *self._contrib_args)
        return (self._r, *self._carry_args, self._dangling,
                self._zero_in, self._valid, *self._contrib_args)

    def _adopt_step_out(self, out):
        """Split one step program's output tuple: the rank vector and
        the carried state are adopted in place, the rest (delta, mass,
        probe/ledger/check tails) returns to the caller. Every step
        form returns ``(r2, *carry, ...)`` — ONE adoption spelling so
        a form that forgets to thread the carry fails loudly in the
        tests rather than silently running ever-staler boundaries."""
        nc = len(self._carry_args)
        self._r = out[0]
        if nc:
            self._carry_args = tuple(out[1:1 + nc])
        return out[1 + nc:]

    def _prime_carry(self) -> None:
        """(Re)compute the carried state from the CURRENT rank vector
        — a no-op on synchronous forms. Called at build end and after
        every state replacement (set_ranks: snapshot resume, elastic
        rescue, SDC redo via restore_state), so the first step after
        any (re)start reads a lag-0 boundary and staleness never
        exceeds one iteration."""
        if self._carry_prime is not None:
            self._carry_args = self._carry_prime()

    def fence(self) -> None:
        """Block until all queued steps actually finished on device."""
        jax.device_get(jnp.sum(self._r))

    def rank_mass(self) -> float:
        """sum(ranks) via one device-side scalar reduction + fetch (the
        mass-drift health probe, engine.run) — never a full-vector
        device->host transfer. Padding slots are zero, so the padded
        sum IS the rank mass."""
        return float(jax.device_get(jnp.sum(self._r)))

    def ranks(self) -> np.ndarray:
        return self.decode_ranks(self._r)

    def device_ranks(self):
        """Device-side copy of the current (padded, relabeled) rank
        vector. The live buffer is donated to the next step, so callers
        that overlap offload with compute (utils/snapshot.py:
        AsyncRankWriter) must hold a copy; pass it to
        :meth:`decode_ranks` off-thread."""
        return jnp.copy(self._r)

    def decode_ranks(self, padded) -> np.ndarray:
        """Fetch a padded relabeled rank vector to host and undo the
        in-degree relabel. Blocking; safe to call from a worker thread
        (the transfer releases the GIL)."""
        r = np.asarray(jax.device_get(padded))[: self.graph.n]
        if self._perm is not None:
            out = np.empty(self.graph.n, dtype=r.dtype)
            out[self._perm] = r
            return out
        return r

    def set_ranks(self, r: np.ndarray, iteration: int = 0) -> None:
        if r.shape != (self.graph.n,):
            raise ValueError(f"rank shape {r.shape} != ({self.graph.n},)")
        r = np.asarray(r, dtype=self._dtype)
        if self._perm is not None:
            rr = np.zeros(self._n_state, dtype=self._dtype)
            rr[: self.graph.n] = r[self._perm]
            r = rr
        self._r = jax.device_put(r, self._state_sharding)
        # Replaced state invalidates any carried boundary: re-prime so
        # the next step reads a lag-0 boundary of the NEW ranks
        # (snapshot resume, elastic rescue and warm starts all land
        # here — ROBUSTNESS.md "Rescue x double buffer").
        self._prime_carry()
        self._last_step_delta = 0.0  # primed -> next step is lag-0
        self.iteration = iteration

    def layout_info(self) -> Dict[str, object]:
        """The RESOLVED kernel/layout/autotune decisions of this build —
        what ACTUALLY ran (ISSUE 6): the kernel (plus the requested one
        when a pallas probe fell back to the native ell layout), lane
        group, stripe/partition geometry, gather width, the autotuned
        chunk, and the accumulation mode. bench.py embeds this per leg
        so BENCH_r*.json cells are attributable to a concrete layout."""
        info: Dict[str, object] = {
            "kernel": getattr(self, "_kernel", None),
            "pair": bool(getattr(self, "_pair", False)),
            "accum_dtype": str(self._accum_dtype)
            if getattr(self, "_accum_dtype", None) is not None else None,
            "vertex_sharded": bool(self.config.vertex_sharded),
        }
        info.update(self._layout)
        if self._kernel_requested:
            info["kernel_requested"] = self._kernel_requested
        return info

    def snapshot_meta(self) -> Dict[str, object]:
        """Mesh topology + partition geometry recorded alongside every
        snapshot (Snapshotter.mesh_meta; ISSUE 7): which mesh shape and
        layout produced the checkpoint. Purely provenance — snapshots
        hold the canonical host-order vector, so resume works on ANY
        mesh shape; this is what the run report / a postmortem reads
        to see that a rescue actually changed the mesh."""
        mesh = self._mesh
        devs = (
            [d for d in mesh.devices.reshape(-1)]
            if mesh is not None else []
        )
        return {
            "engine": self.name,
            "num_devices": len(devs) if devs else 1,
            "axis": self.config.mesh_axis,
            "device_ids": [int(d.id) for d in devs],
            "device_kinds": sorted({str(d.device_kind) for d in devs}),
            "vertex_sharded": bool(self.config.vertex_sharded),
            "n_state": int(getattr(self, "_n_state", 0) or 0),
            "layout": {
                k: self._layout.get(k)
                for k in ("form", "partition_span", "n_stripes",
                          "stripe_span", "group")
            },
        }

    @property
    def mesh(self):
        return self._mesh
