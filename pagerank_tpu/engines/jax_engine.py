"""JaxTpuEngine — the TPU-native solver (L3 over L0).

The reference's per-iteration dataflow (Sparky.java:187-238) — 3 shuffles,
|dangUrls|+1 driver round-trips, one S3 write — collapses into ONE jitted
step per iteration:

  - edge shards (dst-sorted COO) live sharded across a 1-D device mesh;
  - the rank vector is replicated (a Spark "broadcast" that never leaves
    device, Sparky.java:135);
  - each device computes a dense contribution partial with a sorted
    segment-sum, then one `jax.lax.psum` over ICI merges partials —
    the only cross-device communication per iteration;
  - dangling mass, zero-in-degree retention, and the teleport term are
    fused elementwise arithmetic (XLA fuses them into the epilogue);
  - the rank buffer is donated, so device memory is O(1) in iterations
    (the reference instead re-caches every iteration with no unpersist,
    Sparky.java:216,235 — SURVEY.md §3.3).

Zero host round-trips per iteration unless the caller asks for per-iter
logging/snapshots; the L1 delta and dangling mass come back as device
scalars fetched lazily.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax import shard_map

from pagerank_tpu.engine import PageRankEngine, register_engine
from pagerank_tpu.graph import Graph
from pagerank_tpu.models import pagerank as pr_model
from pagerank_tpu.ops import spmv
from pagerank_tpu.parallel import mesh as mesh_lib
from pagerank_tpu.parallel import partition


@register_engine("jax")
class JaxTpuEngine(PageRankEngine):
    """Sharded power iteration over a 1-D device mesh."""

    def __init__(self, config=None, devices=None):
        super().__init__(config)
        self._devices = devices
        self._mesh = None

    # -- build ------------------------------------------------------------

    def build(self, graph: Graph) -> "JaxTpuEngine":
        cfg = self.config
        self.graph = graph
        self._mesh = mesh_lib.make_mesh(
            cfg.num_devices, cfg.mesh_axis, devices=self._devices
        )
        axis = cfg.mesh_axis
        ndev = self._mesh.devices.size

        dtype = jnp.dtype(cfg.dtype)
        self._dtype = dtype
        self._accum_dtype = jnp.dtype(cfg.accum_dtype)

        shards = partition.partition_edges(graph, ndev, weight_dtype=dtype)
        e_shard = mesh_lib.edge_sharding(self._mesh)
        rep = mesh_lib.replicated(self._mesh)

        self._src = jax.device_put(shards.src, e_shard)
        self._dst = jax.device_put(shards.dst, e_shard)
        self._w = jax.device_put(shards.weight, e_shard)
        # Reference mode: post-repair dangUrls (uncrawled targets).
        # Textbook mode: standard dangling definition (out_degree == 0).
        mass_mask = (
            graph.dangling_mask
            if cfg.semantics == "reference"
            else graph.out_degree == 0
        )
        self._dangling = jax.device_put(mass_mask.astype(dtype), rep)
        self._zero_in = jax.device_put(graph.zero_in_mask.astype(dtype), rep)
        self._r = jax.device_put(
            pr_model.initial_rank(graph.n, cfg.semantics, dtype, jnp), rep
        )
        self.iteration = 0

        n = graph.n
        damping = cfg.damping
        semantics = cfg.semantics
        accum = self._accum_dtype
        mesh = self._mesh

        def sharded_contrib(r, src, dst, w):
            part = spmv.edge_contrib_segment_sum(r, src, dst, w, n, accum)
            return jax.lax.psum(part, axis)

        contrib_fn = shard_map(
            sharded_contrib,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(axis)),
            out_specs=P(),
        )

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step_fn(r, src, dst, w, dangling, zero_in):
            contrib = contrib_fn(r, src, dst, w)
            m = spmv.dangling_mass(r, dangling, accum)
            r_new = pr_model.apply_update(
                contrib, r.astype(accum), zero_in.astype(accum), m, n,
                damping, semantics, jnp,
            ).astype(r.dtype)
            delta = jnp.sum(jnp.abs(r_new.astype(accum) - r.astype(accum)))
            return r_new, delta, m

        self._step_fn = step_fn
        return self

    # -- iteration --------------------------------------------------------

    def _device_step(self):
        """One iteration; returns (delta, mass) as device scalars."""
        self._r, delta, m = self._step_fn(
            self._r, self._src, self._dst, self._w, self._dangling, self._zero_in
        )
        return delta, m

    def step(self) -> Dict[str, float]:
        delta, m = self._device_step()
        return {"l1_delta": float(delta), "dangling_mass": float(m)}

    def run_fast(self, num_iters: Optional[int] = None) -> np.ndarray:
        """Benchmark loop: no per-iteration host sync at all. Device
        scalars are discarded; one block_until_ready at the end."""
        total = self.config.num_iters if num_iters is None else num_iters
        while self.iteration < total:
            self._device_step()
            self.iteration += 1
        jax.block_until_ready(self._r)
        return self.ranks()

    def ranks(self) -> np.ndarray:
        return np.asarray(jax.device_get(self._r))

    def set_ranks(self, r: np.ndarray, iteration: int = 0) -> None:
        if r.shape != (self.graph.n,):
            raise ValueError(f"rank shape {r.shape} != ({self.graph.n},)")
        self._r = jax.device_put(
            np.asarray(r, dtype=self._dtype), mesh_lib.replicated(self._mesh)
        )
        self.iteration = iteration

    @property
    def mesh(self):
        return self._mesh
