"""ReferenceCpuEngine — float64 numpy/scipy oracle with exact reference
semantics.

This engine stands in for the Spark-RDD engine (no JVM/Spark in this
environment): it computes, in exact vectorized form, what
`Sparky.java:187-238` computes in local[*] mode:

  contribs  = Aᵀ_norm r          # join+flatMap+reduceByKey, Sparky.java:192-229
  m         = Σ_{dangling} r     # danglingContrib loop,      Sparky.java:219-222
  sum       = contribs + z ⊙ r   # subtractByKey retention,   Sparky.java:224-225
  r'        = 0.15 + 0.85 (sum + m/N)                       # Sparky.java:233

It is the acceptance oracle for every other engine (L1 ≤ 1e-6 gate,
BASELINE.md).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from pagerank_tpu import graph as graph_lib
from pagerank_tpu.engine import PageRankEngine, register_engine
from pagerank_tpu.graph import Graph
from pagerank_tpu.models import pagerank as pr_model


@register_engine("cpu")
class ReferenceCpuEngine(PageRankEngine):
    """Single-host float64 oracle (scipy.sparse SpMV)."""

    def build(self, graph: Graph) -> "ReferenceCpuEngine":
        self.graph = graph
        self._at = graph_lib.to_csr_transpose(graph)  # Aᵀ_norm, CSR
        # Reference mode uses the post-repair dangUrls (uncrawled targets);
        # textbook mode uses the standard definition (out_degree == 0).
        mass_mask = (
            graph.dangling_mask
            if self.config.semantics == "reference"
            else graph.out_degree == 0
        )
        self._dangling = mass_mask.astype(np.float64)
        self._zero_in = graph.zero_in_mask.astype(np.float64)
        self._r = pr_model.initial_rank(
            graph.n, self.config.semantics, np.float64, np
        )
        self.iteration = 0
        return self

    def step(self) -> Dict[str, float]:
        cfg = self.config
        r = self._r
        contrib = self._at @ r
        m = float(self._dangling @ r)
        # Rank-mass-ledger sums (ISSUE 13; obs/graph_profile.py),
        # MEASURED off the step's own intermediates — three O(n)
        # reductions the oracle can afford unconditionally; the probed
        # step reads them via ledger_values().
        self._last_ledger = (
            float(r.sum()),
            float(contrib.sum()),
            float((self._zero_in * r).sum()),
        )
        r_new = pr_model.apply_update(
            contrib, r, self._zero_in, m, self.graph.n, cfg.damping, cfg.semantics, np
        )
        delta = float(np.abs(r_new - r).sum())
        self._r = r_new
        return {"dangling_mass": m, "l1_delta": delta}

    def ledger_values(self):
        return getattr(self, "_last_ledger", None)

    def ranks(self) -> np.ndarray:
        return np.asarray(self._r)

    def set_ranks(self, r: np.ndarray, iteration: int = 0) -> None:
        if r.shape != (self.graph.n,):
            raise ValueError(f"rank shape {r.shape} != ({self.graph.n},)")
        self._r = np.asarray(r, dtype=np.float64)
        self.iteration = iteration
