"""Command-line entry point — the config/flag system the reference lacks
(args are ignored at Sparky.java:39; inputs `:44-58`, iterations `:187`,
damping `:233`, and the output bucket `:237` are all hardcoded).

Examples:
  python -m pagerank_tpu.cli --input edges.txt --iters 10
  python -m pagerank_tpu.cli --input crawl.tsv --format crawl --out ranks.tsv
  python -m pagerank_tpu.cli --synthetic rmat:20 --iters 50 --engine jax
  python -m pagerank_tpu.cli --input edges.npz --snapshot-dir ckpt/ --resume
  python -m pagerank_tpu.cli --input edges.txt --ppr-sources random:256 \
      --ppr-topk 50 --out ppr.tsv
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time
import warnings

import numpy as np

from pagerank_tpu import PageRankConfig, build_graph, jobs, make_engine, obs
from pagerank_tpu import sdc as sdc_mod
from pagerank_tpu.exitcodes import ExitCode
from pagerank_tpu.utils import fsio
from pagerank_tpu.utils.metrics import MetricsLogger
from pagerank_tpu.utils.snapshot import Snapshotter, TextDumper, resume_engine


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pagerank_tpu",
        description="TPU-native PageRank (reference or textbook semantics).",
        epilog="Developer tooling: `python -m pagerank_tpu.analysis` "
        "runs the repo's AST lint + jaxpr contract checker "
        "(docs/ANALYSIS.md); `python -m pagerank_tpu.obs campaign "
        "run` executes the full measurement campaign with resumable "
        "legs and a typed decision ledger (docs/OBSERVABILITY.md).",
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--input",
        help="edge list (.txt/.tsv), binary .npz, crawl TSV, or Hadoop "
        "SequenceFile(s) of (Text url, Text json) — a file, a segment "
        "directory, or a comma-joined list (the reference's input form, "
        "Sparky.java:42-61). Paths may use any URI scheme registered "
        "with pagerank_tpu.utils.fsio (the reference reads s3n:// URIs)",
    )
    src.add_argument(
        "--synthetic",
        help="synthetic graph, e.g. rmat:20 (scale) or uniform:1000000:16000000 (n:e)",
    )
    p.add_argument(
        "--format",
        choices=["auto", "edgelist", "npz", "crawl", "seqfile"],
        default="auto",
        help="input format (auto: by extension/magic — 'SEQ' magic => "
        "seqfile, .tsv with non-integer columns => crawl)",
    )
    p.add_argument(
        "--device-build", action="store_true",
        help="build + pack the graph ON DEVICE (ops/device_build) — the "
        "bench's fast path: over a tunneled TPU the host->device "
        "transfer of packed arrays dominates wall-clock, so --synthetic "
        "ships only a PRNG seed and integer edge inputs (npz/edgelist) "
        "ship 8 bytes/edge instead of the packed layout. Crawl/seqfile "
        "inputs work too: ids are assigned host-side (the url->int map "
        "is inherently host work), then the dedup/sort/pack runs on "
        "device with the reference's uncrawled-targets dangling mask. "
        "Requires --engine jax. Snapshots taken with --device-build "
        "resume only with --device-build (different fingerprint "
        "derivation)",
    )
    p.add_argument("--iters", type=int, default=10, help="iterations (reference: 10)")
    p.add_argument("--damping", type=float, default=0.85)
    p.add_argument("--semantics", choices=["reference", "textbook"], default="reference")
    p.add_argument("--engine", choices=["jax", "cpu"], default="jax")
    p.add_argument("--num-devices", type=int, default=None)
    p.add_argument(
        "--vertex-sharded", action="store_true",
        help="partition the per-vertex state (rank vector, masks, "
             "1/out-degree) over the mesh instead of replicating it — "
             "the reference's hash-partitioned ranks RDD "
             "(Sparky.java:165-170); per-chip state memory scales as "
             "1/num_devices (jax engine, ell kernel)",
    )
    p.add_argument(
        "--halo-exchange", action="store_true",
        help="with --vertex-sharded: sparse boundary exchange "
             "(ISSUE 8) — replace the dense all_gather + reduce-"
             "scatter with build-time halo tables (head-replication "
             "psum + static ppermute rounds), so per-iteration "
             "exchanged bytes scale with the boundary instead of n; "
             "comms.* counters report the model (downgrades to the "
             "dense exchange on multi-dispatch layouts)",
    )
    p.add_argument(
        "--halo-head", type=int, default=-1,
        help="head-replication K for --halo-exchange: -1 auto (the "
             "in-degree prefix whose replication MINIMIZES the "
             "modeled exchange bytes over the build-time read sets — "
             "may resolve to 0 on mild graphs), 0 off, >0 explicit "
             "(rounded up to a multiple of 128)",
    )
    p.add_argument(
        "--halo-async", action="store_true",
        help="with --halo-exchange: asynchronous stale-boundary "
             "iteration (ISSUE 17) — double-buffer the boundary so "
             "iteration k's segment-sum overlaps the exchange of "
             "iteration k's boundary outputs (remote reads lag one "
             "iteration; per-step cost drops from compute + comms "
             "toward max(compute, comms)); auto-downgrades to the "
             "synchronous exchange when the predicted overlap gain "
             "(comms.predicted_overlap_gain) is below "
             "--halo-async-min-gain or the mesh is single-device "
             "(layout_info records the downgrade)",
    )
    p.add_argument(
        "--stale-max-lag", type=int, default=1, choices=(0, 1),
        help="staleness bound for --halo-async: 1 (default) runs the "
             "double-buffered overlap with boundary reads one "
             "iteration stale; 0 is the exact synchronous path (bit-"
             "identical to the plain sparse exchange, zero extra "
             "buffers) — the A/B lever the convergence-vs-staleness "
             "bench sweep and the correctness tests pivot on",
    )
    p.add_argument(
        "--halo-async-min-gain", type=float, default=0.02,
        help="auto-gate threshold for --halo-async: the predicted "
             "overlap gain (exchange fraction x overlappable byte "
             "share) below which the build downgrades to the "
             "synchronous exchange; 0 pins the gate open (useful on "
             "toy graphs where the modeled exchange fraction is "
             "negligible)",
    )
    p.add_argument(
        "--vs-bounded", action="store_true",
        help="with --vertex-sharded: bound per-chip STEP transients too "
             "(destination-partitioned slot rows + per-stripe z "
             "broadcast) — per-chip step memory is O(stripe_span + "
             "N/num_devices), never O(N); results agree with the other "
             "modes to accumulation-dtype rounding (host-built graphs "
             "only)",
    )
    p.add_argument("--dtype", default="float32")
    p.add_argument("--accum-dtype", default=None, help="defaults to --dtype")
    p.add_argument(
        "--lane-group", type=int, default=None,
        help="grouped-lane ELL group size (power of two, 1..128; "
        "default: config default; 64 is fastest on v5e for large "
        "power-law graphs)",
    )
    p.add_argument(
        "--partition-span", type=int, default=0,
        help="partition-centric SpMV layout (ISSUE 6): sub-bin slots "
        "by source partition of this many vertices so each chunk's "
        "gather window is VMEM/cache-resident. 0 = off (default "
        "layout), -1 = auto (engine rule: dense cells + resident "
        "window, off when not worth it), >0 = explicit span "
        "(multiple of 128). jax ell kernel, 32-bit accumulation only",
    )
    p.add_argument(
        "--stream-dtype", default="", choices=["", "bfloat16"],
        help="stream the gather table in this dtype with f32 "
        "accumulation (the fast_bf16 leg: ~half the table-side HBM "
        "traffic for ~2^-9 relative z quantization). Requires "
        "--partition-span (only the partitioned layout consumes the "
        "narrowed stream)",
    )
    p.add_argument("--tol", type=float, default=None, help="L1 early-stop (default: none)")
    p.add_argument(
        "--fused", action="store_true",
        help="run the iteration loop as fused device dispatches "
        "(JaxTpuEngine.run_fused: a jitted lax.scan over the step; "
        "per-iteration metrics come from on-device traces and wall-clock "
        "is averaged). With --snapshot-dir, one fused dispatch per "
        "--snapshot-every iterations with snapshots at the boundaries "
        "(run_fused_chunked). With --tol the early stop runs on device "
        "(run_fused_tol: lax.while_loop; only the final delta/mass "
        "exist) or at chunk boundaries when snapshotting. jax engine "
        "only; incompatible with --dump-text-dir, which needs host "
        "control every iteration",
    )
    p.add_argument("--snapshot-dir", default=None)
    p.add_argument(
        "--snapshot-every",
        type=int,
        default=1,
        help="snapshot cadence in iterations; 0 disables (reference: every iter)",
    )
    p.add_argument("--resume", action="store_true", help="resume from latest snapshot")
    p.add_argument(
        "--sync-io", action="store_true",
        help="write snapshots/text dumps synchronously in the iteration "
        "loop instead of overlapping the device->host offload with "
        "compute (AsyncRankWriter)",
    )
    p.add_argument(
        "--dump-text-dir",
        default=None,
        help="also write plain-text rank dumps per iteration "
        "(PageRank{i}/part-00000 tuple lines, mirroring the reference's "
        "per-iteration saveAsTextFile)",
    )
    p.add_argument("--out", default=None, help="write final ranks (TSV: id/url, rank)")
    p.add_argument(
        "--top", type=int, default=0,
        help="write only the N highest-ranked vertices to --out, sorted "
        "by rank descending (ties by id ascending); 0 = the full vector "
        "in id order (the reference's dump shape, Sparky.java:237)",
    )
    ft = p.add_argument_group("fault tolerance (docs/ROBUSTNESS.md)")
    ft.add_argument(
        "--write-retries", type=int, default=3,
        help="total attempts per snapshot/text-dump write before the "
        "--on-write-failure policy applies (1 disables retries)",
    )
    ft.add_argument(
        "--on-write-failure", choices=["fail", "warn_and_drop"],
        default="fail",
        help="when a snapshot/dump write exhausts its retries: 'fail' "
        "aborts the run (default); 'warn_and_drop' records the dropped "
        "iteration in a dead_letter.json manifest next to the snapshots "
        "and keeps solving",
    )
    ft.add_argument(
        "--max-rollbacks", type=int, default=3,
        help="snapshot rollbacks the self-healing solve loop may "
        "perform on an unhealthy step (NaN/Inf, mass drift) before "
        "raising; needs --snapshot-dir to have anything to roll back to",
    )
    ft.add_argument(
        "--max-rescues", type=int, default=None,
        help="elastic-rescue budget under --stall-action rescue: mesh "
        "teardown + re-shard + warm-start recoveries allowed after "
        "device losses (default: the --max-rollbacks budget)",
    )
    ft.add_argument(
        "--job-dir", default=None, metavar="PATH",
        help="run as a RESUMABLE job (docs/ROBUSTNESS.md 'Preemption & "
        "resumable jobs'): each pipeline stage (ingest -> build -> "
        "solve -> output) persists a checksummed durable artifact "
        "into PATH, snapshots default into PATH/snapshots, and a "
        "restarted job with the same command validates the artifacts "
        "(graph fingerprint + layout geometry + config hash) and "
        "SKIPS completed stages — a preempted VM resumes instead of "
        "recomputing. SIGTERM/SIGINT trigger a graceful drain (exit "
        f"{int(ExitCode.INTERRUPTED)}); corrupt or mismatched "
        "artifacts are recomputed, never trusted",
    )
    ft.add_argument(
        "--drain-deadline", type=float,
        default=jobs.DEFAULT_DRAIN_DEADLINE_S, metavar="SECONDS",
        help="budget for the graceful SIGTERM/SIGINT drain: finish "
        "the in-flight step, flush the async writer (a failing sink "
        "still honors the SinkGuard dead-letter policy), write a "
        "final snapshot + interrupted-marked run report. A flush "
        "still hanging at the deadline is abandoned with a warning; "
        "a SECOND signal hard-exits 128+signum immediately",
    )
    ft.add_argument(
        "--mass-tol", type=float, default=None,
        help="opt-in per-step relative rank-mass drift tolerance for "
        "the health check (default: NaN/Inf checks only)",
    )
    ft.add_argument(
        "--no-health-checks", action="store_true",
        help="disable the per-step solver health check entirely",
    )
    ft.add_argument(
        "--sdc-check-every", type=int, default=0, metavar="K",
        help="silent-data-corruption defense (docs/ROBUSTNESS.md "
        "'Silent data corruption'; pagerank_tpu/sdc.py): every K-th "
        "step runs the ABFT-checked variant — per-device "
        "random-projection fingerprints, dual w.r computation, "
        "link-mass conservation, and the mass-ledger identity, all "
        "inside the step's own dispatch (contract PTC008: the exact "
        "collective multiset of the plain step). A breach triggers a "
        "deadline-bounded re-execution from the retained state: a "
        "clean redo is TRANSIENT (counted, continue); a repeat breach "
        "on the same device is STICKY and quarantines that chip "
        "through the elastic rescue path (--stall-action rescue), "
        "persisting the id in job.json so a resumed job never "
        "re-adopts it. 0 (default) disables: the solve is "
        "bit-identical with ZERO check computations; jax engine, "
        "stepwise loop only",
    )
    ft.add_argument(
        "--sdc-seed", type=int, default=0,
        help="seed of the SDC random-projection fingerprint vector "
        "(reproducible per (seed, state length))",
    )
    ft.add_argument(
        "--sdc-redo-deadline", type=float, default=30.0,
        metavar="SECONDS",
        help="wall-clock budget for one SDC breach's bounded "
        "re-execution window before the episode escalates "
        "(quarantine when attributed, a diagnostic error otherwise)",
    )
    p.add_argument("--log-every", type=int, default=1, help="0 silences per-iter logs")
    p.add_argument("--jsonl", default=None, help="append per-iter metrics to this JSONL file")
    ob = p.add_argument_group("observability (docs/OBSERVABILITY.md)")
    ob.add_argument(
        "--trace", default=None, metavar="PATH",
        help="record a span trace of the whole run and export it here: "
        "Chrome trace-event JSON (open in Perfetto / chrome://tracing), "
        "or span-per-line JSONL when PATH ends in .jsonl. Enabling "
        "tracing also engages the device build's per-stage fences "
        "(stages serialize — the same observer effect as bench.py "
        "--build-only)",
    )
    ob.add_argument(
        "--run-report", default=None, metavar="PATH",
        help="write the run flight-recorder JSON here: environment "
        "fingerprint (jax/backend/device/x64/git), resolved config, "
        "span summary, metrics-registry snapshot, per-iteration "
        "history, robustness summary. Implies tracing. Inspect/diff "
        "with `python -m pagerank_tpu.obs report A.json [B.json]`",
    )
    ob.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler trace of the solve here "
                    "(obs.profiler_session: stopped on every exit path, "
                    "recorded as a 'profile' span when tracing)")
    ob.add_argument(
        "--history", default=None, metavar="LEDGER",
        help="perf-history ledger (obs/history.py; docs/OBSERVABILITY"
        ".md 'Perf history & gating'): arm the history.* baseline-"
        "delta gauges from the ledger's median for this run's leg — a "
        "RUNNING solve shows %% vs baseline through the live exporter "
        "— and append this run's normalized RunRecord after the "
        "solve. A missing ledger just means no baseline yet; the "
        "append creates it",
    )
    ob.add_argument(
        "--probe-every", type=int, default=0, metavar="K",
        help="compute convergence probes every K iterations — L1 "
        "residual, rank mass, top-k churn — on device inside the "
        "step's own dispatch (contract PTC007: zero extra host syncs, "
        "no collectives beyond the form's budget). Records land in "
        "the per-iteration history, probe.* gauges, and the trace. "
        "0 (default) disables: the solve takes the exact unprobed "
        "code path, the reference's check-free loop",
    )
    ob.add_argument(
        "--probe-topk", type=int, default=64, metavar="N",
        help="top-k set size the probe's churn telemetry tracks "
        "(rank-movement stability — how many of the top N changed "
        "since the previous probe)",
    )
    ob.add_argument(
        "--stop-tol", type=float, default=None,
        help="early-exit when the PROBED L1 residual reaches this "
        "(checked at probe points only — needs --probe-every; --tol "
        "checks every iteration instead). Unset keeps exact "
        "reference semantics: no convergence check at all",
    )
    ob.add_argument(
        "--metrics-textfile", default=None, metavar="PATH",
        help="live Prometheus text-format export of the metrics "
        "registry, atomically rewritten every iteration "
        "(fsio.atomic_write — a node-exporter textfile collector "
        "never reads a torn file)",
    )
    ob.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="serve the same registry snapshot over HTTP GET "
        "/metrics on 127.0.0.1:PORT (0 = ephemeral); zero-dependency "
        "daemon thread",
    )
    ob.add_argument(
        "--device-sample-every", type=int, default=0, metavar="K",
        help="sample per-device HBM stats every K completed solve "
        "steps (obs/devices.DeviceSampler; ISSUE 10): device.<id>.* "
        "gauges through the live exporter, per-device counter tracks "
        "in the --trace Chrome export, and the HBM high-water mark in "
        "the run report (failure-marked reports included — the OOM "
        "post-mortem evidence). 0 (default) disarms: the solve loop "
        "makes zero sampler calls, and reports still carry a one-shot "
        "boundary sample",
    )
    ob.add_argument(
        "--preflight", action="store_true",
        help="OOM-preflight fit check before building (ISSUE 10; "
        "obs/devices.fit_check): abstract-eval the build+step at this "
        "run's geometry against per-chip HBM (bytes_limit or the "
        "device-kind table) and exit 3 with the per-stage table when "
        "it provably does not fit. Synthetic specs check BEFORE any "
        "graph work; file inputs check after the host parse, before "
        "the engine build (the device-allocation gate either way)",
    )
    ob.add_argument(
        "--dump-hlo", default=None, metavar="DIR",
        help="after the solve, harvest the step program(s)' OPTIMIZED "
        "HLO (obs/hlo.py; ISSUE 11) — gather-strategy classification, "
        "fusion/collective structure, lowering fingerprint — into the "
        "run report's `lowering` section and write the raw modules to "
        "DIR as <form>.hlo for offline diffing. Off by default: a "
        "plain run makes zero inspector calls (the tracer/sampler "
        "booby-trap discipline); jax engine only",
    )
    ob.add_argument(
        "--graph-profile", action="store_true",
        help="arm the data-plane profiler (ISSUE 13; "
        "obs/graph_profile.py): device builds compute the structural "
        "profile — log2 degree histograms, dedup/self-loop counts, "
        "top hubs, partition skew, power-law tail — in one fused "
        "reduction pass during the build; host builds profile in "
        "numpy after the engine packs. Publishes graph.* gauges, the "
        "run report's `graph` section (diffed FIRST as data drift by "
        "`obs report`), the skew-driven load prediction for this "
        "run's mesh, and — under --job-dir — a checksummed profile "
        "artifact keyed by graph fingerprint. Off by default: a "
        "disarmed run makes zero profile computations (the "
        "tracer/sampler booby-trap discipline)",
    )
    ob.add_argument(
        "--stall-timeout", type=float, default=None, metavar="SECONDS",
        help="arm the stall watchdog: if no solve step completes "
        "within SECONDS, log a loud diagnostic (last-completed "
        "iteration + per-device view) — a hung collective becomes "
        "visible instead of silent. Fused runs heartbeat at chunk "
        "boundaries; size the timeout above a chunk's expected wall",
    )
    ob.add_argument(
        "--stall-action", choices=["warn", "raise", "rescue"],
        default="warn",
        help="what the watchdog does on a stall: 'warn' logs and "
        "keeps waiting; 'raise' also interrupts the run "
        "(KeyboardInterrupt at the next bytecode boundary); 'rescue' "
        "classifies the stall (hang vs device-lost via per-device "
        "liveness probes), and on device loss tears down the mesh, "
        "rebuilds it over the surviving devices, re-shards the graph, "
        "and warm-starts from the newest valid snapshot "
        "(docs/ROBUSTNESS.md 'Elastic solve'; jax engine, host-built "
        "graph, stepwise loop)",
    )
    p.add_argument("--strict-parse", action="store_true", help="crawl mode: die on bad records")
    p.add_argument(
        "--ingest-workers", type=int, default=None,
        help="parallel parse processes for multi-file SequenceFile "
        "segments (the reference parses its 301 segment files across "
        "the cluster, Sparky.java:61). Setting this selects the Python "
        "process-pool path explicitly (default: the native C++ parser "
        "when available — one per core, capped by file count; 1 = "
        "serial). Record order (and so vertex ids) is identical on "
        "every path",
    )
    p.add_argument(
        "--no-native-ingest", action="store_true",
        help="force the pure-Python crawl/SequenceFile parser instead "
        "of the native C++ L1 (native/crawl_ingest.cpp)",
    )
    p.add_argument(
        "--host-mem-cap-gb", type=float, default=None,
        help="route the host build through the out-of-core "
        "external-sort (ingest/external.py) with this working-memory "
        "cap in GiB — for edge sets whose in-memory build would exceed "
        "host RAM (the reference streams partitions from S3 and never "
        "holds the edge set in one space, Sparky.java:61,124). "
        "Integer edge inputs (text/.npz) stream directly; "
        "crawl/SequenceFile inputs drain the native L1's edges "
        "per-batch into the same sort (the interner's url table, "
        "O(vertices), stays in RAM — it IS the product). Identical "
        "Graph output. Not with --device-build/--synthetic",
    )
    p.add_argument(
        "--no-compile-cache", action="store_true",
        help="don't persist XLA executables across runs "
        "(utils/compile_cache; default: cache under the checkout's "
        ".jax_cache or ~/.cache/pagerank_tpu)",
    )
    ppr = p.add_argument_group("personalized PageRank (batched SpMM)")
    ppr.add_argument(
        "--ppr-sources",
        default=None,
        help="run PPR instead of global PageRank: comma-separated vertex "
        "ids, 'random:K' for K random sources, or a file with one id/url "
        "per line",
    )
    ppr.add_argument("--ppr-topk", type=int, default=100,
                     help="top-k ranked vertices reported per source")
    ppr.add_argument("--ppr-chunk", type=int, default=64,
                     help="source-batch columns processed per device pass")
    ppr.add_argument(
        "--ppr-dangling",
        choices=["source", "uniform"],
        default="source",
        help="where dangling mass re-enters (source = standard PPR)",
    )
    return p


def parse_ppr_sources(spec: str, ids, n: int) -> np.ndarray:
    """--ppr-sources value -> vertex id array. Accepts 'random:K', a
    comma list of ids (or urls when the graph has an id map), or a path
    to a file of one id/url per line."""

    def resolve(tok: str) -> int:
        tok = tok.strip()
        if tok.lstrip("-").isdigit():
            v = int(tok)
            if not 0 <= v < n:
                raise SystemExit(f"--ppr-sources: id {v} out of range [0, {n})")
            return v
        if ids is None:
            raise SystemExit(
                f"--ppr-sources: {tok!r} is not an integer id and this "
                f"input has no url->id table"
            )
        v = ids.get(tok)
        if v is None:
            raise SystemExit(f"--ppr-sources: unknown url {tok!r}")
        return v

    if spec.startswith("random:"):
        try:
            k = int(spec.split(":", 1)[1])
        except ValueError:
            raise SystemExit(f"--ppr-sources: bad count in {spec!r}")
        if k <= 0:
            raise SystemExit(f"--ppr-sources: count must be positive in {spec!r}")
        rng = np.random.default_rng(0)
        return rng.choice(n, size=min(k, n), replace=False).astype(np.int64)
    # Treat the spec as a source FILE only when it plausibly is one: a
    # local path that exists, or a registered-scheme URI that exists.
    # URL-named vertices (crawl graphs) legitimately contain "://" —
    # "http://a/,http://b/" must resolve through the id map, not fsio.
    scheme = fsio.scheme_of(spec)
    is_file = (
        fsio.exists(spec)
        if scheme is not None and fsio.registered(scheme)
        else scheme is None and os.path.exists(spec)
    )
    if is_file:
        with fsio.fopen(spec) as f:
            toks = [ln for ln in (l.strip() for l in f) if ln]
        return np.array([resolve(t) for t in toks], dtype=np.int64)
    return np.array([resolve(t) for t in spec.split(",")], dtype=np.int64)


def reject_ppr_incompatible_flags(args) -> None:
    """Flags that only apply to the global-PageRank path; reject loudly
    rather than silently dropping what the user asked for. Pure-args —
    called from main() BEFORE the (potentially minutes-long) graph
    load, like the --fused/--device-build guards. (--host-mem-cap-gb
    legitimately applies — it shapes the shared host graph build the
    PPR engine consumes.)"""
    ignored = [
        (name, flag)
        for name, flag in (
            ("--semantics", args.semantics != "reference"),
            ("--tol", args.tol is not None),
            ("--snapshot-dir", args.snapshot_dir is not None),
            ("--resume", args.resume),
            ("--dump-text-dir", args.dump_text_dir is not None),
            ("--jsonl", args.jsonl is not None),
            ("--profile-dir", args.profile_dir is not None),
            # The PPR engine has its own chunked dispatch loop; the
            # tracer/flight-recorder instrumentation covers the global-
            # PageRank path only (for now — reject, never silently drop).
            ("--trace", args.trace is not None),
            ("--run-report", args.run_report is not None),
            ("--probe-every", bool(args.probe_every)),
            ("--stop-tol", args.stop_tol is not None),
            ("--metrics-textfile", args.metrics_textfile is not None),
            ("--metrics-port", args.metrics_port is not None),
            ("--stall-timeout", args.stall_timeout is not None),
            # The sampler hooks PageRankEngine.run (the global-
            # PageRank loop); the PPR engine's chunked dispatch never
            # reads it — reject rather than silently not sample.
            ("--device-sample-every", bool(args.device_sample_every)),
            ("--preflight", args.preflight),
            # PprJaxEngine builds replicated [n, k] state and its own
            # stripe layout; the memory-scaling mode and the lane-group
            # override are not implemented there (VERDICT r4 weak #2).
            ("--vertex-sharded", args.vertex_sharded),
            ("--vs-bounded", args.vs_bounded),
            ("--lane-group", args.lane_group is not None),
        )
        if flag
    ]
    if ignored:
        raise SystemExit(
            "ppr mode does not support: "
            + ", ".join(name for name, _ in ignored)
        )
    if args.ppr_chunk is not None and args.ppr_chunk <= 0:
        raise SystemExit("--ppr-chunk must be positive")


def run_ppr(args, graph, ids) -> int:

    cfg = PageRankConfig(
        num_iters=args.iters,
        damping=args.damping,
        dtype=args.dtype,
        accum_dtype=args.accum_dtype or args.dtype,
        num_devices=args.num_devices,
    ).validate()
    sources = parse_ppr_sources(args.ppr_sources, ids, graph.n)
    t0 = time.perf_counter()
    if args.engine == "cpu":
        from pagerank_tpu.engines.ppr import ppr_cpu_topk

        print(
            "ppr --engine cpu runs the float64 numpy oracle; "
            "--ppr-chunk/--num-devices/--dtype/--accum-dtype do not apply",
            file=sys.stderr,
        )
        res = ppr_cpu_topk(
            graph, cfg, sources, topk=args.ppr_topk,
            dangling_to=args.ppr_dangling,
        )
    else:
        from pagerank_tpu.engines.ppr import PprJaxEngine

        eng = PprJaxEngine(cfg, dangling_to=args.ppr_dangling).build(graph)
        res = eng.run(sources, topk=args.ppr_topk, chunk=args.ppr_chunk)
    dt = time.perf_counter() - t0
    topk = int(res.topk_ids.shape[1])
    print(
        f"ppr: {len(sources)} sources x {args.iters} iters, top-{topk} "
        f"in {dt:.2f}s ({graph.num_edges * len(sources) * args.iters / dt:.3g} "
        f"edge·vectors/s)",
        file=sys.stderr,
    )
    names = ids.names if ids is not None else None
    out = args.out
    f = fsio.fopen(out, "w") if out else sys.stdout
    try:
        for si, s in enumerate(res.sources):
            skey = names[s] if names else s
            for v, r in zip(res.topk_ids[si], res.topk_scores[si]):
                vkey = names[v] if names else v
                f.write(f"{skey}\t{vkey}\t{float(r)!r}\n")
    finally:
        if out:
            f.close()
    if out:
        print(f"wrote {len(res.sources)}x{topk} ppr rows to {out}",
              file=sys.stderr)
    return 0


def _device_build_graph(args, src, dst, n, dangling_mask=None,
                        names=None):
    """Pack raw (src, dst) edges on device with the SAME layout planner
    the bench uses (ops/device_build.plan_build), so product users get
    the build performance the bench measures (VERDICT r2 #3). ``src``/
    ``dst`` may be host numpy (uploaded raw: 8 bytes/edge) or already
    device arrays (synthetic rmat: only a seed crossed the link).
    ``dangling_mask`` carries crawl inputs' uncrawled-targets-only
    dangling semantics into the device build (SURVEY.md §2a.3)."""
    if n == 0:
        # Same error as the host path's build_graph, instead of building
        # an n=0 DeviceEllGraph that fails obscurely downstream; main()
        # converts it to a clean SystemExit for both paths.
        raise ValueError("empty graph: no vertices")
    from pagerank_tpu.ops import device_build as db

    # Resumable-job hook (jobs.py; armed by _job_load_graph): persist
    # the raw edges as the ingest artifact BEFORE the device build, so
    # a job killed mid-build resumes without re-parsing. Synthetic
    # inputs arrive as device arrays — nothing worth persisting, only
    # the seed crossed the link.
    job = getattr(args, "_job", None)
    if job is not None:
        if isinstance(src, np.ndarray):
            arrays = {"src": np.asarray(src), "dst": np.asarray(dst)}
            if dangling_mask is not None:
                arrays["dangling_mask"] = np.asarray(dangling_mask)
            job.save_stage_artifact(
                "ingest", arrays,
                {"key": args._job_key, "kind": "raw_edges", "n": int(n)},
            )
            if names is not None:
                # Crawl/seqfile inputs: the id->name table commits WITH
                # the raw edges, not after the 30-75s build — a job
                # killed mid-sort must still write urls (not integer
                # ids) from --out on every later resume.
                job.save_names(names, args._job_key)
            job.complete("ingest")
        else:
            job.complete("ingest", synthetic=True)
        job.begin("build")

    # stream_dtype never changes the planned GEOMETRY (the stream is a
    # per-iteration cast) and requires a resolved span to validate, so
    # the plan config omits it — but the MODE flags must be here, or
    # plan_build's partition-span compatibility gate (vertex-sharded
    # modes plan span 0) never fires for device builds.
    plan_cfg = PageRankConfig(
        dtype=args.dtype, accum_dtype=args.accum_dtype or args.dtype,
        vertex_sharded=args.vertex_sharded, vs_bounded=args.vs_bounded,
    ).validate()
    grp, stripe, part = db.plan_build(
        plan_cfg, n, lane_group=args.lane_group or 0, num_edges=len(src),
        partition_span=args.partition_span,
    )
    # The run config must adopt the RESOLVED span (engine.build_device
    # checks it against the packed stripe span) — stash it for main().
    args._resolved_partition_span = part
    return db.build_ell_device(
        src, dst, n=n, group=grp, stripe_size=stripe,
        with_weights=False,  # presentinel: no per-slot weight plane
        dangling_mask=dangling_mask,
    )


def load_graph(args):
    from pagerank_tpu.ingest import edgelist as el

    if args.host_mem_cap_gb and (args.device_build or args.synthetic):
        # Never silently drop a memory-bound promise: the out-of-core
        # build covers host builds of integer edge inputs only.
        raise SystemExit(
            "--host-mem-cap-gb applies to the HOST build of integer "
            "edge inputs (text/.npz); it cannot combine with "
            "--device-build or --synthetic"
        )
    if args.synthetic:
        # THE shared spec parser (also the --preflight geometry
        # source) — one grammar, one set of defaults.
        geo = _parse_synthetic_geometry(args.synthetic)
        if geo is None:
            raise SystemExit(f"unknown synthetic spec {args.synthetic!r}")
        kind, n, e, scale = geo
        if kind == "rmat":
            if args.device_build:
                from pagerank_tpu.ops import device_build as db

                src, dst = db.rmat_edges_device(scale, seed=0)
                return _device_build_graph(args, src, dst, n), None
            from pagerank_tpu.utils import synth

            src, dst = synth.rmat_edges(scale)
            return build_graph(src, dst, n=n), None
        if args.device_build:
            from pagerank_tpu.ops import device_build as db

            src, dst = db.uniform_edges_device(n, e, seed=0)
            return _device_build_graph(args, src, dst, n), None
        from pagerank_tpu.utils import synth

        src, dst = synth.uniform_edges(n, e)
        return build_graph(src, dst, n=n), None

    fmt = args.format
    path = args.input
    if fmt == "auto":
        from pagerank_tpu.ingest.seqfile import expand_seqfile_paths

        probe = path
        if fsio.isdir(path) or ("," in path and not fsio.exists(path)):
            # Comma-joined lists / segment dirs only make sense for
            # SequenceFile segments (the reference's input form); probe
            # the first file's magic. A plain file whose NAME contains a
            # comma is still a plain file.
            probe = expand_seqfile_paths(path)[0]
        with fsio.fopen(probe, "rb") as fb:
            magic = fb.read(4)
        # Require a version byte the reader actually supports (<= 6) so a
        # text file that merely *starts* with "SEQ" — including "SEQ\n"
        # (0x0A) or "SEQ\t" (0x09), both control bytes — falls through
        # to the text-format detection instead of hard-failing in the
        # SequenceFile reader's version check.
        if magic[:3] == b"SEQ" and len(magic) == 4 and magic[3] <= 6:
            fmt = "seqfile"
        elif probe != path:
            raise SystemExit(
                f"{path}: directory / comma-list inputs are for Hadoop "
                f"SequenceFile segments, but {probe} has no SEQ magic"
            )
        elif path.endswith(".npz"):
            fmt = "npz"
        else:
            with fsio.fopen(path, "r", errors="replace") as f:
                first = f.readline()
                while first.startswith("#"):
                    first = f.readline()
            tokens = first.split()
            fmt = (
                "edgelist"
                if len(tokens) == 2 and all(t.lstrip("-").isdigit() for t in tokens)
                else "crawl"
            )
    native = "off" if args.no_native_ingest else "auto"
    if args.host_mem_cap_gb and fmt in ("seqfile", "crawl"):
        # Out-of-core crawl build (VERDICT r4 #4): native L1 batches
        # drained into the external sort; the edge set is never
        # resident in one space. Never silently drop a memory-bound
        # promise: without the native library (or with it disabled),
        # fail loudly instead of falling back to the in-memory path.
        from pagerank_tpu.ingest.native import crawl_load_external
        from pagerank_tpu.ingest.seqfile import expand_seqfile_paths

        if native == "off":
            raise SystemExit(
                "--host-mem-cap-gb with crawl/SequenceFile inputs needs "
                "the native ingest path; drop --no-native-ingest"
            )
        paths = expand_seqfile_paths(path) if fmt == "seqfile" else [path]
        res = crawl_load_external(
            paths, "seqfile" if fmt == "seqfile" else "tsv",
            mem_cap_bytes=int(args.host_mem_cap_gb * (1 << 30)),
            strict=args.strict_parse, threads=args.ingest_workers,
        )
        if res is None:
            raise SystemExit(
                "--host-mem-cap-gb with crawl/SequenceFile inputs needs "
                "the native library (g++ toolchain) — it is unavailable "
                "or predates crawl_drain_edges"
            )
        return res
    if fmt == "seqfile":
        if args.device_build:
            from pagerank_tpu.ingest import load_crawl_seqfile_arrays

            src, dst, crawled, ids = load_crawl_seqfile_arrays(
                path, strict=args.strict_parse, workers=args.ingest_workers,
                native=native,
            )
            return _device_build_graph(args, src, dst, len(ids),
                                       dangling_mask=~crawled,
                                       names=ids.names), ids
        from pagerank_tpu.ingest import load_crawl_seqfile

        graph, ids = load_crawl_seqfile(
            path, strict=args.strict_parse, workers=args.ingest_workers,
            native=native,
        )
        return graph, ids
    if fmt == "crawl":
        if args.device_build:
            from pagerank_tpu.ingest import load_crawl_file_arrays

            src, dst, crawled, ids = load_crawl_file_arrays(
                path, strict=args.strict_parse, native=native)
            return _device_build_graph(args, src, dst, len(ids),
                                       dangling_mask=~crawled,
                                       names=ids.names), ids
        from pagerank_tpu.ingest import load_crawl_file

        graph, ids = load_crawl_file(path, strict=args.strict_parse,
                                     native=native)
        return graph, ids
    if args.host_mem_cap_gb:
        # Out-of-core external-sort build for integer edge inputs: the
        # path dispatches on extension (.npz / text) itself.
        from pagerank_tpu.ingest import external

        return external.build_graph_external(
            path, mem_cap_bytes=int(args.host_mem_cap_gb * (1 << 30))
        ), None
    if fmt == "npz":
        src, dst, n = el.load_binary_edges(path)
        if args.device_build:
            if n is None:  # optional field; mirror build_graph's max+1
                n = int(max(src.max(), dst.max())) + 1 if len(src) else 0
            return _device_build_graph(args, src, dst, n), None
        return build_graph(src, dst, n=n), None
    src, dst = el.load_edgelist(path)
    if args.device_build:
        n = int(max(src.max(), dst.max())) + 1 if len(src) else 0
        return _device_build_graph(args, src, dst, n), None
    return build_graph(src, dst), None


def _s3_retry_total(paths) -> int:
    """Sum of transparent request retries across the distinct
    S3FileSystem instances serving the given output paths (for the
    run's robustness summary)."""
    from pagerank_tpu.utils.s3 import S3FileSystem

    seen, total = set(), 0
    for p in paths:
        if not p:
            continue
        scheme = fsio.scheme_of(p)
        if scheme is None or not fsio.registered(scheme):
            continue
        fs = fsio.get_fs(p)
        if isinstance(fs, S3FileSystem) and id(fs) not in seen:
            seen.add(id(fs))
            total += fs.retry_stats.retries
    return total


def _publish_graph_profile(args, cfg, graph, engine, job) -> None:
    """--graph-profile (ISSUE 13; obs/graph_profile.py): make sure a
    profile exists and is published — device builds computed it inside
    the build, resumed jobs restore the checksummed artifact keyed by
    graph fingerprint, host builds profile in numpy at the layout the
    engine actually packed — then attach the skew-driven load
    prediction for this run's mesh (parallel/comms) and persist the
    job artifact. Best-effort telemetry: never fails the run."""
    from pagerank_tpu.obs import graph_profile
    from pagerank_tpu.parallel import comms

    try:
        prof = graph_profile.get_profile()
        restored = False
        if prof is None and job is not None:
            prof = job.load_profile(graph.fingerprint())
            if prof is not None:
                graph_profile.publish(prof)
                restored = True
        if prof is None and hasattr(graph, "in_degree"):
            lay = (engine.layout_info()
                   if engine is not None
                   and hasattr(engine, "layout_info") else {})
            group, span = graph_profile.layout_profile_geometry(lay)
            prof = graph_profile.profile_graph(
                graph, group=group, partition_span=span,
            )
            graph_profile.publish(prof)
        if prof is None:
            return  # device graph restored without its artifact
        ndev = 1
        if engine is not None and getattr(engine, "mesh", None) is not None:
            ndev = engine.mesh.devices.size
        pred = comms.predict_from_profile(prof, ndev)
        comms.publish_prediction(pred)
        prof.prediction = pred
        if job is not None and not restored:
            job.save_profile(prof)
    except Exception as e:  # telemetry must not fail the solve
        print(f"pagerank_tpu: graph profile publish failed ({e!r})",
              file=sys.stderr)


def _robustness_summary(args, engine, guard) -> dict:
    """The run's robustness counters (docs/ROBUSTNESS.md) as one dict —
    feeds both the stderr summary line and the flight recorder."""
    counters = obs.get_registry().snapshot()["counters"]
    return {
        "rollbacks": getattr(engine, "health", {}).get("rollbacks", 0) or 0,
        "rescues": int(counters.get("elastic.rescues", 0)),
        "devices_lost": int(counters.get("elastic.devices_lost", 0)),
        "write_retries": guard.retries,
        "dropped_writes": len(guard.dropped),
        "s3_request_retries": _s3_retry_total(
            (args.snapshot_dir, args.dump_text_dir, args.out, args.jsonl)
        ),
        # SDC plane (ISSUE 15; pagerank_tpu/sdc.py): detection /
        # classification / quarantine counts — zero on a disarmed run.
        "sdc_flips_detected": int(counters.get("sdc.flips_detected", 0)),
        "sdc_transient_flips": int(
            counters.get("sdc.transient_flips", 0)),
        "sdc_quarantined_devices": int(
            counters.get("sdc.quarantined_devices", 0)),
    }


def _arm_history_baseline(ledger_path, cfg, graph, num_chips) -> None:
    """--history, the live half (ISSUE 9): read the perf ledger, take
    the robust baseline (median of the trailing window) of
    edges/s/chip for THIS run's leg within THIS environment class
    (baselines never mix backends — the r5 lesson), and arm the
    ``history.*`` gauges so every iteration publishes % vs baseline
    through the exporter. Advisory only: an unreadable or empty
    ledger just means no baseline."""
    from pagerank_tpu.obs import history as history_mod
    from pagerank_tpu.obs import live as obs_live

    try:
        records = history_mod.read_ledger(ledger_path)
    except ValueError as e:
        print(f"pagerank_tpu: perf ledger unreadable ({e}); no "
              "baseline armed", file=sys.stderr)
        return
    klass = ...
    try:
        import jax

        devs = jax.devices()
        klass = (jax.default_backend(),
                 devs[0].device_kind if devs else None)
    except Exception as e:  # backend down: baseline unscoped, loudly
        print(f"pagerank_tpu: backend probe failed ({e!r}); history "
              "baseline compares across all environment classes",
              file=sys.stderr)
    leg = history_mod.leg_name_for_config(cfg)
    pts = history_mod.series(records, leg, "edges_per_sec_per_chip",
                             klass=klass)
    vals = [v for _, v in pts][-history_mod.DEFAULT_DETECTION["window"]:]
    if not vals:
        print(f"pagerank_tpu: perf ledger {ledger_path} has no "
              f"'{leg}' records for this environment; no baseline "
              "armed", file=sys.stderr)
        return
    med, _mad = history_mod.median_mad(vals)
    obs_live.arm_history_baseline(obs_live.HistoryBaseline(
        leg=leg, baseline_eps=med, num_edges=int(graph.num_edges),
        num_chips=num_chips, n_baseline=len(vals)))


def _append_history_record(args, cfg, graph, summary, robustness,
                           tracer, report=None) -> None:
    """--history, the durable half: this run, normalized to the
    canonical RunRecord (via its flight-recorder report — the same
    shape `obs report` consumes; the report --run-report already built
    is reused rather than re-serialized), appended to the ledger.
    Best-effort: a full solve must never die writing its own
    history."""
    from pagerank_tpu.obs import history as history_mod

    if report is None:
        report = obs.build_run_report(
            config=cfg,
            tracer=tracer,
            registry=obs.get_registry(),
            summary=summary,
            robustness=robustness,
            extra={
                "graph": {"n": int(graph.n),
                          "num_edges": int(graph.num_edges),
                          **obs.graph_profile.report_section()},
                "engine": args.engine,
            },
        )
    try:
        rec = history_mod.normalize_result(report, source="cli")
        added = history_mod.append_record(args.history, rec)
    except (OSError, ValueError) as e:
        print(f"pagerank_tpu: perf-history append failed: {e!r}",
              file=sys.stderr)
        return
    print(("appended run record to" if added
           else "run record already in")
          + f" perf ledger {args.history}", file=sys.stderr)


def _export_observability(args, tracer, cfg, graph, metrics, summary,
                          robustness, probes=None, error=None,
                          interrupted=None, job=None) -> None:
    """Write the --trace export and/or --run-report artifact
    (docs/OBSERVABILITY.md). Called on the success path AND — with
    ``error`` set, best-effort — from the failure path: the failing
    run's telemetry is exactly what a postmortem needs. ``cfg`` /
    ``graph`` / ``metrics`` may be None on early failures (the run
    died before they existed); the report still carries every section
    key. The ``costs`` section comes from the process cost ledger
    (obs/costs.py) by default; ``probes`` adds the convergence-probe
    history as its own section (fused runs' probe records don't ride
    the per-iteration history). Returns the report dict when one was
    built (None otherwise) so --history can reuse it instead of
    serializing the registry/span/cost state a second time."""
    if args.trace:
        tracer.export(args.trace)
        print(f"wrote trace to {args.trace}", file=sys.stderr)
    if not args.run_report:
        return None
    extra = {
        # Data plane (ISSUE 13): the graph's identity plus — when
        # --graph-profile armed the profiler — the structural profile
        # and load prediction, diffed FIRST by `obs report A B`.
        "graph": (
            {"n": int(graph.n), "num_edges": int(graph.num_edges),
             **obs.graph_profile.report_section()}
            if graph is not None else None
        ),
        "engine": args.engine,
        "fused": bool(args.fused),
        "failed": error is not None,
        # Preemption drain (ISSUE 12): an interrupted run is NOT a
        # failed one — it drained cleanly and resumes from its job
        # dir; the marker lets `obs report` say which it was.
        "interrupted": interrupted is not None,
        "probes": probes.history if probes is not None else [],
        # SDC plane (ISSUE 15): the detection/classification summary
        # — empty on a disarmed run, diffed by `obs report A B`.
        "sdc": sdc_mod.report_section(),
    }
    if error is not None:
        extra["error"] = repr(error)
    if interrupted is not None:
        extra["interrupt_signal"] = getattr(interrupted, "signum", None)
    report = obs.build_run_report(
        config=cfg,
        tracer=tracer,
        registry=obs.get_registry(),
        history=metrics.history if metrics is not None else [],
        summary=summary,
        robustness=robustness,
        job=job.report_section() if job is not None else None,
        extra=extra,
    )
    obs.write_run_report(args.run_report, report)
    print(f"wrote run report to {args.run_report}", file=sys.stderr)
    return report


def _export_failure(ctx, err) -> None:
    """Best-effort failure-path export from whatever run state exists.
    ``ctx`` is filled incrementally by _main as objects come into
    existence, so a run that dies during ingest, engine build, resume,
    the solve, or the final --out write all leave their trace and a
    failure-marked report — the postmortem case the flight recorder
    exists for. (When the success export already ran and a LATER step
    failed, this overwrites it with the correctly failure-marked one.)
    Never masks the primary error."""
    args = ctx.get("args")
    tracer = ctx.get("tracer")
    if args is None or tracer is None or not tracer.enabled:
        return
    if not (args.trace or args.run_report):
        return
    try:
        metrics = ctx.get("metrics")
        guard = ctx.get("guard")
        _export_observability(
            args, tracer, ctx.get("cfg"), ctx.get("graph"), metrics,
            summary=metrics.summary() if metrics is not None else {},
            robustness=(
                _robustness_summary(args, ctx.get("engine"), guard)
                if guard is not None else {}
            ),
            probes=ctx.get("probes"),
            error=err,
            job=ctx.get("job"),
        )
    except Exception as e2:
        print(f"pagerank_tpu: failure-path observability export "
              f"failed: {e2!r}", file=sys.stderr)


def _parse_synthetic_geometry(spec: str):
    """(kind, n, raw num_edges, scale-or-None) from a --synthetic
    spec, or None when the spec is unrecognized/malformed. THE one
    spelling of the spec grammar and its defaults (rmat scale 20, 16
    edges/vertex — utils/synth's edge_factor): load_graph dispatches
    on it AND --preflight gates on it, so the two can never disagree
    about what geometry a spec means."""
    kind, _, rest = spec.partition(":")
    try:
        if kind == "rmat":
            scale = int(rest or 20)
            return "rmat", 1 << scale, 16 << scale, scale
        if kind == "uniform":
            n_s, _, e_s = rest.partition(":")
            n = int(n_s)
            return "uniform", n, int(e_s or 16 * n), None
    except ValueError:
        return None
    return None


def _run_preflight(args, n: int, num_edges: int, scale,
                   device_build: bool) -> None:
    """--preflight (ISSUE 10): the OOM fit check at THIS run's
    geometry — exits 3 with the per-stage table when per-chip HBM
    provably cannot hold it, so a doomed scale-24/25 run dies in
    seconds instead of after a 75 s build."""
    from pagerank_tpu.obs import devices as obs_devices

    ndev = args.num_devices
    if ndev is None and args.vertex_sharded:
        import jax

        ndev = len(jax.devices())
    res = obs_devices.fit_check(
        scale if device_build else None, n=n, num_edges=num_edges,
        ndev=ndev or 1, dtype=args.dtype,
        accum_dtype=args.accum_dtype or args.dtype,
        vertex_sharded=bool(args.vertex_sharded),
        vs_bounded=bool(args.vs_bounded),
        device_build=device_build,
        # The run's OWN layout flags: the gate must model the build
        # the run executes, not the default layout's.
        lane_group=args.lane_group or 0,
        partition_span=args.partition_span,
    )
    print(obs_devices.render_fit(res), file=sys.stderr)
    if not res.fits:
        raise SystemExit(int(ExitCode.PREFLIGHT_UNFIT))


def _input_stamp(path):
    """Best-effort identity of a LOCAL input beyond its path string:
    (size, mtime_ns) — a file regenerated IN PLACE between runs must
    not let a resumed job serve the old graph's artifacts. Remote
    paths (s3://...), comma-joined lists, and vanished files degrade
    to None: the checksum+fingerprint validation still guards artifact
    INTEGRITY, this stamp guards input FRESHNESS where the filesystem
    can answer cheaply."""
    if not path:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    return [int(st.st_size), int(st.st_mtime_ns)]


def _job_graph_key(args) -> str:
    """Hash of everything that determines the ingest/build artifacts'
    CONTENT (input spec + layout-shaping args) — artifacts from a
    different input or layout must never satisfy this run's stages."""
    return jobs.key_hash({
        "input": args.input or args.synthetic,
        "input_stamp": _input_stamp(args.input),
        "format": args.format,
        # Parse SEMANTICS change the edge set (strict=False drops
        # malformed crawl entries); the native-vs-python path does NOT
        # (differentially tested identical) and stays out of the key.
        "strict_parse": bool(args.strict_parse),
        "device_build": bool(args.device_build),
        "host_mem_cap_gb": args.host_mem_cap_gb,
        "dtype": args.dtype,
        "accum_dtype": args.accum_dtype or args.dtype,
        "lane_group": args.lane_group or 0,
        "partition_span": args.partition_span,
        "vertex_sharded": bool(args.vertex_sharded),
        "vs_bounded": bool(args.vs_bounded),
    })


def _job_load_graph(args, job, drain):
    """The ingest + build stages of a resumable job (jobs.py): restore
    the graph from a validated durable artifact when one matches this
    run's key, else run the normal loaders and persist the artifacts.
    Corrupt or key-mismatched artifacts are recomputed, never trusted
    (the PR-3 snapshot discipline)."""
    key = _job_graph_key(args)

    if not args.device_build:
        # Host path: the BUILT Graph is the one artifact — restoring it
        # skips the parse AND the host sort; the engine packs its own
        # layout at build (the solve stage).
        hit = job.load_stage_artifact("ingest", expect={"key": key})
        if hit is not None:
            arrays, meta = hit
            try:
                with obs.span("job/ingest_restore"):
                    graph = jobs.graph_from_arrays(arrays, meta)
            except jobs.ArtifactCorruptError as e:
                warnings.warn(
                    f"job ingest artifact rejected ({e}); recomputing",
                    RuntimeWarning,
                )
            else:
                job.skip("ingest", fingerprint=meta.get("fingerprint"))
                job.skip("build",
                         note="host layout packs at engine build")
                names = jobs.decode_names(arrays)
                return graph, (jobs.RestoredIds(names) if names else None)
        with job.stage_span("ingest"):
            with obs.span("ingest/load",
                          input=args.input or args.synthetic):
                graph, ids = load_graph(args)
        arrays, meta = jobs.graph_to_arrays(graph)
        meta["key"] = key
        job.save_stage_artifact("ingest", arrays, meta)
        job.complete("ingest", fingerprint=meta["fingerprint"])
        job.begin("build")
        job.complete("build", note="host layout packs at engine build")
        # Drain AFTER the artifact commit: a SIGTERM that arrived
        # mid-ingest must not throw away the stage it just finished —
        # the resume's whole point is skipping this work.
        drain.check("ingest")
        return graph, ids

    # Device build: the build artifact holds the post-sort packed
    # planes — a restore skips ingest AND the composite-key sort (the
    # single biggest unrecoverable cost before ISSUE 12).
    from pagerank_tpu.ops import device_build as db

    hit = job.load_stage_artifact("build", expect={"key": key})
    if hit is not None:
        arrays, meta = hit
        try:
            with obs.span("job/build_restore"):
                graph = db.restore_device_graph(arrays, meta)
        except (ValueError, jobs.ArtifactCorruptError) as e:
            warnings.warn(
                f"job build artifact rejected ({e}); recomputing",
                RuntimeWarning,
            )
        else:
            job.skip("ingest", note="covered by build artifact")
            job.skip("build", fingerprint=meta.get("fingerprint"))
            if meta.get("partition_span"):
                args._resolved_partition_span = int(
                    meta["partition_span"])
            names = job.load_names(key)
            return graph, (jobs.RestoredIds(names) if names else None)

    graph, ids = None, None
    if not args.synthetic:
        # A prior run may have died DURING the build: the raw-edges
        # ingest artifact still skips the host parse.
        ing = job.load_stage_artifact("ingest", expect={"key": key})
        if ing is not None:
            arrs, imeta = ing
            job.skip("ingest")
            drain.check("ingest")
            names = job.load_names(key)
            ids = jobs.RestoredIds(names) if names else None
            job.begin("build")
            with obs.span("job/build"):
                graph = _device_build_graph(
                    args, arrs["src"], arrs["dst"], int(imeta["n"]),
                    dangling_mask=arrs.get("dangling_mask"),
                )
    if graph is None:
        # Fresh run: the normal loader path, with the supervisor hook
        # armed so _device_build_graph persists the raw-edges ingest
        # artifact (file inputs) and marks the stage transitions.
        args._job = job
        args._job_key = key
        try:
            with obs.span("ingest/load",
                          input=args.input or args.synthetic):
                graph, ids = load_graph(args)
        finally:
            args._job = None
    arrays, meta = db.checkpoint_arrays(graph)
    meta["key"] = key
    part = getattr(args, "_resolved_partition_span", None)
    if part:
        meta["partition_span"] = int(part)
    job.save_stage_artifact("build", arrays, meta)
    job.complete("build", fingerprint=meta["fingerprint"])
    # (names.npz already committed: the fresh crawl path saves it with
    # the raw-edges artifact inside _device_build_graph's hook, and the
    # restored-ingest branch just loaded it from disk — no rewrite of a
    # potentially huge id->url table here.)
    # Drain AFTER the artifact commit (not before): a SIGTERM during
    # the 30-75s sort must still persist build.npz — that artifact is
    # the single biggest thing a resume exists to skip.
    drain.check("build")
    return graph, ids


def main(argv=None) -> int:
    ctx = {}
    try:
        return _main(argv, ctx)
    except BaseException as e:
        _export_failure(ctx, e)
        raise
    finally:
        # The process-global tracer (and an armed watchdog or device
        # sampler) must never outlive the run that enabled it —
        # success, failure, and SystemExit alike (tests drive main()
        # in-process; a leaked tracer would silently accumulate the
        # next run's spans, and a leaked watchdog thread would bark at
        # an idle process).
        obs.disable_tracing()
        obs.disarm_watchdog()
        obs.disarm_sampler()
        obs.disarm_history_baseline()
        obs.graph_profile.disarm()


def _main(argv, ctx) -> int:
    args = build_parser().parse_args(argv)
    ctx["args"] = args
    # Preemption drain (ISSUE 12; pagerank_tpu/jobs.py): the
    # SIGTERM/SIGINT handlers live ONLY around this entry point —
    # library modules stay handler-free (lint PTL008). A drain request
    # surfaces as DrainInterrupt at the next safe point (completed
    # step / stage boundary) and exits ExitCode.INTERRUPTED after the
    # deadline-bounded flush; a second signal hard-exits 128+signum.
    drain = jobs.GracefulDrain(deadline_s=args.drain_deadline)
    ctx["drain"] = drain
    with drain:
        try:
            return _run(args, ctx, drain)
        except jobs.DrainInterrupt as e:
            return _interrupted_exit(ctx, e, drain)


def _interrupted_exit(ctx, e: "jobs.DrainInterrupt", drain) -> int:
    """The graceful-preemption exit path: record the drain wall, mark
    the job manifest interrupted (when a stage didn't already), export
    the interrupted-marked run report + trace from whatever run state
    exists, and return the documented distinct code. The in-solve half
    of the drain (final snapshot, writer flush) already ran in
    _run_solve's handler before this."""
    args = ctx["args"]
    spent = drain.finish()
    job = ctx.get("job")
    if job is not None and job.manifest.get("status") != "interrupted":
        job.interrupt(e.where or "run", signal=e.signum)
    metrics = ctx.get("metrics")
    tracer = ctx.get("tracer")
    guard = ctx.get("guard")
    try:
        if metrics is not None:
            metrics.close()
        if tracer is not None and (args.trace or args.run_report):
            _export_observability(
                args, tracer, ctx.get("cfg"), ctx.get("graph"), metrics,
                summary=metrics.summary() if metrics is not None else {},
                robustness=(
                    _robustness_summary(args, ctx.get("engine"), guard)
                    if guard is not None else {}
                ),
                probes=ctx.get("probes"),
                interrupted=e,
                job=job,
            )
    except Exception as e2:  # the drain must still exit 75
        print(f"pagerank_tpu: interrupted-run observability export "
              f"failed: {e2!r}", file=sys.stderr)
    try:
        sig = signal.Signals(e.signum).name if e.signum else "signal"
    except ValueError:
        sig = f"signal {e.signum}"
    print(
        f"pagerank_tpu: interrupted by {sig}; drained in {spent:.2f}s"
        + (f" — rerun with --job-dir {args.job_dir} to resume"
           if args.job_dir else "")
        + f" (exit {int(ExitCode.INTERRUPTED)})",
        file=sys.stderr,
    )
    return int(ExitCode.INTERRUPTED)


def _run(args, ctx, drain) -> int:
    if args.engine == "jax" and not args.no_compile_cache:
        # Persist XLA executables across CLI runs: the engine-setup
        # chain is ~50 small jitted programs (and the device build ~50
        # more), each ~0.6s through a tunneled remote-compile service —
        # warm runs then spend seconds, not minutes, before iterating
        # (bench.py does the same — utils/compile_cache docstring).
        from pagerank_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache()
    if args.device_build:
        if args.engine != "jax":
            print("--device-build requires --engine jax", file=sys.stderr)
            return int(ExitCode.USAGE)
        if args.ppr_sources:
            print("--device-build does not support --ppr-sources "
                  "(the PPR engine builds from a host graph)",
                  file=sys.stderr)
            return int(ExitCode.USAGE)
    if args.fused:
        # Pure-args validation BEFORE the (potentially minutes-long)
        # graph load and engine build. (--tol IS fused-compatible: the
        # early stop runs on device via run_fused_tol.)
        bad = [
            flag for flag, on in (
                ("--dump-text-dir", args.dump_text_dir is not None),
                ("--ppr-sources", bool(args.ppr_sources)),
            ) if on
        ]
        if bad:
            print(
                f"--fused runs the loop in fused device dispatches; "
                f"{', '.join(bad)} need host control every iteration",
                file=sys.stderr,
            )
            return int(ExitCode.USAGE)
        if args.engine != "jax":
            print("--fused requires --engine jax", file=sys.stderr)
            return int(ExitCode.USAGE)
    if args.stall_action == "rescue":
        # Pure-args validation before the graph load: rescue rebuilds
        # the engine over surviving devices, which needs the stepwise
        # loop and a host graph to re-shard (a device-built graph's
        # slot arrays are donated away at build).
        bad = [
            flag for flag, on in (
                ("--fused", args.fused),
                ("--device-build", args.device_build),
                ("--ppr-sources", bool(args.ppr_sources)),
            ) if on
        ]
        if bad:
            print(
                f"--stall-action rescue re-shards the graph onto a "
                f"rebuilt mesh (stepwise loop, host-built graph); "
                f"incompatible with {', '.join(bad)}",
                file=sys.stderr,
            )
            return int(ExitCode.USAGE)
        if args.engine != "jax":
            print("--stall-action rescue requires --engine jax",
                  file=sys.stderr)
            return int(ExitCode.USAGE)
    if args.ppr_sources:
        reject_ppr_incompatible_flags(args)
    if args.device_sample_every < 0:
        print("--device-sample-every must be >= 0", file=sys.stderr)
        return int(ExitCode.USAGE)
    if args.sdc_check_every:
        # Pure-args validation before the graph load: the SDC guard
        # drives the STEPWISE loop (retain/redo needs host control
        # between steps) and measures per-device invariants only the
        # jax engine's mesh has.
        if args.sdc_check_every < 0:
            print("--sdc-check-every must be >= 0", file=sys.stderr)
            return int(ExitCode.USAGE)
        if args.fused:
            print("--sdc-check-every drives the stepwise loop "
                  "(bounded re-execution needs host control between "
                  "steps); incompatible with --fused",
                  file=sys.stderr)
            return int(ExitCode.USAGE)
        if args.engine != "jax":
            print("--sdc-check-every requires --engine jax (the ABFT "
                  "invariants are per-device measurements)",
                  file=sys.stderr)
            return int(ExitCode.USAGE)
    if args.job_dir:
        # Pure-args validation + defaults BEFORE any work: the
        # resumable stage machine covers the global-PageRank pipeline;
        # snapshots land in the job dir (resume always attempted).
        if args.ppr_sources:
            print("--job-dir does not support --ppr-sources (the "
                  "stage machine covers the global-PageRank pipeline)",
                  file=sys.stderr)
            return int(ExitCode.USAGE)
        if args.drain_deadline <= 0:
            print("--drain-deadline must be positive", file=sys.stderr)
            return int(ExitCode.USAGE)
        if not args.snapshot_dir:
            args.snapshot_dir = fsio.join(args.job_dir, "snapshots")
        args.resume = True
    if args.preflight and args.engine != "jax":
        print("--preflight sizes against device HBM; it requires "
              "--engine jax", file=sys.stderr)
        return int(ExitCode.USAGE)
    # Observability state is per-run, never inherited: a previous
    # in-process main() call (tests drive the CLI this way) must not
    # leak its tracer, counters, or cost ledger into this one.
    obs.disable_tracing()
    obs.get_registry().reset()
    obs.costs.reset()
    obs.hlo.reset()
    obs.graph_profile.reset()
    sdc_mod.reset()
    if args.graph_profile:
        # Data-plane profiler (ISSUE 13): armed BEFORE the graph load
        # so a --device-build computes the profile inside the build's
        # own fused reduction pass; disarmed in main()'s finally.
        obs.graph_profile.arm()
    tracer = (obs.enable_tracing() if (args.trace or args.run_report)
              else obs.get_tracer())
    ctx["tracer"] = tracer
    # Resumable-job supervisor (ISSUE 12; jobs.py): created AFTER the
    # registry reset so its job.* telemetry survives into this run's
    # report. Finding a prior manifest in the dir counts a resume.
    job = jobs.JobSupervisor(args.job_dir) if args.job_dir else None
    ctx["job"] = job
    if job is not None and args.sdc_check_every:
        # Convictions persist AT conviction time (ISSUE 15): a sticky
        # chip lands in job.json even when no elastic rescue is wired
        # to survive it — the resumed job excludes it either way.
        sdc_mod.set_quarantine_hook(job.quarantine_devices)
    if args.preflight and args.synthetic:
        # Synthetic geometry is knowable from the spec alone: the fit
        # check runs BEFORE any graph work — the whole point (a
        # device-built scale-25 graph IS the allocation being gated).
        geo = _parse_synthetic_geometry(args.synthetic)
        if geo is not None:
            _kind, n_syn, e_syn, scale_syn = geo
            _run_preflight(args, n_syn, e_syn, scale_syn,
                           device_build=args.device_build)
    t0 = time.perf_counter()
    try:
        if job is not None:
            graph, ids = _job_load_graph(args, job, drain)
        else:
            with obs.span("ingest/load",
                          input=args.input or args.synthetic):
                graph, ids = load_graph(args)
    except ValueError as e:
        # e.g. "empty graph: no vertices" (host build_graph and the
        # device-build guard alike) — a clean CLI error, not a
        # traceback.
        raise SystemExit(str(e))
    t_load = time.perf_counter() - t0
    ctx["graph"] = graph
    # Stage-boundary drain point for EVERY run (job dirs have their own
    # post-commit checks): a first Ctrl-C during a long ingest exits at
    # its end instead of being silently deferred to the solve loop.
    drain.check("ingest")
    if args.preflight and not args.synthetic:
        # File inputs: the geometry exists only after the host parse;
        # the check still precedes the ENGINE build — the device-
        # allocation gate (solve residency; the host build already
        # happened, so the build-pipeline stages don't apply).
        _run_preflight(args, graph.n, graph.num_edges, None,
                       device_build=False)
    print(
        f"graph: {graph.n:,} vertices, {graph.num_edges:,} edges, "
        f"{int(graph.dangling_mask.sum()):,} dangling ({t_load:.2f}s load)",
        file=sys.stderr,
    )

    if args.ppr_sources:
        return run_ppr(args, graph, ids)

    from pagerank_tpu.utils.config import RobustnessConfig

    cfg = PageRankConfig(
        num_iters=args.iters,
        damping=args.damping,
        semantics=args.semantics,
        dtype=args.dtype,
        accum_dtype=args.accum_dtype or args.dtype,
        tol=args.tol,
        probe_every=args.probe_every,
        probe_topk=args.probe_topk,
        stop_tol=args.stop_tol,
        num_devices=args.num_devices,
        vertex_sharded=args.vertex_sharded,
        vs_bounded=args.vs_bounded,
        halo_exchange=args.halo_exchange,
        halo_head=args.halo_head,
        halo_async=args.halo_async,
        stale_max_lag=args.stale_max_lag,
        halo_async_min_gain=args.halo_async_min_gain,
        snapshot_dir=args.snapshot_dir,
        snapshot_every=args.snapshot_every,
        log_every=args.log_every,
        sdc_check_every=args.sdc_check_every,
        sdc_seed=args.sdc_seed,
        robustness=RobustnessConfig(
            health_checks=not args.no_health_checks,
            mass_tol=args.mass_tol,
            max_rollbacks=args.max_rollbacks,
            max_rescues=args.max_rescues,
            write_attempts=args.write_retries,
            on_write_failure=args.on_write_failure,
            sdc_redo_deadline_s=args.sdc_redo_deadline,
        ),
    )
    if args.lane_group is not None:
        cfg = cfg.replace(lane_group=args.lane_group)
    if args.partition_span:
        # Device builds resolved the span when packing the graph
        # (_device_build_graph); host builds resolve it here with the
        # SAME shared planner (an explicit span passes through, -1
        # resolves the engine's auto rule — possibly to 0/off).
        part = getattr(args, "_resolved_partition_span", None)
        if part is None:
            from pagerank_tpu.ops.device_build import plan_build

            _g, _s, part = plan_build(
                cfg, graph.n, lane_group=args.lane_group or 0,
                host=True, num_edges=graph.num_edges,
                partition_span=args.partition_span,
            )
        if part:
            cfg = cfg.replace(partition_span=part)
        elif args.partition_span > 0:
            # The planner refused an EXPLICIT span (unsupported mode
            # combo): surface the config error as a clean CLI error,
            # not a traceback.
            try:
                cfg = cfg.replace(
                    partition_span=args.partition_span
                ).validate()
            except ValueError as e:
                raise SystemExit(str(e))
    if args.stream_dtype:
        # Only the partitioned layout consumes the narrowed stream;
        # when the auto rule resolved the span to 0 (or no span was
        # requested), drop it LOUDLY instead of tripping validate
        # (bench.py's legs do the same).
        if cfg.partition_span:
            cfg = cfg.replace(stream_dtype=args.stream_dtype)
        else:
            print(
                "--stream-dtype needs the partitioned layout "
                "(--partition-span); running without the narrowed "
                "stream",
                file=sys.stderr,
            )
    cfg.validate()
    ctx["cfg"] = cfg
    # Resumable-job solve stage (ISSUE 12; jobs.py): a validated
    # final-ranks artifact from a completed prior solve satisfies the
    # stage outright — the engine is never built, so a job SIGKILL'd
    # AFTER the solve resumes straight to output.
    solve_fp = solve_hash = None
    solve_hit = None
    if job is not None:
        solve_fp = graph.fingerprint()
        solve_hash = jobs.solve_config_hash(cfg)
        # Scope the job's snapshots BY SOLVE CONFIG: the intra-stage
        # resume grain must obey the same key discipline as the stage
        # artifacts — a Snapshotter validates only graph fingerprint +
        # semantics, so without this a rerun with changed solve flags
        # (e.g. --damping) would warm-start the OLD config's
        # trajectory and serve its ranks verbatim. A reconfigured
        # rerun gets a fresh subdir and solves from r0; the prior
        # config's snapshots stay valid for ITS resumes.
        if args.snapshot_dir:
            args.snapshot_dir = fsio.join(args.snapshot_dir, solve_hash)
        solve_hit = job.load_stage_artifact(
            "solve",
            expect={"fingerprint": solve_fp, "solve_config": solve_hash},
        )
    if solve_hit is not None:
        from pagerank_tpu.utils.snapshot import SinkGuard

        ranks = solve_hit[0]["ranks"]
        job.skip("solve", iterations=solve_hit[1].get("iterations"))
        print(
            "solve stage satisfied by durable artifact "
            f"({solve_hit[1].get('iterations')} iteration(s) recorded)",
            file=sys.stderr,
        )
        engine = None
        ctx["engine"] = None
        metrics = None
        probes = None
        summary = {}
        guard = SinkGuard()
        ctx["guard"] = guard
        if args.graph_profile:
            _publish_graph_profile(args, cfg, graph, None, job)
    else:
        if job is not None:
            job.begin("solve")
        # Persisted SDC quarantine (ISSUE 15): a resumed job must
        # never re-adopt a chip a prior run convicted of sticky
        # corruption — the initial mesh already excludes the ids
        # recorded in job.json.
        quarantined = set(job.quarantined_devices()) if job is not None \
            else set()
        if quarantined and args.engine == "jax":
            from pagerank_tpu.engines.jax_engine import JaxTpuEngine
            from pagerank_tpu.parallel import mesh as mesh_lib

            try:
                # THE one spelling of "the mesh minus the casualty
                # list" — shared with ElasticRunner's rescue path.
                devs = mesh_lib.surviving_devices(sorted(quarantined))
            except RuntimeError as e:
                raise SystemExit(str(e))
            if cfg.num_devices:
                devs = devs[:cfg.num_devices]
            print(
                f"excluding quarantined device(s) "
                f"{sorted(quarantined)} (job manifest); building on "
                f"{len(devs)} device(s)",
                file=sys.stderr,
            )
            cfg = cfg.replace(num_devices=len(devs)).validate()
            ctx["cfg"] = cfg
            engine = JaxTpuEngine(cfg, devices=devs)
        else:
            engine = make_engine(args.engine, cfg)
        ctx["engine"] = engine
        if args.device_build:
            engine.build_device(graph)
        else:
            engine.build(graph)
        # A signal during the engine build/compile surfaces here, not
        # after a whole first iteration.
        drain.check("solve")
        if args.graph_profile:
            # Published BEFORE the solve so the live exporter carries
            # graph.* next to the solve gauges; prediction targets the
            # mesh this run actually built.
            _publish_graph_profile(args, cfg, graph, engine, job)

        # Engine indirection for the elastic path: a rescue REPLACES the
        # engine mid-run (teardown + rebuild over survivors), so every
        # closure below reaches the engine through this holder instead of
        # binding the original object.
        engine_ref = {"engine": engine}

        def _eng():
            return engine_ref["engine"]

        snap = None
        if args.snapshot_dir:
            # mesh_meta: topology + partition-geometry provenance in every
            # snapshot (mesh-shape-agnostic resume; docs/ROBUSTNESS.md
            # "Elastic solve").
            snap = Snapshotter(args.snapshot_dir, graph.fingerprint(),
                               cfg.semantics, mesh_meta=engine.snapshot_meta())
            if args.resume:
                try:
                    it = resume_engine(engine, snap)
                except ValueError as e:
                    # A job dir reused for a DIFFERENT graph: its old
                    # snapshots fail the fingerprint check. Under the
                    # supervisor that is the artifact-mismatch case —
                    # recompute from r0, never trust (explicit --resume
                    # without --job-dir still refuses loudly).
                    if job is None:
                        raise
                    warnings.warn(
                        f"job snapshots do not match this graph ({e}); "
                        "solving from r0", RuntimeWarning,
                    )
                    it = 0
                if it:
                    print(f"resumed from iteration {it}", file=sys.stderr)

        num_chips = 1
        if args.engine == "jax":
            num_chips = engine.mesh.devices.size
        metrics = MetricsLogger(
            graph.num_edges, num_chips, log_every=args.log_every, jsonl_path=args.jsonl
        )
        ctx["metrics"] = metrics
        if args.history:
            # Baseline-delta gauges for the live exporter (ISSUE 9): the
            # running solve publishes history.* % -vs-ledger-baseline.
            _arm_history_baseline(args.history, cfg, graph, num_chips)

        dumper = None
        if args.dump_text_dir:
            dumper = TextDumper(
                args.dump_text_dir, names=ids.names if ids is not None else None
            )

        # Async offload (C17 build target): the iteration loop submits a
        # device-side rank copy and keeps dispatching; a worker thread does
        # the device->host transfer + file writes. --sync-io restores the
        # reference-like per-iteration barrier; the cpu engine's ranks are
        # already host-side, so it stays synchronous.
        def write_sinks(i, payload):
            # THE single sink path — async and --sync-io runs must stay
            # byte-identical (tests/test_snapshot.py asserts it).
            want_snap, ranks = payload
            if want_snap:
                snap.save(i + 1, ranks)
            if dumper is not None:
                dumper.dump(i, ranks)

        # One write-failure policy for BOTH I/O modes (SinkGuard): bounded
        # retries, then fail or warn-and-drop with a dead-letter manifest
        # of the dropped iterations (docs/ROBUSTNESS.md).
        from pagerank_tpu.utils.snapshot import SinkGuard

        dead_letter = None
        if args.on_write_failure == "warn_and_drop":
            base = args.snapshot_dir or args.dump_text_dir
            if base:
                dead_letter = fsio.join(base, "dead_letter.json")
        guard = SinkGuard(
            retry_policy=cfg.robustness.write_retry_policy(),
            on_failure=args.on_write_failure,
            dead_letter_path=dead_letter,
        )
        ctx["guard"] = guard

        writer = None
        can_write = dumper is not None or (snap and args.snapshot_every)
        if can_write and args.engine == "jax" and not args.sync_io:
            from pagerank_tpu.utils.snapshot import AsyncRankWriter

            writer = AsyncRankWriter(
                lambda p: (p[0], _eng().decode_ranks(p[1])), [write_sinks],
                guard=guard,
            )

        # In-loop convergence probes (obs/probes.py; docs/OBSERVABILITY.md
        # "Convergence probes"). --probe-every 0 leaves this None and the
        # solve loop makes zero probe calls.
        probes = None
        if args.probe_every:
            probes = obs.ConvergenceProbes(
                args.probe_every, topk=args.probe_topk, stop_tol=args.stop_tol
            )
        ctx["probes"] = probes

        # Constructed (and argument-validated) BEFORE the exporter below
        # spawns its HTTP thread, so a bad --stall-timeout cannot leak a
        # live server; armed right before the solve.
        watchdog = None
        if args.stall_timeout:
            # Classification probes the SOLVE MESH's devices (tracking the
            # rebuilt engine after a rescue), not every visible chip — a
            # wedged device the solve never uses must not read as OUR loss.
            device_source = None
            if args.engine == "jax":
                def device_source():
                    return list(_eng().mesh.devices.reshape(-1))
            watchdog = obs.StallWatchdog(
                args.stall_timeout, action=args.stall_action,
                device_source=device_source,
            )

        # Device-plane sampler (obs/devices.py; ISSUE 10): armed ONLY on
        # explicit opt-in — engine.run reads it once per run, and the
        # disarmed hot loop makes zero sampler calls (the tracer
        # discipline). Run reports still embed a one-shot boundary sample
        # when disarmed (obs/report.build_run_report).
        if args.device_sample_every:
            # Sample the SOLVE MESH's devices (the watchdog's
            # device_source discipline): on a shared host the watermark
            # must not attribute a foreign job's HBM peak to this run.
            # Resolved per sweep — None (pre-build boundary samples, the
            # CPU engine) degrades to every visible device.
            sample_source = None
            if args.engine == "jax":
                def sample_source():
                    return list(_eng().mesh.devices.reshape(-1))
            obs.arm_sampler(obs.DeviceSampler(
                every=args.device_sample_every, devices=sample_source))

        # Live metrics exporter (obs/live.py): atomic Prometheus textfile
        # per iteration and/or an HTTP /metrics endpoint.
        exporter = None
        if args.metrics_textfile or args.metrics_port is not None:
            exporter = obs.MetricsExporter(
                textfile=args.metrics_textfile, port=args.metrics_port
            )
            if exporter.port is not None:
                print(
                    f"serving metrics on http://127.0.0.1:{exporter.port}"
                    f"/metrics",
                    file=sys.stderr,
                )

        def on_iteration(i, info):
            metrics(i, info)
            if exporter is not None:
                exporter.write_textfile()
            want_snap = bool(
                snap and args.snapshot_every and (i + 1) % args.snapshot_every == 0
            )
            if want_snap or dumper is not None:
                if writer is not None:
                    writer.submit(i, (want_snap, _eng().device_ranks()))
                else:
                    # one device->host fetch for both sinks
                    guard(i, lambda: write_sinks(i, (want_snap, _eng().ranks())))
            # Preemption points (ISSUE 12), AFTER this step's sinks were
            # queued: the seeded chaos plan may deliver its signal here
            # (job.tick), and a pending drain request surfaces here — the
            # in-flight step always finishes before the drain starts.
            if job is not None:
                job.tick("solve", i)
            drain.check("solve")

        # Stall watchdog (obs/live.py): armed around the solve only — the
        # engine heartbeats it per completed step (chunk boundaries when
        # fused); disarmed in the finally below on every exit path.
        if watchdog is not None:
            obs.arm_watchdog(watchdog)

        interrupted = None
        try:
            # Profiler lifecycle via obs.profiler_session: started here,
            # stopped on EVERY exit path (the trace of a failing run is
            # what the user wants to inspect), recorded as a 'profile'
            # span when tracing is on — replaces the hand-rolled
            # start/stop+finally this block used to carry.
            with obs.profiler_session(args.profile_dir):
                if args.fused:
                    import jax
                    import math

                    first = engine.iteration
                    # Chunk cadence: fused dispatches between the host-
                    # visible points — snapshot boundaries, probe points,
                    # or both (their gcd aligns every needed boundary on a
                    # chunk edge; off-cadence boundaries are skipped per
                    # consumer below).
                    snap_every = (
                        args.snapshot_every
                        if (snap is not None and args.snapshot_every) else 0
                    )
                    cadences = [c for c in (snap_every, args.probe_every) if c]
                    chunk_every = math.gcd(*cadences) if cadences else 0
                    if chunk_every and cadences and chunk_every < min(cadences):
                        # Neither cadence divides the other: the gcd can be
                        # far below both (coprime worst case: 1 — fully
                        # unfused dispatch). Warn rather than silently
                        # degrade the fused run.
                        print(
                            f"--snapshot-every {snap_every} and "
                            f"--probe-every {args.probe_every} share no "
                            f"cadence; fused chunks drop to gcd="
                            f"{chunk_every} iterations — align one to a "
                            f"multiple of the other to keep dispatches "
                            f"fused",
                            file=sys.stderr,
                        )
                    chunked = bool(chunk_every)
                    # compile outside the timed region
                    engine.prepare_fused(
                        tol=args.tol,
                        every=chunk_every if chunked else None,
                    )
                    t_run = time.perf_counter()
                    if chunked:
                        # Fused dispatches BETWEEN snapshot/probe points;
                        # snapshots at chunk boundaries ride the same async
                        # writer/sink path as the stepwise loop.
                        def on_chunk(done_iters, ranks_thunk, traces):
                            # Fused runs drain at chunk boundaries — the
                            # only host-visible points they have.
                            drain.check("solve")
                            # --stop-tol fires at PROBE boundaries only —
                            # returned truthy to stop the chunked run, so a
                            # snapshot-only boundary (both cadences set,
                            # gcd chunks) can never early-exit the solve
                            # the way the every-iteration --tol may.
                            stop = False
                            if (probes is not None
                                    and done_iters % args.probe_every == 0):
                                # The boundary's residual was already
                                # computed on device (the chunk traces).
                                rec = probes.probe_boundary(
                                    engine, done_iters - 1,
                                    l1_delta=float(
                                        jax.device_get(traces[0][-1])
                                    ),
                                )
                                stop = probes.should_stop(rec)
                            if exporter is not None:
                                exporter.write_textfile()
                            # Same absolute cadence as the stepwise loop: no
                            # snapshot at an off-cadence final-remainder
                            # boundary, so both modes write identical file
                            # sets. (The device-side rank copy is only made
                            # when the thunk is called — skipped boundaries
                            # cost nothing.)
                            if not snap_every or done_iters % snap_every != 0:
                                return stop
                            if writer is not None:
                                writer.submit(done_iters - 1,
                                              (True, ranks_thunk()))
                            else:
                                guard(
                                    done_iters - 1,
                                    lambda: write_sinks(
                                        done_iters - 1,
                                        (True,
                                         engine.decode_ranks(ranks_thunk())),
                                    ),
                                )
                            return stop

                        ranks = engine.run_fused_chunked(
                            every=chunk_every, on_chunk=on_chunk,
                            tol=args.tol,
                        )
                    elif args.tol is not None:
                        # On-device early stop: only the FINAL iteration's
                        # delta/mass exist (dynamic trip count).
                        ranks = engine.run_fused_tol(args.tol)
                    else:
                        ranks = engine.run_fused()
                    total = time.perf_counter() - t_run
                    tr = engine.last_run_metrics
                    deltas = np.asarray(jax.device_get(tr["l1_delta"]))
                    masses = np.asarray(jax.device_get(tr["dangling_mass"]))
                    done = engine.iteration - first
                    if tracer.enabled:
                        # One span for the fused dispatch window (per-step
                        # host spans don't exist here by design — the loop
                        # runs on device).
                        tracer.add_span("solve/fused", t_run, total,
                                        iters=done)
                    for i in range(len(deltas) if done else 0):
                        # one record per executed iteration, except the
                        # device-tol form which keeps only the final one.
                        it = first + (i if len(deltas) == done else done - 1)
                        metrics.record(
                            it,
                            {"l1_delta": deltas[i], "dangling_mass": masses[i]},
                            total / max(1, done),
                            timing="averaged",
                        )
                    fused_summary = dict(iters=done, total_seconds=total)
                else:
                    # snap doubles as the rollback source for the
                    # self-healing loop (unhealthy steps restore the newest
                    # valid snapshot and recompute — engine.run;
                    # docs/ROBUSTNESS.md). With the async writer active,
                    # rollback scans must drain its queue first or they
                    # race the snapshots still in flight.
                    roll_snap = snap
                    if snap is not None and writer is not None:
                        from pagerank_tpu.utils.snapshot import (
                            WriterSyncedSnapshotter)

                        roll_snap = WriterSyncedSnapshotter(snap, writer)
                    if args.stall_action == "rescue":
                        # Elastic solve (docs/ROBUSTNESS.md "Elastic
                        # solve"): device losses — injected, backend
                        # runtime errors confirmed by liveness probes, or
                        # watchdog fires classified as device-lost — tear
                        # down the mesh, rebuild over survivors, re-shard
                        # the graph, and warm-start from the newest valid
                        # snapshot.
                        from pagerank_tpu.engines.jax_engine import (
                            JaxTpuEngine)
                        from pagerank_tpu.parallel.elastic import (
                            DeviceHealthMonitor, ElasticRunner)

                        def _factory(devs):
                            e = JaxTpuEngine(
                                cfg.replace(num_devices=len(devs)),
                                devices=devs,
                            )
                            return e.build(graph)

                        runner_ref = {}

                        def _rebound(e):
                            engine_ref["engine"] = e
                            ctx["engine"] = e
                            if snap is not None:
                                meta = e.snapshot_meta()
                                q = runner_ref.get("runner")
                                if q is not None and \
                                        q.quarantined_device_ids:
                                    meta["quarantined_devices"] = \
                                        sorted(q.quarantined_device_ids)
                                snap.mesh_meta = meta

                        # Conviction persistence rides the sdc
                        # quarantine hook (set at job creation above)
                        # — it fires AT conviction time, before the
                        # rescue even starts, so no on_quarantine
                        # callback is needed here.
                        runner = ElasticRunner(
                            engine, _factory, snapshotter=roll_snap,
                            max_rescues=cfg.robustness.rescue_budget(),
                            monitor=DeviceHealthMonitor(
                                straggler_factor=(
                                    cfg.robustness.straggler_factor),
                            ),
                            on_rebuild=_rebound,
                            exclude_device_ids=sorted(quarantined),
                        )
                        runner_ref["runner"] = runner
                        ranks = runner.run(on_iteration=on_iteration,
                                           probes=probes)
                        engine = engine_ref["engine"]
                    else:
                        ranks = engine.run(on_iteration=on_iteration,
                                           snapshotter=roll_snap,
                                           probes=probes)
        except jobs.DrainInterrupt as di:
            # Graceful preemption (ISSUE 12): the in-flight step finished;
            # the finally below flushes the writer under the drain
            # deadline, then the epilogue writes a final snapshot and
            # re-raises for main()'s interrupted-exit path.
            interrupted = di
        finally:
            # Capture BEFORE any nested try: inside an except handler,
            # sys.exc_info() would report the just-caught close() error.
            # (Failure-path observability export happens in main()'s
            # wrapper — _export_failure — so ingest/build/resume/--out
            # failures are covered too, not just this block's.)
            propagating = sys.exc_info()[0] is not None
            obs.disarm_watchdog()
            if writer is not None:
                try:
                    # A drain bounds the flush by its deadline — a hanging
                    # sink is abandoned (warned + counted); a FAILING sink
                    # still drains normally under the SinkGuard policy
                    # (dead_letter.json, never a hang).
                    writer.close(
                        timeout=drain.remaining() if drain.requested
                        else None
                    )  # flush pending writes; surface failures
                except Exception:
                    if not propagating:
                        raise
                    # an engine error is already propagating; don't mask it
            if exporter is not None:
                try:
                    exporter.close()  # final textfile flush + HTTP teardown
                except Exception:
                    if not propagating:
                        raise
        if interrupted is not None:
            # Final snapshot of the drained state (the last completed
            # step), manifest bookkeeping, then surface the interrupt —
            # _interrupted_exit writes the interrupted-marked report.
            if snap is not None:
                try:
                    # _eng() for the ITERATION too: after an elastic
                    # rescue the local `engine` is the stale pre-rescue
                    # object — labeling the rebuilt engine's ranks with
                    # its old iteration would mislabel (and possibly
                    # clobber) a genuine snapshot.
                    guard(_eng().iteration,
                          lambda: snap.save(_eng().iteration,
                                            _eng().ranks()))
                except Exception as e:
                    print(f"pagerank_tpu: final drain snapshot failed: "
                          f"{e!r}", file=sys.stderr)
            if job is not None:
                job.interrupt("solve", iteration=int(_eng().iteration),
                              signal=interrupted.signum)
            raise interrupted
        # Fused runs know the true iteration count and wall-clock directly
        # (the tol form records only the final iteration).
        summary = metrics.summary(**fused_summary) if args.fused else metrics.summary()
        metrics.close()
        if job is not None:
            # Durable solve artifact: the decoded final ranks, keyed by
            # graph fingerprint + solve-config hash — a later restart
            # skips straight to the output stage.
            job.save_stage_artifact(
                "solve", {"ranks": np.asarray(ranks)},
                {"fingerprint": solve_fp, "solve_config": solve_hash,
                 "iterations": int(engine.iteration)},
            )
            job.complete("solve", iterations=int(engine.iteration))
            drain.check("solve")
    if summary:
        # The rate fields are null (not inf) on a degenerate zero
        # wall-clock (utils/metrics.py) — skip them rather than format
        # None.
        eps = summary["edges_per_sec_per_chip"]
        print(
            f"done: {summary['iters']} iters, "
            f"{summary['mean_iter_seconds'] * 1e3:.2f} ms/iter"
            + (f", {eps:.4g} edges/s/chip" if eps is not None else ""),
            file=sys.stderr,
        )
    # Robustness summary (docs/ROBUSTNESS.md): rollback/retry/drop
    # counts, plus transparent S3 request retries for any object-store
    # outputs. Printed only when something is worth reporting.
    rb_summary = _robustness_summary(args, engine, guard)
    rollbacks = rb_summary["rollbacks"]
    rescues = rb_summary["rescues"]
    io_retries = rb_summary["s3_request_retries"]
    sdc_detected = rb_summary["sdc_flips_detected"]
    if (rollbacks or rescues or guard.retries or guard.dropped
            or io_retries or sdc_detected):
        parts = [f"{rollbacks} rollback(s)", f"{guard.retries} write retr(y/ies)"]
        if rescues:
            parts.append(
                f"{rescues} elastic rescue(s) "
                f"({rb_summary['devices_lost']} device(s) lost)"
            )
        if sdc_detected:
            parts.append(
                f"{sdc_detected} SDC breach(es) "
                f"({rb_summary['sdc_transient_flips']} transient, "
                f"{rb_summary['sdc_quarantined_devices']} "
                f"quarantined chip(s))"
            )
        if io_retries:
            parts.append(f"{io_retries} s3 request retr(y/ies)")
        if guard.dropped:
            parts.append(
                f"{len(guard.dropped)} DROPPED write(s) "
                f"(iterations {[d['iteration'] for d in guard.dropped]}"
                + (f", manifest {dead_letter}" if dead_letter else "")
                + ")"
            )
        print("robustness: " + ", ".join(parts), file=sys.stderr)

    # Flight recorder + trace export (docs/OBSERVABILITY.md): ONE
    # artifact that explains the run — env fingerprint, resolved
    # config, span summary, metrics snapshot, per-iteration history,
    # cost model, robustness counters. Diff two with
    # `python -m pagerank_tpu.obs report A.json B.json`.
    if args.dump_hlo and args.engine == "jax" and engine is not None:
        # Compiler plane (ISSUE 11; obs/hlo.py): harvest the step
        # program(s)' optimized-HLO lowering reports (arming the
        # inspector around ONE cost_reports pass — same compiled
        # handles, zero extra compiles; this also fills the cost
        # ledger, so the cost_reports call below is a ledger hit) and
        # dump the raw modules for offline diffing. The classified
        # reports ride the run report's `lowering` section and the
        # --history RunRecord's lowering fingerprint. After the solve
        # by design: a lowering harvest must never sit on the hot path.
        try:
            reports = engine.lowering_reports()
            written = obs.hlo.dump_texts(args.dump_hlo)
            whole = reports.get("step") or reports.get("final")
            verdict = ((whole.get("gather") or {}).get("strategy")
                       if whole else None)
            print(
                f"dumped {len(written)} optimized-HLO module(s) to "
                f"{args.dump_hlo}"
                + (f"; gather lowering: {verdict}" if verdict else ""),
                file=sys.stderr,
            )
        except Exception as e:  # telemetry must not fail the solve
            print(f"pagerank_tpu: HLO dump failed ({e!r})",
                  file=sys.stderr)
    if ((args.run_report or args.history) and args.engine == "jax"
            and engine is not None):
        # Fill the cost ledger with the step program's XLA cost model
        # (the fused executables harvested at their compile already);
        # best-effort by contract — cost_reports never raises. The
        # perf-history record needs it too: bytes/edge is the ledger's
        # program-change attribution axis.
        engine.cost_reports()

    # Output stage BEFORE the observability export: the run report's
    # ``job`` section must record the COMPLETED manifest (status,
    # output wall, final skip set) — smoke R and `obs report` read
    # job.resumes/status off the report, not the manifest file.
    if job is not None:
        job.begin("output")
    if args.out:
        names = ids.names if ids is not None else None
        if args.top > 0:
            # Deterministic total order (rank desc, id asc) BEFORE the
            # cut, so boundary ties select by id too — PageRank ties
            # are routine (every zero-in vertex shares a rank). A full
            # lexsort is O(n log n) but host-side and once per run.
            k = min(args.top, len(ranks))
            order = np.lexsort((np.arange(len(ranks)), -ranks))[:k]
        else:
            order = range(len(ranks))
        with fsio.fopen(args.out, "w") as f:
            for i in order:
                key = names[i] if names else i
                f.write(f"{key}\t{float(ranks[i])!r}\n")
        print(f"wrote {len(order):,} ranks to {args.out}", file=sys.stderr)
    if job is not None:
        job.complete("output", out=args.out)
        job.finish()

    report = _export_observability(args, tracer, cfg, graph, metrics,
                                   summary=summary,
                                   robustness=rb_summary,
                                   probes=probes, job=job)
    if args.history:
        # Durable half of --history: this run's canonical RunRecord
        # appended to the perf ledger (content-hash deduped; reuses
        # the --run-report build when both flags are set).
        _append_history_record(args, cfg, graph, summary, rb_summary,
                               tracer, report=report)

    if job is not None:
        skipped = [s for s, r in job.manifest["stages"].items()
                   if r.get("skipped")]
        print(
            f"job complete in {args.job_dir} "
            f"(resume #{job.manifest['resumes']}, "
            f"{len(skipped)} stage(s) satisfied by durable artifacts"
            + (f": {', '.join(skipped)}" if skipped else "") + ")",
            file=sys.stderr,
        )
    return int(ExitCode.OK)


if __name__ == "__main__":
    sys.exit(main())
