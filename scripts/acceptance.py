"""Standing acceptance runs — BASELINE.md configs 2/3 stand-ins.

Config 2 (web-Google, 875K nodes / 5.1M edges, 20 iters, single chip)
and config 3 (soc-LiveJournal1, 4.8M nodes / 69M edges, 30 iters) gate
on ranks within 1e-6 L1 of the oracle. The SNAP datasets are not
fetchable here (zero egress), so the stand-ins are R-MAT graphs of the
same order run in the ACCURACY-GRADE TPU config (pair-f64: f64 rank
storage + pair-packed f64 accumulation — BASELINE.md "Accuracy
configs"; oracle-exact to ~3e-14 at 50 reference-semantics iterations,
vs 1.6e-7 for f32-storage+pair and 1.6e-6 for plain f32) and diffed
against the float64 CPU oracle on the same graph:

  A (config-2 stand-in): scale-20 R-MAT (1.05M vertices), 20 iters
  B (config-3 stand-in): scale-23 R-MAT (8.4M vertices),  30 iters
  C (config-4 PER-CHIP stand-in, not run by default — pass --only C):
    scale-24 R-MAT (16.8M vertices / 263M edges), 50 iters — the edge
    count one chip of config 4's v4-8 holds of Twitter-2010
    (1.47B/8 ~= 184M), at the reference's full 50-iteration count

Gate: BOTH the raw normalized L1 and the mass-normalized L1 must be
<= 1e-6 (since the f64-vdot lowering fix — PERF_NOTES "Reference-mode
mass growth and the f64-vdot lowering bug" — the pair-f64 config holds
~1e-13-grade agreement even at the full 50 reference iterations, so
the raw gate binds everywhere; the two columns diverging again would
signal a regression of the global-scale class). Each run appends a row
to BASELINE.md's "Acceptance runs" table (use --no-append to skip).

Usage:
  PYTHONPATH=. python scripts/acceptance.py [--only A|B|C] [--no-append]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GATE = 1e-6

CONFIGS = {
    "A": dict(scale=20, iters=20, label="config-2 stand-in (web-Google class)"),
    "B": dict(scale=23, iters=30, label="config-3 stand-in (LiveJournal class)"),
    # Not in the default set (the ~15-minute host build + oracle pass
    # makes it a deliberate run): the per-chip share of config 4.
    "C": dict(scale=24, iters=50,
              label="config-4 per-chip stand-in (Twitter class, 50 iters)"),
}
DEFAULT_KEYS = ["A", "B"]


def run_one(key: str):
    from pagerank_tpu import (JaxTpuEngine, PageRankConfig,
                              ReferenceCpuEngine, build_graph)
    from pagerank_tpu.utils.synth import rmat_edges

    spec = CONFIGS[key]
    scale, iters = spec["scale"], spec["iters"]
    t0 = time.perf_counter()
    src, dst = rmat_edges(scale, 16, seed=11)
    g = build_graph(src, dst, n=1 << scale)
    t_build = time.perf_counter() - t0
    print(f"[{key}] graph: scale {scale}: {g.n:,} vertices, "
          f"{g.num_edges:,} edges ({t_build:.1f}s host build)",
          file=sys.stderr)

    cfg_pair = PageRankConfig(
        num_iters=iters, dtype="float64", accum_dtype="float64",
        wide_accum="pair",
    )
    t0 = time.perf_counter()
    eng = JaxTpuEngine(cfg_pair).build(g)
    t_dev_build = time.perf_counter() - t0
    # Compile outside the timed window, then restore the initial state
    # (reference semantics: rank 1.0 per vertex, Sparky.java:168). The
    # timed window covers steps + the honest scalar fence ONLY (bench.py
    # pattern) — the full rank decode/D2H happens after, so it doesn't
    # deflate the rate column.
    eng.step()
    eng.fence()
    eng.set_ranks(np.full(g.n, 1.0), iteration=0)
    chips = eng.mesh.devices.size
    t0 = time.perf_counter()
    for _ in range(iters):
        eng._device_step()
    eng.fence()
    t_run = time.perf_counter() - t0
    r_tpu = eng.ranks()

    t0 = time.perf_counter()
    cfg_oracle = PageRankConfig(num_iters=iters, dtype="float64",
                                accum_dtype="float64")
    r_cpu = ReferenceCpuEngine(cfg_oracle).build(g).run()
    t_oracle = time.perf_counter() - t0

    from pagerank_tpu.utils.metrics import oracle_l1

    _, norm, mass_norm = oracle_l1(r_tpu, r_cpu)
    rate = g.num_edges * iters / t_run / chips
    rec = {
        "config": key,
        "label": spec["label"],
        "scale": scale,
        "iters": iters,
        "num_edges": int(g.num_edges),
        "normalized_l1": norm,
        "mass_normalized_l1": mass_norm,
        "mass_growth": float(r_cpu.sum()) / g.n,
        "gate": GATE,
        "passed": bool(norm <= GATE and mass_norm <= GATE),
        "tpu_seconds": t_run,
        "edges_per_sec_per_chip": rate,
    }
    print(
        f"[{key}] {iters} iters in {t_run:.2f}s (device build "
        f"{t_dev_build:.1f}s, oracle {t_oracle:.1f}s): normalized L1 "
        f"{norm:.3e} (mass-normalized {mass_norm:.3e}) vs gate {GATE:g} "
        f"-> {'PASS' if rec['passed'] else 'FAIL'}; {rate:.3g} edges/s/chip",
        file=sys.stderr,
    )
    return rec


def append_baseline(recs) -> None:
    path = os.path.join(REPO, "BASELINE.md")
    with open(path) as f:
        text = f.read()
    header = "## Acceptance runs (configs 2-4 stand-ins)"
    if header not in text:
        text += (
            f"\n{header}\n\n"
            "Scripted by `scripts/acceptance.py`: accuracy-grade TPU "
            "config (pair-f64: f64 storage + pair accumulation) vs the "
            "f64 CPU oracle on the same R-MAT graph. Gate: BOTH raw "
            "normalized L1 and mass-normalized L1 <= 1e-6. One row "
            "appended per run.\n\n"
            "| Stand-in | Workload | Iters | Normalized L1 | "
            "Mass-normalized L1 | Gate | Result | edges/s/chip |\n"
            "|---|---|---|---|---|---|---|---|\n"
        )
    rows = "".join(
        f"| {r['label']} | R-MAT {r['scale']} ({r['num_edges']:,} edges) "
        f"| {r['iters']} | {r['normalized_l1']:.3e} | "
        f"{r['mass_normalized_l1']:.3e} | {r['gate']:g} | "
        f"{'PASS' if r['passed'] else 'FAIL'} | "
        f"{r['edges_per_sec_per_chip']:.3g} |\n"
        for r in recs
    )
    with open(path, "w") as f:
        f.write(text + rows)
    print(f"appended {len(recs)} row(s) to BASELINE.md", file=sys.stderr)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--only", choices=sorted(CONFIGS), default=None)
    p.add_argument("--no-append", action="store_true")
    args = p.parse_args(argv)

    from bench import _enable_compile_cache

    _enable_compile_cache()
    keys = [args.only] if args.only else DEFAULT_KEYS
    recs = [run_one(k) for k in keys]
    if not args.no_append:
        append_baseline(recs)
    print(json.dumps(recs))
    return 0 if all(r["passed"] for r in recs) else 1


if __name__ == "__main__":
    sys.exit(main())
