"""Standing acceptance runs — BASELINE.md configs 2/3/5 + textbook stand-ins.

Config 2 (web-Google, 875K nodes / 5.1M edges, 20 iters, single chip)
and config 3 (soc-LiveJournal1, 4.8M nodes / 69M edges, 30 iters) gate
on ranks within 1e-6 L1 of the oracle. The SNAP datasets are not
fetchable here (zero egress), so the stand-ins are R-MAT graphs of the
same order run in the ACCURACY-GRADE TPU config (pair-f64: f64 rank
storage + pair-packed f64 accumulation — BASELINE.md "Accuracy
configs"; oracle-exact to ~3e-14 at 50 reference-semantics iterations,
vs 1.6e-7 for f32-storage+pair and 1.6e-6 for plain f32) and diffed
against the float64 CPU oracle on the same graph:

  A (config-2 stand-in): scale-20 R-MAT (1.05M vertices), 20 iters
  B (config-3 stand-in): scale-23 R-MAT (8.4M vertices),  30 iters
  C (config-4 PER-CHIP stand-in, not run by default — pass --only C):
    scale-24 R-MAT (16.8M vertices / 263M edges), 50 iters — the edge
    count one chip of config 4's v4-8 holds of Twitter-2010
    (1.47B/8 ~= 184M), at the reference's full 50-iteration count

Gate: BOTH the raw normalized L1 and the mass-normalized L1 must be
<= 1e-6 (since the f64-vdot lowering fix — PERF_NOTES "Reference-mode
mass growth and the f64-vdot lowering bug" — the pair-f64 config holds
~1e-13-grade agreement even at the full 50 reference iterations, so
the raw gate binds everywhere; the two columns diverging again would
signal a regression of the global-scale class). Each run appends a row
to BASELINE.md's "Acceptance runs" table (use --no-append to skip).

Beyond A/B/C (reference-semantics pair-f64 vs the f64 oracle), the
default set includes T — the TEXTBOOK-semantics mode under the same
oracle-diff gate (both modes are the behavioral contract, SURVEY §2a) —
and P, the config-5 PPR stand-in: device batched-SpMM (f32) vs the f64
oracle, gated on per-source top-k id overlap and top-k score L1.

E is the reference's LITERAL job end to end: a 301-file SequenceFile
segment of crawl metadata -> native C++ L1 -> host build -> pair-f64
jax engine, reference semantics, 10 iterations -> per-iteration
`PageRank{i}/` text dumps — gated on oracle L1 with the wall-clock
split (L1 / build / solve / L4) recorded in BASELINE.md (SURVEY
§3.1-3.2; VERDICT r3 weak #3).

Beyond those, the cheap smokes run FIRST in the default order: D
(build-stage breakdown), G (observability), H (live telemetry), K
(partition-centric layout: a windowed solve with --probe-every plus
the contract-sweep coverage assertion — ISSUE 6), L (elastic rescue:
an 8-fake-device chaos run with one injected device kill that must
finish on the surviving mesh and match the oracle — ISSUE 7), M
(sparse boundary exchange: an 8-fake-device halo solve gated on
oracle parity AND measured exchanged bytes below the dense model —
ISSUE 8), N (perf sentry: a fresh bench result through the history
ledger + the noise-aware CI gate, regression-vs-drift attribution —
ISSUE 9), O (device plane: an 8-fake-device ATTRIBUTED halo solve —
comms-vs-compute attribution block, per-device sampler gauges, and
the OOM-preflight fit check passing at scale 14 while refusing an
absurd scale — ISSUE 10), Q (compiler plane: `obs hlo` over the
default + partitioned forms — a gather-strategy classification per
form, strict JSON, no EXPANDED verdict — ISSUE 11), S (data plane:
`obs graph` at scale 14 — strict JSON, the rank-mass ledger
reconciling at the f32 gate, predicted per-device skew within 10% of
the measured 8-fake-device edge counts — ISSUE 13), U (concurrency
plane: the PTR thread/signal-context race pass over the whole package
— zero unwaived findings, every thread root + the GracefulDrain
signal root discovered, <2 s — ISSUE 14), V (SDC plane: a seeded
sticky bit flip on 8 fake devices must be detected by the ABFT
invariants, localized to the injected device, quarantined through the
elastic rescue, and the solve must finish on 7 devices at the f32
oracle gate — ISSUE 15), F (fault injection).

Usage:
  PYTHONPATH=. python scripts/acceptance.py [--only <KEY>] [--no-append]
"""

import argparse
import json
import os
import signal
import sys
import time
import warnings

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GATE = 1e-6

CONFIGS = {
    "A": dict(scale=20, iters=20, label="config-2 stand-in (web-Google class)"),
    "B": dict(scale=23, iters=30, label="config-3 stand-in (LiveJournal class)"),
    # Not in the default set (the ~15-minute host build + oracle pass
    # makes it a deliberate run): the per-chip share of config 4.
    "C": dict(scale=24, iters=50,
              label="config-4 per-chip stand-in (Twitter class, 50 iters)"),
    # Textbook semantics (SURVEY §2a: BOTH modes are the behavioral
    # contract; the non-reference mode needs its own standing gate
    # against drift — VERDICT r2 #7). Same scale/iteration class as A.
    "T": dict(scale=20, iters=50, semantics="textbook",
              label="textbook-mode stand-in (scale-20, 50 iters)"),
    # Config 5 (PPR): mid-scale batched-SpMM run gated on oracle top-k
    # overlap + score L1 (VERDICT r2 #6).
    "P": dict(scale=20, iters=20, sources=256, topk=100, kind="ppr",
              label="config-5 stand-in (PPR, 256 sources)"),
    # Vertex-sharded variants on the real chip (VERDICT r4 #3): the
    # 1e-6 oracle gate through the psum+slice f64 merge (BV/TV) and
    # the r5 dst-partitioned bounded mode (BB). CV is the config-4
    # class, opt-in like C.
    "BV": dict(scale=23, iters=30, vertex_sharded=True,
               label="config-3 stand-in, VERTEX-SHARDED (psum+slice merge)"),
    "BB": dict(scale=23, iters=30, vertex_sharded=True, vs_bounded=True,
               label="config-3 stand-in, VS-BOUNDED (dst-partitioned)"),
    "TV": dict(scale=20, iters=50, semantics="textbook",
               vertex_sharded=True,
               label="textbook-mode stand-in, VERTEX-SHARDED"),
    "CV": dict(scale=24, iters=50, vertex_sharded=True,
               label="config-4 per-chip stand-in, VERTEX-SHARDED"),
    # The reference's LITERAL job, end to end (VERDICT r3 weak #3): a
    # multi-file SequenceFile segment of crawl metadata (301 files,
    # the reference's metadata-%05d naming, Sparky.java:44-58) ->
    # native C++ L1 -> host graph build with the post-repair dangling
    # semantics -> pair-f64 jax engine, reference semantics, 10
    # iterations (Sparky.java:187) -> per-iteration PageRank{i}/ text
    # dumps (Sparky.java:237) — gated on oracle L1 AND recording the
    # wall-clock split (L1 / build / solve / L4) in BASELINE.md.
    "E": dict(kind="e2e", files=301, records=1000, iters=10,
              label="reference-job end-to-end (301-file segment)"),
    # Fault-injection smoke (ISSUE 3): a seeded chaos run at small
    # scale (<30 s) — per-iteration snapshots through a deterministic
    # FaultInjectingFileSystem, a mid-run NaN poisoning + snapshot
    # corruption healed by rollback, gated on oracle ranks AND on the
    # same seed reproducing the same fault schedule bit-for-bit across
    # two runs (docs/ROBUSTNESS.md). Early in the default order: it is
    # cheap and the robustness layer underpins every snapshotting run.
    "F": dict(kind="faults", seed=23, iters=12,
              label="fault-injection smoke (seeded chaos, rollback+retry)"),
    # Build-pipeline smoke (ISSUE 2): a scale-18 pair-f64 device build
    # through bench.run_build — gates that the per-stage breakdown
    # keys exist and build_s stays under the recorded budget, with the
    # AST lint run over ops/ (the build chain's own modules) in the
    # same gate. First in the default order: it is the cheapest gate
    # and a broken build pipeline fails everything after it anyway.
    "D": dict(kind="build", scale=18,
              label="build-stage smoke (scale-18 pair-f64 device build)"),
    # Observability smoke (ISSUE 4): a tiny traced CLI run that must
    # produce a complete run_report.json (every REPORT_KEYS section,
    # env fingerprint, per-iteration history) and a parseable Chrome
    # trace, in under OBS_SMOKE_BUDGET_S. Right after D: sub-second,
    # and every other gate's artifacts lean on this layer.
    "G": dict(kind="obs", iters=4,
              label="observability smoke (traced run + flight recorder)"),
    # Live-telemetry smoke (ISSUE 5): a probed CPU run with the
    # Prometheus textfile exporter and the stall watchdog armed —
    # probe history in the run report at the exact cadence, a
    # strict text-format parse of the exporter output, and watchdog
    # non-fire. Right after G: same sub-second class, and the live
    # layer is what a wedged long run is diagnosed with.
    "H": dict(kind="live", iters=6, probe_every=2,
              label="live-telemetry smoke (probes + exporter + watchdog)"),
    # Partition-centric smoke (ISSUE 6): a short jax-engine solve on
    # the partitioned layout with --probe-every through the CLI —
    # probe records at the exact cadence — plus the dispatch-form
    # coverage assertion: the contract sweep must carry the
    # partitioned forms (PTC001/002/005/007 run against them in
    # tier-1 and, when this process is on the CPU backend, right
    # here).
    "K": dict(kind="partitioned", iters=6, probe_every=2, span=512,
              label="partition-centric smoke (windowed solve + contracts)"),
    # Elastic-rescue smoke (ISSUE 7): an 8-fake-device chaos run with
    # one seed-deterministic device kill mid-solve — the solve must
    # FINISH on the surviving mesh (teardown -> re-shard -> warm-start
    # from the newest snapshot), final ranks must match the f64 CPU
    # oracle at the standing f32 tolerance, and the run report must
    # carry the elastic/rescue span + elastic.* counters. Runs
    # in-process on a CPU backend with >= 2 devices; otherwise
    # re-invokes itself in a subprocess with the fake-device flags.
    "L": dict(kind="elastic", iters=12, kill_iter=6, kill_device=2,
              seed=5,
              label="elastic-rescue smoke (8-fake-device chaos, "
                    "one device kill)"),
    # Sparse-boundary-exchange smoke (ISSUE 8): an 8-fake-device
    # vertex-sharded solve through the halo exchange at small R-MAT
    # scale — the step must run the vs_halo form, final ranks must
    # match the f64 CPU oracle at the standing f32 gate, and the
    # MEASURED per-iteration exchanged bytes (the static model the
    # comms.bytes_exchanged counter accumulates) must be strictly
    # below the dense all_gather+reduce-scatter model's. Runs
    # in-process on a multi-device CPU backend, else re-invokes
    # itself in a subprocess like L.
    "M": dict(kind="halo", scale=12, iters=12,
              label="sparse-exchange smoke (8-fake-device halo solve)"),
    # Perf-sentry smoke (ISSUE 9; obs/history.py): a fresh scale-14
    # bench result is ingested into a TEMP COPY of the checked-in
    # ledger via `bench.py --history`; `obs history gate` against the
    # checked-in perf_budgets.json must PASS in under
    # HISTORY_GATE_BUDGET_S. Then, on a baseline built from the fresh
    # record, an env-fingerprint-only drift (wall moved, cost model
    # flat, jax version bumped) must exit 0 WITH a drift warning,
    # while an injected regression (wall + cost model moved) must
    # exit nonzero classified program-change — the two failure modes
    # the r5 incident could only separate by hand.
    "N": dict(kind="history", scale=14, iters=3,
              label="perf-sentry smoke (ledger ingest + noise-aware "
                    "gate)"),
    # Device-plane smoke (ISSUE 10; obs/devices.py): an 8-fake-device
    # ATTRIBUTED halo solve — the comms-vs-compute attribution block
    # must be present and self-consistent, the per-device sampler
    # gauges must be registered and the exporter output must
    # strict-parse, and the OOM-preflight fit check must PASS at
    # scale 14 and FAIL (exit-style verdict) at an absurd scale — the
    # instrument panel the next TPU session reads first.
    "O": dict(kind="devices", scale=12, iters=8, fit_ok_scale=14,
              fit_bad_scale=26,
              label="device-plane smoke (attributed multichip + "
                    "sampler + fit check)"),
    # Compiler-plane smoke (ISSUE 11; obs/hlo.py) — key Q because P
    # was already the config-5 PPR stand-in: `obs hlo` over the
    # default + partitioned dispatch forms at scale 14 must emit a
    # gather-strategy classification for EACH form as strict JSON,
    # exit 0 (no form classifies EXPANDED — the fast-gather-defeated
    # signature the instrument exists to catch), and come in under
    # HLO_SMOKE_BUDGET_S — the verdict a TPU session reads BEFORE
    # spending chip time.
    "Q": dict(kind="hlo", scale=14, forms="default,partitioned",
              label="compiler-plane smoke (optimized-HLO gather "
                    "verdict, default + partitioned)"),
    # Preemption smoke (ISSUE 12; pagerank_tpu/jobs.py): a resumable
    # job is SIGTERM'd mid-solve by a seeded ProcessKillPlan — the
    # graceful drain must exit INTERRUPTED (75) with the manifest
    # marked interrupted, and a second invocation against the same
    # --job-dir must RESUME (skip the graph stages, warm-start the
    # solve) and complete with oracle-parity ranks, `job.resumes == 1`
    # in the run report, under R_SMOKE_BUDGET_S — the preemptible-VM
    # lifecycle the TPU measurement campaign will actually run on.
    "R": dict(kind="jobs", scale=10, iters=12, kill_iter=6,
              label="preemption smoke (SIGTERM drain + job-dir "
                    "resume)"),
    # Data-plane smoke (ISSUE 13; obs/graph_profile.py): a profiled
    # scale-14 run through `obs graph` — strict-JSON parse, the
    # rank-mass ledger reconciling at the f32 gate over every probed
    # iteration, and the predicted per-device straggler skew agreeing
    # with the MEASURED per-device edge counts on the 8-fake-device
    # mesh within 10% — the predict-before-you-burn-a-TPU-session
    # instrument, gated end to end.
    "S": dict(kind="graph", scale=14, ndev=8, iters=3,
              label="data-plane smoke (graph profile + mass ledger + "
                    "skew prediction)"),
    # Concurrency-plane smoke (ISSUE 14; analysis/concurrency.py): the
    # PTR thread/signal-context race pass over the whole package —
    # zero unwaived findings, every known thread root discovered with
    # its label (rank-writer, watchdog, metrics HTTP, deadline
    # dispatch, liveness probes) plus the GracefulDrain signal root,
    # in under CONCURRENCY_SMOKE_BUDGET_S. Pure AST, no device work —
    # the same pass the --no-analysis pre-gate runs via --lint-only.
    "U": dict(kind="concurrency",
              label="concurrency-plane smoke (PTR race pass, zero "
                    "unwaived findings)"),
    # SDC smoke (ISSUE 15; pagerank_tpu/sdc.py): an 8-fake-device
    # solve with a seeded STICKY bit flip — the ABFT invariants must
    # detect the breach within the check cadence, localize it to the
    # injected device, convict it sticky across the bounded redo,
    # quarantine it through the elastic rescue path, and FINISH on 7
    # devices at the f32 oracle gate, with sdc.flips_detected /
    # sdc.quarantined_devices in the run report — under
    # SDC_SMOKE_BUDGET_S. Re-invokes itself in a subprocess with the
    # fake-device flags when this backend can't host the mesh (the
    # smoke-L protocol).
    "V": dict(kind="sdc", iters=12, flip_iter=5, flip_device=2,
              seed=11,
              label="sdc smoke (sticky bit-flip -> detect/localize/"
                    "quarantine on 8 fake devices)"),
    # Kernel-plane smoke (ISSUE 16; analysis/kernels.py): the PTK
    # static pass over the shipped Pallas kernel registry (toy + bench
    # scale 22-25 geometries) — zero unwaived findings against the
    # checked-in allowlist (the legacy whole-z entries waive as
    # documented), AND every seeded-defect fixture trips EXACTLY its
    # rule (a fixture that stops tripping means the rule went blind).
    # Pure tracing + numpy, no TPU, no execution.
    "W": dict(kind="kernels",
              label="kernel-plane smoke (PTK pass clean, every seeded "
                    "defect trips its rule)"),
    # Async stale-boundary smoke (ISSUE 17; config.halo_async): an
    # 8-fake-device solve through the DOUBLE-BUFFERED halo exchange —
    # the step must run the vs_halo_async form with the lag-1 buffer
    # on, the vs_halo_async contract sweep must come back clean
    # (PTC001 pins its collective multiset identical to vs_halo —
    # overlap reorders, never adds), final ranks must match the f64
    # CPU oracle at the standing f32 gate under TEXTBOOK semantics
    # (the contraction guarantees the fixed point the lag-1 schedule
    # converges to; reference semantics has none to compare at), the
    # measured exchanged bytes must equal iters x the static model
    # (staleness moves WHEN boundary bytes arrive, never HOW MANY),
    # and the PTR race pass over the package must hold at zero
    # unwaived findings with the buffer-rotation host state in the
    # tree. Subprocess protocol as L/M/V when this backend can't host
    # the mesh.
    "X": dict(kind="halo_async", scale=12, iters=120,
              label="async-exchange smoke (8-fake-device stale-"
                    "boundary halo solve)"),
    "Y": dict(kind="serve", seed=7, queries=40, iters=5,
              kill_batch=3, kill_device=5, drain_at=34,
              label="serving smoke (8-fake-device query daemon under "
                    "chaos: kill + SIGTERM drain, bit-identical "
                    "replay)"),
    # Query-plane smoke (ISSUE 19): the Y chaos load re-run with the
    # query plane AND the tracer armed — determinism must survive
    # instrumentation (same trace_digest), the slow-query log must
    # schema-validate as strict JSONL, latency-bucket exemplars must
    # strict-parse in the OpenMetrics rendering, and a real SIGTERM
    # drain must leave a flight-recorder dump in the run report's
    # serving section.
    "Z": dict(kind="qtrace", seed=7, queries=40, iters=5,
              kill_batch=3, kill_device=5, drain_at=34,
              label="query-plane smoke (armed tracing under chaos: "
                    "determinism, exemplars, slow-query log, flight "
                    "recorder)"),
    # Campaign-plane smoke (ISSUE 20; obs/campaign.py): the whole
    # measurement campaign, dry — one `campaign run --fake-devices 8`
    # subprocess at smoke scale must complete every leg inside its
    # per-leg wall budget, report.json must strict-parse as canonical
    # JSON, all five typed verdicts must be present and NON-binding
    # with decision "defer" (a CPU dry run never flips a TPU
    # decision), the decision ledger must render one entry per
    # verdict, and `campaign report` must re-render the identical
    # bytes — under CAMPAIGN_SMOKE_BUDGET_S.
    "AA": dict(kind="campaign",
               label="campaign-plane smoke (dry-run campaign on 8 "
                     "fake devices: all legs, 5 non-binding "
                     "verdicts, decision ledger)"),
}
DEFAULT_KEYS = ["D", "G", "H", "K", "L", "M", "X", "Y", "Z", "N", "O",
                "Q", "R", "S", "U", "V", "W", "F", "A", "B", "T", "P",
                "E", "BV", "BB", "TV", "AA"]

# Recorded budget for the scale-18 build smoke (seconds): the restaged
# single-sort pipeline builds this geometry in low single digits warm
# on v5e (and ~15s on the CPU test substrate); 60s absorbs a cold
# compile cache while still catching an order-of-magnitude build
# regression of the r5 class (74.8s at scale 23).
BUILD_SMOKE_BUDGET_S = 60.0

# Budget for the observability smoke (seconds): a 4-iteration cpu-engine
# run on a 400-vertex graph plus two JSON artifacts is tens of
# milliseconds; 2s absorbs a loaded host while still catching an
# accidentally-heavyweight tracer (the whole point of the no-op/cheap
# contract, docs/OBSERVABILITY.md).
OBS_SMOKE_BUDGET_S = 2.0

# Budget for the live-telemetry smoke (seconds): a 6-iteration probed
# cpu run + a textfile rewrite per iteration is tens of milliseconds;
# 2s catches an accidentally-heavyweight probe/exporter path — the
# zero-extra-host-syncs contract's wall-clock shadow (PTC007 checks
# the structural half).
LIVE_SMOKE_BUDGET_S = 2.0

# Budget for the campaign-plane smoke (seconds): the dry-run campaign
# executes all seven legs in one subprocess — measured ~48s warm /
# ~167s with a cold XLA compile cache on the CPU test substrate (the
# bench legs dominate). 240s absorbs the cold-cache case while still
# catching a campaign that hangs or re-runs legs it should resume.
CAMPAIGN_SMOKE_BUDGET_S = 240.0

# PPR gates. Top-k membership is judged against ORACLE SCORES, not id
# sets: vertices tied at the k-th score legitimately swap in/out of an
# id-based top-k (at toy scales the plain id overlap drops to 0.1 on
# pure ties while every score agrees to ~5e-8), so a device id is
# "acceptable" iff its oracle score reaches the oracle's k-th score
# within PPR_TIE_EPS (absolute; columns sum to 1, f32 device scores
# carry ~3e-7/element — tests/test_ppr.py). Score agreement is gated
# separately: worst per-source L1 over the rank-sorted top-k scores.
PPR_TIE_EPS = 1e-6
PPR_OVERLAP_GATE = 0.999
PPR_SCORE_L1_GATE = 1e-4


def _make_graph(key: str, scale: int):
    from pagerank_tpu import build_graph
    from pagerank_tpu.utils.synth import rmat_edges

    t0 = time.perf_counter()
    src, dst = rmat_edges(scale, 16, seed=11)
    g = build_graph(src, dst, n=1 << scale)
    t_build = time.perf_counter() - t0
    print(f"[{key}] graph: scale {scale}: {g.n:,} vertices, "
          f"{g.num_edges:,} edges ({t_build:.1f}s host build)",
          file=sys.stderr)
    return g


def run_build_smoke(key: str):
    """ISSUE-2 build gate: a scale-18 pair-f64 device build via
    bench.run_build — the per-stage breakdown keys must all exist, the
    build must land under the recorded budget, and the AST lint must be
    clean over ops/ (regressions in the 32-bit pin or the stage
    restage show up here before the minutes-long accuracy runs)."""
    import bench
    from pagerank_tpu.analysis.__main__ import main as analysis_main

    spec = CONFIGS[key]
    ops_dir = os.path.join(REPO, "pagerank_tpu", "ops")
    lint_ok = analysis_main(["--lint-only", ops_dir]) == 0
    if not lint_ok:
        print(f"[{key}] static analysis over ops/ FAILED (run "
              "`python -m pagerank_tpu.analysis pagerank_tpu/ops`)",
              file=sys.stderr)
    b = bench.run_build(spec["scale"], dtype="float64",
                        accum_dtype="float64", wide_accum="pair")
    missing = [k for k in bench.BUILD_STAGE_KEYS if k not in b["stages"]]
    passed = bool(lint_ok and not missing
                  and b["build_s"] <= BUILD_SMOKE_BUDGET_S)
    rec = {
        "config": key,
        "kind": "build",
        "label": spec["label"],
        "scale": spec["scale"],
        "build_s": b["build_s"],
        "stages": b["stages"],
        "missing_stage_keys": missing,
        "ops_lint_ok": lint_ok,
        "budget_s": BUILD_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] pair-f64 device build {b['build_s']:.1f}s vs budget "
        f"{BUILD_SMOKE_BUDGET_S:g}s; stage keys "
        f"{'complete' if not missing else 'MISSING ' + repr(missing)}; "
        f"ops lint {'OK' if lint_ok else 'FAILED'} -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


def run_fault_smoke(key: str):
    """ISSUE-3 robustness gate, in seconds not minutes: a full solve
    with per-iteration snapshots through a seeded fault-injecting
    filesystem (transient failures + truncated writes), a mid-run NaN
    poisoning plus snapshot-directory corruption healed by checksum-
    verified rollback — run TWICE with the same seed. Gates: final
    ranks match the f64 CPU oracle (atol 1e-6), at least one fault and
    one rollback actually happened, and the two runs' fault schedules
    (and ranks) are bit-for-bit identical."""
    import warnings

    from pagerank_tpu import (PageRankConfig, ReferenceCpuEngine,
                              build_graph)
    from pagerank_tpu.testing.faults import (FaultInjectingFileSystem,
                                             FaultSchedule)
    from pagerank_tpu.utils import fsio
    from pagerank_tpu.utils.retry import RetryPolicy
    from pagerank_tpu.utils.snapshot import SinkGuard, Snapshotter

    spec = CONFIGS[key]
    seed, iters = spec["seed"], spec["iters"]
    rng = np.random.default_rng(3)
    n, e = 1500, 12000
    src, dst = rng.integers(0, n, e), rng.integers(0, n, e)
    cfg = PageRankConfig(num_iters=iters, dtype="float64",
                         accum_dtype="float64")

    def chaos_run():
        g = build_graph(src, dst, n=n)
        inner = fsio.MemoryFileSystem()
        sched = FaultSchedule(seed=seed, fail_rate=0.08, truncate_rate=0.04,
                              max_faults=8)
        fsio.register("chaos", FaultInjectingFileSystem(
            inner, sched, sleep=lambda s: None))
        try:
            snap = Snapshotter("chaos://ck", g.fingerprint(), "reference")
            guard = SinkGuard(retry_policy=RetryPolicy(
                max_attempts=6, base_delay=0.0, seed=seed))
            eng = ReferenceCpuEngine(cfg).build(g)
            orig, state = eng.step, {"fired": False}

            def step():
                info = orig()
                if eng.iteration == iters // 2 and not state["fired"]:
                    state["fired"] = True
                    with fsio.fopen(snap.path(iters // 2), "wb") as f:
                        f.write(b"corrupted mid-run")
                    eng._r = eng._r * np.nan
                    return {k: float("nan") for k in info}
                return info

            eng.step = step
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                ranks = eng.run(
                    on_iteration=lambda i, info: guard(
                        i, lambda: snap.save(i + 1, eng.ranks())),
                    snapshotter=snap,
                )
            return ranks, list(sched.log), dict(eng.health), guard.retries
        finally:
            fsio.unregister("chaos")

    t0 = time.perf_counter()
    r1, log1, health1, retries1 = chaos_run()
    r2, log2, _, _ = chaos_run()
    oracle = ReferenceCpuEngine(cfg).build(build_graph(src, dst, n=n)).run()
    t_run = time.perf_counter() - t0
    l1 = float(np.abs(r1 - oracle).sum()) / float(np.abs(oracle).sum())
    faults = sum(1 for _, _, _, a in log1 if a != "-")
    passed = bool(
        log1 == log2
        and np.array_equal(r1, r2)
        and l1 <= GATE
        and faults > 0
        and health1["rollbacks"] >= 1
    )
    rec = {
        "config": key,
        "kind": "faults",
        "label": spec["label"],
        "seed": seed,
        "iters": iters,
        "faults_injected": faults,
        "write_retries": retries1,
        "rollbacks": health1["rollbacks"],
        "schedule_reproducible": bool(log1 == log2),
        "normalized_l1": l1,
        "gate": GATE,
        "seconds": t_run,
        "passed": passed,
    }
    print(
        f"[{key}] seed {seed}: {faults} fault(s) injected, {retries1} "
        f"write retr(y/ies), {health1['rollbacks']} rollback(s); schedule "
        f"{'reproducible' if rec['schedule_reproducible'] else 'DIVERGED'}; "
        f"oracle L1 {l1:.3e} vs gate {GATE:g} ({t_run:.1f}s) -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


def run_obs_smoke(key: str):
    """ISSUE-4 observability gate, in milliseconds not minutes: one
    traced CLI run (`--trace` + `--run-report`) on a tiny synthetic
    graph. Gates: the CLI exits 0, run_report.json carries EVERY
    schema section (obs/report.REPORT_KEYS) + the env fingerprint +
    one history record per iteration + a solve/step span per
    iteration, the Chrome trace parses as STRICT JSON with schema-
    complete events, and the whole thing lands under
    OBS_SMOKE_BUDGET_S."""
    import shutil
    import tempfile

    from pagerank_tpu.cli import main as cli_main
    from pagerank_tpu.obs.report import REPORT_KEYS

    spec = CONFIGS[key]
    iters = spec["iters"]
    work = tempfile.mkdtemp(prefix="pagerank_obs_")
    t0 = time.perf_counter()
    try:
        report_path = os.path.join(work, "run_report.json")
        trace_path = os.path.join(work, "trace.json")
        rc = cli_main([
            "--synthetic", "uniform:400:3000", "--engine", "cpu",
            "--iters", str(iters), "--log-every", "0",
            "--trace", trace_path, "--run-report", report_path,
        ])

        def strict(path):
            def no_const(name):
                raise ValueError(f"non-spec JSON constant {name!r}")

            with open(path) as f:
                return json.load(f, parse_constant=no_const)

        report = strict(report_path)
        trace_doc = strict(trace_path)
    finally:
        shutil.rmtree(work, ignore_errors=True)
    t_run = time.perf_counter() - t0

    missing = [k for k in REPORT_KEYS if k not in report]
    env_ok = all(
        k in report.get("environment", {})
        for k in ("jax_version", "backend", "device_kind", "x64", "git_rev")
    )
    steps = report.get("spans", {}).get("solve/step", {})
    events = trace_doc.get("traceEvents", [])
    trace_ok = bool(events) and all(
        "name" in e and e.get("ph") in ("X", "i") and "ts" in e
        and "pid" in e and "tid" in e
        and ("dur" in e if e.get("ph") == "X" else True)
        for e in events
    )
    passed = bool(
        rc == 0 and not missing and env_ok and trace_ok
        and steps.get("count") == iters
        and len(report.get("iterations", [])) == iters
        and t_run <= OBS_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "obs",
        "label": spec["label"],
        "iters": iters,
        "missing_report_keys": missing,
        "env_fingerprint_ok": env_ok,
        "trace_events": len(events),
        "trace_schema_ok": trace_ok,
        "seconds": t_run,
        "budget_s": OBS_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] traced run + flight recorder in {t_run:.2f}s vs budget "
        f"{OBS_SMOKE_BUDGET_S:g}s; report "
        f"{'complete' if not missing else 'MISSING ' + repr(missing)}; "
        f"env fingerprint {'OK' if env_ok else 'INCOMPLETE'}; "
        f"{len(events)} trace event(s) "
        f"{'schema-OK' if trace_ok else 'SCHEMA-BAD'} -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


_PROM_SAMPLE_RE = None


def _parse_prometheus_strict(text: str) -> int:
    """Line-by-line strict parse of Prometheus text exposition format;
    returns the sample count, raises AssertionError on any bad line
    (the exporter's syntax gate — tests/test_telemetry.py carries the
    same grammar)."""
    import re

    global _PROM_SAMPLE_RE
    if _PROM_SAMPLE_RE is None:
        _PROM_SAMPLE_RE = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
            r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
            r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
            r" (?:[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|Inf)|NaN)$"
        )
    samples = 0
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _PROM_SAMPLE_RE.match(line), f"bad exporter line: {line!r}"
        samples += 1
    return samples


def run_live_smoke(key: str):
    """ISSUE-5 live-telemetry gate: one probed CPU run through the CLI
    with `--metrics-textfile` and the stall watchdog armed. Gates: the
    CLI exits 0, the run report's probe history has one record per
    probe point with residual/mass/churn, those records also appear in
    the per-iteration history, the final textfile parses strictly as
    Prometheus text format and carries the probe counters, the
    watchdog never fired, and the whole thing lands under
    LIVE_SMOKE_BUDGET_S."""
    import shutil
    import tempfile

    from pagerank_tpu.cli import main as cli_main

    spec = CONFIGS[key]
    iters, every = spec["iters"], spec["probe_every"]
    work = tempfile.mkdtemp(prefix="pagerank_live_")
    t0 = time.perf_counter()
    try:
        report_path = os.path.join(work, "run_report.json")
        textfile = os.path.join(work, "metrics.prom")
        rc = cli_main([
            "--synthetic", "uniform:400:3000", "--engine", "cpu",
            "--iters", str(iters), "--log-every", "0",
            "--probe-every", str(every), "--probe-topk", "16",
            "--metrics-textfile", textfile,
            "--stall-timeout", "300",
            "--run-report", report_path,
        ])
        with open(report_path) as f:
            report = json.load(f)
        text = open(textfile).read()
    finally:
        shutil.rmtree(work, ignore_errors=True)
    t_run = time.perf_counter() - t0

    want_iters = [i for i in range(iters) if (i + 1) % every == 0]
    probes = report.get("probes") or []
    probes_ok = (
        [r.get("iteration") for r in probes] == want_iters
        and all(
            r.get("l1_residual") is not None
            and r.get("rank_mass") is not None
            and r.get("topk_churn") is not None
            for r in probes
        )
    )
    hist_probe_iters = [
        r["iter"] for r in report.get("iterations", [])
        if "rank_mass" in r
    ]
    history_ok = hist_probe_iters == want_iters
    try:
        samples = _parse_prometheus_strict(text)
        text_ok = (samples > 0
                   and f"pagerank_probe_points {len(want_iters)}" in text)
    except AssertionError as e:
        samples, text_ok = 0, False
        print(f"[{key}] {e}", file=sys.stderr)
    counters = (report.get("metrics") or {}).get("counters") or {}
    watchdog_quiet = counters.get("watchdog.stalls", 0) == 0
    passed = bool(
        rc == 0 and probes_ok and history_ok and text_ok
        and watchdog_quiet and t_run <= LIVE_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "live",
        "label": spec["label"],
        "iters": iters,
        "probe_every": every,
        "probe_records_ok": probes_ok,
        "history_records_ok": history_ok,
        "exporter_samples": samples,
        "exporter_syntax_ok": text_ok,
        "watchdog_fired": not watchdog_quiet,
        "seconds": t_run,
        "budget_s": LIVE_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] probed run + exporter + watchdog in {t_run:.2f}s vs "
        f"budget {LIVE_SMOKE_BUDGET_S:g}s; probe records "
        f"{'OK' if probes_ok else 'BAD'}; history "
        f"{'OK' if history_ok else 'BAD'}; {samples} exporter sample(s) "
        f"{'parse OK' if text_ok else 'PARSE BAD'}; watchdog "
        f"{'quiet' if watchdog_quiet else 'FIRED'} -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


PARTITIONED_SMOKE_BUDGET_S = 120.0

# Budget for the serving smoke (seconds, measured around ONE chaos
# load run — NOT the engine compile in start() or the f64-free replay
# run): 40 virtual-clock queries on 256 vertices with one device kill,
# a rescue + batch re-run, and a mid-load drain inside it.
SERVE_SMOKE_BUDGET_S = 3.0

# Every terminal outcome the serving daemon is allowed to hand back
# (pagerank_tpu/serving/query.py). Anything else — in particular "" /
# "unsettled" — is a silent drop, the failure class ISSUE 18 bans.
SERVE_OUTCOMES = frozenset({
    "answered", "answered_cache", "answered_degraded",
    "shed_overload", "rejected_draining", "rejected_deadline",
})

# Budget for the elastic-rescue smoke (seconds, measured around the
# chaos run itself — NOT the initial engine compile, the f64 oracle
# pass, or a subprocess fallback's interpreter/jax import): a
# 12-iteration f32 solve on 1024 vertices with one device kill, one
# classify + mesh teardown + survivor rebuild + warm-start inside it.
ELASTIC_SMOKE_BUDGET_S = 3.0

# The standing f32-grade oracle gate (normalized L1): f32 storage +
# f32 accumulation carries ~1e-7/element rounding; 1e-4 bounds it with
# margin while still failing any real rescue-path corruption.
ELASTIC_F32_GATE = 1e-4


def _fake_mesh_subprocess(key: str, kind: str, child_var: str):
    """Re-invoke one smoke in a subprocess with the 8-fake-CPU-device
    flags and adopt the child's record — shared by every smoke that
    needs a multi-device CPU mesh this process's backend cannot host
    (a live TPU, or fewer than 2 devices: L, M). ``child_var`` is the
    recursion guard env var."""
    import subprocess

    spec = CONFIGS[key]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    if env.get(child_var):
        raise RuntimeError(
            f"{kind} smoke child still lacks a multi-device CPU "
            "backend; refusing to recurse"
        )
    env[child_var] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--only", key,
         "--no-append", "--no-analysis"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    sys.stderr.write(proc.stderr)
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])[0]
    except Exception:
        return {"config": key, "kind": kind,
                "label": spec["label"], "passed": False,
                "error": f"child rc={proc.returncode}"}


def run_elastic_smoke(key: str):
    """ISSUE-7 gate: seed-deterministic device kill mid-solve on the
    8-fake-device CPU mesh -> classify -> teardown -> re-shard ->
    warm-start -> FINISH; rank parity vs the f64 oracle at the f32
    gate; `elastic/rescue` span + `elastic.*` counters in the run
    report; under ELASTIC_SMOKE_BUDGET_S. When this process's backend
    cannot host the fake mesh, the smoke re-invokes itself in a
    subprocess with the fake-device flags and adopts the child's
    record (_fake_mesh_subprocess)."""
    import jax

    spec = CONFIGS[key]
    if jax.default_backend() != "cpu" or len(jax.devices()) < 2:
        return _fake_mesh_subprocess(key, "elastic",
                                     "PAGERANK_ELASTIC_SMOKE_CHILD")

    import shutil
    import tempfile
    import warnings

    from pagerank_tpu import (JaxTpuEngine, PageRankConfig,
                              ReferenceCpuEngine, build_graph, obs)
    from pagerank_tpu.parallel.elastic import (DeviceHealthMonitor,
                                               ElasticRunner)
    from pagerank_tpu.testing.faults import (DeviceFaultSchedule,
                                             install_device_faults)
    from pagerank_tpu.utils.snapshot import Snapshotter

    iters, seed = spec["iters"], spec["seed"]
    kill_iter, kill_device = spec["kill_iter"], spec["kill_device"]
    ndev = min(8, len(jax.devices()))
    rng = np.random.default_rng(9)
    n, e = 1024, 8192
    src, dst = rng.integers(0, n, e), rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    cfg = PageRankConfig(num_iters=iters, dtype="float32",
                         accum_dtype="float32", num_devices=ndev)

    obs.disable_tracing()
    obs.get_registry().reset()
    tracer = obs.enable_tracing()
    work = tempfile.mkdtemp(prefix="pagerank_elastic_")
    try:
        snap = Snapshotter(work, g.fingerprint(), "reference")
        sched = DeviceFaultSchedule(seed=seed,
                                    kill={kill_iter: kill_device})
        eng = JaxTpuEngine(cfg).build(g)
        snap.mesh_meta = eng.snapshot_meta()
        install_device_faults(eng, sched)
        # The budget times the CHAOS RUN itself — solve + kill +
        # classify + teardown + survivor rebuild + warm-start — not
        # the initial 8-device compile above or the oracle pass below.
        t0 = time.perf_counter()

        def factory(devs):
            return JaxTpuEngine(
                cfg.replace(num_devices=len(devs)), devices=devs
            ).build(g)

        def rebound(e2):
            install_device_faults(e2, sched)
            snap.mesh_meta = e2.snapshot_meta()

        runner = ElasticRunner(
            eng, factory, snapshotter=snap, max_rescues=2,
            liveness=sched.liveness_probe,
            monitor=DeviceHealthMonitor(),
            on_rebuild=rebound,
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            ranks = runner.run(
                on_iteration=lambda i, info: snap.save(
                    i + 1, runner.engine.ranks()),
            )
        t_run = time.perf_counter() - t0
        report = obs.build_run_report(
            config=cfg, tracer=tracer, registry=obs.get_registry(),
            robustness={"rescues": runner.rescues,
                        "lost_devices": runner.lost_device_ids},
        )
    finally:
        obs.disable_tracing()
        shutil.rmtree(work, ignore_errors=True)
    oracle = ReferenceCpuEngine(
        PageRankConfig(num_iters=iters, dtype="float64",
                       accum_dtype="float64")
    ).build(build_graph(src, dst, n=n)).run()

    l1 = float(np.abs(ranks - oracle).sum()) / float(np.abs(oracle).sum())
    counters = (report.get("metrics") or {}).get("counters") or {}
    elastic_counters = {k: v for k, v in counters.items()
                        if k.startswith("elastic.")}
    rescue_span = "elastic/rescue" in (report.get("spans") or {})
    passed = bool(
        runner.rescues == 1
        and runner.engine.mesh.devices.size == ndev - 1
        and l1 <= ELASTIC_F32_GATE
        and rescue_span
        and elastic_counters.get("elastic.rescues") == 1
        and elastic_counters.get("elastic.devices_lost") == 1
        and t_run <= ELASTIC_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "elastic",
        "label": spec["label"],
        "iters": iters,
        "devices": ndev,
        "kill": {"iteration": kill_iter, "device": kill_device},
        "rescues": runner.rescues,
        "surviving_devices": int(runner.engine.mesh.devices.size),
        "normalized_l1": l1,
        "gate": ELASTIC_F32_GATE,
        "rescue_span_ok": rescue_span,
        "elastic_counters": elastic_counters,
        "seconds": t_run,
        "budget_s": ELASTIC_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] kill dev {kill_device} @ iter {kill_iter} on {ndev} "
        f"fake devices: {runner.rescues} rescue(s), finished on "
        f"{rec['surviving_devices']} device(s); oracle L1 {l1:.3e} vs "
        f"gate {ELASTIC_F32_GATE:g}; rescue span "
        f"{'OK' if rescue_span else 'MISSING'}; counters "
        f"{sorted(elastic_counters)}; {t_run:.2f}s vs budget "
        f"{ELASTIC_SMOKE_BUDGET_S:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


def run_serve_smoke(key: str):
    """ISSUE-18 gate: the PPR query daemon under chaos on the
    8-fake-device CPU mesh. Seed-deterministic load (virtual clock)
    with one device kill mid-serve -> rescue + in-flight batch re-run
    -> mid-load drain; every offered query must end in a typed outcome
    (zero silent drops, zero hangs), and a second same-seed run must
    replay bit-identically (admission log AND result digest). Then a
    REAL SIGTERM through the PR-12 GracefulDrain handler: the answered
    query stays answered, post-drain submits get typed Draining. The
    serve.* counter plane must surface in the run report, and the
    chaos run itself lands under SERVE_SMOKE_BUDGET_S."""
    import jax

    spec = CONFIGS[key]
    if jax.default_backend() != "cpu" or len(jax.devices()) < 2:
        return _fake_mesh_subprocess(key, "serve",
                                     "PAGERANK_SERVE_SMOKE_CHILD")

    from pagerank_tpu import PageRankConfig, build_graph, jobs, obs
    from pagerank_tpu.serving import PprServer, ServeConfig
    from pagerank_tpu.testing.faults import DeviceFaultSchedule
    from pagerank_tpu.testing.load import (QueryLoadGenerator,
                                           install_serve_faults,
                                           run_serve_load)
    from pagerank_tpu.testing.schedules import VirtualClock
    from pagerank_tpu.utils import synth

    seed = spec["seed"]
    ndev = min(8, len(jax.devices()))
    src, dst = synth.rmat_edges(8, edge_factor=8, seed=3)
    g = build_graph(src, dst, n=256)
    cfg = PageRankConfig(num_iters=spec["iters"])

    def serve_config(cache_capacity=64):
        # wall_alpha=0 freezes the batch-wall EWMA at wall_initial_s:
        # with the virtual clock, every shed/close decision is then a
        # pure function of the seed (the determinism contract).
        return ServeConfig(max_batch=4, queue_depth=16, deadline_ms=400.0,
                           topk=8, wall_alpha=0.0, wall_initial_s=0.05,
                           cache_capacity=cache_capacity,
                           batch_margin_s=0.01)

    def one_run():
        clock = VirtualClock()
        sched = DeviceFaultSchedule(
            seed=seed, kill={spec["kill_batch"]: spec["kill_device"]}
        )
        srv = PprServer(g, config=cfg, serve_config=serve_config(),
                        liveness_probe=sched.liveness_probe, clock=clock)
        srv.start(dispatcher=False)
        install_serve_faults(srv, sched, clock=clock, service_s=0.05)
        plan = QueryLoadGenerator(seed=seed, num_queries=spec["queries"],
                                  n=256, mean_gap_s=0.02, k=8).plan()
        # The budget times the CHAOS LOAD itself — admissions, kill,
        # rescue, re-run, drain — not the compile inside start().
        t0 = time.perf_counter()
        rep = run_serve_load(srv, clock, plan, drain_at=spec["drain_at"],
                             drain_deadline_s=1.0)
        rep["seconds"] = time.perf_counter() - t0
        return rep

    obs.disable_tracing()
    obs.get_registry().reset()
    tracer = obs.enable_tracing()
    try:
        r1 = one_run()
        r2 = one_run()
        report = obs.build_run_report(
            config=cfg, tracer=tracer, registry=obs.get_registry(),
        )
    finally:
        obs.disable_tracing()

    # Real-SIGTERM drain: the production exit path, with an actual
    # signal through the installed handler — not a direct drain() call.
    clock3 = VirtualClock()
    srv3 = PprServer(g, config=cfg, serve_config=serve_config(0),
                     clock=clock3)
    srv3.start(dispatcher=False)
    drained = False
    with jobs.GracefulDrain(deadline_s=5.0) as drain:
        q_before = srv3.submit(5, k=4)
        clock3.advance(0.36)  # inside the close margin, before expiry
        srv3.pump()
        os.kill(os.getpid(), signal.SIGTERM)
        try:
            drain.check("serve-smoke")
        except jobs.DrainInterrupt:
            srv3.drain(deadline_s=drain.remaining())
            drained = True
        q_after = srv3.submit(6, k=4)
        drain.finish()
    sigterm_ok = bool(drained and q_before.outcome == "answered"
                      and q_after.outcome == "rejected_draining")

    counters = (report.get("metrics") or {}).get("counters") or {}
    serve_counters = {k: v for k, v in counters.items()
                      if k.startswith("serve.")}
    outcomes_seen = set(r1["outcomes"]) | set(r2["outcomes"])
    accounted = (r1["unsettled"] == 0 and r2["unsettled"] == 0
                 and outcomes_seen <= SERVE_OUTCOMES)
    replay_ok = (r1["results_digest"] == r2["results_digest"]
                 and r1["admission_log"] == r2["admission_log"])
    passed = bool(
        accounted
        and replay_ok
        and r1["degraded"] and r1["device_count"] == ndev - 1
        and r1["outcomes"].get("rejected_draining", 0) >= 1
        and serve_counters.get("serve.rescues") == 2  # one per run
        and serve_counters.get("serve.batch_reruns", 0) >= 2
        and sigterm_ok
        and r1["seconds"] <= SERVE_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "serve",
        "label": spec["label"],
        "devices": ndev,
        "queries": spec["queries"],
        "kill": {"batch": spec["kill_batch"],
                 "device": spec["kill_device"]},
        "outcomes": dict(r1["outcomes"]),
        "unsettled": r1["unsettled"] + r2["unsettled"],
        "degraded": r1["degraded"],
        "surviving_devices": r1["device_count"],
        "replay_identical": replay_ok,
        "sigterm_drain_ok": sigterm_ok,
        "serve_counters": serve_counters,
        "seconds": r1["seconds"],
        "budget_s": SERVE_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] {spec['queries']} queries on {ndev} fake devices, "
        f"kill dev {spec['kill_device']} @ batch {spec['kill_batch']}: "
        f"outcomes {dict(sorted(r1['outcomes'].items()))}, finished on "
        f"{r1['device_count']} device(s); replay "
        f"{'bit-identical' if replay_ok else 'DIVERGED'}; SIGTERM drain "
        f"{'OK' if sigterm_ok else 'BAD'}; counters "
        f"{sorted(serve_counters)}; {r1['seconds']:.2f}s vs budget "
        f"{SERVE_SMOKE_BUDGET_S:g}s -> {'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the query-plane smoke (seconds, ISSUE 19, measured around
# ONE armed chaos load — not the compile in start()): the same load as
# the serving smoke plus per-query trace assembly, exemplar records,
# and slow-query JSONL writes. Same 3 s bound as the unarmed smoke —
# the plane is bounded work per settle, never a second pass.
QTRACE_SMOKE_BUDGET_S = 3.0

_OM_SAMPLE_RE = None


def _parse_openmetrics_strict(text: str):
    """Strict parse of the OpenMetrics rendering: every sample line
    must match the grammar (counter samples ``_total``-suffixed,
    optional `` # {trace_id="..."} value`` exemplar clause on histogram
    bucket lines), and the body must end with the ``# EOF`` terminator.
    Returns ``(samples, exemplars)``; raises AssertionError on any bad
    line. tests/test_qtrace.py carries the same grammar."""
    import re

    global _OM_SAMPLE_RE
    if _OM_SAMPLE_RE is None:
        _v = r"(?:[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|Inf)|NaN)"
        _OM_SAMPLE_RE = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
            r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
            r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
            r" " + _v +
            r'( # \{trace_id="[^"]+"\} ' + _v + r")?$"
        )
    lines = text.splitlines()
    assert lines and lines[-1] == "# EOF", "missing # EOF terminator"
    samples = 0
    exemplars = 0
    for line in lines[:-1]:
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _OM_SAMPLE_RE.match(line), f"bad openmetrics line: {line!r}"
        samples += 1
        exemplars += " # {" in line
    return samples, exemplars


def run_qtrace_smoke(key: str):
    """ISSUE-19 gate: the serving chaos load with the query plane and
    tracer ARMED. Gates: seed-determinism survives instrumentation
    (admission log, result digest AND the timestamp-free trace-structure
    digest all replay identically), the slow-query JSONL log
    schema-validates line-by-line, the OpenMetrics rendering
    strict-parses with >=1 trace-id exemplar on the serve latency
    buckets, a REAL SIGTERM drain leaves a reason="drain" flight dump
    (with trace-carrying timelines) in the run report's serving
    section, and the armed chaos run still lands under
    QTRACE_SMOKE_BUDGET_S."""
    import jax

    spec = CONFIGS[key]
    if jax.default_backend() != "cpu" or len(jax.devices()) < 2:
        return _fake_mesh_subprocess(key, "qtrace",
                                     "PAGERANK_QTRACE_SMOKE_CHILD")

    import shutil
    import tempfile

    from pagerank_tpu import PageRankConfig, build_graph, jobs, obs
    from pagerank_tpu.obs import live as obs_live
    from pagerank_tpu.serving import PprServer, ServeConfig, qtrace
    from pagerank_tpu.testing.faults import DeviceFaultSchedule
    from pagerank_tpu.testing.load import (QueryLoadGenerator,
                                           install_serve_faults,
                                           run_serve_load)
    from pagerank_tpu.testing.schedules import VirtualClock
    from pagerank_tpu.utils import synth

    seed = spec["seed"]
    ndev = min(8, len(jax.devices()))
    src, dst = synth.rmat_edges(8, edge_factor=8, seed=3)
    g = build_graph(src, dst, n=256)
    cfg = PageRankConfig(num_iters=spec["iters"])

    def serve_config(cache_capacity=64):
        return ServeConfig(max_batch=4, queue_depth=16, deadline_ms=400.0,
                           topk=8, wall_alpha=0.0, wall_initial_s=0.05,
                           cache_capacity=cache_capacity,
                           batch_margin_s=0.01)

    def one_run(slow_log):
        # A FRESH plane per run: the structure digest then covers
        # exactly one load, so equal digests mean equal span trees.
        plane = qtrace.arm_query_plane(slow_query_ms=0.0,
                                       slow_query_path=slow_log)
        try:
            clock = VirtualClock()
            sched = DeviceFaultSchedule(
                seed=seed, kill={spec["kill_batch"]: spec["kill_device"]}
            )
            srv = PprServer(g, config=cfg, serve_config=serve_config(),
                            liveness_probe=sched.liveness_probe,
                            clock=clock)
            srv.start(dispatcher=False)
            install_serve_faults(srv, sched, clock=clock, service_s=0.05)
            plan = QueryLoadGenerator(seed=seed,
                                      num_queries=spec["queries"],
                                      n=256, mean_gap_s=0.02, k=8).plan()
            t0 = time.perf_counter()
            rep = run_serve_load(srv, clock, plan,
                                 drain_at=spec["drain_at"],
                                 drain_deadline_s=1.0)
            rep["seconds"] = time.perf_counter() - t0
            rep["slow_count"] = plane.slow_count
            rep["phase_p99_ms"] = plane.phase_p99_ms()
        finally:
            qtrace.disarm_query_plane()
        return rep

    def reject_constant(s):
        raise AssertionError(f"non-strict JSON constant {s!r}")

    def slow_log_ok(path, expect):
        """Strict-JSONL schema gate on one slow-query log."""
        count = 0
        with open(path, encoding="utf-8") as f:
            for line in f:
                rec = json.loads(line, parse_constant=reject_constant)
                if set(rec) != set(qtrace.SLOW_QUERY_KEYS):
                    return False
                if rec["type"] != "slow_query":
                    return False
                if not (isinstance(rec["trace_id"], str)
                        and len(rec["trace_id"]) == 32):
                    return False
                count += 1
        return count == expect and count > 0

    obs.disable_tracing()
    obs.get_registry().reset()
    tracer = obs.enable_tracing()
    work = tempfile.mkdtemp(prefix="pagerank_qtrace_")
    try:
        log1 = os.path.join(work, "slow1.jsonl")
        log2 = os.path.join(work, "slow2.jsonl")
        r1 = one_run(log1)
        r2 = one_run(log2)
        slow_ok = bool(slow_log_ok(log1, r1["slow_count"])
                       and slow_log_ok(log2, r2["slow_count"]))

        # The armed runs recorded trace-id exemplars into the latency
        # histogram; the OpenMetrics rendering must carry them and
        # still strict-parse (plain-Prometheus stays exemplar-free).
        om_text = obs_live.render_openmetrics()
        try:
            _, exemplars = _parse_openmetrics_strict(om_text)
            exemplar_ok = exemplars >= 1
        except AssertionError:
            exemplar_ok = False

        # Real SIGTERM through the PR-12 handler with the plane armed:
        # the drain must leave a flight-recorder dump in the report.
        plane3 = qtrace.arm_query_plane()
        clock3 = VirtualClock()
        srv3 = PprServer(g, config=cfg, serve_config=serve_config(0),
                         clock=clock3)
        srv3.start(dispatcher=False)
        drained = False
        with jobs.GracefulDrain(deadline_s=5.0) as drain:
            q_before = srv3.submit(5, k=4)
            clock3.advance(0.36)
            srv3.pump()
            os.kill(os.getpid(), signal.SIGTERM)
            try:
                drain.check("qtrace-smoke")
            except jobs.DrainInterrupt:
                srv3.drain(deadline_s=drain.remaining())
                drained = True
            drain.finish()
        report = obs.build_run_report(
            config=cfg, tracer=tracer, registry=obs.get_registry(),
        )
        serving = report.get("serving") or {}
        dumps = serving.get("flight_dumps") or []
        drain_dumps = [d for d in dumps if d.get("reason") == "drain"]
        dump_traces_ok = bool(
            drain_dumps
            and drain_dumps[-1]["traces"]
            and all(len(t.get("trace_id", "")) == 32
                    for t in drain_dumps[-1]["traces"])
        )
        sigterm_ok = bool(drained and serving.get("enabled")
                          and q_before.outcome == "answered"
                          and dump_traces_ok)
        qtrace.disarm_query_plane()
    finally:
        obs.disable_tracing()
        qtrace.disarm_query_plane()
        shutil.rmtree(work, ignore_errors=True)

    trace_ok = bool(r1.get("trace_digest") and
                    r1.get("trace_digest") == r2.get("trace_digest"))
    replay_ok = (r1["results_digest"] == r2["results_digest"]
                 and r1["admission_log"] == r2["admission_log"])
    accounted = r1["unsettled"] == 0 and r2["unsettled"] == 0
    decomposed = all(
        leg in r1["phase_p99_ms"] for leg in qtrace.DECOMPOSITION_LEGS
    ) and r1["phase_p99_ms"]["batch_wait"] > 0
    passed = bool(
        accounted
        and replay_ok
        and trace_ok
        and decomposed
        and slow_ok
        and exemplar_ok
        and sigterm_ok
        and r1["seconds"] <= QTRACE_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "qtrace",
        "label": spec["label"],
        "devices": ndev,
        "queries": spec["queries"],
        "outcomes": dict(r1["outcomes"]),
        "trace_digest_identical": trace_ok,
        "replay_identical": replay_ok,
        "phase_p99_ms": r1["phase_p99_ms"],
        "slow_log_ok": slow_ok,
        "slow_queries": r1["slow_count"],
        "exemplars_ok": exemplar_ok,
        "sigterm_flight_dump_ok": sigterm_ok,
        "seconds": r1["seconds"],
        "budget_s": QTRACE_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] armed chaos x2 on {ndev} fake devices: trace digest "
        f"{'identical' if trace_ok else 'DIVERGED'}; replay "
        f"{'bit-identical' if replay_ok else 'DIVERGED'}; "
        f"{r1['slow_count']} slow-query line(s) "
        f"{'schema OK' if slow_ok else 'SCHEMA BAD'}; exemplars "
        f"{'parse OK' if exemplar_ok else 'PARSE BAD'}; SIGTERM flight "
        f"dump {'OK' if sigterm_ok else 'BAD'}; {r1['seconds']:.2f}s vs "
        f"budget {QTRACE_SMOKE_BUDGET_S:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the SDC smoke (seconds, ISSUE 15): times the CHAOS RUN
# itself — checked solve + breach + bounded redo + sticky conviction +
# teardown + 7-device rebuild + finish — not the initial 8-device
# compile or the f64 oracle pass (the smoke-L protocol, same 3 s
# class: one extra checked-step compile + one rebuild inside it).
SDC_SMOKE_BUDGET_S = 3.0


def run_sdc_smoke(key: str):
    """ISSUE-15 gate: a seeded STICKY bit flip on the 8-fake-device
    CPU mesh -> ABFT detect (within the check cadence) -> localize to
    the injected device -> bounded redo convicts sticky -> quarantine
    through the elastic rescue -> FINISH on 7 devices; rank parity vs
    the f64 oracle at the f32 gate; sdc.flips_detected /
    sdc.quarantined_devices in the run report; under
    SDC_SMOKE_BUDGET_S. Subprocess fallback per the smoke-L
    protocol."""
    import jax

    spec = CONFIGS[key]
    if jax.default_backend() != "cpu" or len(jax.devices()) < 2:
        return _fake_mesh_subprocess(key, "sdc",
                                     "PAGERANK_SDC_SMOKE_CHILD")

    import warnings

    from pagerank_tpu import (JaxTpuEngine, PageRankConfig,
                              ReferenceCpuEngine, build_graph, obs)
    from pagerank_tpu import sdc as sdc_mod
    from pagerank_tpu.parallel.elastic import (DeviceHealthMonitor,
                                               ElasticRunner)
    from pagerank_tpu.testing.faults import (DeviceFaultSchedule,
                                             install_device_faults)

    iters, seed = spec["iters"], spec["seed"]
    flip_iter, flip_device = spec["flip_iter"], spec["flip_device"]
    ndev = min(8, len(jax.devices()))
    rng = np.random.default_rng(9)
    n, e = 1024, 8192
    src, dst = rng.integers(0, n, e), rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    cfg = PageRankConfig(num_iters=iters, dtype="float32",
                         accum_dtype="float32", num_devices=ndev,
                         sdc_check_every=1)

    obs.disable_tracing()
    obs.get_registry().reset()
    sdc_mod.reset()
    tracer = obs.enable_tracing()
    sched = DeviceFaultSchedule(
        seed=seed, flip={flip_iter: (flip_device, "mantissa")},
        sticky_flips=[flip_iter],
    )
    eng = JaxTpuEngine(cfg).build(g)
    install_device_faults(eng, sched)
    # Warm the checked-step executables outside the timed region (the
    # smoke-L protocol excludes the initial compiles): one untimed
    # checked step on a retained copy, restored before the run.
    tok = eng.retain_state()
    eng.sdc_state_values()
    eng._prefault_step_sdc()
    eng.restore_state(tok)
    t0 = time.perf_counter()

    def factory(devs):
        return JaxTpuEngine(
            cfg.replace(num_devices=len(devs)), devices=devs
        ).build(g)

    runner = ElasticRunner(
        eng, factory, snapshotter=None, max_rescues=2,
        liveness=sched.liveness_probe,
        monitor=DeviceHealthMonitor(),
        on_rebuild=lambda e2: install_device_faults(e2, sched),
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        ranks = runner.run()
    t_run = time.perf_counter() - t0
    report = obs.build_run_report(
        config=cfg, tracer=tracer, registry=obs.get_registry(),
        extra={"sdc": sdc_mod.report_section()},
    )
    obs.disable_tracing()

    oracle = ReferenceCpuEngine(
        PageRankConfig(num_iters=iters, dtype="float64",
                       accum_dtype="float64")
    ).build(build_graph(src, dst, n=n)).run()
    l1 = float(np.abs(ranks - oracle).sum()) / float(np.abs(oracle).sum())

    counters = (report.get("metrics") or {}).get("counters") or {}
    sdc_counters = {k: v for k, v in counters.items()
                    if k.startswith("sdc.")}
    sdc_section = report.get("sdc") or {}
    localized = (sdc_section.get("last_breach") or {}).get("device")
    passed = bool(
        sdc_counters.get("sdc.flips_detected", 0) >= 1
        and sdc_counters.get("sdc.quarantined_devices") == 1
        and localized == flip_device
        and runner.quarantined_device_ids == [flip_device]
        and runner.rescues == 1
        and runner.engine.mesh.devices.size == ndev - 1
        and l1 <= ELASTIC_F32_GATE
        and t_run <= SDC_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "sdc",
        "label": spec["label"],
        "iters": iters,
        "devices": ndev,
        "flip": {"iteration": flip_iter, "device": flip_device,
                 "kind": "mantissa", "sticky": True},
        "localized_device": localized,
        "quarantined": list(runner.quarantined_device_ids),
        "rescues": runner.rescues,
        "surviving_devices": int(runner.engine.mesh.devices.size),
        "normalized_l1": l1,
        "gate": ELASTIC_F32_GATE,
        "sdc_counters": sdc_counters,
        "seconds": t_run,
        "budget_s": SDC_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] sticky {rec['flip']['kind']} flip on dev "
        f"{flip_device} @ iter {flip_iter}: detected "
        f"{sdc_counters.get('sdc.flips_detected', 0)}, localized to "
        f"dev {localized}, quarantined {rec['quarantined']}, finished "
        f"on {rec['surviving_devices']} device(s); oracle L1 "
        f"{l1:.3e} vs gate {ELASTIC_F32_GATE:g}; {t_run:.2f}s vs "
        f"budget {SDC_SMOKE_BUDGET_S:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the sparse-exchange smoke (seconds, timed around the
# solve loop itself — the build/plan and the f64 oracle pass are
# excluded, the first step's compile is not): a 12-iteration f32
# vertex-sharded solve on 4096 vertices over 8 fake CPU devices.
HALO_SMOKE_BUDGET_S = 3.0


def run_halo_smoke(key: str):
    """ISSUE-8 gate: the sparse boundary exchange end to end on the
    8-fake-device CPU mesh — vs_halo dispatch form, oracle L1 at the
    standing f32 gate, measured exchanged bytes strictly below the
    dense model, `comms.*` gauges + counter in the registry, under
    HALO_SMOKE_BUDGET_S. Re-invokes itself in a subprocess with the
    fake-device flags when this backend can't host the mesh
    (_fake_mesh_subprocess, same protocol as smoke L)."""
    import jax

    spec = CONFIGS[key]
    if jax.default_backend() != "cpu" or len(jax.devices()) < 2:
        return _fake_mesh_subprocess(key, "halo",
                                     "PAGERANK_HALO_SMOKE_CHILD")

    from pagerank_tpu import (JaxTpuEngine, PageRankConfig,
                              ReferenceCpuEngine, build_graph, obs)
    from pagerank_tpu.obs import metrics as obs_metrics
    from pagerank_tpu.utils.synth import rmat_edges

    scale, iters = spec["scale"], spec["iters"]
    ndev = min(8, len(jax.devices()))
    src, dst = rmat_edges(scale, 8, seed=4)
    g = build_graph(src, dst, n=1 << scale)
    obs.get_registry().reset()
    cfg = PageRankConfig(num_iters=iters, dtype="float32",
                         accum_dtype="float32", num_devices=ndev,
                         vertex_sharded=True, halo_exchange=True)
    eng = JaxTpuEngine(cfg).build(g)
    form = eng.layout_info().get("form")
    cm = eng.comms_model() or {}
    ctr = obs_metrics.counter("comms.bytes_exchanged")
    c0 = ctr.value
    t0 = time.perf_counter()
    ranks = eng.run_fast()
    t_run = time.perf_counter() - t0
    measured = int(ctr.value - c0)

    oracle = ReferenceCpuEngine(
        PageRankConfig(num_iters=iters, dtype="float64",
                       accum_dtype="float64")
    ).build(g).run()
    l1 = float(np.abs(ranks - oracle).sum()) / float(
        np.abs(oracle).sum())

    sparse = int(cm.get("sparse_bytes_per_iter") or 0)
    dense = int(cm.get("dense_bytes_per_iter") or 0)
    counters = obs.get_registry().snapshot().get("counters", {})
    gauges = obs.get_registry().snapshot().get("gauges", {})
    comms_visible = ("comms.bytes_exchanged" in counters
                     and "comms.halo_fraction" in gauges)
    passed = bool(
        form == "vs_halo"
        and l1 <= ELASTIC_F32_GATE
        and 0 < sparse < dense
        and measured == sparse * iters
        and comms_visible
        and t_run <= HALO_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "halo",
        "label": spec["label"],
        "scale": scale,
        "iters": iters,
        "devices": ndev,
        "form": form,
        "normalized_l1": l1,
        "gate": ELASTIC_F32_GATE,
        "sparse_bytes_per_iter": sparse,
        "dense_bytes_per_iter": dense,
        "measured_bytes": measured,
        "halo_fraction": cm.get("halo_fraction"),
        "head_k": cm.get("head_k"),
        "comms_metrics_ok": comms_visible,
        "seconds": t_run,
        "budget_s": HALO_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] sparse exchange on {ndev} fake devices (scale "
        f"{scale}, {iters} iters): form {form}; oracle L1 {l1:.3e} vs "
        f"gate {ELASTIC_F32_GATE:g}; bytes/iter {sparse:,} sparse < "
        f"{dense:,} dense ({'OK' if 0 < sparse < dense else 'BAD'}), "
        f"measured {measured:,}; comms metrics "
        f"{'OK' if comms_visible else 'MISSING'}; {t_run:.2f}s vs "
        f"budget {HALO_SMOKE_BUDGET_S:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the async-exchange smoke (seconds, timed around the solve
# loop itself — build/plan, the contract sweep, the PTR pass, and the
# f64 oracle are excluded; the first step's compile is not): a
# 120-iteration textbook f32 solve on 4096 vertices over 8 fake CPU
# devices through the double-buffered exchange.
HALO_ASYNC_SMOKE_BUDGET_S = 3.0


def run_halo_async_smoke(key: str):
    """ISSUE-17 gate: the asynchronous stale-boundary exchange end to
    end on the 8-fake-device CPU mesh — vs_halo_async dispatch form
    with the lag-1 double buffer ON, the form's contract sweep clean,
    oracle L1 at the standing f32 gate (textbook semantics — the
    lag-1 schedule must converge to the SAME fixed point), measured
    exchanged bytes == iters x the static model, and the PTR
    concurrency pass at zero unwaived findings. Re-invokes itself in
    a subprocess with the fake-device flags when this backend can't
    host the mesh (the smoke-L protocol)."""
    import jax

    spec = CONFIGS[key]
    if jax.default_backend() != "cpu" or len(jax.devices()) < 2:
        return _fake_mesh_subprocess(key, "halo_async",
                                     "PAGERANK_HALO_ASYNC_SMOKE_CHILD")

    from pagerank_tpu import (JaxTpuEngine, PageRankConfig,
                              ReferenceCpuEngine, build_graph, obs)
    from pagerank_tpu.analysis import concurrency as conc_mod
    from pagerank_tpu.analysis import load_allowlist, split_allowlisted
    from pagerank_tpu.analysis.contracts import run_contracts
    from pagerank_tpu.analysis.lint import package_root
    from pagerank_tpu.obs import metrics as obs_metrics
    from pagerank_tpu.utils.synth import rmat_edges

    scale, iters = spec["scale"], spec["iters"]
    ndev = min(8, len(jax.devices()))
    src, dst = rmat_edges(scale, 8, seed=4)
    g = build_graph(src, dst, n=1 << scale)
    obs.get_registry().reset()
    cfg = PageRankConfig(num_iters=iters, dtype="float32",
                         accum_dtype="float32", num_devices=ndev,
                         vertex_sharded=True, halo_exchange=True,
                         halo_async=True, halo_async_min_gain=0.0,
                         semantics="textbook")
    eng = JaxTpuEngine(cfg).build(g)
    li = eng.layout_info()
    form = li.get("form")
    async_state = str(li.get("halo_async", ""))
    cm = eng.comms_model() or {}
    ctr = obs_metrics.counter("comms.bytes_exchanged")
    c0 = ctr.value
    t0 = time.perf_counter()
    ranks = eng.run_fast()
    t_run = time.perf_counter() - t0
    measured = int(ctr.value - c0)
    gauges = obs.get_registry().snapshot().get("gauges", {})
    predicted_gain = gauges.get("comms.predicted_overlap_gain")

    oracle = ReferenceCpuEngine(
        PageRankConfig(num_iters=iters, dtype="float64",
                       accum_dtype="float64", semantics="textbook")
    ).build(g).run()
    l1 = float(np.abs(ranks - oracle).sum()) / float(
        np.abs(oracle).sum())

    # The form's own jaxpr contract sweep (PTC001 collective multiset
    # pinned identical to vs_halo, plus the probed/ledger/sdc variant
    # rows) — empty findings = clean.
    contract_findings = run_contracts(forms=["vs_halo_async"])

    # PTR race pass with the buffer-rotation host state in the tree.
    prog = conc_mod.build_package_program()
    allow = os.path.join(package_root(), "analysis", "allowlist.txt")
    active, _waived = split_allowlisted(
        conc_mod.analyze_program(prog), load_allowlist(allow))

    model = int(cm.get("bytes_per_iter") or 0)
    overlappable = int(cm.get("overlappable_bytes_per_iter") or 0)
    passed = bool(
        form == "vs_halo_async"
        and async_state.startswith("on:")
        and not contract_findings
        and l1 <= ELASTIC_F32_GATE
        and model > 0
        and measured == model * iters
        and overlappable > 0
        and not active
        and t_run <= HALO_ASYNC_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "halo_async",
        "label": spec["label"],
        "scale": scale,
        "iters": iters,
        "devices": ndev,
        "form": form,
        "halo_async": async_state,
        "contract_findings": [f.render() for f in contract_findings],
        "normalized_l1": l1,
        "gate": ELASTIC_F32_GATE,
        "bytes_per_iter": model,
        "overlappable_bytes_per_iter": overlappable,
        "measured_bytes": measured,
        "predicted_overlap_gain": predicted_gain,
        "ptr_unwaived": len(active),
        "seconds": t_run,
        "budget_s": HALO_ASYNC_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] async stale-boundary exchange on {ndev} fake devices "
        f"(scale {scale}, {iters} iters, textbook): form {form} "
        f"({async_state}); contracts "
        f"{'clean' if not contract_findings else 'DIRTY'}; oracle L1 "
        f"{l1:.3e} vs gate {ELASTIC_F32_GATE:g}; measured "
        f"{measured:,} == {iters} x {model:,} model "
        f"({'OK' if measured == model * iters else 'BAD'}, "
        f"{overlappable:,} overlappable); PTR {len(active)} unwaived; "
        f"{t_run:.2f}s vs budget {HALO_ASYNC_SMOKE_BUDGET_S:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the perf-sentry GATE run (seconds): reading a ~10-record
# ledger + per-(leg, metric) median/MAD math is milliseconds; 2s is
# the ISSUE-9 acceptance bound and still catches an accidentally
# quadratic detector. The fresh bench run itself is NOT under this
# budget (it compiles real programs).
HISTORY_GATE_BUDGET_S = 2.0


def run_history_smoke(key: str):
    """ISSUE-9 gate: the perf-regression sentry end to end. A fresh
    scale-14 single-config bench (subprocess, real bench.py) appends
    itself to a TEMP COPY of the checked-in ledger via ``--history``;
    `obs history gate --budgets perf_budgets.json` must PASS under
    HISTORY_GATE_BUDGET_S. Then, with a baseline built from the fresh
    record (3 jittered clones), an env-fingerprint-only drift record
    (rate -20%, cost model flat, jax version bumped) must gate CLEAN
    with a drift warning, while an injected regression (rate -50%,
    cost model moved, env identical) must exit nonzero classified
    program-change — regression-vs-drift as exit codes, not hand
    analysis."""
    import copy
    import shutil
    import subprocess
    import tempfile

    from pagerank_tpu.obs import history as history_mod
    from pagerank_tpu.obs.__main__ import main as obs_main

    spec = CONFIGS[key]
    scale, iters = spec["scale"], spec["iters"]
    budgets_path = os.path.join(REPO, "perf_budgets.json")
    work = tempfile.mkdtemp(prefix="pagerank_hist_")
    try:
        ledger = os.path.join(work, "PERF_HISTORY.jsonl")
        shutil.copy(os.path.join(REPO, "PERF_HISTORY.jsonl"), ledger)
        n_seed = len(history_mod.read_ledger(ledger))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--scale", str(scale), "--dtype", "float32",
             "--iters", str(iters), "--warmup", "1", "--host-build",
             "--no-accuracy", "--history", ledger],
            capture_output=True, text=True, timeout=600,
        )
        records = history_mod.read_ledger(ledger)
        ingested = proc.returncode == 0 and len(records) == n_seed + 1

        t0 = time.perf_counter()
        rc_fresh = obs_main(["history", "gate", ledger,
                             "--budgets", budgets_path])
        t_gate = time.perf_counter() - t0

        # Baseline for the fresh record's environment class: three
        # jittered clones (rate +-0.2/0.4%, cost + env identical).
        fresh = records[-1]
        budgets = history_mod.load_budgets(budgets_path)

        def variant(src_rec, name, eps_factor, cost_factor=1.0,
                    env_patch=None):
            rec = copy.deepcopy(src_rec)
            rec["source"] = name
            rec.pop("content_hash", None)
            rec.pop("ingested_unix", None)
            leg = rec["legs"]["fast_f32"]
            leg["edges_per_sec_per_chip"] *= eps_factor
            if "cost_bytes_per_edge" in leg:
                leg["cost_bytes_per_edge"] *= cost_factor
            if env_patch:
                rec["env"].update(env_patch)
            rec["content_hash"] = history_mod.content_hash(rec)
            return rec

        for i in (1, 2, 3):
            history_mod.append_record(
                ledger, variant(fresh, f"clone{i}", 1.0 + 0.002 * i))

        # Env-fingerprint-only drift: must WARN and pass.
        drift = variant(fresh, "drift", 0.80,
                        env_patch={"jax_version": "0.0.0+smoke-drift",
                                   "jaxlib_version": "0.0.0+smoke"})
        history_mod.append_record(ledger, drift)
        rc_drift = obs_main(["history", "gate", ledger,
                             "--budgets", budgets_path])
        res_drift = history_mod.evaluate_gate(
            history_mod.read_ledger(ledger), budgets)
        drift_flag = [c for c in res_drift.changes
                      if c.flagged and c.leg == "fast_f32"
                      and c.metric == "edges_per_sec_per_chip"]
        drift_ok = (rc_drift == 0 and bool(res_drift.drift_warnings)
                    and bool(drift_flag)
                    and drift_flag[0].classification == "env-drift")

        # Injected regression: wall AND cost model moved, env
        # identical — must FAIL, classified program-change.
        prog = variant(fresh, "regression", 0.50, cost_factor=2.0)
        history_mod.append_record(ledger, prog)
        rc_prog = obs_main(["history", "gate", ledger,
                            "--budgets", budgets_path])
        res_prog = history_mod.evaluate_gate(
            history_mod.read_ledger(ledger), budgets)
        prog_flag = [c for c in res_prog.changes
                     if c.flagged and c.leg == "fast_f32"
                     and c.metric == "edges_per_sec_per_chip"]
        prog_ok = (rc_prog == 1 and bool(prog_flag)
                   and prog_flag[0].classification == "program-change")
    finally:
        shutil.rmtree(work, ignore_errors=True)

    passed = bool(ingested and rc_fresh == 0
                  and t_gate <= HISTORY_GATE_BUDGET_S
                  and drift_ok and prog_ok)
    rec = {
        "config": key,
        "kind": "history",
        "label": spec["label"],
        "scale": scale,
        "iters": iters,
        "fresh_record_ingested": ingested,
        "fresh_gate_rc": rc_fresh,
        "gate_seconds": t_gate,
        "gate_budget_s": HISTORY_GATE_BUDGET_S,
        "env_drift_warns_and_passes": drift_ok,
        "program_change_fails": prog_ok,
        "passed": passed,
    }
    print(
        f"[{key}] fresh scale-{scale} bench "
        f"{'ingested' if ingested else 'NOT INGESTED'}; gate "
        f"{'PASS' if rc_fresh == 0 else 'FAIL'} in {t_gate:.2f}s vs "
        f"budget {HISTORY_GATE_BUDGET_S:g}s; env-drift record "
        f"{'warned+passed' if drift_ok else 'MISHANDLED'}; injected "
        f"regression "
        f"{'failed as program-change' if prog_ok else 'MISSED'} -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the device-plane smoke (seconds, timed around the
# attributed solve + attribution probe — the build and the two fit
# checks are excluded; the fit checks are sub-3s themselves and
# recorded separately): an 8-iteration f32 halo solve on 4096 vertices
# over 8 fake CPU devices plus ~20 timing sub-dispatches.
DEVICES_SMOKE_BUDGET_S = 3.0


def run_devices_smoke(key: str):
    """ISSUE-10 gate: the device plane end to end on the 8-fake-device
    CPU mesh — an ATTRIBUTED halo solve (attribution block present and
    self-consistent vs the comms model, comms.exchange_fraction /
    comms.achieved_bytes_per_sec gauges published), the per-device
    sampler armed through engine.run (device.<id>.* gauge names
    registered, exporter output strict-parses despite the CPU
    backend's all-None stats), and the OOM-preflight fit check passing
    at scale 14 while REFUSING an absurd scale. Subprocess fallback
    when this backend can't fake the mesh (smoke L/M protocol)."""
    import jax

    spec = CONFIGS[key]
    if jax.default_backend() != "cpu" or len(jax.devices()) < 2:
        return _fake_mesh_subprocess(key, "devices",
                                     "PAGERANK_DEVICES_SMOKE_CHILD")

    from pagerank_tpu import (JaxTpuEngine, PageRankConfig, build_graph,
                              obs)
    from pagerank_tpu.obs import devices as obs_devices
    from pagerank_tpu.obs import live as obs_live
    from pagerank_tpu.utils.synth import rmat_edges

    sys.path.insert(0, os.path.join(REPO, "tests"))
    try:
        from test_telemetry import assert_prometheus_syntax
    finally:
        sys.path.pop(0)

    scale, iters = spec["scale"], spec["iters"]
    ndev = min(8, len(jax.devices()))
    src, dst = rmat_edges(scale, 8, seed=4)
    g = build_graph(src, dst, n=1 << scale)
    obs.get_registry().reset()
    obs.disarm_sampler()
    cfg = PageRankConfig(num_iters=iters, dtype="float32",
                         accum_dtype="float32", num_devices=ndev,
                         vertex_sharded=True, halo_exchange=True)
    eng = JaxTpuEngine(cfg).build(g)
    cm = eng.comms_model() or {}
    obs.arm_sampler(obs.DeviceSampler(every=2))
    try:
        t0 = time.perf_counter()
        eng.run()
        att = obs_devices.attribute_exchange(eng, iters=4, warmup=1)
        t_run = time.perf_counter() - t0
    finally:
        sampler = obs.disarm_sampler()

    snap = obs.get_registry().snapshot()
    gauges = snap["gauges"]
    att_ok = bool(
        att is not None
        and att["mode"] == "sparse"
        and att["exchange_s"] > 0
        # No step_s >= exchange_s assertion: the walls are measured
        # independently and toy geometries are dispatch-overhead-
        # dominated — the FRACTION is clamped to [0, 1] instead.
        and att["step_s"] > 0
        and 0 <= att["exchange_fraction"] <= 1
        and att["model_bytes_per_iter"] == cm.get("bytes_per_iter")
        and att["achieved_bytes_per_sec"] > 0
        and gauges.get("comms.exchange_fraction")
        == att["exchange_fraction"]
        and "comms.achieved_bytes_per_sec" in gauges
    )
    sampled_ids = sorted(
        int(k.split(".")[1]) for k in gauges
        if k.startswith("device.") and k.endswith(".bytes_in_use")
    )
    try:
        assert_prometheus_syntax(obs_live.render_prometheus())
        prom_ok = True
    except AssertionError:
        prom_ok = False
    sampler_ok = bool(
        sampled_ids == list(range(ndev))
        and sampler is not None
        and sampler.samples >= iters // 2
        and prom_ok
    )
    fit_ok = obs_devices.fit_check(spec["fit_ok_scale"])
    fit_bad = obs_devices.fit_check(spec["fit_bad_scale"])
    fit_verdicts_ok = bool(fit_ok.fits and not fit_bad.fits)

    passed = bool(att_ok and sampler_ok and fit_verdicts_ok
                  and t_run <= DEVICES_SMOKE_BUDGET_S)
    rec = {
        "config": key,
        "kind": "devices",
        "label": spec["label"],
        "scale": scale,
        "iters": iters,
        "devices": ndev,
        "attribution": {k: att.get(k) for k in (
            "exchange_s", "step_s", "exchange_fraction",
            "achieved_bytes_per_sec", "mode")} if att else None,
        "attribution_ok": att_ok,
        "sampler_ok": sampler_ok,
        "sampled_devices": sampled_ids,
        "fit_ok_scale": spec["fit_ok_scale"],
        "fit_bad_scale": spec["fit_bad_scale"],
        "fit_verdicts_ok": fit_verdicts_ok,
        "seconds": t_run,
        "budget_s": DEVICES_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] attributed halo solve on {ndev} fake devices (scale "
        f"{scale}, {iters} iters): attribution "
        f"{'OK' if att_ok else 'BAD'}"
        + (f" (exchange {att['exchange_fraction']:.0%} of step)"
           if att else "")
        + f"; sampler {'OK' if sampler_ok else 'BAD'} "
        f"(devices {sampled_ids}, exporter "
        f"{'parses' if prom_ok else 'BROKEN'}); fit scale "
        f"{spec['fit_ok_scale']} {'fits' if fit_ok.fits else 'REFUSED'} "
        f"/ scale {spec['fit_bad_scale']} "
        f"{'refused' if not fit_bad.fits else 'ACCEPTED (BAD)'}; "
        f"{t_run:.2f}s vs budget {DEVICES_SMOKE_BUDGET_S:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the compiler-plane smoke (seconds, timed around the two
# in-process `obs hlo` form inspections — interpreter/jax import is
# paid by the acceptance process already): building + AOT-lowering two
# scale-14 dispatch forms on CPU plus the text parse is well under a
# second each; 2s is the ISSUE-11 acceptance bound and still catches
# an accidentally-eager harvest (e.g. a per-iteration inspector call).
HLO_SMOKE_BUDGET_S = 2.0


def run_hlo_smoke(key: str):
    """ISSUE-11 gate: the compiler plane end to end — `python -m
    pagerank_tpu.obs hlo` over the default + partitioned dispatch
    forms at scale 14 must classify the gather lowering of EACH form
    (the "did XLA keep the fast gather" verdict a TPU session reads
    before spending chip time), the emitted JSON must strict-parse
    with a per-form strategy + structural fingerprint, the exit code
    must be 0 (no EXPANDED verdict anywhere), and the whole inspection
    must land under HLO_SMOKE_BUDGET_S."""
    import contextlib
    import io

    from pagerank_tpu import obs
    from pagerank_tpu.obs import hlo as hlo_mod
    from pagerank_tpu.obs.__main__ import main as obs_main

    spec = CONFIGS[key]
    scale, forms = spec["scale"], spec["forms"]
    obs.get_registry().reset()
    hlo_mod.reset()
    buf = io.StringIO()
    t0 = time.perf_counter()
    with contextlib.redirect_stdout(buf):
        rc = obs_main(["hlo", "--form", forms, "--scale", str(scale),
                       "--json"])
    t_run = time.perf_counter() - t0
    hlo_mod.reset()

    strategies, json_ok = {}, False
    try:
        doc = json.loads(buf.getvalue(), parse_constant=lambda c: (
            (_ for _ in ()).throw(ValueError(f"non-strict constant {c}"))
        ))
        json_ok = set(doc) == set(forms.split(","))
        for form, snapshot in doc.items():
            whole = snapshot.get("step") or snapshot.get("final") or {}
            strategies[form] = (whole.get("gather") or {}).get("strategy")
    except ValueError:
        pass
    classified = bool(strategies) and all(
        s in ("native", "expanded", "none") for s in strategies.values()
    )
    # The standing expectation on every current form, not just
    # not-EXPANDED: the hot traffic must actually be a native gather.
    native = bool(strategies) and all(
        s == "native" for s in strategies.values())

    passed = bool(rc == 0 and json_ok and classified and native
                  and t_run <= HLO_SMOKE_BUDGET_S)
    rec = {
        "config": key,
        "kind": "hlo",
        "label": spec["label"],
        "scale": scale,
        "forms": forms,
        "exit_code": rc,
        "strict_json": json_ok,
        "gather_strategies": strategies,
        "seconds": t_run,
        "budget_s": HLO_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] obs hlo over {forms} at scale {scale}: rc {rc}, "
        f"strict JSON {'OK' if json_ok else 'BAD'}, verdicts "
        + (", ".join(f"{f}={s}" for f, s in strategies.items())
           if strategies else "NONE")
        + f"; {t_run:.2f}s vs budget {HLO_SMOKE_BUDGET_S:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the data-plane smoke (seconds): a scale-14 host build +
# numpy profile + an 8-fake-device vertex-sharded probed solve (3
# iterations) lands well under 2s warm on the CPU substrate; the
# in-process form times exactly the `obs graph` work (the subprocess
# fallback for non-CPU backends pays jax import on top — its budget
# adds the documented interpreter grace).
GRAPH_SMOKE_BUDGET_S = 2.0
GRAPH_SMOKE_SUBPROC_GRACE_S = 20.0
# Predicted-vs-measured per-device skew agreement gate (relative).
GRAPH_SKEW_GATE = 0.10


def run_graph_smoke(key: str):
    """ISSUE-13 gate: the data plane end to end — `python -m
    pagerank_tpu.obs graph --scale 14 --ndev 8` must emit strict JSON
    whose rank-mass LEDGER reconciles at the f32 gate over every
    probed iteration, whose predicted per-device straggler skew agrees
    with the MEASURED per-device edge counts of the 8-fake-device mesh
    within GRAPH_SKEW_GATE, and land under GRAPH_SMOKE_BUDGET_S. Runs
    in-process on a multi-device CPU backend; otherwise re-invokes in
    a subprocess with the fake-device flags (the L/M discipline)."""
    import jax

    spec = CONFIGS[key]
    scale, ndev, iters = spec["scale"], spec["ndev"], spec["iters"]
    argv = ["graph", "--scale", str(scale), "--ndev", str(ndev),
            "--iters", str(iters), "--json"]
    in_process = (jax.default_backend() == "cpu"
                  and len(jax.devices()) >= ndev)
    budget = GRAPH_SMOKE_BUDGET_S
    if in_process:
        import contextlib
        import io

        from pagerank_tpu import obs
        from pagerank_tpu.obs.__main__ import main as obs_main

        obs.get_registry().reset()
        obs.graph_profile.reset()
        buf = io.StringIO()
        t0 = time.perf_counter()
        with contextlib.redirect_stdout(buf):
            rc = obs_main(argv)
        t_run = time.perf_counter() - t0
        out_text = buf.getvalue()
        obs.graph_profile.reset()
    else:
        import subprocess

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={ndev}"
        ).strip()
        env["PYTHONPATH"] = REPO
        budget += GRAPH_SMOKE_SUBPROC_GRACE_S
        t0 = time.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "pagerank_tpu.obs", *argv],
            capture_output=True, text=True, env=env, timeout=600,
        )
        t_run = time.perf_counter() - t0
        rc, out_text = r.returncode, r.stdout

    doc, json_ok = {}, False
    try:
        doc = json.loads(out_text, parse_constant=lambda c: (
            (_ for _ in ()).throw(ValueError(f"non-strict constant {c}"))
        ))
        json_ok = {"profile", "prediction", "measured",
                   "ledger"} <= set(doc)
    except ValueError:
        pass
    ledger = (doc.get("ledger") or {})
    ledger_ok = bool(ledger.get("ok")) and \
        ledger.get("entries", 0) >= iters
    pred = (doc.get("prediction") or {}).get("predicted_straggler_skew")
    meas = (doc.get("measured") or {}).get("straggler_skew")
    skew_rel_err = (abs(pred - meas) / meas
                    if isinstance(pred, (int, float))
                    and isinstance(meas, (int, float)) and meas else None)
    skew_ok = skew_rel_err is not None and skew_rel_err <= GRAPH_SKEW_GATE

    passed = bool(rc == 0 and json_ok and ledger_ok and skew_ok
                  and t_run <= budget)
    rec = {
        "config": key,
        "kind": "graph",
        "label": spec["label"],
        "scale": scale,
        "ndev": ndev,
        "exit_code": rc,
        "strict_json": json_ok,
        "ledger_ok": ledger_ok,
        "ledger_max_abs_residual": ledger.get("max_abs_residual"),
        "predicted_skew": pred,
        "measured_skew": meas,
        "skew_rel_err": skew_rel_err,
        "skew_gate": GRAPH_SKEW_GATE,
        "in_process": in_process,
        "seconds": t_run,
        "budget_s": budget,
        "passed": passed,
    }
    print(
        f"[{key}] obs graph scale {scale} x{ndev}dev: rc {rc}, strict "
        f"JSON {'OK' if json_ok else 'BAD'}, ledger "
        f"{'OK' if ledger_ok else 'VIOLATED'}"
        + (f" (max |resid| {ledger['max_abs_residual']:.2e})"
           if isinstance(ledger.get("max_abs_residual"), float) else "")
        + f", skew pred {pred} vs measured {meas}"
        + (f" ({skew_rel_err:.1%} vs {GRAPH_SKEW_GATE:.0%} gate)"
           if skew_rel_err is not None else " (UNMEASURED)")
        + f"; {t_run:.2f}s vs budget {budget:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the concurrency-plane smoke (seconds): the whole-package
# PTR pass (parse + call graph + contexts + six rules) measures ~1.5s
# nominal on the CPU test substrate — the <2s pre-gate latency target
# (ISSUE 14) — and 3s absorbs a loaded host (the R/L/M convention)
# while still catching an order-of-magnitude pass regression.
CONCURRENCY_SMOKE_BUDGET_S = 3.0


def run_concurrency_smoke(key: str):
    """ISSUE-14 gate: the PTR thread/signal-context race pass
    (analysis/concurrency.py) over the shipped package. Gates: ZERO
    unwaived PTR findings against the checked-in allowlist, every
    known thread root discovered WITH its label (a silently vanished
    root would gut PTR001's context inference), the GracefulDrain
    signal-handler root discovered through the shared
    analysis/roots.py source of truth, and the whole pass under
    CONCURRENCY_SMOKE_BUDGET_S."""
    from pagerank_tpu.analysis import concurrency as conc_mod
    from pagerank_tpu.analysis import load_allowlist, split_allowlisted
    from pagerank_tpu.analysis.lint import package_root

    spec = CONFIGS[key]
    t0 = time.perf_counter()
    prog = conc_mod.build_package_program()
    findings = conc_mod.analyze_program(prog)
    allow = os.path.join(package_root(), "analysis", "allowlist.txt")
    active, waived = split_allowlisted(findings, load_allowlist(allow))
    t_run = time.perf_counter() - t0

    labels = {ts.label for ts in prog.thread_sites}
    expected_roots = {
        "rank-writer", "pagerank-stall-watchdog", "pagerank-metrics-http",
        "pagerank-deadline-dispatch", "pagerank-liveness-probe",
    }
    missing_roots = sorted(expected_roots - labels)
    signal_ok = any(r == "jobs.py::GracefulDrain._handler"
                    for _label, r in prog.signal_roots)
    ptr_waived = sum(1 for f, _w in waived if f.rule.startswith("PTR"))
    passed = bool(
        not active and not missing_roots and signal_ok
        and t_run <= CONCURRENCY_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "concurrency",
        "label": spec["label"],
        "active_findings": [f.render() for f in active],
        "ptr_waived": ptr_waived,
        "thread_roots": sorted(labels),
        "missing_roots": missing_roots,
        "signal_root_ok": signal_ok,
        "seconds": t_run,
        "budget_s": CONCURRENCY_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] PTR race pass in {t_run:.2f}s vs budget "
        f"{CONCURRENCY_SMOKE_BUDGET_S:g}s; {len(active)} unwaived / "
        f"{ptr_waived} waived PTR finding(s); roots "
        f"{'complete' if not missing_roots else 'MISSING ' + repr(missing_roots)}; "
        f"signal root {'OK' if signal_ok else 'MISSING'} -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the kernel-plane smoke (seconds): abstract tracing of
# both shipped Pallas kernels at the toy + bench geometries plus the
# six defect fixtures is ~0.6s on the CPU test substrate (the numpy
# index-map interpreter keeps the full-grid evaluation off the
# compiler); 2s absorbs a loaded host while catching an
# accidentally-compiling evaluation path.
KERNELS_SMOKE_BUDGET_S = 2.0

#: Seeded defect fixture -> the ONE PTK rule it must trip (and no
#: other rule may fire on it).
KERNELS_FIXTURE_RULES = {
    "fixture:vmem_overflow": "PTK001",
    "fixture:misaligned_tile": "PTK002",
    "fixture:index_gap": "PTK003",
    "fixture:index_overlap": "PTK003",
    "fixture:f64_scratch": "PTK004",
    "fixture:cost_mismatch": "PTK005",
}


def run_kernels_smoke(key: str):
    """ISSUE-16 gate: the PTK kernel-plane static pass
    (analysis/kernels.py). Gates: ZERO unwaived findings over the
    shipped registry against the checked-in allowlist (the legacy
    whole-z VMEM entries waive with their documented geometry bound,
    and ONLY those), every seeded-defect fixture trips exactly its
    rule, and the whole pass under KERNELS_SMOKE_BUDGET_S. Abstract
    tracing only — no TPU, nothing executes."""
    from pagerank_tpu.analysis import kernels as kernels_mod
    from pagerank_tpu.analysis import load_allowlist, split_allowlisted
    from pagerank_tpu.analysis.lint import package_root

    spec = CONFIGS[key]
    t0 = time.perf_counter()
    findings = kernels_mod.check_kernel_plane()
    allow = os.path.join(package_root(), "analysis", "allowlist.txt")
    active, waived = split_allowlisted(findings, load_allowlist(allow))
    fixture_bad = {}
    for case in kernels_mod.defect_cases():
        rules = sorted({f.rule for f in
                        kernels_mod.check_kernel_case(case)})
        want = KERNELS_FIXTURE_RULES[case.label]
        if rules != [want]:
            fixture_bad[case.label] = rules
    t_run = time.perf_counter() - t0

    ptk_waived = sum(1 for f, _w in waived if f.rule.startswith("PTK"))
    passed = bool(
        not active and not fixture_bad
        and ptk_waived == len(kernels_mod.BENCH_SCALES)
        and t_run <= KERNELS_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "kernels",
        "label": spec["label"],
        "active_findings": [f.render() for f in active],
        "ptk_waived": ptk_waived,
        "fixtures_checked": len(KERNELS_FIXTURE_RULES),
        "fixture_mismatches": fixture_bad,
        "seconds": t_run,
        "budget_s": KERNELS_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] PTK kernel pass in {t_run:.2f}s vs budget "
        f"{KERNELS_SMOKE_BUDGET_S:g}s; {len(active)} unwaived / "
        f"{ptk_waived} waived finding(s); fixtures "
        f"{'all trip' if not fixture_bad else 'BAD ' + repr(fixture_bad)}"
        f" -> {'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


# Budget for the preemption smoke (seconds, measured around the
# SIGTERM'd run + the resumed run — NOT the f64 oracle pass): two
# 1024-vertex cpu-engine solves, a drain, and artifact save/restore
# are well under a second; 3s absorbs a loaded host while catching a
# drain that blocks on its deadline or a resume that recomputes the
# world.
R_SMOKE_BUDGET_S = 3.0


def run_jobs_smoke(key: str):
    """ISSUE-12 gate: the preemption lifecycle end to end, in-process
    (the SIGTERM is self-delivered by the seeded ProcessKillPlan at an
    exact solve iteration, so the whole drain->resume cycle is
    deterministic and fits the budget). Gates: the killed run returns
    ExitCode.INTERRUPTED with an interrupted-marked manifest, the
    resumed run returns 0 having SKIPPED the graph stages (durable
    artifacts) and warm-started the solve, the final ranks match the
    f64 CPU oracle at the standing f32 gate, the resumed run report
    carries job.resumes == 1, and both runs land under
    R_SMOKE_BUDGET_S."""
    import shutil
    import tempfile

    from pagerank_tpu import (PageRankConfig, ReferenceCpuEngine,
                              build_graph)
    from pagerank_tpu.cli import main as cli_main
    from pagerank_tpu.exitcodes import ExitCode
    from pagerank_tpu.testing.faults import ProcessKillPlan
    from pagerank_tpu.utils import synth

    spec = CONFIGS[key]
    scale, iters, kill_iter = spec["scale"], spec["iters"], spec["kill_iter"]
    work = tempfile.mkdtemp(prefix="pagerank_jobs_")
    job_dir = os.path.join(work, "job")
    out_path = os.path.join(work, "ranks.tsv")
    report_path = os.path.join(work, "run_report.json")
    argv = ["--synthetic", f"rmat:{scale}", "--engine", "cpu",
            "--iters", str(iters), "--job-dir", job_dir,
            "--out", out_path, "--log-every", "0"]
    plan_env = ProcessKillPlan(
        "solve", iteration=kill_iter, signum=signal.SIGTERM).to_env()
    t0 = time.perf_counter()
    try:
        os.environ.update(plan_env)
        try:
            rc_kill = cli_main(argv)
        finally:
            for k in plan_env:
                os.environ.pop(k, None)
        with open(os.path.join(job_dir, "job.json")) as f:
            man_killed = json.load(f)
        with warnings.catch_warnings():
            # The resumed run's solve-artifact miss warns by design.
            warnings.simplefilter("ignore", RuntimeWarning)
            rc_resume = cli_main(argv + ["--run-report", report_path])
        t_run = time.perf_counter() - t0
        with open(report_path) as f:
            report = json.load(f)
        n = 1 << scale
        got = np.zeros(n)
        with open(out_path) as f:
            for line in f:
                k, v = line.split("\t")
                got[int(k)] = float(v)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    src, dst = synth.rmat_edges(scale)
    g = build_graph(src, dst, n=n)
    oracle = ReferenceCpuEngine(
        PageRankConfig(num_iters=iters, dtype="float64",
                       accum_dtype="float64")).build(g).run()
    l1 = float(np.abs(got - oracle).sum() / np.abs(oracle).sum())

    jb = report.get("job") or {}
    stages = jb.get("stages") or {}
    drained = (rc_kill == int(ExitCode.INTERRUPTED)
               and man_killed.get("status") == "interrupted")
    resumed_ok = (rc_resume == 0 and jb.get("resumes") == 1
                  and jb.get("status") == "complete"
                  and (stages.get("build") or {}).get("skipped") is True
                  and (stages.get("solve") or {}).get("skipped") is False)
    passed = bool(drained and resumed_ok and l1 <= ELASTIC_F32_GATE
                  and t_run <= R_SMOKE_BUDGET_S)
    rec = {
        "config": key,
        "kind": "jobs",
        "label": spec["label"],
        "scale": scale,
        "iters": iters,
        "kill_iter": kill_iter,
        "kill_exit_code": rc_kill,
        "resume_exit_code": rc_resume,
        "drained": drained,
        "job_resumes": jb.get("resumes"),
        "stages_skipped": sorted(s for s, r in stages.items()
                                 if r.get("skipped")),
        "accuracy_l1": l1,
        "seconds": t_run,
        "budget_s": R_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] SIGTERM at solve iter {kill_iter}: exit {rc_kill} "
        f"({'drained' if drained else 'NOT DRAINED'}); resume exit "
        f"{rc_resume}, resumes={jb.get('resumes')}, skipped "
        f"{','.join(rec['stages_skipped']) or 'none'}; oracle L1 "
        f"{l1:.2e} vs {ELASTIC_F32_GATE:g}; {t_run:.2f}s vs budget "
        f"{R_SMOKE_BUDGET_S:g}s -> {'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


def run_campaign_smoke(key: str):
    """ISSUE-20 gate: the whole measurement campaign, dry. One
    `python -m pagerank_tpu.obs campaign run --fake-devices 8`
    subprocess (real child, so the fake-device XLA flags never touch
    this process's backend) must complete every leg of the smoke
    profile inside its per-leg wall budget. Gates: exit 0 with a
    complete strict-JSON report.json (canonical form, constants
    rejected), all five typed verdicts present and NON-binding with
    decision "defer", one decision-ledger entry per verdict, every
    leg done within budget, `campaign report --json` re-rendering
    byte-identical to the durable report.json, and the wall under
    CAMPAIGN_SMOKE_BUDGET_S."""
    import shutil
    import tempfile

    from pagerank_tpu.obs import campaign as campaign_mod
    from pagerank_tpu.testing.faults import run_job_subprocess

    spec = CONFIGS[key]
    work = tempfile.mkdtemp(prefix="pagerank_campaign_")
    reject = lambda c: (_ for _ in ()).throw(  # noqa: E731
        ValueError(f"non-finite constant {c} in campaign report"))
    t0 = time.perf_counter()
    try:
        proc = run_job_subprocess(
            ["campaign", "run", "--campaign-dir", work,
             "--fake-devices", "8", "--json"],
            module="pagerank_tpu.obs",
            timeout=CAMPAIGN_SMOKE_BUDGET_S + 120.0)
        t_run = time.perf_counter() - t0
        report_raw = b""
        report = {}
        report_path = os.path.join(work, "report.json")
        if os.path.exists(report_path):
            with open(report_path, "rb") as f:
                report_raw = f.read()
            report = json.loads(report_raw, parse_constant=reject)
        rerender = run_job_subprocess(
            ["campaign", "report", "--campaign-dir", work, "--json"],
            module="pagerank_tpu.obs", timeout=120.0)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    expected = set(campaign_mod.VERDICTS)
    verdicts = report.get("verdicts") or {}
    legs = report.get("legs") or []
    nonbinding = (report.get("binding") is False
                  and report.get("fake_devices") == 8
                  and all(v.get("binding") is False
                          and v.get("decision") == "defer"
                          for v in verdicts.values()))
    legs_ok = bool(legs) and all(
        leg.get("status") == "done" and leg.get("within_budget")
        for leg in legs)
    ledger = report.get("decision_ledger") or []
    rerender_ok = (rerender.returncode == 0
                   and rerender.stdout.encode() == report_raw)
    passed = bool(proc.returncode == 0 and report.get("complete")
                  and set(verdicts) == expected and nonbinding
                  and legs_ok and len(ledger) == len(expected)
                  and rerender_ok
                  and t_run <= CAMPAIGN_SMOKE_BUDGET_S)
    rec = {
        "config": key,
        "kind": "campaign",
        "label": spec["label"],
        "exit_code": proc.returncode,
        "complete": bool(report.get("complete")),
        "legs_done": sum(1 for leg in legs
                         if leg.get("status") == "done"),
        "legs_total": len(legs),
        "verdicts": sorted(verdicts),
        "all_nonbinding_defer": nonbinding,
        "ledger_entries": len(ledger),
        "report_rerender_identical": rerender_ok,
        "seconds": t_run,
        "budget_s": CAMPAIGN_SMOKE_BUDGET_S,
        "passed": passed,
    }
    if not passed and proc.stderr:
        rec["stderr_tail"] = proc.stderr[-2000:]
    verdict_note = ("all defer/non-binding" if nonbinding
                    else "BINDING OR NON-DEFER")
    print(
        f"[{key}] campaign dry run: exit {proc.returncode}, "
        f"{rec['legs_done']}/{rec['legs_total']} legs done, "
        f"{len(verdicts)}/{len(expected)} verdicts ({verdict_note}), "
        f"ledger {len(ledger)} entries, re-render "
        f"{'identical' if rerender_ok else 'DIVERGED'}; "
        f"{t_run:.1f}s vs budget {CAMPAIGN_SMOKE_BUDGET_S:g}s -> "
        f"{'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


def run_partitioned_smoke(key: str):
    """ISSUE-6 gate: a short solve on the partition-centric layout —
    the jax engine through the CLI with an explicit --partition-span
    and --probe-every — plus the contract-coverage assertion. Gates:
    the CLI exits 0 with probe records at the exact cadence, the
    contract sweep LISTS the partitioned dispatch forms, those forms'
    contracts (collective budget, probe transparency PTC007, donation,
    f64) come back clean when this process is on the CPU backend (on
    a TPU the sweep's fake mesh would fight the live backend — tier-1
    covers it there), and the wall stays under the budget."""
    import shutil
    import tempfile

    import jax

    from pagerank_tpu.analysis.contracts import engine_forms, run_contracts
    from pagerank_tpu.cli import main as cli_main

    spec = CONFIGS[key]
    iters, every, span = spec["iters"], spec["probe_every"], spec["span"]
    work = tempfile.mkdtemp(prefix="pagerank_part_")
    t0 = time.perf_counter()
    try:
        report_path = os.path.join(work, "run_report.json")
        rc = cli_main([
            "--synthetic", "uniform:2048:65536",
            "--iters", str(iters), "--log-every", "0",
            "--partition-span", str(span),
            "--probe-every", str(every), "--probe-topk", "16",
            "--run-report", report_path,
        ])
        with open(report_path) as f:
            report = json.load(f)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    want_iters = [i for i in range(iters) if (i + 1) % every == 0]
    probes = report.get("probes") or []
    probes_ok = [r.get("iteration") for r in probes] == want_iters

    part_forms = ["partitioned", "partitioned_bf16",
                  "device_build_partitioned"]
    names = [f.name for f in engine_forms(1)]
    covered = all(f in names for f in part_forms)
    findings = []
    contracts_ran = jax.default_backend() == "cpu"
    if contracts_ran and covered:
        findings = run_contracts(forms=part_forms)
    t_run = time.perf_counter() - t0

    passed = bool(
        rc == 0 and probes_ok and covered and not findings
        and t_run <= PARTITIONED_SMOKE_BUDGET_S
    )
    rec = {
        "config": key,
        "kind": "partitioned",
        "label": spec["label"],
        "iters": iters,
        "partition_span": span,
        "probe_records_ok": probes_ok,
        "contract_forms_covered": covered,
        "contracts_ran": contracts_ran,
        "contract_findings": [str(f) for f in findings],
        "seconds": t_run,
        "budget_s": PARTITIONED_SMOKE_BUDGET_S,
        "passed": passed,
    }
    print(
        f"[{key}] partitioned solve (span {span}, probe every {every}) "
        f"in {t_run:.1f}s vs budget {PARTITIONED_SMOKE_BUDGET_S:g}s; "
        f"probes {'OK' if probes_ok else 'BAD'}; contract sweep "
        f"{'covers' if covered else 'MISSING'} the partitioned forms"
        f"{' (' + str(len(findings)) + ' finding(s))' if findings else ''}"
        f" -> {'PASS' if passed else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


def run_ppr(key: str):
    """Config-5 standing gate: device batched-SpMM PPR vs the f64 CPU
    oracle — per-source top-k id overlap and top-k score L1."""
    from pagerank_tpu import PageRankConfig
    from pagerank_tpu.engines.ppr import PprJaxEngine, ppr_cpu

    spec = CONFIGS[key]
    scale, iters = spec["scale"], spec["iters"]
    n_sources, topk = spec["sources"], spec["topk"]
    g = _make_graph(key, scale)
    rng = np.random.default_rng(17)
    sources = rng.choice(g.n, size=n_sources, replace=False)

    cfg = PageRankConfig(num_iters=iters, dtype="float32",
                         accum_dtype="float32")
    t0 = time.perf_counter()
    eng = PprJaxEngine(cfg).build(g)
    t_dev_build = time.perf_counter() - t0
    chips = eng._mesh.devices.size
    # One chunk-sized warm-up run so the timed window excludes the
    # chunk executable's compile (the A/B/C/T configs do the same with
    # a throwaway step). ONE chunk constant: warm-up and timed run must
    # compile the same shapes or the timed window silently pays compile.
    chunk = 64
    # A ragged tail would compile a second (tail-shaped) executable
    # inside the timed window; the warm-up covers exactly one shape
    # (min(n_sources, chunk) wide), so the config must not mix shapes.
    assert n_sources % chunk == 0 or n_sources < chunk, (n_sources, chunk)
    eng.run(sources[:chunk], topk=topk, chunk=chunk)
    # Accuracy columns from the engine's public run (untimed).
    res = eng.run(sources, topk=topk, chunk=chunk)

    # Rate column from a PIPELINED device-only loop (VERDICT r3 weak
    # #4): eng.run()'s wall-clock includes per-chunk HOST work (the
    # [n_state, chunk] one-hot build + transfer + top-k fetch), which
    # on a loaded 1-core host dominated the window and made the column
    # swing 4.32e8-1.95e9 across runs. Here every source chunk is
    # staged on device FIRST, the timed loop only dispatches the jitted
    # chunk executable + device top-k (async, pipelined), and one
    # honest scalar fetch fences the tail — same protocol as bench.py.
    import jax as _jax
    import jax.numpy as _jnp
    from pagerank_tpu.parallel.mesh import replicated as _replicated

    rep = _replicated(eng._mesh)
    inv_perm = eng._inv_perm
    p_chunks = []
    for lo in range(0, n_sources, chunk):
        batch = sources[lo : lo + chunk]
        p = np.zeros((eng._n_state, len(batch)), dtype=np.float32)
        p[inv_perm[batch], np.arange(len(batch))] = 1.0
        p_chunks.append(_jax.device_put(_jnp.asarray(p), rep))
    t0 = time.perf_counter()
    tails = []
    for p_dev in p_chunks:
        r = eng._run_chunk(
            p_dev.copy(), p_dev, iters, eng._inv_out, eng._dangling,
            eng._valid, *eng._slot_args,
        )
        tails.append(eng._topk(r, topk))
    _jax.device_get(tails[-1][1][0, 0])  # honest fence (in-order queue)
    t_run = time.perf_counter() - t0

    t0 = time.perf_counter()
    r_full = ppr_cpu(g, sources, num_iters=iters, damping=cfg.damping)
    t_oracle = time.perf_counter() - t0

    # Acceptable-membership overlap (see PPR_TIE_EPS comment) + sorted
    # top-k oracle scores for the L1 column. One O(n) argpartition per
    # column — a full-column sort (or negated copies of the [n, s]
    # oracle, ~2 GB each at the default config) is never materialized.
    cols = np.arange(n_sources)
    part = np.argpartition(r_full, g.n - topk, axis=0)[g.n - topk:]  # [k, s]
    top_scores = np.take_along_axis(r_full, part, axis=0)  # [k, s] unsorted
    kth = top_scores.min(axis=0)  # [s] k-th largest per source
    dev_scores_true = r_full[res.topk_ids, cols[:, None]]  # [s, k]
    overlaps = (dev_scores_true >= (kth[:, None] - PPR_TIE_EPS)).mean(axis=1)
    oracle_topk = np.sort(top_scores, axis=0)[::-1].T  # [s, k] descending
    score_l1 = np.abs(
        res.topk_scores.astype(np.float64) - oracle_topk
    ).sum(axis=1)
    rate = g.num_edges * n_sources * iters / t_run / chips
    rec = {
        "config": key,
        "kind": "ppr",
        "label": spec["label"],
        "scale": scale,
        "iters": iters,
        "sources": n_sources,
        "topk": topk,
        "num_edges": int(g.num_edges),
        "min_topk_overlap": float(overlaps.min()),
        "mean_topk_overlap": float(overlaps.mean()),
        "max_score_l1": float(score_l1.max()),
        "overlap_gate": PPR_OVERLAP_GATE,
        "score_l1_gate": PPR_SCORE_L1_GATE,
        "passed": bool(
            overlaps.min() >= PPR_OVERLAP_GATE
            and score_l1.max() <= PPR_SCORE_L1_GATE
        ),
        "tpu_seconds": t_run,
        "edge_vectors_per_sec_per_chip": rate,
        # The rate window is the staged device-only pipelined loop (no
        # per-chunk host work) — see the comment at the timed loop.
        "rate_protocol": "pipelined-device",
    }
    print(
        f"[{key}] {n_sources} sources x {iters} iters, top-{topk} in "
        f"{t_run:.2f}s (device build {t_dev_build:.1f}s, oracle "
        f"{t_oracle:.1f}s): overlap min {overlaps.min():.4f} / mean "
        f"{overlaps.mean():.4f} (gate {PPR_OVERLAP_GATE}), max score L1 "
        f"{score_l1.max():.3e} (gate {PPR_SCORE_L1_GATE:g}) -> "
        f"{'PASS' if rec['passed'] else 'FAIL'}; {rate:.3g} "
        f"edge-vectors/s/chip",
        file=sys.stderr,
    )
    return rec


def _gen_segment(d: str, files: int, per_file: int, seed: int = 23) -> float:
    """Synthetic Common-Crawl-style metadata segment: ``files``
    SequenceFiles named ``metadata-%05d`` (the reference's segment
    naming, Sparky.java:47-56), each holding ``per_file`` (url,
    json-metadata) Text records with anchor links. ~8% of pages are
    linkless (the reference's dangling-sentinel case, Sparky.java:114),
    ~15% of link targets are never-crawled urls (the post-repair
    dangling set, SURVEY §2a.3). Returns generation wall-clock."""
    import json as _json

    from pagerank_tpu.ingest.seqfile import write_sequence_file

    rng = np.random.default_rng(seed)
    n_crawled = files * per_file

    def url(i: int) -> str:
        return f"http://site{i % 997}.test/p{i}"

    t0 = time.perf_counter()
    for fi in range(files):
        pairs = []
        base = fi * per_file
        for ri in range(per_file):
            u = url(base + ri)
            links = []
            if rng.random() >= 0.08:
                for t in rng.integers(0, n_crawled, rng.integers(3, 13)):
                    links.append(
                        f"http://uncrawled{int(t)}.test/"
                        if rng.random() < 0.15 else url(int(t))
                    )
            pairs.append((u, _json.dumps(
                {"url": u, "content": {"links": [
                    {"type": "a", "href": l} for l in links
                ]}}
            )))
        write_sequence_file(os.path.join(d, f"metadata-{fi:05d}"), pairs)
    return time.perf_counter() - t0


def run_e2e(key: str):
    """The reference's literal job end to end, timed in its layer
    split: L1 segment parse (native C++), L2 host graph build, L3
    engine build + 10 reference-semantics iterations on the TPU, L4
    per-iteration Spark-format text dumps — the exact materialization
    structure of Sparky.java:187-238 (the dump inside the loop forces
    every iterate, SURVEY §3.3). Gated on the f64 CPU oracle."""
    import shutil
    import tempfile

    from pagerank_tpu import (JaxTpuEngine, PageRankConfig,
                              ReferenceCpuEngine, build_graph)
    from pagerank_tpu.ingest import load_crawl_seqfile_arrays
    from pagerank_tpu.models.pagerank import initial_rank
    from pagerank_tpu.utils.metrics import oracle_l1
    from pagerank_tpu.utils.snapshot import AsyncRankWriter, TextDumper

    spec = CONFIGS[key]
    files, per_file, iters = spec["files"], spec["records"], spec["iters"]
    work = tempfile.mkdtemp(prefix="pagerank_e2e_")
    try:
        seg = os.path.join(work, "segment")
        os.makedirs(seg)
        t_gen = _gen_segment(seg, files, per_file)

        t0 = time.perf_counter()
        src, dst, crawled, ids = load_crawl_seqfile_arrays(seg)
        t_l1 = time.perf_counter() - t0

        t0 = time.perf_counter()
        g = build_graph(src, dst, n=len(ids), dangling_mask=~crawled)
        t_l2 = time.perf_counter() - t0

        cfg = PageRankConfig(
            num_iters=iters, dtype="float64", accum_dtype="float64",
            wide_accum="pair",
        )
        t0 = time.perf_counter()
        eng = JaxTpuEngine(cfg).build(g)
        t_eng_build = time.perf_counter() - t0
        # Compile outside the timed window (run_one pattern), restore r0.
        eng.step()
        eng.fence()
        eng.set_ranks(initial_rank(g.n, "reference", np.float64, np),
                      iteration=0)

        # L4 rides the framework's own async path (VERDICT r4 weak #1):
        # the worker thread decodes a device-side rank copy and writes
        # the dump through the native bulk formatter while the next
        # step computes (utils/snapshot.AsyncRankWriter — C17's build
        # target, unlike the reference's synchronous saveAsTextFile
        # barrier, Sparky.java:237). Timing: t_solve is the fenced
        # per-step device time (run_one protocol); t_l4 is the EXPOSED
        # L4 wall — everything the loop + final flush spent beyond the
        # solve — and t_dump_work is the worker's time inside dump()
        # (formatter + file write), reported as lines/s.
        dumper = TextDumper(os.path.join(work, "out"), names=ids.names)
        t_dump_work = [0.0]

        def dump_sink(i, ranks):
            t0 = time.perf_counter()
            dumper.dump(i, ranks)
            t_dump_work[0] += time.perf_counter() - t0

        t_solve = 0.0
        t_loop0 = time.perf_counter()
        with AsyncRankWriter(eng.decode_ranks, [dump_sink]) as writer:
            for it in range(iters):
                t0 = time.perf_counter()
                eng._device_step()
                eng.fence()
                t_solve += time.perf_counter() - t0
                writer.submit(it, eng.device_ranks())
        t_l4 = time.perf_counter() - t_loop0 - t_solve
        r_tpu = eng.ranks()

        # The dump directories must have the reference's output shape:
        # PageRank{i}/part-00000 + _SUCCESS, one line per vertex.
        for it in range(iters):
            d = os.path.join(work, "out", f"PageRank{it}")
            assert os.path.exists(os.path.join(d, "_SUCCESS")), d
            part = os.path.join(d, "part-00000")
            assert os.path.exists(part), d
        with open(os.path.join(work, "out", f"PageRank{iters - 1}",
                               "part-00000")) as f:
            dump_lines = sum(1 for _ in f)
        assert dump_lines == g.n, (dump_lines, g.n)

        t0 = time.perf_counter()
        r_cpu = ReferenceCpuEngine(
            PageRankConfig(num_iters=iters, dtype="float64",
                           accum_dtype="float64")
        ).build(g).run()
        t_oracle = time.perf_counter() - t0
    finally:
        shutil.rmtree(work, ignore_errors=True)

    _, norm, mass_norm = oracle_l1(r_tpu, r_cpu)
    rec = {
        "config": key,
        "kind": "e2e",
        "label": spec["label"],
        "files": files,
        "records": files * per_file,
        "n": int(g.n),
        "num_edges": int(g.num_edges),
        "iters": iters,
        "normalized_l1": norm,
        "mass_normalized_l1": mass_norm,
        "gate": GATE,
        "passed": bool(norm <= GATE and mass_norm <= GATE),
        "l1_parse_s": t_l1,
        "host_build_s": t_l2,
        "engine_build_s": t_eng_build,
        "solve_s": t_solve,
        "dumps_s": t_l4,
        "dump_work_s": t_dump_work[0],
        "dump_lines_per_s": iters * int(g.n) / t_dump_work[0],
        "records_per_sec_l1": files * per_file / t_l1,
    }
    print(
        f"[{key}] {files} files / {files * per_file:,} records -> "
        f"{g.n:,} vertices / {g.num_edges:,} edges; split: gen "
        f"{t_gen:.1f}s (not part of the job), L1 {t_l1:.1f}s, host "
        f"build {t_l2:.1f}s, engine build {t_eng_build:.1f}s, solve "
        f"{t_solve:.2f}s, dumps exposed {t_l4:.1f}s (worker dump work "
        f"{t_dump_work[0]:.1f}s = {rec['dump_lines_per_s']:.3g} "
        f"lines/s; oracle {t_oracle:.1f}s); "
        f"normalized L1 {norm:.3e} (mass-normalized {mass_norm:.3e}) "
        f"vs gate {GATE:g} -> {'PASS' if rec['passed'] else 'FAIL'}",
        file=sys.stderr,
    )
    return rec


def run_one(key: str):
    from pagerank_tpu import (JaxTpuEngine, PageRankConfig,
                              ReferenceCpuEngine)

    spec = CONFIGS[key]
    scale, iters = spec["scale"], spec["iters"]
    semantics = spec.get("semantics", "reference")
    g = _make_graph(key, scale)

    cfg_pair = PageRankConfig(
        num_iters=iters, dtype="float64", accum_dtype="float64",
        wide_accum="pair", semantics=semantics,
        # Sharded variants (VERDICT r4 #3): vertex_sharded=True on the
        # single real chip exercises the psum+slice f64 contribution
        # merge (and, with vs_bounded, the dst-partitioned owner-
        # computes path + per-stripe z psum) under the same oracle
        # gate as the replicated rows.
        vertex_sharded=spec.get("vertex_sharded", False),
        vs_bounded=spec.get("vs_bounded", False),
    )
    t0 = time.perf_counter()
    eng = JaxTpuEngine(cfg_pair).build(g)
    t_dev_build = time.perf_counter() - t0
    # Compile outside the timed window, then restore the initial state
    # (reference semantics: rank 1.0 per vertex, Sparky.java:168;
    # textbook: 1/N — models/pagerank.initial_rank). The timed window
    # covers steps + the honest scalar fence ONLY (bench.py pattern) —
    # the full rank decode/D2H happens after, so it doesn't deflate the
    # rate column.
    from pagerank_tpu.models.pagerank import initial_rank

    eng.step()
    eng.fence()
    eng.set_ranks(initial_rank(g.n, semantics, np.float64, np), iteration=0)
    chips = eng.mesh.devices.size
    t0 = time.perf_counter()
    for _ in range(iters):
        eng._device_step()
    eng.fence()
    t_run = time.perf_counter() - t0
    r_tpu = eng.ranks()

    t0 = time.perf_counter()
    cfg_oracle = PageRankConfig(num_iters=iters, dtype="float64",
                                accum_dtype="float64", semantics=semantics)
    r_cpu = ReferenceCpuEngine(cfg_oracle).build(g).run()
    t_oracle = time.perf_counter() - t0

    from pagerank_tpu.utils.metrics import oracle_l1

    _, norm, mass_norm = oracle_l1(r_tpu, r_cpu)
    rate = g.num_edges * iters / t_run / chips
    rec = {
        "config": key,
        "label": spec["label"],
        "semantics": semantics,
        "scale": scale,
        "iters": iters,
        "num_edges": int(g.num_edges),
        "normalized_l1": norm,
        "mass_normalized_l1": mass_norm,
        "mass_growth": float(r_cpu.sum()) / g.n,
        "gate": GATE,
        "passed": bool(norm <= GATE and mass_norm <= GATE),
        "tpu_seconds": t_run,
        "edges_per_sec_per_chip": rate,
    }
    print(
        f"[{key}] {iters} iters in {t_run:.2f}s (device build "
        f"{t_dev_build:.1f}s, oracle {t_oracle:.1f}s): normalized L1 "
        f"{norm:.3e} (mass-normalized {mass_norm:.3e}) vs gate {GATE:g} "
        f"-> {'PASS' if rec['passed'] else 'FAIL'}; {rate:.3g} edges/s/chip",
        file=sys.stderr,
    )
    return rec


def _append_table(text: str, header: str, intro: str, row_strs) -> str:
    """Append rows under ``header``, creating the table (with ``intro``)
    on first use. Rows land at the END of the header's section (the
    next '## ' or EOF), so repeated runs interleave correctly even when
    other sections follow."""
    rows = "".join(row_strs)
    if not rows:
        return text
    if header not in text:
        return text + f"\n{header}\n\n" + intro + rows
    start = text.index(header)
    end = text.find("\n## ", start + len(header))
    if end == -1:
        return text + rows
    return text[:end] + rows + text[end:]


def append_baseline(recs) -> None:
    path = os.path.join(REPO, "BASELINE.md")
    with open(path) as f:
        text = f.read()
    global_rows = [
        f"| {r['label']} | R-MAT {r['scale']} ({r['num_edges']:,} edges) "
        f"| {r['iters']} | {r['normalized_l1']:.3e} | "
        f"{r['mass_normalized_l1']:.3e} | {r['gate']:g} | "
        f"{'PASS' if r['passed'] else 'FAIL'} | "
        f"{r['edges_per_sec_per_chip']:.3g} |\n"
        for r in recs if r.get("kind") not in ("ppr", "e2e", "build",
                                               "faults")
        # Smoke records (obs/live/partitioned/elastic/halo/history/
        # devices) gate their own axes and don't carry the oracle-table
        # columns; only key-complete records join the accuracy table.
        and {"scale", "num_edges", "normalized_l1",
             "mass_normalized_l1", "gate",
             "edges_per_sec_per_chip"} <= set(r)
    ]
    text = _append_table(
        text,
        "## Acceptance runs (configs 2-4 stand-ins)",
        "Scripted by `scripts/acceptance.py`: accuracy-grade TPU "
        "config (pair-f64: f64 storage + pair accumulation) vs the "
        "f64 CPU oracle on the same R-MAT graph (reference semantics "
        "unless the stand-in says textbook). Gate: BOTH raw "
        "normalized L1 and mass-normalized L1 <= 1e-6. One row "
        "appended per run.\n\n"
        "| Stand-in | Workload | Iters | Normalized L1 | "
        "Mass-normalized L1 | Gate | Result | edges/s/chip |\n"
        "|---|---|---|---|---|---|---|---|\n",
        global_rows,
    )
    ppr_rows = [
        f"| {r['label']} | R-MAT {r['scale']} ({r['num_edges']:,} edges), "
        f"{r['sources']} sources | {r['iters']} | "
        f"{r['min_topk_overlap']:.4f} / {r['mean_topk_overlap']:.4f} | "
        f"{r['max_score_l1']:.3e} | >= {r['overlap_gate']}, <= "
        f"{r['score_l1_gate']:g} | {'PASS' if r['passed'] else 'FAIL'} | "
        f"{r['edge_vectors_per_sec_per_chip']:.3g} |\n"
        for r in recs if r.get("kind") == "ppr"
    ]
    text = _append_table(
        text,
        "## PPR acceptance runs (config-5 stand-in)",
        "Device batched-SpMM PPR (f32) vs the f64 CPU oracle: "
        "per-source top-k id overlap (min/mean; ties at the k boundary "
        "may swap) and worst per-source L1 over top-k scores (columns "
        "sum to 1, so relative).\n\n"
        "| Stand-in | Workload | Iters | Top-k overlap min/mean | "
        "Max score L1 | Gates | Result | edge-vectors/s/chip |\n"
        "|---|---|---|---|---|---|---|---|\n",
        ppr_rows,
    )
    e2e_rows = [
        f"| {r['label']} | {r['files']} files / {r['records']:,} records "
        f"-> {r['n']:,} v / {r['num_edges']:,} e | {r['iters']} | "
        f"{r['l1_parse_s']:.1f} | {r['host_build_s']:.1f} | "
        f"{r['engine_build_s']:.1f} | {r['solve_s']:.2f} | "
        + (
            f"{r['dumps_s']:.2f} (async; work {r['dump_work_s']:.2f} @ "
            f"{r['dump_lines_per_s']:.2g} lines/s)"
            if "dump_work_s" in r else f"{r['dumps_s']:.1f}"
        )
        + f" | {r['normalized_l1']:.3e} | "
        f"{'PASS' if r['passed'] else 'FAIL'} |\n"
        for r in recs if r.get("kind") == "e2e"
    ]
    text = _append_table(
        text,
        "## Reference-job end-to-end acceptance",
        "The reference's literal job (SURVEY §3.1-3.2): synthetic "
        "Common-Crawl-style 301-file SequenceFile segment -> native "
        "C++ L1 -> host graph build (post-repair dangling semantics) "
        "-> pair-f64 jax engine, reference semantics, 10 iterations "
        "-> per-iteration Spark-format `PageRank{i}/` dumps "
        "(AsyncRankWriter + native bulk formatter; the Dumps column "
        "is the EXPOSED L4 wall beyond solve, with the worker's "
        "in-dump time and formatter rate in parentheses). Gate: "
        "normalized + mass-normalized L1 vs the f64 oracle <= 1e-6. "
        "All times seconds.\n\n"
        "| Run | Workload | Iters | L1 parse | Host build | "
        "Engine build | Solve | Dumps | Normalized L1 | Result |\n"
        "|---|---|---|---|---|---|---|---|---|---|\n",
        e2e_rows,
    )
    with open(path, "w") as f:
        f.write(text)
    print(f"appended {len(recs)} row(s) to BASELINE.md", file=sys.stderr)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--only", choices=sorted(CONFIGS), default=None)
    p.add_argument("--no-append", action="store_true")
    p.add_argument("--no-analysis", action="store_true",
                   help="skip the static-analysis pre-gate")
    args = p.parse_args(argv)

    if not args.no_analysis:
        # Cheap pre-gate: the AST lint PLUS the PTR concurrency pass
        # (docs/ANALYSIS.md — --lint-only runs both; ISSUE 14) — a
        # dirty tree fails fast before minutes of acceptance runs.
        # The jaxpr contract suite is skipped here: it forces a CPU
        # fake mesh, which would fight this process's TPU backend; it
        # runs in tier-1 pytest instead.
        from pagerank_tpu.analysis.__main__ import main as analysis_main

        if analysis_main(["--lint-only"]) != 0:
            print("acceptance: static analysis failed (run "
                  "`python -m pagerank_tpu.analysis` for details)",
                  file=sys.stderr)
            return 1

    from bench import _enable_compile_cache

    _enable_compile_cache()
    keys = [args.only] if args.only else DEFAULT_KEYS
    runners = {"ppr": run_ppr, "e2e": run_e2e, "build": run_build_smoke,
               "faults": run_fault_smoke, "obs": run_obs_smoke,
               "live": run_live_smoke, "partitioned": run_partitioned_smoke,
               "elastic": run_elastic_smoke, "serve": run_serve_smoke,
               "qtrace": run_qtrace_smoke,
               "halo": run_halo_smoke,
               "halo_async": run_halo_async_smoke,
               "history": run_history_smoke,
               "devices": run_devices_smoke, "hlo": run_hlo_smoke,
               "jobs": run_jobs_smoke, "graph": run_graph_smoke,
               "concurrency": run_concurrency_smoke,
               "sdc": run_sdc_smoke, "kernels": run_kernels_smoke,
               "campaign": run_campaign_smoke}
    recs = [
        runners.get(CONFIGS[k].get("kind"), run_one)(k) for k in keys
    ]
    if not args.no_append:
        append_baseline(recs)
    print(json.dumps(recs))
    return 0 if all(r["passed"] for r in recs) else 1


if __name__ == "__main__":
    sys.exit(main())
