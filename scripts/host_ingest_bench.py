"""Measured host-ingest costs (VERDICT r2 #2: SURVEY §7 calls
billion-edge host ingest a hard part, and no measured number existed).

Three measurements, printed as a markdown table for docs/PERF_NOTES.md:

  1. host R-MAT edge generation + build_graph at scale >= 25 (time and
     peak RSS — the np.unique path's transient is what bounds host
     capacity);
  2. np.unique vs the C++ radix sort-dedup (native/fast_ingest.cpp) on
     the same edges (the auto-enable rule in build_graph keys off this);
  3. a 300-file synthetic SequenceFile segment (the reference's input
     shape, Sparky.java:44-58) through load_crawl_seqfile, serial vs
     process-pool workers.

Run:  python scripts/host_ingest_bench.py [--scale 25] [--files 300]
"""

import argparse
import os
import resource
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_gb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def _build_child(q, src, dst, n, native):
    """One build in a FRESH forked process so ru_maxrss is that path's
    own high-water mark — in-process, the second build would inherit
    the first's process-lifetime peak and the per-path RSS column would
    be meaningless."""
    from pagerank_tpu import build_graph

    t0 = time.perf_counter()
    g = build_graph(src, dst, n=n, use_native_sort=native)
    q.put((time.perf_counter() - t0, int(g.num_edges), rss_gb()))


def bench_host_build(scale: int, edge_factor: int):
    import multiprocessing

    from pagerank_tpu.utils.synth import rmat_edges

    t0 = time.perf_counter()
    src, dst = rmat_edges(scale, edge_factor, seed=0)
    t_gen = time.perf_counter() - t0
    raw = len(src)
    print(f"rmat gen: scale {scale} ef {edge_factor}: {raw:,} raw edges "
          f"in {t_gen:.1f}s (rss {rss_gb():.1f} GB)", file=sys.stderr)

    ctx = multiprocessing.get_context("fork")  # COW: edges not copied
    rows = []
    for label, native in (("np.unique", False), ("C++ radix", True)):
        q = ctx.Queue()
        p = ctx.Process(target=_build_child,
                        args=(q, src, dst, 1 << scale, native))
        p.start()
        result = None
        while result is None:
            try:
                result = q.get(timeout=30)
            except Exception:
                if not p.is_alive():  # died before q.put (e.g. OOM kill)
                    raise RuntimeError(
                        f"{label} build child exited with "
                        f"{p.exitcode} before reporting a result"
                    )
        dt, num_edges, rss = result
        p.join()
        rows.append((label, raw, num_edges, dt, rss))
        print(f"build[{label}]: {num_edges:,} unique edges in {dt:.1f}s "
              f"({raw / dt / 1e6:.1f} M raw edges/s, child peak rss "
              f"{rss:.1f} GB)", file=sys.stderr)
    return t_gen, rows


def bench_segment(n_files: int, recs_per_file: int, workers_list):
    import json

    from pagerank_tpu.ingest import load_crawl_seqfile, write_sequence_file

    rng = np.random.default_rng(0)
    n_urls = 2000
    urls = [f"http://site{i}.example/path/page.html" for i in range(n_urls)]

    def meta(targets):
        return json.dumps({"content": {"links": [
            {"type": "a", "href": t} for t in targets]}})

    td = tempfile.mkdtemp(prefix="seg")
    t0 = time.perf_counter()
    n_records = 0
    for i in range(n_files):
        recs = []
        for _ in range(recs_per_file):
            u = urls[int(rng.integers(n_urls))]
            targets = [urls[int(t)] for t in
                       rng.integers(0, n_urls, 20)]
            recs.append((u, meta(targets)))
            n_records += 1
        write_sequence_file(
            os.path.join(td, f"metadata-{i:05d}"), recs,
            compression="block",
        )
    print(f"segment: {n_files} files x {recs_per_file} records "
          f"({n_records:,} records, 20 links each) written in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

    # Warm the native library OUTSIDE the timed window (on-demand g++
    # compile can take minutes) and drop the row honestly when the
    # toolchain is absent — never mislabel the Python fallback.
    from pagerank_tpu.ingest import native as native_mod

    modes = []
    if native_mod.available():
        modes.append(("native", dict(native="auto")))
    else:
        print("native library unavailable; skipping the native row",
              file=sys.stderr)
    modes += [(f"python workers={w}", dict(native="off", workers=w))
              for w in workers_list]
    rows = []
    for label, kw in modes:
        t0 = time.perf_counter()
        g, ids = load_crawl_seqfile(td, **kw)
        dt = time.perf_counter() - t0
        rows.append((label, n_records, g.num_edges, dt))
        print(f"ingest[{label}]: {g.num_edges:,} unique edges, "
              f"{n_records / dt:,.0f} records/s ({dt:.1f}s)",
              file=sys.stderr)
    return rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=int, default=25)
    p.add_argument("--edge-factor", type=int, default=16)
    p.add_argument("--files", type=int, default=300)
    p.add_argument("--recs-per-file", type=int, default=200)
    args = p.parse_args()

    cores = os.cpu_count() or 1
    print(f"host: {cores} core(s)", file=sys.stderr)
    workers = [1] if cores == 1 else [1, cores]

    seg_rows = bench_segment(args.files, args.recs_per_file, workers)
    t_gen, build_rows = bench_host_build(args.scale, args.edge_factor)

    print("\n## Host ingest (markdown)\n")
    print("| measurement | input | result |")
    print("|---|---|---|")
    for label, raw, uniq, dt, rss in build_rows:
        print(f"| host build ({label}) | R-MAT {args.scale} ef "
              f"{args.edge_factor}: {raw / 1e6:.0f}M raw / {uniq / 1e6:.0f}M "
              f"unique edges | {dt:.1f}s = {raw / dt / 1e6:.1f} M raw "
              f"edges/s, peak RSS {rss:.1f} GB |")
    for label, n_records, uniq, dt in seg_rows:
        print(f"| segment ingest ({label}) | {args.files}-file "
              f"block-compressed SequenceFile segment, {n_records:,} "
              f"records | {n_records / dt:,.0f} records/s "
              f"({uniq / dt / 1e6:.2f}M unique edges/s) |")


if __name__ == "__main__":
    main()
