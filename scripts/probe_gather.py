"""Micro-benchmark: candidate formulations of the SpMV gather on the live
TPU, to pick the kernel the engine should default to.

The hot op is contrib = Aᵀ_norm r — per ELL slot: z[src[row, lane]] * w.
The gather of z at arbitrary src indices is the whole game (the multiply
and row segment-sum are streaming). Variants probed:

  take1d       : z[src]                       — plain 1-D take
  onehot8      : z.reshape(-1, 8)[src>>3] ⊙ one_hot(src&7)   (current)
  onehot16     : width-16 variant
  onehot32     : width-32 variant
  onehot128mxu : z.reshape(-1,128)[src>>7] one-hot contracted on the MXU
  pallas_*     : Pallas in-kernel gather forms (support probe + timing)

Run: python scripts/probe_gather.py [--rows 65536] [--n 1048576]
"""

from __future__ import annotations

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.device_get(jnp.sum(out if not isinstance(out, tuple) else out[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.device_get(jnp.sum(out if not isinstance(out, tuple) else out[0]))
    return (time.perf_counter() - t0) / iters


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=1 << 16)  # rows of 128 slots
    p.add_argument("--n", type=int, default=1 << 20)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()

    rows, n = args.rows, args.n
    dtype = jnp.dtype(args.dtype)
    slots = rows * 128
    rng = np.random.default_rng(0)
    src = rng.integers(0, n, (rows, 128)).astype(np.int32)
    w = rng.random((rows, 128), np.float32).astype(dtype)
    z = rng.random(n, np.float32).astype(dtype)

    src_d = jax.device_put(src)
    w_d = jax.device_put(w)
    z_d = jax.device_put(z)

    results = {}

    @jax.jit
    def take1d(z, s, w):
        return z[s] * w

    results["take1d"] = timeit(take1d, z_d, src_d, w_d, iters=args.iters)

    def make_onehot(width):
        shift = width.bit_length() - 1
        mask = width - 1

        @jax.jit
        def f(z, s, w):
            zw = z.reshape(-1, width)
            rows_g = zw[s >> shift]
            sel = jax.nn.one_hot(s & mask, width, dtype=z.dtype)
            return (rows_g * sel).sum(-1) * w

        return f

    for width in (8, 16, 32):
        results[f"onehot{width}"] = timeit(
            make_onehot(width), z_d, src_d, w_d, iters=args.iters
        )

    def make_onehot_chunked(width):
        """The engine's production form: scan over row chunks sized so
        the (chunk, 128, width) gather intermediate stays ~33MB — beyond
        that, tables >= ~16MB collapse ~4x (measured on v5e; small
        tables are insensitive)."""
        shift = width.bit_length() - 1
        mask = width - 1
        chunk = max(256, 8192 * 8 // width)

        @jax.jit
        def f(z, s, w):
            zw = z.reshape(-1, width)
            nc = s.shape[0] // chunk

            def body(acc, args):
                s_c, w_c = args
                rows_g = zw[s_c >> shift]
                sel = jax.nn.one_hot(s_c & mask, width, dtype=z.dtype)
                return acc + ((rows_g * sel).sum(-1) * w_c).sum(0), None

            acc, _ = jax.lax.scan(
                body, jnp.zeros(128, z.dtype),
                (s.reshape(nc, chunk, 128), w.reshape(nc, chunk, 128)),
            )
            return acc

        return f

    for width in (8, 16, 32, 64, 128):
        if rows % max(256, 8192 * 8 // width):
            results[f"onehot{width}c"] = "SKIP rows not chunk-divisible"
            continue
        if (n // width) * width != n:
            results[f"onehot{width}c"] = "SKIP width does not divide n"
            continue
        results[f"onehot{width}c"] = timeit(
            make_onehot_chunked(width), z_d, src_d, w_d, iters=args.iters
        )

    # MXU form: per slot, one_hot(128) dot the gathered 128-row.
    @jax.jit
    def onehot128mxu(z, s, w):
        zw = z.reshape(-1, 128)
        rows_g = zw[s >> 7]  # (rows, 128, 128)
        sel = jax.nn.one_hot(s & 127, 128, dtype=z.dtype)
        return jnp.einsum("rlk,rlk->rl", rows_g, sel) * w

    try:
        results["onehot128mxu"] = timeit(
            onehot128mxu, z_d, src_d, w_d, iters=max(2, args.iters // 4)
        )
    except Exception as e:  # may OOM at big rows
        results["onehot128mxu"] = f"FAIL {type(e).__name__}"

    # Pallas in-kernel forms.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    CHUNK = 512

    def probe_pallas(name, kernel_body):
        try:
            f = pl.pallas_call(
                kernel_body,
                out_shape=jax.ShapeDtypeStruct((rows, 128), dtype),
                grid=(rows // CHUNK,),
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.VMEM),  # z, whole, resident
                    pl.BlockSpec((CHUNK, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
                    pl.BlockSpec((CHUNK, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec(
                    (CHUNK, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
                ),
            )
            jf = jax.jit(f)
            out = jf(z_d, src_d, w_d)
            jax.device_get(jnp.sum(out))
            results[name] = timeit(jf, z_d, src_d, w_d, iters=args.iters)
        except Exception as e:
            msg = str(e).splitlines()[0][:120] if str(e) else type(e).__name__
            results[name] = f"FAIL {type(e).__name__}: {msg}"

    def k_take(z_ref, s_ref, w_ref, o_ref):
        o_ref[:] = z_ref[...][s_ref[...]] * w_ref[...]

    probe_pallas("pallas_take1d", k_take)

    def k_onehot8(z_ref, s_ref, w_ref, o_ref):
        zw = z_ref[...].reshape(-1, 8)
        s = s_ref[...]
        rows_g = zw[s >> 3]
        sel = jax.nn.one_hot(s & 7, 8, dtype=zw.dtype)
        o_ref[:] = (rows_g * sel).sum(-1) * w_ref[...]

    probe_pallas("pallas_onehot8", k_onehot8)

    def k_taa(z_ref, s_ref, w_ref, o_ref):
        # take_along_axis within 128 lanes after a row gather
        zw = z_ref[...].reshape(-1, 128)
        s = s_ref[...]
        rows_g = zw[s >> 7]  # (CHUNK,128,128) gather - likely unsupported
        o_ref[:] = jnp.take_along_axis(
            rows_g, (s & 127)[..., None], axis=-1
        )[..., 0] * w_ref[...]

    probe_pallas("pallas_rowgather_taa", k_taa)

    gb = slots * (4 + dtype.itemsize * 2) / 1e9  # src + w + out bytes
    print(f"\nrows={rows} slots={slots:,} n={n:,} dtype={args.dtype}")
    for k, v in results.items():
        if isinstance(v, float):
            print(f"  {k:24s} {v * 1e3:8.3f} ms  {slots / v / 1e9:7.3f} Gslot/s  {gb / v:6.1f} GB/s(stream)")
        else:
            print(f"  {k:24s} {v}")


if __name__ == "__main__":
    main()
