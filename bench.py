"""Benchmark: PageRank power-iteration throughput on TPU.

Prints ONE JSON line:
  {"metric": "edges_per_sec_per_chip", "value": N, "unit": "edges/s/chip",
   "vs_baseline": R}

vs_baseline is measured throughput over the north-star implied rate: the
BASELINE.md headline (50 iters on Twitter-2010's 1.47B edges in <60 s on
a v4-8) requires 1.47e9*50/60/8 ≈ 1.53e8 edges/s/chip. The reference
itself publishes no numbers (BASELINE.md), so that target is the bar.

Workload: R-MAT (power-law, Graph500 params) — the SNAP/Common Crawl
graphs aren't fetchable in this zero-egress environment; R-MAT reproduces
the degree skew that makes the workload hard.

The graph is generated AND packed on device (ops/device_build.py): over
a tunneled TPU the host->device link is orders of magnitude slower than
HBM, and shipping packed edge arrays dominates wall-clock. Only a PRNG
seed and two sizing scalars cross the link. --host-build restores the
host ingest path (what a real edge-list run would exercise).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

NORTH_STAR_EDGES_PER_SEC_PER_CHIP = 1.47e9 * 50 / 60 / 8


def _enable_compile_cache():
    """Persist XLA executables across bench runs — the graph-build and
    step compiles are ~2 minutes of the wall-clock otherwise.

    min_compile_time_secs=0: the device build + engine setup issue ~50
    small jitted ops, each ~0.6s to compile through the remote-compile
    service but far under the 1s default cache threshold — caching them
    cuts the warm scale-21 build from ~49s to ~10s (measured v5e)."""
    import jax

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # cache is an optimization, never a requirement
        print(f"bench: compilation cache unavailable ({e})", file=sys.stderr)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=int, default=22,
                   help="R-MAT scale (2^scale vertices). 22 = 4.2M "
                        "vertices / 65M unique edges, the best-measured "
                        "single-stripe point (3.52e8 edges/s/chip on "
                        "v5e-1; scales 21-25 all land 2.0-2.3x the "
                        "north-star rate, BASELINE.md)")
    p.add_argument("--edge-factor", type=int, default=16)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--kernel", default="auto",
                   help="auto|ell|pallas|coo (engine kernels)")
    p.add_argument("--lane-group", type=int, default=0,
                   help="grouped-lane ELL group size; 0 = auto (64 plain "
                        "/ 16 pair, the v5e-measured optima; see "
                        "ops/ell.py and docs/PERF_NOTES.md)")
    p.add_argument("--stripe-size", type=int, default=0,
                   help="source-stripe span in vertices (0 = auto: "
                        "single stripe up to 8.4M f32 vertices / 4.2M "
                        "f64, stripes of half that above — the measured "
                        "optimum, see jax_engine._stripe_max)")
    p.add_argument("--host-build", action="store_true",
                   help="build the graph on host + transfer (default: on-device)")
    p.add_argument("--accuracy-check", action="store_true",
                   help="also diff a small graph against the f64 CPU oracle")
    args = p.parse_args(argv)

    _enable_compile_cache()
    from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph

    # Stripe sources once the gather table outgrows the single-stripe
    # bound; use the engine's own limits so the two can't diverge (a
    # 64-bit dtype runs the pair-packed table on TPU, which carries 2x
    # lanes/row).
    from pagerank_tpu.engines.jax_engine import JaxTpuEngine

    n_padded = -(-(1 << args.scale) // 128) * 128
    pair = np.dtype(args.dtype).itemsize == 8
    fast_cap, stripe_target = JaxTpuEngine.stripe_limits(
        4 if pair else np.dtype(args.dtype).itemsize, pair
    )
    stripe = args.stripe_size or (0 if n_padded <= fast_cap else stripe_target)
    # Clamp the lane group so packed slot words (src << log2g | sub) fit
    # int32 at the span the chosen build will actually pack (the host
    # path ignores --stripe-size; the engine stripes it at stripe_target
    # when n_padded exceeds fast_cap).
    span = min(stripe or n_padded, n_padded)
    if args.host_build:
        span = min(stripe_target if n_padded > fast_cap else n_padded,
                   n_padded)
    # 0 = auto: resolve through the engine's own table so the optima
    # live in one place. bench targets the TPU backend, where
    # wide_accum="auto" always resolves to pair for 64-bit dtypes —
    # hence the itemsize predicate above.
    # "striped" must mirror the layout the chosen build actually packs:
    # the host path ignores --stripe-size (the engine stripes iff
    # n_padded > fast_cap), and an explicit span >= n_padded still packs
    # one stripe.
    if args.host_build:
        is_striped = n_padded > fast_cap
    else:
        is_striped = bool(stripe) and stripe < n_padded
    grp_req = args.lane_group or PageRankConfig().effective_lane_group(
        pair, striped=is_striped
    )
    grp = grp_req
    while grp > 1 and (span + 1) * grp > 2**31 - 1:
        grp //= 2
    if grp != grp_req:
        print(f"bench: lane group clamped to {grp} at scale {args.scale}",
              file=sys.stderr)
    cfg = PageRankConfig(
        num_iters=args.iters, dtype=args.dtype, accum_dtype=args.dtype,
        kernel=args.kernel, lane_group=grp,
    ).validate()

    t0 = time.perf_counter()
    if args.kernel == "coo" and not args.host_build:
        print("--kernel coo requires the host ingest path; using --host-build",
              file=sys.stderr)
        args.host_build = True
    if args.host_build:
        from pagerank_tpu.utils.synth import rmat_edges

        src, dst = rmat_edges(args.scale, args.edge_factor, seed=0)
        graph = build_graph(src, dst, n=1 << args.scale)
        num_edges = graph.num_edges
        engine = JaxTpuEngine(cfg).build(graph)
    else:
        from pagerank_tpu.ops import device_build as db

        src, dst = db.rmat_edges_device(args.scale, args.edge_factor, seed=0)
        pallas = cfg.kernel == "pallas"
        dg = db.build_ell_device(
            src, dst, n=1 << args.scale,
            group=1 if pallas else cfg.lane_group,
            stripe_size=0 if pallas else stripe,
            with_weights=False,  # presentinel: no per-slot weight plane
        )
        num_edges = dg.num_edges
        engine = JaxTpuEngine(cfg).build_device(dg)
    t_build = time.perf_counter() - t0
    print(
        f"graph: scale {args.scale}: {1 << args.scale:,} vertices, "
        f"{num_edges:,} unique edges "
        f"({'host' if args.host_build else 'device'} build {t_build:.1f}s)",
        file=sys.stderr,
    )
    chips = engine.mesh.devices.size

    for _ in range(args.warmup):
        engine._device_step()
    engine.fence()  # block_until_ready is not honest on tunneled backends

    t0 = time.perf_counter()
    for _ in range(args.iters):
        engine._device_step()
    engine.fence()
    dt = time.perf_counter() - t0

    eps_chip = num_edges * args.iters / dt / chips
    print(
        f"{args.iters} iters in {dt:.3f}s on {chips} chip(s): "
        f"{dt / args.iters * 1e3:.2f} ms/iter, {eps_chip:.4g} edges/s/chip",
        file=sys.stderr,
    )

    if args.accuracy_check:
        from pagerank_tpu import ReferenceCpuEngine
        from pagerank_tpu.utils.synth import rmat_edges

        s2, d2 = rmat_edges(16, 16, seed=3)
        g2 = build_graph(s2, d2, n=1 << 16)
        oracle = PageRankConfig(num_iters=20, dtype="float64", accum_dtype="float64")
        r_cpu = ReferenceCpuEngine(oracle).build(g2).run()
        for label, c2 in (
            (f"fast {args.dtype}",
             PageRankConfig(num_iters=20, dtype=args.dtype,
                            accum_dtype=args.dtype)),
            (f"{args.dtype}+f64-accum",
             PageRankConfig(num_iters=20, dtype=args.dtype,
                            accum_dtype="float64")),
        ):
            r_tpu = JaxTpuEngine(c2).build(g2).run_fast()
            l1 = float(np.abs(r_tpu - r_cpu).sum())
            print(
                f"accuracy[{label}]: L1 vs f64 oracle {l1:.3e} "
                f"(normalized {l1 / np.abs(r_cpu).sum():.3e}, scale-16, 20 iters)",
                file=sys.stderr,
            )

    print(
        json.dumps(
            {
                "metric": "edges_per_sec_per_chip",
                "value": eps_chip,
                "unit": "edges/s/chip",
                "vs_baseline": eps_chip / NORTH_STAR_EDGES_PER_SEC_PER_CHIP,
            }
        )
    )


if __name__ == "__main__":
    main()
