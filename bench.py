"""Benchmark: PageRank power-iteration throughput on TPU.

Prints ONE JSON line. Default (couple) mode measures the NORTH-STAR
COUPLE — speed AND accuracy together (BASELINE.md config 4 couples
them: <60 s for 50 iters on Twitter-2010 AND ranks within 1e-6 L1):

  {"metric": "edges_per_sec_per_chip",
   "value": <pair-f64 accuracy-grade rate>, "unit": "edges/s/chip",
   "vs_baseline": <rate / north-star rate>,
   "fast_f32": {"value": ..., "vs_baseline": ..., "costs": ...,
                "layout": ...},
   "partitioned_f32": {... the partition-centric layout leg ...},
   "fast_bf16": {... partitioned + bf16-streamed gather table ...},
   "accuracy": {"config": "pair-f64", "scale": 20, "iters": 50,
                "normalized_l1_vs_f64_oracle": ...,
                "mass_normalized_l1": ...,
                "fast_bf16": {"normalized_l1_vs_f64_oracle": ...}}}

Every rate leg carries its XLA cost-model block ("costs") and the
resolved kernel/layout/autotune record ("layout") — the partitioned
legs' win must show as reduced step bytes/edge against fast_f32's
"step" form, not just wall clock (ISSUE 6 acceptance).

The HEADLINE value is the accuracy-grade config ("pair-f64": f64 rank
storage with pair-packed f64 accumulation — matches the f64 CPU oracle
to ~3e-14 normalized L1 over a full 50-iteration reference-semantics
run; the faster plain-f32 config, reported alongside, lands ~1.6e-6
there). The accuracy field is a standing measurement: a scale-20
(1M-vertex / 16.7M-edge) R-MAT run of the SAME pair-f64 config diffed
against the float64 CPU oracle over the full 50 iterations.

vs_baseline is measured throughput over the north-star implied rate: the
BASELINE.md headline (50 iters on Twitter-2010's 1.47B edges in <60 s on
a v4-8) requires 1.47e9*50/60/8 ≈ 1.53e8 edges/s/chip. The reference
itself publishes no numbers (BASELINE.md), so that target is the bar.

Passing --dtype explicitly selects single-config mode (one rate run of
that dtype, the original schema, plus the standing accuracy field unless
--no-accuracy).

Workload: R-MAT (power-law, Graph500 params) — the SNAP/Common Crawl
graphs aren't fetchable in this zero-egress environment; R-MAT reproduces
the degree skew that makes the workload hard.

The graph is generated AND packed on device (ops/device_build.py): over
a tunneled TPU the host->device link is orders of magnitude slower than
HBM, and shipping packed edge arrays dominates wall-clock. Only a PRNG
seed and two sizing scalars cross the link. --host-build restores the
host ingest path (what a real edge-list run would exercise).

Every emit carries ``schema_version`` (BENCH_SCHEMA_VERSION) and the
workload geometry, and ``--out`` / ``--history`` write the canonical
record / append it to the perf-history ledger directly (ISSUE 9;
docs/OBSERVABILITY.md "Perf history & gating") — the legacy
``{n, cmd, rc, tail, parsed}`` tail-scrape wrapper is dead on the
emit side, though the ledger keeps reading the checked-in r01-r05
wrappers.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

from pagerank_tpu.exitcodes import ExitCode

NORTH_STAR_EDGES_PER_SEC_PER_CHIP = 1.47e9 * 50 / 60 / 8

# Version of bench.py's OWN JSON schemas (couple, single, --build-only,
# --multichip). 1 was the implicit pre-ISSUE-9 era: those artifacts
# carry no version field at all, and the perf-history ledger
# (pagerank_tpu/obs/history.py) still ingests them; 2 adds this field
# plus the workload geometry (scale/iters/edge_factor) to every emit.
BENCH_SCHEMA_VERSION = 2

# The per-stage device-build breakdown schema (--build-only; also
# checked by scripts/acceptance.py's build smoke). Stage walls include
# any compile that stage paid; compile_s counts the STAGE-DISPATCH
# compiles only (utils/compile_cache.stage_call — engine-side compiles,
# autotune candidates included, land in engine_s undifferentiated), and
# engine_s includes autotune_s (reported separately so the historically
# largest engine-side line stays attributable — docs/PERF_NOTES.md
# "Device-build cost").
BUILD_STAGE_KEYS = ("gen_s", "relabel_s", "sort_s", "slots_s", "scatter_s",
                    "autotune_s", "engine_s", "compile_s")


def _device_graph(cfg, scale, edge_factor, stripe, seed=0, timings=None):
    """THE device graph gen + pack sequence — shared by run_rate's
    bench legs and run_build's --build-only breakdown, so the measured
    breakdown can never drift from the build the rate legs actually
    run. ``timings`` engages ops/device_build's per-stage fencing (plus
    an honest gen fence here); None keeps the pipeline fully async."""
    import jax

    from pagerank_tpu.ops import device_build as db

    t0 = time.perf_counter()
    src, dst = db.rmat_edges_device(scale, edge_factor, seed=seed)
    if timings is not None:
        jax.device_get((src[:1], dst[:1]))  # honest gen fence
        timings["gen_s"] = time.perf_counter() - t0
    pallas = cfg.kernel == "pallas"
    # Pallas consumes plain group-1 slot ids. The LEGACY whole-range
    # kernel additionally needs a single-stripe graph (stripe 0); the
    # partitioned kernel needs the stripes — they ARE the partitions
    # (plan_build returned stripe == partition_span for it).
    return db.build_ell_device(
        src, dst, n=1 << scale,
        group=1 if pallas else cfg.lane_group,
        stripe_size=0 if pallas and not cfg.partition_span else stripe,
        with_weights=False,  # presentinel: no per-slot weight plane
        timings=timings,
    )


def run_build(scale, edge_factor=16, dtype="float32", accum_dtype=None,
              wide_accum="auto", stripe_size=0, lane_group=0, seed=0,
              label=None):
    """One device build of the bench R-MAT geometry with the per-stage
    breakdown (BUILD_STAGE_KEYS): gen + the builder's four pipeline
    stages fenced by _device_graph's timing mode, engine setup
    (placements + autotune + fingerprint) fenced by the engine's own
    honest fence. Importable — scripts/acceptance.py's build smoke
    calls it directly. Returns {"build_s", "stages", "num_edges"}."""
    from pagerank_tpu import PageRankConfig
    from pagerank_tpu.engines.jax_engine import JaxTpuEngine
    from pagerank_tpu.ops.device_build import plan_build

    accum_dtype = accum_dtype or dtype
    cfg = PageRankConfig(
        num_iters=1, dtype=dtype, accum_dtype=accum_dtype,
        wide_accum=wide_accum,
    ).validate()
    # The breakdown legs measure the DEFAULT layout's build pipeline
    # (partition_span=0): the partitioned pack is the same pipeline at
    # a different stripe key, so its stages are covered by these legs.
    grp, stripe, _part = plan_build(
        cfg, 1 << scale, stripe_size=stripe_size, lane_group=lane_group,
        num_edges=edge_factor << scale, partition_span=0,
    )
    cfg = cfg.replace(lane_group=grp)
    # Start EMPTY: every key except compile_s must be written by a real
    # fence/timer below, so a dropped stage fence shows up as a missing
    # key in the acceptance gate instead of a pre-seeded 0.0.
    stages = {}
    t_total = time.perf_counter()
    dg = _device_graph(cfg, scale, edge_factor, stripe, seed=seed,
                       timings=stages)
    t0 = time.perf_counter()
    engine = JaxTpuEngine(cfg).build_device(dg)
    engine.fence()
    stages["engine_s"] = time.perf_counter() - t0
    stages["autotune_s"] = engine.build_timings.get("autotune_s", 0.0)
    # Zero compiles is a real value (warm caches), not a missing stage.
    stages.setdefault("compile_s", 0.0)
    build_s = time.perf_counter() - t_total
    num_edges = dg.num_edges
    del engine, dg
    if label is None:
        label = dtype + ("+pair" if wide_accum == "pair" else "")
    print(
        f"build[{label}]: scale {scale}: {build_s:.1f}s total — "
        + " ".join(
            f"{k[:-2]} {stages[k]:.1f}" for k in BUILD_STAGE_KEYS
            if k in stages
        ),
        file=sys.stderr,
    )
    return {"build_s": build_s, "stages": stages, "num_edges": num_edges}


def _fallback_span(n: int) -> int:
    """THE one spelling of the small-graph fallback partition span the
    bench legs use when the engine's auto rule says 'not worth it' —
    a quarter of the padded range, so the partitioned/bf16 legs always
    run and record what they ran (run_rate and run_accuracy share it;
    plan_build applies its own clamps on top)."""
    n_padded = -(-n // 128) * 128
    return max(128, (n_padded // 4) & ~127)


def _env_fingerprint():
    """Environment fingerprint embedded in every bench JSON artifact
    (obs/report.py): jax/jaxlib version, backend + device kind, x64,
    git rev. BENCH_r*.json cells recorded with this field are
    comparable across backend drift — the r5 failure mode, where an
    hour-scale backend degradation contaminated cells and had to be
    controlled for by hand (VERDICT r5; docs/OBSERVABILITY.md)."""
    from pagerank_tpu.obs import environment_fingerprint

    return environment_fingerprint()


def _enable_compile_cache():
    """Persist XLA executables across bench runs — the graph-build and
    step compiles are ~2 minutes of the wall-clock otherwise (shared
    helper: utils/compile_cache, also used by the CLI's --device-build)."""
    from pagerank_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    )


def _emit(out: dict, args) -> dict:
    """THE one bench output path (ISSUE 9): stamp the schema version,
    print the ONE JSON line the driver contract requires, and write
    the canonical artifacts directly — ``--out`` saves the record
    itself (strict JSON: the BENCH_r*.json shape going forward,
    replacing the legacy ``{n, cmd, rc, tail, parsed}`` tail-scrape
    wrapper the r01-r05 files carry; the perf-history ledger keeps
    accepting the old shape), and ``--history`` normalizes the record
    into the append-only perf ledger (pagerank_tpu/obs/history.py) —
    couple, single, --build-only, and --multichip runs alike."""
    from pagerank_tpu.obs.report import _json_safe

    out["schema_version"] = BENCH_SCHEMA_VERSION
    line = json.dumps(_json_safe(out), allow_nan=False)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"wrote bench record to {args.out}", file=sys.stderr)
    if args.history:
        from pagerank_tpu.obs import history as history_mod

        source = os.path.basename(args.out) if args.out else "bench"
        rec = history_mod.normalize_result(json.loads(line),
                                           source=source)
        added = history_mod.append_record(args.history, rec)
        print(("appended record to" if added
               else "record already in (content-hash dedupe)")
              + f" perf ledger {args.history}", file=sys.stderr)
    return out


def run_rate(args, dtype: str, accum_dtype: str, wide_accum: str = "auto",
             build_only: bool = False, partition_span: int = 0,
             stream_dtype: str = "", force_span_fallback: bool = False,
             kernel: str = ""):
    """One throughput measurement: build (device by default) + timed
    stepwise loop with the honest scalar fence. Returns the result dict.

    ``partition_span`` engages the partition-centric layout for this
    leg (-1 = the engine's auto rule); ``force_span_fallback`` makes a
    -1 that resolves to "off" run on a quarter-range fallback span
    instead — the couple mode's dedicated partitioned legs use it so
    they always run and record what they ran, while single-config
    ``--partition-span -1`` honors the rule's "off" verdict.
    ``stream_dtype`` streams the gather table reduced-precision (the
    ``fast_bf16`` leg). ``kernel`` overrides ``--kernel`` for this leg
    (the couple mode's ``pallas_partitioned`` leg passes "pallas" so
    the fused Mosaic kernel gets its own series without changing the
    XLA legs; a probe downgrade is visible in the leg's recorded
    layout via ``kernel_requested``).

    ``build_only`` (VERDICT r4 weak #4): build, time it, free, and
    return only ``build_s`` — couple mode calls this LAST with the
    pair config, so the number is the WARM tuning+compile-cache build
    by construction (the same config built earlier in the process) and
    cannot perturb the rate legs (a mid-couple rebuild once preceded a
    6x collapse of the following f32 leg). The first build's cost
    depends on the cache state (cold on a fresh checkout — .jax_cache
    is gitignored); the warm number reproduces (PERF_NOTES
    "Device-build cost": 22.8s warm vs 30.4s cold at scale 23).
    """
    from pagerank_tpu import PageRankConfig, build_graph
    from pagerank_tpu.engines.jax_engine import JaxTpuEngine

    host_build = args.host_build
    kernel = kernel or args.kernel
    if kernel == "coo" and not host_build:
        print("--kernel coo requires the host ingest path; using --host-build",
              file=sys.stderr)
        host_build = True

    # Stripe + lane-group sizing: THE shared planner (ops/device_build.
    # plan_build) so bench, CLI --device-build, and the engine can't
    # diverge on layout choices.
    from pagerank_tpu.ops.device_build import plan_build

    # stream_dtype joins the config only after the span resolves (it
    # validates against a set partition_span).
    cfg = PageRankConfig(
        num_iters=args.iters, dtype=dtype, accum_dtype=accum_dtype,
        kernel=kernel, wide_accum=wide_accum,
    ).validate()
    grp, stripe, part = plan_build(
        cfg, 1 << args.scale, stripe_size=args.stripe_size,
        lane_group=args.lane_group, host=host_build,
        num_edges=args.edge_factor << args.scale,  # raw count: the
        # occupancy rule is a density threshold, dedup loss is noise
        partition_span=partition_span,
    )
    if partition_span == -1 and not part and force_span_fallback:
        # Auto said "not worth it" at this geometry (small/sparse);
        # the dedicated couple-mode legs run anyway on a fallback span
        # so they stay measurable/attributable — the recorded layout
        # says which span actually ran.
        grp, stripe, part = plan_build(
            cfg, 1 << args.scale, lane_group=args.lane_group,
            host=host_build, num_edges=args.edge_factor << args.scale,
            partition_span=_fallback_span(1 << args.scale),
        )
    cfg = cfg.replace(lane_group=grp, partition_span=part,
                      stream_dtype=stream_dtype if part else "").validate()
    if stream_dtype and not part:
        print("stream_dtype needs the partitioned layout; leg runs "
              "without the narrowed stream", file=sys.stderr)

    def do_build():
        if host_build:
            from pagerank_tpu.utils.synth import rmat_edges

            src, dst = rmat_edges(args.scale, args.edge_factor, seed=0)
            graph = build_graph(src, dst, n=1 << args.scale)
            return JaxTpuEngine(cfg).build(graph), graph.num_edges, graph
        dg = _device_graph(cfg, args.scale, args.edge_factor, stripe)
        return JaxTpuEngine(cfg).build_device(dg), dg.num_edges, None

    # Data plane (ISSUE 13): rate legs arm the graph profiler so every
    # emitted leg carries its `graph` block (device builds compute the
    # profile in one fused reduction inside the build — a small,
    # now-standing addition to build_s; host legs profile in numpy
    # below). --build-only stays DISARMED: its build_s is the
    # stage-breakdown budget gate and must measure the bare pipeline.
    from pagerank_tpu.obs import graph_profile

    if not build_only:
        graph_profile.reset()
        graph_profile.arm()
    t0 = time.perf_counter()
    try:
        engine, num_edges, host_graph = do_build()
    finally:
        if not build_only:
            graph_profile.disarm()
    t_build = time.perf_counter() - t0
    label = f"{dtype}" + (f"+{accum_dtype}-accum" if accum_dtype != dtype else "")
    if wide_accum == "pair":
        label += "+pair"
    if part:
        label += f"+span{part}"
        if stream_dtype:
            label += f"+{stream_dtype}"
    if kernel == "pallas":
        label += "+pallas"
    if build_only:
        del engine
        print(f"build[{label}]: warm rebuild {t_build:.1f}s "
              "(tuning+compile cache)", file=sys.stderr)
        return {"build_s": t_build}
    print(
        f"graph[{label}]: scale {args.scale}: {1 << args.scale:,} vertices, "
        f"{num_edges:,} unique edges "
        f"({'host' if host_build else 'device'} build {t_build:.1f}s)",
        file=sys.stderr,
    )
    chips = engine.mesh.devices.size

    for _ in range(args.warmup):
        engine._device_step()
    engine.fence()  # block_until_ready is not honest on tunneled backends

    t0 = time.perf_counter()
    for _ in range(args.iters):
        engine._device_step()
    engine.fence()
    dt = time.perf_counter() - t0

    eps_chip = num_edges * args.iters / dt / chips
    print(
        f"rate[{label}]: {args.iters} iters in {dt:.3f}s on {chips} chip(s): "
        f"{dt / args.iters * 1e3:.2f} ms/iter, {eps_chip:.4g} edges/s/chip",
        file=sys.stderr,
    )
    costs, lowering = _leg_costs(engine, dt / args.iters, num_edges,
                                 dump_hlo=args.dump_hlo, label=label)
    layout = engine.layout_info()
    graph_block = _leg_graph_block(engine, host_graph, layout)
    # SDC detection overhead (ISSUE 15; pagerank_tpu/sdc.py): when
    # --sdc-check-every arms the plane, time the CHECKED step against
    # the plain loop just measured — the per-checked-iteration cost a
    # production config pays amortized over its cadence. None when
    # disarmed (the schema is None-tolerant by contract,
    # tests/test_bench_contract.py).
    sdc_overhead = None
    if getattr(args, "sdc_check_every", 0):
        sdc_overhead = _sdc_overhead_pct(engine, args.iters,
                                         dt / args.iters)
        print(f"sdc[{label}]: checked-step overhead "
              f"{sdc_overhead:.1f}% per checked iteration",
              file=sys.stderr)
    del engine  # free HBM before the next config builds
    return {
        "sdc_check_overhead_pct": sdc_overhead,
        "value": eps_chip,
        "vs_baseline": eps_chip / NORTH_STAR_EDGES_PER_SEC_PER_CHIP,
        "build_s": t_build,  # graph build wall-clock (VERDICT r3 weak #1)
        # XLA cost model per compiled form + achieved-vs-roofline at
        # the measured rate (obs/costs; None fields where the backend
        # doesn't report) — the "is this fast enough" anchor the r5
        # backend-variance incident lacked.
        "costs": costs,
        # The RESOLVED kernel/layout/autotune decisions (ISSUE 6) so
        # every BENCH_r*.json cell is attributable to a concrete
        # layout — including a pallas probe fallback, the autotuned
        # chunk, and the partition-centric geometry when engaged.
        "layout": layout,
        # The compiler-plane lowering verdict (ISSUE 11; obs/hlo.py):
        # gather strategy, fusion count, collective multiset, the
        # HLO-derived bytes/edge, and the structural fingerprint the
        # perf-history ledger tracks. None when the backend reports
        # no optimized HLO.
        "lowering": lowering,
        # Data-plane block (ISSUE 13; obs/graph_profile.py): the
        # structural profile + skew-driven prediction this leg's
        # graph/layout implies — the DATA axis the perf-history
        # classifier attributes against. None on non-reporting paths.
        "graph": graph_block,
    }


def _sdc_overhead_pct(engine, iters: int, plain_s_per_iter: float):
    """Per-checked-iteration SDC detection overhead: ``iters`` full
    checked boundaries — the standalone boundary-state dispatch, the
    ABFT-checked step with its host fetch, AND the host-side invariant
    reconciliation, i.e. exactly what ``SdcGuard.checked_step`` pays
    per boundary — against the plain loop's measured wall, as percent
    extra. The probe retains/restores the engine state, so the
    measured solve trajectory is untouched; the checked programs
    compile OUTSIDE the timed region (the prepare_fused
    discipline)."""
    from pagerank_tpu import sdc as sdc_mod

    cfg = engine.config
    ne = int(engine.graph.num_edges) if engine.graph is not None else None

    def boundary():
        pre = engine.sdc_state_values()
        _info, chk = engine.step_sdc()
        sdc_mod.evaluate_check(
            pre, chk, damping=cfg.damping, semantics=cfg.semantics,
            n=int(engine.graph.n), num_edges=ne,
            eps=engine._ledger_eps(),
        )

    token = engine.retain_state()
    try:
        boundary()  # compile + warm outside the timing
        engine.restore_state(token)
        t0 = time.perf_counter()
        for _ in range(max(1, iters)):
            boundary()
        checked = (time.perf_counter() - t0) / max(1, iters)
    finally:
        engine.restore_state(token)
    return max(0.0, (checked - plain_s_per_iter)
               / max(plain_s_per_iter, 1e-12) * 100.0)


def _leg_graph_block(engine, host_graph, layout):
    """One rate leg's ``graph`` data-plane block (ISSUE 13): the
    profile the device build published (or a numpy profile of the host
    graph at the leg's RESOLVED layout geometry) plus the load
    prediction for the leg's mesh. None when neither source exists
    (e.g. a restored device graph without its artifact)."""
    from pagerank_tpu.obs import graph_profile
    from pagerank_tpu.parallel import comms

    prof = graph_profile.get_profile()
    if prof is None and host_graph is not None:
        group, span = graph_profile.layout_profile_geometry(layout)
        prof = graph_profile.profile_graph(
            host_graph, group=group, partition_span=span,
        )
        graph_profile.publish(prof)
    if prof is None:
        return None
    pred = comms.predict_from_profile(prof, engine.mesh.devices.size)
    comms.publish_prediction(pred)
    prof.prediction = pred
    return {"profile": prof.summary(), "prediction": pred}


def _leg_costs(engine, seconds_per_iter, num_edges, dump_hlo=None,
               label=""):
    """One rate leg's cost + lowering blocks: reset both ledgers
    (per-leg scoping — a warm second leg must not inherit the first
    leg's stale stage entries), harvest the step program(s) ONCE with
    the compiler-plane inspector armed (ISSUE 11: the lowering reports
    come off the same compiled handles as the cost model — zero extra
    compiles), attach the measured per-iteration wall, and snapshot
    both. The wall attaches ONLY to the whole-iteration 'step'
    program: on multi-dispatch layouts the ledger holds
    prescale/stripe{i}/final instead, and dividing the finalize
    program's bytes (a fraction of the iteration's traffic) by the
    full wall would fabricate a too-low roofline fraction — the
    per-program models stay unmeasured there (roofline null).

    Returns ``(costs, lowering)`` — ``lowering`` is the per-form
    LoweringReport dict (gather strategy, fusion count, fingerprint,
    hlo_bytes_per_edge), or ``None`` when the backend reports no HLO.
    ``dump_hlo`` additionally writes each form's raw optimized HLO to
    that directory as ``<label>.<form>.hlo`` for offline diffing."""
    from pagerank_tpu.obs import costs as obs_costs
    from pagerank_tpu.obs import hlo as obs_hlo

    obs_costs.reset()
    obs_hlo.reset()
    obs_hlo.arm()
    try:
        engine.cost_reports()
    finally:
        obs_hlo.disarm()
    step = obs_costs.attach_measurement("step", seconds_per_iter,
                                        num_edges=num_edges)
    if step is not None and step.bytes_per_edge is not None:
        line = f"cost[step]: {step.bytes_per_edge:.1f} B/edge"
        if step.roofline_fraction is not None:
            line += f", {step.roofline_fraction:.1%} of HBM roofline"
        print(line, file=sys.stderr)
    lowering = obs_hlo.ledger_snapshot() or None
    whole = (lowering or {}).get("step") or (lowering or {}).get("final")
    if whole is not None:
        g = whole.get("gather") or {}
        print(
            f"lowering[{label or 'step'}]: gather "
            f"{str(g.get('strategy', '?')).upper()}, "
            f"{whole.get('fusion_count')} fusion(s), fingerprint "
            f"{whole.get('fingerprint')}",
            file=sys.stderr,
        )
    if dump_hlo:
        written = obs_hlo.dump_texts(dump_hlo, prefix=label)
        if written:
            print(f"dumped {len(written)} HLO module(s) to {dump_hlo}",
                  file=sys.stderr)
    return obs_costs.ledger_snapshot(), lowering


def run_accuracy(scale: int = 20, iters: int = 50, with_bf16: bool = False,
                 bf16_partition_span: int = -1):
    """Standing accuracy field: the accuracy-grade TPU config (pair-f64:
    f64 rank storage + pair-packed f64 accumulation) vs the float64 CPU
    oracle on the SAME host-built R-MAT graph, full-run L1.

    Two numbers, both reported: ``normalized_l1_vs_f64_oracle`` (raw
    N-scaled vectors; ~3e-14 measured at scale-20/50-iters) and
    ``mass_normalized_l1`` (unit-mass vectors — the relative structure
    PageRank defines; ~1.5e-14). They can diverge only through a
    global-scale error, which is how the f64-vdot lowering bug was
    found and fixed (docs/PERF_NOTES.md "Reference-mode mass growth and
    the f64-vdot lowering bug") — keeping both makes any regression of
    that class immediately visible.
    """
    from pagerank_tpu import (JaxTpuEngine, PageRankConfig,
                              ReferenceCpuEngine, build_graph)
    from pagerank_tpu.utils.synth import rmat_edges

    t0 = time.perf_counter()
    src, dst = rmat_edges(scale, 16, seed=3)
    g = build_graph(src, dst, n=1 << scale)
    cfg_pair = PageRankConfig(
        num_iters=iters, dtype="float64", accum_dtype="float64",
        wide_accum="pair",
    )
    r_tpu = JaxTpuEngine(cfg_pair).build(g).run_fast()
    cfg_f64 = PageRankConfig(num_iters=iters, dtype="float64",
                             accum_dtype="float64")
    r_cpu = ReferenceCpuEngine(cfg_f64).build(g).run()
    from pagerank_tpu.utils.metrics import oracle_l1

    l1, norm, mass_norm = oracle_l1(r_tpu, r_cpu)
    print(
        f"accuracy[pair-f64]: scale-{scale}, {iters} iters: "
        f"L1 vs f64 oracle {l1:.3e} (normalized {norm:.3e}, "
        f"mass-normalized {mass_norm:.3e}) "
        f"[{time.perf_counter() - t0:.1f}s]",
        file=sys.stderr,
    )
    out = {
        "config": "pair-f64",
        "scale": scale,
        "iters": iters,
        "normalized_l1_vs_f64_oracle": norm,
        "mass_normalized_l1": mass_norm,
    }
    if with_bf16:
        # The fast_bf16 leg's accuracy bound (ISSUE 6 acceptance): the
        # SAME graph and iteration count through the bf16-streamed
        # partitioned form, diffed against the SAME f64 oracle the
        # pair run is certified by — the pair-f64 oracle chain bounds
        # the leg's normalized-L1 error in every bench artifact that
        # ships the leg.
        span = bf16_partition_span
        if span == -1:
            from pagerank_tpu.ops.device_build import plan_build

            cfg_f = PageRankConfig(num_iters=iters)
            _g2, _s2, span = plan_build(
                cfg_f, g.n, host=True, num_edges=g.num_edges,
                partition_span=-1,
            )
            if not span:
                _g2, _s2, span = plan_build(
                    cfg_f, g.n, host=True, num_edges=g.num_edges,
                    partition_span=_fallback_span(g.n),
                )
        cfg_b = PageRankConfig(
            num_iters=iters, dtype="float32", accum_dtype="float32",
            stream_dtype="bfloat16", partition_span=span,
        )
        r_b = JaxTpuEngine(cfg_b).build(g).run_fast()
        _l1b, norm_b, mass_b = oracle_l1(r_b, r_cpu)
        print(
            f"accuracy[fast_bf16]: scale-{scale}, {iters} iters: "
            f"normalized L1 vs f64 oracle {norm_b:.3e} "
            f"(mass-normalized {mass_b:.3e})",
            file=sys.stderr,
        )
        out["fast_bf16"] = {
            "normalized_l1_vs_f64_oracle": norm_b,
            "mass_normalized_l1": mass_b,
        }
    return out


def _mc_leg(graph, *, ndev, iters, warmup, halo, label, dump_hlo=None,
            kernel="", partition_span=0, halo_async=False,
            pack_cache=None):
    """One multichip rate leg: a vertex-sharded f32 solve over ``ndev``
    devices through the dense or sparse (halo) exchange. Returns the
    leg dict: edges/s/chip, cost + layout + comms blocks, the
    actually-accumulated ``comms.bytes_exchanged`` delta for the timed
    iterations (the model is static, so delta == iters * model — the
    equality is part of what the schema test pins), and the
    comms-vs-compute ``attribution`` block (ISSUE 10): fenced
    exchange-only vs full-step sub-dispatch timing + achieved wire
    bytes/s against the model — the is-it-exchange-bound verdict the
    next TPU session reads first.

    ``kernel``/``partition_span`` (ISSUE 16): the ``pallas_partitioned``
    leg runs the fused Mosaic kernel's replicated-rank partitioned
    layout over the same mesh instead — the hand kernel doesn't compose
    with the vertex-sharded exchange (it consumes the whole rank vector
    per source window), so its multichip series measures the
    data-parallel form; the recorded layout says which one ran.

    ``halo_async`` (ISSUE 17): the ``sparse_async`` leg runs the
    stale-boundary double-buffered exchange (config.halo_async) with
    the auto-gate threshold pinned to 0 so the leg measures the async
    form even at geometries where the gate would normally refuse it.
    ``pack_cache`` (ISSUE 17): a dict shared across legs so every leg
    whose resolved layout plan matches reuses ONE host ELL pack
    instead of re-packing the same graph per leg."""
    from pagerank_tpu import PageRankConfig
    from pagerank_tpu.engines.jax_engine import JaxTpuEngine
    from pagerank_tpu.obs import devices as obs_devices
    from pagerank_tpu.obs import metrics as obs_metrics

    if kernel:
        cfg = PageRankConfig(
            num_iters=iters, dtype="float32", accum_dtype="float32",
            num_devices=ndev, kernel=kernel,
            partition_span=partition_span,
        ).validate()
    else:
        # Gate pinned open for the async bench leg: the whole point is
        # to MEASURE the async form; the auto-gate's prediction is a
        # separate recorded fact (comms.predicted_overlap_gain).
        async_kw = ({"halo_async": True, "halo_async_min_gain": 0.0}
                    if halo_async else {})
        cfg = PageRankConfig(
            num_iters=iters, dtype="float32", accum_dtype="float32",
            num_devices=ndev, vertex_sharded=True, halo_exchange=halo,
            **async_kw,
        ).validate()
    t0 = time.perf_counter()
    engine = JaxTpuEngine(cfg, pack_cache=pack_cache).build(graph)
    t_build = time.perf_counter() - t0
    for _ in range(warmup):
        engine._device_step()
    engine.fence()
    ctr = obs_metrics.counter("comms.bytes_exchanged")
    c0 = ctr.value
    t0 = time.perf_counter()
    for _ in range(iters):
        engine._device_step()
    engine.fence()
    dt = time.perf_counter() - t0
    # Counter delta read BEFORE attribution: the attribution's own
    # timing steps legitimately accumulate bytes too, and the schema
    # test pins delta == iters * model for the TIMED loop.
    bytes_exchanged = int(ctr.value - c0)
    attribution = obs_devices.attribute_exchange(
        engine, iters=max(2, min(iters, 10)), warmup=1
    )
    eps_chip = graph.num_edges * iters / dt / ndev
    line = (
        f"multichip[{label}]: {iters} iters on {ndev} device(s): "
        f"{dt / iters * 1e3:.2f} ms/iter, {eps_chip:.4g} edges/s/chip"
    )
    if attribution and attribution.get("exchange_fraction") is not None:
        line += (
            f"; exchange {attribution['exchange_s'] * 1e3:.2f} ms "
            f"({attribution['exchange_fraction']:.0%} of step"
            + (f", {attribution['achieved_bytes_per_sec'] / 1e9:.2f} "
               f"GB/s achieved"
               if attribution.get("achieved_bytes_per_sec") else "")
            + ")"
        )
    print(line, file=sys.stderr)
    costs, lowering = _leg_costs(engine, dt / iters, graph.num_edges,
                                 dump_hlo=dump_hlo, label=label)
    # Fresh per-leg data-plane block (ISSUE 13): each leg profiles at
    # ITS layout geometry and predicts for ITS mesh size — the
    # predicted-vs-measured skew pairing lives within one leg.
    from pagerank_tpu.obs import graph_profile

    graph_profile.reset()
    graph_block = _leg_graph_block(engine, graph, engine.layout_info())
    leg = {
        "value": eps_chip,
        "vs_baseline": eps_chip / NORTH_STAR_EDGES_PER_SEC_PER_CHIP,
        "n_devices": ndev,
        "ms_per_iter": dt / iters * 1e3,
        "build_s": t_build,
        "costs": costs,
        "lowering": lowering,
        "graph": graph_block,
        "layout": engine.layout_info(),
        "comms": engine.comms_model(),
        "bytes_exchanged": bytes_exchanged,
        # Comms-vs-compute wall attribution (ISSUE 10; obs/devices):
        # None on layouts without an exchange-only program
        # (multi-dispatch downgrades).
        "attribution": attribution,
    }
    del engine
    return leg


def run_multichip(args):
    """The MULTICHIP benchmark promoted from dryrun to headline
    (ISSUE 8): shard ONE host-built R-MAT graph over the mesh and
    measure the vertex-sharded f32 solve through the DENSE exchange
    (all_gather + reduce-scatter) and the SPARSE boundary exchange
    (config.halo_exchange), against a single-device leg of the same
    config for the scaling-efficiency figure. A separate accuracy leg
    (scale capped at ``--accuracy-scale``-with-a-floor-of-18 when the
    headline scale exceeds it) runs the sparse 8-device solve against
    the f64 CPU oracle — the pair-f64 oracle chain every other gate
    uses. One JSON line, schema pinned by
    tests/test_bench_contract.py::test_multichip_json_contract."""
    import jax

    from pagerank_tpu import (PageRankConfig, ReferenceCpuEngine,
                              build_graph)
    from pagerank_tpu.engines.jax_engine import JaxTpuEngine
    from pagerank_tpu.parallel import mesh as mesh_lib
    from pagerank_tpu.utils.metrics import oracle_l1
    from pagerank_tpu.utils.synth import rmat_edges

    ndev = min(args.multichip_devices, len(jax.devices()))
    t0 = time.perf_counter()
    src, dst = rmat_edges(args.scale, args.edge_factor, seed=0)
    graph = build_graph(src, dst, n=1 << args.scale)
    print(
        f"multichip graph: scale {args.scale}: {graph.n:,} vertices, "
        f"{graph.num_edges:,} unique edges "
        f"({time.perf_counter() - t0:.1f}s host build)",
        file=sys.stderr,
    )
    # One host ELL pack shared across every leg whose resolved layout
    # plan matches (ISSUE 17): single/dense/sparse/async all resolve
    # the same packer plan for this graph, so the graph is packed ONCE;
    # the pallas leg's partition-span plan differs and packs its own.
    pack_cache = {}
    kw = dict(iters=args.iters, warmup=args.warmup,
              dump_hlo=args.dump_hlo, pack_cache=pack_cache)
    single = _mc_leg(graph, ndev=1, halo=False, label="single_chip", **kw)
    dense = _mc_leg(graph, ndev=ndev, halo=False, label="dense_exchange",
                    **kw)
    sparse = _mc_leg(graph, ndev=ndev, halo=True,
                     label="sparse_exchange", **kw)
    sparse_async = _mc_leg(graph, ndev=ndev, halo=True, halo_async=True,
                           label="sparse_async", **kw)
    # Fused Mosaic kernel leg (ISSUE 16): the partitioned pallas form
    # over the same mesh (replicated ranks — see _mc_leg docstring),
    # so the multichip cell carries the hand-kernel series too. Span:
    # the engine's auto rule, with the couple legs' quarter-range
    # fallback when the rule says "off" at this geometry.
    n_padded = -(-graph.n // 128) * 128
    pspan = JaxTpuEngine.partition_span(n_padded, graph.num_edges) \
        or _fallback_span(graph.n)
    pallas = _mc_leg(graph, ndev=ndev, halo=False,
                     label="pallas_partitioned", kernel="pallas",
                     partition_span=pspan, **kw)
    # Overlap verdict (ISSUE 17): is the async leg's measured full-step
    # wall strictly below the sync leg's compute + exchange sum? That
    # sum is what the synchronous schedule PAYS per step; the async
    # schedule's ceiling is max(compute, comms). Both sides come from
    # the fenced attribution blocks of THIS run.
    overlap = None
    a_sync, a_async = sparse.get("attribution"), \
        sparse_async.get("attribution")
    if a_sync and a_async:
        sync_sum = a_sync["compute_s"] + a_sync["exchange_s"]
        overlap = {
            "sync_compute_plus_exchange_s": sync_sum,
            "async_step_s": a_async["step_s"],
            "async_below_sync_sum": bool(a_async["step_s"] < sync_sum),
            "gain": (1.0 - a_async["step_s"] / sync_sum
                     if sync_sum > 0 else None),
        }
        print(
            f"multichip[overlap]: async step "
            f"{a_async['step_s'] * 1e3:.2f} ms vs sync compute+exchange "
            f"{sync_sum * 1e3:.2f} ms "
            f"({'HIDDEN' if overlap['async_below_sync_sum'] else 'NOT hidden'})",
            file=sys.stderr,
        )
    cm = sparse["comms"]
    # The sparse leg can legitimately DOWNGRADE to the dense exchange
    # (multi-dispatch layouts past SCAN_STRIPE_UNITS; layout_info's
    # "halo" note says why) — report that honestly instead of
    # comparing against a model that never ran.
    sm = cm.get("sparse_bytes_per_iter")
    out = {
        "metric": "multichip_edges_per_sec_per_chip",
        "value": sparse["value"],
        "unit": "edges/s/chip",
        "n_devices": ndev,
        "scale": args.scale,
        "iters": args.iters,
        "single_chip": single,
        "dense_exchange": dense,
        "sparse_exchange": sparse,
        "sparse_async": sparse_async,
        "pallas_partitioned": pallas,
        # Sync-sum vs async-step wall comparison (ISSUE 17); None when
        # either leg lacks an attribution block.
        "exchange_overlap": overlap,
        # Per-chip rate retained at ndev chips vs 1 chip — the honest
        # scale-out figure (1.0 = linear scaling).
        "scaling_efficiency": sparse["value"] / single["value"],
        "scaling_efficiency_dense": dense["value"] / single["value"],
        "exchanged_bytes": {
            "sparse_model_per_iter": sm,
            "dense_model_per_iter": cm["dense_bytes_per_iter"],
            "sparse_below_dense": (
                bool(sm < cm["dense_bytes_per_iter"])
                if sm is not None else None
            ),
            "halo_fraction": cm["halo_fraction"],
            "head_k": cm["head_k"],
        },
        # One line per mesh device (id/kind/process/HBM when the
        # backend reports it) — the per-device evidence the watchdog
        # prints, embedded so a MULTICHIP cell records what mesh it
        # actually ran on (parallel/mesh.device_view).
        "device_view": list(mesh_lib.device_view()),
    }
    # Oracle leg: the sparse exchange at >= scale-18 class (capped so
    # the f64 CPU oracle pass stays tractable at headline scales) vs
    # the f64 oracle, through the SAME sparse 8-device step.
    acc_scale = min(args.scale, max(18, args.accuracy_scale)) \
        if args.scale > 18 else args.scale
    acc_iters = min(args.iters, 20)
    if acc_scale == args.scale:
        g_acc = graph
    else:
        s2, d2 = rmat_edges(acc_scale, args.edge_factor, seed=3)
        g_acc = build_graph(s2, d2, n=1 << acc_scale)
    cfg_s = PageRankConfig(
        num_iters=acc_iters, dtype="float32", accum_dtype="float32",
        num_devices=ndev, vertex_sharded=True, halo_exchange=True,
    )
    eng = JaxTpuEngine(cfg_s, pack_cache=pack_cache).build(g_acc)
    r_sparse = eng.run_fast()
    acc_cm = eng.comms_model()
    del eng
    cfg_o = PageRankConfig(num_iters=acc_iters, dtype="float64",
                           accum_dtype="float64")
    r_oracle = ReferenceCpuEngine(cfg_o).build(g_acc).run()
    _l1, norm, mass_norm = oracle_l1(r_sparse, r_oracle)
    print(
        f"multichip accuracy[sparse {ndev}-dev]: scale-{acc_scale}, "
        f"{acc_iters} iters: normalized L1 vs f64 oracle {norm:.3e}",
        file=sys.stderr,
    )
    acc_sm = acc_cm.get("sparse_bytes_per_iter")
    out["accuracy"] = {
        "config": f"sparse-exchange f32 x{ndev}",
        "scale": acc_scale,
        "iters": acc_iters,
        "normalized_l1_vs_f64_oracle": norm,
        "mass_normalized_l1": mass_norm,
        "sparse_model_per_iter": acc_sm,
        "dense_model_per_iter": acc_cm["dense_bytes_per_iter"],
        "sparse_below_dense": (
            bool(acc_sm < acc_cm["dense_bytes_per_iter"])
            if acc_sm is not None else None
        ),
    }
    # Convergence-vs-staleness sweep (ISSUE 17): iterations-to-tol at
    # boundary lag 0 (async plumbing, fresh reads — must match sync)
    # vs lag 1 (the overlapped schedule) — what the one-iteration
    # staleness COSTS in convergence, priced in iterations. Textbook
    # semantics: the contraction guarantees a fixed point to converge
    # TO (reference semantics legitimately diverges on graphs with
    # zero-in-degree vertices, so "iterations to tol" is undefined
    # there); tol 1e-6 sits above the f32 noise floor.
    sweep_tol, sweep_cap = 1e-6, 400
    sweep = {"tol": sweep_tol, "semantics": "textbook", "legs": {}}
    for name, akw in (
        ("sync", {}),
        ("async_lag0", {"halo_async": True, "stale_max_lag": 0,
                        "halo_async_min_gain": 0.0}),
        ("async_lag1", {"halo_async": True, "stale_max_lag": 1,
                        "halo_async_min_gain": 0.0}),
    ):
        cfg_w = PageRankConfig(
            num_iters=sweep_cap, dtype="float32", accum_dtype="float32",
            num_devices=ndev, vertex_sharded=True, halo_exchange=True,
            semantics="textbook", **akw,
        ).validate()
        eng_w = JaxTpuEngine(cfg_w, pack_cache=pack_cache).build(g_acc)
        eng_w.run_fused_tol(tol=sweep_tol, num_iters=sweep_cap)
        sweep["legs"][name] = {
            "iters_to_tol": int(eng_w.iteration),
            "converged": bool(eng_w.iteration < sweep_cap),
        }
        del eng_w
    print(
        "multichip staleness sweep (textbook, tol "
        f"{sweep_tol:g}): " + ", ".join(
            f"{k}={v['iters_to_tol']}" for k, v in sweep["legs"].items()),
        file=sys.stderr,
    )
    out["staleness_sweep"] = sweep
    # The async leg carries its own iters-to-tol so the history
    # normalizer (obs/history) can track it as a first-class leg metric.
    sparse_async["iters_to_tol"] = \
        sweep["legs"]["async_lag1"]["iters_to_tol"]
    out["edge_factor"] = args.edge_factor
    out["env"] = _env_fingerprint()
    return _emit(out, args)


def _preflight(args) -> bool:
    """bench --preflight (ISSUE 10): run the OOM fit check at the
    geometry this invocation is ABOUT to build, before any device
    allocation. Couple mode checks the headline pair-f64 config (the
    fattest resident set of the couple's legs); --dtype checks that
    config; --multichip checks the vertex-sharded solve over the leg
    mesh (host-built graph — the build stages don't gate). Prints the
    per-stage table to stderr; returns whether it fits."""
    from pagerank_tpu.obs import devices as obs_devices

    if args.multichip:
        # Model the mesh the legs will ACTUALLY run on: run_multichip
        # clamps to the visible devices, and a wider modeled mesh
        # would shard the residency thinner than reality — a preflight
        # that passes a run that then OOMs. The run's FIRST leg is a
        # single-chip solve of the same graph (full-width tables and
        # state on one chip, ~ndev x the sharded residency) — gate
        # that too, or the fattest leg slips past the check.
        import jax

        ndev = min(args.multichip_devices, len(jax.devices()))
        res_single = obs_devices.fit_check(
            args.scale, edge_factor=args.edge_factor,
            ndev=1, vertex_sharded=True,
            device_build=False,
        )
        print(obs_devices.render_fit(res_single), file=sys.stderr)
        if not res_single.fits:
            return False
        res = obs_devices.fit_check(
            args.scale, edge_factor=args.edge_factor,
            ndev=ndev, vertex_sharded=True,
            device_build=False,
        )
    else:
        dtype = args.dtype or "float64"
        wide = "auto" if args.dtype else "pair"
        res = obs_devices.fit_check(
            args.scale, edge_factor=args.edge_factor,
            dtype=dtype, wide_accum=wide,
            device_build=not args.host_build,
            # The invocation's own layout flags (the gate must model
            # the build this run executes; plan_build applies the
            # same mode gating the legs do).
            stripe_size=args.stripe_size, lane_group=args.lane_group,
            partition_span=args.partition_span,
        )
    print(obs_devices.render_fit(res), file=sys.stderr)
    return res.fits


def run_ppr_serve(args):
    """The ``ppr_serve`` leg (ISSUE 18): drive the resident PPR query
    daemon (pagerank_tpu/serving/) open-loop at ``--serve-qps`` and
    report the serving headline — sustained queries/s over accepted
    queries, exact p50/p99 latency (percentiles over the per-query
    walls, NOT the coarse power-of-two histogram buckets), the shed
    fraction (typed Overloaded rejections / offered), and the rescue
    count. One JSON line, ``metric: ppr_serve_queries_per_sec``;
    --history normalizes it into the ``ppr_serve`` ledger leg."""
    import numpy as np

    from pagerank_tpu import PageRankConfig, build_graph
    from pagerank_tpu.serving import PprServer, ServeConfig
    from pagerank_tpu.serving import qtrace
    from pagerank_tpu.testing.load import QueryLoadGenerator
    from pagerank_tpu.utils.synth import rmat_edges

    n = 1 << args.scale
    src, dst = rmat_edges(args.scale, edge_factor=args.edge_factor,
                          seed=args.seed)
    graph = build_graph(src, dst, n=n)
    cfg = PageRankConfig(num_iters=args.iters)
    sc = ServeConfig(
        max_batch=args.serve_max_batch,
        queue_depth=args.serve_queue_depth,
        deadline_ms=args.serve_deadline_ms,
        topk=min(args.serve_topk, n),
    )
    server = PprServer(graph, config=cfg, serve_config=sc)
    server.start()  # dispatcher thread; AOT warm happens here

    gap = 1.0 / max(args.serve_qps, 1e-9)
    plan = QueryLoadGenerator(
        seed=args.seed, num_queries=args.serve_queries, n=n,
        mean_gap_s=gap, k=sc.topk,
        deadline_range_s=(sc.deadline_ms / 1e3, sc.deadline_ms / 1e3),
    ).plan()

    # Query plane (ISSUE 19): armed for the measured window so the
    # leg carries WHERE the tail lives, not just how long it is.
    plane = qtrace.arm_query_plane()
    try:
        handles = []
        t0 = time.perf_counter()
        for gap_s, source, k, deadline_s in plan:
            time.sleep(gap_s)
            handles.append(
                server.submit(source, k=k, deadline_s=deadline_s))
        # Settle: every handle resolves (answered or typed-rejected) —
        # accounting identity, nothing silently dropped.
        settle = sc.deadline_ms / 1e3 + sc.dispatch_timeout_s + 5.0
        for q in handles:
            q.wait(timeout=settle)
        elapsed = time.perf_counter() - t0
        rescues = server.rescues_done
        server.drain()
        phase_p99_ms = plane.phase_p99_ms()
    finally:
        qtrace.disarm_query_plane()

    outcomes = {}
    lat_ms = []
    for q in handles:
        outcomes[q.outcome or "unsettled"] = (
            outcomes.get(q.outcome or "unsettled", 0) + 1
        )
        if q.outcome.startswith("answered") and q.latency_s is not None:
            lat_ms.append(q.latency_s * 1e3)
    answered = sum(v for k_, v in outcomes.items()
                   if k_.startswith("answered"))
    shed = outcomes.get("shed_overload", 0)
    out = {
        "metric": "ppr_serve_queries_per_sec",
        "value": answered / elapsed if elapsed > 0 else 0.0,
        "unit": "queries/s",
        "p50_ms": float(np.percentile(lat_ms, 50)) if lat_ms else None,
        "p99_ms": float(np.percentile(lat_ms, 99)) if lat_ms else None,
        # ISSUE 19: per-phase p99 decomposition of the tail (query
        # plane) — --history lifts each leg into *_p99_ms columns.
        "phase_p99_ms": phase_p99_ms,
        "shed_fraction": shed / len(handles) if handles else 0.0,
        "rescues": rescues,
        "queries": len(handles),
        "answered": answered,
        "outcomes": outcomes,
        "elapsed_s": elapsed,
        "offered_qps": args.serve_qps,
        "scale": args.scale,
        "iters": args.iters,
        "edge_factor": args.edge_factor,
        "max_batch": sc.max_batch,
        "deadline_ms": sc.deadline_ms,
        "queue_depth": sc.queue_depth,
        "topk": sc.topk,
        "env": _env_fingerprint(),
    }
    return _emit(out, args)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--scale", type=int, default=23,
                   help="R-MAT scale (2^scale vertices). 23 = 8.4M "
                        "vertices / 131M unique edges — the largest "
                        "SINGLE-stripe point for both configs since the "
                        "pair bound moved to 8.4M, and the best-measured "
                        "pair rate (2.58e8 vs 2.22e8 at scale 22; "
                        "BASELINE.md)")
    p.add_argument("--edge-factor", type=int, default=16)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--dtype", default=None,
                   help="single-config mode: run ONLY this dtype "
                        "(storage and accumulation). Default: couple "
                        "mode — pair-f64 headline + f32 secondary")
    p.add_argument("--kernel", default="auto",
                   help="auto|ell|pallas|coo (engine kernels)")
    p.add_argument("--lane-group", type=int, default=0,
                   help="grouped-lane ELL group size; 0 = auto (64 plain "
                        "/ 16 pair single-stripe / 64 pair striped, the "
                        "v5e-measured optima; see ops/ell.py and "
                        "docs/PERF_NOTES.md)")
    p.add_argument("--stripe-size", type=int, default=0,
                   help="source-stripe span in vertices (0 = auto: "
                        "single stripe up to 8.4M f32 vertices / 4.2M "
                        "pair, full-bound stripes of the same span "
                        "above, widened on sparse graphs — the measured "
                        "optima; see jax_engine.stripe_limits and "
                        "occupancy_span)")
    p.add_argument("--partition-span", type=int, default=0,
                   help="partition-centric layout span (ISSUE 6). "
                        "Couple mode: the partitioned_f32/fast_bf16 "
                        "legs always run (0 here means those legs use "
                        "the engine's auto rule); single-config mode: "
                        "0 = off, -1 = auto, >0 = explicit span for "
                        "the one measured config")
    p.add_argument("--multichip", action="store_true",
                   help="the multichip benchmark (ISSUE 8): a vertex-"
                        "sharded f32 solve over the mesh through the "
                        "dense AND the sparse (halo) exchange, plus a "
                        "single-device leg for scaling efficiency and "
                        "an oracle-parity accuracy leg; one JSON line "
                        "(MULTICHIP_*.json schema)")
    p.add_argument("--multichip-devices", type=int, default=8,
                   help="device count for the --multichip legs "
                        "(clamped to the visible mesh)")
    p.add_argument("--ppr-serve", action="store_true",
                   help="the serving benchmark (ISSUE 18): drive the "
                        "resident PPR query daemon "
                        "(pagerank_tpu/serving/) open-loop at "
                        "--serve-qps and report sustained queries/s, "
                        "exact p50/p99 latency, shed fraction, and "
                        "rescue count — one JSON line "
                        "(ppr_serve_queries_per_sec)")
    p.add_argument("--serve-queries", type=int, default=200,
                   help="queries offered by the --ppr-serve leg")
    p.add_argument("--serve-qps", type=float, default=100.0,
                   help="offered open-loop rate for --ppr-serve "
                        "(mean of the seeded exponential gaps)")
    p.add_argument("--serve-max-batch", type=int, default=8,
                   help="--ppr-serve daemon micro-batch size (the ONE "
                        "AOT-warmed program's static batch)")
    p.add_argument("--serve-deadline-ms", type=float, default=500.0,
                   help="--ppr-serve per-query deadline")
    p.add_argument("--serve-queue-depth", type=int, default=64,
                   help="--ppr-serve admission queue bound")
    p.add_argument("--serve-topk", type=int, default=64,
                   help="--ppr-serve top-k returned per query "
                        "(clamped to n)")
    p.add_argument("--seed", type=int, default=0,
                   help="R-MAT + load-plan seed (--ppr-serve)")
    p.add_argument("--host-build", action="store_true",
                   help="build the graph on host + transfer (default: on-device)")
    p.add_argument("--build-only", action="store_true",
                   help="device builds only, with the per-stage "
                        "breakdown (BUILD_STAGE_KEYS); couple mode "
                        "builds pair-f64 then f32 and reports the "
                        "ratio, --dtype builds one config")
    p.add_argument("--accuracy-scale", type=int, default=20,
                   help="R-MAT scale of the standing accuracy probe")
    p.add_argument("--no-accuracy", action="store_true",
                   help="skip the standing accuracy field")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="ALSO write the JSON record here, directly "
                        "(ISSUE 9: the canonical BENCH_r*.json shape — "
                        "no more tail-scraped {n,cmd,rc,tail,parsed} "
                        "wrapper; the perf-history ledger still "
                        "ingests the legacy r01-r05 wrappers)")
    p.add_argument("--history", default=None, metavar="LEDGER",
                   help="auto-append this run, normalized to the "
                        "canonical RunRecord, to the append-only perf "
                        "ledger (pagerank_tpu/obs/history.py; couple, "
                        "single, --build-only, and --multichip runs "
                        "alike). Inspect with `python -m "
                        "pagerank_tpu.obs history trend LEDGER`")
    p.add_argument("--dump-hlo", default=None, metavar="DIR",
                   help="ALSO write every rate leg's optimized HLO "
                        "modules to DIR as <leg>.<form>.hlo for "
                        "offline diffing (ISSUE 11; obs/hlo.py) — the "
                        "classified verdict rides the JSON's per-leg "
                        "'lowering' block either way")
    p.add_argument("--sdc-check-every", type=int, default=0,
                   metavar="K",
                   help="ALSO measure the SDC-checked step's overhead "
                        "per rate leg (ISSUE 15; pagerank_tpu/sdc.py): "
                        "each leg's JSON carries "
                        "'sdc_check_overhead_pct' — percent extra wall "
                        "per CHECKED iteration vs the plain step "
                        "(amortize over the cadence K for the "
                        "production cost). 0 (default) disarms: the "
                        "field rides as null and zero check "
                        "computations run")
    p.add_argument("--preflight", action="store_true",
                   help="OOM-preflight fit check (ISSUE 10; "
                        "obs/devices.fit_check) BEFORE anything "
                        "allocates: abstract-eval the build+step at "
                        "this run's geometry against per-chip HBM "
                        "(bytes_limit or the device-kind table) and "
                        "exit 3 with the per-stage table when it "
                        "provably does not fit — a 75 s scale-24 "
                        "build should never be how we learn the "
                        "answer")
    args = p.parse_args(argv)

    # Cache BEFORE the preflight: its AOT stage compiles are the same
    # programs the build will compile — repeat preflights (the
    # gate-then-run workflow) and the run itself share the entries.
    _enable_compile_cache()

    if args.preflight and not _preflight(args):
        # Same code as the CLI's --preflight refusal (the exit-code
        # taxonomy, pagerank_tpu/exitcodes.py; bench exited 2 here
        # before ISSUE 12 unified the two).
        sys.exit(int(ExitCode.PREFLIGHT_UNFIT))

    if args.ppr_serve:
        return run_ppr_serve(args)

    if args.multichip:
        return run_multichip(args)

    if args.build_only:
        if args.host_build:
            p.error("--build-only measures the device build pipeline; "
                    "drop --host-build")
        if args.kernel not in ("auto", "ell"):
            # pallas builds group=1/unstriped and coo coerces the host
            # path (run_rate) — the breakdown would silently measure a
            # DIFFERENT build than that config runs.
            p.error(f"--build-only measures the XLA ell build layout; "
                    f"--kernel {args.kernel} builds a different one")
        kw = dict(scale=args.scale, edge_factor=args.edge_factor,
                  stripe_size=args.stripe_size, lane_group=args.lane_group)
        if args.dtype is not None:
            rec = run_build(dtype=args.dtype, **kw)
            out = {"metric": "build_s", "value": rec["build_s"],
                   "unit": "s", "scale": args.scale, **rec}
        else:
            # Pair FIRST (it flips x64 mid-build): the f32 build then
            # reuses the 32-bit-pinned stage executables across the
            # flip (utils/compile_cache.stage_call), which is the
            # cache's whole point.
            pair = run_build(dtype="float64", accum_dtype="float64",
                             wide_accum="pair", **kw)
            f32 = run_build(dtype="float32", **kw)
            # Warm pair rebuild: the leg that actually measures the
            # index-width claim for the 15% couple gate. The cold pair
            # leg runs first and pays every shared cold compile, so
            # pair_over_f32 is cache-temperature-biased against pair
            # on a fresh checkout (.jax_cache is gitignored); both
            # ratios are reported, gate on the warm one.
            pair_warm = run_build(dtype="float64", accum_dtype="float64",
                                  wide_accum="pair",
                                  label="float64+pair warm", **kw)
            out = {"metric": "build_s", "value": pair["build_s"],
                   "unit": "s", "scale": args.scale, "pair": pair,
                   "f32": f32, "pair_warm": pair_warm,
                   "pair_over_f32": pair["build_s"] / f32["build_s"],
                   "pair_warm_over_f32":
                       pair_warm["build_s"] / f32["build_s"]}
        out["env"] = _env_fingerprint()
        return _emit(out, args)

    if args.dtype is not None:
        # Single-config mode (the original schema).
        rate = run_rate(args, args.dtype, args.dtype,
                        partition_span=args.partition_span)
        out = {
            "metric": "edges_per_sec_per_chip",
            "value": rate["value"],
            "unit": "edges/s/chip",
            "vs_baseline": rate["vs_baseline"],
            "build_s": rate["build_s"],
            "costs": rate["costs"],
            "lowering": rate["lowering"],
            "graph": rate["graph"],
            "layout": rate["layout"],
            "sdc_check_overhead_pct": rate["sdc_check_overhead_pct"],
            "scale": args.scale,
            "iters": args.iters,
            "edge_factor": args.edge_factor,
        }
        if not args.no_accuracy:
            out["accuracy"] = run_accuracy(args.accuracy_scale, args.iters)
        out["env"] = _env_fingerprint()
        return _emit(out, args)

    # Couple mode: the headline is the ACCURACY-GRADE config's rate
    # (pair-f64: f64 storage + pair accumulation — f32 storage loses
    # the 1e-6 grade over 50 reference-semantics iterations, see module
    # docstring), with the plain-f32 rate and the standing oracle-L1
    # field alongside — one artifact demonstrating the <60s-AND-1e-6
    # north-star couple. wide_accum is PINNED to pair so the headline
    # measures the same kernel the accuracy probe certifies on every
    # backend ("auto" would resolve to native f64 off-TPU).
    pair_rate = run_rate(args, "float64", "float64", wide_accum="pair")
    f32_rate = run_rate(args, "float32", "float32")
    # Partition-centric legs (ISSUE 6): the SAME f32 workload through
    # the partitioned layout, and its bf16-streamed variant — separate
    # legs so the win (and its cost-model bytes/edge delta vs the
    # fast_f32 'step' form) is attributable. --partition-span > 0
    # forces the span; otherwise the engine's auto rule (with a
    # small-graph fallback) sizes it, and each leg's "layout" records
    # what actually ran.
    leg_span = args.partition_span if args.partition_span > 0 else -1
    part_rate = run_rate(args, "float32", "float32",
                         partition_span=leg_span,
                         force_span_fallback=True)
    bf16_rate = run_rate(args, "float32", "float32",
                         partition_span=leg_span,
                         stream_dtype="bfloat16",
                         force_span_fallback=True)
    # Fused Mosaic kernel leg (ISSUE 16): the SAME partitioned f32
    # workload through ops/pallas_spmv.ell_contrib_pallas_partitioned
    # instead of the XLA gather pipeline — its own series so the
    # hand-kernel-vs-XLA delta is attributable per round. The kernel
    # override is leg-local; a probe downgrade records itself in the
    # leg's layout (kernel_requested='pallas', form back to
    # 'partitioned') rather than silently re-measuring the XLA leg.
    pallas_rate = run_rate(args, "float32", "float32",
                           partition_span=leg_span,
                           force_span_fallback=True,
                           kernel="pallas")
    out = {
        "metric": "edges_per_sec_per_chip",
        "value": pair_rate["value"],
        "unit": "edges/s/chip",
        "vs_baseline": pair_rate["vs_baseline"],
        "build_s": pair_rate["build_s"],
        "costs": pair_rate["costs"],  # headline (pair) leg's cost model
        "lowering": pair_rate["lowering"],  # headline lowering verdict
        "graph": pair_rate["graph"],  # headline data-plane block
        "layout": pair_rate["layout"],
        # Headline leg's SDC detection overhead (ISSUE 15): null
        # unless --sdc-check-every armed the measurement.
        "sdc_check_overhead_pct": pair_rate["sdc_check_overhead_pct"],
        "fast_f32": f32_rate,  # carries its own "costs" block
        "partitioned_f32": part_rate,
        "pallas_partitioned": pallas_rate,
        "fast_bf16": bf16_rate,
        "scale": args.scale,
        "iters": args.iters,
        "edge_factor": args.edge_factor,
    }
    if not args.host_build and args.kernel != "coo":
        # LAST, so the rebuild cannot perturb the rate legs; warm by
        # construction (same config as the first leg). Device builds
        # only — the host path's cost is numpy gen + pack + transfer,
        # which no cache affects (and --kernel coo coerces run_rate to
        # the host path regardless of the flag).
        out["build_warm_s"] = run_rate(
            args, "float64", "float64", wide_accum="pair", build_only=True
        )["build_s"]
    if not args.no_accuracy:
        # with_bf16: the fast_bf16 leg ships in this artifact, so its
        # oracle-L1 bound ships next to it (ISSUE 6 acceptance).
        out["accuracy"] = run_accuracy(args.accuracy_scale, args.iters,
                                       with_bf16=True)
    out["env"] = _env_fingerprint()
    return _emit(out, args)


if __name__ == "__main__":
    main()
