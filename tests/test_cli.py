"""CLI end-to-end tests (config/flag subsystem, SURVEY.md §5)."""

import json
import re

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.cli import main
from pagerank_tpu.ingest import save_binary_edges


@pytest.fixture
def edges_file(tmp_path):
    rng = np.random.default_rng(0)
    src, dst = rng.integers(0, 40, 200), rng.integers(0, 40, 200)
    p = tmp_path / "edges.txt"
    lines = ["# test graph"] + [f"{s} {d}" for s, d in zip(src, dst)]
    p.write_text("\n".join(lines) + "\n")
    return str(p), src, dst


def read_ranks_tsv(path, n):
    out = np.zeros(n)
    with open(path) as f:
        for line in f:
            k, v = line.split("\t")
            out[int(k)] = float(v)
    return out


def test_cli_edgelist_matches_oracle(tmp_path, edges_file):
    path, src, dst = edges_file
    out = str(tmp_path / "ranks.tsv")
    rc = main(
        ["--input", path, "--iters", "10", "--engine", "jax", "--out", out,
         "--dtype", "float64", "--log-every", "0"]
    )
    assert rc == 0
    g = build_graph(src, dst)
    expected = ReferenceCpuEngine(PageRankConfig(num_iters=10)).build(g).run()
    got = read_ranks_tsv(out, g.n)
    np.testing.assert_allclose(got, expected, rtol=0, atol=1e-9)


def test_cli_npz_and_jsonl_metrics(tmp_path, edges_file):
    _, src, dst = edges_file
    npz = str(tmp_path / "edges.npz")
    save_binary_edges(npz, src, dst, n=40)
    jsonl = str(tmp_path / "metrics.jsonl")
    rc = main(["--input", npz, "--iters", "5", "--engine", "cpu",
               "--jsonl", jsonl, "--log-every", "0"])
    assert rc == 0
    recs = [json.loads(l) for l in open(jsonl)]
    assert len(recs) == 5
    assert recs[0]["iter"] == 0 and "l1_delta" in recs[0]


def test_cli_crawl_autodetect(tmp_path):
    p = tmp_path / "crawl.tsv"
    meta = json.dumps({"content": {"links": [{"href": "http://b", "type": "a"}]}})
    p.write_text(f"http://a\t{meta}\nhttp://b\t{json.dumps({})}\n")
    out = str(tmp_path / "ranks.tsv")
    rc = main(["--input", str(p), "--iters", "3", "--engine", "cpu",
               "--out", out, "--log-every", "0"])
    assert rc == 0
    text = open(out).read()
    assert "http://a\t" in text and "http://b\t" in text


def test_cli_seq_prefixed_text_is_not_seqfile(tmp_path):
    # A text input whose first bytes happen to be "SEQ" must fall through
    # to the text-format detection (the binary sniff also requires a
    # plausible version byte <= 6), not hard-fail in the SequenceFile
    # reader (ADVICE r1).
    p = tmp_path / "crawl.tsv"
    meta = json.dumps({"content": {"links": [{"href": "http://b", "type": "a"}]}})
    p.write_text(f"SEQ://a\t{meta}\nhttp://b\t{json.dumps({})}\n")
    out = str(tmp_path / "ranks.tsv")
    rc = main(["--input", str(p), "--iters", "2", "--engine", "cpu",
               "--out", out, "--log-every", "0"])
    assert rc == 0
    assert "SEQ://a\t" in open(out).read()
    # Control bytes after 'SEQ' that are NOT a supported version (<= 6)
    # also fall through: a crawl row whose url is literally "SEQ" makes
    # the file start with b"SEQ\t" (0x09) — text, not a SequenceFile
    # (ADVICE r2).
    p2 = tmp_path / "crawl2.tsv"
    p2.write_text(f"SEQ\t{meta}\nhttp://b\t{json.dumps({})}\n")
    out2 = str(tmp_path / "ranks2.tsv")
    rc = main(["--input", str(p2), "--iters", "2", "--engine", "cpu",
               "--out", out2, "--log-every", "0"])
    assert rc == 0
    assert "SEQ\t" in open(out2).read()


def test_cli_device_build_matches_host_build(tmp_path, edges_file):
    """--device-build packs the graph on device (the bench's fast path,
    VERDICT r2 #3); ranks must match the host-built run exactly on the
    same input, for both edgelist and npz inputs."""
    path, src, dst = edges_file
    out_h = str(tmp_path / "host.tsv")
    out_d = str(tmp_path / "dev.tsv")
    base = ["--iters", "8", "--dtype", "float64", "--accum-dtype",
            "float64", "--log-every", "0"]
    assert main(["--input", path, "--out", out_h] + base) == 0
    assert main(["--input", path, "--out", out_d, "--device-build"] + base) == 0
    n = 40
    np.testing.assert_allclose(
        read_ranks_tsv(out_d, n), read_ranks_tsv(out_h, n), rtol=0, atol=1e-12
    )
    npz = str(tmp_path / "edges.npz")
    save_binary_edges(npz, src, dst, n=n)
    out_z = str(tmp_path / "npz.tsv")
    assert main(["--input", npz, "--out", out_z, "--device-build"] + base) == 0
    np.testing.assert_allclose(
        read_ranks_tsv(out_z, n), read_ranks_tsv(out_h, n), rtol=0, atol=1e-12
    )


def test_cli_device_build_synthetic_snapshot_resume(tmp_path):
    """--synthetic rmat:N --device-build runs end-to-end, snapshots via
    the DeviceEllGraph fingerprint, and resumes to the same ranks as an
    uninterrupted run."""
    ck = str(tmp_path / "ck")
    out1 = str(tmp_path / "r1.tsv")
    out2 = str(tmp_path / "r2.tsv")
    base = ["--synthetic", "rmat:8", "--device-build", "--log-every", "0"]
    assert main(base + ["--iters", "6", "--out", out1]) == 0
    assert main(base + ["--iters", "3", "--snapshot-dir", ck]) == 0
    assert main(base + ["--iters", "6", "--snapshot-dir", ck, "--resume",
                        "--out", out2]) == 0
    n = 1 << 8
    np.testing.assert_allclose(
        read_ranks_tsv(out2, n), read_ranks_tsv(out1, n), rtol=0, atol=1e-6
    )


def test_cli_device_build_rejections(tmp_path):
    # cpu engine has no device path
    assert main(["--synthetic", "rmat:6", "--device-build",
                 "--engine", "cpu"]) == 2
    # PPR builds from a host graph
    assert main(["--synthetic", "rmat:6", "--device-build",
                 "--ppr-sources", "0,1"]) == 2


def test_cli_device_build_crawl_matches_host(tmp_path):
    """Crawl/seqfile inputs compose with --device-build: host-side id
    assignment, on-device dedup/sort/pack with the reference's
    uncrawled-targets dangling mask (NOT out_degree==0 — http://c is
    crawled and linkless, so it must carry no dangling mass), names
    preserved in the output."""
    from pagerank_tpu.ingest import write_sequence_file

    def meta(targets):
        return json.dumps(
            {"content": {"links": [{"type": "a", "href": t} for t in targets]}}
        )

    records = [
        ("http://a/", meta(["http://b/", "http://d/", "http://b/"])),
        ("http://b/", meta(["http://a/", "http://c/"])),
        ("http://c/", meta([])),  # crawled, linkless: NOT dangling
        # http://d/ never crawled: dangling
    ]
    seg = tmp_path / "seg"
    seg.mkdir()
    write_sequence_file(str(seg / "metadata-00000"), records[:2])
    write_sequence_file(str(seg / "metadata-00001"), records[2:])
    outs = []
    for extra in ([], ["--device-build"]):
        out = str(tmp_path / f"r{len(outs)}.tsv")
        assert main(["--input", str(seg), "--iters", "6", "--out", out,
                     "--log-every", "0"] + extra) == 0
        with open(out) as f:
            outs.append(dict(line.split("\t") for line in f))
    assert set(outs[0]) == set(outs[1]) == {
        "http://a/", "http://b/", "http://c/", "http://d/"}
    for k in outs[0]:
        assert abs(float(outs[0][k]) - float(outs[1][k])) < 1e-5, k
    # TSV crawl files route the same way
    p = tmp_path / "crawl.tsv"
    p.write_text("".join(f"{u}\t{m}\n" for u, m in records))
    out = str(tmp_path / "tsv.tsv")
    assert main(["--input", str(p), "--iters", "6", "--out", out,
                 "--device-build", "--log-every", "0"]) == 0
    with open(out) as f:
        tsv_ranks = dict(line.split("\t") for line in f)
    for k in outs[0]:
        assert abs(float(outs[0][k]) - float(tsv_ranks[k])) < 1e-5, k


def test_cli_snapshot_resume(tmp_path, edges_file):
    path, src, dst = edges_file
    ck = str(tmp_path / "ckpt")
    out1 = str(tmp_path / "r1.tsv")
    main(["--input", path, "--iters", "4", "--engine", "cpu",
          "--snapshot-dir", ck, "--log-every", "0"])
    main(["--input", path, "--iters", "10", "--engine", "cpu",
          "--snapshot-dir", ck, "--resume", "--out", out1, "--log-every", "0"])
    g = build_graph(src, dst)
    expected = ReferenceCpuEngine(PageRankConfig(num_iters=10)).build(g).run()
    got = read_ranks_tsv(out1, g.n)
    np.testing.assert_allclose(got, expected, rtol=0, atol=1e-12)


def test_cli_synthetic(tmp_path):
    rc = main(["--synthetic", "rmat:8", "--iters", "2", "--engine", "cpu",
               "--log-every", "0"])
    assert rc == 0


def test_cli_tol_early_stop(edges_file, capsys):
    path, _, _ = edges_file
    rc = main(["--input", path, "--iters", "500", "--engine", "cpu",
               "--tol", "1e-9", "--log-every", "0"])
    assert rc == 0


def test_cli_textbook_semantics(tmp_path, edges_file):
    path, src, dst = edges_file
    out = str(tmp_path / "ranks.tsv")
    rc = main(["--input", path, "--iters", "20", "--semantics", "textbook",
               "--engine", "cpu", "--out", out, "--log-every", "0"])
    assert rc == 0
    g = build_graph(src, dst)
    got = read_ranks_tsv(out, g.n)
    assert got.sum() == pytest.approx(1.0, abs=1e-9)


def test_cli_ppr(tmp_path, edges_file):
    path, src, dst = edges_file
    out = str(tmp_path / "ppr.tsv")
    rc = main(["--input", path, "--iters", "10", "--ppr-sources", "0,3",
               "--ppr-topk", "5", "--out", out, "--log-every", "0"])
    assert rc == 0
    lines = open(out).read().splitlines()
    assert len(lines) == 2 * 5
    s0, v0, r0 = lines[0].split("\t")
    assert s0 == "0" and float(r0) > 0
    # top hit for a source under source-dangling PPR is usually itself —
    # at minimum scores are descending per source
    scores = [float(l.split("\t")[2]) for l in lines[:5]]
    assert scores == sorted(scores, reverse=True)


def test_cli_ppr_url_sources_resolve_through_id_map(tmp_path, capsys):
    # URL-named vertices contain "://"; a comma list of them must go
    # through the id map, not be mistaken for a filesystem path.
    p = tmp_path / "crawl.tsv"
    meta_a = json.dumps({"content": {"links": [{"href": "http://b", "type": "a"}]}})
    meta_b = json.dumps({"content": {"links": [{"href": "http://a", "type": "a"}]}})
    p.write_text(f"http://a\t{meta_a}\nhttp://b\t{meta_b}\n")
    rc = main(["--input", str(p), "--iters", "5", "--engine", "cpu",
               "--ppr-sources", "http://a,http://b", "--ppr-topk", "2",
               "--log-every", "0"])
    assert rc == 0
    rows = [l for l in capsys.readouterr().out.splitlines() if l.count("\t") == 2]
    assert len(rows) == 2 * 2
    assert rows[0].startswith("http://a\t")


def test_cli_ppr_random_sources(edges_file, capsys):
    path, _, _ = edges_file
    rc = main(["--input", path, "--iters", "5", "--ppr-sources", "random:4",
               "--ppr-topk", "3", "--log-every", "0"])
    assert rc == 0
    rows = [l for l in capsys.readouterr().out.splitlines() if l.count("\t") == 2]
    assert len(rows) == 4 * 3


def test_cli_ppr_bad_source(edges_file):
    path, _, _ = edges_file
    with pytest.raises(SystemExit):
        main(["--input", path, "--ppr-sources", "999999", "--log-every", "0"])


def test_cli_ppr_cpu_engine_matches_jax(tmp_path, edges_file):
    path, src, dst = edges_file
    out_j = str(tmp_path / "ppr_jax.tsv")
    out_c = str(tmp_path / "ppr_cpu.tsv")
    base = ["--input", path, "--iters", "8", "--ppr-sources", "0,3",
            "--ppr-topk", "4", "--log-every", "0", "--dtype", "float64"]
    assert main(base + ["--engine", "jax", "--out", out_j]) == 0
    assert main(base + ["--engine", "cpu", "--out", out_c]) == 0
    rows_j = [l.split("\t") for l in open(out_j).read().splitlines()]
    rows_c = [l.split("\t") for l in open(out_c).read().splitlines()]
    assert [r[:2] for r in rows_j] == [r[:2] for r in rows_c]
    np.testing.assert_allclose(
        [float(r[2]) for r in rows_j], [float(r[2]) for r in rows_c],
        rtol=1e-9,
    )


def test_cli_ppr_rejects_global_only_flags(tmp_path, edges_file):
    path, _, _ = edges_file
    with pytest.raises(SystemExit, match="--snapshot-dir"):
        main(["--input", path, "--ppr-sources", "0", "--snapshot-dir",
              str(tmp_path / "s"), "--log-every", "0"])


def test_cli_ppr_rejects_vertex_sharded_and_lane_group(edges_file):
    """PprJaxEngine implements neither the memory-scaling mode nor the
    lane-group override; asking for them must fail loudly, not no-op
    (VERDICT r4 weak #2)."""
    path, _, _ = edges_file
    with pytest.raises(SystemExit, match="--vertex-sharded"):
        main(["--input", path, "--ppr-sources", "0", "--vertex-sharded",
              "--log-every", "0"])
    with pytest.raises(SystemExit, match="--lane-group"):
        main(["--input", path, "--ppr-sources", "0", "--lane-group", "8",
              "--log-every", "0"])


@pytest.mark.parametrize("spec", ["random:abc", "random:-3", "random:0"])
def test_cli_ppr_bad_random_spec(edges_file, spec):
    path, _, _ = edges_file
    with pytest.raises(SystemExit, match="--ppr-sources"):
        main(["--input", path, "--ppr-sources", spec, "--log-every", "0"])


def test_cli_ppr_topk_clamped_message(edges_file, capsys):
    path, _, _ = edges_file
    rc = main(["--input", path, "--iters", "3", "--ppr-sources", "0",
               "--ppr-topk", "100000", "--log-every", "0"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "top-40" in err  # clamped to n=40, and reported as such


def test_cli_fused_matches_stepwise(tmp_path, edges_file):
    path, _, _ = edges_file

    out1 = tmp_path / "r1.tsv"
    out2 = tmp_path / "r2.tsv"
    jsonl = tmp_path / "m.jsonl"
    assert main(["--input", path, "--iters", "8",
                 "--out", str(out1), "--log-every", "0"]) == 0
    assert main(["--input", path, "--iters", "8", "--fused",
                 "--out", str(out2), "--jsonl", str(jsonl),
                 "--log-every", "0"]) == 0
    r1 = {l.split("\t")[0]: float(l.split("\t")[1]) for l in open(out1)}
    r2 = {l.split("\t")[0]: float(l.split("\t")[1]) for l in open(out2)}
    assert r1.keys() == r2.keys()
    for k in r1:
        assert abs(r1[k] - r2[k]) < 1e-5
    # per-iteration traces landed in the JSONL
    recs = [json.loads(l) for l in open(jsonl)]
    assert len(recs) == 8 and all("l1_delta" in r for r in recs)


def test_cli_fused_snapshots_match_stepwise(tmp_path, edges_file):
    """--fused --snapshot-dir runs chunked fused dispatches with
    snapshots at the boundaries; files and final ranks must match the
    stepwise run byte-for-byte (same arithmetic, same sink path)."""
    import os

    path, _, _ = edges_file
    ck_f = str(tmp_path / "ck_fused")
    ck_s = str(tmp_path / "ck_step")
    jsonl = str(tmp_path / "m.jsonl")
    assert main(["--input", path, "--iters", "6", "--fused",
                 "--snapshot-dir", ck_f, "--snapshot-every", "2",
                 "--jsonl", jsonl, "--log-every", "0"]) == 0
    assert main(["--input", path, "--iters", "6",
                 "--snapshot-dir", ck_s, "--snapshot-every", "2",
                 "--log-every", "0"]) == 0
    names = sorted(n for n in os.listdir(ck_f) if n.endswith(".npz"))
    assert names == ["ranks_iter2.npz", "ranks_iter4.npz", "ranks_iter6.npz"]
    assert names == sorted(n for n in os.listdir(ck_s) if n.endswith(".npz"))
    for n in names:
        a = np.load(os.path.join(ck_f, n))["ranks"]
        b = np.load(os.path.join(ck_s, n))["ranks"]
        np.testing.assert_array_equal(a, b)
    # chunked runs keep every iteration's trace
    recs = [json.loads(l) for l in open(jsonl)]
    assert len(recs) == 6 and all(r["timing"] == "averaged" for r in recs)


def test_cli_fused_snapshot_resume(tmp_path, edges_file):
    path, _, _ = edges_file
    ck = str(tmp_path / "ck")
    out_f = str(tmp_path / "rf.tsv")
    out_c = str(tmp_path / "rc.tsv")
    assert main(["--input", path, "--iters", "3", "--fused",
                 "--snapshot-dir", ck, "--log-every", "0"]) == 0
    # Resume from iteration 3 with cadence 2: chunks re-align to the
    # ABSOLUTE grid (boundary at 4, then 6), exactly like stepwise.
    assert main(["--input", path, "--iters", "7", "--fused",
                 "--snapshot-dir", ck, "--resume", "--snapshot-every", "2",
                 "--out", out_f, "--log-every", "0"]) == 0
    import os

    post = {n for n in os.listdir(ck) if n.endswith(".npz")}
    assert {"ranks_iter4.npz", "ranks_iter6.npz"} <= post
    assert "ranks_iter5.npz" not in post and "ranks_iter7.npz" not in post
    assert main(["--input", path, "--iters", "7", "--out", out_c,
                 "--log-every", "0"]) == 0
    r1 = {l.split("\t")[0]: float(l.split("\t")[1]) for l in open(out_f)}
    r2 = {l.split("\t")[0]: float(l.split("\t")[1]) for l in open(out_c)}
    assert r1 == r2


def test_cli_fused_remainder_chunk_follows_stepwise_cadence(tmp_path, edges_file):
    """iters not divisible by --snapshot-every: the fused final
    remainder chunk must NOT write an off-cadence snapshot — file sets
    stay identical to stepwise. Negative cadence is rejected outright."""
    import os

    path, _, _ = edges_file
    ck_f, ck_s = str(tmp_path / "f"), str(tmp_path / "s")
    assert main(["--input", path, "--iters", "7", "--fused",
                 "--snapshot-dir", ck_f, "--snapshot-every", "2",
                 "--log-every", "0"]) == 0
    assert main(["--input", path, "--iters", "7",
                 "--snapshot-dir", ck_s, "--snapshot-every", "2",
                 "--log-every", "0"]) == 0
    names = sorted(n for n in os.listdir(ck_f) if n.endswith(".npz"))
    assert names == ["ranks_iter2.npz", "ranks_iter4.npz", "ranks_iter6.npz"]
    assert names == sorted(n for n in os.listdir(ck_s) if n.endswith(".npz"))
    with pytest.raises(ValueError, match="snapshot_every"):
        main(["--input", path, "--iters", "4", "--fused",
              "--snapshot-dir", ck_f, "--snapshot-every", "-2",
              "--log-every", "0"])


def test_cli_fused_chunked_tol_stops_at_boundary(tmp_path, edges_file):
    path, _, _ = edges_file
    ck = str(tmp_path / "ck")
    jsonl = str(tmp_path / "m.jsonl")
    rc = main(["--input", path, "--iters", "60", "--fused", "--tol", "1e-3",
               "--snapshot-dir", ck, "--snapshot-every", "5",
               "--jsonl", jsonl, "--log-every", "0"])
    assert rc == 0
    recs = [json.loads(l) for l in open(jsonl)]
    # stopped early, at a chunk boundary, with per-iteration traces
    assert 0 < len(recs) < 60 and len(recs) % 5 == 0
    assert recs[-1]["l1_delta"] <= 1e-3


def test_cli_fused_jsonl_tags_averaged_timing(tmp_path, edges_file):
    # Fused per-iteration records carry synthetic (averaged) seconds;
    # the JSONL must say so (ADVICE r1).
    path, *_ = edges_file
    jsonl = str(tmp_path / "m.jsonl")
    rc = main(["--input", path, "--iters", "4", "--fused",
               "--jsonl", jsonl, "--log-every", "0"])
    assert rc == 0
    recs = [json.loads(l) for l in open(jsonl)]
    assert len(recs) == 4
    assert all(r.get("timing") == "averaged" for r in recs)


def test_cli_fused_rejects_host_control_flags(tmp_path, edges_file):
    path, _, _ = edges_file

    assert main(["--input", path, "--fused",
                 "--dump-text-dir", str(tmp_path / "d")]) == 2
    assert main(["--input", path, "--fused",
                 "--engine", "cpu"]) == 2


def test_cli_fused_with_tol_stops_early(tmp_path, edges_file, capsys):
    path, _, _ = edges_file
    out = tmp_path / "r.tsv"
    jsonl = tmp_path / "m.jsonl"
    assert main(["--input", path, "--iters", "100", "--fused",
                 "--tol", "1e-7", "--dtype", "float64",
                 "--accum-dtype", "float64", "--out", str(out),
                 "--jsonl", str(jsonl), "--log-every", "0"]) == 0
    recs = [json.loads(l) for l in open(jsonl)]
    assert len(recs) == 1  # dynamic trip count -> final record only
    assert recs[0]["l1_delta"] <= 1e-7
    assert recs[0]["iter"] < 99  # stopped well before the budget
    # the summary reports the TRUE iteration count, not len(history)
    err = capsys.readouterr().err
    m = re.search(r"done: (\d+) iters", err)
    assert m, err[-300:]
    assert 1 < int(m.group(1)) == recs[0]["iter"] + 1


def test_cli_top_n_output(tmp_path, edges_file):
    path, src, dst = edges_file
    out_full = str(tmp_path / "full.tsv")
    out_top = str(tmp_path / "top.tsv")
    base = ["--input", path, "--iters", "8", "--engine", "cpu",
            "--log-every", "0"]
    assert main(base + ["--out", out_full]) == 0
    assert main(base + ["--out", out_top, "--top", "5"]) == 0
    full = read_ranks_tsv(out_full, 40)
    lines = [l.split("\t") for l in open(out_top).read().splitlines()]
    assert len(lines) == 5
    got_ids = [int(k) for k, _ in lines]
    got_ranks = [float(v) for _, v in lines]
    # descending by rank, and exactly the 5 largest of the full vector
    assert got_ranks == sorted(got_ranks, reverse=True)
    assert sorted(got_ranks) == sorted(np.sort(full)[-5:].tolist())
    for i, r in zip(got_ids, got_ranks):
        assert full[i] == r
    # --top larger than n writes everything
    out_all = str(tmp_path / "all.tsv")
    assert main(base + ["--out", out_all, "--top", "1000"]) == 0
    assert len(open(out_all).read().splitlines()) == 40


def test_cli_top_boundary_ties_deterministic(tmp_path):
    # Equal ranks at the --top cutoff must select by ascending id —
    # a symmetric graph where several vertices tie exactly.
    p = tmp_path / "edges.txt"
    # ring of 6: every vertex has identical in/out structure -> all tie
    p.write_text("\n".join(f"{i} {(i + 1) % 6}" for i in range(6)) + "\n")
    out = str(tmp_path / "top.tsv")
    assert main(["--input", str(p), "--iters", "3", "--engine", "cpu",
                 "--out", out, "--top", "3", "--log-every", "0"]) == 0
    ids = [int(l.split("\t")[0]) for l in open(out).read().splitlines()]
    assert ids == [0, 1, 2]


def test_cli_device_build_uniform_synthetic(tmp_path):
    # uniform synthetic on --device-build generates ON device (only a
    # seed crosses the link) and is deterministic per seed.
    out1 = str(tmp_path / "u1.tsv")
    out2 = str(tmp_path / "u2.tsv")
    base = ["--synthetic", "uniform:300:2000", "--device-build",
            "--iters", "4", "--log-every", "0"]
    assert main(base + ["--out", out1]) == 0
    assert main(base + ["--out", out2]) == 0
    assert open(out1).read() == open(out2).read()
    assert len(open(out1).read().splitlines()) == 300


def test_cli_empty_input_device_build_clean_error(tmp_path):
    # ADVICE r3: an empty crawl input with --device-build must fail with
    # the host path's clean 'empty graph' error, not an obscure n=0
    # device-build failure downstream.
    p = str(tmp_path / "empty.txt")
    open(p, "w").close()
    with pytest.raises(SystemExit, match="empty graph"):
        main(["--input", p, "--device-build", "--log-every", "0"])


def test_cli_empty_input_host_build_clean_error(tmp_path):
    # The host path raises the same clean error (no raw traceback).
    p = str(tmp_path / "empty.txt")
    open(p, "w").close()
    with pytest.raises(SystemExit, match="empty graph"):
        main(["--input", p, "--log-every", "0"])


def test_cli_profile_dir_writes_trace(tmp_path, edges_file):
    # VERDICT r3 weak #5: pin --profile-dir so the flag cannot rot — a
    # 2-iter CPU-backend run must leave a non-empty trace directory.
    path, _, _ = edges_file
    prof = tmp_path / "trace"
    assert main(["--input", path, "--iters", "2", "--log-every", "0",
                 "--profile-dir", str(prof)]) == 0
    files = [p for p in prof.rglob("*") if p.is_file()]
    assert files, f"no trace files under {prof}"


def test_cli_host_mem_cap_external_build(tmp_path, edges_file):
    # --host-mem-cap-gb routes the edge-list build through the
    # out-of-core external-sort path; ranks identical to the default.
    path, _, _ = edges_file
    out_a = str(tmp_path / "a.tsv")
    out_b = str(tmp_path / "b.tsv")
    base = ["--input", path, "--iters", "4", "--log-every", "0",
            "--dtype", "float64"]
    assert main(base + ["--out", out_a]) == 0
    assert main(base + ["--host-mem-cap-gb", "1", "--out", out_b]) == 0
    assert open(out_a).read() == open(out_b).read()


def test_cli_host_mem_cap_incompatible_combos(tmp_path, edges_file):
    path, _, _ = edges_file
    with pytest.raises(SystemExit, match="host-mem-cap-gb"):
        main(["--input", path, "--host-mem-cap-gb", "1", "--device-build",
              "--log-every", "0"])
    with pytest.raises(SystemExit, match="host-mem-cap-gb"):
        main(["--synthetic", "rmat:8", "--host-mem-cap-gb", "1",
              "--log-every", "0"])
    # Crawl inputs COMPOSE with the cap since r5 (the out-of-core
    # native-L1 drain path) — but never silently: with the native path
    # disabled the memory-bound promise is rejected loudly.
    crawl = str(tmp_path / "c.tsv")
    open(crawl, "w").write(
        'http://a\t{"content":{"links":[{"type":"a","href":"http://b"}]}}\n'
    )
    with pytest.raises(SystemExit, match="native"):
        main(["--input", crawl, "--host-mem-cap-gb", "1",
              "--no-native-ingest", "--log-every", "0"])
    from pagerank_tpu.ingest import native as native_mod

    lib = native_mod.get_lib()
    if lib is not None and hasattr(lib, "crawl_drain_edges"):
        assert main(["--input", crawl, "--host-mem-cap-gb", "1",
                     "--log-every", "0"]) == 0
        # sub-floor caps are rejected loudly, mirroring the
        # integer-edge path's 64 MiB check (main() converts the
        # loader's ValueError into a clean SystemExit)
        with pytest.raises(SystemExit, match="128 MiB"):
            main(["--input", crawl, "--host-mem-cap-gb", "0.0625",
                  "--log-every", "0"])
