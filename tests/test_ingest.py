"""Ingestion tests (SURVEY.md §4 "Unit": JSON anchor filter, quote
stripping, dedup; loader round-trips)."""

import json

import numpy as np
import pytest

from pagerank_tpu.ingest import (
    IdMap,
    load_binary_edges,
    load_edgelist,
    parse_metadata_record,
    records_to_graph,
    save_binary_edges,
)
from pagerank_tpu.ingest.crawljson import iter_crawl_records


def meta(links):
    return json.dumps({"content": {"links": links}})


def test_anchor_filter_only_type_a():
    # Only type=="a" links count (Sparky.java:103); "img"/others dropped.
    m = meta(
        [
            {"href": "http://x/1", "type": "a"},
            {"href": "http://x/2", "type": "img"},
            {"href": "http://x/3", "type": "a"},
        ]
    )
    url, targets = parse_metadata_record("http://src", m)
    assert targets == ["http://x/1", "http://x/3"]


def test_non_string_type_never_matches():
    m = meta([{"href": "h", "type": 1}, {"href": "h2", "type": None}])
    _, targets = parse_metadata_record("u", m)
    assert targets == []


def test_quote_stripping_operates_on_gson_rendering():
    # replace("\"","") runs on the *quoted* Gson rendering
    # (Sparky.java:105): surrounding quotes vanish, and an embedded quote
    # was escaped to \" so stripping leaves its backslash behind.
    m = meta([{"href": 'a"b"c', "type": "a"}])
    _, targets = parse_metadata_record("u", m)
    assert targets == ["a\\b\\c"]
    m2 = meta([{"href": "plain", "type": "a"}])
    assert parse_metadata_record("u", m2)[1] == ["plain"]


def test_no_anchor_links_is_dangling():
    # Pages with no anchor links (or no content/links at all) are
    # dangling (Sparky.java:91-94,114-118).
    for m in [meta([]), meta([{"href": "h", "type": "img"}]),
              json.dumps({"content": {}}), json.dumps({}),
              json.dumps({"content": None})]:
        _, targets = parse_metadata_record("u", m)
        assert targets == []


def test_missing_href_strict_raises_lenient_skips():
    m = meta([{"type": "a"}, {"href": "ok", "type": "a"}])
    with pytest.raises(KeyError):
        parse_metadata_record("u", m, strict=True)
    _, targets = parse_metadata_record("u", m, strict=False)
    assert targets == ["ok"]


def test_malformed_json_strict_raises():
    with pytest.raises(json.JSONDecodeError):
        parse_metadata_record("u", "{not json", strict=True)
    assert parse_metadata_record("u", "{not json", strict=False) == ("u", [])


def test_records_to_graph_uncrawled_targets():
    graph, ids = records_to_graph([("a", ["b", "c"]), ("b", ["a"])])
    # c is linked-to but never crawled: exists, dangling (Sparky.java:137-161)
    assert graph.n == 3
    c = ids.get("c")
    assert graph.dangling_mask[c]
    assert graph.out_degree[c] == 0


def test_crawled_linkless_page_is_not_dangling():
    # The repair pass (Sparky.java:172-184) removes every *crawled* page
    # from dangUrls — lookup() wraps values in a list, so a crawled
    # linkless page's get(0) is the non-null Iterable([null]). Only
    # uncrawled targets carry dangling mass.
    graph, ids = records_to_graph([("a", ["b"]), ("b", [])])
    b = ids.get("b")
    assert graph.out_degree[b] == 0
    assert not graph.dangling_mask[b]  # crawled => repaired out of dangUrls


def test_idmap_roundtrip():
    ids = IdMap()
    assert ids.get_or_add("x") == 0
    assert ids.get_or_add("y") == 1
    assert ids.get_or_add("x") == 0
    assert "y" in ids and ids.get("z") is None
    assert ids.names == ["x", "y"]


def test_edgelist_text_loader(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("# comment line\n0 1\n1 2\n2 0\n\n# end\n3 1\n")
    src, dst = load_edgelist(str(p))
    np.testing.assert_array_equal(src, [0, 1, 2, 3])
    np.testing.assert_array_equal(dst, [1, 2, 0, 1])


def test_binary_roundtrip(tmp_path):
    p = str(tmp_path / "edges.npz")
    save_binary_edges(p, np.array([0, 1]), np.array([1, 2]), n=5)
    src, dst, n = load_binary_edges(p)
    np.testing.assert_array_equal(src, [0, 1])
    np.testing.assert_array_equal(dst, [1, 2])
    assert n == 5


def test_crawl_tsv_file(tmp_path):
    p = tmp_path / "crawl.tsv"
    rows = [
        "http://a\t" + meta([{"href": "http://b", "type": "a"}]),
        "http://b\t" + meta([]),
    ]
    p.write_text("\n".join(rows) + "\n")
    recs = list(iter_crawl_records(str(p)))
    assert recs == [("http://a", ["http://b"]), ("http://b", [])]


from pagerank_tpu.ingest.native import iter_read_batches



def test_iter_read_batches_cap_checked_before_append(tmp_path):
    # A file that would push a batch past the byte cap flushes the
    # current batch FIRST (ADVICE r3): with a 100-byte cap and files of
    # 60/60/250/10 bytes, batches are [60], [60], [250] (single file may
    # exceed the cap), [10] — never 60+60 or 250+10 together.
    sizes = [60, 60, 250, 10]
    paths = []
    for i, s in enumerate(sizes):
        p = str(tmp_path / f"f{i}")
        open(p, "wb").write(b"x" * s)
        paths.append(p)
    batches = list(iter_read_batches(paths, window=8, byte_cap=100))
    got = [[len(d) for d in datas] for _, datas in batches]
    assert got == [[60], [60], [250], [10]]
    # window bound still applies when under the cap
    batches = list(iter_read_batches(paths[:2], window=1, byte_cap=10**9))
    assert [[len(d) for d in ds] for _, ds in batches] == [[60], [60]]
