"""Tier-1 pins for the kernel-plane static analyzer (ISSUE 16;
pagerank_tpu/analysis/kernels.py).

The PTK rules prove a ``pl.pallas_call`` geometry safe WITHOUT running
it: VMEM budget (PTK001), tile/lane alignment (PTK002), index-map
coverage (PTK003), memory-space discipline (PTK004), and grid/cost
sanity (PTK005) — all from the traced jaxpr, so the pass runs on CPU
in tier-1. Pinned here:

- the shipped registry is clean after the checked-in allowlist, and
  the ONLY waived findings are the legacy whole-z kernel's PTK001 at
  the bench scales (the documented, runtime-downgraded geometry hole);
- the partitioned kernel is clean at every bench-campaign geometry —
  the "proved safe before TPU time" acceptance;
- every seeded-defect fixture trips exactly its rule;
- the numpy index-map interpreter agrees with the jax evaluator (the
  fast path is an optimization, never a semantics change);
- CLI: ``--select PTK`` exit codes and the strict ``--json`` schema.
"""

import json
import os

import numpy as np
import pytest

from pagerank_tpu.analysis import load_allowlist, split_allowlisted
from pagerank_tpu.analysis.__main__ import main as analysis_main
from pagerank_tpu.analysis import kernels as K
from pagerank_tpu.analysis.findings import Finding

ALLOWLIST = os.path.join(os.path.dirname(os.path.abspath(K.__file__)),
                         "allowlist.txt")

# fixture label -> the ONE rule it must trip (scripts/acceptance.py's
# kernel smoke pins the same mapping).
FIXTURE_RULES = {
    "fixture:vmem_overflow": "PTK001",
    "fixture:misaligned_tile": "PTK002",
    "fixture:index_gap": "PTK003",
    "fixture:index_overlap": "PTK003",
    "fixture:f64_scratch": "PTK004",
    "fixture:cost_mismatch": "PTK005",
}


@pytest.fixture(scope="module")
def shipped_findings():
    return K.check_kernel_plane()


def test_shipped_pass_is_clean_after_allowlist(shipped_findings):
    active, waived = split_allowlisted(
        shipped_findings, load_allowlist(ALLOWLIST)
    )
    assert active == [], [f.render() for f in active]
    # The only waived findings are the legacy kernel's PTK001 at the
    # bench scales — the waiver is geometry-bounded, not a blanket.
    assert len(waived) == len(K.BENCH_SCALES)
    for f, w in waived:
        assert f.rule == "PTK001"
        assert f.snippet.startswith("kernel=ell_contrib_pallas@scale")
        assert "partitioned" not in f.snippet


def test_legacy_kernel_overflows_vmem_at_every_bench_scale(
        shipped_findings):
    """The silent-scaling hole the ISSUE names: ell_contrib_pallas
    holds z_ext whole in VMEM, so PTK001 must FAIL it at every bench
    scale (and at nothing else — the toy geometry fits)."""
    for s in K.BENCH_SCALES:
        label = f"kernel=ell_contrib_pallas@scale{s}"
        rules = [f.rule for f in shipped_findings if f.snippet == label]
        assert rules == ["PTK001"], (s, rules)
    toy = [f for f in shipped_findings
           if f.snippet == "kernel=ell_contrib_pallas@toy"]
    assert toy == [], [f.render() for f in toy]


def test_partitioned_kernel_clean_at_all_bench_geometries(
        shipped_findings):
    """The acceptance: the partition-centric kernel passes PTK001-005
    at every scale-22..25 geometry (f32 and the bf16 stream) with NO
    allowlist help."""
    bad = [f for f in shipped_findings if "partitioned" in f.snippet]
    assert bad == [], [f.render() for f in bad]


def test_allowlist_anchor_cannot_waive_partitioned_labels():
    """Round-trip the checked-in waiver: it matches the legacy labels
    and ONLY them — a PTK001 regression in the partitioned kernel must
    surface, not vanish into the legacy kernel's documented hole."""
    waivers = [w for w in load_allowlist(ALLOWLIST)
               if w.rule == "PTK001"]
    assert waivers, "the legacy PTK001 waiver must exist"
    legacy = Finding(
        rule="PTK001", path="ops/pallas_spmv.py", line=1, message="m",
        snippet="kernel=ell_contrib_pallas@scale24",
    )
    partitioned = Finding(
        rule="PTK001", path="ops/pallas_spmv.py", line=1, message="m",
        snippet="kernel=ell_contrib_pallas_partitioned@scale24",
    )
    assert any(w.matches(legacy) for w in waivers)
    assert not any(w.matches(partitioned) for w in waivers)


def test_every_defect_fixture_is_pinned():
    assert {c.label for c in K.defect_cases()} == set(FIXTURE_RULES)


@pytest.mark.parametrize("label,rule", sorted(FIXTURE_RULES.items()))
def test_defect_fixture_trips_exactly_its_rule(label, rule):
    (case,) = [c for c in K.defect_cases() if c.label == label]
    rules = [f.rule for f in K.check_kernel_plane([case])]
    assert rules and set(rules) == {rule}, (label, rules)


def test_numpy_index_map_interpreter_matches_jax(monkeypatch):
    """The numpy fast path is the oracle-checked optimization: for the
    partitioned kernel's scalar-driven maps (the z-window dynamic
    slice included) it must produce bit-identical block indices to the
    jax evaluator — and it must actually ENGAGE (a silent fallback
    would put the eager-vmap recompile back on the CLI's hot path)."""
    case = next(c for c in K.shipped_cases()
                if c.label == "ell_contrib_pallas_partitioned@toy-span")
    calls = []
    orig = K._np_eval_index_map

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(K, "_np_eval_index_map", spy)
    site_np = K.extract_site(case)
    assert calls, "numpy interpreter never engaged on the shipped maps"

    def refuse(*a, **kw):
        raise K._NpUnsupported("forced jax fallback")

    monkeypatch.setattr(K, "_np_eval_index_map", refuse)
    site_jax = K.extract_site(case)
    pairs = list(zip(site_np.in_blocks + site_np.out_blocks,
                     site_jax.in_blocks + site_jax.out_blocks))
    assert pairs
    for (_, idx_np), (_, idx_jax) in pairs:
        np.testing.assert_array_equal(idx_np, idx_jax)


def test_cli_select_ptk_is_clean_on_the_repo(capsys):
    rc = analysis_main(["--select", "PTK", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] is True
    assert out["version"] == 1
    assert out["counts"]["active"] == 0
    assert out["counts"]["waived"] == len(K.BENCH_SCALES)
    assert out["findings"] == []


def test_cli_without_allowlist_reports_the_legacy_hole(capsys):
    rc = analysis_main(["--select", "PTK", "--json",
                        "--allowlist", "none"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["ok"] is False
    assert [f["rule"] for f in out["findings"]] == \
        ["PTK001"] * len(K.BENCH_SCALES)
    # Strict finding schema: the fields history/CI consume, no extras.
    for f in out["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message",
                          "snippet"}
        assert f["path"] == "ops/pallas_spmv.py" and f["line"] > 0


@pytest.mark.parametrize(
    "fixture", sorted(n.split(":", 1)[1] for n in FIXTURE_RULES)
)
def test_cli_fixture_exits_nonzero(capsys, fixture):
    rc = analysis_main(["--select", "PTK", "--json",
                        "--kernel-fixture", fixture])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["ok"] is False
    rules = {f["rule"] for f in out["findings"]}
    assert rules == {FIXTURE_RULES["fixture:" + fixture]}, out["findings"]
    # Fixture findings anchor to THIS analysis module, so the shipped
    # allowlist (scoped to ops/pallas_spmv.py) can never absorb them.
    assert out["counts"]["waived"] == 0


def test_cli_unknown_fixture_is_usage_error(capsys):
    rc = analysis_main(["--select", "PTK", "--kernel-fixture", "nope"])
    assert rc == 2
    assert "unknown kernel fixture" in capsys.readouterr().err


def test_list_rules_includes_the_kernel_plane(capsys):
    rc = analysis_main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rid in K.RULES:
        assert rid in out, rid
    assert "PTH004" in out
