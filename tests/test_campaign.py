"""Campaign-plane tests (ISSUE 20; pagerank_tpu/obs/campaign.py).

Fast tier: golden-artifact verdict fixtures (including degraded
inputs — missing lowering blocks, None cost fields, a leg that blew
its wall budget), the budget-proposal derivation, and the full
runner orchestration (resume-skip, drain, failure, byte-identical
stable report) driven through STUB entrypoints so no jax work runs.

Slow tier (excluded from tier-1 by ``-m 'not slow'``): the real
``python -m pagerank_tpu.obs campaign run --fake-devices 8`` dry run
as a subprocess, plus the SIGKILL-mid-leg chaos resume whose final
report must be byte-identical to an uninterrupted run — the ISSUE 20
acceptance criterion verbatim. The acceptance smoke AA
(scripts/acceptance.py) runs the same flow in the default order.
"""

import json
import os
import signal

import pytest

from pagerank_tpu import jobs
from pagerank_tpu.obs import campaign
from pagerank_tpu.obs import history
from pagerank_tpu.obs import report as report_mod
from pagerank_tpu.obs.__main__ import main as obs_main
from pagerank_tpu.testing.faults import ProcessKillPlan, \
    run_job_subprocess


# -- golden leg documents ----------------------------------------------------


def couple_output(part=4.2e8, f32=3.5e8, pallas=3.4e8,
                  kernel="pallas", requested=None,
                  with_lowering=True):
    out = {
        "metric": "edges_per_sec_per_chip",
        "value": 2.6e8,
        "fast_f32": {"value": f32},
        "partitioned_f32": {"value": part},
        "pallas_partitioned": {"value": pallas,
                               "layout": {"kernel": kernel}},
    }
    if requested is not None:
        out["pallas_partitioned"]["layout"]["kernel_requested"] = \
            requested
    if with_lowering:
        out["partitioned_f32"]["lowering"] = {
            "step": {"hlo_bytes_per_edge": 171.2}}
    return out


def multichip_output(sparse=2.0e8, dense=1.6e8, gain=0.12,
                     below=True, sync_iters=10, async_iters=12,
                     converged=True):
    return {
        "metric": "multichip_edges_per_sec_per_chip",
        "sparse_exchange": {
            "value": sparse,
            "attribution": {"exchange_fraction": 0.31,
                            "achieved_bytes_per_sec": 1.1e9},
        },
        "dense_exchange": {"value": dense},
        "exchange_overlap": {
            "sync_compute_plus_exchange_s": 0.010,
            "async_step_s": 0.010 * (1.0 - gain),
            "async_below_sync_sum": below,
            "gain": gain,
        },
        "exchanged_bytes": {"sparse_below_dense": True,
                            "halo_fraction": 0.07, "head_k": 128},
        "staleness_sweep": {"legs": {
            "sync": {"iters_to_tol": sync_iters, "converged": True},
            "async_lag1": {"iters_to_tol": async_iters,
                           "converged": converged},
        }},
    }


def serve_output(qps=150.0, p99=120.0, shed=0.05):
    return {"metric": "ppr_serve_queries_per_sec", "value": qps,
            "p99_ms": p99, "shed_fraction": shed}


@pytest.fixture(scope="module")
def budgets():
    return history.load_budgets(campaign.default_budgets_path())


# -- verdict extraction: typed decisions, degraded inputs --------------------


def test_partitioned_flip_and_keep(budgets):
    d, reason, ev = campaign.VERDICTS["partitioned_vs_default"](
        couple_output(part=4.2e8, f32=3.5e8), budgets)
    assert d == "flip_partitioned_to_default"
    assert ev["measured_ratio"] == pytest.approx(1.2)
    assert ev["model_ratio"] == pytest.approx(588.6 / 165.7)
    d, _, _ = campaign.VERDICTS["partitioned_vs_default"](
        couple_output(part=3.6e8, f32=3.5e8), budgets)
    assert d == "keep_step_default"


def test_partitioned_missing_lowering_block_still_decides(budgets):
    """Degraded input: no lowering block (backend reported no HLO) —
    the rate evidence still adjudicates; the HLO field is just None."""
    d, _, ev = campaign.VERDICTS["partitioned_vs_default"](
        couple_output(with_lowering=False), budgets)
    assert d == "flip_partitioned_to_default"
    assert ev["partitioned_hlo_bytes_per_edge"] is None


def test_partitioned_none_values_inconclusive(budgets):
    out = couple_output()
    out["fast_f32"]["value"] = None
    d, reason, _ = campaign.VERDICTS["partitioned_vs_default"](
        out, budgets)
    assert d == "inconclusive"
    assert "rate values" in reason
    d, _, _ = campaign.VERDICTS["partitioned_vs_default"](None, budgets)
    assert d == "inconclusive"


def test_pallas_keep_delete_and_downgrade(budgets):
    # Clears the 3.0e8 floor and holds >= 0.95x of the XLA leg.
    d, _, _ = campaign.VERDICTS["pallas_keep_or_delete"](
        couple_output(pallas=4.1e8, part=4.2e8), budgets)
    assert d == "keep_pallas_kernel"
    # Below the checked-in perf_budgets floor -> delete (PTH004).
    d, reason, _ = campaign.VERDICTS["pallas_keep_or_delete"](
        couple_output(pallas=2.0e8, part=4.2e8), budgets)
    assert d == "delete_pallas_kernel"
    assert "floor" in reason
    # Above the floor but losing >5% to XLA -> delete.
    d, _, _ = campaign.VERDICTS["pallas_keep_or_delete"](
        couple_output(pallas=3.2e8, part=4.2e8), budgets)
    assert d == "delete_pallas_kernel"
    # Probe downgrade: the kernel never ran -> inconclusive.
    d, reason, ev = campaign.VERDICTS["pallas_keep_or_delete"](
        couple_output(kernel="partitioned", requested="pallas"),
        budgets)
    assert d == "inconclusive"
    assert "downgraded" in reason
    assert ev["kernel_requested"] == "pallas"


def test_halo_vs_dense(budgets):
    d, _, ev = campaign.VERDICTS["halo_vs_dense"](
        multichip_output(sparse=2.0e8, dense=1.6e8), budgets)
    assert d == "keep_sparse_halo_default"
    assert ev["head_k"] == 128
    d, _, _ = campaign.VERDICTS["halo_vs_dense"](
        multichip_output(sparse=1.4e8, dense=1.6e8), budgets)
    assert d == "prefer_dense_exchange"
    d, _, _ = campaign.VERDICTS["halo_vs_dense"]({}, budgets)
    assert d == "inconclusive"


def test_async_overlap(budgets):
    d, _, _ = campaign.VERDICTS["async_overlap"](
        multichip_output(gain=0.12, below=True), budgets)
    assert d == "flip_halo_async_default"
    d, _, _ = campaign.VERDICTS["async_overlap"](
        multichip_output(gain=0.02, below=True), budgets)
    assert d == "keep_synchronous_exchange"
    # Convergence penalty eats the wall gain.
    d, reason, _ = campaign.VERDICTS["async_overlap"](
        multichip_output(gain=0.2, sync_iters=10, async_iters=40),
        budgets)
    assert d == "keep_synchronous_exchange"
    assert "penalty" in reason
    d, _, _ = campaign.VERDICTS["async_overlap"](
        multichip_output(gain=0.2, converged=False), budgets)
    assert d == "keep_synchronous_exchange"
    # Degraded: attribution block missing entirely.
    out = multichip_output()
    del out["exchange_overlap"]
    d, reason, _ = campaign.VERDICTS["async_overlap"](out, budgets)
    assert d == "inconclusive"
    assert "exchange_overlap" in reason


def test_ppr_serve_floors(budgets):
    d, _, _ = campaign.VERDICTS["ppr_serve_floors"](
        serve_output(qps=150.0), budgets)
    assert d == "tighten_serve_floors"  # >= 1.2x the 100 q/s floor
    d, _, _ = campaign.VERDICTS["ppr_serve_floors"](
        serve_output(qps=105.0), budgets)
    assert d == "keep_serve_floors"
    d, reason, ev = campaign.VERDICTS["ppr_serve_floors"](
        serve_output(qps=50.0, p99=700.0), budgets)
    assert d == "investigate_serve_regression"
    assert len(ev["violations"]) == 2
    d, _, _ = campaign.VERDICTS["ppr_serve_floors"](
        serve_output(qps=None), budgets)
    assert d == "inconclusive"
    d, reason, _ = campaign.VERDICTS["ppr_serve_floors"](
        serve_output(), {"budgets": []})
    assert d == "inconclusive"
    assert "no ppr_serve floors" in reason


def test_extract_verdict_overrides(budgets):
    doc = {"output": couple_output()}
    # Binding + within budget: the measured decision binds.
    v = campaign.extract_verdict("partitioned_vs_default",
                                 "bench_couple", doc, budgets,
                                 binding=True, over_budget=False)
    assert v["decision"] == "flip_partitioned_to_default"
    assert v["binding"] is True
    # Binding + over budget: measurements are suspect -> inconclusive.
    v = campaign.extract_verdict("partitioned_vs_default",
                                 "bench_couple", doc, budgets,
                                 binding=True, over_budget=True)
    assert v["decision"] == "inconclusive"
    assert "wall budget" in v["reason"]
    # Non-binding: defer, with the would-be decision in the evidence.
    v = campaign.extract_verdict("partitioned_vs_default",
                                 "bench_couple", doc, budgets,
                                 binding=False, over_budget=False)
    assert v["decision"] == "defer"
    assert v["reason"] == campaign.NONBINDING_REASON
    assert v["evidence"]["would_decide"] == \
        "flip_partitioned_to_default"
    # Missing artifact: inconclusive whatever the mode.
    v = campaign.extract_verdict("partitioned_vs_default",
                                 "bench_couple", None, budgets,
                                 binding=True, over_budget=False)
    assert v["decision"] == "inconclusive"
    assert "no artifact" in v["reason"]
    # Every decision the extractors can return has ledger text.
    assert v["decision"] in campaign.ACTION_TEXT


# -- budget proposal (obs history gate --propose-budgets) --------------------


def _serve_record(qps, backend="tpu"):
    return {"legs": {"ppr_serve": {"queries_per_sec": qps}},
            "env": {"backend": backend}, "workload": {"scale": 22}}


PROPOSE_BUDGETS = {
    "schema_version": 1,
    "detection": {"window": 8, "min_samples": 3},
    "budgets": [
        {"leg": "ppr_serve", "metric": "queries_per_sec",
         "min": 100.0, "env": {"backend": "tpu"}},
        {"leg": "ppr_serve", "metric": "p99_ms", "max": 500.0,
         "env": {"backend": "tpu"}},
        {"leg": "fast_f32", "metric": "edges_per_sec_per_chip",
         "min": 3.0e8, "env": {"backend": "tpu"}},
    ],
}


def test_propose_budgets_derivation():
    records = [_serve_record(q) for q in (190.0, 200.0, 210.0, 205.0)]
    # CPU rows must NOT contribute to a tpu-scoped floor.
    records += [_serve_record(20.0, backend="cpu")]
    out = history.propose_budgets(records, PROPOSE_BUDGETS)
    changes = {(c["leg"], c["metric"], c["bound"]): c
               for c in out["changes"]}
    c = changes[("ppr_serve", "queries_per_sec", "min")]
    assert c["old"] == 100.0
    # safety * median(190, 200, 205, 210) = 0.9 * 202.5, to 3 sig figs
    assert c["new"] == 182.0
    assert c["n"] == 4
    # Entries with too few matching rows are skipped, never guessed.
    skipped = {(s["leg"], s["metric"]) for s in out["skipped"]}
    assert ("ppr_serve", "p99_ms") in skipped
    assert ("fast_f32", "edges_per_sec_per_chip") in skipped
    # The proposal doc is still a valid budgets file, with the
    # derivation recorded on the changed entry.
    prop = out["proposal"]
    entry = next(b for b in prop["budgets"]
                 if b["metric"] == "queries_per_sec")
    assert entry["min"] == c["new"]
    assert entry["derived"]["n"] == 4
    # The input doc is untouched.
    assert PROPOSE_BUDGETS["budgets"][0]["min"] == 100.0


def test_propose_budgets_cli(tmp_path, capsys):
    ledger = tmp_path / "ledger.jsonl"
    for q in (190.0, 200.0, 210.0):
        history.append_record(str(ledger), _serve_record(q))
    bpath = tmp_path / "budgets.json"
    bpath.write_text(json.dumps(PROPOSE_BUDGETS))
    out = tmp_path / "proposal.json"
    rc = obs_main(["history", "gate", str(ledger),
                   "--budgets", str(bpath),
                   "--propose-budgets", str(out), "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["gate"]["ok"] is True
    assert doc["proposal"]["changes"][0]["bound"] == "min"
    written = json.loads(out.read_text())
    assert history.load_budgets(str(out))  # valid budgets file
    assert written["budgets"][0]["min"] == pytest.approx(0.9 * 200.0)
    # Without --budgets the flag is a usage error.
    rc = obs_main(["history", "gate", str(ledger),
                   "--propose-budgets", str(out)])
    assert rc == 2


# -- runner orchestration (stub entrypoints; no jax work) --------------------


STUB_HLO = {"command": ["obs", "hlo"], "exit_code": 0,
            "output": {"partitioned": {"step": {
                "gather": {"strategy": "native"}}}}}
STUB_HLO_DEFEATED = {"command": ["obs", "hlo"], "exit_code": 1,
                     "output": {"partitioned": {"step": {
                         "gather": {"strategy": "expanded"}}}}}


def stub_spec():
    return campaign.CampaignSpec(name="stub", legs=(
        campaign.LegSpec("hlo", "stub", {"doc": STUB_HLO},
                         budget_s=60.0),
        campaign.LegSpec("bench_couple", "stub",
                         {"doc": {"command": ["bench"], "exit_code": 0,
                                  "output": couple_output()}},
                         budget_s=60.0,
                         preconditions=("gather_native",),
                         verdicts=("partitioned_vs_default",)),
        campaign.LegSpec("ppr_serve", "stub",
                         {"doc": {"command": ["bench"], "exit_code": 0,
                                  "output": serve_output()}},
                         budget_s=60.0,
                         verdicts=("ppr_serve_floors",)),
    ))


@pytest.fixture
def stub_entry(monkeypatch):
    calls = []

    def _stub(params, ctx):
        calls.append(params)
        if params.get("raise"):
            raise RuntimeError("leg exploded")
        return params["doc"]

    monkeypatch.setitem(campaign.ENTRYPOINTS, "stub", _stub)
    return calls


def test_runner_complete_and_resume_byte_identical(tmp_path,
                                                   stub_entry):
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    # Uninterrupted run.
    r1 = campaign.CampaignRunner(d1, stub_spec(), fake_devices=8)
    r1.run()
    assert r1.manifest["status"] == "complete"
    r1.write_report()
    # Interrupted run: bench_couple explodes mid-campaign. Only the
    # exploding leg's params change — the intact legs keep their
    # artifact keys so the fixed spec can validate-and-skip them.
    broken = campaign.CampaignSpec(name="stub", legs=tuple(
        leg if leg.name != "bench_couple" else campaign.LegSpec(
            leg.name, leg.entrypoint,
            dict(leg.params, **{"raise": True}),
            budget_s=leg.budget_s, preconditions=leg.preconditions,
            verdicts=leg.verdicts)
        for leg in stub_spec().legs))
    r2 = campaign.CampaignRunner(d2, broken, fake_devices=8)
    r2.run()
    assert r2.manifest["status"] == "failed"
    assert r2.manifest["legs"]["bench_couple"]["status"] == "failed"
    assert "leg exploded" in r2.manifest["legs"]["bench_couple"]["error"]
    # ...then the fixed spec resumes: completed legs are SKIPPED
    # (validated artifacts), only the failed leg re-runs.
    del stub_entry[:]
    r3 = campaign.CampaignRunner(d2, stub_spec(), fake_devices=8)
    r3.run()
    assert r3.manifest["status"] == "complete"
    assert r3.manifest["legs"]["hlo"]["skipped"] is True
    assert r3.manifest["legs"]["bench_couple"]["skipped"] is False
    assert [p["doc"]["command"] for p in stub_entry] == [["bench"]]
    r3.write_report()
    # The stable report is byte-identical to the uninterrupted run's.
    with open(os.path.join(d1, campaign.REPORT_NAME), "rb") as f:
        a = f.read()
    with open(os.path.join(d2, campaign.REPORT_NAME), "rb") as f:
        b = f.read()
    assert a == b
    rep = json.loads(a)
    assert rep["complete"] is True
    assert rep["binding"] is False
    assert set(rep["verdicts"]) == {"partitioned_vs_default",
                                    "ppr_serve_floors"}
    assert all(v["decision"] == "defer"
               for v in rep["verdicts"].values())
    assert len(rep["decision_ledger"]) == 2
    # Volatile fields stay out of the stable report.
    assert "resumes" not in rep and "evidence" not in rep


def test_runner_drain_interrupt_and_resume(tmp_path, stub_entry):
    d = str(tmp_path / "c")

    class FakeDrain:
        def check(self, where=""):
            if where == "campaign/ppr_serve":
                raise jobs.DrainInterrupt(f"drain at {where}")

    r = campaign.CampaignRunner(d, stub_spec(), fake_devices=8)
    with pytest.raises(jobs.DrainInterrupt):
        r.run(drain=FakeDrain())
    r.interrupt("campaign/ppr_serve")
    m = json.load(open(os.path.join(d, campaign.MANIFEST_NAME)))
    assert m["status"] == "interrupted"
    assert m["legs"]["bench_couple"]["status"] == "done"
    assert "ppr_serve" not in m["legs"]
    # Resume completes only the un-run leg.
    del stub_entry[:]
    r2 = campaign.CampaignRunner(d, stub_spec(), fake_devices=8)
    r2.run()
    assert r2.manifest["status"] == "complete"
    assert r2.manifest["resumes"] == 1
    assert len(stub_entry) == 1


def test_runner_binding_precondition_blocks(tmp_path, monkeypatch):
    """Binding run: a defeated gather BLOCKS the bench leg; the
    dry run only warns (pinned via manifest warnings)."""
    def _stub(params, ctx):
        return params["doc"]

    monkeypatch.setitem(campaign.ENTRYPOINTS, "stub", _stub)
    spec = campaign.CampaignSpec(name="stub", legs=(
        campaign.LegSpec("hlo", "stub", {"doc": STUB_HLO_DEFEATED},
                         budget_s=60.0),
        campaign.LegSpec("bench_couple", "stub",
                         {"doc": {"command": ["bench"], "exit_code": 0,
                                  "output": couple_output()}},
                         budget_s=60.0,
                         preconditions=("gather_native",),
                         verdicts=("partitioned_vs_default",)),
    ))
    rb = campaign.CampaignRunner(str(tmp_path / "bind"), spec,
                                 fake_devices=0)
    rb.run()
    assert rb.manifest["status"] == "failed"
    assert rb.manifest["legs"]["bench_couple"]["status"] == "blocked"
    assert "DEFEATED" in rb.manifest["legs"]["bench_couple"]["error"]
    rep = rb.write_report()
    assert rep["verdicts"]["partitioned_vs_default"]["decision"] == \
        "inconclusive"
    # Dry run: same spec runs the leg anyway, with a recorded warning.
    rf = campaign.CampaignRunner(str(tmp_path / "fake"), spec,
                                 fake_devices=8)
    rf.run()
    assert rf.manifest["status"] == "complete"
    warnings = rf.manifest["legs"]["bench_couple"]["warnings"]
    assert any("non-binding dry run" in w for w in warnings)


def test_runner_over_budget_leg_poisons_binding_verdict(tmp_path,
                                                        stub_entry):
    spec = campaign.CampaignSpec(name="stub", legs=(
        campaign.LegSpec("ppr_serve", "stub",
                         {"doc": {"command": ["bench"], "exit_code": 0,
                                  "output": serve_output()}},
                         budget_s=0.0,  # any wall overruns
                         verdicts=("ppr_serve_floors",)),
    ))
    r = campaign.CampaignRunner(str(tmp_path / "ob"), spec,
                                fake_devices=0,
                                clock=iter([0.0, 5.0]).__next__)
    r.run()
    assert r.manifest["legs"]["ppr_serve"]["over_budget"] is True
    rep = r.write_report()
    assert rep["legs"][0]["within_budget"] is False
    v = rep["verdicts"]["ppr_serve_floors"]
    assert v["decision"] == "inconclusive"
    assert "wall budget" in v["reason"]


def test_corrupt_artifact_recomputes(tmp_path, stub_entry):
    d = str(tmp_path / "corrupt")
    r = campaign.CampaignRunner(d, stub_spec(), fake_devices=8)
    r.run()
    path = r.artifact_path(0, stub_spec().legs[0])
    with open(path, "r+b") as f:
        f.seek(60)
        f.write(b"\xff\xff\xff\xff")
    del stub_entry[:]
    r2 = campaign.CampaignRunner(d, stub_spec(), fake_devices=8)
    r2.run()
    # The corrupt leg recomputed; the intact ones resumed.
    assert r2.manifest["legs"]["hlo"]["skipped"] is False
    assert r2.manifest["legs"]["bench_couple"]["skipped"] is True
    assert any(p["doc"] is STUB_HLO for p in stub_entry)


def test_campaign_cli_status_report_exit_codes(tmp_path, stub_entry,
                                               capsys):
    # Missing campaign dir: usage error.
    assert obs_main(["campaign", "status", "--campaign-dir",
                     str(tmp_path / "nope")]) == 2
    assert obs_main(["campaign", "report", "--campaign-dir",
                     str(tmp_path / "nope")]) == 2
    capsys.readouterr()
    # Incomplete campaign: report renders but exits 1.
    d = str(tmp_path / "partial")

    class FakeDrain:
        def check(self, where=""):
            if where == "campaign/ppr_serve":
                raise jobs.DrainInterrupt("drain")

    r = campaign.CampaignRunner(d, stub_spec(), fake_devices=8)
    with pytest.raises(jobs.DrainInterrupt):
        r.run(drain=FakeDrain())
    r.interrupt("campaign/ppr_serve")
    assert obs_main(["campaign", "status", "--campaign-dir", d]) == 0
    out = capsys.readouterr().out
    assert "interrupted" in out
    assert obs_main(["campaign", "report", "--campaign-dir", d,
                     "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["complete"] is False
    # status of the not-yet-run leg shows pending in the leg table.
    assert [e["status"] for e in rep["legs"]] == \
        ["done", "done", "pending"]
    # --full adds the volatile evidence block.
    assert obs_main(["campaign", "report", "--campaign-dir", d,
                     "--json", "--full"]) == 1
    full = json.loads(capsys.readouterr().out)
    assert "evidence" in full and "leg_docs" in full
    assert full["verdicts"].keys() == rep["verdicts"].keys()


def test_stable_report_is_canonical_and_pure(tmp_path, stub_entry):
    d = str(tmp_path / "canon")
    r = campaign.CampaignRunner(d, stub_spec(), fake_devices=8)
    r.run()
    rep1 = r.write_report()
    spec, manifest, docs, metas = campaign.load_campaign(d)
    rep2 = campaign.build_report(spec, manifest, docs, metas,
                                 budgets=None)
    # build_report is pure over (spec, statuses, docs): re-deriving
    # from disk canonicalizes to the same bytes the runner wrote —
    # modulo budgets, which only shape evidence, not dry-run
    # decisions.
    assert report_mod.canonical_json(rep2) == \
        report_mod.canonical_json(rep1)
    with open(r.report_path) as f:
        assert f.read() == report_mod.canonical_json(rep1)


def test_build_spec_profiles():
    smoke = campaign.build_spec("smoke", ndev=8)
    road = campaign.build_spec("roadmap", ndev=8)
    assert [l.name for l in smoke.legs] == [l.name for l in road.legs]
    assert [l.name for l in smoke.legs] == [
        "hlo", "fit", "graph", "bench_couple", "bench_multichip",
        "ppr_serve", "history_gate"]
    # All verdict/precondition/entrypoint names resolve.
    for leg in smoke.legs:
        assert leg.entrypoint in campaign.ENTRYPOINTS
        for v in leg.verdicts:
            assert v in campaign.VERDICTS
        for p in leg.preconditions:
            assert p in campaign.PRECONDITIONS
    assert {v for l in smoke.legs for v in l.verdicts} == \
        set(campaign.VERDICTS)
    # Spec round-trips through its manifest encoding.
    assert campaign.CampaignSpec.from_doc(smoke.to_doc()) == smoke


# -- the real thing (slow tier; also acceptance smoke AA) --------------------


def _campaign_child_args(d):
    return ["campaign", "run", "--campaign-dir", str(d),
            "--fake-devices", "8"]


@pytest.mark.slow
def test_campaign_dry_run_sigkill_chaos_byte_identical(tmp_path):
    """ISSUE 20 acceptance criterion verbatim: the dry run completes
    end-to-end on CPU as a real subprocess; SIGKILL mid-leg + re-run
    resumes by skipping completed legs; the final report is
    byte-identical to the uninterrupted run's."""
    d1, d2 = tmp_path / "clean", tmp_path / "chaos"
    r = run_job_subprocess(_campaign_child_args(d1),
                           module="pagerank_tpu.obs", timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    clean = (d1 / campaign.REPORT_NAME).read_bytes()
    rep = json.loads(clean)
    assert rep["complete"] and not rep["binding"]
    assert set(rep["verdicts"]) == set(campaign.VERDICTS)
    assert all(v["decision"] == "defer"
               for v in rep["verdicts"].values())
    assert len(rep["decision_ledger"]) == len(campaign.VERDICTS)
    # SIGKILL lands mid-campaign, at the bench_couple leg.
    kill = ProcessKillPlan(stage="bench_couple",
                           signum=signal.SIGKILL)
    r = run_job_subprocess(_campaign_child_args(d2), kill=kill,
                           module="pagerank_tpu.obs", timeout=900,
                           kill_log=str(tmp_path / "kill.log"))
    assert r.returncode == -signal.SIGKILL
    m = json.load(open(d2 / campaign.MANIFEST_NAME))
    assert m["legs"]["hlo"]["status"] == "done"
    assert m["legs"]["bench_couple"]["status"] == "running"
    assert not (d2 / campaign.REPORT_NAME).exists()
    # Resume: completed legs skip, only the killed leg onward re-runs.
    r = run_job_subprocess(_campaign_child_args(d2),
                           module="pagerank_tpu.obs", timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    assert r.stderr.count("validated artifact, skipping") == 3
    m = json.load(open(d2 / campaign.MANIFEST_NAME))
    assert m["resumes"] == 1
    assert m["legs"]["hlo"]["skipped"] is True
    assert m["legs"]["bench_couple"]["skipped"] is False
    assert (d2 / campaign.REPORT_NAME).read_bytes() == clean


@pytest.mark.slow
def test_campaign_sigterm_drains_to_75(tmp_path):
    d = tmp_path / "drain"
    kill = ProcessKillPlan(stage="fit", signum=signal.SIGTERM)
    r = run_job_subprocess(_campaign_child_args(d), kill=kill,
                           module="pagerank_tpu.obs", timeout=900)
    assert r.returncode == 75, (r.returncode, r.stderr[-2000:])
    m = json.load(open(d / campaign.MANIFEST_NAME))
    assert m["status"] == "interrupted"
    assert m["legs"]["hlo"]["status"] == "done"
