"""Worker process for the multi-host (DCN) validation test.

Each of two processes fakes 2 local CPU devices, joins a
``jax.distributed`` cluster through the framework's own init helper
(parallel/distributed.py — the TPU-native stand-in for the reference's
Spark cluster manager, SURVEY.md §5), builds the SAME graph host-side,
and runs the sharded engine over the 4-device GLOBAL mesh. Process 0
writes the final ranks; the parent test diffs them against a
single-process run. Run only via tests/test_multihost.py.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
)


def main():
    coordinator, pid, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    # The site plugin in this image pins the platform programmatically;
    # re-pin to CPU (config beats env).
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

    from pagerank_tpu.parallel.distributed import (
        maybe_initialize_distributed,
        process_info,
    )

    assert maybe_initialize_distributed(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    idx, count = process_info()
    assert count == 2 and idx == pid, (idx, count)
    assert len(jax.devices()) == 4, jax.devices()

    import numpy as np

    from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph

    rng = np.random.default_rng(0)  # identical graph in both processes
    n, e = 400, 4000
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    cfg = PageRankConfig(
        num_iters=10, dtype="float64", accum_dtype="float64", lane_group=8
    )
    eng = JaxTpuEngine(cfg).build(g)
    assert eng.mesh.devices.size == 4
    ranks = eng.run_fast()
    if idx == 0:
        np.save(out_path, ranks)
    # All processes must reach teardown together (collectives in flight).
    jax.effects_barrier()


if __name__ == "__main__":
    main()
